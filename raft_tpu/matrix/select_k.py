"""Batched top-k selection (ref: matrix/select_k.cuh:75,
matrix/select_k_types.hpp:28-66, detail/select_radix.cuh,
detail/select_warpsort.cuh).

The reference implements two CUDA families (multi-pass radix histogram
filtering — "Air Top-k" — and warp bitonic sort queues) with a shape-based
heuristic (detail/select_k-inl.cuh:38-63).  On TPU the hardware story is
different: there are no warp shuffles or global atomics, and XLA's
`lax.top_k` is already a tuned TPU sort-based selection.  The rebuilt
dispatch is:

- ``kAuto``: `lax.top_k` for k ≤ 1024 or small rows; two-stage tiled
  selection for very wide rows (len ≫ k) where sorting the whole row wastes
  bandwidth — the same motivation as the reference's radix path.
- explicit algos kept for parity: kRadix* maps onto the 2-stage tiled
  tournament, kWarpsortImmediate onto the direct path, and
  kWarpsortFiltered/Distributed onto a third contender — a streaming
  running-top-k (single row pass, scan-merged k-buffer; the reference's
  filtered/distributed warpsort variants are likewise the
  stream-and-merge family).

The two-stage path mirrors the radix idea in TPU form: split each row into
T tiles, top-k each tile on the VPU (cheap local sort), then top-k the
T·k-wide candidate pool — a 2-level tournament with identical results for
any distribution, because a global top-k element is necessarily a top-k
element of its tile.

Hardware verdict (round-3 v5e grid, `tpu_battery_out/bench_full.jsonl`
matrix/select_k + select_k_large, adjudicated by ci/derive_select_k.py):

- direct `lax.top_k` wins every k ≤ 16 cell (3.8-5.0 ms; its best cell
  runs at 71 GB/s ≈ 9% of HBM) and the (1M, k ≥ 2048) cells;
- the 2-stage tournament wins the mid-k long-row band — (65k, 256)
  1.43×, (65k, 2048) 1.16×, (1M, 256) 1.09× over direct — which sets
  `_choose_tiled`'s measured rule (wide row, k > 16, candidate pool
  bounded);
- the streaming contender NEVER wins a cell (its scan-merge re-pays a
  top_k per tile; 1.4× to 7.5× behind the winner as k grows) — kept
  only as the explicit kWarpsortFiltered/Distributed parity mapping.

The round-2 design note here bet that a Pallas radix kernel could not
beat `lax.top_k`. The grid REFUTES the premise that top_k is
bandwidth-shaped: every k ≥ 256 winner sits at ~1% of HBM bandwidth
(e.g. 8192×8192 f32 = 256 MB selected in 46 ms ≈ 5.8 GB/s, a ~50×
roofline gap). That triggered the gate the note named, and the Pallas
radix kernel exists: :mod:`raft_tpu.matrix.radix_select`. Its round-5
threshold stage (a 32-step binary search) itself measured 3.6-6.4 GB/s
on hardware — the era-7 rebuild replaced it with the reference's true
multi-pass DIGIT-HISTOGRAM walk (NPASS=4 streamed passes, 256-bin
per-row histograms as factorized one-hot MXU contractions — see the
radix_select module docstring), and kAuto dispatches to it across the
full roofline-indicted band (radix_select.preferred: long rows above
k=256, short rows 16 < k <= MAX_K); the radix algo enums map to it
directly. The era-7 armed battery rows (matrix/select_k_bars encodes
the VERDICT hardware bars) re-adjudicate the bands on the next TPU
window through ci/derive_select_k.py.

Round 5 added a FIFTH contender: bound-gated sorted insertion
(:mod:`raft_tpu.matrix.topk_insert`, k <= 256) — the drain that took
the fused kNN kernel from 1.9 s to 98 ms, applied to materialized
input. It maps to the kWarpsortFiltered/Distributed enums (the
reference's filtered warpsort IS the insert-if-beats-bound family,
select_warpsort.cuh:129) and joins the bench tournament as algo
"insert". The five-way adjudication is structural now: the CPU tier
populates smoke-scale ``partial: true`` insert rows
(matrix/select_k_smoke) and ci/derive_select_k.py fails loudly on any
armed-but-unmeasured contender, so the empty-column round-5 state
(VERDICT Weak #2) cannot recur; AUTO adopts insert where the
re-derived grid says it wins.
"""

from __future__ import annotations

import enum
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from raft_tpu.util.math import cdiv, round_up_to_multiple
from raft_tpu.util.pallas_utils import interpret_needs_ref


class SelectAlgo(enum.Enum):
    """ref: SelectAlgo (select_k_types.hpp:28-66)."""

    AUTO = "auto"
    RADIX_8BITS = "radix_8bits"
    RADIX_11BITS = "radix_11bits"
    RADIX_11BITS_EXTRA_PASS = "radix_11bits_extra_pass"
    WARPSORT_IMMEDIATE = "warpsort_immediate"
    WARPSORT_FILTERED = "warpsort_filtered"
    WARPSORT_DISTRIBUTED = "warpsort_distributed"
    WARPSORT_DISTRIBUTED_EXT = "warpsort_distributed_ext"


def _choose_tiled(n_rows: int, n_cols: int, k: int,
                  tile: int = 8192) -> bool:
    """Heuristic analogue of choose_select_k_algorithm
    (detail/select_k-inl.cuh:38-63), re-derived from the round-3 v5e grid
    and the round-5 17:11 four-way capture: tiled wins wide rows at
    k > 16 as long as the stage-2 candidate pool (n_tiles · k) stays
    bounded — the (4M, 256) cell's 131k pool still wins (48.9 ms vs
    52.2 direct, select_k_derive.txt), so the cap sits just above it;
    at (1M, 2048) the 262k pool handed the win back to direct in r3
    (that band now belongs to radix via radix_select.preferred, checked
    first)."""
    pool = cdiv(n_cols, tile) * k
    return n_cols >= 64 * 1024 and k > 16 and pool <= 144 * 1024


def _order_flip(values: jnp.ndarray) -> jnp.ndarray:
    """Strictly order-reversing, self-inverse transform.

    Floats negate; integers use bitwise NOT (~x = -x-1 in two's complement),
    which reverses order without the overflow of -INT_MIN and is also correct
    for unsigned dtypes (~x = MAX - x).
    """
    if jnp.issubdtype(values.dtype, jnp.integer):
        return ~values
    return -values


def _direct_select(values: jnp.ndarray, k: int, select_min: bool):
    if select_min:
        vals, idx = jax.lax.top_k(_order_flip(values), k)
        return _order_flip(vals), idx
    return jax.lax.top_k(values, k)


def _pad_lowest(dtype):
    if jnp.issubdtype(dtype, jnp.floating):
        return -jnp.inf
    return jnp.iinfo(dtype).min


def _flip_pad_rows(values: jnp.ndarray, k: int, select_min: bool,
                   tile: int):
    """Shared selection prologue: clamp tile to k (correctness — a tile
    may hold up to k global winners, so it can never be smaller than k),
    fall back to direct when one tile covers the row, order-flip for
    select_min, pad the row length to a tile multiple with the
    lowest-sorting sentinel. Returns (v, n_tiles, tile) or None when the
    direct path should be taken."""
    n_rows, n_cols = values.shape
    tile = max(tile, k)
    if n_cols <= tile:
        return None
    v = _order_flip(values) if select_min else values
    n_tiles = cdiv(n_cols, tile)
    padded = n_tiles * tile
    if padded != n_cols:
        v = jnp.pad(v, ((0, 0), (0, padded - n_cols)),
                    constant_values=_pad_lowest(v.dtype))
    return v, n_tiles, tile


def _tiled_select(values: jnp.ndarray, k: int, select_min: bool,
                  tile: int = 8192):
    n_rows, n_cols = values.shape
    pre = _flip_pad_rows(values, k, select_min, tile)
    if pre is None:
        return _direct_select(values, k, select_min)
    v, n_tiles, tile = pre
    vt = v.reshape(n_rows, n_tiles, tile)
    # Stage 1: per-tile top-k (batched over rows × tiles).
    tvals, tidx = jax.lax.top_k(vt, k)
    base = (jnp.arange(n_tiles, dtype=jnp.int32) * tile)[None, :, None]
    gidx = tidx.astype(jnp.int32) + base
    # Stage 2: top-k of the candidate pool.
    pool_v = tvals.reshape(n_rows, -1)
    pool_i = gidx.reshape(n_rows, -1)
    fvals, fpos = jax.lax.top_k(pool_v, k)
    fidx = jnp.take_along_axis(pool_i, fpos, axis=1)
    return (_order_flip(fvals) if select_min else fvals), fidx


def _stream_select(values: jnp.ndarray, k: int, select_min: bool,
                   tile: int = 8192):
    """Single-pass streaming selection: scan the row in tiles, folding
    each tile into a running k-buffer via one top_k over the
    [buffer | tile] pool (the knn running-top-k pattern,
    neighbors/brute_force._knn_scan). One read of the data + O(n_tiles·k)
    merge work — the bandwidth-shaped contender for len ≫ k where the
    direct path sorts the whole row and the 2-stage tournament buffers
    every tile's candidates. The third algo of the hardware tournament
    (ci/derive_select_k.py decides the dispatch)."""
    n_rows, n_cols = values.shape
    pre = _flip_pad_rows(values, k, select_min, tile)
    if pre is None:
        return _direct_select(values, k, select_min)
    v, n_tiles, tile = pre
    # scan over tile OFFSETS with dynamic_slice — no [n_tiles, rows,
    # tile] transpose copy of the (potentially huge) input; the scan
    # body reads each tile straight out of the row-major buffer
    offsets = jnp.arange(1, n_tiles, dtype=jnp.int32) * tile

    def tile_at(off):
        return jax.lax.dynamic_slice(v, (jnp.int32(0), off),
                                     (n_rows, tile))

    def step(carry, off):
        bv, bi = carry                       # [n_rows, k] running best
        cv, ci = jax.lax.top_k(tile_at(off), k)   # tile-local top-k
        pool_v = jnp.concatenate([bv, cv], axis=1)
        pool_i = jnp.concatenate([bi, ci.astype(jnp.int32) + off], axis=1)
        nv, pos = jax.lax.top_k(pool_v, k)
        return (nv, jnp.take_along_axis(pool_i, pos, axis=1)), None

    # seed the buffer from tile 0 (a pad-filled seed would tie-win
    # against real extreme values — e.g. rows containing -inf — and
    # surface its bogus indices); scan folds the remaining tiles
    iv, ii = jax.lax.top_k(tile_at(jnp.int32(0)), k)
    init = (iv, ii.astype(jnp.int32))
    (fv, fi), _ = jax.lax.scan(step, init, offsets)
    return (_order_flip(fv) if select_min else fv), fi


def select_k(res, values, k: int, select_min: bool = True,
             in_idx: Optional[jnp.ndarray] = None,
             algo: SelectAlgo = SelectAlgo.AUTO,
             sorted: bool = True) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Batched top-k: smallest (select_min) or largest k per row.

    values: [batch, len]; optional in_idx [batch, len] gives payload indices
    to return instead of positions (ref: select_k.cuh in_idx passthrough).
    Returns (out_val [batch, k], out_idx [batch, k]), sorted best-first.

    >>> import numpy as np
    >>> from raft_tpu.matrix import select_k
    >>> vals, idx = select_k(None, np.array([[9., 1., 5., 3.]]), k=2)
    >>> np.asarray(vals).tolist(), np.asarray(idx).tolist()
    ([[1.0, 3.0]], [[1, 3]])
    """
    values = jnp.asarray(values)
    squeeze = values.ndim == 1
    if squeeze:
        values = values[None, :]
    n_rows, n_cols = values.shape
    if k > n_cols:
        raise ValueError(f"k={k} > len={n_cols}")

    from raft_tpu.matrix import radix_select

    def _radix_ok():
        # The radix kernels carry shard_map vma (join_vma + vma
        # out_shapes); only the INTERPRETER cannot replay vma-carrying
        # kernels (pallas_utils.interpret_needs_ref) — the CPU test tier
        # falls back to the tournament paths under shard_map there.
        return (radix_select.supports(values.dtype, n_cols, k)
                and not interpret_needs_ref(values))

    if algo == SelectAlgo.AUTO:
        # Roofline-motivated dispatch: radix takes the band where the
        # measured grids showed lax.top_k ~50x under the bandwidth
        # roofline, extended past k=2048 on 1M rows by the round-5
        # capture (radix won every k >= 256 there, incl. 10^4:
        # 65.5 ms vs direct 115) and to MAX_K on short rows by the
        # era-7 digit-histogram rebuild — radix_select.preferred is
        # the single source of truth, shared with the chunked kNN
        # gate. Outside the band: direct for small k, tiled per
        # _choose_tiled; thresholds re-derive from
        # ci/derive_select_k.py (which now fails loudly on any
        # armed-but-unmeasured contender, incl. the insert column).
        if radix_select.preferred(n_cols, k) and _radix_ok():
            mode = "radix"
        elif _choose_tiled(n_rows, n_cols, k):
            mode = "tiled"
        else:
            mode = "direct"
    elif algo in (SelectAlgo.RADIX_8BITS, SelectAlgo.RADIX_11BITS,
                  SelectAlgo.RADIX_11BITS_EXTRA_PASS):
        # the reference's radix slots map to the Pallas radix-rank kernel
        if _radix_ok():
            mode = "radix"
        else:
            mode = "tiled" if n_cols > 8192 else "direct"
    elif algo in (SelectAlgo.WARPSORT_FILTERED,
                  SelectAlgo.WARPSORT_DISTRIBUTED,
                  SelectAlgo.WARPSORT_DISTRIBUTED_EXT):
        # the reference's "filtered" warpsort inserts only candidates
        # that beat the current k-th bound (select_warpsort.cuh:129) —
        # exactly the bound-gated insertion drain, so these slots map
        # to matrix/topk_insert when it applies (f32-family, k <= 256,
        # not the interpret-under-shard_map tier); the streaming
        # running-top-k keeps the remainder of the family
        from raft_tpu.matrix import topk_insert

        if (topk_insert.supports(values.dtype, k)
                and not interpret_needs_ref(values)):
            mode = "insert"
        else:
            mode = "stream" if n_cols > 8192 else "direct"
    else:
        mode = "direct"

    if mode == "radix":
        out_val, out_idx = radix_select.radix_select_k(values, k,
                                                       select_min)
    elif mode == "insert":
        from raft_tpu.matrix import topk_insert

        out_val, out_idx = topk_insert.insert_select(values, k,
                                                     select_min)
    elif mode == "tiled":
        out_val, out_idx = _tiled_select(values, k, select_min)
    elif mode == "stream":
        out_val, out_idx = _stream_select(values, k, select_min)
    else:
        out_val, out_idx = _direct_select(values, k, select_min)

    if in_idx is not None:
        in_idx = jnp.asarray(in_idx)
        if in_idx.ndim == 1:
            in_idx = in_idx[None, :]
        out_idx = jnp.take_along_axis(in_idx, out_idx, axis=1)
    else:
        out_idx = out_idx.astype(jnp.int32)

    if squeeze:
        return out_val[0], out_idx[0]
    return out_val, out_idx
