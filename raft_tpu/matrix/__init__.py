"""Dense matrix primitives (ref: cpp/include/raft/matrix/)."""

from raft_tpu.matrix.select_k import SelectAlgo, select_k  # noqa: F401
from raft_tpu.matrix.argminmax import argmin, argmax  # noqa: F401
from raft_tpu.matrix.gather import gather, gather_if, scatter  # noqa: F401
from raft_tpu.matrix.linewise_op import linewise_op  # noqa: F401
from raft_tpu.matrix.ops import (  # noqa: F401
    copy,
    get_diagonal,
    set_diagonal,
    invert_diagonal,
    eye,
    fill,
    linspace,
    l2_norm,
    weighted_power,
    power,
    ratio,
    reciprocal,
    col_reverse,
    row_reverse,
    sign_flip,
    slice,
    sqrt,
    zero_small_values,
    upper_triangular,
    lower_triangular,
    SHIFT_TOWARDS_END,
    SHIFT_TOWARDS_BEGINNING,
    col_shift,
    row_shift,
    sort_cols_per_row,
    sample_rows,
)
