"""Dense matrix primitives (ref: cpp/include/raft/matrix/)."""

from raft_tpu.matrix.select_k import SelectAlgo, select_k  # noqa: F401
from raft_tpu.matrix.epilogue import argmin, argmax  # noqa: F401
from raft_tpu.matrix.gather import (gather, gather_if, scatter,  # noqa: F401
                                    take_rows)
from raft_tpu.matrix.linewise_op import linewise_op  # noqa: F401
from raft_tpu.matrix.ops import (  # noqa: F401
    copy,
    get_diagonal,
    set_diagonal,
    invert_diagonal,
    eye,
    fill,
    linspace,
    l2_norm,
    weighted_power,
    power,
    ratio,
    reciprocal,
    col_reverse,
    row_reverse,
    sign_flip,
    slice,
    sqrt,
    zero_small_values,
    upper_triangular,
    lower_triangular,
    SHIFT_TOWARDS_END,
    SHIFT_TOWARDS_BEGINNING,
    col_shift,
    row_shift,
    sort_cols_per_row,
    sample_rows,
)

# Reference-spelling aliases (one name per public header of raft/matrix/ —
# migration-doc parity; the canonical raft_tpu names above are preferred):
# col_wise_sort.cuh, diagonal.cuh, norm.cuh, reverse.cuh, shift.cuh,
# threshold.cuh, triangular.cuh, print.cuh.
from raft_tpu.matrix.ops import print_matrix  # noqa: F401

col_wise_sort = sort_cols_per_row
diagonal = get_diagonal
norm = l2_norm
reverse = row_reverse
shift = row_shift
threshold = zero_small_values
triangular = upper_triangular
