"""Per-row argmin/argmax (ref: matrix/argmax.cuh, matrix/argmin.cuh).

Tie-breaking: smallest index wins, matching the reference's KVP atomics.
"""

from __future__ import annotations

import jax.numpy as jnp


def argmin(res, matrix):
    """Index of the minimum of each row (ref: argmin.cuh)."""
    return jnp.argmin(jnp.asarray(matrix), axis=1).astype(jnp.int32)


def argmax(res, matrix):
    """Index of the maximum of each row (ref: argmax.cuh)."""
    return jnp.argmax(jnp.asarray(matrix), axis=1).astype(jnp.int32)
