"""Deprecated shim: per-row argmin/argmax moved into the unified
epilogue layer (:mod:`raft_tpu.matrix.epilogue`, ISSUE 14). This module
re-exports the same callables so existing ``matrix.argminmax`` imports
keep working; new code should import from ``raft_tpu.matrix`` (or
``raft_tpu.matrix.epilogue``) directly.
"""

from __future__ import annotations

from raft_tpu.matrix.epilogue import argmax, argmin  # noqa: F401

__all__ = ["argmin", "argmax"]
