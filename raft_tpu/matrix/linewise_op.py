"""Row/column broadcast op over a matrix with one or more vectors
(ref: matrix/linewise_op.cuh, detail/linewise_op.cuh:40,246-296).

The reference's `struct Linewise` hand-vectorizes the broadcast; XLA emits
the same fused loads from a broadcasted expression, so this is a thin,
layout-aware wrapper.
"""

from __future__ import annotations

from typing import Callable

import jax.numpy as jnp


def linewise_op(res, matrix, op: Callable, along_lines: bool, *vecs):
    """Apply op(m_ij, v1_?, v2_?, ...) broadcasting each vec along matrix
    lines.  along_lines=True: vectors have length n_cols and broadcast
    across rows (vec indexed by column); False: length n_rows, indexed by
    row (ref: linewise_op.cuh matrixLinewiseOp)."""
    m = jnp.asarray(matrix)
    if along_lines:
        bvecs = [jnp.asarray(v)[None, :] for v in vecs]
    else:
        bvecs = [jnp.asarray(v)[:, None] for v in vecs]
    return op(m, *bvecs)
