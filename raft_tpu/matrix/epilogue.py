"""Unified fused-epilogue primitives (ISSUE 14): the ONE spelling of the
iota-compare argmin, the factorized one-hot contractions, the running
min-fold, and the bound-gated insertion drain that every selection /
assignment epilogue in the tree rides.

The contraction engine runs at MXU rate; its consumers are throttled by
their VPU epilogues (BASELINE roofline: the north star at 57% MXU with
the argmin/one-hot epilogue binding, kNN at mxu_frac 0.057 with ~85% of
the kernel in insertion drain). Before this module the same machinery
was hand-rolled in at least three places (cluster/kmeans.py's mnmg
one-hot, matrix/radix_select.py's histogram/emission one-hots,
neighbors/fused_topk.py's drain strip). Centralizing it means a lever
spent here — the shared-iota argmin/one-hot fusion (VERDICT task 6) and
the widened drain strip (task 5) — lands in kmeans, kNN, IVF, and
select_k simultaneously, and raftlint R9 keeps the deleted duplication
deleted.

Primitive -> consumer map (mirrored in docs/architecture.md):

===================  ====================================================
primitive            consumers
===================  ====================================================
iota_argmin          contractions._distance_tile (fused argmin / Lloyd /
                     tiled kernels), via the _mask_argmin alias
argmin_ref           contractions._argmin_jnp (interpret / odd-dtype
                     reference path), distance.pairwise 1-NN reference
assign_onehot        contractions._lloyd_kernel(+_split), _lloyd_jnp —
                     the shared-iota lever: iota_argmin's column iota
                     feeds BOTH the argmin and the one-hot update
label_onehot         kmeans._weighted_sums, kmeans.mnmg_lloyd_step
                     (model-axis block one-hot), contractions'
                     VMEM-fallback chunked update
onehot_pair/
onehot_histogram     radix_select._threshold_kernel (16x16 digit
                     histogram), _emit_chunk_body (slot x column-value
                     emission)
slot_onehot          radix_select threshold narrowing (hi-nibble select)
masked_fold          contractions tiled argmin kernels,
                     fused_topk._minonly_body
insert_drain         topk_insert (insert_select), fused_topk (knn_fused)
masked_topk          ivf_flat._probe_topk (+ ivf_mnmg / serve via it),
                     brute_force._knn_chunked / _knn_scan
host_assign_update   kmeans_fit_elastic (numpy host loop)
argmin/argmax        matrix API (folded from matrix/argminmax.py)
===================  ====================================================

Every primitive keeps the tie contract of the fused-NN KVP min-reduce
lineage: smallest index wins globally — within a tile by first-minimum
argmin, across tiles because earlier insertions sit left of (and folds
keep) an equal newcomer.

Mosaic legality notes carried with the code they protect: reduce-min +
masked-iota argmin (lax.argmin's variadic reduce fails legalization),
i32 max-reduce instead of jnp.any (bool proxy reduces through f64 under
x64), dtype-matched inf constants (bare jnp.inf is weak-f64), masked
one-lane reduce for the k-th bound (a (tm, 1)-index gather from
(tm, bw) is not legal), `pltpu.roll` lane shifts.

Module-level imports are restricted to jax/pallas/numpy/util so
linalg.contractions can import this module at the top level; the radix
import inside :func:`masked_topk` stays lazy (epilogue -> radix ->
contractions -> epilogue would otherwise cycle).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from raft_tpu.util.math import round_up_to_multiple

LANES = 128
MAX_K = 2 * LANES   # up to two vregs of sorted best per query row
                    # (larger k takes the radix / tournament paths)

# Default drain-strip width (VERDICT task 5): the per-round extraction
# cost of the insertion drain is O(tm * strip), independent of the
# producer tile width, so a 256-lane strip under the measured tn=1024
# kNN tile cuts the dead-lane extraction work ~4x while the round count
# (one per improving candidate) is unchanged. Cost model at the
# BASELINE kNN shape (1M x 128, q=4096, k=64: 97.65 ms total, ~12.6 ms
# distance + ~85 ms drain): 12.6 + 85/4 = 33.9 ms, a ~2.9x model cut
# that would put mxu_frac at ~0.16 (the task-5 bar is >= 0.15).
DRAIN_SW = 256


# ---------------------------------------------------------------------------
# argmin family
# ---------------------------------------------------------------------------


def argmin_ref(d):
    """jnp reference argmin epilogue: per-row (min, first-min argmin) of
    a materialized distance block via lax.argmin — the spelling the
    interpret / odd-dtype paths use (pallas_utils.interpret_needs_ref
    dispatch). Same tie rule (smallest index) and NaN semantics as
    :func:`iota_argmin`; the kernels never call this (lax.argmin's
    variadic-reduce lowering fails Mosaic legalization)."""
    arg = jax.lax.argmin(d, 1, jnp.int32)
    minval = jnp.min(d, axis=1)
    return minval, arg


def iota_argmin(d, n_valid, finite: bool = False):
    """Mosaic-safe fused mask + argmin over a (tm, np_) distance tile.

    Returns ``(col, minval, arg)`` with ``minval``/``arg`` keepdims
    (tm, 1) — and ``col``, the (tm, np_) column iota, so the caller can
    REUSE it for the one-hot update (``assign_onehot``): the shared-iota
    lever (VERDICT task 6) — one iota feeds both the assignment and the
    centroid-update epilogue instead of each building its own.

    dtype-matched inf: a bare jnp.inf is a weak-f64 constant under
    jax_enable_x64, and the resulting f64→f32 convert has no Mosaic
    lowering (caught by tests/test_mosaic_lowering.py).
    When n_valid is STATIC and aligned (the north-star k=1024 exactly
    fills its tile) skip the whole masking pass — the epilogue is the
    binding resource (BASELINE.md roofline note), so a dead (tm, np_)
    compare+select per tile is real time, not hygiene. The tiled-argmin
    path passes a TRACED n_valid (per-tile validity): always mask there.

    Manual first-minimum argmin: lax.argmin's variadic-reduce lowering
    fails Mosaic legalization at narrow tiles (unresolved f32->i32
    materialization, observed on-chip at a (257, 19) tile); min +
    masked-iota uses only plain reduce-min/where ops (no variadic
    reduce) and keeps the KVP first-minimum tie rule. On-chip evidence
    gate: the smoke tier's test_fused_argmin[257-31-19]. NaN positions
    count as minimal (lax.argmin/numpy parity — XLA reduce-min
    propagates NaN, so minval is NaN and only the NaN columns survive
    the candidate mask).

    ``finite`` statically declares NaN-free distances (the Lloyd paths:
    k-means on non-finite data is undefined anyway) and skips the NaN
    candidate clause — two dead (tm, np_) VPU passes per tile on the
    epilogue-bound kernel (BASELINE.md roofline, r5 lever)."""
    col = jax.lax.broadcasted_iota(jnp.int32, d.shape, 1)
    if not (isinstance(n_valid, int) and n_valid >= d.shape[1]):
        d = jnp.where(col < n_valid, d, jnp.asarray(jnp.inf, d.dtype))
    minval = jnp.min(d, axis=1, keepdims=True)
    cand = d == minval
    if not finite:
        cand = cand | (d != d)
    sentinel = jnp.asarray(jnp.iinfo(jnp.int32).max, jnp.int32)
    arg = jnp.min(jnp.where(cand, col, sentinel), axis=1, keepdims=True)
    return col, minval, arg


def row_min_arg(pool, col):
    """Per-row (min, first-min argmin) of a (tm, tn) pool whose column
    indices the caller already holds — reduce-min + masked-iota, the
    Mosaic-safe argmin spelling (see :func:`iota_argmin` for why
    lax.argmin is not used)."""
    pm = jnp.min(pool, axis=1, keepdims=True)
    sentinel = jnp.asarray(jnp.iinfo(jnp.int32).max, jnp.int32)
    pidx = jnp.min(jnp.where(pool == pm, col, sentinel), axis=1,
                   keepdims=True)
    return pm, pidx


# ---------------------------------------------------------------------------
# one-hot family
# ---------------------------------------------------------------------------


def assign_onehot(col, arg, row_mask=None):
    """Boolean assignment one-hot from :func:`iota_argmin`'s outputs —
    the shared-iota lever: ``col`` is the SAME iota the argmin consumed,
    so the one-hot costs one (tm, np_) compare instead of a fresh iota +
    compare. ``row_mask`` (tm, 1) masks padded X rows (they must not
    inflate counts). Caller picks the accumulation dtype (f32 on the
    plain path, bf16 on the split path — 0/1 is exact in both)."""
    oh = col == arg
    if row_mask is not None:
        oh = oh & row_mask
    return oh


def label_onehot(labels, n_classes: int, mask=None,
                 dtype=jnp.float32):
    """(m, n_classes) one-hot from an (m,) label vector — the XLA-side
    twin of :func:`assign_onehot` for paths that carry labels instead of
    a resident distance tile (kmeans weighted/mnmg updates, the
    VMEM-fallback chunked update). Out-of-range labels (the padded-row
    ``n_classes`` convention) produce all-zero rows, matching
    jax.nn.one_hot, whose spelling this replaces 1:1."""
    col = jax.lax.broadcasted_iota(
        jnp.int32, (labels.shape[0], n_classes), 1)
    oh = col == labels[:, None]
    if mask is not None:
        oh = oh & mask[:, None]
    return oh.astype(dtype)


def onehot_pair(hi, lo, nh: int, nl: int, active=None,
                dtype=jnp.bfloat16):
    """The factorized one-hot operand pair behind every MXU histogram /
    emission contraction: digit = nl*hi + lo, ``ohhi`` (tm, nh, tl) and
    ``ohlo`` (tm, tl, nl) such that their row-batched dot lands each
    (hi, lo) pair in its own output cell. ``active`` (tm, tl) masks
    elements out of the hi side (a -1 sentinel in ``hi`` matches no row
    and needs no mask). 0/1 operands are exact in bf16."""
    iota_h = jax.lax.broadcasted_iota(jnp.int32, (1, nh, 1), 1)
    iota_l = jax.lax.broadcasted_iota(jnp.int32, (1, 1, nl), 2)
    hh = iota_h == hi[:, None, :]
    if active is not None:
        hh = hh & active[:, None, :]
    ohhi = hh.astype(dtype)                              # (tm, nh, tl)
    ohlo = (lo[:, :, None] == iota_l).astype(dtype)      # (tm, tl, nl)
    return ohhi, ohlo


def onehot_histogram(hi, lo, active=None, nh: int = 16, nl: int = 16):
    """All nh*nl digit bins of a (tm, tl) tile as exact f32 counts in
    ONE row-batched MXU contraction — the TPU replacement for the
    reference's shared-memory atomic histogram (radix_select lineage):
    (tm, nh, tl) @ (tm, tl, nl) of the factorized one-hots. 0/1 bf16
    operands with f32 accumulate: counts exact to 2^24 > MAX_LEN."""
    ohhi, ohlo = onehot_pair(hi, lo, nh, nl, active)
    return jax.lax.dot_general(
        ohhi, ohlo, (((2,), (1,)), ((0,), (0,))),
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.DEFAULT)             # (tm, nh, nl)


def onehot_histogram_ref(hi, lo, active=None, nh: int = 16,
                         nl: int = 16):
    """jnp reference for :func:`onehot_histogram` (test oracle): the
    same counts via a plain compare-and-sum, no MXU contraction."""
    digit = hi * nl + lo
    tm, tl = digit.shape
    oh = digit[:, :, None] == jnp.arange(nh * nl,
                                         dtype=digit.dtype)[None, None, :]
    if active is not None:
        oh = oh & active[:, :, None]
    return jnp.sum(oh.astype(jnp.float32), axis=1).reshape(tm, nh, nl)


def slot_onehot(idx, nbins: int, dtype=jnp.float32):
    """(tm, nbins, 1) selector one-hot from a (tm, 1) bin index — the
    histogram-row select of the radix threshold narrowing."""
    iota = jax.lax.broadcasted_iota(jnp.int32, (1, nbins, 1), 1)
    return (iota == idx[:, :, None]).astype(dtype)


# ---------------------------------------------------------------------------
# running min-fold (tiled-kernel epilogue)
# ---------------------------------------------------------------------------


def masked_fold(val_ref, idx_ref, minval, arg, offset):
    """Tiled-kernel running-min epilogue shared by the argmin kernels
    (split and non-split) and the kNN min-only floor probe: initialize
    the revisited (1, tm) (val, idx) block on the first y-tile, then
    fold this tile's keepdims (tm, 1) (min, argmin) in — ties keep the
    earlier tile (strict ``<``), the global first-minimum rule.
    ``offset`` rebases tile-local argmins to global columns (pass 0 when
    ``arg`` is already global)."""
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        val_ref[:] = jnp.full_like(val_ref, jnp.inf)
        idx_ref[:] = jnp.zeros_like(idx_ref)

    garg = (arg + offset).T                           # (1, tm)
    minval = minval.T
    prev_val = val_ref[:]
    better = minval < prev_val
    val_ref[:] = jnp.where(better, minval, prev_val)
    idx_ref[:] = jnp.where(better, garg, idx_ref[:])


def masked_fold_ref(best_val, best_idx, minval, arg, offset):
    """jnp reference twin of :func:`masked_fold` (functional, no refs):
    one fold step over already-initialized running (val, idx)."""
    garg = arg + offset
    better = minval < best_val
    return (jnp.where(better, minval, best_val),
            jnp.where(better, garg, best_idx))


# ---------------------------------------------------------------------------
# bound-gated insertion drain
# ---------------------------------------------------------------------------


def resolve_tn_sw(tn: int, sw: Optional[int], n: int):
    """One spelling of the tile-width clamp + strip-width contract for
    every drain consumer (knn_fused, insert_select): lane-align tn,
    clamp it to the data width, and validate sw against the REQUESTED
    tn — an sw that never divided the caller's tn is an error, while
    indivisibility introduced only by the small-data clamp degrades to
    the whole-tile drain (a perf knob must not error on small inputs).
    ``sw=None`` picks the default lever (:data:`DRAIN_SW` when it
    divides the requested tile, whole-tile otherwise — an explicit tn
    the lever cannot strip is the caller's tile choice, not an error).
    Returns (tn, sw)."""
    tn_req = max(128, tn - tn % 128)        # caller's lane-aligned ask
    tn = min(tn_req, round_up_to_multiple(n, 128))
    if sw is None:
        sw = DRAIN_SW if tn_req % DRAIN_SW == 0 else 0
    if sw and (sw < 0 or sw % 128 or tn_req % sw):
        raise ValueError(f"sw must be a positive lane-aligned divisor "
                         f"of tn={tn_req}")
    if sw and tn % sw:
        sw = 0                  # clamp-induced indivisibility only
    return tn, sw


def best_width(k: int) -> int:
    """Lane-aligned width of the sorted-best buffer: one vreg for
    k <= 128, two for k <= 256 (insert cost scales with the width, so
    the buffer is as narrow as k allows)."""
    return LANES * ((k + LANES - 1) // LANES)


def insert_drain(dist, val_ref, idx_ref, j, tn: int, k: int,
                 n_valid: int, sw: int = 0):
    """Drain a (tm, tn) candidate tile into the sorted (tm, bw) best.

    Each round: per-row pool min + first-min argmin (smallest column
    wins ties), consume that lane, and for rows where the minimum beats
    their k-th bound, compare-shift it into the sorted best. Rows whose
    pool holds nothing below their bound extract dead mins into a
    guarded no-op — progress is global (every looping row consumes one
    lane per round), and the loop exits when no row can improve. Tie
    contract (smallest index wins globally): within a tile the first-min
    argmin inserts equal values in column order; across tiles, earlier
    insertions win because ``keep = best <= candidate`` leaves existing
    entries to the left of an equal newcomer.

    ``sw`` (strip width, 0 = whole tile): drain the tile in static
    lane-aligned strips so the per-round vector work is O(tm·sw) while
    the producer tile keeps its full width — the tile width and the
    drain width are INDEPENDENT knobs. Round count is unchanged (a
    candidate is a candidate in any strip); only the dead-lane
    extraction width shrinks. Strips see ascending global columns,
    preserving the tie contract. :data:`DRAIN_SW` is the spent lever
    default at the drain's call sites (see the module docstring's cost
    model).

    NaN candidates are mapped to +inf HERE, for every producer: a NaN
    pool minimum would match no lane (nothing consumed) and the while
    loop could spin forever on the DEVICE while any finite candidate
    sits below the bound — a hang, not a wrong answer. One compare+
    select per tile element buys termination; +inf is the drain's own
    never-selected sentinel (NaN sorts last)."""
    tm = dist.shape[0]
    dist = jnp.where(jnp.isnan(dist), jnp.asarray(jnp.inf, jnp.float32),
                     dist)
    bw = best_width(k)
    lane = jax.lax.broadcasted_iota(jnp.int32, (tm, bw), 1)
    inf = jnp.asarray(jnp.inf, jnp.float32)

    @pl.when(j == 0)
    def _init():
        val_ref[:] = jnp.full((tm, bw), jnp.inf, jnp.float32)
        idx_ref[:] = jnp.zeros((tm, bw), jnp.int32)

    def kth(bv):
        # masked one-lane reduce: a (tm, 1)-index gather from (tm, bw)
        # is not Mosaic-legal (same-shape operand rule)
        return jnp.min(jnp.where(lane == k - 1, bv, inf), axis=1,
                       keepdims=True)

    def cond(carry):
        pool, bv, _ = carry
        # i32 max, not bool any: jnp.any's bool proxy reduces through
        # f64 under jax_enable_x64 and fails Mosaic lowering
        # (radix_select precedent)
        return jnp.max((pool < kth(bv)).astype(jnp.int32)) > 0

    def drain(pool, col_g, bv, bi):
        def body(carry):
            pool, bv, bi = carry
            pm, pidx = row_min_arg(pool, col_g)
            pool = jnp.where(col_g == pidx, inf, pool)  # consume lane
            improving = pm < kth(bv)
            keep = bv <= pm                 # prefix mask (sorted best)
            pos = jnp.sum(keep.astype(jnp.int32), axis=1, keepdims=True)
            shv = pltpu.roll(bv, 1, axis=1)
            shi = pltpu.roll(bi, 1, axis=1)
            nv = jnp.where(lane < pos, bv,
                           jnp.where(lane == pos, pm, shv))
            ni = jnp.where(lane < pos, bi,
                           jnp.where(lane == pos, pidx, shi))
            bv = jnp.where(improving, nv, bv)
            bi = jnp.where(improving, ni, bi)
            return pool, bv, bi

        _, bv, bi = jax.lax.while_loop(cond, body, (pool, bv, bi))
        return bv, bi

    sw = sw or tn
    bv, bi = val_ref[:], idx_ref[:]
    for s in range(0, tn, sw):              # static: unrolled strips
        strip = dist[:, s:s + sw]
        col_g = (jax.lax.broadcasted_iota(jnp.int32, strip.shape, 1)
                 + j * tn + s)
        pool = jnp.where(col_g < n_valid, strip, inf)
        bv, bi = drain(pool, col_g, bv, bi)
    val_ref[:] = bv
    idx_ref[:] = bi


def insert_drain_ref(values, k: int):
    """jnp reference twin of the drain's end-to-end contract over a
    materialized (m, n) block: ascending top-k by value with first-index
    ties (lax.top_k is stable over the negated input) and NaN mapped to
    the drain's +inf sentinel (NaN sorts last, never inserts)."""
    v = jnp.asarray(values).astype(jnp.float32)
    v = jnp.where(jnp.isnan(v), jnp.inf, v)
    neg, idx = jax.lax.top_k(-v, k)
    return -neg, idx


# ---------------------------------------------------------------------------
# masked scoring epilogue (XLA-side: IVF probe scan, chunked-radix kNN)
# ---------------------------------------------------------------------------


def masked_topk(dist, valid, k: int, use_radix: bool):
    """Validity-masked ascending top-k of a materialized (m, n) score
    block — the ONE spelling of the mask + select epilogue behind
    ivf_flat's probe scan and brute_force's chunked/scan formulations.
    Invalid slots become +inf (never selected; a fully-invalid row
    returns +inf values, which callers map to id -1). ``use_radix``
    routes to the digit-histogram radix select (the bandwidth-class
    epilogue for wide rows) vs lax.top_k (short rows / reference)."""
    dist = jnp.where(valid, dist, jnp.inf)
    if use_radix:
        from raft_tpu.matrix.radix_select import radix_select_k

        return radix_select_k(dist, k)
    neg, pos = jax.lax.top_k(-dist, k)
    return -neg, pos


# ---------------------------------------------------------------------------
# host (numpy) twin — the elastic fit's per-rank assignment + update
# ---------------------------------------------------------------------------


def host_assign_update(xs, ws, c):
    """One rank's Lloyd assignment + weighted one-hot update on the
    HOST (numpy f64) — the elastic fit's per-iteration body, kept next
    to its device twins so the tie rule (np.argmin = first minimum) and
    the expanded-form distances stay in one reviewed place. Returns
    ``(labels, sums [k, d], counts [k], best [m])`` with ``best`` the
    clamped per-row squared distance (unweighted; the caller folds
    weights into its inertia term)."""
    d2 = ((xs * xs).sum(1)[:, None] - 2.0 * (xs @ c.T)
          + (c * c).sum(1)[None, :])
    labels = np.argmin(d2, axis=1)
    k, d = c.shape
    sums = np.zeros((k, d), np.float64)
    np.add.at(sums, labels, xs * ws[:, None])
    counts = np.zeros(k, np.float64)
    np.add.at(counts, labels, ws)
    best = np.maximum(d2[np.arange(len(xs)), labels], 0.0)
    return labels, sums, counts, best


# ---------------------------------------------------------------------------
# per-row argmin/argmax API (folded from matrix/argminmax.py)
# ---------------------------------------------------------------------------


def argmin(res, matrix):
    """Index of the minimum of each row (ref: argmin.cuh). Tie-breaking:
    smallest index wins, matching the reference's KVP atomics."""
    return jnp.argmin(jnp.asarray(matrix), axis=1).astype(jnp.int32)


def argmax(res, matrix):
    """Index of the maximum of each row (ref: argmax.cuh)."""
    return jnp.argmax(jnp.asarray(matrix), axis=1).astype(jnp.int32)
