"""Pallas radix-rank select: exact batched top-k below the sort roofline.

The round-3 hardware grid (``tpu_battery_out/bench_full.jsonl``,
matrix/select_k*) showed every `lax.top_k`-based winner at k >= 256
running at ~1% of HBM bandwidth (8192x8192 f32 = 256 MB selected in
46 ms = 5.8 GB/s) — a ~50x roofline gap.  This module is the TPU
re-design of the reference's radix selection (ref:
matrix/detail/select_radix.cuh:639 — the "Air Top-k" multi-pass
histogram filter): same exact-threshold idea, but shaped for the MXU/VPU
instead of warp atomics, and with the candidate COMPACTION step — the
part CUDA does with global-atomic buffers, previously believed
inexpressible on TPU — done as a one-hot rank CONTRACTION on the MXU.

Two Pallas kernels over a precomputed sortable-key array:

1. `_threshold_kernel` — rows resident in VMEM, a 32-step bitwise binary
   search finds the EXACT k-th smallest key per row (the reference's
   per-digit histogram walk collapses to count(key <= probe) reductions:
   one VPU compare+reduce per bit, zero extra HBM traffic). Also emits
   `n_tie` = how many threshold-equal elements belong in the output.
2. `_emit_kernel` — streams the rows once more; per chunk it computes
   each candidate's output slot (a running rank carried across grid
   steps; the in-chunk exclusive cumsum is a rotate+mask log-scan —
   round 3 used a (tl, tl) triangular matmul because the concat-shift
   spelling could not lower, round 5's legal pltpu.roll shifts cut that
   ~2K-cycle MXU cost to ~0.25K VPU), factorizes the slot one-hot as
   rank = 128*hi + lo,
   and contracts (one-hot_hi * column-index-part) against one-hot_lo on
   the MXU — emitting winner indices without a sort, scatter, or
   variable-length compaction.  Column indices (< 2^24) ride exactly in
   three bf16 parts (split via the bitcast rounding helper — the
   astype spelling would be folded by XLA's excess-precision pass, see
   linalg/contractions._round_to_bf16_f32).

Values are then a k-wide `take_along_axis` gather, and the final
best-first ordering a stable (R, k) sort by sortable key — ties keep
emission order, which IS ascending column order, reproducing the
reference's first-come tie rule (select_radix.cuh's
last-filter-pass in-order candidate writes).

Key domain: floats map through the sign-magnitude fold
``b ^ ((b >> 31) & 0x7fffffff)`` (IEEE total order: -NaN < -inf,
+NaN > +inf — the same order the reference's radix bit-twiddle
induces); ints widen; uint32 re-biases; select_max is ``~key``.
NaN payloads and every value bit survive (values are gathered, never
arithmetically transformed).

Supported: f32/bf16/f16 + (u)int8/16/32 values, n_cols <= 2^24 (index
exactness in three bf16 parts), k <= 16384.  Callers (select_k) fall
back to the tournament paths outside that envelope.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from raft_tpu.linalg.contractions import _VMEM_BUDGET, _round_to_bf16_f32
from raft_tpu.util.math import cdiv, round_up_to_multiple
from raft_tpu.util.pallas_utils import join_vma, out_struct, pallas_call

_I32_MAX = 0x7FFFFFFF
_I32_MIN = -0x80000000

# The emission chunk is deliberately wide (tl = 1024 where it fits):
# each grid step pays fixed overhead (dominated by the 128-wide one-hot
# builds; the in-chunk cumsum is a log-step roll scan, ~10 VPU passes).


def _emit_live_set_bytes(tm: int, tl: int, kh: int) -> int:
    """Simultaneously-live VMEM of one _emit_kernel grid step: the
    one-hot/index operand `a` (tm, 3kh, tl) bf16 + ohhi (tm, kh, tl)
    bf16 ride the kh axis; ohlo (tm, tl, 128) bf16; the roll-scan
    masks/carry (2tm, tl) f32 x ~2 live + key/excl/rank temporaries
    (~24 B/elem over (tm, tl)); the per-chunk count blocks
    (2 x (tm, wc<=1024) i32); slabs (tm, 3kh, 128) f32 and the
    (tm, kh*128) f32 output block."""
    return (8 * tm * kh * tl          # a + ohhi
            + 256 * tm * tl           # ohlo
            + 24 * tm * tl            # key/masks/scan carry/excl/rank
            + 8 * tm * 1024           # lt/eq count blocks (wc cap)
            + 1536 * tm * kh          # slabs
            + 512 * tm * kh)          # out block


def _emit_tiles(kh: int) -> Tuple[int, int]:
    """(tm, tl) for the emission kernel: the largest tile whose live set
    fits the ~10 MB working-set budget (contractions._VMEM_BUDGET).
    kh <= 16 (the whole preferred dispatch band, k <= 2048) keeps the
    round-3 (16, 1024) tile — the hardware-validated band, so tm = 16
    is not offered above it even where the estimate would fit; larger
    k — reachable via the explicit RADIX_* enums up to MAX_K — shrinks
    tl before tm so the (tm, 3kh, tl) operand cannot blow VMEM (advisor
    finding, round 3: at kh=128/tm=8/tl=1024 the live set is
    ~14-15 MB)."""
    candidates = ((16, 1024),) if kh <= 16 else ()
    candidates += ((8, 1024), (8, 512), (8, 256), (8, 128))
    for tm, tl in candidates:
        if _emit_live_set_bytes(tm, tl, kh) <= _VMEM_BUDGET:
            return tm, tl
    return 8, 128

# One row lives VMEM-resident in the threshold kernel: 1M * 4 B = 4 MB,
# ~8 MB with Pallas double-buffering — inside the same ~10 MB working-set
# budget every other kernel sizes to (contractions._VMEM_BUDGET). Rows
# past CHUNK_LEN run the exact two-level scheme (per-chunk select, then
# one merge select over the C*k pool — see radix_select_k), so the
# supported length is bounded by index exactness (the emission encodes
# columns in three bf16 parts: 24 mantissa bits), the reference
# radix_topk's multi-block role (matrix/detail/select_radix.cuh:877).
CHUNK_LEN = 1 << 20
MAX_LEN = 1 << 24
MAX_K = 16384


def supports(dtype, n_cols: int, k: int) -> bool:
    """Whether the radix path handles this problem (callers fall back)."""
    dt = jnp.dtype(dtype)
    ok = dt in (jnp.dtype(jnp.float32), jnp.dtype(jnp.bfloat16),
                jnp.dtype(jnp.float16), jnp.dtype(jnp.int8),
                jnp.dtype(jnp.int16), jnp.dtype(jnp.int32),
                jnp.dtype(jnp.uint8), jnp.dtype(jnp.uint16),
                jnp.dtype(jnp.uint32))
    if n_cols > CHUNK_LEN:
        # two-level: the merge pool must itself be a supported problem
        n_chunks = cdiv(n_cols, CHUNK_LEN)
        if n_chunks * k > CHUNK_LEN:
            return False
    return ok and k <= n_cols and n_cols <= MAX_LEN and k <= MAX_K


# Minimum row length of the preferred band (exported so callers sizing
# their own tiles — the chunked kNN gate — stay in lockstep).
MIN_COLS = 8192


def preferred(n_cols: int, k: int) -> bool:
    """The single source of truth for the dispatch band where radix is
    expected to win (select_k AUTO and the chunked kNN path both gate on
    this). Long rows (>= 2^20): the 17:11 round-5 four-way grid
    (tpu_battery_out/select_k_derive.txt) shows radix winning from
    k=2048 up (53.4 ms vs direct 60.4/tiled 68.2; k=10^4: 72.6 vs
    114.8/269.7) while TILED edges it at k=256 (47.7 vs 49.5, and 48.9
    vs 56.0 at 4M) — the band starts above 256 (512-1024 interpolated:
    radix's cost is near-flat in k, direct's grows). Short rows keep
    the round-3-derived (16, 2048] band until the select_k family's
    65k grid lands (rc=124 both round-5 passes)."""
    if n_cols >= (1 << 20):
        return 256 < k <= MAX_K
    return n_cols >= MIN_COLS and 16 < k <= 2048


def _to_key(values: jnp.ndarray, select_min: bool) -> jnp.ndarray:
    """Order-preserving map into int32 ("sortable key") — ascending key
    == ascending IEEE-total-order value. One fused XLA elementwise pass;
    the kernels then work dtype-free."""
    v = values
    if jnp.issubdtype(v.dtype, jnp.floating):
        f = v.astype(jnp.float32)  # exact + monotone for f16/bf16
        b = jax.lax.bitcast_convert_type(f, jnp.int32)
        key = b ^ ((b >> 31) & jnp.int32(_I32_MAX))
    elif v.dtype == jnp.uint32:
        # unsigned order -> signed order: flip the top bit
        key = jax.lax.bitcast_convert_type(v, jnp.int32) ^ jnp.int32(
            _I32_MIN)
    else:
        key = v.astype(jnp.int32)
    return key if select_min else ~key


def _threshold_kernel(key_ref, t_ref, ntie_ref, *, k: int):
    """Exact k-th smallest key per row for a BLOCK of rows (grid step =
    tm rows) via a per-row bitwise binary search. Rows arrive reshaped
    (tm, Lp/128, 128) so both Mosaic-tiled dims are aligned regardless
    of row length; tm scales with VMEM budget so short-row/many-row
    problems (the chunked kNN shape) don't pay one grid step per row.

    Invariant entering the step for bit b: T in
    [prefix, prefix + 2^(b+1) - 1]. probe = prefix + 2^b - 1 tests
    whether T fits with bit b clear: count(key <= probe) >= k keeps the
    bit 0, else the bit is set. The sign bit is the seed step (negatives
    sort below in the signed key domain). Padded tail columns hold
    INT32_MAX; probes only reach INT32_MAX where the answer is forced
    (count >= k trivially), so the padding never biases a decision."""
    kk = jnp.float32(k)
    tm = t_ref.shape[0]
    blk = key_ref.shape                  # (tm, ls, 128)

    def count_le(t):
        # t (tm, 1) — broadcast_in_dim, NOT a reshape: a (tm,) -> (tm,1,1)
        # reshape crashes Mosaic's VectorLayoutInferer for tm > 1
        # ("arr.size() >= layout_rank(implicit_dim)", layout.h:320; round-5
        # deviceless-AOT bisect), so every intermediate here stays rank-2
        # and the block compare broadcasts the rank-2 threshold directly.
        # Re-read the block per call: keeps its live range inside one loop
        # iteration instead of spanning the fori_loop.
        if tm == 1:
            # the CHUNK_LEN single-row block: rank-3 reductions with a unit
            # leading dim leave implicit-dim layouts Mosaic rejects either
            # way it is reduced; drop to 2-D by reading off the unit dim
            tb = jax.lax.broadcast_in_dim(t, blk[1:], (0, 1))
            m = (key_ref[0] <= tb).astype(jnp.float32)     # (ls, 128)
            c2 = jnp.sum(m, axis=0, keepdims=True)         # (1, 128)
        else:
            tb = jax.lax.broadcast_in_dim(t, blk, (0, 1))
            m = (key_ref[:] <= tb).astype(jnp.float32)
            c2 = jnp.sum(m, axis=2)                        # (tm, ls)
        return jnp.sum(c2, axis=1, keepdims=True)          # (tm, 1)

    neg = count_le(jnp.full((tm, 1), -1, jnp.int32))
    prefix = jnp.where(neg >= kk, jnp.int32(_I32_MIN), jnp.int32(0))

    # The probed bit rides in the CARRY (2^30 halving each step) instead
    # of being derived from the fori index: referencing the loop index in
    # the body trips a RecursionError in jax.export's lowering under
    # jax_enable_x64 (jax 0.9.0; reproduced minimally — any use of `i`
    # inside a pallas_call fori body recurses; ignoring it is fine).
    def body(_, carry):
        prefix, bit = carry
        probe = prefix + bit - jnp.int32(1)
        cnt = count_le(probe)
        return (jnp.where(cnt < kk, probe + jnp.int32(1), prefix),
                bit >> jnp.int32(1))

    t, _ = jax.lax.fori_loop(0, 31, body,
                             (prefix, jnp.int32(1 << 30)))
    # count(key < T) — at T = INT32_MIN nothing is below
    c_less = jnp.where(t == jnp.int32(_I32_MIN), jnp.float32(0.0),
                       count_le(t - jnp.int32(1)))
    # stores via broadcast_in_dim to the (tm, 1, 1) refs — the 3-D ref
    # shape is the only BlockSpec legal at every tm (trailing dims must
    # be (8,128)-divisible or equal the array's), and broadcast avoids
    # the rank-changing reshape that crashes the layout inferer
    t_ref[:] = jax.lax.broadcast_in_dim(t, (tm, 1, 1), (0, 1))
    ntie = jnp.int32(k) - c_less.astype(jnp.int32)
    ntie_ref[:] = jax.lax.broadcast_in_dim(ntie, (tm, 1, 1), (0, 1))


def _emit_kernel(key_ref, t_ref, ntie_ref, lt_ref, eq_ref, out_ref,
                 less_run, tie_run, *,
                 k: int, kh: int, tl: int, tm: int, wc: int):
    """Emit each candidate's global column index into its output slot.

    rank(candidate) = #earlier-candidates; strict-below-threshold
    elements first (in column order), then the first `n_tie`
    threshold-equal elements. Slot one-hot factorizes as
    rank = 128*hi + lo; the index value rides the hi side in three exact
    bf16 parts and one ROW-BATCHED (tm, 3*kh, tl) @ (tm, tl, 128)
    dot_general accumulates all three parts' slabs, summed into the
    (kh*128,) output block f32-exactly (each slot receives exactly one
    candidate). Batching the rows through one dot keeps the kernel body
    compact (the earlier per-row unrolled loop grew the module with tm
    and serialized tm small matmuls per grid step).

    DEAD-CHUNK SKIP (round 5): ``lt_ref``/``eq_ref`` hold resident
    (tm, wc) per-chunk strict/tie counts (precomputed in XLA from the
    threshold). A chunk with no strict candidate and no tie quota left
    emits nothing — its whole body (the triangular cumsum matmul, both
    one-hot builds, the slab dot: the emission's fixed cost) is skipped
    and the running ranks advance from the precomputed counts. At small
    k over long rows most chunks are dead (k=16 at 1M: ~2 live of 1024);
    at k ~ tl all chunks are live and the only cost is the column
    extraction (~wc/128 vector ops)."""
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        out_ref[:] = jnp.zeros_like(out_ref)
        less_run[:] = jnp.zeros_like(less_run)
        tie_run[:] = jnp.zeros_like(tie_run)

    ntie = ntie_ref[:]                                 # (tm, 1)
    run_less = less_run[:]                             # (tm, 1) i32
    run_tie = tie_run[:]
    iota_w = jax.lax.broadcasted_iota(jnp.int32, (tm, wc), 1)
    selj = iota_w == j
    zf = jnp.float32(0.0)
    lt_j = jnp.sum(jnp.where(selj, lt_ref[:].astype(jnp.float32), zf),
                   axis=1, keepdims=True).astype(jnp.int32)
    eq_j = jnp.sum(jnp.where(selj, eq_ref[:].astype(jnp.float32), zf),
                   axis=1, keepdims=True).astype(jnp.int32)
    # 32-bit reduction: jnp.any's bool proxy reduces through f64 under
    # jax_enable_x64 and the scalar squeeze fails Mosaic export (same
    # class as the fori-index pitfall above)
    live_v = (lt_j > 0) | ((eq_j > 0) & (run_tie < ntie))
    live = jnp.max(live_v.astype(jnp.int32)) > 0

    @pl.when(jnp.logical_not(live))
    def _skip():
        less_run[:] = run_less + lt_j
        tie_run[:] = run_tie + eq_j

    @pl.when(live)
    def _process():
        _emit_chunk_body(key_ref, t_ref, out_ref, less_run, tie_run,
                         run_less, run_tie, ntie, lt_j, eq_j, j,
                         k=k, kh=kh, tl=tl, tm=tm)


def _emit_chunk_body(key_ref, t_ref, out_ref, less_run, tie_run,
                     run_less, run_tie, ntie, lt_j, eq_j, j, *,
                     k: int, kh: int, tl: int, tm: int):
    key = key_ref[:]                                   # (tm, tl) i32
    t = t_ref[:]                                       # (tm, 1)
    strict = key < t
    tie = key == t

    # In-chunk EXCLUSIVE cumsums via a log-step roll scan — rotate+mask
    # is the legal lane-shift spelling (round 5; the concat-of-slices
    # shift needed relayouts Mosaic cannot do, which is why round 3 used
    # a (tl, tl) triangular MATMUL here: ~tl MACs per element, the
    # dominant live-chunk cost at ~2K MXU cycles per step vs ~0.25K VPU
    # for the scan). Counts are integers in f32 — exact under any
    # association. One fused scan covers both masks (sublane stack).
    masks = jnp.concatenate(
        [strict.astype(jnp.float32), tie.astype(jnp.float32)], axis=0)
    lane = jax.lax.broadcasted_iota(jnp.int32, masks.shape, 1)
    c = masks
    d = 1
    while d < tl:
        r = pltpu.roll(c, jnp.int32(d), 1)
        c = c + jnp.where(lane >= d, r, jnp.float32(0.0))
        d *= 2
    excl = c - masks                                   # exclusive
    excl_strict = excl[:tm].astype(jnp.int32)          # (tm, tl)
    excl_tie = excl[tm:].astype(jnp.int32)

    member_tie = tie & ((run_tie + excl_tie) < ntie)
    c_less_total = jnp.int32(k) - ntie
    rank = jnp.where(strict, run_less + excl_strict,
                     c_less_total + run_tie + excl_tie)
    member = strict | member_tie
    hi = jnp.where(member, rank >> 7, jnp.int32(-1))   # -1: no slot
    lo = rank & jnp.int32(127)

    # Global column index of each chunk element, in three exact bf16
    # parts (col < 2^24 = 8+8+8 mantissa bits).
    col = (jnp.float32(j * tl)
           + jax.lax.broadcasted_iota(jnp.int32, (1, tl), 1)
           .astype(jnp.float32))
    p0 = _round_to_bf16_f32(col)
    r1 = col - p0
    p1 = _round_to_bf16_f32(r1)
    p2 = r1 - p1

    iota_h = jax.lax.broadcasted_iota(jnp.int32, (1, kh, 1), 1)
    iota_l = jax.lax.broadcasted_iota(jnp.int32, (1, 1, 128), 2)
    ohhi = (iota_h == hi[:, None, :]).astype(jnp.bfloat16)  # (tm, kh, tl)
    pb0 = p0.astype(jnp.bfloat16)[None, :, :]          # (1, 1, tl)
    pb1 = p1.astype(jnp.bfloat16)[None, :, :]
    pb2 = p2.astype(jnp.bfloat16)[None, :, :]
    a = jnp.concatenate([ohhi * pb0, ohhi * pb1, ohhi * pb2],
                        axis=1)                        # (tm, 3kh, tl)
    ohlo = (lo[:, :, None] == iota_l).astype(jnp.bfloat16)  # (tm, tl, 128)
    slabs = jax.lax.dot_general(
        a, ohlo, (((2,), (1,)), ((0,), (0,))),
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.DEFAULT)           # (tm, 3kh, 128)
    slab = (slabs[:, :kh] + slabs[:, kh:2 * kh] + slabs[:, 2 * kh:]
            ).reshape(tm, kh * 128)
    out_ref[:] += slab

    # the precomputed per-chunk counts ARE this chunk's strict/tie sums
    # (same compare against the same threshold) — no extra reductions
    less_run[:] = run_less + lt_j
    tie_run[:] = run_tie + eq_j


@functools.partial(jax.jit, static_argnames=("k",))
def _radix_ranks(keys: jnp.ndarray, k: int) -> jnp.ndarray:
    """keys (R, L) i32 -> winner column indices (R, k) i32, in ascending
    column order (strict-below first, then in-order threshold ties)."""
    n_rows, n_cols = keys.shape
    # lp multiple of 1024 so the (lp/128, 128) row view is sublane-aligned
    lp = round_up_to_multiple(n_cols, 1024)
    # rows per threshold grid step: fill the VMEM budget (the whole point
    # — many-row/short-row problems like the chunked kNN shape must not
    # pay one grid step per row); power of two so rp stays a common
    # multiple with the emission row block
    # emission row block: wider halves the grid-step count (per-step
    # overhead is the emission's fixed cost at many-row shapes); at
    # large k the (tm, 3*kh, tl) operand would blow VMEM, so fall back
    kh = cdiv(k, 128)
    # tile sized from the FULL emission live set (≈ 8.6 MB at
    # kh=16/tm=16/tl=1024; tl shrinks as kh grows past the preferred
    # band so the explicit-enum k <= MAX_K route stays inside budget)
    tm_e, tl_e = _emit_tiles(kh)
    tm_a = 1
    row_cap = round_up_to_multiple(n_rows, tm_e)
    # grow only while the resulting row padding stays at the emission
    # minimum — a bigger threshold block must never force extra pad rows
    # (they would ride through BOTH kernels)
    while (tm_a * 2 * lp * 4 <= CHUNK_LEN * 4 and tm_a < 128
           and round_up_to_multiple(n_rows, max(tm_a * 2, tm_e))
           == row_cap):
        tm_a *= 2
    rp = round_up_to_multiple(n_rows, max(tm_a, tm_e))
    kpad = jnp.pad(keys, ((0, rp - n_rows), (0, lp - n_cols)),
                   constant_values=_I32_MAX)
    ls = lp // 128
    # shard_map plumbing (contractions.py pattern): operands pcast to
    # the joint varying-mesh-axes, out_shapes declare the same vma
    vma, (kpad,) = join_vma(kpad)

    t3, ntie3 = pallas_call(
        functools.partial(_threshold_kernel, k=k),
        grid=(rp // tm_a,),
        in_specs=[pl.BlockSpec((tm_a, ls, 128), lambda i: (i, 0, 0),
                               memory_space=pltpu.VMEM)],
        out_specs=[pl.BlockSpec((tm_a, 1, 1), lambda i: (i, 0, 0),
                                memory_space=pltpu.VMEM),
                   pl.BlockSpec((tm_a, 1, 1), lambda i: (i, 0, 0),
                                memory_space=pltpu.VMEM)],
        out_shape=[out_struct((rp, 1, 1), jnp.int32, vma),
                   out_struct((rp, 1, 1), jnp.int32, vma)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary",),
            # the count intermediates at the VMEM-filling tm_a sit just
            # over the default 16M scoped budget (16.87M observed at
            # tm_a=64, lp=8192 — round-5 deviceless AOT)
            vmem_limit_bytes=32 * 1024 * 1024),
    )(kpad.reshape(rp, ls, 128))
    t = t3.reshape(rp, 1)
    ntie = ntie3.reshape(rp, 1)

    tm, tl = tm_e, tl_e
    # per-chunk strict/tie counts for the emission's dead-chunk skip —
    # computed in plain XLA (layout-free; one extra streaming pass over
    # the keys) and held resident in the kernel as (tm, wc) blocks
    nch = lp // tl
    wc = round_up_to_multiple(nch, 128)
    lt_map = jnp.sum((kpad < t).reshape(rp, nch, tl), axis=2,
                     dtype=jnp.int32)
    le_map = jnp.sum((kpad <= t).reshape(rp, nch, tl), axis=2,
                     dtype=jnp.int32)
    eq_map = le_map - lt_map
    lt_map = jnp.pad(lt_map, ((0, 0), (0, wc - nch)))
    eq_map = jnp.pad(eq_map, ((0, 0), (0, wc - nch)))

    idx_f = pallas_call(
        functools.partial(_emit_kernel, k=k, kh=kh, tl=tl, tm=tm, wc=wc),
        grid=(rp // tm, nch),
        in_specs=[
            pl.BlockSpec((tm, tl), lambda i, j: (i, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((tm, 1), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((tm, 1), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((tm, wc), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((tm, wc), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((tm, kh * 128), lambda i, j: (i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=out_struct((rp, kh * 128), jnp.float32, vma),
        scratch_shapes=[pltpu.VMEM((tm, 1), jnp.int32),
                        pltpu.VMEM((tm, 1), jnp.int32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary", "arbitrary")),
    )(kpad, t, ntie, lt_map, eq_map)

    return idx_f[:n_rows, :k].astype(jnp.int32)


def radix_select_k(values: jnp.ndarray, k: int,
                   select_min: bool = True
                   ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Exact batched top-k (smallest if select_min) of values (R, L).

    Returns (vals (R, k), idx (R, k)) sorted best-first; threshold ties
    resolve to the lowest column indices (reference tie rule). Callers
    must check :func:`supports` first.
    """
    values = jnp.asarray(values)
    n_rows, n_cols = values.shape
    if not supports(values.dtype, n_cols, k):
        raise ValueError(
            f"radix_select_k: unsupported problem (dtype={values.dtype}, "
            f"n_cols={n_cols}, k={k}); check supports()")
    keys = _to_key(values, select_min)

    if n_cols > CHUNK_LEN:
        # Two-level exact select for rows past the VMEM-resident bound
        # (the reference's multi-block radix_topk role,
        # matrix/detail/select_radix.cuh:877): per-chunk exact top-k,
        # then ONE exact merge select over the C*k candidate pool. Tie
        # contract: within a chunk, EQUAL-key winners keep ascending
        # column order (equal keys share a strict/tie segment, and each
        # segment is emitted column-ordered — the full emission is NOT
        # column-sorted, strict-belows precede ties), and the pool is
        # chunk-major, so pool position ascends with global column among
        # equal keys; the merge pass's position-order tie rule therefore
        # reproduces the global lowest-column contract exactly. The
        # final stable sort must stay keyed on the sortable key alone.
        n_chunks = cdiv(n_cols, CHUNK_LEN)
        lc = round_up_to_multiple(cdiv(n_cols, n_chunks), 1024)
        kc = jnp.pad(keys, ((0, 0), (0, n_chunks * lc - n_cols)),
                     constant_values=_I32_MAX
                     ).reshape(n_rows * n_chunks, lc)
        idx_c = _radix_ranks(kc, k)
        # every downstream gather stays CHUNK-LOCAL — a gather from the
        # full-width row fuses the whole row into VMEM (274M > 128M at
        # 2^22 cols, observed on the v5e AOT compile)
        pool_k = jnp.take_along_axis(kc, idx_c, axis=1
                                     ).reshape(n_rows, n_chunks * k)
        vc = jnp.pad(values, ((0, 0), (0, n_chunks * lc - n_cols))
                     ).reshape(n_rows * n_chunks, lc)
        pool_v = jnp.take_along_axis(vc, idx_c, axis=1
                                     ).reshape(n_rows, n_chunks * k)
        # global column ids of the pool candidates (chunk-major)
        base = (jnp.arange(n_chunks, dtype=jnp.int32) * lc)[None, :, None]
        pool_i = (idx_c.reshape(n_rows, n_chunks, k) + base
                  ).reshape(n_rows, n_chunks * k)
        # pad-chunk winners carry _I32_MAX keys, so they cannot win the
        # merge while any real candidate remains (k <= n_cols contract)
        idx_m = _radix_ranks(pool_k, k)
        idx = jnp.take_along_axis(pool_i, idx_m, axis=1)
        out_k = jnp.take_along_axis(pool_k, idx_m, axis=1)
        out_v = jnp.take_along_axis(pool_v, idx_m, axis=1)
    else:
        idx = _radix_ranks(keys, k)
        out_v = jnp.take_along_axis(values, idx, axis=1)
        out_k = jnp.take_along_axis(keys, idx, axis=1)
    # Best-first ordering: stable sort by sortable key keeps the
    # emission's ascending-column order among equal values.
    out_k, out_v, idx = jax.lax.sort((out_k, out_v, idx), dimension=1,
                                     is_stable=True, num_keys=1)
    return out_v, idx
