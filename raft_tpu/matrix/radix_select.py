"""Pallas radix-rank select: exact batched top-k below the sort roofline.

The round-3 hardware grid (``tpu_battery_out/bench_full.jsonl``,
matrix/select_k*) showed every `lax.top_k`-based winner at k >= 256
running at ~1% of HBM bandwidth (8192x8192 f32 = 256 MB selected in
46 ms = 5.8 GB/s) — a ~50x roofline gap.  This module is the TPU
re-design of the reference's radix selection (ref:
matrix/detail/select_radix.cuh:639 — the "Air Top-k" multi-pass
histogram filter): same exact-threshold idea, but shaped for the MXU/VPU
instead of warp atomics, and with the candidate COMPACTION step — the
part CUDA does with global-atomic buffers, previously believed
inexpressible on TPU — done as a one-hot rank CONTRACTION on the MXU.

Two Pallas kernels over a precomputed sortable-key array:

1. `_threshold_kernel` — the reference's multi-pass digit-histogram
   walk, rebuilt for the MXU: NPASS=4 passes over the row (8-bit
   digits of the bias-folded 32-bit key, most-significant first); each
   pass streams the row once, builds a 256-bin per-row histogram as a
   FACTORIZED one-hot contraction (digit = 16·hi + lo; a (tm,16,tl)
   one-hot batched against a (tm,tl,16) one-hot gives exact f32
   counts on the MXU — no atomics needed), then narrows to the bin
   holding the k-th element. Four streamed passes replace the round-3
   32-step binary search (32 full-row VPU reduction sweeps over a
   VMEM-resident row — measured 3.6–6.4 GB/s, ~0.5–0.8% of HBM,
   ~25× off its own cost model; VERDICT Weak #1), cutting threshold
   HBM traffic 8×. Also emits `n_tie` = how many threshold-equal
   elements belong in the output (the running `want` after the last
   narrowing IS the tie quota).
2. `_emit_kernel` — streams the rows once more; per chunk it computes
   each candidate's output slot (a running rank carried across grid
   steps; the in-chunk exclusive cumsum is a rotate+mask log-scan —
   round 3 used a (tl, tl) triangular matmul because the concat-shift
   spelling could not lower, round 5's legal pltpu.roll shifts cut that
   ~2K-cycle MXU cost to ~0.25K VPU), factorizes the slot one-hot as
   rank = 128*hi + lo,
   and contracts (one-hot_hi * column-index-part) against one-hot_lo on
   the MXU — emitting winner indices without a sort, scatter, or
   variable-length compaction.  Column indices (< 2^24) ride exactly in
   three bf16 parts (split via the bitcast rounding helper — the
   astype spelling would be folded by XLA's excess-precision pass, see
   linalg/contractions._round_to_bf16_f32).

Values are then a k-wide `take_along_axis` gather, and the final
best-first ordering a stable (R, k) sort by sortable key — ties keep
emission order, which IS ascending column order, reproducing the
reference's first-come tie rule (select_radix.cuh's
last-filter-pass in-order candidate writes).

Key domain: floats map through the sign-magnitude fold
``b ^ ((b >> 31) & 0x7fffffff)`` (IEEE total order: -NaN < -inf,
+NaN > +inf — the same order the reference's radix bit-twiddle
induces); ints widen; uint32 re-biases; select_max is ``~key``.
NaN payloads and every value bit survive (values are gathered, never
arithmetically transformed).

Supported: f32/bf16/f16 + (u)int8/16/32 values, n_cols <= 2^24 (index
exactness in three bf16 parts), k <= 16384.  Callers (select_k) fall
back to the tournament paths outside that envelope.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from raft_tpu.core import trace
from raft_tpu.linalg.contractions import _VMEM_BUDGET, _round_to_bf16_f32
from raft_tpu.matrix.epilogue import (onehot_histogram, onehot_pair,
                                      slot_onehot)
from raft_tpu.util.math import cdiv, round_up_to_multiple
from raft_tpu.util.pallas_utils import join_vma, out_struct, pallas_call

_I32_MAX = 0x7FFFFFFF
_I32_MIN = -0x80000000

# The emission chunk is deliberately wide (tl = 1024 where it fits):
# each grid step pays fixed overhead (dominated by the 128-wide one-hot
# builds; the in-chunk cumsum is a log-step roll scan, ~10 VPU passes).


def _emit_live_set_bytes(tm: int, tl: int, kh: int) -> int:
    """Simultaneously-live VMEM of one _emit_kernel grid step: the
    one-hot/index operand `a` (tm, 3kh, tl) bf16 + ohhi (tm, kh, tl)
    bf16 ride the kh axis; ohlo (tm, tl, 128) bf16; the roll-scan
    masks/carry (2tm, tl) f32 x ~2 live + key/excl/rank temporaries
    (~24 B/elem over (tm, tl)); the per-chunk count blocks
    (2 x (tm, wc<=1024) i32); slabs (tm, 3kh, 128) f32 and the
    (tm, kh*128) f32 output block."""
    return (8 * tm * kh * tl          # a + ohhi
            + 256 * tm * tl           # ohlo
            + 24 * tm * tl            # key/masks/scan carry/excl/rank
            + 8 * tm * 1024           # lt/eq count blocks (wc cap)
            + 1536 * tm * kh          # slabs
            + 512 * tm * kh)          # out block


def _emit_tiles(kh: int) -> Tuple[int, int]:
    """(tm, tl) for the emission kernel: the largest tile whose live set
    fits the ~10 MB working-set budget (contractions._VMEM_BUDGET).
    kh <= 16 (the whole preferred dispatch band, k <= 2048) keeps the
    round-3 (16, 1024) tile — the hardware-validated band, so tm = 16
    is not offered above it even where the estimate would fit; larger
    k — reachable via the explicit RADIX_* enums up to MAX_K — shrinks
    tl before tm so the (tm, 3kh, tl) operand cannot blow VMEM (advisor
    finding, round 3: at kh=128/tm=8/tl=1024 the live set is
    ~14-15 MB)."""
    candidates = ((16, 1024),) if kh <= 16 else ()
    candidates += ((8, 1024), (8, 512), (8, 256), (8, 128))
    for tm, tl in candidates:
        if _emit_live_set_bytes(tm, tl, kh) <= _VMEM_BUDGET:
            return tm, tl
    return 8, 128

# Both kernels stream the row at chunk granularity, so CHUNK_LEN is no
# longer a VMEM-residency bound (that was the retired binary-search
# threshold). It remains the single-level bound because past it the
# emission's dead-chunk count maps and the k-wide gathers grow with the
# row; longer rows run the exact two-level scheme (per-chunk select,
# then one merge select over the C*k pool — see radix_select_k), so the
# supported length is bounded by index exactness (the emission encodes
# columns in three bf16 parts: 24 mantissa bits), the reference
# radix_topk's multi-block role (matrix/detail/select_radix.cuh:877).
CHUNK_LEN = 1 << 20
MAX_LEN = 1 << 24
MAX_K = 16384


def supports(dtype, n_cols: int, k: int) -> bool:
    """Whether the radix path handles this problem (callers fall back)."""
    dt = jnp.dtype(dtype)
    ok = dt in (jnp.dtype(jnp.float32), jnp.dtype(jnp.bfloat16),
                jnp.dtype(jnp.float16), jnp.dtype(jnp.int8),
                jnp.dtype(jnp.int16), jnp.dtype(jnp.int32),
                jnp.dtype(jnp.uint8), jnp.dtype(jnp.uint16),
                jnp.dtype(jnp.uint32))
    if n_cols > CHUNK_LEN:
        # two-level: the merge pool must itself be a supported problem
        n_chunks = cdiv(n_cols, CHUNK_LEN)
        if n_chunks * k > CHUNK_LEN:
            return False
    return ok and k <= n_cols and n_cols <= MAX_LEN and k <= MAX_K


# Minimum row length of the preferred band (exported so callers sizing
# their own tiles — the chunked kNN gate — stay in lockstep).
MIN_COLS = 8192


def preferred(n_cols: int, k: int) -> bool:
    """The single source of truth for the dispatch band where radix is
    expected to win (select_k AUTO and the chunked kNN path both gate on
    this). Long rows (>= 2^20): the 17:11 round-5 four-way grid
    (tpu_battery_out/select_k_derive.txt) shows radix winning from
    k=2048 up (53.4 ms vs direct 60.4/tiled 68.2; k=10^4: 72.6 vs
    114.8/269.7) while TILED edges it at k=256 (47.7 vs 49.5, and 48.9
    vs 56.0 at 4M) — the band starts above 256 (512-1024 interpolated:
    radix's cost is near-flat in k, direct's grows). Short rows: the
    digit-histogram rebuild (era 7) lifts the round-3 band's 2048 cap
    to MAX_K — the threshold is now ~NPASS streamed passes, flat in k,
    and those rows' old cap came from the retired binary search's
    cost at deep k (benches/select_model.py quantifies the ~6.6x
    byte-traffic cut; the era-7 armed battery rows re-adjudicate on
    hardware)."""
    if n_cols > MAX_LEN:
        return False               # outside the kernel envelope
    if n_cols >= (1 << 20):
        return 256 < k <= MAX_K
    return n_cols >= MIN_COLS and 16 < k <= MAX_K


def _to_key(values: jnp.ndarray, select_min: bool) -> jnp.ndarray:
    """Order-preserving map into int32 ("sortable key") — ascending key
    == ascending IEEE-total-order value. One fused XLA elementwise pass;
    the kernels then work dtype-free."""
    v = values
    if jnp.issubdtype(v.dtype, jnp.floating):
        f = v.astype(jnp.float32)  # exact + monotone for f16/bf16
        b = jax.lax.bitcast_convert_type(f, jnp.int32)
        key = b ^ ((b >> 31) & jnp.int32(_I32_MAX))
    elif v.dtype == jnp.uint32:
        # unsigned order -> signed order: flip the top bit
        key = jax.lax.bitcast_convert_type(v, jnp.int32) ^ jnp.int32(
            _I32_MIN)
    else:
        key = v.astype(jnp.int32)
    return key if select_min else ~key


# Threshold stage: the reference's multi-pass digit walk
# (select_radix.cuh:639), 32-bit keys as NPASS digits of DIGIT_BITS,
# most-significant first. Each pass streams the row once at chunk
# granularity — ~NPASS full-row passes total vs the 32 VPU reduction
# sweeps of the retired binary search.
NPASS = 4
DIGIT_BITS = 8
_NBINS = 1 << DIGIT_BITS            # 256, factorized as 16 x 16


def _hist_live_set_bytes(tm: int, tl: int) -> int:
    """Simultaneously-live VMEM of one threshold grid step: the key
    chunk (x2, Pallas double-buffered) i32; biased-key/digit/nibble/
    active temporaries (~20 B/elem); the two 16-deep one-hot operands
    bf16 (64 B/elem over (tm, tl)); the (tm, 16, 16) f32 histogram and
    its bin-scan temporaries."""
    return (8 * tm * tl       # key chunk, double-buffered
            + 20 * tm * tl    # ukey/digit/nibbles/active temporaries
            + 64 * tm * tl    # ohhi (tm,16,tl) + ohlo (tm,tl,16) bf16
            + 8192 * tm)      # histogram + cumsum/bin-select scratch


def _hist_tiles(n_rows: int, lp: int, tm_e: int) -> Tuple[int, int]:
    """(tm, tl) for the threshold kernel. tl: the widest lane chunk
    dividing lp (lp is a 1024-multiple, so 1024 always divides); tm
    grows while the live set fits the ~10 MB working-set budget AND the
    row padding stays at the emission minimum — a bigger threshold
    block must never force extra pad rows (they would ride through
    BOTH kernels)."""
    tl = max(t for t in (8192, 4096, 2048, 1024) if lp % t == 0)
    tm = 8
    row_cap = round_up_to_multiple(n_rows, tm_e)
    while (tm < 64
           and _hist_live_set_bytes(tm * 2, tl) <= _VMEM_BUDGET
           and round_up_to_multiple(n_rows, max(tm * 2, tm_e))
           == row_cap):
        tm *= 2
    return tm, tl


def _threshold_kernel(key_ref, t_ref, ntie_ref, hist, prefix, want, *,
                      k: int, nch: int):
    """Exact k-th smallest key per row for a BLOCK of rows via the
    multi-pass digit histogram. Grid (rows, NPASS, nch): the chunk
    axis is innermost, so each pass streams every (tm, tl) chunk of
    the row, accumulates the 256-bin per-row histogram in scratch,
    and narrows at the last chunk; `prefix`/`want` scratch carries the
    decided digits and the remaining rank across passes.

    The histogram is a FACTORIZED one-hot contraction (the emission
    kernel's idiom): digit = 16·hi + lo, and a row-batched
    (tm, 16, tl) @ (tm, tl, 16) dot of the two one-hots lands all 256
    bins as exact f32 counts on the MXU — the TPU replacement for the
    reference's shared-memory atomic histogram. Inactive elements
    (high digits ≠ prefix) are masked out of the hi one-hot.

    Invariant entering pass p: exactly `want` of the elements whose
    decided high digits equal `prefix` are <= the target (want starts
    at k and each pass subtracts the strictly-below mass it resolves —
    the union over passes of those masses is exactly {key < T}, so the
    final `want` IS the emission's tie quota n_tie). Padded tail
    columns hold INT32_MAX (all-ones biased key, the top bin); k <=
    n_cols means the target never lands past a real element, so the
    padding never biases a narrowing."""
    p = pl.program_id(1)
    j = pl.program_id(2)

    @pl.when((p == 0) & (j == 0))
    def _start():
        prefix[:] = jnp.zeros_like(prefix)
        want[:] = jnp.full_like(want, jnp.int32(k))

    @pl.when(j == 0)
    def _new_pass():
        hist[:] = jnp.zeros_like(hist)

    # bias fold: ^INT32_MIN maps signed key order onto lexicographic
    # unsigned digit order, so every pass is a plain MSD narrowing
    ukey = key_ref[:] ^ jnp.int32(_I32_MIN)              # (tm, tl)
    shift = jnp.int32(32) - jnp.int32(DIGIT_BITS) * (p + 1)
    # ACTIVE = the already-decided high digits equal the prefix. >> is
    # arithmetic; `decided` (2^(8p)-1) strips both the sign-extension
    # and the not-yet-decided low bits — at p=0 it is 0, making every
    # element active against the zero prefix. The shift amount clamps
    # at 31 (p=0 would shift by 32, undefined) where the zero mask
    # makes the result irrelevant anyway.
    amt = jnp.minimum(shift + jnp.int32(DIGIT_BITS), jnp.int32(31))
    decided = (jnp.int32(1) << (jnp.int32(DIGIT_BITS) * p)) - 1
    active = ((ukey >> amt) & decided) == prefix[:]      # (tm, tl)
    digit = (ukey >> shift) & jnp.int32(_NBINS - 1)
    hi = digit >> 4
    lo = digit & jnp.int32(15)
    # 0/1 bf16 operands, f32 accumulate: counts exact to 2^24 > MAX_LEN
    # (the factorized 16x16 contraction is epilogue.onehot_histogram —
    # one spelling shared with the emission's slot one-hots)
    hist[:] += onehot_histogram(hi, lo, active)          # (tm, 16, 16)

    @pl.when(j == nch - 1)
    def _narrow():
        # two-level bin scan over the completed histogram: pick the hi
        # nibble whose inclusive cumsum reaches `want`, then the lo
        # nibble within that histogram row. The 16-bin cumsum is a
        # broadcast-compare-sum over the 16x16 lower-triangular mask —
        # integer-valued f32, exact under any association.
        h2 = hist[:]                                     # (tm, 16, 16)
        wantf = want[:].astype(jnp.float32)              # (tm, 1)
        le = (jax.lax.broadcasted_iota(jnp.int32, (1, 16, 16), 1)
              <= jax.lax.broadcasted_iota(jnp.int32, (1, 16, 16), 2)
              ).astype(jnp.float32)

        def pick(bins, need):
            # bins (tm, 16): index of the bin where the inclusive
            # cumsum first reaches `need`, and the mass strictly below
            csum = jnp.sum(bins[:, :, None] * le, axis=1)  # (tm, 16)
            m = csum < need
            bstar = jnp.sum(m.astype(jnp.float32), axis=1,
                            keepdims=True).astype(jnp.int32)
            below = jnp.max(jnp.where(m, csum, jnp.float32(0.0)),
                            axis=1, keepdims=True)
            return bstar, below

        hstar, below_h = pick(jnp.sum(h2, axis=2), wantf)
        want_l = wantf - below_h
        ohsel = slot_onehot(hstar, 16)
        lstar, below_l = pick(jnp.sum(h2 * ohsel, axis=1), want_l)
        prefix[:] = ((prefix[:] << jnp.int32(DIGIT_BITS))
                     | (hstar << 4) | lstar)
        want[:] = (want_l - below_l).astype(jnp.int32)

    @pl.when((p == NPASS - 1) & (j == nch - 1))
    def _publish():
        # runs after _narrow (program order): prefix holds the full
        # biased key of the k-th smallest; want is its tie quota
        t_ref[:] = prefix[:] ^ jnp.int32(_I32_MIN)
        ntie_ref[:] = want[:]


def _emit_kernel(key_ref, t_ref, ntie_ref, lt_ref, eq_ref, out_ref,
                 less_run, tie_run, *,
                 k: int, kh: int, tl: int, tm: int, wc: int):
    """Emit each candidate's global column index into its output slot.

    rank(candidate) = #earlier-candidates; strict-below-threshold
    elements first (in column order), then the first `n_tie`
    threshold-equal elements. Slot one-hot factorizes as
    rank = 128*hi + lo; the index value rides the hi side in three exact
    bf16 parts and one ROW-BATCHED (tm, 3*kh, tl) @ (tm, tl, 128)
    dot_general accumulates all three parts' slabs, summed into the
    (kh*128,) output block f32-exactly (each slot receives exactly one
    candidate). Batching the rows through one dot keeps the kernel body
    compact (the earlier per-row unrolled loop grew the module with tm
    and serialized tm small matmuls per grid step).

    DEAD-CHUNK SKIP (round 5): ``lt_ref``/``eq_ref`` hold resident
    (tm, wc) per-chunk strict/tie counts (precomputed in XLA from the
    threshold). A chunk with no strict candidate and no tie quota left
    emits nothing — its whole body (the triangular cumsum matmul, both
    one-hot builds, the slab dot: the emission's fixed cost) is skipped
    and the running ranks advance from the precomputed counts. At small
    k over long rows most chunks are dead (k=16 at 1M: ~2 live of 1024);
    at k ~ tl all chunks are live and the only cost is the column
    extraction (~wc/128 vector ops)."""
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        out_ref[:] = jnp.zeros_like(out_ref)
        less_run[:] = jnp.zeros_like(less_run)
        tie_run[:] = jnp.zeros_like(tie_run)

    ntie = ntie_ref[:]                                 # (tm, 1)
    run_less = less_run[:]                             # (tm, 1) i32
    run_tie = tie_run[:]
    iota_w = jax.lax.broadcasted_iota(jnp.int32, (tm, wc), 1)
    selj = iota_w == j
    zf = jnp.float32(0.0)
    lt_j = jnp.sum(jnp.where(selj, lt_ref[:].astype(jnp.float32), zf),
                   axis=1, keepdims=True).astype(jnp.int32)
    eq_j = jnp.sum(jnp.where(selj, eq_ref[:].astype(jnp.float32), zf),
                   axis=1, keepdims=True).astype(jnp.int32)
    # 32-bit reduction: jnp.any's bool proxy reduces through f64 under
    # jax_enable_x64 and the scalar squeeze fails Mosaic export (the
    # x64-tier lowering pitfall pinned by test_mosaic_lowering)
    live_v = (lt_j > 0) | ((eq_j > 0) & (run_tie < ntie))
    live = jnp.max(live_v.astype(jnp.int32)) > 0

    @pl.when(jnp.logical_not(live))
    def _skip():
        less_run[:] = run_less + lt_j
        tie_run[:] = run_tie + eq_j

    @pl.when(live)
    def _process():
        _emit_chunk_body(key_ref, t_ref, out_ref, less_run, tie_run,
                         run_less, run_tie, ntie, lt_j, eq_j, j,
                         k=k, kh=kh, tl=tl, tm=tm)


def _emit_chunk_body(key_ref, t_ref, out_ref, less_run, tie_run,
                     run_less, run_tie, ntie, lt_j, eq_j, j, *,
                     k: int, kh: int, tl: int, tm: int):
    key = key_ref[:]                                   # (tm, tl) i32
    t = t_ref[:]                                       # (tm, 1)
    strict = key < t
    tie = key == t

    # In-chunk EXCLUSIVE cumsums via a log-step roll scan — rotate+mask
    # is the legal lane-shift spelling (round 5; the concat-of-slices
    # shift needed relayouts Mosaic cannot do, which is why round 3 used
    # a (tl, tl) triangular MATMUL here: ~tl MACs per element, the
    # dominant live-chunk cost at ~2K MXU cycles per step vs ~0.25K VPU
    # for the scan). Counts are integers in f32 — exact under any
    # association. One fused scan covers both masks (sublane stack).
    masks = jnp.concatenate(
        [strict.astype(jnp.float32), tie.astype(jnp.float32)], axis=0)
    lane = jax.lax.broadcasted_iota(jnp.int32, masks.shape, 1)
    c = masks
    d = 1
    while d < tl:
        r = pltpu.roll(c, jnp.int32(d), 1)
        c = c + jnp.where(lane >= d, r, jnp.float32(0.0))
        d *= 2
    excl = c - masks                                   # exclusive
    excl_strict = excl[:tm].astype(jnp.int32)          # (tm, tl)
    excl_tie = excl[tm:].astype(jnp.int32)

    member_tie = tie & ((run_tie + excl_tie) < ntie)
    c_less_total = jnp.int32(k) - ntie
    rank = jnp.where(strict, run_less + excl_strict,
                     c_less_total + run_tie + excl_tie)
    member = strict | member_tie
    hi = jnp.where(member, rank >> 7, jnp.int32(-1))   # -1: no slot
    lo = rank & jnp.int32(127)

    # Global column index of each chunk element, in three exact bf16
    # parts (col < 2^24 = 8+8+8 mantissa bits).
    col = (jnp.float32(j * tl)
           + jax.lax.broadcasted_iota(jnp.int32, (1, tl), 1)
           .astype(jnp.float32))
    p0 = _round_to_bf16_f32(col)
    r1 = col - p0
    p1 = _round_to_bf16_f32(r1)
    p2 = r1 - p1

    # hi = -1 (no slot) matches no one-hot row — no active mask needed
    ohhi, ohlo = onehot_pair(hi, lo, kh, 128)  # (tm,kh,tl) / (tm,tl,128)
    pb0 = p0.astype(jnp.bfloat16)[None, :, :]          # (1, 1, tl)
    pb1 = p1.astype(jnp.bfloat16)[None, :, :]
    pb2 = p2.astype(jnp.bfloat16)[None, :, :]
    a = jnp.concatenate([ohhi * pb0, ohhi * pb1, ohhi * pb2],
                        axis=1)                        # (tm, 3kh, tl)
    slabs = jax.lax.dot_general(
        a, ohlo, (((2,), (1,)), ((0,), (0,))),
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.DEFAULT)           # (tm, 3kh, 128)
    slab = (slabs[:, :kh] + slabs[:, kh:2 * kh] + slabs[:, 2 * kh:]
            ).reshape(tm, kh * 128)
    out_ref[:] += slab

    # the precomputed per-chunk counts ARE this chunk's strict/tie sums
    # (same compare against the same threshold) — no extra reductions
    less_run[:] = run_less + lt_j
    tie_run[:] = run_tie + eq_j


@functools.partial(jax.jit, static_argnames=("k",))
def _radix_ranks(keys: jnp.ndarray, k: int) -> jnp.ndarray:
    """keys (R, L) i32 -> winner column indices (R, k) i32, in ascending
    column order (strict-below first, then in-order threshold ties)."""
    n_rows, n_cols = keys.shape
    # lp multiple of 1024 so every candidate chunk width divides it
    lp = round_up_to_multiple(n_cols, 1024)
    # emission row block: wider halves the grid-step count (per-step
    # overhead is the emission's fixed cost at many-row shapes); at
    # large k the (tm, 3*kh, tl) operand would blow VMEM, so fall back
    kh = cdiv(k, 128)
    # tile sized from the FULL emission live set (≈ 8.6 MB at
    # kh=16/tm=16/tl=1024; tl shrinks as kh grows past the preferred
    # band so the explicit-enum k <= MAX_K route stays inside budget)
    tm_e, tl_e = _emit_tiles(kh)
    tm_h, tl_h = _hist_tiles(n_rows, lp, tm_e)
    rp = round_up_to_multiple(n_rows, max(tm_h, tm_e))
    kpad = jnp.pad(keys, ((0, rp - n_rows), (0, lp - n_cols)),
                   constant_values=_I32_MAX)
    # shard_map plumbing (contractions.py pattern): operands pcast to
    # the joint varying-mesh-axes, out_shapes declare the same vma
    vma, (kpad,) = join_vma(kpad)

    # Threshold: grid (rows, NPASS, chunks) — chunk axis innermost, so
    # each digit pass streams the row once and narrows at its last
    # chunk. ~NPASS full-row HBM passes (+1 for the XLA chunk maps
    # below, +1 emission) vs the retired binary search's VMEM-resident
    # formulation whose 32 serial VPU sweeps measured 3.6-6.4 GB/s.
    nch_h = lp // tl_h
    t, ntie = pallas_call(
        functools.partial(_threshold_kernel, k=k, nch=nch_h),
        grid=(rp // tm_h, NPASS, nch_h),
        in_specs=[pl.BlockSpec((tm_h, tl_h), lambda i, p, j: (i, j),
                               memory_space=pltpu.VMEM)],
        out_specs=[pl.BlockSpec((tm_h, 1), lambda i, p, j: (i, 0),
                                memory_space=pltpu.VMEM),
                   pl.BlockSpec((tm_h, 1), lambda i, p, j: (i, 0),
                                memory_space=pltpu.VMEM)],
        out_shape=[out_struct((rp, 1), jnp.int32, vma),
                   out_struct((rp, 1), jnp.int32, vma)],
        scratch_shapes=[pltpu.VMEM((tm_h, 16, 16), jnp.float32),
                        pltpu.VMEM((tm_h, 1), jnp.int32),
                        pltpu.VMEM((tm_h, 1), jnp.int32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary", "arbitrary", "arbitrary"),
            # headroom over the ~6 MB live set for the one-hot
            # temporaries the scheduler may keep alive across the dot
            vmem_limit_bytes=32 * 1024 * 1024),
    )(kpad)

    tm, tl = tm_e, tl_e
    # per-chunk strict/tie counts for the emission's dead-chunk skip —
    # computed in plain XLA (layout-free; one extra streaming pass over
    # the keys) and held resident in the kernel as (tm, wc) blocks
    nch = lp // tl
    wc = round_up_to_multiple(nch, 128)
    lt_map = jnp.sum((kpad < t).reshape(rp, nch, tl), axis=2,
                     dtype=jnp.int32)
    le_map = jnp.sum((kpad <= t).reshape(rp, nch, tl), axis=2,
                     dtype=jnp.int32)
    eq_map = le_map - lt_map
    lt_map = jnp.pad(lt_map, ((0, 0), (0, wc - nch)))
    eq_map = jnp.pad(eq_map, ((0, 0), (0, wc - nch)))

    idx_f = pallas_call(
        functools.partial(_emit_kernel, k=k, kh=kh, tl=tl, tm=tm, wc=wc),
        grid=(rp // tm, nch),
        in_specs=[
            pl.BlockSpec((tm, tl), lambda i, j: (i, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((tm, 1), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((tm, 1), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((tm, wc), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((tm, wc), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((tm, kh * 128), lambda i, j: (i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=out_struct((rp, kh * 128), jnp.float32, vma),
        scratch_shapes=[pltpu.VMEM((tm, 1), jnp.int32),
                        pltpu.VMEM((tm, 1), jnp.int32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary", "arbitrary")),
    )(kpad, t, ntie, lt_map, eq_map)

    return idx_f[:n_rows, :k].astype(jnp.int32)


def radix_select_k(values: jnp.ndarray, k: int,
                   select_min: bool = True
                   ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Exact batched top-k (smallest if select_min) of values (R, L).

    Returns (vals (R, k), idx (R, k)) sorted best-first; threshold ties
    resolve to the lowest column indices (reference tie rule). Callers
    must check :func:`supports` first.
    """
    values = jnp.asarray(values)
    n_rows, n_cols = values.shape
    if not supports(values.dtype, n_cols, k):
        raise ValueError(
            f"radix_select_k: unsupported problem (dtype={values.dtype}, "
            f"n_cols={n_cols}, k={k}); check supports()")
    # Pass-count contract (asserted by tests + ci/smoke.sh): the
    # threshold resolves in NPASS streamed passes. Fires at trace time
    # when called under jit — one event per compiled shape, which is
    # what the dispatch gates assert.
    trace.record_event("radix.select", rows=n_rows, cols=n_cols, k=k,
                       threshold_passes=NPASS,
                       path="two_level" if n_cols > CHUNK_LEN
                       else "single")
    keys = _to_key(values, select_min)

    if n_cols > CHUNK_LEN:
        # Two-level exact select for rows past the VMEM-resident bound
        # (the reference's multi-block radix_topk role,
        # matrix/detail/select_radix.cuh:877): per-chunk exact top-k,
        # then ONE exact merge select over the C*k candidate pool. Tie
        # contract: within a chunk, EQUAL-key winners keep ascending
        # column order (equal keys share a strict/tie segment, and each
        # segment is emitted column-ordered — the full emission is NOT
        # column-sorted, strict-belows precede ties), and the pool is
        # chunk-major, so pool position ascends with global column among
        # equal keys; the merge pass's position-order tie rule therefore
        # reproduces the global lowest-column contract exactly. The
        # final stable sort must stay keyed on the sortable key alone.
        n_chunks = cdiv(n_cols, CHUNK_LEN)
        lc = round_up_to_multiple(cdiv(n_cols, n_chunks), 1024)
        kc = jnp.pad(keys, ((0, 0), (0, n_chunks * lc - n_cols)),
                     constant_values=_I32_MAX
                     ).reshape(n_rows * n_chunks, lc)
        idx_c = _radix_ranks(kc, k)
        # every downstream gather stays CHUNK-LOCAL — a gather from the
        # full-width row fuses the whole row into VMEM (274M > 128M at
        # 2^22 cols, observed on the v5e AOT compile)
        pool_k = jnp.take_along_axis(kc, idx_c, axis=1
                                     ).reshape(n_rows, n_chunks * k)
        vc = jnp.pad(values, ((0, 0), (0, n_chunks * lc - n_cols))
                     ).reshape(n_rows * n_chunks, lc)
        pool_v = jnp.take_along_axis(vc, idx_c, axis=1
                                     ).reshape(n_rows, n_chunks * k)
        # global column ids of the pool candidates (chunk-major)
        base = (jnp.arange(n_chunks, dtype=jnp.int32) * lc)[None, :, None]
        pool_i = (idx_c.reshape(n_rows, n_chunks, k) + base
                  ).reshape(n_rows, n_chunks * k)
        # pad-chunk winners carry _I32_MAX keys, so they cannot win the
        # merge while any real candidate remains (k <= n_cols contract)
        idx_m = _radix_ranks(pool_k, k)
        idx = jnp.take_along_axis(pool_i, idx_m, axis=1)
        out_k = jnp.take_along_axis(pool_k, idx_m, axis=1)
        out_v = jnp.take_along_axis(pool_v, idx_m, axis=1)
    else:
        idx = _radix_ranks(keys, k)
        out_v = jnp.take_along_axis(values, idx, axis=1)
        out_k = jnp.take_along_axis(keys, idx, axis=1)
    # Best-first ordering: stable sort by sortable key keeps the
    # emission's ascending-column order among equal values.
    out_k, out_v, idx = jax.lax.sort((out_k, out_v, idx), dimension=1,
                                     is_stable=True, num_keys=1)
    return out_v, idx
