"""Row gather/scatter (ref: matrix/gather.cuh, matrix/scatter.cuh,
detail/gather.cuh, gather_inplace.cuh, scatter_inplace.cuh).

XLA's gather is a first-class op on TPU; the reference's kernel zoo
(gather, gather_if, gatherv, transformed maps) collapses to indexed reads
with optional transforms and masks.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax.numpy as jnp


def gather(res, matrix, indices, transform: Optional[Callable] = None):
    """out[i, :] = matrix[indices[i], :] (ref: gather.cuh gather)."""
    m = jnp.asarray(matrix)
    idx = jnp.asarray(indices)
    out = m[idx]
    return transform(out) if transform is not None else out


def gather_if(res, matrix, indices, stencil, pred: Callable,
              transform: Optional[Callable] = None, fill_value=0):
    """Gather rows whose stencil passes pred; failing rows filled
    (ref: gather.cuh gather_if)."""
    m = jnp.asarray(matrix)
    idx = jnp.asarray(indices)
    keep = pred(jnp.asarray(stencil))
    out = m[idx]
    if transform is not None:
        out = transform(out)
    return jnp.where(keep[:, None], out, jnp.asarray(fill_value,
                                                     dtype=out.dtype))


def scatter(res, matrix, indices, updates=None):
    """out[indices[i], :] = updates[i, :] — or a permutation-scatter of
    matrix itself when updates is None (ref: scatter.cuh in-place kernel)."""
    m = jnp.asarray(matrix)
    idx = jnp.asarray(indices)
    if updates is None:
        return jnp.zeros_like(m).at[idx].set(m)
    return m.at[idx].set(jnp.asarray(updates))
