"""Row gather/scatter (ref: matrix/gather.cuh, matrix/scatter.cuh,
detail/gather.cuh, gather_inplace.cuh, scatter_inplace.cuh).

XLA's gather is a first-class op on TPU; the reference's kernel zoo
(gather, gather_if, gatherv, transformed maps) collapses to indexed reads
with optional transforms and masks.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax.numpy as jnp


def gather(res, matrix, indices, transform: Optional[Callable] = None):
    """out[i, :] = matrix[indices[i], :] (ref: gather.cuh gather)."""
    m = jnp.asarray(matrix)
    idx = jnp.asarray(indices)
    out = m[idx]
    return transform(out) if transform is not None else out


def gather_if(res, matrix, indices, stencil, pred: Callable,
              transform: Optional[Callable] = None, fill_value=0):
    """Gather rows whose stencil passes pred; failing rows filled
    (ref: gather.cuh gather_if)."""
    m = jnp.asarray(matrix)
    idx = jnp.asarray(indices)
    keep = pred(jnp.asarray(stencil))
    out = m[idx]
    if transform is not None:
        out = transform(out)
    return jnp.where(keep[:, None], out, jnp.asarray(fill_value,
                                                     dtype=out.dtype))


def take_rows(res, matrix, starts, counts, max_count: int,
              fill_value=0):
    """Batched variable-count row-block gather: for each batch element
    ``b``, read ``counts[b]`` consecutive rows of ``matrix`` beginning at
    ``starts[b]``, padded out to a static ``max_count`` (ref: gatherv —
    the reference's variable-length gather, collapsed here to ONE padded
    index matrix so every block lands in a dense, MXU-friendly tile
    instead of a per-block host loop).

    ``starts``/``counts`` may carry arbitrary leading batch dims; the
    result block axis is appended after them. Returns ``(blocks, valid)``
    where ``blocks[..., j]`` is ``matrix[starts[...] + j]`` for
    ``j < counts[...]`` and ``fill_value`` beyond, and ``valid`` is the
    ``j < counts[...]`` mask. Out-of-range reads (a start+count that
    would run past the matrix) are clipped in-bounds before the gather
    and masked by ``valid`` — pure jnp, safe under jit.
    """
    m = jnp.asarray(matrix)
    starts = jnp.asarray(starts, jnp.int32)
    counts = jnp.asarray(counts, jnp.int32)
    offs = jnp.arange(max_count, dtype=jnp.int32)
    idx = starts[..., None] + offs                  # [..., max_count]
    valid = (offs < counts[..., None]) & (idx < m.shape[0])
    idx = jnp.clip(idx, 0, m.shape[0] - 1)
    out = m[idx]                                    # [..., max_count(, d)]
    mask = valid[..., None] if m.ndim == 2 else valid
    fill = jnp.asarray(fill_value, dtype=out.dtype)
    return jnp.where(mask, out, fill), valid


def scatter(res, matrix, indices, updates=None):
    """out[indices[i], :] = updates[i, :] — or a permutation-scatter of
    matrix itself when updates is None (ref: scatter.cuh in-place kernel)."""
    m = jnp.asarray(matrix)
    idx = jnp.asarray(indices)
    if updates is None:
        return jnp.zeros_like(m).at[idx].set(m)
    return m.at[idx].set(jnp.asarray(updates))
