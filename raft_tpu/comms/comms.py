"""Host-side communicator: the ``comms_t`` analogue living in the handle.

Reference: ``raft::comms::comms_t`` façade (core/comms.hpp:234) over
``comms_iface`` (core/comms.hpp:115-226), implemented by ``std_comms``
(comms/detail/std_comms.hpp) on NCCL + UCX.

TPU-native design: a :class:`MeshComms` owns a named axis of a
`jax.sharding.Mesh`.  Rank r == device r along that axis.  Eager collective
methods accept *stacked per-rank buffers* — an array whose leading dimension
is the clique size, slot r holding rank r's contribution (the single-
controller analogue of "each rank passes its sendbuff") — shard them over
the mesh, run the matching :mod:`raft_tpu.comms.device` collective inside a
`shard_map`, and return the stacked result.  Each eager call therefore
compiles to exactly the ICI/DCN collective the in-jit path would use; jit
caching makes repeated calls cheap (the analogue of enqueueing NCCL kernels
on a stream).

Host p2p (isend/irecv/waitall — reference UCX tag matching,
std_comms.hpp:163-223) is an in-process tag-matched mailbox shared by all
rank views: sufficient for single-controller SNMG-style rank loops; on
multi-host deployments host-side exchange rides `jax.distributed` /
multihost utilities instead.
"""

from __future__ import annotations

import enum
import threading
import time
import zlib
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from raft_tpu.comms import device as dev
from raft_tpu.comms.device import Op
from raft_tpu.comms.errors import (CommsAbortedError, CommsError,
                                   CommsTimeoutError, PeerFailedError)
from raft_tpu.comms.resilience import TagStore, default_recv_timeout
from raft_tpu.core import logger, trace
from raft_tpu.core.interruptible import InterruptedException
from raft_tpu import obs

# Reserved host-p2p tag namespaces (kept below the split-remap bases in
# comm_split so elastic control traffic never collides with user tags):
_CONSENSUS_TAG = 1 << 20   # survivor-consensus PROPOSE/DECIDE frames
_PROBE_TAG = (1 << 20) + (1 << 18)   # liveness probe sweep
# Child communicators over a non-shared transport (TcpMailbox) remap
# their tags into a per-split band so parent and child traffic share one
# wire without matching each other (see _RankMappedMailbox).
_SPLIT_TAG_SPAN = 1 << 28


class Datatype(enum.Enum):
    """Wire dtype vocabulary (ref: core/comms.hpp:25 ``datatype_t``)."""

    CHAR = "int8"
    UINT8 = "uint8"
    INT32 = "int32"
    UINT32 = "uint32"
    INT64 = "int64"
    UINT64 = "uint64"
    FLOAT32 = "float32"
    FLOAT64 = "float64"


def get_type(x) -> Datatype:
    """dtype → Datatype (ref: core/comms.hpp:37-101 ``get_type<T>()``)."""
    return Datatype(jnp.asarray(x).dtype.name)


class Status(enum.Enum):
    """Result of distributed sync (ref: core/comms.hpp:31-35 ``status_t``)."""

    SUCCESS = 0
    ERROR = 1
    ABORT = 2


class _Mailbox:
    """Tag-matched host message store (ref: UCX p2p, std_comms.hpp:163-223).

    Keyed by (source, dest, tag); each key is a FIFO. Shared across all rank
    views of one clique.

    Resilience semantics (see :mod:`raft_tpu.comms.resilience`): ``get``
    raises the typed taxonomy — ``CommsTimeoutError`` at the deadline,
    ``PeerFailedError`` fast when the source is declared failed,
    ``CommsAbortedError`` when the blocked thread is cancelled — never a
    bare ``queue.Empty``.  A :class:`raft_tpu.comms.faults.FaultInjector`
    on ``faults`` chaos-tests the delivery path; an in-process
    "disconnect" has no physical link to cut, so it reports the source
    rank failed (the observable a cut link produces on the TCP
    transport).
    """

    # one failure detector / abort domain serves every rank view (the
    # single-controller regime); consensus can read it directly instead
    # of running the wire protocol (see MeshComms.agree_on_survivors)
    shared_store = True

    def __init__(self, faults=None, default_timeout: Optional[float] = None):
        self._store = TagStore(name="mailbox")
        self.faults = faults
        # satellite: the old hard-coded 30.0 s literal, now resolved via
        # build_mesh_comms(default_recv_timeout=) / RAFT_TPU_RECV_TIMEOUT
        self.default_timeout = (default_timeout if default_timeout is not None
                                else default_recv_timeout(30.0))

    def put(self, source: int, dest: int, tag: int, payload) -> None:
        if obs.enabled():
            obs.inc("comms_messages_sent_total", 1, transport="inproc")
            obs.inc("comms_bytes_sent_total",
                    getattr(payload, "nbytes",
                            np.asarray(payload).nbytes),
                    transport="inproc")
        if obs.tracing_enabled():
            # in-process cross-rank propagation: the sender's context
            # rides the shared store instead of a wire header
            self._store.note_ctx(source, obs.current_context())
        injector = self.faults
        if injector is not None:
            decision = injector.on_send(source, dest, tag, payload)
            if decision.delay_s:
                # deadline-aware: an injected stall must not hold the
                # sender past an active runtime.limits deadline scope
                from raft_tpu.runtime.limits import sleep_within_deadline
                sleep_within_deadline(decision.delay_s, op="comms.send")
            for p in decision.payloads:
                if decision.corrupt:
                    from raft_tpu.comms.faults import corrupt_array
                    p = corrupt_array(np.asarray(p))
                self._store.deliver(source, dest, tag, p)
            if decision.disconnect:
                self._store.fail_peer(
                    source, "fault-injected disconnect")
            return
        self._store.deliver(source, dest, tag, payload)

    def get(self, source: int, dest: int, tag: int,
            timeout: Optional[float] = None):
        if timeout is None:
            timeout = self.default_timeout
        return self._store.get(source, dest, tag, timeout=timeout)

    def get_nowait(self, source: int, dest: int, tag: int):
        return self._store.get_nowait(source, dest, tag)

    def fail_peer(self, rank: int, reason: str) -> None:
        self._store.fail_peer(rank, reason)

    def revive_peer(self, rank: int) -> None:
        self._store.revive_peer(rank)

    def peer_failed(self, rank: int) -> Optional[str]:
        return self._store.peer_failed(rank)

    def failed_peers(self) -> Dict[int, str]:
        return self._store.failed_peers()

    def abort(self, reason: str) -> None:
        """In-process abort propagation: the store is shared by every
        rank view, so poisoning it IS the broadcast."""
        self._store.abort(reason)

    def clear_abort(self) -> None:
        self._store.clear_abort()

    def aborted(self) -> Optional[str]:
        return self._store.aborted()


class _RankMappedMailbox:
    """Child-communicator view of a cross-process transport.

    ``comm_split`` over the in-process ``_Mailbox`` hands each color
    group a fresh store, but a ``TcpMailbox`` owns real sockets — a
    survivors-only sub-communicator (``MeshComms.shrink``) must keep
    riding the parent's established links.  This adapter remaps the
    child's dense ranks onto the parent's (``members[new] == old``) and
    shifts tags into a per-split band (``tag_base``) so parent and child
    traffic share the wire without tag-matching each other.  Failure /
    abort state delegates to the parent transport: a peer dead on the
    wire is dead in every communicator built over it.
    """

    shared_store = False

    def __init__(self, base, members: Sequence[int], tag_base: int):
        self._base = base
        self._members = list(members)
        self._tag_base = int(tag_base)

    def _old(self, rank: int) -> int:
        return self._members[rank]

    def _new(self, old_rank: int) -> Optional[int]:
        try:
            return self._members.index(old_rank)
        except ValueError:
            return None

    def _tag(self, tag: int) -> int:
        # mask keeps composed bases inside the int32 wire header; nested
        # splits therefore share a wrapped namespace (documented, and
        # fine for control-plane traffic volumes)
        return (self._tag_base + tag) & 0x7FFFFFFF

    def _remap_error(self, e: CommsError) -> CommsError:
        if isinstance(e, PeerFailedError) and e.rank is not None:
            new = self._new(e.rank)
            if new is not None:
                raise PeerFailedError(str(e), rank=new,
                                      endpoint=e.endpoint) from e
        raise e

    @property
    def faults(self):
        return getattr(self._base, "faults", None)

    @property
    def default_timeout(self):
        return getattr(self._base, "default_timeout", None)

    @property
    def heartbeat_interval(self):
        return getattr(self._base, "heartbeat_interval", None)

    @property
    def heartbeat_timeout(self):
        return getattr(self._base, "heartbeat_timeout", None)

    def put(self, source: int, dest: int, tag: int, payload) -> None:
        try:
            self._base.put(self._old(source), self._old(dest),
                           self._tag(tag), payload)
        except PeerFailedError as e:
            self._remap_error(e)

    def get(self, source: int, dest: int, tag: int,
            timeout: Optional[float] = None):
        try:
            return self._base.get(self._old(source), self._old(dest),
                                  self._tag(tag), timeout=timeout)
        except (PeerFailedError, CommsTimeoutError) as e:
            self._remap_error(e)

    def get_nowait(self, source: int, dest: int, tag: int):
        return self._base.get_nowait(self._old(source), self._old(dest),
                                     self._tag(tag))

    def fail_peer(self, rank: int, reason: str) -> None:
        self._base.fail_peer(self._old(rank), reason)

    def revive_peer(self, rank: int) -> None:
        self._base.revive_peer(self._old(rank))

    def peer_failed(self, rank: int) -> Optional[str]:
        return self._base.peer_failed(self._old(rank))

    def failed_peers(self) -> Dict[int, str]:
        out: Dict[int, str] = {}
        for old, reason in self._base.failed_peers().items():
            new = self._new(old)
            if new is not None:
                out[new] = reason
        return out

    def abort(self, reason: str) -> None:
        self._base.abort(reason)

    def clear_abort(self) -> None:
        self._base.clear_abort()

    def aborted(self) -> Optional[str]:
        return self._base.aborted()


class _Request:
    """Pending host p2p op (ref: ``request_t`` handles, core/comms.hpp:24)."""

    def __init__(self, fn):
        self._fn = fn
        self.result = None

    def wait(self):
        if self._fn is not None:
            self.result = self._fn()
            self._fn = None
        return self.result


class MeshComms:
    """Communicator over one mesh axis (ref: comms_t, core/comms.hpp:234).

    Parameters
    ----------
    mesh : jax.sharding.Mesh with the clique axis.
    axis_name : name of the clique axis within ``mesh``.
    rank : which device along the axis this view addresses; host rank loops
        (the SNMG pattern, core/device_resources_snmg.hpp:102-126) iterate
        ``comms.rank_view(r)``.
    """

    def __init__(self, mesh: Mesh, axis_name: str = "data", rank: int = 0,
                 _mailbox: Optional[_Mailbox] = None,
                 _shared: Optional[dict] = None):
        if axis_name not in mesh.axis_names:
            raise ValueError(f"axis {axis_name!r} not in mesh {mesh.axis_names}")
        self.mesh = mesh
        self.axis_name = axis_name
        self._rank = int(rank)
        self._mailbox = _mailbox if _mailbox is not None else _Mailbox()
        # Clique-wide state shared by all rank views: compiled-collective
        # cache and per-split child mailboxes (so sub-communicators built
        # from different rank views can exchange host messages).
        self._shared = _shared if _shared is not None else {
            "jit": {}, "split": {}, "lock": threading.Lock()}

    # -- identity (ref: core/comms.hpp:244-258) -----------------------------

    def get_size(self) -> int:
        return self.mesh.shape[self.axis_name]

    def get_rank(self) -> int:
        return self._rank

    def rank_view(self, rank: int) -> "MeshComms":
        """A view of the same clique addressing a different rank."""
        return MeshComms(self.mesh, self.axis_name, rank,
                         _mailbox=self._mailbox, _shared=self._shared)

    # -- split (ref: core/comms.hpp:267 comm_split; ncclCommSplit) ----------

    def comm_split(self, color: Sequence[int], key: Sequence[int]
                   ) -> "MeshComms":
        """Split into sub-communicators by color, ordered by key.

        ``color[r]``/``key[r]`` give rank r's color and ordering key (the
        reference passes scalars per rank; single-controller passes the full
        vectors).  Returns the sub-communicator containing *this view's*
        rank: a MeshComms over a sub-mesh of the devices with the same
        color, whose new rank order sorts by (key, old rank).
        """
        color = list(color)
        key = list(key)
        n = self.get_size()
        if len(color) != n or len(key) != n:
            raise ValueError("color/key must have one entry per rank")
        my_color = color[self._rank]
        members = sorted((r for r in range(n) if color[r] == my_color),
                         key=lambda r: (key[r], r))
        # A failed peer inside my color group makes the sub-clique
        # unusable: fail fast here instead of letting the first child
        # collective hang out its deadline (ISSUE 2 satellite; peers of
        # *other* colors may be dead — shrink() relies on that to carve
        # the survivor group around them).
        for r in members:
            if r != self._rank:
                reason = self._mailbox.peer_failed(r)
                if reason is not None:
                    raise PeerFailedError(
                        f"comm_split: rank {r} in color group {my_color} "
                        f"already failed ({reason})", rank=r)
        axis_devs = self._axis_devices()
        sub_devices = np.asarray([axis_devs[r] for r in members])
        sub_mesh = Mesh(sub_devices, axis_names=(self.axis_name,))
        new_rank = members.index(self._rank)
        # Sub-communicators from different rank views of the same split must
        # share one mailbox per color group, or their host p2p can't match.
        # They must also share one clique-state dict, so second-level splits
        # (sub.comm_split from different rank views) coordinate too.
        split_key = (tuple(color), tuple(key), my_color)
        with self._shared["lock"]:
            entry = self._shared["split"].get(split_key)
            if entry is None:
                if getattr(self._mailbox, "shared_store", False):
                    # single-controller: a fresh store per color group
                    # gives the child a clean failure/abort domain
                    mbox = _Mailbox(
                        default_timeout=self._mailbox.default_timeout)
                else:
                    # cross-process transport (TcpMailbox): the child
                    # must keep riding the parent's sockets — remap its
                    # dense ranks and shift tags into a per-split band
                    tag_base = _SPLIT_TAG_SPAN | (
                        zlib.crc32(repr(split_key).encode())
                        & (_SPLIT_TAG_SPAN - 1))
                    mbox = _RankMappedMailbox(self._mailbox, members,
                                              tag_base)
                entry = {
                    "mailbox": mbox,
                    "shared": {"jit": {}, "split": {},
                               "lock": threading.Lock()},
                }
                self._shared["split"][split_key] = entry
        return MeshComms(sub_mesh, self.axis_name, new_rank,
                         _mailbox=entry["mailbox"],
                         _shared=entry["shared"])

    def axis_index_groups(self, color: Sequence[int]) -> List[List[int]]:
        """Same split expressed for in-jit grouped collectives
        (``axis_index_groups`` of lax.psum etc.)."""
        groups: Dict[int, List[int]] = {}
        for r, c in enumerate(color):
            groups.setdefault(c, []).append(r)
        return [groups[c] for c in sorted(groups)]

    def _axis_devices(self):
        """Devices along the clique axis (other axes fixed at this view)."""
        ax = self.mesh.axis_names.index(self.axis_name)
        dev_arr = np.asarray(self.mesh.devices)
        index = [0] * dev_arr.ndim
        index[ax] = slice(None)
        return list(dev_arr[tuple(index)])

    # -- sync / barrier (ref: core/comms.hpp:269-276) -----------------------

    def sync_stream(self, *arrays) -> Status:
        """Block until enqueued device work completes (ref: sync_stream).

        Folds the typed comms taxonomy back onto the ``status_t``
        contract: cancellation → ``ABORT`` (ref status_t::ABORT via
        interruptible), any comms/runtime failure → ``ERROR`` — logged,
        never silently swallowed (the round-1 blanket catch-all handler
        is gone; the ci/smoke.sh hygiene lint keeps it out).
        """
        try:
            for a in arrays:
                if hasattr(a, "block_until_ready"):
                    a.block_until_ready()
            if not arrays:
                jax.effects_barrier()
            return Status.SUCCESS
        except (CommsAbortedError, InterruptedException):
            return Status.ABORT
        except (CommsError, RuntimeError, ValueError, OSError) as e:
            # RuntimeError covers jax's XlaRuntimeError hierarchy
            logger.error("sync_stream failed: %r", e)
            return Status.ERROR

    def barrier(self) -> None:
        """allreduce of one int + sync (exactly std_comms.hpp:133-147)."""
        out = self._run(("barrier",), lambda x: dev.barrier(self.axis_name),
                        jnp.ones((self.get_size(), 1), jnp.int32))
        self.sync_stream(out)

    # -- host p2p (ref: core/comms.hpp:278-291; UCX tag matching) -----------

    def isend(self, buf, dest: int, tag: int) -> _Request:
        payload = np.asarray(buf)
        self._mailbox.put(self._rank, dest, tag, payload)
        return _Request(None)

    def irecv(self, source: int, tag: int,
              timeout: Optional[float] = None) -> _Request:
        """``timeout`` overrides the transport's default recv deadline;
        the wait raises the typed taxonomy (CommsTimeoutError /
        PeerFailedError / CommsAbortedError) on failure."""
        if timeout is None:
            return _Request(
                lambda: self._mailbox.get(source, self._rank, tag))
        return _Request(
            lambda: self._mailbox.get(source, self._rank, tag,
                                      timeout=timeout))

    def waitall(self, requests: Sequence[_Request]) -> List[Any]:
        return [r.wait() for r in requests]

    def host_allreduce(self, x, tag: int) -> np.ndarray:
        """Deterministic host-side sum-allreduce over the mailbox
        (tags ``tag`` for the gather leg, ``tag + 1`` for the bcast
        leg; all ranks must call with the same tag).

        Partials gather to rank 0 of this clique and are summed in
        ascending rank order — a *fixed* floating-point reduction
        order, so results are bit-for-bit reproducible for a given
        clique size.  The elastic solvers use this instead of a device
        psum when the clique must outlive rank death: XLA collectives
        over a global mesh cannot complete once a participating
        process is gone, host mailbox traffic can."""
        n = self.get_size()
        x = np.asarray(x)
        if n == 1:
            return x.copy()
        with obs.span("comms.host_allreduce", tag=tag, n=n):
            if self._rank == 0:
                total = x.copy()
                for r in range(1, n):
                    part = np.asarray(self._mailbox.get(r, 0, tag))
                    total = total + part.astype(total.dtype)
                for r in range(1, n):
                    self._mailbox.put(0, r, tag + 1, total)
                return total
            self._mailbox.put(self._rank, 0, tag, x)
            return np.asarray(self._mailbox.get(0, self._rank, tag + 1))

    # -- elastic execution (ISSUE 2 tentpole) -------------------------------
    #
    # The reference comms_t surfaces failure through sync_stream's
    # status_t (SUCCESS/ERROR/ABORT, core/comms.hpp:31) and expects the
    # algorithm to react; these methods give MNMG rank loops the verbs to
    # do so: abort() poisons every rank's host p2p within a heartbeat,
    # agree_on_survivors() is the failure-consensus barrier, shrink()
    # rebuilds a survivors-only clique over the comm_split machinery.

    @property
    def heartbeat_interval(self) -> float:
        """Transport heartbeat period (drives abort-latency contracts);
        in-process transports have no heartbeats — poisoning the shared
        store is instantaneous — so they report 0."""
        hb = getattr(self._mailbox, "heartbeat_interval", None)
        return float(hb) if hb else 0.0

    def abort(self, reason: str) -> None:
        """Broadcast a poison frame: every pending and future host recv
        on *every* rank raises :class:`CommsAbortedError` within one
        heartbeat, instead of each rank discovering the failure through
        its own staggered recv timeout (the comms_t status_t::Abort
        contract, propagated instead of polled)."""
        trace.record_event("comms.mesh_abort", rank=self._rank,
                           reason=reason)
        obs.inc("comms_aborts_total", 1, transport="mesh")
        self._mailbox.abort(reason)

    def clear_abort(self) -> None:
        """Re-arm host p2p after recovery (survivors of a shrink start
        from a clean abort domain)."""
        self._mailbox.clear_abort()

    def aborted(self) -> Optional[str]:
        return self._mailbox.aborted()

    def ensure_healthy(self) -> None:
        """Raise the pending failure, if any: CommsAbortedError when the
        clique is aborted, PeerFailedError when a peer of this clique is
        dead.  Runs a :meth:`probe_peers` sweep — passive on wire
        transports (the heartbeat detector is authoritative), an active
        fault-path probe on shared-store ones — so iterative solvers
        calling this at poll boundaries discover injected disconnects
        without waiting for organic traffic from the dead rank.
        """
        reason = self._mailbox.aborted()
        if reason is not None:
            raise CommsAbortedError(
                f"rank {self._rank}: clique aborted ({reason})")
        for r, why in self.probe_peers().items():
            if r != self._rank:
                raise PeerFailedError(
                    f"rank {self._rank}: peer rank {r} failed ({why})",
                    rank=r)

    def probe_peers(self) -> Dict[int, str]:
        """Active liveness sweep; returns {rank: reason} for dead peers.

        On a shared-store transport the sweep pushes a probe *from* each
        peer's rank through the fault-injected send path, so an injected
        per-rank disconnect is discovered here rather than at that
        rank's next real send.  On wire transports the heartbeat failure
        detector is already authoritative — this just snapshots it.
        """
        n = self.get_size()
        if getattr(self._mailbox, "shared_store", False):
            for r in range(n):
                if r == self._rank or self._mailbox.peer_failed(r):
                    continue
                self._mailbox.put(r, self._rank, _PROBE_TAG + r,
                                  np.zeros(1, np.int8))
                while self._mailbox.get_nowait(
                        r, self._rank, _PROBE_TAG + r) is not None:
                    pass
        return {r: why for r, why in self._mailbox.failed_peers().items()
                if 0 <= r < n}

    def _recv_latest(self, source: int, tag: int, timeout: float):
        """Drain queued messages for (source, tag), keeping the newest;
        block only when none is queued.  Consensus rounds re-send under
        one tag after a leader change — only the latest frame matters."""
        msg = None
        while True:
            nxt = self._mailbox.get_nowait(source, self._rank, tag)
            if nxt is None:
                break
            msg = nxt
        if msg is not None:
            return msg
        return self._mailbox.get(source, self._rank, tag, timeout=timeout)

    def agree_on_survivors(self, timeout: Optional[float] = None
                           ) -> Tuple[int, ...]:
        """Failure-consensus barrier: returns the live-rank set every
        surviving peer agrees on (sorted old ranks).  All live ranks
        must call this; a rank evicted by the decision raises
        :class:`CommsAbortedError`.

        Shared-store transports read the (single) failure detector
        directly — one failure domain needs no protocol.  Wire
        transports run a leader-based two-phase exchange: every rank
        proposes its live-view bitmap to the lowest live rank, the
        leader intersects proposals with its responder set and
        broadcasts the decision.  A leader death mid-round triggers
        re-election (next-lowest live rank) with the same tags;
        ``_recv_latest`` makes re-sent frames idempotent.
        """
        n = self.get_size()
        failed = self.probe_peers()
        live = [r for r in range(n) if r not in failed]
        if getattr(self._mailbox, "shared_store", False):
            survivors = tuple(live)
            trace.record_event("comms.consensus", rank=self._rank,
                               mode="shared", survivors=survivors)
            return survivors
        hb_timeout = getattr(self._mailbox, "heartbeat_timeout", None)
        base_t = timeout if timeout is not None else 2.0 * float(
            hb_timeout or 10.0)
        with self._shared["lock"]:
            epoch = int(self._shared.get("consensus_epoch", 0))
            self._shared["consensus_epoch"] = epoch + 1
        propose_tag = _CONSENSUS_TAG + 2 * epoch
        decide_tag = propose_tag + 1
        while True:
            if not live or self._rank not in live:
                raise CommsAbortedError(
                    f"rank {self._rank}: no quorum of live peers")
            leader = min(live)
            bitmap = np.zeros(n, np.int8)
            bitmap[live] = 1
            if self._rank == leader:
                views = [set(live)]
                responders = [self._rank]
                for r in live:
                    if r == self._rank:
                        continue
                    try:
                        bm = np.asarray(self._recv_latest(
                            r, propose_tag, timeout=base_t))
                        views.append(
                            {i for i in range(min(n, bm.shape[0]))
                             if bm[i]})
                        responders.append(r)
                    except (CommsTimeoutError, PeerFailedError) as e:
                        logger.warn(
                            "consensus leader %d: no proposal from rank "
                            "%d (%r); excluding", self._rank, r, e)
                decided = set(responders)
                for v in views:
                    decided &= v
                out = np.zeros(n, np.int8)
                out[sorted(decided)] = 1
                for r in sorted(decided):
                    if r != self._rank:
                        self._mailbox.put(self._rank, r, decide_tag, out)
                survivors = tuple(sorted(decided))
                trace.record_event("comms.consensus", rank=self._rank,
                                   mode="leader", survivors=survivors)
                return survivors
            try:
                self._mailbox.put(self._rank, leader, propose_tag, bitmap)
                decision = np.asarray(self._recv_latest(
                    leader, decide_tag, timeout=base_t * (len(live) + 1)))
            except (PeerFailedError, CommsTimeoutError) as e:
                # leader died mid-round: exclude it and re-elect
                logger.warn("consensus rank %d: leader %d lost (%r); "
                            "re-electing", self._rank, leader, e)
                live = [r for r in live if r != leader]
                continue
            survivors = tuple(
                int(i) for i in range(min(n, decision.shape[0]))
                if decision[i])
            if self._rank not in survivors:
                raise CommsAbortedError(
                    f"rank {self._rank}: evicted by survivor consensus "
                    f"(decision {survivors})")
            trace.record_event("comms.consensus", rank=self._rank,
                               mode="follower", survivors=survivors)
            return survivors

    def shrink(self, survivors: Sequence[int]) -> "MeshComms":
        """Survivors-only clique over the comm_split machinery (the
        elastic analogue of ncclCommShrink): survivors keep their
        relative order but get dense new ranks; dead ranks land in a
        discard color.  The new clique's abort domain starts clean.
        """
        survivors = sorted(int(r) for r in survivors)
        n = self.get_size()
        if self._rank not in survivors:
            raise CommsAbortedError(
                f"rank {self._rank}: not in survivor set {survivors}")
        color = [0 if r in set(survivors) else 1 for r in range(n)]
        sub = self.comm_split(color, list(range(n)))
        sub.clear_abort()
        trace.record_event("comms.shrink", rank=self._rank,
                           new_rank=sub.get_rank(),
                           survivors=tuple(survivors))
        return sub

    # -- eager collectives over stacked per-rank buffers --------------------
    #
    # Each takes `x` with leading dim == get_size() (slot r = rank r's
    # sendbuff) and returns the stacked recvbuffs. Compiled via shard_map so
    # the actual data movement is the real XLA collective.

    def _is_multiprocess(self) -> bool:
        """True when the clique's mesh spans more than this process (the
        `jax.distributed` multi-controller regime — each process only
        addresses its local devices)."""
        flag = self._shared.get("multiprocess")
        if flag is None:
            me = jax.process_index()
            flag = any(d.process_index != me
                       for d in np.asarray(self.mesh.devices).flat)
            self._shared["multiprocess"] = flag
        return flag

    def _run(self, cache_key, shard_fn, x):
        """Compile-once-per-(op, shape, dtype) eager collective dispatch.

        ``cache_key`` identifies the collective + its static params; the
        compiled shard_map is cached in clique-shared state so repeated
        calls cost one dispatch, not one compile (the analogue of NCCL
        kernels being enqueued, not rebuilt).

        Multi-controller (mesh spans processes): the stacked buffer —
        identical on every process, as each comms-battery caller builds
        the same one — is turned into a global sharded array by slicing
        each process's addressable shards out of it, and the output is
        replicated so every process can read the full stacked result.
        All processes must call eager collectives in the same order (the
        usual SPMD contract; ref: every NCCL rank enqueues symmetric
        calls or deadlocks — std_comms.hpp inherits the same rule).
        """
        multi = self._is_multiprocess()
        # validate on the host view; only materialize on device once, on
        # the path that will actually consume it (the multi path slices
        # process-local shards straight from host memory)
        if multi:
            host = np.asarray(x)
            # same dtype rules as jnp.asarray (e.g. f64→f32 when x64 is
            # off) so the single- and multi-controller paths agree
            host = host.astype(jax.dtypes.canonicalize_dtype(host.dtype))
            x = host
        else:
            x = jnp.asarray(x)
        n = self.get_size()
        if x.shape[0] != n:
            raise ValueError(
                f"leading dim {x.shape[0]} != clique size {n}; eager "
                "collectives take stacked per-rank buffers")
        full_key = (self.mesh, self.axis_name, cache_key, x.shape,
                    str(x.dtype))
        cache = self._shared["jit"]
        with self._shared["lock"]:
            f = cache.get(full_key)
        if obs.enabled():
            obs.inc("runtime_compile_cache_total", 1, cache="comms_eager",
                    outcome=("hit" if f is not None else "miss"))
        if f is None:
            f = _build_eager_collective(self.mesh, self.axis_name, shard_fn,
                                        replicate_out=multi)
            with self._shared["lock"]:
                cache[full_key] = f
        if multi:
            from jax.sharding import NamedSharding

            sharding = NamedSharding(self.mesh, P(self.axis_name))
            ga = jax.make_array_from_callback(
                host.shape, sharding, lambda idx: host[idx])
            x = ga
        if not obs.enabled():
            return f(x)
        # metrics-on path trades dispatch asynchrony for a real latency
        # sample: eager collectives are semantically synchronous anyway
        t0 = time.monotonic()
        out = f(x)
        jax.block_until_ready(out)
        obs.observe("comms_collective_seconds", time.monotonic() - t0,
                    op=str(cache_key[0]))
        return out

    def allreduce(self, x, op: Op = Op.SUM):
        """ref: comms_t::allreduce → ncclAllReduce (std_comms.hpp:366-374)."""
        return self._run(
            ("allreduce", op),
            lambda s: dev.allreduce(s, op=op, axis_name=self.axis_name), x)

    def bcast(self, x, root: int = 0):
        """ref: comms_t::bcast → ncclBroadcast (std_comms.hpp:377-395)."""
        return self._run(
            ("bcast", root),
            lambda s: dev.bcast(s, root=root, axis_name=self.axis_name), x)

    def reduce(self, x, op: Op = Op.SUM, root: int = 0):
        """ref: comms_t::reduce → ncclReduce (std_comms.hpp:398-422)."""
        return self._run(
            ("reduce", op, root),
            lambda s: dev.reduce(s, op=op, root=root,
                                 axis_name=self.axis_name), x)

    def allgather(self, x):
        """ref: comms_t::allgather → ncclAllGather (std_comms.hpp:425-433).

        Input [n, m, ...] (slot r = rank r's m-row sendbuff); output
        [n, n*m, ...]: every rank's recvbuff holds all ranks' rows.
        """
        return self._run(
            ("allgather",),
            lambda s: dev.allgather(s, axis_name=self.axis_name, tiled=True),
            x)

    def allgatherv(self, x, recvcounts: Sequence[int]):
        """ref: comms_t::allgatherv (std_comms.hpp:436-468). ``x`` is padded
        per-rank [n, maxcount, ...]; returns [n, sum(recvcounts), ...]."""
        return self._run(
            ("allgatherv", tuple(int(c) for c in recvcounts)),
            lambda s: dev.allgatherv(s, recvcounts,
                                     axis_name=self.axis_name), x)

    def gather(self, x, root: int = 0):
        """ref: comms_t::gather (std_comms.hpp:471-495)."""
        return self._run(
            ("gather", root),
            lambda s: dev.gather(s, root=root, axis_name=self.axis_name)
            .reshape((-1,) + s.shape[1:]),
            x)

    def gatherv(self, x, recvcounts: Sequence[int], root: int = 0):
        """ref: comms_t::gatherv (std_comms.hpp:498-528).

        Root contract (same as :meth:`gather`): XLA collectives are SPMD,
        so every rank receives the gathered buffer; ``root`` names the
        rank whose view is contractually valid — non-roots may ignore
        theirs and XLA DCEs unused outputs. There is no cheaper root-only
        collective on ICI (NCCL's gatherv is likewise grouped sends)."""
        del root   # all ranks compute; root names the valid view
        return self.allgatherv(x, recvcounts)

    def reducescatter(self, x, op: Op = Op.SUM):
        """ref: comms_t::reducescatter → ncclReduceScatter
        (std_comms.hpp:531-541). Input [n, n*m, ...] → output [n, m, ...]."""
        return self._run(
            ("reducescatter", op),
            lambda s: dev.reducescatter(s, op=op, axis_name=self.axis_name),
            x)

    def device_sendrecv(self, x, perm: Sequence[Tuple[int, int]]):
        """ref: comms_t::device_send/recv/sendrecv (std_comms.hpp:544-571):
        the per-rank (dest, source) host loop collapses to one static
        ``perm`` of (source, dest) pairs."""
        return self._run(
            ("sendrecv", tuple(map(tuple, perm))),
            lambda s: dev.device_sendrecv(s, perm,
                                          axis_name=self.axis_name), x)

    def device_multicast_sendrecv(self, x, pairs: Sequence[Tuple[int, int]]):
        """ref: comms_t::device_multicast_sendrecv (std_comms.hpp:574-601)."""
        return self._run(
            ("multicast", tuple(map(tuple, pairs))),
            lambda s: dev.device_multicast_sendrecv(
                s, pairs, axis_name=self.axis_name), x)

    # group_start/group_end (std_comms.hpp:150-160) have no analogue: XLA
    # fuses/schedules collectives itself. Provided as no-ops for parity.
    def group_start(self) -> None:
        pass

    def group_end(self) -> None:
        pass


def _build_eager_collective(mesh, axis_name, shard_fn, replicate_out=False):
    """shard x's leading dim over the axis, apply shard_fn per shard, restack.

    Inside the shard the leading dim is 1 (one rank's buffer); shard_fn sees
    the squeezed buffer. ``replicate_out`` adds a final all-gather so every
    process of a multi-controller clique holds the full stacked result
    (single-controller callers skip it — they already address every shard).
    """
    spec = P(axis_name)

    def wrapped(block):
        s = block[0]  # squeeze the per-rank slot
        r = shard_fn(s)
        return r[None]

    sm = jax.shard_map(wrapped, mesh=mesh, in_specs=spec, out_specs=spec)
    if replicate_out:
        from jax.sharding import NamedSharding

        return jax.jit(sm, out_shardings=NamedSharding(mesh, P()))
    return jax.jit(sm)


def build_mesh_comms(res=None, mesh: Optional[Mesh] = None,
                     axis_name: str = "data", rank: int = 0,
                     default_recv_timeout: Optional[float] = None
                     ) -> MeshComms:
    """Create a MeshComms and inject it into the handle.

    The analogue of ``build_comms_nccl_only`` / ``build_comms_nccl_ucx``
    (comms/std_comms.hpp:60-108): where those wrap an externally
    bootstrapped ncclComm and call ``resource::set_comms``, this wraps the
    handle's mesh — no rendezvous needed; device discovery is XLA's job
    (``jax.distributed.initialize`` on multi-host).

    ``default_recv_timeout`` sets the clique's blocking-recv deadline;
    None resolves via the RAFT_TPU_RECV_TIMEOUT env var, falling back
    to 30 s (the transport deadline previously hard-coded in
    ``_Mailbox.get``).
    """
    from raft_tpu.core import resources as core_res

    if res is not None and mesh is None:
        mesh = core_res.get_mesh(res)
    if mesh is None:
        devs = np.asarray(jax.devices())
        mesh = Mesh(devs, axis_names=(axis_name,))
    comms = MeshComms(mesh, axis_name=axis_name, rank=rank,
                      _mailbox=_Mailbox(default_timeout=default_recv_timeout))
    if res is not None:
        core_res.set_comms(res, comms)
    return comms
