"""Cross-process tag-matched host p2p — the UCX analogue
(ref: comms/detail/std_comms.hpp:163-223 ucp tag send/recv;
ucp_helper.hpp; raft_dask common/ucx.py listener/endpoint manager).

Single-controller cliques use the in-process `_Mailbox` (comms.comms); a
multi-process SPMD job (one controller per host, wired together with
`jax.distributed` — see comms.bootstrap.initialize_distributed) uses this
`TcpMailbox` instead: same (source, dest, tag) FIFO semantics, but
messages to remote ranks travel over TCP. Payloads are numpy arrays in
``.npy`` wire format (no pickle: nothing executable crosses the wire).

Design note (the committed multi-process story, VERDICT #7): device-side
collectives in a multi-process job are XLA's own — a jitted computation
over the global mesh moves data over ICI/DCN, so MeshComms never needs a
device-side wire protocol of its own. What the reference's UCX layer adds
beyond NCCL is *host* tag-matched p2p for control/bootstrap traffic; this
module is that layer's TPU-stack equivalent.
"""

from __future__ import annotations

import io
import queue
import socket
import struct
import threading
from typing import Dict, List, Optional, Tuple

import numpy as np

_HDR = struct.Struct("<iiiq")  # source, dest, tag, nbytes


def _recv_exact(conn: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = conn.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed mid-message")
        buf.extend(chunk)
    return bytes(buf)


class TcpMailbox:
    """Tag-matched mailbox whose remote legs ride TCP.

    Parameters
    ----------
    rank : this process's rank.
    addrs : per-rank "host:port" listen addresses (every rank gets the
        same list — the analogue of the worker address exchange in
        raft_dask comms.py:144's worker_info).
    """

    def __init__(self, rank: int, addrs: List[str]):
        self.rank = int(rank)
        self.addrs = list(addrs)
        self._queues: Dict[Tuple[int, int, int], "queue.Queue"] = {}
        self._lock = threading.Lock()
        # One persistent connection per destination, guarded by a per-dest
        # lock: all messages to a peer travel one ordered byte stream, and
        # the peer's single per-connection serve thread enqueues them in
        # arrival order — preserving the _Mailbox per-(source,dest,tag)
        # FIFO contract across processes.
        self._conns: Dict[int, socket.socket] = {}
        self._conn_locks: Dict[int, threading.Lock] = {}
        host, port = self.addrs[self.rank].rsplit(":", 1)
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind((host, int(port)))
        self._srv.listen(64)
        self._closed = False
        self._accept_thread = threading.Thread(target=self._accept_loop,
                                               daemon=True)
        self._accept_thread.start()

    # -- the _Mailbox interface (comms.comms) ------------------------------

    def _connect(self, dest: int) -> socket.socket:
        host, port = self.addrs[dest].rsplit(":", 1)
        # Peers come up at different speeds during bootstrap; retry any
        # transient connect failure (refused before the listener binds,
        # SYN drops past the backlog → timeout, peer resets) — the
        # reference's UCX endpoint creation likewise blocks in a
        # rendezvous (ucx.py:47).
        last: Optional[OSError] = None
        for _ in range(40):
            try:
                return socket.create_connection((host, int(port)),
                                                timeout=30)
            except OSError as e:
                last = e
                import time
                time.sleep(0.25)
        raise last

    def put(self, source: int, dest: int, tag: int, payload) -> None:
        arr = np.asarray(payload)
        if dest == self.rank:
            self._q((source, dest, tag)).put(arr)
            return
        bio = io.BytesIO()
        np.save(bio, arr, allow_pickle=False)
        raw = bio.getvalue()
        with self._lock:
            lock = self._conn_locks.setdefault(dest, threading.Lock())
        with lock:
            s = self._conns.get(dest)
            if s is None:
                s = self._connect(dest)
                self._conns[dest] = s
            try:
                s.sendall(_HDR.pack(source, dest, tag, len(raw)))
                s.sendall(raw)
            except OSError:
                # peer restarted: reconnect once and resend
                try:
                    s.close()
                except OSError:
                    pass
                s = self._connect(dest)
                self._conns[dest] = s
                s.sendall(_HDR.pack(source, dest, tag, len(raw)))
                s.sendall(raw)

    def get(self, source: int, dest: int, tag: int,
            timeout: float = 120.0):
        """Blocking tag-matched receive. The default deadline is sized
        for a LOADED host: the peer may be stuck behind multi-second XLA
        compiles or a saturated CPU before it sends (observed: the
        30 s default flaked the multiprocess tier when the full test
        suite and bench battery shared the machine). It is a
        deadlock-detection bound, not a latency promise."""
        assert dest == self.rank, \
            f"rank {self.rank} cannot receive for rank {dest}"
        return self._q((source, dest, tag)).get(timeout=timeout)

    # -- plumbing ----------------------------------------------------------

    def _q(self, key):
        with self._lock:
            if key not in self._queues:
                self._queues[key] = queue.Queue()
            return self._queues[key]

    def _accept_loop(self):
        while not self._closed:
            try:
                conn, _ = self._srv.accept()
            except OSError:
                return                      # listener closed
            threading.Thread(target=self._serve, args=(conn,),
                             daemon=True).start()

    def _serve(self, conn: socket.socket):
        try:
            with conn:
                while True:                 # messages stream until close
                    hdr = _recv_exact(conn, _HDR.size)
                    source, dest, tag, nbytes = _HDR.unpack(hdr)
                    raw = _recv_exact(conn, nbytes)
                    arr = np.load(io.BytesIO(raw), allow_pickle=False)
                    self._q((source, dest, tag)).put(arr)
        except (ConnectionError, OSError, ValueError):
            pass                            # peer closed / torn connection

    def close(self):
        self._closed = True
        try:
            self._srv.close()
        except OSError:
            pass
        with self._lock:
            conns = list(self._conns.values())
            self._conns.clear()
        for s in conns:
            try:
                s.close()
            except OSError:
                pass

    def __del__(self):
        self.close()
