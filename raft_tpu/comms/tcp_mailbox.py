"""Cross-process tag-matched host p2p — the UCX analogue
(ref: comms/detail/std_comms.hpp:163-223 ucp tag send/recv;
ucp_helper.hpp; raft_dask common/ucx.py listener/endpoint manager).

Single-controller cliques use the in-process `_Mailbox` (comms.comms); a
multi-process SPMD job (one controller per host, wired together with
`jax.distributed` — see comms.bootstrap.initialize_distributed) uses this
`TcpMailbox` instead: same (source, dest, tag) FIFO semantics, but
messages to remote ranks travel over TCP. Payloads are numpy arrays in
``.npy`` wire format (no pickle: nothing executable crosses the wire),
each framed with a CRC32 so wire damage is *detected* and dropped rather
than delivered.

Resilience (ref: the reliability NCCL/UCX provide internally, which a
re-owned transport must rebuild — see docs/architecture.md "Comms
resilience"):

* connect/send retries ride :class:`raft_tpu.comms.resilience.RetryPolicy`
  (exponential backoff + jitter, deadline-aware);
* every connection opens with a HELLO frame naming the sender's rank, so
  the receiving side can attribute the connection — and its death — to a
  peer; periodic HEARTBEAT frames keep attributed peers provably alive,
  and a failure detector declares a peer dead on connection loss without
  a GOODBYE or on heartbeat silence, failing pending ``get``s fast with
  :class:`PeerFailedError` (dead rank attached) instead of letting them
  wait out the full deadline;
* a :class:`raft_tpu.comms.faults.FaultInjector` on ``faults``
  chaos-tests the wire path (drop / delay / duplicate / corrupt /
  disconnect) — the same injector drives the in-process `_Mailbox`.

Design note (the committed multi-process story, VERDICT #7): device-side
collectives in a multi-process job are XLA's own — a jitted computation
over the global mesh moves data over ICI/DCN, so MeshComms never needs a
device-side wire protocol of its own. What the reference's UCX layer adds
beyond NCCL is *host* tag-matched p2p for control/bootstrap traffic; this
module is that layer's TPU-stack equivalent.
"""

from __future__ import annotations

import contextlib
import io
import socket
import struct
import threading
import time
import zlib
from typing import Dict, List, Optional, Set

import numpy as np

from raft_tpu.comms.errors import CommsTimeoutError, PeerFailedError
from raft_tpu.comms.faults import corrupt_array, corrupt_bytes
from raft_tpu.comms.resilience import (
    CONNECT_POLICY,
    RECONNECT_POLICY,
    RetryPolicy,
    TagStore,
    default_recv_timeout as _default_recv_timeout,
)
from raft_tpu.core import logger, trace
from raft_tpu import obs

# kind, source, dest, tag, crc32(body), nbytes
_HDR = struct.Struct("<iiiiIq")

_DATA = 0       # tag-matched payload frame (body = .npy bytes)
_HELLO = 1      # connection preamble: attributes the stream to a rank
_HEARTBEAT = 2  # periodic liveness proof on idle/busy links alike
_GOODBYE = 3    # graceful departure: peer is leaving, not crashing
_ABORT = 4      # poison frame: body = utf-8 reason; every pending and
                # future get on the receiver raises CommsAbortedError
                # (the wire leg of MeshComms.abort — ref status_t::Abort)
_CTX = 5        # optional trace-context header (ISSUE 10): body =
                # TraceContext.to_header() utf-8; sent ahead of DATA
                # frames when tracing is on, so a collective's spans on
                # every rank share one trace_id. A corrupt or malformed
                # context frame is dropped silently — tracing is
                # best-effort metadata, never a delivery failure.


def _recv_exact(conn: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = conn.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed mid-message")
        buf.extend(chunk)
    return bytes(buf)


class TcpMailbox:
    """Tag-matched mailbox whose remote legs ride TCP.

    Parameters
    ----------
    rank : this process's rank.
    addrs : per-rank "host:port" listen addresses (every rank gets the
        same list — the analogue of the worker address exchange in
        raft_dask comms.py:144's worker_info).
    faults : optional FaultInjector installed on the send path.
    heartbeat_interval : seconds between HEARTBEAT frames on each open
        outbound connection.
    heartbeat_timeout : silence (no frame of any kind) from an attributed
        peer after which the failure detector declares it dead.  Sized
        generously by default: a loaded host can stall user threads for
        seconds (the same rationale as ``get``'s deadline); the *fast*
        detection path is connection EOF, which needs no timer.
    connect_policy : RetryPolicy for first-contact connects (default
        tolerates slow bootstrap, resilience.CONNECT_POLICY).
    default_recv_timeout : default blocking-get deadline; None resolves
        via RAFT_TPU_RECV_TIMEOUT / the 120 s loaded-host fallback (see
        ``get``'s deadline rationale).
    """

    # each process owns its own store: abort/failure state must cross
    # the wire (the _ABORT frame), and survivor consensus must run the
    # real protocol instead of reading a shared detector
    shared_store = False

    def __init__(self, rank: int, addrs: List[str], *, faults=None,
                 heartbeat_interval: float = 2.0,
                 heartbeat_timeout: float = 10.0,
                 connect_policy: Optional[RetryPolicy] = None,
                 default_recv_timeout: Optional[float] = None):
        self.rank = int(rank)
        self.addrs = list(addrs)
        self.faults = faults
        self.heartbeat_interval = float(heartbeat_interval)
        self.heartbeat_timeout = float(heartbeat_timeout)
        self.connect_policy = connect_policy or CONNECT_POLICY
        self.default_timeout = (
            default_recv_timeout if default_recv_timeout is not None
            else _default_recv_timeout(120.0))
        self._store = TagStore(name=f"tcp-mailbox[rank {self.rank}]")
        self._lock = threading.Lock()
        # One persistent connection per destination, guarded by a per-dest
        # lock: all messages to a peer travel one ordered byte stream, and
        # the peer's single per-connection serve thread enqueues them in
        # arrival order — preserving the _Mailbox per-(source,dest,tag)
        # FIFO contract across processes.
        self._conns: Dict[int, socket.socket] = {}
        self._conn_locks: Dict[int, threading.Lock] = {}
        self._inbound: Set[socket.socket] = set()
        self._last_seen: Dict[int, float] = {}
        self._departed: Set[int] = set()
        self.corrupt_frames = 0
        host, port = self.addrs[self.rank].rsplit(":", 1)
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind((host, int(port)))
        self._srv.listen(64)
        self._closed = False
        self._stop = threading.Event()
        self._accept_thread = threading.Thread(target=self._accept_loop,
                                               daemon=True)
        self._accept_thread.start()
        self._maint_thread = threading.Thread(target=self._maintenance,
                                              daemon=True)
        self._maint_thread.start()

    # -- the _Mailbox interface (comms.comms) ------------------------------

    def _connect(self, dest: int,
                 policy: Optional[RetryPolicy] = None) -> socket.socket:
        """Dial a peer under a RetryPolicy (peers come up at different
        speeds during bootstrap — refused before the listener binds, SYN
        drops past the backlog, peer resets; the reference's UCX endpoint
        creation likewise blocks in a rendezvous, ucx.py:47).  Exhaustion
        marks the peer failed and raises PeerFailedError."""
        host, port = self.addrs[dest].rsplit(":", 1)
        policy = policy or self.connect_policy

        def attempt() -> socket.socket:
            return socket.create_connection((host, int(port)), timeout=30)

        try:
            s = policy.call(attempt, retry_on=(OSError,),
                            describe=f"connect rank {self.rank}->{dest}",
                            seed=(self.rank << 16) | dest)
        except (OSError, CommsTimeoutError) as e:
            self._store.fail_peer(dest, f"connect failed: {e!r}")
            raise PeerFailedError(
                f"tcp-mailbox rank {self.rank}: rank {dest} unreachable: "
                f"{e!r}", rank=dest) from e
        with contextlib.suppress(OSError):
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        # identify this stream so the peer can attribute its death to us
        s.sendall(_HDR.pack(_HELLO, self.rank, dest, 0, 0, 0))
        return s

    def put(self, source: int, dest: int, tag: int, payload) -> None:
        arr = np.asarray(payload)
        decision = (self.faults.on_send(source, dest, tag, arr)
                    if self.faults is not None else None)
        if decision is not None and decision.delay_s:
            # deadline-aware: an injected stall must not hold the sender
            # past an active runtime.limits deadline scope
            from raft_tpu.runtime.limits import sleep_within_deadline
            sleep_within_deadline(decision.delay_s, op="comms.send")
        payloads = [arr] if decision is None else decision.payloads
        ctx = obs.current_context() if obs.tracing_enabled() else None
        if dest == self.rank:
            if ctx is not None:
                self._store.note_ctx(source, ctx)
            for p in payloads:
                if decision is not None and decision.corrupt:
                    p = corrupt_array(np.asarray(p))
                self._store.deliver(source, dest, tag, p)
                if obs.enabled():
                    obs.inc("comms_messages_sent_total", 1,
                            transport="tcp-local")
                    obs.inc("comms_bytes_sent_total",
                            np.asarray(p).nbytes, transport="tcp-local")
            if decision is not None and decision.disconnect:
                self._store.fail_peer(source, "fault-injected disconnect")
            return
        frames = []
        if ctx is not None:
            # context header travels as a frame in the same list so the
            # reconnect-resend path replays it ahead of the data
            hdr_raw = ctx.to_header().encode("utf-8")
            frames.append((_CTX, zlib.crc32(hdr_raw), hdr_raw))
        for p in payloads:
            bio = io.BytesIO()
            np.save(bio, np.asarray(p), allow_pickle=False)
            raw = bio.getvalue()
            crc = zlib.crc32(raw)
            if decision is not None and decision.corrupt:
                # damage the body after CRC: the receiver detects + drops
                raw = corrupt_bytes(raw)
            frames.append((_DATA, crc, raw))
        with self._lock:
            lock = self._conn_locks.setdefault(dest, threading.Lock())
        with lock:
            s = self._get_conn(dest)
            try:
                self._send_frames(s, source, dest, tag, frames)
            except OSError as e:
                # established link dropped under us: one short-leash
                # reconnect + resend (at-least-once — a partially sent
                # frame may duplicate; receivers needing exactly-once
                # dedupe by tag protocol), then give the peer up
                with contextlib.suppress(OSError):
                    s.close()
                with self._lock:
                    self._conns.pop(dest, None)
                trace.record_event("comms.send_reconnect", dest=dest,
                                   tag=tag, error=repr(e))
                obs.inc("comms_reconnects_total", 1, transport="tcp")
                s = self._connect(dest, policy=RECONNECT_POLICY)
                with self._lock:
                    self._conns[dest] = s
                try:
                    self._send_frames(s, source, dest, tag, frames)
                except OSError as e2:
                    self._store.fail_peer(
                        dest, f"send failed after reconnect: {e2!r}")
                    raise PeerFailedError(
                        f"tcp-mailbox rank {self.rank}: send to rank "
                        f"{dest} failed after reconnect: {e2!r}",
                        rank=dest, endpoint=(source, dest, tag)) from e2
            if obs.enabled():
                obs.inc("comms_messages_sent_total",
                        sum(1 for k, _, _ in frames if k == _DATA),
                        transport="tcp")
                obs.inc("comms_bytes_sent_total",
                        sum(len(raw) + _HDR.size for _, _, raw in frames),
                        transport="tcp")
            if decision is not None and decision.disconnect:
                # chaos: cut the link mid-stream; the peer sees EOF with
                # no GOODBYE and its failure detector fires
                with contextlib.suppress(OSError):
                    s.shutdown(socket.SHUT_RDWR)
                with contextlib.suppress(OSError):
                    s.close()
                with self._lock:
                    self._conns.pop(dest, None)

    def _get_conn(self, dest: int) -> socket.socket:
        with self._lock:
            s = self._conns.get(dest)
        if s is None:
            s = self._connect(dest)
            with self._lock:
                self._conns[dest] = s
        return s

    @staticmethod
    def _send_frames(s: socket.socket, source: int, dest: int, tag: int,
                     frames) -> None:
        for kind, crc, raw in frames:
            s.sendall(_HDR.pack(kind, source, dest, tag, crc, len(raw)))
            s.sendall(raw)

    def get(self, source: int, dest: int, tag: int,
            timeout: Optional[float] = None):
        """Blocking tag-matched receive. The default deadline is sized
        for a LOADED host: the peer may be stuck behind multi-second XLA
        compiles or a saturated CPU before it sends (observed: the
        30 s default flaked the multiprocess tier when the full test
        suite and bench battery shared the machine). It is a
        deadlock-detection bound, not a latency promise — a peer proven
        dead fails the wait *immediately* with PeerFailedError via the
        failure detector; cancellation raises CommsAbortedError; only
        the no-evidence case waits out the deadline into
        CommsTimeoutError."""
        assert dest == self.rank, \
            f"rank {self.rank} cannot receive for rank {dest}"
        if timeout is None:
            timeout = self.default_timeout
        return self._store.get(source, dest, tag, timeout=timeout)

    def get_nowait(self, source: int, dest: int, tag: int):
        return self._store.get_nowait(source, dest, tag)

    def fail_peer(self, rank: int, reason: str) -> None:
        self._store.fail_peer(rank, reason)

    def revive_peer(self, rank: int) -> None:
        self._store.revive_peer(rank)

    def peer_failed(self, rank: int) -> Optional[str]:
        return self._store.peer_failed(rank)

    def failed_peers(self) -> Dict[int, str]:
        return self._store.failed_peers()

    # -- abort propagation (the wire leg of MeshComms.abort) ----------------

    def abort(self, reason: str) -> None:
        """Poison this store AND broadcast an _ABORT frame to every
        peer, so a blocked get on any live rank raises
        CommsAbortedError within a delivery, not a recv-timeout
        staircase.  Best-effort per peer: a rank that is already dead or
        unreachable simply misses the frame (its own failure detector is
        someone else's problem by then)."""
        self._store.abort(reason)
        obs.inc("comms_aborts_total", 1, transport="tcp")
        body = reason.encode("utf-8", "replace")[:4096]
        crc = zlib.crc32(body)
        for dest in range(len(self.addrs)):
            if dest == self.rank or self._store.peer_failed(dest) is not None:
                continue
            try:
                with self._lock:
                    lock = self._conn_locks.setdefault(dest,
                                                       threading.Lock())
                with lock:
                    s = self._get_conn(dest)
                    s.sendall(_HDR.pack(_ABORT, self.rank, dest, 0, crc,
                                        len(body)))
                    s.sendall(body)
            except (OSError, PeerFailedError) as e:
                trace.record_event("comms.abort_send_failed", dest=dest,
                                   error=repr(e))

    def clear_abort(self) -> None:
        self._store.clear_abort()

    def aborted(self) -> Optional[str]:
        return self._store.aborted()

    # -- plumbing ----------------------------------------------------------

    def _accept_loop(self):
        while not self._closed:
            try:
                conn, _ = self._srv.accept()
            except OSError:
                return                      # listener closed
            with self._lock:
                self._inbound.add(conn)
            threading.Thread(target=self._serve, args=(conn,),
                             daemon=True).start()

    def _mark_alive(self, source: int) -> None:
        with self._lock:
            self._last_seen[source] = time.monotonic()
            self._departed.discard(source)
        # fresh liveness evidence clears any (possibly transient) failure
        self._store.revive_peer(source)

    def _serve(self, conn: socket.socket):
        peer: Optional[int] = None
        graceful = False
        reason = "connection closed"
        try:
            with conn:
                while True:                 # messages stream until close
                    hdr = _recv_exact(conn, _HDR.size)
                    kind, source, dest, tag, crc, nbytes = _HDR.unpack(hdr)
                    peer = source
                    self._mark_alive(source)
                    if kind == _GOODBYE:
                        graceful = True
                        break
                    if kind in (_HELLO, _HEARTBEAT):
                        continue
                    if kind == _ABORT:
                        raw = _recv_exact(conn, nbytes)
                        why = (raw.decode("utf-8", "replace")
                               if zlib.crc32(raw) == crc else "(corrupt)")
                        self._store.abort(
                            f"abort from rank {source}: {why}")
                        continue
                    if kind == _CTX:
                        raw = _recv_exact(conn, nbytes)
                        if zlib.crc32(raw) == crc:
                            # best-effort metadata: drop on parse error
                            with contextlib.suppress(ValueError,
                                                     UnicodeDecodeError):
                                self._store.note_ctx(
                                    source, obs.TraceContext.from_header(
                                        raw.decode("utf-8")))
                        continue
                    raw = _recv_exact(conn, nbytes)
                    if zlib.crc32(raw) != crc:
                        with self._lock:
                            self.corrupt_frames += 1
                        obs.inc("comms_frames_corrupt_total", 1,
                                transport="tcp")
                        trace.record_event("comms.frame_corrupt",
                                           source=source, dest=dest,
                                           tag=tag)
                        logger.warn_once(
                            ("tcp-mailbox-corrupt", self.rank, source),
                            "tcp-mailbox rank %d: corrupt frame from rank"
                            " %d dropped (crc mismatch); further drops "
                            "logged at debug", self.rank, source)
                        continue
                    arr = np.load(io.BytesIO(raw), allow_pickle=False)
                    self._store.deliver(source, dest, tag, arr)
                    if obs.enabled():
                        obs.inc("comms_messages_recv_total", 1,
                                transport="tcp")
                        obs.inc("comms_bytes_recv_total",
                                nbytes + _HDR.size, transport="tcp")
        except (ConnectionError, OSError, ValueError) as e:
            reason = repr(e)
        finally:
            with self._lock:
                self._inbound.discard(conn)
        if self._closed or peer is None:
            return
        if graceful:
            with self._lock:
                self._departed.add(peer)
                self._last_seen.pop(peer, None)
            self._store.fail_peer(peer, "peer departed (graceful close)")
        else:
            self._store.fail_peer(peer, f"connection lost ({reason})")

    def _maintenance(self):
        """Heartbeat sender + failure detector (one thread per mailbox)."""
        period = max(0.05, min(self.heartbeat_interval / 2.0, 1.0))
        next_hb = 0.0
        while not self._stop.wait(period):
            now = time.monotonic()
            if now >= next_hb:
                next_hb = now + self.heartbeat_interval
                self._send_heartbeats()
            self._check_liveness(now)

    def _send_heartbeats(self):
        with self._lock:
            dests = list(self._conns)
        for dest in dests:
            with self._lock:
                lock = self._conn_locks.setdefault(dest, threading.Lock())
            with lock:
                with self._lock:
                    s = self._conns.get(dest)
                if s is None:
                    continue
                try:
                    s.sendall(_HDR.pack(_HEARTBEAT, self.rank, dest,
                                        0, 0, 0))
                except OSError:
                    # link torn under us: drop the cached conn (the next
                    # put re-dials); the peer's own detector covers their
                    # side of the stream
                    with contextlib.suppress(OSError):
                        s.close()
                    with self._lock:
                        self._conns.pop(dest, None)

    def _check_liveness(self, now: float):
        with self._lock:
            stale = [(r, t) for r, t in self._last_seen.items()
                     if now - t > self.heartbeat_timeout]
            for r, _ in stale:
                self._last_seen.pop(r, None)
        if stale:
            obs.inc("comms_heartbeat_misses_total", len(stale),
                    transport="tcp")
        for r, t in stale:
            self._store.fail_peer(
                r, f"no heartbeat for {now - t:.1f}s "
                   f"(timeout {self.heartbeat_timeout}s)")

    def close(self):
        with self._lock:
            if self._closed:
                return
            self._closed = True
            conns = dict(self._conns)
            self._conns.clear()
            inbound = list(self._inbound)
            self._inbound.clear()
        self._stop.set()
        for dest, s in conns.items():
            # a parting GOODBYE distinguishes departure from death on the
            # peer's failure detector
            with contextlib.suppress(OSError):
                s.sendall(_HDR.pack(_GOODBYE, self.rank, dest, 0, 0, 0))
            with contextlib.suppress(OSError):
                s.close()
        for s in inbound:
            with contextlib.suppress(OSError):
                s.close()
        with contextlib.suppress(OSError):
            self._srv.close()
        self._store.stir()

    def __del__(self):
        with contextlib.suppress(Exception):
            self.close()
