"""Communicator self-tests, callable from user code.

Mirrors the reference's device-verifying comms tests
(comms/detail/test.hpp:31-513 and comms/comms_test.hpp:23-133), which
raft-dask exposes as ``perform_test_comms_*`` (comms_utils.pyx:68-218).
Each function takes a handle (or a MeshComms) and returns a bool exactly as
the reference does; Python test code asserts on the result.
"""

from __future__ import annotations

import numpy as np

from raft_tpu.comms.comms import MeshComms, Op


def _comms(handle_or_comms) -> MeshComms:
    if isinstance(handle_or_comms, MeshComms):
        return handle_or_comms
    from raft_tpu.core import resources as core_res

    return core_res.get_comms(handle_or_comms)


def perform_test_comms_allreduce(handle, root: int = 0) -> bool:
    """Each rank contributes 1; all must see clique size
    (ref: test_collective_allreduce, detail/test.hpp:31-55)."""
    comm = _comms(handle)
    n = comm.get_size()
    out = comm.allreduce(np.ones((n, 1), np.int32), op=Op.SUM)
    comm.barrier()
    return bool(np.all(np.asarray(out) == n))


def perform_test_comms_bcast(handle, root: int = 0) -> bool:
    """Root sends its rank id; all must receive ``root``
    (ref: test_collective_broadcast, detail/test.hpp:57-90)."""
    comm = _comms(handle)
    n = comm.get_size()
    send = np.arange(n, dtype=np.int32).reshape(n, 1)  # slot r holds r
    out = comm.bcast(send, root=root)
    comm.barrier()
    return bool(np.all(np.asarray(out) == root))


def perform_test_comms_reduce(handle, root: int = 0) -> bool:
    """Each rank sends ``root``; root must see root*size
    (ref: test_collective_reduce, detail/test.hpp:92-131)."""
    comm = _comms(handle)
    n = comm.get_size()
    send = np.full((n, 1), root, np.int32)
    out = np.asarray(comm.reduce(send, op=Op.SUM, root=root))
    comm.barrier()
    return bool(out[root, 0] == root * n)


def perform_test_comms_allgather(handle, root: int = 0) -> bool:
    """Each rank sends its rank id; all must see [0..n)
    (ref: test_collective_allgather, detail/test.hpp:133-166)."""
    comm = _comms(handle)
    n = comm.get_size()
    send = np.arange(n, dtype=np.int32).reshape(n, 1)
    out = np.asarray(comm.allgather(send))  # [n, n]
    comm.barrier()
    want = np.tile(np.arange(n, dtype=np.int32), (n, 1))
    return bool(np.array_equal(out, want))


def perform_test_comms_allgatherv(handle, root: int = 0) -> bool:
    """Variable counts: rank r contributes r+1 copies of r
    (ref: test_collective_allgatherv, detail/test.hpp:168-224)."""
    comm = _comms(handle)
    n = comm.get_size()
    counts = [r + 1 for r in range(n)]
    maxc = max(counts)
    send = np.zeros((n, maxc), np.int32)
    for r in range(n):
        send[r, : counts[r]] = r
    out = np.asarray(comm.allgatherv(send, counts))  # [n, sum(counts)]
    comm.barrier()
    want = np.concatenate(
        [np.full(counts[r], r, np.int32) for r in range(n)])
    return bool(all(np.array_equal(out[r], want) for r in range(n)))


def perform_test_comms_gather(handle, root: int = 0) -> bool:
    """ref: test_collective_gather (detail/test.hpp:226-263).

    Also pins the DOCUMENTED divergence from the reference (gatherv
    docstring, comms.py): XLA collectives are SPMD, so every rank — not
    just root — receives the gathered buffer. Reference-ported code that
    relied on non-root recv buffers staying untouched must not assume
    that here; this test makes the behavioral contract explicit."""
    comm = _comms(handle)
    n = comm.get_size()
    send = np.arange(n, dtype=np.int32).reshape(n, 1)
    out = np.asarray(comm.gather(send, root=root))
    comm.barrier()
    want = np.arange(n, dtype=np.int32)
    if not np.array_equal(out[root], want):
        return False
    # the divergence: non-root ranks hold the same full buffer
    return bool(all(np.array_equal(out[r], want) for r in range(n)))


def perform_test_comms_gatherv(handle, root: int = 0) -> bool:
    """ref: test_collective_gatherv (detail/test.hpp:265-324)."""
    comm = _comms(handle)
    n = comm.get_size()
    counts = [r + 1 for r in range(n)]
    maxc = max(counts)
    send = np.zeros((n, maxc), np.int32)
    for r in range(n):
        send[r, : counts[r]] = r
    out = np.asarray(comm.gatherv(send, counts, root=root))
    comm.barrier()
    want = np.concatenate(
        [np.full(counts[r], r, np.int32) for r in range(n)])
    if not np.array_equal(out[root], want):
        return False
    # assert the SPMD divergence (see perform_test_comms_gather): every
    # rank receives the full gathered buffer, not only root
    return bool(all(np.array_equal(out[r], want) for r in range(n)))


def perform_test_comms_reducescatter(handle, root: int = 0) -> bool:
    """Each rank sends ones[n]; each receives its block summed to n
    (ref: test_collective_reducescatter, detail/test.hpp:326-360)."""
    comm = _comms(handle)
    n = comm.get_size()
    send = np.ones((n, n), np.int32)
    out = np.asarray(comm.reducescatter(send, op=Op.SUM))  # [n, 1]
    comm.barrier()
    return bool(np.all(out == n))


def perform_test_comms_send_recv(handle, num_trials: int = 2) -> bool:
    """Host tag-matched p2p ring (ref: test_pointToPoint_simple_send_recv,
    detail/test.hpp:362-418: each rank sends its rank to all others)."""
    comm = _comms(handle)
    n = comm.get_size()
    for _ in range(num_trials):
        reqs = []
        for r in range(n):
            view = comm.rank_view(r)
            for dst in range(n):
                if dst != r:
                    reqs.append(view.isend(np.int32(r), dst, tag=r))
        recv_reqs = []
        for r in range(n):
            view = comm.rank_view(r)
            for src in range(n):
                if src != r:
                    recv_reqs.append((r, src, view.irecv(src, tag=src)))
        for r, src, req in recv_reqs:
            got = req.wait()
            if int(got) != src:
                return False
        comm.waitall([q for q in reqs])
    comm.barrier()
    return True


def perform_test_comms_device_send_recv(handle, root: int = 0) -> bool:
    """Device p2p ring shift: rank r sends r to r+1
    (ref: test_pointToPoint_device_send_or_recv, detail/test.hpp:420-452)."""
    comm = _comms(handle)
    n = comm.get_size()
    send = np.arange(n, dtype=np.int32).reshape(n, 1)
    perm = [(i, (i + 1) % n) for i in range(n)]
    out = np.asarray(comm.device_sendrecv(send, perm))
    comm.barrier()
    want = np.roll(np.arange(n, dtype=np.int32), 1).reshape(n, 1)
    return bool(np.array_equal(out, want))


def perform_test_comms_device_sendrecv(handle, root: int = 0) -> bool:
    """Simultaneous send+recv pairs (ref: test_pointToPoint_device_sendrecv,
    detail/test.hpp:454-482: pair ranks exchange values)."""
    comm = _comms(handle)
    n = comm.get_size()
    if n % 2 != 0:
        return True  # pairing test needs even clique, as in the reference
    send = np.arange(n, dtype=np.int32).reshape(n, 1)
    perm = []
    for i in range(0, n, 2):
        perm += [(i, i + 1), (i + 1, i)]
    out = np.asarray(comm.device_sendrecv(send, perm))
    comm.barrier()
    want = send.copy()
    for i in range(0, n, 2):
        want[i, 0], want[i + 1, 0] = send[i + 1, 0], send[i, 0]
    return bool(np.array_equal(out, want))


def perform_test_comms_device_multicast_sendrecv(handle, root: int = 0
                                                 ) -> bool:
    """Each rank multicasts to all others; receivers sum contributions
    (ref: test_pointToPoint_device_multicast_sendrecv,
    detail/test.hpp:484-513). ppermute delivers one source per dest, so the
    multicast is expressed as a rotation sweep accumulated over rounds."""
    comm = _comms(handle)
    n = comm.get_size()
    send = np.arange(n, dtype=np.int32).reshape(n, 1)
    acc = np.zeros((n, 1), np.int32)
    for shift in range(1, n):
        pairs = [(i, (i + shift) % n) for i in range(n)]
        acc = acc + np.asarray(comm.device_multicast_sendrecv(send, pairs))
    comm.barrier()
    total = n * (n - 1) // 2
    want = np.array([[total - r] for r in range(n)], np.int32)
    return bool(np.array_equal(acc, want))


def perform_test_comm_split(handle, n_colors: int = 2) -> bool:
    """Split into n_colors subcliques and run allreduce in each
    (ref: test_commsplit, detail/test.hpp — comm_split path;
    raft-dask test_comms.py:283)."""
    comm = _comms(handle)
    n = comm.get_size()
    if n < n_colors:
        return False
    color = [r % n_colors for r in range(n)]
    key = list(range(n))
    for r in range(n):
        sub = comm.rank_view(r).comm_split(color, key)
        m = sub.get_size()
        out = np.asarray(sub.allreduce(np.ones((m, 1), np.int32), op=Op.SUM))
        if not np.all(out == m):
            return False
        expect_rank = sum(1 for q in range(r) if color[q] == color[r])
        if sub.get_rank() != expect_rank:
            return False
    return True


# Reference-exact alias (raft-dask exports the device p2p self-test as
# perform_test_comms_device_send_or_recv, comms_utils.pyx / common/__init__).
perform_test_comms_device_send_or_recv = perform_test_comms_device_send_recv
