"""Typed error taxonomy for the comms stack (ref: core/comms.hpp:31-35
``status_t`` + raft::interruptible's ``interrupted_exception``).

The reference surfaces distributed failure through a tri-state
``status_t`` (SUCCESS / ERROR / ABORT) returned from ``sync_stream``;
richer context travels as exceptions.  Here every comms failure mode is
an exception type carrying the peer rank (where one is attributable) and
the tag-matched endpoint (where p2p context exists), and
``MeshComms.sync_stream`` folds the taxonomy back onto the ``Status``
enum for status_t-contract callers:

========================  ==========================================
type                      meaning / status_t mapping
========================  ==========================================
``CommsError``            base of the taxonomy (→ ``Status.ERROR``)
``CommsTimeoutError``     a deadline elapsed with the peer apparently
                          alive (→ ``Status.ERROR``); also a stdlib
                          ``TimeoutError`` for pre-taxonomy callers
``PeerFailedError``       the failure detector declared a peer dead;
                          ``.rank`` names it (→ ``Status.ERROR``)
``CommsAbortedError``     the operation was cancelled through
                          ``core.interruptible`` (→ ``Status.ABORT``);
                          also an ``InterruptedException`` so existing
                          cancellation-point handlers keep working
========================  ==========================================
"""

from __future__ import annotations

from typing import Optional, Tuple

from raft_tpu.core.interruptible import InterruptedException


class CommsError(RuntimeError):
    """Base comms failure (maps to ``status_t::ERROR``).

    Parameters
    ----------
    message : human-readable description.
    rank : peer rank the failure is attributed to, when known.
    endpoint : the ``(source, dest, tag)`` of the tag-matched op that
        observed the failure, when p2p context exists.
    """

    def __init__(self, message: str, *, rank: Optional[int] = None,
                 endpoint: Optional[Tuple[int, int, int]] = None):
        super().__init__(message)
        self.rank = rank
        self.endpoint = tuple(endpoint) if endpoint is not None else None


class CommsTimeoutError(CommsError, TimeoutError):
    """A comms deadline elapsed (blocking recv, retry budget, connect).

    Distinct from :class:`PeerFailedError`: a timeout means the peer has
    not been *proven* dead — it may merely be slow (the loaded-host case
    the mailbox deadlines are sized for)."""


class PeerFailedError(CommsError):
    """A peer was detected dead (connection lost without a goodbye,
    heartbeat silence, or fault-injected disconnect).  ``.rank`` always
    names the dead peer; pending receives matched against it fail fast
    with this instead of waiting out their full timeout."""


class CommsAbortedError(CommsError, InterruptedException):
    """The blocking comms op was cancelled via ``interruptible.cancel()``
    (maps to ``status_t::ABORT``).  Subclasses ``InterruptedException``
    so code treating cancellation points uniformly catches it too."""
