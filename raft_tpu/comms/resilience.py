"""Retry/backoff and failure-aware blocking — the resilience layer under
both comms transports.

Two pieces:

* :class:`RetryPolicy` — exponential backoff with jitter, deadline-aware
  and seedable, used by ``TcpMailbox`` connect/send and by
  ``bootstrap.initialize_distributed``.  The reference gets this for
  free from NCCL/UCX internals; re-owning the host p2p layer means
  re-owning its retry discipline.

* :class:`TagStore` — the tag-matched FIFO store shared by the
  in-process ``_Mailbox`` (comms.comms) and ``TcpMailbox``
  (comms.tcp_mailbox).  Unlike the ``queue.Queue``-per-key design it
  replaces, a single condition variable guards all keys, so a blocked
  ``get`` can be woken by *any* of: a matching message, the failure
  detector declaring the awaited peer dead (→ fast
  :class:`PeerFailedError` instead of a full-deadline stall), or an
  ``interruptible.cancel()`` aimed at the blocked thread (→
  :class:`CommsAbortedError`, the ref ``interruptible::synchronize``
  contract extended to host p2p).

Every retry / failure transition is recorded via
``core.trace.record_event`` (landing in the emitting thread's active
trace range) and logged through ``core.logger``.
"""

from __future__ import annotations

import collections
import random
import threading
import time
import zlib
from dataclasses import dataclass
from typing import Callable, Deque, Dict, Optional, Tuple

from raft_tpu.core import interruptible, logger, trace
from raft_tpu import obs
from raft_tpu.comms.errors import (
    CommsAbortedError,
    CommsTimeoutError,
    PeerFailedError,
)

_log = logger.child("comms")

# How long a blocked get sleeps between wake checks when nothing stirs
# the condition variable. Wakeups (message arrival, fail_peer, cancel)
# interrupt this immediately; the cap only bounds clock-driven checks
# (deadline expiry) on a quiet store.
_POLL_CAP_S = 0.1


def _limits():
    # function-level so importing comms never drags in the runtime
    # package (runtime.solver imports the solvers, which import comms)
    from raft_tpu.runtime import limits

    return limits


# Per-scope retry budgets (ISSUE 16): a sliding-window cap on the
# *total* retry rate each policy scope may emit, process-wide.  Without
# one, N callers hitting the same dead peer each run their full backoff
# schedule — the retry storm is N× the primary load precisely when the
# peer is least able to absorb it.  The budget bounds the amplification:
# once the window is spent, further failures fall through to their
# terminal error immediately (metered, not silently swallowed).
_retry_budgets: Dict[str, "object"] = {}
_retry_budgets_lock = threading.Lock()


def retry_budget(scope: str, *, max_events: int, window_s: float):
    """Get-or-create the process-wide retry budget for ``scope``.

    The first caller's sizing wins (scopes are policy-owned constants,
    not per-call knobs); tests use :func:`reset_retry_budgets` to
    re-size.  Returns a :class:`raft_tpu.runtime.limits.RateBudget`.
    """
    with _retry_budgets_lock:
        bud = _retry_budgets.get(scope)
        if bud is None:
            bud = _limits().RateBudget(max_events=max_events,
                                       window_s=window_s)
            _retry_budgets[scope] = bud
        return bud


def reset_retry_budgets() -> None:
    """Drop all per-scope retry budgets (test hook, mirroring
    ``limits.reset_breakers``)."""
    with _retry_budgets_lock:
        _retry_budgets.clear()


def default_recv_timeout(fallback: float) -> float:
    """Resolve the default blocking-recv deadline for a transport.

    ``RAFT_TPU_RECV_TIMEOUT`` (seconds) overrides the per-transport
    fallback (30 s in-process, 120 s TCP — the latter sized for loaded
    hosts, see TcpMailbox.get).  Explicit ``default_recv_timeout=``
    arguments on the mailbox constructors / ``build_mesh_comms`` win
    over both.  A malformed value raises ``ValueError`` — a typo'd
    timeout must never silently become the default.
    """
    from raft_tpu.core import env

    return env.read("RAFT_TPU_RECV_TIMEOUT", fallback)


@dataclass(frozen=True)
class RetryPolicy:
    """Exponential backoff + jitter, deadline-aware (ref: the rendezvous
    loops UCX/NCCL run internally; raft_dask ucx.py:47 blocks similarly).

    ``delay(attempt)`` grows ``base_delay * multiplier**attempt`` capped
    at ``max_delay``; ``jitter`` subtracts a uniformly random fraction of
    up to that share of the delay (decorrelating peer retry storms).
    ``deadline`` bounds the *total* wall time budget across attempts;
    when the next backoff would overrun it, the retry loop raises
    :class:`CommsTimeoutError` chaining the last underlying error.

    ``budget_scope`` enrolls the policy in a process-wide retry budget
    (see :func:`retry_budget`): every retry this policy would sleep for
    first spends one slot from the scope's sliding window
    (``budget_max`` events per ``budget_window_s``).  An exhausted
    budget converts the retry into an immediate re-raise of the last
    transient error, metered as ``limits_rejected_total{reason=
    "retry_budget"}`` — bounding the storm N failing callers can aim at
    one recovering peer.
    """

    max_attempts: int = 5
    base_delay: float = 0.05
    max_delay: float = 2.0
    multiplier: float = 2.0
    jitter: float = 0.5
    deadline: Optional[float] = None
    budget_scope: Optional[str] = None
    budget_max: int = 0
    budget_window_s: float = 60.0

    def delay(self, attempt: int, rng: Optional[random.Random] = None
              ) -> float:
        d = min(self.max_delay, self.base_delay * self.multiplier ** attempt)
        if self.jitter and rng is not None:
            d *= 1.0 - self.jitter * rng.random()
        return d

    def call(self, fn: Callable, *, retry_on=(OSError,), describe: str = "",
             seed: Optional[int] = None,
             on_retry: Optional[Callable[[int, BaseException], None]] = None):
        """Run ``fn()`` retrying transient failures under this policy.

        ``retry_on`` names the exception types considered transient; any
        other exception propagates immediately.  ``seed`` makes the
        jitter sequence reproducible; when omitted it derives from
        ``describe`` (crc32), so the whole retry schedule is a pure
        function of the call site — two peers retrying the same link
        replay identical backoffs run-to-run, while differently-named
        links stay decorrelated.  Each retry emits a
        ``comms.retry`` trace event in the caller's active range;
        exhaustion re-raises the last transient error, while a deadline
        overrun raises :class:`CommsTimeoutError` chaining it.
        Cancellation (``interruptible.cancel``) is observed between
        attempts, and so is the caller's ``runtime.limits`` deadline
        scope: backoff sleeps are capped by ``Deadline.remaining()`` and
        an expired scope raises ``DeadlineExceededError`` instead of
        burning further attempts.
        """
        if seed is None and describe:
            # deterministic decorrelation: jitter is a function of the
            # link's name, not of global RNG state at call time
            seed = zlib.crc32(describe.encode("utf-8", "replace"))
        rng = random.Random(seed)
        budget = (retry_budget(self.budget_scope,
                               max_events=self.budget_max,
                               window_s=self.budget_window_s)
                  if self.budget_scope and self.budget_max > 0 else None)
        start = time.monotonic()
        last: Optional[BaseException] = None
        for attempt in range(max(1, self.max_attempts)):
            interruptible.yield_now()
            _limits().check_deadline("comms.retry")
            try:
                return fn()
            except retry_on as e:
                last = e
                wait = self.delay(attempt, rng)
                elapsed = time.monotonic() - start
                if (self.deadline is not None
                        and elapsed + wait > self.deadline):
                    trace.record_event("comms.retry.deadline",
                                       what=describe, attempt=attempt + 1,
                                       elapsed=round(elapsed, 3),
                                       error=repr(e))
                    obs.inc("comms_retries_total", 1, outcome="deadline")
                    raise CommsTimeoutError(
                        f"{describe or 'comms op'}: retry deadline "
                        f"{self.deadline}s overrun after {attempt + 1} "
                        f"attempt(s): {e!r}") from e
                if attempt + 1 >= max(1, self.max_attempts):
                    break
                if budget is not None and not budget.try_spend():
                    # scope-wide retry budget spent: this caller's storm
                    # contribution ends here — fail fast, metered
                    trace.record_event("comms.retry.budget", what=describe,
                                       scope=self.budget_scope,
                                       attempt=attempt + 1, error=repr(e))
                    obs.inc("limits_rejected_total", 1,
                            reason="retry_budget", op=self.budget_scope)
                    obs.inc("comms_retries_total", 1, outcome="budget")
                    _log.warning(
                        "%s: retry budget for scope %r exhausted "
                        "(%d/%gs) — failing fast: %r", describe or
                        "comms op", self.budget_scope, self.budget_max,
                        self.budget_window_s, e)
                    raise e
                trace.record_event("comms.retry", what=describe,
                                   attempt=attempt + 1,
                                   delay=round(wait, 4), error=repr(e))
                obs.inc("comms_retries_total", 1, outcome="retried")
                _log.debug("retrying %s (attempt %d, backoff %.3fs): %r",
                           describe, attempt + 1, wait, e)
                if on_retry is not None:
                    on_retry(attempt, e)
                _limits().sleep_within_deadline(wait, op="comms.retry")
        trace.record_event("comms.retry.exhausted", what=describe,
                           attempts=max(1, self.max_attempts),
                           error=repr(last))
        obs.inc("comms_retries_total", 1, outcome="exhausted")
        _log.warning("%s failed after %d attempt(s): %r",
                     describe or "comms op", max(1, self.max_attempts), last)
        assert last is not None
        raise last


# Connect during bootstrap tolerates slow peers (multi-second XLA
# compiles before a listener binds — see TcpMailbox.get's deadline
# rationale); send-path reconnects after an established link drops get
# a much shorter leash, as a vanished *established* peer is the failure
# detector's business.  Each scope carries a process-wide retry budget
# sized far above any healthy workload (a full-mesh bootstrap of 16
# ranks retrying hard stays under 1/10th of it) — they exist to cap
# pathological amplification, not to shave healthy retries.
CONNECT_POLICY = RetryPolicy(max_attempts=60, base_delay=0.1, max_delay=1.0,
                             multiplier=1.5, jitter=0.3, deadline=120.0,
                             budget_scope="comms.connect",
                             budget_max=2400, budget_window_s=60.0)
RECONNECT_POLICY = RetryPolicy(max_attempts=4, base_delay=0.05,
                               max_delay=0.5, deadline=5.0,
                               budget_scope="comms.reconnect",
                               budget_max=240, budget_window_s=60.0)
BOOTSTRAP_POLICY = RetryPolicy(max_attempts=3, base_delay=0.5, max_delay=4.0,
                               jitter=0.3, deadline=60.0,
                               budget_scope="comms.bootstrap",
                               budget_max=120, budget_window_s=60.0)


class TagStore:
    """Tag-matched FIFO store with failure-, cancel- and deadline-aware
    blocking gets (the resilience-layer core shared by both mailboxes).

    Keys are ``(source, dest, tag)``; each key is a FIFO.  Messages
    already delivered are always drained before failure state is
    consulted, so a peer's parting messages remain readable after its
    death is recorded.
    """

    def __init__(self, name: str = "mailbox"):
        self.name = name
        self._cv = threading.Condition()
        self._queues: Dict[Tuple[int, int, int], Deque] = {}
        self._failed: Dict[int, str] = {}
        self._abort_reason: Optional[str] = None
        # latest trace context noted per source rank (ISSUE 10
        # cross-rank propagation) — populated only when tracing is on
        self._ctx: Dict[int, "obs.TraceContext"] = {}

    # -- producers ----------------------------------------------------------

    def deliver(self, source: int, dest: int, tag: int, payload) -> None:
        with self._cv:
            self._queues.setdefault((source, dest, tag),
                                    collections.deque()).append(payload)
            self._cv.notify_all()

    def note_ctx(self, source: int, ctx) -> None:
        """Record the trace context ``source``'s latest frames carried
        (the transport's context header / the in-process sender's
        thread-local). A matched ``get`` adopts it so a collective's
        spans on every rank share one trace_id."""
        if ctx is None:
            return
        with self._cv:
            self._ctx[source] = ctx

    def noted_ctx(self, source: int):
        with self._cv:
            return self._ctx.get(source)

    def stir(self) -> None:
        """Wake every blocked getter to re-check its exit conditions
        (registered as an ``interruptible`` waker during gets)."""
        with self._cv:
            self._cv.notify_all()

    # -- failure detector interface -----------------------------------------

    def fail_peer(self, rank: int, reason: str) -> None:
        """Declare ``rank`` dead: pending and future gets matched against
        it raise :class:`PeerFailedError` fast (after draining anything
        it already delivered)."""
        with self._cv:
            if rank not in self._failed:
                self._failed[rank] = reason
                trace.record_event("comms.peer_failed", store=self.name,
                                   rank=rank, reason=reason)
                obs.inc("comms_peer_failures_total", 1)
                _log.warning("%s: peer rank %d declared failed: %s",
                             self.name, rank, reason)
            self._cv.notify_all()

    def revive_peer(self, rank: int) -> None:
        """Clear failure state on fresh liveness evidence (a frame from
        the peer after a transient disconnect)."""
        with self._cv:
            if self._failed.pop(rank, None) is not None:
                trace.record_event("comms.peer_revived", store=self.name,
                                   rank=rank)
                _log.warning("%s: peer rank %d revived", self.name, rank)

    def peer_failed(self, rank: int) -> Optional[str]:
        with self._cv:
            return self._failed.get(rank)

    def failed_peers(self) -> Dict[int, str]:
        """Snapshot of the failure detector's current suspicions."""
        with self._cv:
            return dict(self._failed)

    # -- abort propagation (ISSUE 2 tentpole part 1) ------------------------

    def abort(self, reason: str) -> None:
        """Poison the store: every pending and future ``get`` raises
        :class:`CommsAbortedError` immediately (the store-local leg of
        ``MeshComms.abort`` — one rank's cancellation surfaces on every
        blocked peer within a wakeup, not a recv-timeout staircase).
        Unlike ``fail_peer``, abort wins over queued messages: a job
        being torn down must not keep draining stale data."""
        with self._cv:
            if self._abort_reason is None:
                self._abort_reason = reason
                trace.record_event("comms.abort", store=self.name,
                                   reason=reason)
                _log.warning("%s: aborted: %s", self.name, reason)
            self._cv.notify_all()

    def clear_abort(self) -> None:
        """Re-arm the store after recovery (a shrunken survivor clique
        starts from a clean slate)."""
        with self._cv:
            self._abort_reason = None

    def aborted(self) -> Optional[str]:
        with self._cv:
            return self._abort_reason

    # -- consumer -----------------------------------------------------------

    def get_nowait(self, source: int, dest: int, tag: int):
        """Pop a matching message if one is already queued, else None.
        Consults neither the failure detector nor abort state — used by
        drain-latest consumers (consensus, probe sweeps)."""
        with self._cv:
            dq = self._queues.get((source, dest, tag))
            return dq.popleft() if dq else None

    def get(self, source: int, dest: int, tag: int, timeout: float = 30.0):
        """Blocking tag-matched receive.

        Raises :class:`PeerFailedError` as soon as the failure detector
        declares ``source`` dead, :class:`CommsAbortedError` when this
        thread's ``interruptible`` token is cancelled (the cancel wakes
        the wait immediately), and :class:`CommsTimeoutError` at the
        deadline.  A ``runtime.limits`` deadline scope on the calling
        thread tightens the wait further: once it expires the recv
        raises ``DeadlineExceededError`` (within one poll cap), so a
        request deadline bounds the whole collective instead of racing
        the fixed transport timeout.
        """
        key = (source, dest, tag)
        token = interruptible.get_token()
        token.add_waker(self.stir)
        deadline = time.monotonic() + timeout
        limit = _limits().current_deadline()
        try:
            with self._cv:
                while True:
                    if self._abort_reason is not None:
                        raise CommsAbortedError(
                            f"{self.name}: aborted ({self._abort_reason}) "
                            f"with recv {key} pending", endpoint=key)
                    dq = self._queues.get(key)
                    if dq:
                        msg = dq.popleft()
                        if self._ctx and obs.tracing_enabled() \
                                and obs.current_context() is None:
                            # join the sender's trace: a rank thread
                            # blocked in a collective inherits the
                            # context its peer's frames carried
                            ctx = self._ctx.get(source)
                            if ctx is not None:
                                obs.adopt(ctx)
                        return msg
                    if token.cancelled():
                        token.clear()
                        raise CommsAbortedError(
                            f"{self.name}: recv {key} cancelled",
                            endpoint=key)
                    reason = self._failed.get(source)
                    if reason is not None:
                        # name the trace this death kills (the dead
                        # peer's noted context, else the waiter's own)
                        ctx = self._ctx.get(source) \
                            or obs.current_context()
                        suffix = (f" [trace {ctx.trace_id}]"
                                  if ctx is not None else "")
                        exc = PeerFailedError(
                            f"{self.name}: peer rank {source} failed "
                            f"({reason}) with recv {key} pending"
                            f"{suffix}",
                            rank=source, endpoint=key)
                        with obs.use_context(ctx):
                            obs.record_failure(exc, op="comms.recv")
                        raise exc
                    if limit is not None and limit.expired():
                        # raises DeadlineExceededError with the op key
                        # (and counts it) — queued messages above still
                        # win, so data already delivered stays readable
                        _limits().check_deadline("comms.recv")
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        raise CommsTimeoutError(
                            f"{self.name}: recv {key} timed out after "
                            f"{timeout}s (peer not proven dead — see "
                            f"PeerFailedError vs timeout semantics)",
                            rank=source, endpoint=key)
                    self._cv.wait(min(remaining, _POLL_CAP_S))
        finally:
            token.remove_waker(self.stir)
