"""Multi-chip communicator infrastructure over JAX mesh collectives.

TPU-native re-design of the reference comms stack
(cpp/include/raft/core/comms.hpp:115-234 ``comms_iface``/``comms_t``;
cpp/include/raft/comms/std_comms.hpp — NCCL/UCX implementation).

Where the reference layers a virtual interface over NCCL collectives and
UCX tag-matched p2p, the TPU design has *two* surfaces:

1. **Device-side functional collectives** (:mod:`raft_tpu.comms.device`) —
   free functions (`allreduce`, `bcast`, `allgather`, ...) legal *inside*
   `shard_map`-traced code, compiled by XLA into ICI/DCN collectives.
   These replace the NCCL enqueue calls that appear inside reference
   MNMG algorithms.
2. **`MeshComms`** (:mod:`raft_tpu.comms.comms`) — the host-side
   ``comms_t`` analogue injected into the handle via
   `raft_tpu.core.resources.set_comms`.  It owns a `jax.sharding.Mesh`
   axis, answers `get_size`/`get_rank`, performs *eager* collectives on
   mesh-sharded arrays (each call jit-compiles a shard_map — the analogue
   of enqueueing an NCCL kernel on a stream), splits into
   sub-communicators (`comm_split` → sub-mesh), and hosts a tag-matched
   host mailbox standing in for UCX isend/irecv.

The self-test suite mirroring comms/detail/test.hpp:31-513 lives in
:mod:`raft_tpu.comms.test_suite` and is runnable on any mesh (including the
8-virtual-CPU-device test mesh) — the analogue of ``perform_test_comms_*``.

Resilience layer (docs/architecture.md "Comms resilience"): a typed
error taxonomy (:mod:`raft_tpu.comms.errors` — ``CommsError`` →
``CommsTimeoutError`` / ``PeerFailedError`` / ``CommsAbortedError``,
mirroring the reference ``status_t`` contract), retry/backoff
(:mod:`raft_tpu.comms.resilience` ``RetryPolicy``), peer liveness
(heartbeats + failure detection in :mod:`raft_tpu.comms.tcp_mailbox`),
and seedable rank-scoped fault injection
(:mod:`raft_tpu.comms.faults` ``FaultInjector``) behind both mailbox
transports.

Elastic layer (ISSUE 2): ``MeshComms.abort`` broadcasts a poison frame
(all ranks fail within one heartbeat), ``agree_on_survivors`` is the
failure-consensus barrier, ``shrink`` rebuilds a survivors-only clique
via the comm_split machinery, and ``bootstrap.reinitialize_survivors``
re-injects handles over the survivor mesh — together with
:mod:`raft_tpu.core.checkpoint` this lets iterative MNMG solvers finish
on fewer ranks after a rank loss.
"""

from raft_tpu.comms.errors import (  # noqa: F401
    CommsError,
    CommsTimeoutError,
    PeerFailedError,
    CommsAbortedError,
)
from raft_tpu.comms.resilience import (  # noqa: F401
    RetryPolicy,
    TagStore,
    default_recv_timeout,
)
from raft_tpu.comms.faults import FaultInjector  # noqa: F401
from raft_tpu.comms.comms import (  # noqa: F401
    Op,
    Datatype,
    Status,
    MeshComms,
    build_mesh_comms,
)
from raft_tpu.comms import device  # noqa: F401
from raft_tpu.comms.test_suite import (  # noqa: F401
    perform_test_comms_allreduce,
    perform_test_comms_bcast,
    perform_test_comms_reduce,
    perform_test_comms_allgather,
    perform_test_comms_allgatherv,
    perform_test_comms_gather,
    perform_test_comms_gatherv,
    perform_test_comms_reducescatter,
    perform_test_comms_send_recv,
    perform_test_comms_device_send_recv,
    perform_test_comms_device_send_or_recv,
    perform_test_comms_device_sendrecv,
    perform_test_comms_device_multicast_sendrecv,
    perform_test_comm_split,
)
from raft_tpu.comms.tcp_mailbox import TcpMailbox  # noqa: F401
from raft_tpu.comms.bootstrap import (  # noqa: F401
    Comms,
    initialize_distributed,
    inject_comms_on_handle,
    inject_comms_on_handle_coll_only,
    local_handle,
    get_raft_comm_state,
    reinitialize_survivors,
)
