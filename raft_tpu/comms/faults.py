"""Fault injection for the comms transports (the chaos hook the
reference never needed to expose: NCCL/UCX failures are injected with
real network tooling; a re-owned host p2p layer must ship its own).

A :class:`FaultInjector` installs behind *both* transports — the
in-process ``_Mailbox`` (comms.comms) and ``TcpMailbox``
(comms.tcp_mailbox) — via their ``faults`` attribute, so one chaos suite
drives both.  Every send consults :meth:`FaultInjector.on_send`, which
rolls a seeded RNG against the configured probabilities and returns a
:class:`FaultDecision` describing what the transport must do:

=============  =============================================================
fault          transport behavior
=============  =============================================================
``drop``       the message is never delivered / never hits the wire
``delay``      sender sleeps ``delay_s`` before delivery (reordering
               against other links; kept on the send path so a fixed
               seed gives a deterministic per-link schedule)
``duplicate``  the message is delivered / sent twice (at-least-once
               delivery stress — real TCP reconnect resends can do this)
``corrupt``    in-process: the payload is bit-flipped and *delivered*
               (memory-corruption model); on the wire: the frame body is
               flipped after CRC computation, so the receiver's
               integrity check detects and drops it (wire-damage model)
``disconnect`` the link is torn after the send: ``TcpMailbox`` force-
               closes the connection (peer sees EOF without a goodbye →
               failure detector fires); ``_Mailbox`` has no physical
               link, so it reports the source rank failed directly
=============  =============================================================

Beyond the probabilistic kinds, :meth:`FaultInjector.stall` arms a
deterministic latency-spike mode: every in-scope send carries at least
the given delay — the *slow* peer (as opposed to the dead one) that
deadline contracts must be tested against.

Determinism: the RNG is advanced by a fixed number of rolls per
*in-scope* send regardless of configuration, so the same seed and send
sequence replay the same fault schedule even as probabilities change
(``stall`` consumes no rolls — arming it never perturbs the schedule).
Rank scoping (``source_ranks`` / ``dest_ranks``) confines the chaos to
chosen links; out-of-scope sends neither fault nor advance the RNG.
"""

from __future__ import annotations

import collections
import os
import random
import signal
import threading
from dataclasses import dataclass
from typing import List, Optional, Set

import numpy as np

from raft_tpu.core import trace

KINDS = ("drop", "delay", "duplicate", "corrupt", "disconnect")

#: crash_point modes — "raise" surfaces CrashPointError for in-process
#: chaos tests; "kill" delivers an uncatchable SIGKILL to this process,
#: the real torn-state model the crash-consistency witnesses need.
CRASH_MODES = ("raise", "kill")


class CrashPointError(RuntimeError):
    """An armed :meth:`FaultInjector.crash_point` fired in raise mode
    (the in-process stand-in for the SIGKILL the kill mode delivers)."""

    def __init__(self, name: str):
        super().__init__(f"armed crash point {name!r} fired")
        self.name = name


@dataclass
class FaultDecision:
    """What the transport must do with one send."""

    payloads: List  # 0 entries = dropped, 2 = duplicated
    delay_s: float = 0.0
    disconnect: bool = False
    corrupt: bool = False
    kinds: tuple = ()  # which fault kinds fired (for logging/tests)


def corrupt_array(arr: np.ndarray) -> np.ndarray:
    """Deterministically bit-flip the first byte of a copy of ``arr``
    (the in-process corruption model)."""
    arr = np.asarray(arr)
    if arr.nbytes == 0:
        return arr
    raw = bytearray(arr.tobytes())
    raw[0] ^= 0xFF
    return np.frombuffer(bytes(raw), dtype=arr.dtype).reshape(arr.shape)


def corrupt_bytes(raw: bytes) -> bytes:
    """Bit-flip one byte of a serialized frame body (the wire-damage
    model — applied after CRC computation so the receiver detects it)."""
    if not raw:
        return raw
    buf = bytearray(raw)
    buf[len(buf) // 2] ^= 0xFF
    return bytes(buf)


class FaultInjector:
    """Seedable, rank-scoped fault plan for a mailbox transport.

    Parameters are per-send probabilities in [0, 1] for each kind in
    :data:`KINDS`; ``delay_s`` is the sleep applied when a delay fires;
    ``source_ranks`` / ``dest_ranks`` scope which links can fault
    (``None`` = all).  ``counts`` tallies fired faults for assertions.
    """

    def __init__(self, *, seed: int = 0, drop: float = 0.0,
                 delay: float = 0.0, duplicate: float = 0.0,
                 corrupt: float = 0.0, disconnect: float = 0.0,
                 delay_s: float = 0.02,
                 source_ranks: Optional[Set[int]] = None,
                 dest_ranks: Optional[Set[int]] = None):
        self.probs = {"drop": drop, "delay": delay, "duplicate": duplicate,
                      "corrupt": corrupt, "disconnect": disconnect}
        for k, p in self.probs.items():
            if not 0.0 <= p <= 1.0:
                raise ValueError(f"{k} probability {p} outside [0, 1]")
        self.delay_s = float(delay_s)
        self.source_ranks = (set(source_ranks)
                             if source_ranks is not None else None)
        self.dest_ranks = set(dest_ranks) if dest_ranks is not None else None
        self.seed = seed
        self._rng = random.Random(seed)
        self._lock = threading.Lock()
        self._stall_s = 0.0
        self._armed_crashes: dict = {}
        self._seen_crash_points: list = []
        self.counts: collections.Counter = collections.Counter()

    # -- deterministic crash points (ISSUE 17) ------------------------

    def arm_crash(self, name: str, *, mode: str = "raise") -> None:
        """Arm the named :meth:`crash_point`: the next time execution
        reaches it, the process dies there — ``mode="kill"`` delivers
        a real SIGKILL (the torn-state model: no atexit, no finally),
        ``mode="raise"`` raises :class:`CrashPointError` for in-process
        tests. Arming consumes no RNG rolls, so a probabilistic fault
        schedule replays identically with or without a crash armed —
        the same determinism discipline as :meth:`stall`."""
        if mode not in CRASH_MODES:
            raise ValueError(f"crash mode must be one of {CRASH_MODES}, "
                             f"got {mode!r}")
        with self._lock:
            self._armed_crashes[str(name)] = mode

    def disarm_crash(self, name: str) -> None:
        with self._lock:
            self._armed_crashes.pop(str(name), None)

    def seen_crash_points(self) -> List[str]:
        """Every named crash point execution has reached, in first-seen
        order (armed or not) — the enumeration the every-named-point
        crash-consistency witness sweeps over."""
        with self._lock:
            return list(self._seen_crash_points)

    def crash_point(self, name: str) -> None:
        """A named, deterministic kill site. Instrumented code calls
        this at protocol boundaries (``compact.pre_commit``,
        ``ingest.post_journal``, ...); unarmed it only records the name
        and returns — chaos tests then kill at exact protocol states
        instead of racing a timer against the worker thread."""
        name = str(name)
        with self._lock:
            if name not in self._seen_crash_points:
                self._seen_crash_points.append(name)
            mode = self._armed_crashes.get(name)
            if mode is not None:
                self.counts[f"crash:{name}"] += 1
        if mode is None:
            return
        trace.record_event("faults.crash_point", point=name, mode=mode)
        if mode == "kill":
            os.kill(os.getpid(), signal.SIGKILL)
        raise CrashPointError(name)

    def corrupt_bytes(self, path, *, offset: Optional[int] = None) -> int:
        """Deterministically flip one byte of the file at ``path`` —
        the seeded at-rest bit-flip the scrub/read-repair witnesses
        inject (ISSUE 18). The default offset is the middle byte: past
        any container magic/header, so detection exercises the
        per-entry CRC verification, not the cheap magic check. Like
        :meth:`stall` and :meth:`arm_crash` this consumes no RNG rolls —
        corrupting a file never perturbs a probabilistic fault
        schedule. Returns the flipped offset."""
        path = os.fspath(path)
        with open(path, "rb") as f:
            raw = bytearray(f.read())
        if not raw:
            raise ValueError(f"cannot corrupt empty file {path!r}")
        off = len(raw) // 2 if offset is None else int(offset)
        if not 0 <= off < len(raw):
            raise ValueError(f"offset {off} outside file of {len(raw)} "
                             f"bytes")
        raw[off] ^= 0xFF
        with open(path, "wb") as f:
            f.write(raw)
        with self._lock:
            self.counts["corrupt_file"] += 1
        trace.record_event("faults.corrupt_bytes", path=path, offset=off)
        return off

    def stall(self, seconds: float) -> None:
        """Arm the latency-spike mode: every subsequent in-scope send
        sleeps at least ``seconds`` before delivery (0 disarms).

        Unlike the probabilistic ``delay`` kind this is unconditional
        and consumes no RNG rolls, so a chaos schedule replays
        identically with or without the stall — the knob deadline tests
        turn to make a peer *slow* rather than dead."""
        seconds = float(seconds)
        if seconds < 0.0:
            raise ValueError(f"stall seconds must be >= 0, got {seconds}")
        with self._lock:
            self._stall_s = seconds

    def current_stall(self) -> float:
        """The armed stall, in seconds (0 = disarmed). Serving-side
        chaos (``Executor(faults=...)``) reads this per launch to
        straggle a replica without touching the transport path."""
        with self._lock:
            return self._stall_s

    def in_scope(self, source: int, dest: int) -> bool:
        return ((self.source_ranks is None or source in self.source_ranks)
                and (self.dest_ranks is None or dest in self.dest_ranks))

    def on_send(self, source: int, dest: int, tag: int,
                payload) -> FaultDecision:
        """Roll the fault plan for one send (transport-agnostic: the
        caller applies the decision in its own delivery terms)."""
        if not self.in_scope(source, dest):
            return FaultDecision(payloads=[payload])
        with self._lock:
            # fixed roll order/count per send → deterministic replay
            rolls = {k: self._rng.random() for k in KINDS}
            fired = tuple(k for k in KINDS if rolls[k] < self.probs[k])
            for k in fired:
                self.counts[k] += 1
            self.counts["sends"] += 1
            # stall rides outside the roll block: no RNG advance, so the
            # probabilistic schedule is identical with or without it
            stall_s = self._stall_s
            if stall_s:
                self.counts["stall"] += 1
        if stall_s:
            fired = fired + ("stall",)
        if fired:
            trace.record_event("comms.fault", kinds=fired, source=source,
                               dest=dest, tag=tag)
        # payloads carries fan-out only (drop/duplicate); corruption is a
        # *flag* — each transport applies its own damage model
        # (corrupt_array in-process, corrupt_bytes on the wire)
        payloads: List = [payload]
        if "duplicate" in fired:
            payloads = payloads * 2
        if "drop" in fired:
            payloads = []
        return FaultDecision(
            payloads=payloads,
            delay_s=max(self.delay_s if "delay" in fired else 0.0, stall_s),
            disconnect="disconnect" in fired,
            corrupt="corrupt" in fired,
            kinds=fired)
