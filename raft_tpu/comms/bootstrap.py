"""Cluster bootstrap in the shape of raft-dask's ``Comms`` (ref:
python/raft-dask/raft_dask/common/comms.py:28-233 `Comms`,
:236 `local_handle`, :257 `get_raft_comm_state`,
comms_utils.pyx:248-317 `inject_comms_on_handle*`).

TPU-native translation of the bootstrap dance (SURVEY.md §3.3): where the
reference places an NCCL uniqueId, rendezvouses every Dask worker on it and
injects a `std_comms` into each worker's handle, here the "cluster" is the
device mesh XLA already knows about — `jax.distributed.initialize` (on
multi-host) or the local device set (single-host) — so ``init()`` builds a
Mesh, creates one handle per participating rank and injects a `MeshComms`
rank view into each. Session registry semantics (sessionId keys, per-rank
state dicts, idempotent destroy) mirror the reference so downstream
"rank loop" algorithms port directly.

Multi-process design note (the committed story; exercised by
tests/test_multiprocess.py over real processes): device-side collectives
in a multi-process job are XLA's own — jit over the global mesh moves
data over ICI/DCN, so no NCCL-style wire protocol is re-implemented.
Host tag-matched p2p (the reference's UCX role) crosses processes via
`raft_tpu.comms.tcp_mailbox.TcpMailbox`, a drop-in for the in-process
mailbox: ``MeshComms(mesh, rank=process_index, _mailbox=TcpMailbox(...))``.
"""

from __future__ import annotations

import uuid
import weakref
from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh

from raft_tpu.core import logger
from raft_tpu.core import resources as core_res
from raft_tpu.comms.comms import MeshComms
from raft_tpu.comms.resilience import BOOTSTRAP_POLICY, RetryPolicy

# sessionId -> {"comms": weakref.ref(Comms), "handles": {rank: Resources},
# ...}; get_raft_comm_state dereferences the weakref before returning
# (ref: comms.py:257 get_raft_comm_state's per-worker state dict)
_session_state: dict = {}


def initialize_distributed(coordinator_address: Optional[str] = None,
                           num_processes: Optional[int] = None,
                           process_id: Optional[int] = None,
                           retry_policy: Optional[RetryPolicy] = None
                           ) -> None:
    """Multi-host process-group init — the analogue of the NCCL-uniqueId
    broadcast (comms.py:126-142): on TPU pods, `jax.distributed.initialize`
    wires every host into one XLA runtime; afterwards `jax.devices()`
    spans the whole slice. No-op if already initialized.

    Failure handling: the coordinator process routinely comes up *after*
    some workers (the same bootstrap race TcpMailbox._connect tolerates),
    so transient failures — connection refused/reset, XLA runtime errors
    from an absent coordinator — are retried under ``retry_policy``
    (default :data:`resilience.BOOTSTRAP_POLICY`: 3 attempts, exponential
    backoff, 60 s budget).  Structural errors (bad arguments raise
    ``ValueError``) propagate immediately; a deadline overrun raises
    ``CommsTimeoutError`` chaining the last runtime error, so the job can
    never silently fall back to running single-host."""
    policy = retry_policy or BOOTSTRAP_POLICY

    def attempt() -> None:
        try:
            jax.distributed.initialize(coordinator_address, num_processes,
                                       process_id)
        except RuntimeError as e:
            # Only the benign re-init case may be swallowed; a coordinator
            # timeout (XlaRuntimeError is a RuntimeError subclass) must
            # propagate (and be retried) or the job would silently run
            # single-host.
            if "already" in str(e).lower():
                logger.debug("jax.distributed already initialized: %s", e)
            else:
                raise

    policy.call(attempt, retry_on=(RuntimeError, ConnectionError, OSError),
                describe="jax.distributed.initialize",
                seed=process_id if process_id is not None else 0)


def inject_comms_on_handle(handle, mesh: Mesh, axis_name: str, rank: int,
                           _shared: Optional[dict] = None,
                           _mailbox=None) -> MeshComms:
    """Create a rank view of the clique communicator and set it on the
    handle (ref: comms_utils.pyx:278-317 → build_comms_nccl_ucx →
    resource::set_comms)."""
    comms = MeshComms(mesh, axis_name=axis_name, rank=rank,
                      _mailbox=_mailbox, _shared=_shared)
    core_res.set_mesh(handle, mesh)
    core_res.set_comms(handle, comms)
    return comms



def inject_comms_on_handle_coll_only(handle, mesh: Mesh, axis_name: str,
                                     rank: int, verbose: bool = False):
    """API parity with raft-dask's collectives-only injection
    (comms_utils.pyx `inject_comms_on_handle_coll_only` — NCCL without
    UCX). On TPU both variants wire the same MeshComms: device
    collectives always ride XLA; the host mailbox is in-process state
    with no setup cost, so there is nothing to omit. ``verbose`` is
    accepted for call compatibility and ignored."""
    del verbose
    return inject_comms_on_handle(handle, mesh, axis_name, rank)

class Comms:
    """Initializes and manages an SPMD communicator clique over the mesh
    (ref: raft_dask comms.py:28 `Comms`; comms_p2p there toggles UCX —
    here host p2p always works through the MeshComms mailbox).
    """

    def __init__(self, devices=None, axis_name: str = "world",
                 verbose: bool = False, nccl_root_location: str = "n/a"):
        self.sessionId = uuid.uuid4().bytes
        self._axis_name = axis_name
        self._devices = devices
        self._verbose = verbose
        self.nccl_root_location = nccl_root_location  # accepted for parity
        self._initialized = False

    # -- lifecycle (ref: comms.py:161 init, :210 destroy) -------------------

    def init(self, devices=None):
        """Build the mesh and one injected handle per rank.

        ``devices``: explicit device list (defaults to all of
        ``jax.devices()``), standing in for the reference's dask worker
        list (comms.py:161's `workers`).
        """
        if self._initialized:
            logger.warn("Comms have already been initialized.")
            return
        devs = list(devices if devices is not None
                    else (self._devices or jax.devices()))
        mesh = Mesh(np.asarray(devs), axis_names=(self._axis_name,))
        nranks = len(devs)

        shared = None
        mailbox = None
        handles = {}
        comms_views = {}
        for rank in range(nranks):
            handle = core_res.Resources()
            view = inject_comms_on_handle(
                handle, mesh, self._axis_name, rank,
                _shared=shared, _mailbox=mailbox)
            # all rank views share one mailbox + compiled-collective cache
            shared = view._shared
            mailbox = view._mailbox
            handles[rank] = handle
            comms_views[rank] = view

        # weakref inside _SessionState: the registry must not keep the Comms
        # object alive, or __del__-driven cleanup could never run and
        # un-destroyed sessions would accumulate for the process lifetime
        _session_state[self.sessionId] = _SessionState(
            comms=weakref.ref(self),
            mesh=mesh,
            nranks=nranks,
            handles=handles,
            comms_views=comms_views,
        )
        self._initialized = True
        if self._verbose:
            logger.info("Initialized comms session over %d devices", nranks)

    def destroy(self):
        """Tear the session down (ref: comms.py:210-233)."""
        if not self._initialized:
            return
        _session_state.pop(self.sessionId, None)
        self._initialized = False

    def __del__(self):
        self.destroy()


def reinitialize_survivors(sessionId, survivors):
    """Rebuild a comms session in place for the surviving ranks (the
    bootstrap leg of elastic recovery, ISSUE 2).

    After ``agree_on_survivors()`` names the live set, every survivor's
    rank view is shrunk (``MeshComms.shrink`` → comm_split over the
    survivor devices), fresh handles are injected over the survivor
    mesh, and the session registry entry is updated so
    ``get_raft_comm_state`` / ``local_handle`` keep working under the
    *new* dense ranks.  ``old_ranks`` in the session state maps new rank
    → pre-shrink rank, which resharding code needs to relocate data.

    Raises ``KeyError`` for an unknown/destroyed session and
    ``ValueError`` for an empty survivor set.
    """
    state = _session_state.get(sessionId)
    if state is None:
        raise KeyError(f"unknown comms session {sessionId!r}")
    survivors = sorted(int(r) for r in survivors)
    if not survivors:
        raise ValueError("survivor set is empty")
    old_views = state["comms_views"]
    handles = {}
    comms_views = {}
    for new_rank, old_rank in enumerate(survivors):
        sub = old_views[old_rank].shrink(survivors)
        assert sub.get_rank() == new_rank
        handle = core_res.Resources()
        core_res.set_mesh(handle, sub.mesh)
        core_res.set_comms(handle, sub)
        handles[new_rank] = handle
        comms_views[new_rank] = sub
    state["mesh"] = comms_views[0].mesh
    state["nranks"] = len(survivors)
    state["handles"] = handles
    state["comms_views"] = comms_views
    state["old_ranks"] = {new: old for new, old in enumerate(survivors)}
    logger.info("comms session reinitialized for %d survivor(s): %s",
                len(survivors), survivors)
    return handles


def local_handle(sessionId, rank: int = 0):
    """Simple helper to retrieve the rank's handle for a comms session
    (ref: comms.py:236 `local_handle`)."""
    state = _session_state.get(sessionId)
    return None if state is None else state["handles"].get(rank)


class _SessionState(dict):
    """Live, mutable per-session state (the reference contract: rank-loop
    code stashes values in this dict between calls, comms.py:257). The
    "comms" slot is stored as a weakref (so the registry can't pin the
    Comms object) but reads back as the live object or None."""

    def __getitem__(self, key):
        val = super().__getitem__(key)
        if key == "comms" and isinstance(val, weakref.ref):
            return val()
        return val

    def get(self, key, default=None):
        try:
            return self[key]
        except KeyError:
            return default


def get_raft_comm_state(sessionId):
    """Per-session LIVE state dict (ref: comms.py:257) — mutations persist
    across calls. Empty dict for unknown/destroyed sessions."""
    return _session_state.get(sessionId, {})
