"""Device-side collectives: legal inside `shard_map`-traced code.

These are the TPU-native analogue of the NCCL calls the reference makes from
inside MNMG algorithms (comms/detail/std_comms.hpp:366-571).  Each maps to an
XLA collective that rides ICI within a slice (DCN across slices), chosen by
the compiler from the mesh axis:

    reference (NCCL)                 raft_tpu (XLA, inside shard_map)
    ----------------                 --------------------------------
    ncclAllReduce                    lax.psum / pmin / pmax / psum(log-mul)
    ncclBroadcast                    select root shard + psum  (bcast)
    ncclReduce                       psum + keep-on-root
    ncclAllGather                    lax.all_gather
    grouped bcast loop (allgatherv)  lax.all_gather + per-rank slicing
    ncclSend/Recv loops (gatherv)    lax.all_gather + host-side slicing
    ncclReduceScatter                lax.psum_scatter
    ncclSend + ncclRecv (p2p)        lax.ppermute
    grouped multicast loops          lax.ppermute per (src,dst) pair

`op_t` (core/comms.hpp:26) maps to the reductions below; PROD is implemented
with psum of logs only where XLA lacks a pprod — we instead use
``lax.all_gather`` + product for exactness on small ranks, since XLA exposes
no native product collective.
"""

from __future__ import annotations

import enum
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
from jax import lax


class Op(enum.Enum):
    """Reduction vocabulary (ref: core/comms.hpp:26 ``op_t``)."""

    SUM = "sum"
    PROD = "prod"
    MIN = "min"
    MAX = "max"


def _axis_size(axis_name) -> int:
    if hasattr(lax, "axis_size"):
        return lax.axis_size(axis_name)
    # pre-axis_size jax: psum of a unit literal folds to the static size
    return lax.psum(1, axis_name)


def rank(axis_name="data"):
    """This shard's rank along ``axis_name`` (ref: get_rank())."""
    return lax.axis_index(axis_name)


def size(axis_name="data") -> int:
    """Number of shards along ``axis_name`` (ref: get_size())."""
    return _axis_size(axis_name)


def _grouped_reduce(x, op: Op, axis_name, groups):
    """Grouped reduction emulated with all_gather + static membership mask
    (shard_map collectives don't take axis_index_groups; the data movement
    is one all_gather on ICI, the masked reduce fuses into it)."""
    import numpy as np

    n = _axis_size(axis_name)
    member = np.zeros((n, n), bool)
    for grp in groups:
        for i in grp:
            member[i, list(grp)] = True
    g = lax.all_gather(x, axis_name=axis_name)  # [n, ...]
    idx = lax.axis_index(axis_name)
    mask = jnp.asarray(member)[idx]  # [n]
    mask = mask.reshape((n,) + (1,) * (g.ndim - 1))
    if op == Op.SUM:
        return jnp.sum(jnp.where(mask, g, jnp.zeros_like(g)), axis=0)
    if op == Op.MIN:
        big = jnp.full_like(g, jnp.inf if jnp.issubdtype(g.dtype, jnp.floating)
                            else jnp.iinfo(g.dtype).max)
        return jnp.min(jnp.where(mask, g, big), axis=0)
    if op == Op.MAX:
        small = jnp.full_like(g, -jnp.inf if jnp.issubdtype(g.dtype, jnp.floating)
                              else jnp.iinfo(g.dtype).min)
        return jnp.max(jnp.where(mask, g, small), axis=0)
    if op == Op.PROD:
        return jnp.prod(jnp.where(mask, g, jnp.ones_like(g)), axis=0)
    raise ValueError(f"unsupported op {op}")


def allreduce(x, op: Op = Op.SUM, axis_name="data",
              axis_index_groups: Optional[Sequence[Sequence[int]]] = None):
    """All-reduce across the named axis (ref: std_comms.hpp:366-374).

    ``axis_index_groups`` implements grouped reductions — the in-jit analogue
    of operating in a split communicator.
    """
    if axis_index_groups is not None:
        return _grouped_reduce(x, op, axis_name, axis_index_groups)
    if op == Op.SUM:
        return lax.psum(x, axis_name=axis_name)
    if op == Op.MIN:
        return lax.pmin(x, axis_name=axis_name)
    if op == Op.MAX:
        return lax.pmax(x, axis_name=axis_name)
    if op == Op.PROD:
        # XLA has no product collective; gather along the axis and reduce.
        g = lax.all_gather(x, axis_name=axis_name)
        return jnp.prod(g, axis=0)
    raise ValueError(f"unsupported op {op}")


def bcast(x, root: int = 0, axis_name="data"):
    """Broadcast the root shard's value to all shards
    (ref: std_comms.hpp:377-395 ncclBroadcast).

    Implemented as mask + psum: zero all non-root contributions, sum.
    XLA lowers this to a broadcast-shaped collective on ICI.
    """
    idx = lax.axis_index(axis_name)
    contrib = jnp.where(idx == root, x, jnp.zeros_like(x))
    return lax.psum(contrib, axis_name=axis_name)


def reduce(x, op: Op = Op.SUM, root: int = 0, axis_name="data"):
    """Reduce to root; non-root shards receive their input unchanged
    (ref: std_comms.hpp:398-422 ncclReduce semantics: recvbuff valid on root).
    """
    red = allreduce(x, op=op, axis_name=axis_name)
    idx = lax.axis_index(axis_name)
    return jnp.where(idx == root, red, x)


def allgather(x, axis_name="data", tiled: bool = False):
    """All-gather shards along a new (or tiled) leading dimension
    (ref: std_comms.hpp:425-433 ncclAllGather).
    """
    return lax.all_gather(x, axis_name=axis_name, tiled=tiled)


def allgatherv(x, recvcounts: Sequence[int], axis_name="data"):
    """Variable-count all-gather (ref: std_comms.hpp:436-468, implemented
    there as a loop of per-root grouped broadcasts).

    Each shard contributes its first ``recvcounts[rank]`` rows of ``x``
    (shards pad to a common static shape — the TPU-native stand-in for
    variable buffer sizes, which XLA's static shapes cannot express
    directly).  Returns the concatenation, padded to ``sum(max_count)``
    with validity handled by the caller via ``recvcounts``.
    """
    counts = [int(c) for c in recvcounts]  # static: buffer sizes are
    g = lax.all_gather(x, axis_name=axis_name)  # [size, pad, ...]
    nranks = g.shape[0]
    # Compact via static cumulative displacements (counts are host values,
    # exactly as the reference's size_t* recvcounts/displs are host memory).
    total = sum(counts)
    out_shape = (total,) + g.shape[2:]
    out = jnp.zeros(out_shape, g.dtype)
    displ = 0
    for r in range(nranks):  # static unroll: nranks is a mesh constant
        out = lax.dynamic_update_slice(
            out, g[r, : counts[r]],
            (displ,) + (0,) * (len(out_shape) - 1))
        displ += counts[r]
    return out


def gather(x, root: int = 0, axis_name="data"):
    """Gather shards to root (ref: std_comms.hpp:471-495).

    All shards receive the gathered array (XLA collectives are SPMD);
    parity with "recvbuff only valid on root" is natural — non-roots may
    ignore the result and XLA DCEs unused outputs.
    """
    return lax.all_gather(x, axis_name=axis_name)


def gatherv(x, recvcounts: Sequence[int], root: int = 0, axis_name="data"):
    """Variable-count gather to root (ref: std_comms.hpp:498-528)."""
    return allgatherv(x, recvcounts, axis_name=axis_name)


def reducescatter(x, op: Op = Op.SUM, axis_name="data"):
    """Reduce-scatter: each shard gets one reduced block
    (ref: std_comms.hpp:531-541 ncclReduceScatter).  ``x`` is the full-size
    per-shard contribution; shard i receives block i of the sum.
    """
    if op == Op.SUM:
        return lax.psum_scatter(x, axis_name=axis_name, tiled=True)
    # MIN/MAX/PROD: gather-reduce-slice (no fused XLA op exists).
    g = lax.all_gather(x, axis_name=axis_name)
    if op == Op.MIN:
        red = jnp.min(g, axis=0)
    elif op == Op.MAX:
        red = jnp.max(g, axis=0)
    else:
        red = jnp.prod(g, axis=0)
    n = _axis_size(axis_name)
    if red.shape[0] % n != 0:
        raise ValueError(
            f"reducescatter length {red.shape[0]} not divisible by "
            f"axis size {n}")
    block = red.shape[0] // n
    idx = lax.axis_index(axis_name)
    return lax.dynamic_slice_in_dim(red, idx * block, block, axis=0)


def device_send(x, dest: int, source: int, axis_name="data"):
    """Point-to-point send as its SPMD equivalent: a single-pair permute
    (ref: std_comms.hpp:544-548 ncclSend).

    NCCL p2p is two-sided; XLA's model is one-sided SPMD, so send and recv
    collapse into one ppermute issued by *all* shards.  Shards outside the
    pair receive zeros.
    """
    return lax.ppermute(x, axis_name, perm=[(source, dest)])


def device_recv(x, source: int, dest: int, axis_name="data"):
    """See :func:`device_send` — the same single-pair permute
    (ref: std_comms.hpp:551-555 ncclRecv)."""
    return lax.ppermute(x, axis_name, perm=[(source, dest)])


def device_sendrecv(x, perm: Sequence[tuple], axis_name="data"):
    """Simultaneous send+recv without deadlock
    (ref: std_comms.hpp:558-571 grouped ncclSend+ncclRecv).

    The reference's host loop calls this per-rank with that rank's
    (dest, source); under SPMD those per-rank pairs collapse into one static
    ``perm`` list of (source, dest) pairs executed as a single ppermute.
    For the common ring pattern use :func:`ring_shift`.
    """
    return lax.ppermute(x, axis_name, perm=list(perm))


def ring_shift(x, shift: int = 1, axis_name="data"):
    """Rotate shards around the ring (the idiomatic TPU p2p pattern:
    neighbor exchange over ICI; used by ring reductions / halo exchange)."""
    n = _axis_size(axis_name)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return lax.ppermute(x, axis_name, perm=perm)


def device_multicast_sendrecv(x, pairs: Sequence[tuple], axis_name="data"):
    """Multiple simultaneous p2p transfers
    (ref: std_comms.hpp:574-601 device_multicast_sendrecv): ``pairs`` is a
    static list of (source, dest) rank pairs, executed as one ppermute.
    Shards not receiving from anyone get zeros.
    """
    return lax.ppermute(x, axis_name, perm=list(pairs))


def barrier(axis_name="data"):
    """In-jit barrier: psum of 1 (exactly the reference's implementation,
    std_comms.hpp:133-147 barrier = allreduce of an int)."""
    return lax.psum(jnp.ones((), jnp.int32), axis_name=axis_name)
