"""Automatic output conversion (ref:
python/pylibraft/pylibraft/common/outputs.py:18-79)."""

from __future__ import annotations

import functools
import warnings

from raft_tpu.compat import config
from raft_tpu.compat.common import device_ndarray


def _import_warn(lib):
    warnings.warn(
        f"Attempted to convert output to {lib}, but {lib} could not be "
        f"imported. Returning original output instead.")


def convert_to_torch(arr: device_ndarray):
    try:
        import torch
        return torch.from_dlpack(arr.values)
    except ImportError:
        _import_warn("torch")
        return arr


def convert_to_numpy(arr: device_ndarray):
    return arr.copy_to_host()


def convert_to_jax(arr: device_ndarray):
    return arr.values


def no_conversion(arr):
    return arr


def _conv(ret):
    if not isinstance(ret, device_ndarray):
        return ret
    output_as = config.output_as_
    if callable(output_as):
        return output_as(ret)
    return {
        "raft": no_conversion,
        "jax": convert_to_jax,
        "numpy": convert_to_numpy,
        "torch": convert_to_torch,
    }[output_as](ret)


def auto_convert_output(f):
    """Convert device_ndarray returns per `set_output_as`
    (ref: outputs.py:64-79; handles scalars, tuples and lists)."""

    @functools.wraps(f)
    def wrapper(*args, **kwargs):
        ret = f(*args, **kwargs)
        if isinstance(ret, (tuple, list)):
            converted = [_conv(r) for r in ret]
            return type(ret)(converted)
        return _conv(ret)

    return wrapper
