"""ref: pylibraft/sparse/linalg/__init__.py — re-exports eigsh."""

from raft_tpu.compat.sparse_api import eigsh  # noqa: F401
