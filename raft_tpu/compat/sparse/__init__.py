"""Import-path parity with ``pylibraft.sparse`` (ref:
python/pylibraft/pylibraft/sparse/__init__.py): migrators who only
rewrite the top-level package name keep their import lines working —
``from raft_tpu.compat.sparse.linalg import eigsh``.
"""

from raft_tpu.compat.sparse import linalg  # noqa: F401
