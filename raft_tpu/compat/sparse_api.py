"""pylibraft.sparse.linalg parity (ref:
python/pylibraft/pylibraft/sparse/linalg/lanczos.pyx:85-200 `eigsh`).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from raft_tpu.compat.common import auto_sync_handle, device_ndarray
from raft_tpu.compat.outputs import auto_convert_output
from raft_tpu.core.sparse_types import CSRMatrix
from raft_tpu.sparse.solver import lanczos as _lanczos


def _as_csr(a) -> CSRMatrix:
    """Accept our CSRMatrix or any scipy-like duck object with
    indptr/indices/data (+ shape), matching the pyx's duck-typed CAI
    unwrapping (lanczos.pyx:147-153)."""
    if isinstance(a, CSRMatrix):
        return a
    if all(hasattr(a, attr) for attr in ("indptr", "indices", "data")):
        shape = getattr(a, "shape", None)
        if shape is None:
            n = len(np.asarray(a.indptr)) - 1
            shape = (n, n)
        return CSRMatrix(jnp.asarray(np.asarray(a.indptr)),
                         jnp.asarray(np.asarray(a.indices)),
                         jnp.asarray(np.asarray(a.data)), tuple(shape))
    raise TypeError(
        f"expected CSRMatrix or an object with indptr/indices/data, "
        f"got {type(a)}")


@auto_sync_handle
@auto_convert_output
def eigsh(a, k: int = 6, which: str = "LM", v0=None, ncv=None,
          maxiter=None, tol: float = 0.0, seed=None, handle=None):
    """Find k eigenvalues/eigenvectors of the sparse symmetric matrix A
    (ref: lanczos.pyx:100 — scipy.sparse.linalg.eigsh-compatible surface;
    the POSITIONAL parameter order matches the reference exactly, so
    ported positional call sites — eigsh(A, 6, "SA") — keep working).

    Returns (eigenvalues, eigenvectors) as device arrays.

    >>> import numpy as np
    >>> from raft_tpu.compat import eigsh
    >>> from raft_tpu.sparse.convert import dense_to_csr
    >>> a = dense_to_csr(np.diag([1., 2., 3., 4., 10.]).astype(np.float32))
    >>> w, v = eigsh(a, k=2, which="SA")
    >>> np.asarray(w).round(4).tolist()
    [1.0, 2.0]
    """
    csr = _as_csr(a)
    w, v = _lanczos.eigsh(
        csr, k=k, which=which, v0=v0,
        ncv=0 if ncv is None else int(ncv),          # 0 = solver default
        maxiter=4000 if maxiter is None else int(maxiter),
        tol=tol if tol > 0 else 1e-7,
        seed=42 if seed is None else int(seed), res=handle)
    return device_ndarray(w), device_ndarray(v)
