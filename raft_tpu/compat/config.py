"""Output-format configuration (ref: python/pylibraft/pylibraft/config.py:9
`set_output_as`)."""

from __future__ import annotations

SUPPORTED_OUTPUT_TYPES = ("raft", "jax", "numpy", "torch")

output_as_ = "raft"


def set_output_as(output):
    """Set the global output format for auto-converted results.

    ``output`` is one of "raft" (device_ndarray, the default), "jax",
    "numpy", "torch", or a callable taking a device_ndarray (ref:
    config.py:9-30; "cupy" there maps to "jax" here — the native device
    array type).
    """
    global output_as_
    if output not in SUPPORTED_OUTPUT_TYPES and not callable(output):
        raise ValueError(
            f"Unsupported output option {output!r}; expected one of "
            f"{SUPPORTED_OUTPUT_TYPES} or a callable")
    output_as_ = output
