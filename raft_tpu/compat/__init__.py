"""pylibraft-shaped Python parity layer (ref: python/pylibraft/ —
SURVEY.md §2.12).

Gives a pylibraft user the same vocabulary on TPU: ``Handle`` /
``DeviceResources``, ``device_ndarray``, ``eigsh``, ``rmat``, output
auto-conversion (``set_output_as``) and the interruptible bridge — all
backed by jax.Array instead of CUDA device memory.
"""

from raft_tpu.compat.common import (  # noqa: F401
    DeviceResourcesSNMG,
    Stream,
    cai_wrapper,
    DeviceResources,
    Handle,
    ai_wrapper,
    auto_sync_handle,
    device_ndarray,
)
from raft_tpu.compat.config import set_output_as  # noqa: F401
from raft_tpu.compat.outputs import auto_convert_output  # noqa: F401
from raft_tpu.compat.interruptible import interruptible  # noqa: F401
from raft_tpu.compat.random_api import rmat  # noqa: F401
from raft_tpu.compat.sparse_api import eigsh  # noqa: F401
from raft_tpu.compat.input_validation import (  # noqa: F401
    do_cols_match, do_dtypes_match, do_rows_match, do_shapes_match,
    is_c_contiguous)
