"""Input-validation helpers with pylibraft's names (ref:
python/pylibraft/pylibraft/common/input_validation.py:13-60).

The reference reads `__cuda_array_interface__` metadata; here the same
predicates run on anything `jnp.asarray` accepts (jax arrays, numpy,
`device_ndarray`) — dtype/shape live on the array itself, and
contiguity is trivially true for jax arrays (XLA owns layout; dlpack
exports are dense row-major), checked via numpy flags when the object
exposes them.
"""

from __future__ import annotations

import numpy as np


def _as_array(a):
    if hasattr(a, "values") and hasattr(a, "_arr"):
        a = a.values                             # device_ndarray unwrap
    # read metadata WITHOUT jnp.asarray: under the default x64-off
    # config that conversion silently downcasts f64 -> f32, making
    # genuinely mismatched dtypes "match"
    if hasattr(a, "dtype") and hasattr(a, "shape"):
        return a
    return np.asarray(a)


def _dtype_of(a):
    d = _as_array(a).dtype
    try:
        return np.dtype(d)
    except TypeError:                    # torch.float32 etc.
        return np.dtype(str(d).rsplit(".", 1)[-1])


def do_dtypes_match(*arrays) -> bool:
    dtypes = {_dtype_of(a) for a in arrays}
    return len(dtypes) <= 1


def do_rows_match(*arrays) -> bool:
    rows = {_as_array(a).shape[0] for a in arrays}
    return len(rows) <= 1


def do_cols_match(*arrays) -> bool:
    cols = {_as_array(a).shape[1] for a in arrays}
    return len(cols) <= 1


def do_shapes_match(*arrays) -> bool:
    shapes = {tuple(_as_array(a).shape) for a in arrays}
    return len(shapes) <= 1


def is_c_contiguous(a) -> bool:
    """True for jax arrays (dense row-major through dlpack); strided
    hosts (numpy, torch) answer from their actual strides — the
    reference computes this from the array-interface strides too
    (common/input_validation.py:53)."""
    a = _as_array(a)
    if isinstance(a, np.ndarray):
        return a.flags["C_CONTIGUOUS"]
    stride = getattr(a, "stride", None)
    if callable(stride):                 # torch-style: strides in ELEMENTS
        strides, shape = tuple(stride()), tuple(a.shape)
        expect, acc = [], 1
        for dim in reversed(shape):
            expect.append(acc)
            acc *= dim
        return strides == tuple(reversed(expect))
    strides = getattr(a, "strides", None)
    if strides is not None:              # numpy-style: strides in BYTES
        itemsize = np.dtype(a.dtype).itemsize
        expect, acc = [], itemsize
        for dim in reversed(tuple(a.shape)):
            expect.append(acc)
            acc *= dim
        return tuple(strides) == tuple(reversed(expect))
    return True                          # jax arrays: XLA owns layout
