"""pylibraft.random parity (ref:
python/pylibraft/pylibraft/random/rmat_rectangular_generator.pyx:69 `rmat`).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from raft_tpu.compat.common import auto_sync_handle, device_ndarray
from raft_tpu.compat.outputs import auto_convert_output
from raft_tpu.random import RngState, rmat_rectangular_gen


@auto_sync_handle
@auto_convert_output
def rmat(out=None, theta=None, r_scale: int = 0, c_scale: int = 0,
         seed: int = 12345, handle=None, *, n_edges: int = 0):
    """Generate R-MAT edges (ref: rmat_rectangular_generator.pyx:69).

    pylibraft signature: ``rmat(out, theta, r_scale, c_scale, seed,
    handle)`` — the positional order matches EXACTLY so ported positional
    call sites keep working; our extension ``n_edges`` (allocate instead
    of passing a preallocated ``out``) is keyword-only for that reason.
    ``out`` is a preallocated [n_edges, 2] int array and ``theta`` a
    [max(r_scale, c_scale) * 4] probability table. The edge list is
    always returned.
    """
    if out is not None:
        n_edges = ai_shape(out)[0]
        dtype = ai_dtype(out)
    else:
        if n_edges <= 0:
            raise ValueError("pass a preallocated `out` or n_edges > 0")
        dtype = jnp.int32
    if theta is None:
        raise ValueError("theta is required")
    theta = np.asarray(theta, np.float32).reshape(-1, 4)
    max_scale = max(r_scale, c_scale)
    if theta.shape[0] < max_scale:
        raise ValueError(
            f"theta must supply {max_scale} levels, got {theta.shape[0]}")
    src, dst = rmat_rectangular_gen(
        None, RngState(seed), r_scale, c_scale, n_edges,
        theta=theta[:max_scale], dtype=dtype)
    edges = jnp.stack([src, dst], axis=1)
    result = device_ndarray(edges)
    if out is not None:
        # pylibraft's contract is an in-place fill of `out`
        # (rmat_rectangular_generator.pyx:69); honor it for every out type
        # we can write to, and refuse loudly otherwise.
        if isinstance(out, device_ndarray):
            out._arr = edges
        elif isinstance(out, np.ndarray) and out.flags.writeable:
            out[...] = np.asarray(edges, dtype=out.dtype)
        else:
            raise TypeError(
                f"cannot fill `out` of type {type(out)} in place; pass a "
                "device_ndarray or a writable numpy array")
    return result


def ai_shape(arr):
    return arr.shape


def ai_dtype(arr):
    return arr.dtype
