"""pylibraft.common parity (ref: python/pylibraft/pylibraft/common/:
handle.pyx:21-120, device_ndarray.py:10-157, ai_wrapper.py/cai_wrapper.py,
auto_sync_handle decorator).
"""

from __future__ import annotations

import functools
import inspect

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.core.resources import (DeviceResources,
                                     DeviceResourcesSNMG, Resources)

# pylibraft exposes Handle as the deprecated alias of DeviceResources
# (ref: common/handle.pyx, core/handle.hpp:23).
Handle = DeviceResources


class device_ndarray:
    """Lightweight device-array wrapper (ref: common/device_ndarray.py:10).

    Where pylibraft wraps an ``__cuda_array_interface__`` over RMM memory,
    the TPU analog wraps a ``jax.Array`` and interoperates through
    ``__array__`` (NumPy), ``__dlpack__`` (torch & friends) and the
    ``.values`` attribute (raw jax.Array).
    """

    def __init__(self, array_like):
        if isinstance(array_like, device_ndarray):
            self._arr = array_like._arr
        else:
            self._arr = jnp.asarray(array_like)

    @classmethod
    def empty(cls, shape, dtype=np.float32, order="C"):
        """Device allocation without meaningful contents
        (ref: device_ndarray.empty)."""
        if order not in ("C", None):
            raise ValueError("TPU arrays are row-major; order must be 'C'")
        return cls(jnp.zeros(shape, dtype))

    @property
    def values(self) -> jax.Array:
        return self._arr

    @property
    def shape(self):
        return self._arr.shape

    @property
    def dtype(self):
        return np.dtype(self._arr.dtype)

    @property
    def c_contiguous(self) -> bool:
        return True

    @property
    def f_contiguous(self) -> bool:
        return self._arr.ndim <= 1

    def copy_to_host(self) -> np.ndarray:
        """Device -> host copy (ref: device_ndarray.copy_to_host)."""
        return np.asarray(self._arr)

    def __array__(self, dtype=None, copy=None):
        host = np.asarray(self._arr)
        return host.astype(dtype) if dtype is not None else host

    def __dlpack__(self, **kwargs):
        return self._arr.__dlpack__(**kwargs)

    def __dlpack_device__(self):
        return self._arr.__dlpack_device__()

    def __len__(self):
        return len(self._arr)

    def __getitem__(self, item):
        return device_ndarray(self._arr[item])

    def __repr__(self):
        return f"device_ndarray({self._arr!r})"


class ai_wrapper:
    """Duck-typed adapter for anything array-interface-ish (ref:
    common/ai_wrapper.py:10-32, cai_wrapper.py — the CUDA-array-interface
    duck type collapses to 'convertible to jax.Array' here)."""

    def __init__(self, ai_arr):
        if isinstance(ai_arr, device_ndarray):
            self._arr = ai_arr.values
        elif hasattr(ai_arr, "__dlpack__") or hasattr(ai_arr, "__array__") \
                or isinstance(ai_arr, (np.ndarray, jax.Array)):
            self._arr = jnp.asarray(np.asarray(ai_arr)) \
                if not isinstance(ai_arr, jax.Array) else ai_arr
        else:
            raise TypeError(
                f"cannot wrap {type(ai_arr)} as a device array")

    @property
    def dtype(self):
        return np.dtype(self._arr.dtype)

    @property
    def shape(self):
        return self._arr.shape

    @property
    def c_contiguous(self) -> bool:
        return True

    @property
    def values(self) -> jax.Array:
        return self._arr


def auto_sync_handle(f):
    """Decorator injecting a default handle and syncing it on return
    (ref: common/__init__.py `auto_sync_handle`, which creates a Handle if
    the kwarg is absent and calls handle.sync() after).

    The wrapped function must accept a ``handle=`` keyword argument.
    """
    sig = inspect.signature(f)
    if "handle" not in sig.parameters:
        raise TypeError(f"{f.__name__} has no 'handle' parameter")

    @functools.wraps(f)
    def wrapper(*args, **kwargs):
        bound = sig.bind_partial(*args, **kwargs)
        handle = bound.arguments.get("handle")
        if handle is None:
            # inject through the BOUND arguments: ``handle`` may have
            # been passed positionally as None (pylibraft's positional
            # call shape, e.g. rmat(out, theta, rs, cs, seed, None)) —
            # adding a handle kwarg on top would collide with it
            bound.arguments["handle"] = handle = DeviceResources()
            args = bound.args
            kwargs = bound.kwargs
        ret = f(*args, **kwargs)
        # module-level sync works for any Resources, including the plain
        # per-rank handles built by the comms bootstrap
        from raft_tpu.core import resources as core_res
        core_res.sync(handle)
        return ret

    return wrapper


# pylibraft.common exposes cai_wrapper alongside ai_wrapper; on TPU there
# is no CUDA array interface to view zero-copy, so both duck types
# collapse to the same "convertible to jax.Array" adapter (a CAI-bearing
# object without __array__/__dlpack__ raises the same TypeError the
# reference raises for non-CAI inputs).
cai_wrapper = ai_wrapper


class Stream:
    """API-parity stand-in for pylibraft.common.Stream (cuda.pyx).

    XLA owns ordering/streams on TPU; constructing one is free and
    ``sync()`` drains dispatched work (the analogue of
    cudaStreamSynchronize for code ported from the handle+stream idiom).
    """

    def __init__(self, handle=None):
        del handle

    def sync(self) -> None:
        jax.effects_barrier()

    def __repr__(self):
        return "Stream(<xla-managed>)"
