"""SIGINT -> cooperative-cancel bridge (ref:
python/pylibraft/pylibraft/common/interruptible.pyx:21-76
`cuda_interruptible` and the SIGINT handler installation).
"""

from __future__ import annotations

import contextlib
import signal
import threading

from raft_tpu.core import interruptible as core_interruptible


@contextlib.contextmanager
def interruptible():
    """Within the context, SIGINT cancels the current thread's token
    (checked by long-running host-driven loops via
    `core.interruptible.yield_now`) and then re-raises KeyboardInterrupt.
    Mirrors `cuda_interruptible`'s promise: Ctrl+C aborts the computation
    promptly without corrupting state."""
    if threading.current_thread() is not threading.main_thread():
        # Signal handlers are main-thread only; degrade to plain execution
        # exactly like the reference does outside the main thread.
        yield
        return

    token = core_interruptible.get_token()
    prev = signal.getsignal(signal.SIGINT)

    def handler(signum, frame):
        token.cancel()
        if callable(prev):
            prev(signum, frame)

    signal.signal(signal.SIGINT, handler)
    try:
        yield
    finally:
        signal.signal(signal.SIGINT, prev)
        # If the SIGINT arrived while no cancellation checkpoint was
        # reached, the token would stay set and poison the thread's next
        # long-running call — consume any leftover flag on exit.
        with contextlib.suppress(core_interruptible.InterruptedException):
            token.check()


# pylibraft exposes the name cuda_interruptible; keep an alias with the
# platform-neutral spelling primary.
cuda_interruptible = interruptible
