"""Label utilities (ref: cpp/include/raft/label/ — SURVEY.md §2.10)."""

from raft_tpu.label.classlabels import (  # noqa: F401
    get_unique_labels,
    get_ovr_labels,
    make_monotonic,
)
from raft_tpu.label.merge_labels import MAX_LABEL, merge_labels  # noqa: F401
