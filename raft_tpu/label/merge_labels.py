"""Union-find label merging (ref: raft/label/merge_labels.cuh:47,
detail/merge_labels.cuh — the kernel used by MST and connected components).

Two labelings A and B over points 0..N-1 are merged: where ``mask`` is true,
label a_i and b_i are equivalent and both groups get the smaller label.

The reference flattens a union-find forest with three kernels iterated until
a device flag settles. The TPU design expresses one flattening round as pure
scatter-min + gather (jit-able, fixed shapes) and iterates on the host until
the fixed point — the iteration count is O(log N) because path-halving
doubles the flattened depth each round.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

# Sentinel for unlabelled points (ref: MAX_LABEL in detail/merge_labels.cuh).
MAX_LABEL = np.iinfo(np.int32).max


@jax.jit
def _merge_round(r, labels_a, labels_b, mask):
    """One equivalence-propagation round over the label map ``r``
    (size N+1: label value -> representative; labels are 1-based)."""
    a = labels_a
    b = labels_b
    ra = r[a]
    rb = r[b]
    lo = jnp.minimum(ra, rb)
    # where mask: representative of both a- and b-labels becomes min
    safe_a = jnp.where(mask, a, 0)
    safe_b = jnp.where(mask, b, 0)
    upd = jnp.where(mask, lo, MAX_LABEL)
    r = r.at[safe_a].min(upd)
    r = r.at[safe_b].min(upd)
    # path halving: r = r[r]
    r = r.at[1:].set(jnp.minimum(r[1:], r[r[1:]]))
    return r


def merge_labels(labels_a, labels_b, mask):
    """Merged labels (new array; the reference updates labels_a in place).

    Labels take values 1..N; MAX_LABEL marks unlabelled points, which must
    have mask False (ref contract, merge_labels.cuh:17-45).
    """
    a = jnp.asarray(labels_a).astype(jnp.int32)
    b = jnp.asarray(labels_b).astype(jnp.int32)
    mask = jnp.asarray(mask)
    n = a.shape[0]

    # r[v] = current representative of label value v (identity to start).
    # Index 0 is a scratch slot for masked-off scatter targets.
    r = jnp.arange(n + 1, dtype=jnp.int32)

    prev = None
    # O(log N) rounds suffice (path halving); cap defensively.
    for _ in range(max(2, int(np.ceil(np.log2(n + 1))) + 2)):
        r = _merge_round(r, a, b, mask)
        cur = np.asarray(r)
        if prev is not None and np.array_equal(cur, prev):
            break
        prev = cur

    out = jnp.where(a == MAX_LABEL, MAX_LABEL, r[jnp.where(
        a == MAX_LABEL, 0, a)])
    return out.astype(jnp.asarray(labels_a).dtype)
