"""Union-find label merging (ref: raft/label/merge_labels.cuh:47,
detail/merge_labels.cuh — the kernel used by MST and connected components).

Two labelings A and B over points 0..N-1 are merged: where ``mask`` is true,
label a_i and b_i are equivalent and both groups get the smaller label.

The reference flattens a union-find forest with three kernels iterated until
a device flag settles. The TPU design runs the same fixed point entirely on
device: each round is scatter-min equivalence propagation + path halving,
iterated inside a `lax.while_loop` whose change-flag lives on device — zero
host round-trips (the reference polls its flag from the host each round;
over the TPU tunnel one poll costs ~70 ms, so device-resident control flow
is the difference between O(1) and O(log N) RTTs per merge).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

# Sentinel for unlabelled points (ref: MAX_LABEL in detail/merge_labels.cuh).
MAX_LABEL = np.iinfo(np.int32).max


@functools.partial(jax.jit, static_argnames=("max_rounds",))
def _merge_fixpoint(labels_a, labels_b, mask, max_rounds: int):
    """Representative map r (size N+1; labels are 1-based, slot 0 scratch)
    after full equivalence propagation, computed in one device program."""
    n = labels_a.shape[0]
    safe_a = jnp.where(mask, labels_a, 0)
    safe_b = jnp.where(mask, labels_b, 0)
    r0 = jnp.arange(n + 1, dtype=jnp.int32)

    def round_(r):
        ra = r[safe_a]
        rb = r[safe_b]
        lo = jnp.minimum(ra, rb)
        upd = jnp.where(mask, lo, MAX_LABEL)
        r = r.at[safe_a].min(upd)
        r = r.at[safe_b].min(upd)
        # path halving: r = r[r]
        return r.at[1:].set(jnp.minimum(r[1:], r[r[1:]]))

    def cond(state):
        i, r, changed = state
        return changed & (i < jnp.int32(max_rounds))

    def body(state):
        i, r, _ = state
        nr = round_(r)
        return i + 1, nr, jnp.any(nr != r)

    _, r, _ = lax.while_loop(cond, body,
                             (jnp.int32(0), round_(r0), jnp.bool_(True)))
    return r


def merge_labels(labels_a, labels_b, mask):
    """Merged labels (new array; the reference updates labels_a in place).

    Labels take values 1..N; MAX_LABEL marks unlabelled points, which must
    have mask False (ref contract, merge_labels.cuh:17-45).
    """
    a = jnp.asarray(labels_a).astype(jnp.int32)
    b = jnp.asarray(labels_b).astype(jnp.int32)
    mask = jnp.asarray(mask)
    n = a.shape[0]

    # The `changed` flag exits in O(log N) rounds on ordinary inputs;
    # the cap must be DIAMETER-safe (n+2), not logarithmic — adversarial
    # equivalence chains propagate the min one hop per round.
    max_rounds = n + 2
    r = _merge_fixpoint(a, b, mask, max_rounds)

    out = jnp.where(a == MAX_LABEL, MAX_LABEL,
                    r[jnp.where(a == MAX_LABEL, 0, a)])
    return out.astype(jnp.asarray(labels_a).dtype)
