"""Class-label utilities (ref: raft/label/classlabels.cuh,
detail/classlabels.cuh).

The reference sorts + uniques on device (thrust) and maps via a linear-scan
kernel; here unique extraction is a host-side sort (label cardinality is
tiny) and the mapping is a device ``searchsorted`` — one vectorized binary
search instead of an O(n_classes) scan per element.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax.numpy as jnp
import numpy as np


def get_unique_labels(labels):
    """Sorted unique labels (ref: classlabels.cuh `getUniquelabels`)."""
    return jnp.asarray(np.unique(np.asarray(labels)))


def get_ovr_labels(labels, unique_labels, idx: int):
    """One-vs-rest relabeling: +1 where label == unique_labels[idx], else -1
    (ref: classlabels.cuh:55 `getOvrlabels`,
    detail/classlabels.cuh:96-106)."""
    n_classes = unique_labels.shape[0]
    if idx >= n_classes:
        raise ValueError(
            f"idx ({idx}) must be < number of classes ({n_classes})")
    labels = jnp.asarray(labels)
    return jnp.where(labels == unique_labels[idx], 1, -1).astype(labels.dtype)


def make_monotonic(labels, filter_op: Optional[Callable] = None,
                   zero_based: bool = False):
    """Map labels onto a monotonically increasing set (ref:
    classlabels.cuh:81 `make_monotonic`, detail/classlabels.cuh:114-168).

    Values for which ``filter_op`` returns True are passed through unchanged
    (the reference kernel leaves them untouched). Labels start at 1 unless
    ``zero_based``.

    >>> import numpy as np
    >>> from raft_tpu.label import make_monotonic
    >>> np.asarray(make_monotonic(np.array([10, 30, 10, 50]),
    ...                           zero_based=True)).tolist()
    [0, 1, 0, 2]
    """
    labels = jnp.asarray(labels)
    uniq = get_unique_labels(labels)
    ranks = jnp.searchsorted(uniq, labels) + (0 if zero_based else 1)
    ranks = ranks.astype(labels.dtype)
    if filter_op is not None:
        keep = filter_op(labels)
        return jnp.where(keep, labels, ranks)
    return ranks
