"""Combinatorial solvers (ref: cpp/include/raft/solver/ — SURVEY.md §2.10)."""

from raft_tpu.solver.linear_assignment import (  # noqa: F401
    LinearAssignmentProblem,
    solve_linear_assignment,
)
