"""Batched linear assignment problem (ref: raft/solver/linear_assignment.cuh:60
`LinearAssignmentProblem`, solver/detail/lap_{functions,kernels}.cuh).

TPU-first design: the reference ports the Date–Nagi GPU Hungarian algorithm —
a host-driven step state machine (`while (step != 100)`,
linear_assignment.cuh:136) over zero-cover kernels. That control flow is
hostile to XLA (data-dependent branching between six kernel families), so
this implementation uses the *auction algorithm* (Bertsekas) with
epsilon-scaling instead: each bidding round is

    values  = benefit - prices            (one [n, n] broadcast)
    top-2   = lax.top_k(values, 2)        (row reduction)
    winners = per-object scatter-max      (one scatter)

— all fixed-shape vector work inside a single `lax.while_loop`, `vmap`-ed
over the batch dimension. Both algorithms are O(n^3)-ish on dense costs; the
auction's rounds are embarrassingly parallel, which is what the MXU/VPU
want. Prices play the role of the Hungarian dual variables, so primal and
dual objectives are available exactly as in the reference
(`getPrimalObjectiveValue` / `getDualObjectiveValue`).

The solution is optimal to within n*eps of the true minimum; for integer
costs (or integral float costs) with final eps < 1/n it is exactly optimal
(standard auction-algorithm guarantee).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np


@functools.partial(jax.jit, static_argnums=(2,))
def _auction_phase(benefit, prices, n: int, eps, max_rounds):
    """Run one epsilon-phase to completion: all persons assigned.

    benefit: [n, n] person x object payoff (maximization).
    Returns (prices, obj_of_person, person_of_obj, rounds_used).
    """
    neg_inf = jnp.asarray(-jnp.inf, benefit.dtype)
    person_ids = jnp.arange(n, dtype=jnp.int32)
    obj_ids = jnp.arange(n, dtype=jnp.int32)

    def cond(state):
        _, obj_of, _, it = state
        return jnp.any(obj_of < 0) & (it < max_rounds)

    def body(state):
        prices, obj_of, person_of, it = state
        values = benefit - prices[None, :]               # [n, n]
        top2, top2i = jax.lax.top_k(values, 2)
        best_obj = top2i[:, 0].astype(jnp.int32)
        # bid price: current price + (v1 - v2) + eps
        bid = prices[best_obj] + (top2[:, 0] - top2[:, 1]) + eps

        unassigned = obj_of < 0
        bid = jnp.where(unassigned, bid, neg_inf)
        # per-object highest bid (persons not bidding scatter -inf)
        best_bid = jnp.full((n,), neg_inf, benefit.dtype).at[best_obj].max(
            bid)
        # winner = lowest-index unassigned person whose bid equals the max
        is_cand = unassigned & (bid == best_bid[best_obj])
        winner = jnp.full((n,), n, jnp.int32).at[best_obj].min(
            jnp.where(is_cand, person_ids, n))
        has_winner = winner < n

        # objects changing hands: unassign previous owner
        old_owner = person_of
        evicted = has_winner & (old_owner >= 0)
        obj_of = obj_of.at[jnp.where(evicted, old_owner, n)].set(
            -1, mode="drop")
        # assign winners
        obj_of = obj_of.at[jnp.where(has_winner, winner, n)].set(
            jnp.where(has_winner, obj_ids, -1), mode="drop")
        person_of = jnp.where(has_winner, winner, person_of)
        prices = jnp.where(has_winner, best_bid, prices)
        return prices, obj_of, person_of, it + 1

    init = (prices,
            jnp.full((n,), -1, jnp.int32),
            jnp.full((n,), -1, jnp.int32),
            jnp.asarray(0, jnp.int32))
    return jax.lax.while_loop(cond, body, init)


def _solve_one(cost, eps_final: float, scaling_factor: float = 5.0):
    """Auction with epsilon scaling on one [n, n] cost matrix.

    Costs are normalised to unit spread before bidding (the auction is
    invariant to positive scaling) so price increments never fall below the
    dtype's ulp — without this, large-magnitude float32 costs with a tiny
    epsilon stall the bidding and the phase exits unconverged. The scaled
    epsilon is clamped to a few ulps for the same reason; for integer costs
    this keeps exactness as long as epsilon < spread / n.
    """
    n = cost.shape[0]
    if n == 1:
        zero = jnp.zeros((1,), jnp.int32)
        return zero, zero, jnp.zeros((1,), cost.dtype)
    spread = float(jnp.max(cost) - jnp.min(cost))
    if spread == 0.0:
        ident = jnp.arange(n, dtype=jnp.int32)
        return ident, ident, jnp.zeros((n,), cost.dtype)
    benefit = -cost / spread                      # spread now exactly 1
    ulp = float(jnp.finfo(cost.dtype).eps)
    eps_last = max(eps_final / spread, 8.0 * ulp)
    max_rounds = jnp.asarray(50 * n * max(1, int(np.log2(n + 1))), jnp.int32)

    eps = max(0.5, eps_last)
    prices = jnp.zeros((n,), cost.dtype)
    while True:
        prices, obj_of, person_of, _ = _auction_phase(
            benefit, prices, n, jnp.asarray(eps, cost.dtype), max_rounds)
        if eps <= eps_last:
            break
        eps = max(eps / scaling_factor, eps_last)
    if bool(jnp.any(obj_of < 0)):
        raise RuntimeError(
            "auction LAP did not converge (persons left unassigned after "
            f"the final epsilon phase, eps={eps_last * spread:g}); "
            "increase epsilon or check the cost matrix for NaN/inf")
    return obj_of, person_of, prices * spread


class LinearAssignmentProblem:
    """Batched LAP solver (API parity: solver/linear_assignment.cuh:60).

    solve() takes cost matrices [batchsize, size, size] (or [size, size])
    and computes row assignments (person -> object), column assignments
    (object -> person) and primal/dual objective values.
    """

    def __init__(self, res, size: int, batchsize: int = 1,
                 epsilon: float = 1e-6):
        self._res = res
        self._size = size
        self._batch = batchsize
        self._eps = float(epsilon)
        self._row_assign = None
        self._col_assign = None
        self._row_duals = None
        self._col_duals = None
        self._costs = None

    def solve(self, cost_matrix):
        cost = jnp.asarray(cost_matrix)
        if cost.ndim == 2:
            cost = cost[None, :, :]
        if cost.shape != (self._batch, self._size, self._size):
            raise ValueError(
                f"expected cost shape {(self._batch, self._size, self._size)}"
                f", got {cost.shape}")
        obj_of = []
        person_of = []
        prices = []
        for b in range(self._batch):
            o, p, pr = _solve_one(cost[b], self._eps)
            obj_of.append(o)
            person_of.append(p)
            prices.append(pr)
        self._row_assign = jnp.stack(obj_of)
        self._col_assign = jnp.stack(person_of)
        self._col_duals = jnp.stack(prices)
        # row duals: slack left to each person at final prices
        self._row_duals = jnp.max(-cost - self._col_duals[:, None, :],
                                  axis=2)
        self._costs = cost
        return self._row_assign, self._col_assign

    @property
    def row_assignments(self):
        return self._row_assign

    @property
    def col_assignments(self):
        return self._col_assign

    def get_primal_objective_value(self, batch_id: int = 0):
        """Sum of costs along the assignment
        (ref: getPrimalObjectiveValue)."""
        c = self._costs[batch_id]
        rows = jnp.arange(self._size)
        return jnp.sum(c[rows, self._row_assign[batch_id]])

    def get_dual_objective_value(self, batch_id: int = 0):
        """Dual objective sum(row duals) + sum(col duals), negated back to
        minimization scale (ref: getDualObjectiveValue). Within n*eps of
        the primal at optimality."""
        return -(jnp.sum(self._row_duals[batch_id])
                 + jnp.sum(self._col_duals[batch_id]))


def solve_linear_assignment(res, cost_matrix, epsilon: float = 1e-6):
    """Functional one-shot front-end: returns (row_assignment, total_cost).

    >>> import numpy as np
    >>> from raft_tpu.solver import solve_linear_assignment
    >>> cost = np.array([[4., 1., 3.], [2., 0., 5.], [3., 2., 2.]])
    >>> rows, total = solve_linear_assignment(None, cost)
    >>> np.asarray(rows).tolist(), float(total)
    ([1, 0, 2], 5.0)
    """
    cost = jnp.asarray(cost_matrix)
    squeeze = cost.ndim == 2
    if squeeze:
        cost = cost[None]
    lap = LinearAssignmentProblem(res, cost.shape[1], cost.shape[0],
                                  epsilon)
    rows, _ = lap.solve(cost)
    totals = jnp.stack([lap.get_primal_objective_value(b)
                        for b in range(cost.shape[0])])
    if squeeze:
        return rows[0], totals[0]
    return rows, totals
