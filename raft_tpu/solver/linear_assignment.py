"""Batched linear assignment problem (ref: raft/solver/linear_assignment.cuh:60
`LinearAssignmentProblem`, solver/detail/lap_{functions,kernels}.cuh).

TPU-first design: the reference ports the Date–Nagi GPU Hungarian algorithm —
a host-driven step state machine (`while (step != 100)`,
linear_assignment.cuh:136) over zero-cover kernels. That control flow is
hostile to XLA (data-dependent branching between six kernel families), so
this implementation uses the *auction algorithm* (Bertsekas) with
epsilon-scaling instead: each bidding round is

    values  = benefit - prices            (one [n, n] broadcast)
    top-2   = lax.top_k(values, 2)        (row reduction)
    winners = per-object scatter-max      (one scatter)

— all fixed-shape vector work inside a single `lax.while_loop`, `vmap`-ed
over the batch dimension. The whole batched solve — every epsilon phase
included — is ONE compiled device program (the reference likewise keeps its
batch inside one state machine, linear_assignment.cuh:125): the epsilon
schedule is *static* (eps_k = 0.5 / 5^k, clamped per batch lane to that
lane's final epsilon), phases ride a `lax.scan`, and a lane whose clamp was
reached skips the phase via a conditional assignment reset — so the only
host synchronisation is the single convergence check after the program
returns. Prices play the role of the Hungarian dual variables, so primal and
dual objectives are available exactly as in the reference
(`getPrimalObjectiveValue` / `getDualObjectiveValue`).

Accuracy contract (standard auction-algorithm guarantee): the returned
assignment's total cost is within n*eps_final of the true minimum. For
integral costs (including integer-valued floats) with eps_final < 1/n the
result is *exactly* optimal. For arbitrary float costs choose
eps_final < (suboptimality gap)/n for exactness; eps_final below
~8 ulp of the cost spread is clamped (bids must move prices).
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
import numpy as np


def _phase(benefit, prices, obj_of, person_of, eps, max_rounds):
    """Run one epsilon-phase to completion: all persons assigned.

    Unbatched: benefit [n, n] person x object payoff (maximization),
    prices/obj_of/person_of [n], eps scalar. vmap adds the batch axis; a
    lane that enters fully assigned performs no-op rounds (no unassigned
    person -> every bid is -inf -> no winner -> state fixpoint), so mixed
    convergence across vmapped lanes is safe.
    """
    n = benefit.shape[0]
    neg_inf = jnp.asarray(-jnp.inf, benefit.dtype)
    person_ids = jnp.arange(n, dtype=jnp.int32)
    obj_ids = jnp.arange(n, dtype=jnp.int32)

    def cond(state):
        _, obj_of, _, it = state
        return jnp.any(obj_of < 0) & (it < max_rounds)

    def body(state):
        prices, obj_of, person_of, it = state
        values = benefit - prices[None, :]               # [n, n]
        top2, top2i = jax.lax.top_k(values, 2)
        best_obj = top2i[:, 0].astype(jnp.int32)
        # bid price: current price + (v1 - v2) + eps
        bid = prices[best_obj] + (top2[:, 0] - top2[:, 1]) + eps

        unassigned = obj_of < 0
        bid = jnp.where(unassigned, bid, neg_inf)
        # per-object highest bid (persons not bidding scatter -inf)
        best_bid = jnp.full((n,), neg_inf, benefit.dtype).at[best_obj].max(
            bid)
        # winner = lowest-index unassigned person whose bid equals the max
        is_cand = unassigned & (bid == best_bid[best_obj])
        winner = jnp.full((n,), n, jnp.int32).at[best_obj].min(
            jnp.where(is_cand, person_ids, n))
        has_winner = winner < n

        # objects changing hands: unassign previous owner
        old_owner = person_of
        evicted = has_winner & (old_owner >= 0)
        obj_of = obj_of.at[jnp.where(evicted, old_owner, n)].set(
            -1, mode="drop")
        # assign winners
        obj_of = obj_of.at[jnp.where(has_winner, winner, n)].set(
            jnp.where(has_winner, obj_ids, -1), mode="drop")
        person_of = jnp.where(has_winner, winner, person_of)
        prices = jnp.where(has_winner, best_bid, prices)
        return prices, obj_of, person_of, it + 1

    init = (prices, obj_of, person_of, jnp.asarray(0, jnp.int32))
    out = jax.lax.while_loop(cond, body, init)
    return out[0], out[1], out[2]


def _num_phases(dtype) -> int:
    """Phases so the static schedule 0.5/5^k reaches the ulp clamp floor."""
    floor = 8.0 * float(jnp.finfo(dtype).eps)
    return max(1, math.ceil(math.log(0.5 / floor) / math.log(5.0))) + 1


@functools.partial(jax.jit, static_argnums=(2,))
def _solve_batch(cost, eps_final, n_phases: int):
    """All epsilon phases for the whole [B, n, n] batch, one device program.

    Per-lane unit-spread normalisation (the auction is invariant to positive
    scaling) keeps price increments above the dtype ulp; per-lane final
    epsilon is max(eps_final/spread, 8 ulp). Returns
    (obj_of [B,n], person_of [B,n], prices [B,n] back on the cost scale).
    obj_of entries of -1 signal non-convergence (caller checks once).
    """
    bsz, n, _ = cost.shape
    dt = cost.dtype
    cmax = jnp.max(cost, axis=(1, 2))
    cmin = jnp.min(cost, axis=(1, 2))
    spread = cmax - cmin                               # [B]
    degenerate = spread == 0                           # constant cost lane
    bad = ~jnp.isfinite(spread)                        # NaN/inf cost lane
    safe = jnp.where(degenerate | bad, jnp.ones((), dt), spread)
    # bad lanes bid on a neutral benefit (cheap, converges) and are forced
    # back to -1 below so the caller's convergence check raises for them —
    # an all-NaN benefit would instead spin every phase to max_rounds
    benefit = jnp.where(bad[:, None, None], jnp.zeros((), dt),
                        -cost / safe[:, None, None])   # spread now exactly 1
    ulp = jnp.asarray(8.0 * float(jnp.finfo(dt).eps), dt)
    eps_last = jnp.maximum(eps_final.astype(dt) / safe, ulp)   # [B]
    max_rounds = jnp.asarray(50 * n * max(1, int(np.log2(n + 1))), jnp.int32)

    schedule = (0.5 / 5.0 ** np.arange(n_phases)).astype(np.float32)
    vphase = jax.vmap(_phase, in_axes=(0, 0, 0, 0, 0, None))

    def step(carry, eps_k):
        prices, obj_of, person_of, eps_prev = carry
        eps_b = jnp.maximum(jnp.asarray(eps_k, dt), eps_last)  # [B]
        # a lane whose epsilon actually decreased restarts its assignment
        # (standard scaling keeps only prices); a clamped lane keeps its
        # converged assignment and the phase is a no-op for it
        fresh = eps_b < eps_prev
        obj_of = jnp.where(fresh[:, None], -1, obj_of)
        person_of = jnp.where(fresh[:, None], -1, person_of)
        prices, obj_of, person_of = vphase(
            benefit, prices, obj_of, person_of, eps_b, max_rounds)
        return (prices, obj_of, person_of, eps_b), None

    init = (jnp.zeros((bsz, n), dt),
            jnp.full((bsz, n), -1, jnp.int32),
            jnp.full((bsz, n), -1, jnp.int32),
            jnp.full((bsz,), jnp.inf, dt))
    (prices, obj_of, person_of, _), _ = jax.lax.scan(
        step, init, schedule)

    ident = jnp.arange(n, dtype=jnp.int32)[None, :]
    obj_of = jnp.where(degenerate[:, None], ident, obj_of)
    obj_of = jnp.where(bad[:, None], -1, obj_of)       # raises at the caller
    person_of = jnp.where(degenerate[:, None], ident, person_of)
    prices = jnp.where(degenerate[:, None], jnp.zeros((), dt),
                       prices * safe[:, None])
    return obj_of, person_of, prices


def _solve_many(cost, eps_final: float, strict: bool = True):
    """Driver: one `_solve_batch` launch + one host convergence check.

    Returns (obj_of, person_of, prices, report). The convergence failure
    is a :class:`~raft_tpu.core.guards.ConvergenceError` (a
    ``RuntimeError`` subclass, so pre-taxonomy callers keep working)
    carrying the uniform report; ``strict=False`` downgrades it to a
    warn, leaving the unassigned lanes at -1 for the caller to inspect.
    """
    from raft_tpu.core import logger
    from raft_tpu.core.guards import ConvergenceError, ConvergenceReport
    from raft_tpu.runtime import limits

    # one launch + one host sync: the deadline polls bracket the launch
    limits.check_deadline("solver.linear_assignment")
    n = cost.shape[1]
    n_phases = _num_phases(cost.dtype)
    if n == 1:
        zero = jnp.zeros(cost.shape[:1] + (1,), jnp.int32)
        return zero, zero, jnp.zeros_like(zero, cost.dtype), \
            ConvergenceReport(converged=True, n_iter=0, residual=0.0,
                              tol=float(eps_final))
    obj_of, person_of, prices = _solve_batch(
        cost, jnp.asarray(eps_final, cost.dtype), n_phases)
    limits.check_deadline("solver.linear_assignment")
    unassigned = jnp.any(obj_of < 0)
    report = ConvergenceReport(converged=True, n_iter=n_phases,
                               residual=0.0, tol=float(eps_final))
    if bool(unassigned):                               # the only host sync
        bad = np.nonzero(np.asarray(jnp.any(obj_of < 0, axis=1)))[0]
        report.converged = False
        report.residual = float(len(bad))   # unassigned-lane count
        report.detail = f"unconverged batch elements: {bad.tolist()}"
        msg = ("auction LAP did not converge for batch element(s) "
               f"{bad.tolist()} (persons left unassigned after the final "
               f"epsilon phase, eps_final={eps_final:g}); increase epsilon "
               "or check the cost matrix for NaN/inf")
        if strict:
            raise ConvergenceError(msg, report=report,
                                   op="solver.linear_assignment")
        logger.warn("solver.linear_assignment: %s (strict=False; "
                    "unassigned lanes returned as -1)", msg)
    return obj_of, person_of, prices, report


class LinearAssignmentProblem:
    """Batched LAP solver (API parity: solver/linear_assignment.cuh:60).

    solve() takes cost matrices [batchsize, size, size] (or [size, size])
    and computes row assignments (person -> object), column assignments
    (object -> person) and primal/dual objective values. The entire batch is
    solved by one compiled device program (mirroring the reference's
    one-state-machine batch, linear_assignment.cuh:125), not a per-element
    host loop.
    """

    def __init__(self, res, size: int, batchsize: int = 1,
                 epsilon: float = 1e-6, strict: bool = True):
        self._res = res
        self._size = size
        self._batch = batchsize
        self._eps = float(epsilon)
        self._strict = bool(strict)
        self._row_assign = None
        self._col_assign = None
        self._row_duals = None
        self._col_duals = None
        self._costs = None
        self._report = None

    def solve(self, cost_matrix):
        cost = jnp.asarray(cost_matrix)
        if cost.ndim == 2:
            cost = cost[None, :, :]
        if cost.shape != (self._batch, self._size, self._size):
            raise ValueError(
                f"expected cost shape {(self._batch, self._size, self._size)}"
                f", got {cost.shape}")
        (self._row_assign, self._col_assign, self._col_duals,
         self._report) = _solve_many(cost, self._eps, strict=self._strict)
        # row duals: slack left to each person at final prices
        self._row_duals = jnp.max(-cost - self._col_duals[:, None, :],
                                  axis=2)
        self._costs = cost
        return self._row_assign, self._col_assign

    @property
    def row_assignments(self):
        return self._row_assign

    @property
    def col_assignments(self):
        return self._col_assign

    @property
    def report(self):
        """The :class:`~raft_tpu.core.guards.ConvergenceReport` of the
        last :meth:`solve` (None before the first solve)."""
        return self._report

    def get_primal_objective_value(self, batch_id: int = 0):
        """Sum of costs along the assignment
        (ref: getPrimalObjectiveValue)."""
        c = self._costs[batch_id]
        rows = jnp.arange(self._size)
        return jnp.sum(c[rows, self._row_assign[batch_id]])

    def get_dual_objective_value(self, batch_id: int = 0):
        """Dual objective sum(row duals) + sum(col duals), negated back to
        minimization scale (ref: getDualObjectiveValue). Within n*eps of
        the primal at optimality."""
        return -(jnp.sum(self._row_duals[batch_id])
                 + jnp.sum(self._col_duals[batch_id]))


def solve_linear_assignment(res, cost_matrix, epsilon: float = 1e-6,
                            strict: bool = True,
                            return_report: bool = False):
    """Functional one-shot front-end: returns (row_assignment, total_cost).

    ``strict=False`` downgrades a convergence failure from
    :class:`~raft_tpu.core.guards.ConvergenceError` to a warn (unassigned
    rows come back as -1); ``return_report=True`` appends the
    :class:`~raft_tpu.core.guards.ConvergenceReport`.

    >>> import numpy as np
    >>> from raft_tpu.solver import solve_linear_assignment
    >>> cost = np.array([[4., 1., 3.], [2., 0., 5.], [3., 2., 2.]])
    >>> rows, total = solve_linear_assignment(None, cost)
    >>> np.asarray(rows).tolist(), float(total)
    ([1, 0, 2], 5.0)
    """
    cost = jnp.asarray(cost_matrix)
    squeeze = cost.ndim == 2
    if squeeze:
        cost = cost[None]
    lap = LinearAssignmentProblem(res, cost.shape[1], cost.shape[0],
                                  epsilon, strict=strict)
    rows, _ = lap.solve(cost)
    totals = jnp.sum(jnp.take_along_axis(cost, rows[:, :, None],
                                         axis=2)[:, :, 0], axis=1)
    if squeeze:
        rows, totals = rows[0], totals[0]
    if return_report:
        return rows, totals, lap.report
    return rows, totals
