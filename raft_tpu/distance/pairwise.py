"""Pairwise distance matrix over the metric vocabulary of the reference
lineage (cuVS `cuvs::distance::pairwise_distance`, built on the reference's
contractions layer — linalg/detail/contractions.cuh:16).

Expanded metrics (L2Expanded, CosineExpanded, CorrelationExpanded,
InnerProduct) are one GEMM plus rank-1 epilogue terms — the MXU path, via
the Pallas contraction kernel or `jnp.dot`.  Unexpanded metrics (L1,
Chebyshev, Canberra, Minkowski, ...) need |x-y| inside the reduction, which
has no GEMM form; they are expressed as broadcast reductions XLA tiles onto
the VPU, blocked over rows to bound memory.
"""

from __future__ import annotations

import enum
import functools
from typing import Optional

import jax
import jax.numpy as jnp

from raft_tpu.linalg.contractions import pairwise_l2_pallas, \
    fused_l2_argmin_pallas
from raft_tpu.util.precision import with_matmul_precision


class DistanceType(enum.Enum):
    """Metric vocabulary (lineage: raft::distance::DistanceType, retained
    by cuVS; the names keep the reference spelling)."""

    L2Expanded = "l2_expanded"              # squared L2 via GEMM expansion
    L2SqrtExpanded = "l2_sqrt_expanded"
    L2Unexpanded = "l2_unexpanded"          # squared L2, direct form
    L2SqrtUnexpanded = "l2_sqrt_unexpanded"
    L1 = "l1"
    Linf = "linf"                           # Chebyshev
    Canberra = "canberra"
    LpUnexpanded = "lp_unexpanded"          # Minkowski, needs p
    CosineExpanded = "cosine"
    CorrelationExpanded = "correlation"
    InnerProduct = "inner_product"
    HammingUnexpanded = "hamming"
    JaccardExpanded = "jaccard"
    HellingerExpanded = "hellinger"
    JensenShannon = "jensen_shannon"
    KLDivergence = "kl_divergence"
    RusselRaoExpanded = "russelrao"
    DiceExpanded = "dice"
    Haversine = "haversine"                 # [lat, lon] in radians, k==2
    BrayCurtis = "braycurtis"


_EPS = 1e-8


def _as2d(a):
    a = jnp.asarray(a)
    return a[None, :] if a.ndim == 1 else a


def _blocked_rowwise(x, y, row_fn, block: int = 4096):
    """Apply ``row_fn(x_block[bm,k], y[n,k]) -> [bm,n]`` over row blocks of x.

    Bounds the broadcastet [bm, n, k] intermediate for unexpanded metrics;
    the analogue of the reference's tiled Contractions_NT outer loop.
    """
    m = x.shape[0]
    if m <= block:
        return row_fn(x, y)
    blocks = [row_fn(x[i:i + block], y) for i in range(0, m, block)]
    return jnp.concatenate(blocks, axis=0)


def _l2_expanded(x, y, sqrt: bool):
    use_pallas = x.dtype in (jnp.float32, jnp.bfloat16) and \
        y.dtype == x.dtype
    if use_pallas:
        return pairwise_l2_pallas(x, y, sqrt=sqrt)
    xn = jnp.sum(x * x, axis=1, keepdims=True)
    yn = jnp.sum(y * y, axis=1, keepdims=True)
    d = xn - 2.0 * (x @ y.T) + yn.T
    d = jnp.maximum(d, 0.0)
    return jnp.sqrt(d) if sqrt else d


def _use_unexpanded_pallas(x, y) -> bool:
    return x.dtype in (jnp.float32, jnp.bfloat16) and y.dtype == x.dtype


def _unexpanded(x, y, metric: str, p: float = 2.0):
    """Unexpanded metric core: the Pallas VPU reduction tile when dtypes
    allow (contractions.pairwise_unexpanded_pallas — the k axis rides the
    grid, no [m,n,k] HBM intermediate), else the blocked jnp broadcast."""
    if _use_unexpanded_pallas(x, y):
        from raft_tpu.linalg.contractions import pairwise_unexpanded_pallas

        return pairwise_unexpanded_pallas(x, y, metric, p)
    from raft_tpu.linalg.contractions import unexpanded_ref

    return _blocked_rowwise(
        x, y, lambda xb, yy: unexpanded_ref(xb, yy, metric, p),
        block=1024)


def _l2_unexpanded(x, y, sqrt: bool):
    d = _unexpanded(x, y, "l2un")
    return jnp.sqrt(d) if sqrt else d


def _cosine(x, y):
    if x.dtype in (jnp.float32, jnp.bfloat16) and y.dtype == x.dtype:
        from raft_tpu.linalg.contractions import pairwise_pallas

        return pairwise_pallas(x, y, metric="cosine")
    xn = jnp.linalg.norm(x, axis=1, keepdims=True)
    yn = jnp.linalg.norm(y, axis=1, keepdims=True)
    sim = (x @ y.T) / jnp.maximum(xn * yn.T, _EPS)
    return 1.0 - sim


def _correlation(x, y):
    xc = x - jnp.mean(x, axis=1, keepdims=True)
    yc = y - jnp.mean(y, axis=1, keepdims=True)
    return _cosine(xc, yc)


def _hellinger(x, y):
    # d = sqrt(1 - Σ sqrt(x·y)); expanded: GEMM of sqrt inputs.
    s = jnp.sqrt(jnp.maximum(x, 0.0)) @ jnp.sqrt(jnp.maximum(y, 0.0)).T
    return jnp.sqrt(jnp.maximum(1.0 - s, 0.0))


def _kl(x, y):
    def f(xb, yy):
        ratio = jnp.where(xb[:, None, :] > _EPS,
                          xb[:, None, :] /
                          jnp.maximum(yy[None, :, :], _EPS), 1.0)
        term = xb[:, None, :] * jnp.log(jnp.maximum(ratio, _EPS))
        return jnp.sum(jnp.where(xb[:, None, :] > _EPS, term, 0.0), axis=-1)
    return _blocked_rowwise(x, y, f, block=1024)


def _jensen_shannon(x, y):
    def f(xb, yy):
        p = xb[:, None, :]
        q = yy[None, :, :]
        m = 0.5 * (p + q)
        def kl_term(a):
            r = jnp.where(a > _EPS, a * jnp.log(a / jnp.maximum(m, _EPS)),
                          0.0)
            return jnp.sum(r, axis=-1)
        return jnp.sqrt(jnp.maximum(0.5 * (kl_term(p) + kl_term(q)), 0.0))
    return _blocked_rowwise(x, y, f, block=1024)


def _bool_stats(x, y):
    """Pair counts for boolean metrics via GEMM on 0/1 floats."""
    xf = (x != 0).astype(jnp.float32)
    yf = (y != 0).astype(jnp.float32)
    both = xf @ yf.T                       # a: 1-1 matches
    x_only = jnp.sum(xf, axis=1, keepdims=True) - both
    y_only = jnp.sum(yf, axis=1, keepdims=True).T - both
    return both, x_only, y_only, xf.shape[1]


@with_matmul_precision
def pairwise_distance(res, x, y=None,
                      metric: DistanceType = DistanceType.L2Expanded,
                      p: float = 2.0, sqrt: Optional[bool] = None,
                      guard_mode: Optional[str] = None) -> jnp.ndarray:
    """Full m×n distance matrix between rows of x [m,k] and y [n,k].

    API parity with the reference lineage's
    ``pairwise_distance(handle, x, y, out, metric, p)``; y=None means y=x.

    >>> import numpy as np
    >>> from raft_tpu.distance import pairwise_distance, DistanceType
    >>> x = np.array([[0., 0.], [3., 4.]], np.float32)
    >>> d = pairwise_distance(None, x, metric=DistanceType.L2SqrtExpanded)
    >>> np.asarray(d).round(1).tolist()
    [[0.0, 5.0], [5.0, 0.0]]

    With ``y=None`` (self-distance) the diagonal is set to exactly zero
    for every true metric: the expanded forms compute ||x||²-2x·y+||y||²,
    whose cancellation noise on the diagonal scales with the matmul tier
    (~1e-7 rel at f32, ~1e-5 at the default bf16x3 tier) — the same
    conditioning the reference's L2Expanded kernels have in f32. Off-
    diagonal near-zero distances at exact-parity accuracy need the
    Unexpanded metrics, as in the reference.

    Numerical guardrails (ISSUE 3): under guard mode ``check``/``recover``
    a fused finite sentinel rides the output; a non-finite result with
    finite inputs raises :class:`~raft_tpu.core.guards.NonFiniteError`
    (``recover`` first re-runs one matmul tier up the precision ladder).
    Mode ``off`` (default) pays nothing and is bit-identical.

    Admission (ISSUE 5): with a ``runtime.limits`` work budget active, a
    monolithic m×n launch that would overrun it degrades to the bit-equal
    row-tiled path (each output row depends only on its x row and all of
    y, so tiling the m axis cannot change a single bit); a request whose
    operands alone overflow the budget raises
    :class:`~raft_tpu.runtime.limits.RejectedError` with the estimate.
    With no budget active this path is untouched.
    """
    from raft_tpu.core.guards import guard_output, resolve_guard_mode
    from raft_tpu.runtime import limits
    from raft_tpu.util.numerics import matmul_escalation

    x = _as2d(x)
    self_dist = y is None
    y = x if self_dist else _as2d(y)
    if x.shape[1] != y.shape[1]:
        raise ValueError(f"feature dims differ: {x.shape[1]} vs {y.shape[1]}")

    block = None
    budget = limits.active_budget()
    if budget is not None:
        op = "distance.pairwise_distance"
        itemsize = x.dtype.itemsize
        est = limits.estimate_bytes(op, m=x.shape[0], n=y.shape[0],
                                    k=x.shape[1], itemsize=itemsize)
        if not limits.admit(op, est, budget=budget):
            # degrade: the largest x-row block whose working set —
            # resident operand panels plus one [block, n] output strip —
            # fits the budget
            fixed = (x.shape[0] + y.shape[0]) * x.shape[1] * itemsize
            per_row = max(y.shape[0] * itemsize, 1)
            block = (budget.limit_bytes - fixed) // per_row
            if block >= 8:
                block -= block % 8
            if block < 1:
                limits.reject(op, est, budget=budget,
                              detail="operands alone overflow the budget "
                                     "(no row tiling can fit)")
            block = int(block)
            limits.record_degraded(op)

    def _metric(a, b):
        if block is None:
            return _dispatch_metric(a, b, metric, p, sqrt)
        return _blocked_rowwise(
            a, b, lambda ab, bb: _dispatch_metric(ab, bb, metric, p, sqrt),
            block=block)

    def compute():
        # InnerProduct is a similarity and RusselRao's self-"distance" is
        # legitimately nonzero ((k - #ones)/k) — only true metrics get the
        # exact-zero diagonal.
        if self_dist and metric not in (DistanceType.InnerProduct,
                                        DistanceType.RusselRaoExpanded):
            d = _metric(x, x)
            eye = jnp.eye(d.shape[0], dtype=bool)
            return jnp.where(eye, jnp.zeros((), d.dtype), d)
        return _metric(x, y)

    out = compute()
    if resolve_guard_mode(guard_mode) == "off":
        return out
    return guard_output("distance.pairwise_distance", out, inputs=(x, y),
                        recover=matmul_escalation(
                            compute, op="distance.pairwise_distance"),
                        mode=guard_mode)


def _dispatch_metric(x, y, metric: DistanceType, p: float,
                     sqrt: Optional[bool]) -> jnp.ndarray:
    """The metric dispatch table, applied exactly once per public call
    (the self-distance path reuses it without re-entering the guard)."""
    m = metric
    if m == DistanceType.L2Expanded:
        return _l2_expanded(x, y, sqrt=bool(sqrt))
    if m == DistanceType.L2SqrtExpanded:
        return _l2_expanded(x, y, sqrt=True)
    if m == DistanceType.L2Unexpanded:
        return _l2_unexpanded(x, y, sqrt=bool(sqrt))
    if m == DistanceType.L2SqrtUnexpanded:
        return _l2_unexpanded(x, y, sqrt=True)
    if m == DistanceType.L1:
        return _unexpanded(x, y, "l1")
    if m == DistanceType.Linf:
        return _unexpanded(x, y, "linf")
    if m == DistanceType.Canberra:
        return _unexpanded(x, y, "canberra")
    if m == DistanceType.LpUnexpanded:
        return _unexpanded(x, y, "lp", p) ** (1.0 / p)
    if m == DistanceType.CosineExpanded:
        return _cosine(x, y)
    if m == DistanceType.CorrelationExpanded:
        return _correlation(x, y)
    if m == DistanceType.InnerProduct:
        # a bare GEMM: XLA's dot IS the kernel; the 'inner' epilogue only
        # pays off fused with argmin (fused_argmin_pallas)
        return x @ y.T
    if m == DistanceType.HammingUnexpanded:
        if _use_unexpanded_pallas(x, y):
            return _unexpanded(x, y, "hamming") / x.shape[1]
        return _blocked_rowwise(
            x, y, lambda xb, yy: jnp.mean(
                (xb[:, None, :] != yy[None, :, :]).astype(jnp.float32),
                axis=-1))
    if m == DistanceType.JaccardExpanded:
        both, x_only, y_only, _ = _bool_stats(x, y)
        union = both + x_only + y_only
        return 1.0 - jnp.where(union > 0, both / jnp.maximum(union, _EPS),
                               1.0)
    if m == DistanceType.HellingerExpanded:
        return _hellinger(x, y)
    if m == DistanceType.JensenShannon:
        return _jensen_shannon(x, y)
    if m == DistanceType.KLDivergence:
        return _kl(x, y)
    if m == DistanceType.RusselRaoExpanded:
        both, _, _, k = _bool_stats(x, y)
        return (k - both) / k
    if m == DistanceType.DiceExpanded:
        both, x_only, y_only, _ = _bool_stats(x, y)
        denom = 2 * both + x_only + y_only
        return 1.0 - jnp.where(denom > 0,
                               2 * both / jnp.maximum(denom, _EPS), 1.0)
    if m == DistanceType.Haversine:
        if x.shape[1] != 2:
            raise ValueError("haversine needs [lat, lon] pairs (k == 2)")
        lat1, lon1 = x[:, None, 0], x[:, None, 1]
        lat2, lon2 = y[None, :, 0], y[None, :, 1]
        a = (jnp.sin((lat2 - lat1) / 2) ** 2
             + jnp.cos(lat1) * jnp.cos(lat2)
             * jnp.sin((lon2 - lon1) / 2) ** 2)
        return 2.0 * jnp.arcsin(jnp.sqrt(jnp.clip(a, 0.0, 1.0)))
    if m == DistanceType.BrayCurtis:
        def braycurtis(xb, yy):
            num = jnp.sum(jnp.abs(xb[:, None, :] - yy[None, :, :]), axis=-1)
            den = jnp.sum(jnp.abs(xb[:, None, :] + yy[None, :, :]), axis=-1)
            return jnp.where(den > 0, num / jnp.maximum(den, _EPS), 0.0)
        return _blocked_rowwise(x, y, braycurtis, block=1024)
    raise ValueError(f"unsupported metric {metric}")


@with_matmul_precision
def fused_l2_nn_argmin(res, x, y, sqrt: bool = False):
    """Nearest-neighbor (1-NN) under L2 without materializing distances —
    the fusedL2NN of the reference lineage, on the Pallas contraction
    kernel.  Returns (min_dist [m], argmin [m])."""
    x = _as2d(x)
    y = _as2d(y)
    if x.dtype in (jnp.float32, jnp.bfloat16) and y.dtype == x.dtype:
        val, idx = fused_l2_argmin_pallas(x, y)
    else:
        d = _l2_expanded(x, y, sqrt=False)
        from raft_tpu.matrix.epilogue import argmin_ref

        val, idx = argmin_ref(d)
    return (jnp.sqrt(val) if sqrt else val), idx
