"""Pairwise distances, rebuilt from the contraction primitive layer.

The reference migrated its distance algorithms to cuVS (README.md:99-135)
but retained the `contractions` tiling engine they were built on
(linalg/detail/contractions.cuh:16); the BASELINE north star requires
pairwise distance rebuilt from those primitives, exactly as cuVS builds
them.  The TPU contraction engine is `raft_tpu.linalg.contractions`
(Pallas MXU tiles); expanded-form metrics ride it, the rest are XLA
formulations the compiler fuses.
"""

from raft_tpu.distance.pairwise import (  # noqa: F401
    DistanceType,
    pairwise_distance,
    fused_l2_nn_argmin,
)
