"""Borůvka minimum spanning tree / forest (ref: raft/sparse/solver/mst.cuh,
mst_solver.cuh:32 `MST_solver`, detail/mst_solver_inl.cuh:127-131 iteration
loop, detail/mst_kernels.cuh kernels).

TPU formulation: the per-iteration hot work — "cheapest outgoing edge per
supervertex" over all E edges — is a pair of jitted ``segment_min`` passes
(value pass then tie-break-by-edge-id pass, replacing the reference's
atomicMin on an alteration-uniquified weight, detail/mst_solver_inl.cuh:235).
Supervertex merging (`merge_labels`) runs on host union-find between device
steps; the loop count is ≤ log2(V) as in Borůvka.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.core.sparse_types import CSRMatrix


@dataclasses.dataclass
class GraphCOO:
    """ref: mst_solver.cuh:19 `Graph_COO` {src, dst, weights, n_edges}."""
    src: jnp.ndarray
    dst: jnp.ndarray
    weights: jnp.ndarray
    n_edges: int


@functools.partial(jax.jit, static_argnames=("n",))
def _min_edge_per_color(colors, src, dst, weights, n: int):
    """For every color c: the (weight, edge-id) minimal cross edge leaving c.
    Two segment_min passes give a deterministic unique choice."""
    cu = colors[src]
    cv = colors[dst]
    cross = cu != cv
    big = jnp.asarray(jnp.inf, weights.dtype)
    w = jnp.where(cross, weights, big)
    seg_min = jax.ops.segment_min(w, cu, num_segments=n)
    e_ids = jnp.arange(src.shape[0], dtype=jnp.int32)
    is_min = cross & (w == seg_min[cu])
    e_masked = jnp.where(is_min, e_ids, jnp.iinfo(jnp.int32).max)
    seg_edge = jax.ops.segment_min(e_masked, cu, num_segments=n)
    has_edge = seg_min < big
    return seg_edge, has_edge


def mst(res, csr: CSRMatrix, color: Optional[np.ndarray] = None,
        symmetrize_output: bool = True) -> GraphCOO:
    """MST/MSF of an undirected graph in CSR form
    (ref: sparse/solver/mst.cuh `mst`; the input is expected symmetric, as
    in the reference's tests).

    Returns the forest as GraphCOO; `color` (if given, len V) is updated
    in place with final supervertex labels."""
    n = csr.n_rows
    src_h = np.asarray(csr.row_ids(), dtype=np.int32)
    dst_h = np.asarray(csr.indices, dtype=np.int32)
    w_h = np.asarray(csr.data)

    src = jnp.asarray(src_h)
    dst = jnp.asarray(dst_h)
    weights = jnp.asarray(w_h)

    colors = np.arange(n, dtype=np.int32) if color is None \
        else np.asarray(color, dtype=np.int32).copy()

    out_src, out_dst, out_w = [], [], []
    max_iters = max(1, int(np.ceil(np.log2(max(n, 2)))) + 1)

    for _ in range(max_iters):
        seg_edge, has_edge = _min_edge_per_color(
            jnp.asarray(colors), src, dst, weights, n)
        seg_edge_h = np.asarray(seg_edge)
        has_h = np.asarray(has_edge)
        chosen = np.unique(seg_edge_h[has_h])
        if chosen.size == 0:
            break
        eu, ev, ew = src_h[chosen], dst_h[chosen], w_h[chosen]

        # union-find merge of supervertices (ref: label/merge_labels.cuh:47
        # pointer-jumping flatten; host union-find is exact and ≤V work)
        parent = np.arange(n, dtype=np.int32)

        def find(x):
            root = x
            while parent[root] != root:
                root = parent[root]
            while parent[x] != root:
                parent[x], x = root, parent[x]
            return root

        added_any = False
        for u, v_, wv in zip(colors[eu], colors[ev],
                             zip(eu, ev, ew)):
            ru, rv = find(u), find(v_)
            if ru != rv:
                parent[max(ru, rv)] = min(ru, rv)
                out_src.append(wv[0])
                out_dst.append(wv[1])
                out_w.append(wv[2])
                added_any = True
        if not added_any:
            break
        roots = np.array([find(c) for c in range(n)], dtype=np.int32)
        colors = roots[colors]

    if color is not None:
        color[:] = colors

    s = np.asarray(out_src, dtype=np.int32)
    d = np.asarray(out_dst, dtype=np.int32)
    w = np.asarray(out_w, dtype=w_h.dtype)
    if symmetrize_output:
        s, d, w = (np.concatenate([s, d]), np.concatenate([d, s]),
                   np.concatenate([w, w]))
    return GraphCOO(jnp.asarray(s), jnp.asarray(d), jnp.asarray(w),
                    int(s.shape[0]))
