"""Borůvka minimum spanning tree / forest (ref: raft/sparse/solver/mst.cuh,
mst_solver.cuh:32 `MST_solver`, detail/mst_solver_inl.cuh:127-131 iteration
loop, detail/mst_kernels.cuh kernels).

TPU formulation — fully device-resident rounds:

- "cheapest outgoing edge per supervertex" over all E edges is a cascade of
  ``segment_min`` passes: weight, then the canonical *undirected* key
  (min(u,v), max(u,v)) as an int32 pair, then edge id. The canonical key
  plays the role of the reference's alteration trick (making undirected
  weights unique, detail/mst_solver_inl.cuh:235): with a strict total order
  on undirected edges, the chosen-edge graph's only cycles are mutual
  2-cycles, which a min-color rule dedups.
- supervertex merging is scatter-min equivalence propagation + path halving
  inside a `lax.while_loop` (the reference's merge_labels kernels,
  label/merge_labels.cuh:47) — no host round-trips.
- the host loop only polls one boolean per Borůvka round ("any cross edge
  left?"), ≤ log2(V) polls total. Round 1 did per-round host union-find
  over the chosen edges (VERDICT #5) — unusable at the 10M-edge BASELINE
  graph; this version touches the host once per round.
"""

from __future__ import annotations

import contextlib
import dataclasses
import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from raft_tpu.core.sparse_types import CSRMatrix

_I32_MAX = np.iinfo(np.int32).max

# Edge-compaction schedule bounds (see mst()): each _compact CALL lands
# the rounds on one new _boruvka_round shape, so at most
# _COMPACT_STEPS + 1 distinct sizes (all of the form ceil-halvings of
# this input's nnz, floored at _COMPACT_MIN) are ever compiled.
_COMPACT_STEPS = 3
_COMPACT_MIN = 4096


@dataclasses.dataclass
class GraphCOO:
    """ref: mst_solver.cuh:19 `Graph_COO` {src, dst, weights, n_edges}."""
    src: jnp.ndarray
    dst: jnp.ndarray
    weights: jnp.ndarray
    n_edges: int


def _seg_lex_min(lead, keys, seg_ids, n: int):
    """Per-segment lexicographic minimum by cascade: ``lead`` (f32, inf
    identity) is reduced first, then each int key in ``keys`` (int32,
    _I32_MAX identity) refines among the survivors. Shared by the XLA
    round's (w, a, b, eid) and the grid round's (w, rank, eid) selection
    — ONE copy of the select-then-refine tie rule. Returns the reduced
    lead plus each key's per-segment winner, in order."""
    seg_lead = jax.ops.segment_min(lead, seg_ids, num_segments=n)
    sel = lead == seg_lead[seg_ids]
    outs = [seg_lead]
    for key in keys:
        masked = jnp.where(sel, key, _I32_MAX)
        seg_k = jax.ops.segment_min(masked, seg_ids, num_segments=n)
        sel &= key == seg_k[seg_ids]
        outs.append(seg_k)
    return outs


def _merge_colors(colors, has_edge, other, cid, n: int):
    """Merge supervertices by GATHER-ONLY pointer doubling (shared by the
    XLA and grid Borůvka rounds).

    The chosen-edge functional graph f(c) = other(c) has, under the
    strict total order on undirected edges, EXACTLY ONE cycle per weak
    component — the mutual 2-cycle at the component's minimum edge
    (both endpoint colors of that edge pick it; any longer cycle would
    need strictly decreasing minima around the loop). Forward chasing
    therefore lands every color in its component's 2-cycle, and
    min(f^K(c), f(f^K(c))) is a consistent component label. Doubling
    f ← f∘f reaches K = 2^ceil(log2 n) ≥ any chain length in
    ceil(log2 n) steps — each a dense V-gather, NO scatter (the r4
    merge ran scatter-min + path-halving to a fixpoint; scatters
    serialize on TPU, gathers don't — VERDICT r4 #5)."""
    f0 = jnp.where(has_edge, other, cid)
    steps = max(1, int(np.ceil(np.log2(max(n, 2)))))
    fk = lax.fori_loop(0, steps, lambda _, f: f[f], f0)
    r = jnp.minimum(fk, f0[fk])
    return r[colors]


@functools.partial(jax.jit, static_argnames=("n",))
def _boruvka_round(colors, src, dst, weights, n: int):
    """One Borůvka round, entirely on device.

    Returns (new_colors, edge_ids [n], include [n], any_cross) where
    ``edge_ids[c]`` is color c's chosen cross edge (junk when not
    ``include[c]``) and ``include`` marks edges to add to the forest
    (mutual 2-cycles deduped to the smaller color's pick).
    """
    cu = colors[src]
    cv = colors[dst]
    cross = cu != cv
    big = jnp.asarray(jnp.inf, weights.dtype)
    cid = jnp.arange(n, dtype=jnp.int32)

    # --- cheapest strict-total-order edge per color --------------------
    w = jnp.where(cross, weights, big)
    a_key = jnp.where(cross, jnp.minimum(src, dst), _I32_MAX)  # canonical
    b_key = jnp.where(cross, jnp.maximum(src, dst), _I32_MAX)  # undirected
    e_ids = jnp.where(cross, jnp.arange(src.shape[0], dtype=jnp.int32),
                      _I32_MAX)
    seg_w, seg_a, seg_b, seg_e = _seg_lex_min(
        w, (a_key, b_key, e_ids), cu, n)
    has_edge = seg_w < big

    safe_e = jnp.where(has_edge, seg_e, 0)
    other = jnp.where(has_edge, cv[safe_e], cid)       # partner color
    my_a = jnp.where(has_edge, seg_a, -1)
    my_b = jnp.where(has_edge, seg_b, -1)

    # --- mutual 2-cycle dedup (same undirected edge picked both ways) --
    mutual = (has_edge & has_edge[other]
              & (my_a[other] == my_a) & (my_b[other] == my_b))
    include = has_edge & (~mutual | (cid < other))

    # --- merge supervertices (shared gather-only doubling) -------------
    new_colors = _merge_colors(colors, has_edge, other, cid, n)
    # surviving cross-edge count under the NEW coloring: the driver's
    # compaction schedule (and its termination poll) read this scalar
    n_cross = jnp.sum(new_colors[src] != new_colors[dst])
    return new_colors, seg_e, include, n_cross


@functools.partial(jax.jit, static_argnames=("n",))
def _boruvka_round_grid(colors, mp, n: int):
    """One Borůvka round with the Pallas E-stage (sparse/solver/
    mst_grid.py): per-vertex winners from the slot-grid KVP scan, then a
    V-sized per-color lexicographic cascade, mutual-pair dedup by rank
    equality, and the gather-only pointer-doubling merge. Termination
    signal: the number of included edges (zero ⟺ no cross edge exists —
    any cross edge gives some color a winner, and a winner is included
    unless it loses a mutual pair to a color that includes it)."""
    from raft_tpu.sparse.solver.mst_grid import per_vertex_min_edge

    vw, vr, ve = per_vertex_min_edge(mp, colors)
    big = jnp.asarray(jnp.inf, vw.dtype)
    cid = jnp.arange(n, dtype=jnp.int32)

    # per-color lexicographic (w, rank, eid) cascade — V-sized (19x
    # smaller than the r4 E-sized cascade at the BASELINE graph)
    seg_w, seg_r, seg_e = _seg_lex_min(vw, (vr, ve), colors, n)
    has_edge = seg_w < big
    safe_e = jnp.where(has_edge, seg_e, 0)
    other = jnp.where(has_edge, colors[mp.dst[safe_e]], cid)
    my_rank = jnp.where(has_edge, seg_r, -1)
    mutual = has_edge & has_edge[other] & (my_rank[other] == my_rank)
    include = has_edge & (~mutual | (cid < other))

    new_colors = _merge_colors(colors, has_edge, other, cid, n)
    return new_colors, seg_e, include, jnp.sum(include)


@jax.jit
def _accumulate(edge_mask, eids, seg_e, include):
    # seg_e indexes the CURRENT (possibly compacted) edge arrays; eids
    # maps back to original edge ids, where the output mask lives
    safe = jnp.where(include, seg_e, 0)
    return edge_mask.at[eids[safe]].max(include)


@functools.partial(jax.jit, static_argnames=("out_size",))
def _compact(colors, src, dst, weights, eids, out_size: int):
    """Keep only cross edges (intra-component edges never matter again —
    the standard Borůvka filter, here at a STATIC out size chosen by the
    driver's bounded schedule so the jit cache stays bounded). Pad slots
    become infinite-weight self-loops (never cross, never minimal)."""
    cross = colors[src] != colors[dst]
    idx = jnp.nonzero(cross, size=out_size, fill_value=0)[0]
    valid = jnp.arange(out_size) < jnp.sum(cross)
    s2 = jnp.where(valid, src[idx], 0)
    d2 = jnp.where(valid, dst[idx], 0)
    w2 = jnp.where(valid, weights[idx],
                   jnp.asarray(jnp.inf, weights.dtype))
    e2 = jnp.where(valid, eids[idx], 0)
    return s2, d2, w2, e2


# auto-dispatch threshold for the Pallas E-stage: below this the per-call
# plan pack costs more than the XLA rounds it replaces
_MST_GRID_MIN_NNZ = 1 << 18


def _mst_method(csr) -> str:
    """Resolve the Borůvka E-stage formulation. ``RAFT_TPU_MST`` ∈
    {auto, grid, xla} forces a path; ``auto`` picks the slot-grid Pallas
    E-stage (mst_grid.py) for large f32 graphs on the compiled backend,
    subject to the plan's pad-ratio gate (same bound as SpMV's)."""
    from raft_tpu.core import env

    m = env.read("RAFT_TPU_MST")
    if m != "auto":
        return m
    from raft_tpu.sparse.linalg import _GRID_MAX_PAD_RATIO
    from raft_tpu.util.pallas_utils import use_interpret

    if use_interpret():
        return "xla"
    if jnp.dtype(csr.data.dtype) != jnp.dtype(jnp.float32):
        return "xla"   # grid weights are f32; keep f64 ordering exact
    if csr.logical_nnz() < _MST_GRID_MIN_NNZ:
        return "xla"
    if getattr(csr, "_mst_grid_reject", False):
        return "xla"   # remember a pad-gate rejection — the O(E) pack
                       # must not re-run per call just to re-decide
    mp = _cached_mst_plan(csr)
    if mp.plan.pad_ratio > _GRID_MAX_PAD_RATIO:
        with contextlib.suppress(AttributeError):
            del csr._mst_grid_plan
            csr._mst_grid_reject = True
        return "xla"
    return "grid"


def _cached_mst_plan(csr):
    mp = getattr(csr, "_mst_grid_plan", None)
    if mp is None:
        from raft_tpu.sparse.solver.mst_grid import prepare_mst

        mp = prepare_mst(csr)
        with contextlib.suppress(AttributeError):
            csr._mst_grid_plan = mp    # frozen containers skip the memo
    return mp


def _forest_output(src_h, dst_h, w_h, edge_mask,
                   symmetrize_output: bool) -> GraphCOO:
    ids = np.nonzero(np.asarray(edge_mask))[0]
    s = np.asarray(src_h)[ids]
    d = np.asarray(dst_h)[ids]
    w = np.asarray(w_h)[ids]
    if symmetrize_output:
        s, d, w = (np.concatenate([s, d]), np.concatenate([d, s]),
                   np.concatenate([w, w]))
    return GraphCOO(jnp.asarray(s), jnp.asarray(d), jnp.asarray(w),
                    int(s.shape[0]))


def mst(res, csr: CSRMatrix, color: Optional[np.ndarray] = None,
        symmetrize_output: bool = True) -> GraphCOO:
    """MST/MSF of an undirected graph in CSR form
    (ref: sparse/solver/mst.cuh `mst`; the input is expected symmetric, as
    in the reference's tests).

    Returns the forest as GraphCOO; `color` (if given, len V) is updated
    in place with final supervertex labels. Large f32 graphs on the
    compiled backend run the Pallas slot-grid E-stage per round
    (mst_grid.py, VERDICT r4 #5); ``RAFT_TPU_MST`` forces a path.
    A ``runtime.limits`` deadline scope is polled once per Borůvka
    round at the existing host sync."""
    from raft_tpu.runtime import limits

    n = csr.n_rows
    max_iters = max(1, int(np.ceil(np.log2(max(n, 2)))) + 1)
    colors = jnp.arange(n, dtype=jnp.int32) if color is None \
        else jnp.asarray(np.asarray(color, dtype=np.int32))

    if _mst_method(csr) == "grid":
        mp = _cached_mst_plan(csr)
        edge_mask = jnp.zeros((mp.n_edges,), jnp.bool_)
        eids = jnp.arange(mp.n_edges, dtype=jnp.int32)
        for _ in range(max_iters):
            limits.check_deadline("sparse.solver.mst")
            colors, seg_e, include, n_incl = _boruvka_round_grid(
                colors, mp, n)
            count = int(n_incl)          # the round's single host poll
            if count:
                edge_mask = _accumulate(edge_mask, eids, seg_e, include)
            else:
                break
        if color is not None:
            color[:] = np.asarray(colors)
        return _forest_output(mp.src, mp.dst, mp.weights, edge_mask,
                              symmetrize_output)
    src = jnp.asarray(csr.row_ids(), dtype=jnp.int32)
    dst = jnp.asarray(csr.indices, dtype=jnp.int32)
    weights = jnp.asarray(csr.data)
    # bucketing pad entries would be phantom zero-weight edges (last row →
    # vertex 0) and zero-weight MINIMA — rewrite them as infinite-weight
    # SELF-loops (src==dst is never a cross edge, so they can't bridge
    # genuinely disconnected components either)
    logical = csr.logical_nnz()
    if logical != csr.nnz:
        valid = jnp.arange(weights.shape[0]) < logical
        weights = jnp.where(valid, weights,
                            jnp.asarray(np.inf, weights.dtype))
        dst = jnp.where(valid, dst, src)

    edge_mask = jnp.zeros((src.shape[0],), jnp.bool_)

    # Edge filtering (the standard Borůvka compaction, shaped for jit):
    # intra-component edges can never be chosen again, so once the
    # surviving cross count fits half the current buffer the arrays
    # shrink to the next power-of-two size. At most _COMPACT_STEPS
    # halvings (each size is one extra _boruvka_round compile); typical
    # graphs drop most edges in the first rounds, so later rounds scan a
    # fraction of E instead of all of it every time.
    eids = jnp.arange(src.shape[0], dtype=jnp.int32)
    src0, dst0, weights0 = src, dst, weights   # originals: output ids
    steps_left = _COMPACT_STEPS
    for _ in range(max_iters):
        limits.check_deadline("sparse.solver.mst")
        colors, seg_e, include, n_cross = _boruvka_round(
            colors, src, dst, weights, n)
        count = int(n_cross)             # the round's single host poll
        edge_mask = _accumulate(edge_mask, eids, seg_e, include)
        if count == 0:
            break
        cur = int(src.shape[0])
        if steps_left > 0 and cur > _COMPACT_MIN and count <= cur // 2:
            # one _compact call = one new round shape = one budget step,
            # however many halvings the target size jumps
            new_size = max(_COMPACT_MIN, cur // 2)
            while new_size // 2 >= max(count, 1) \
                    and new_size // 2 >= _COMPACT_MIN:
                new_size //= 2
            steps_left -= 1
            src, dst, weights, eids = _compact(
                colors, src, dst, weights, eids, new_size)

    if color is not None:
        color[:] = np.asarray(colors)
    # edge_mask lives in ORIGINAL ids
    return _forest_output(src0, dst0, weights0, edge_mask,
                          symmetrize_output)
