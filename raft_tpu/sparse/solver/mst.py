"""Borůvka minimum spanning tree / forest (ref: raft/sparse/solver/mst.cuh,
mst_solver.cuh:32 `MST_solver`, detail/mst_solver_inl.cuh:127-131 iteration
loop, detail/mst_kernels.cuh kernels).

TPU formulation — fully device-resident rounds:

- "cheapest outgoing edge per supervertex" over all E edges is a cascade of
  ``segment_min`` passes: weight, then the canonical *undirected* key
  (min(u,v), max(u,v)) as an int32 pair, then edge id. The canonical key
  plays the role of the reference's alteration trick (making undirected
  weights unique, detail/mst_solver_inl.cuh:235): with a strict total order
  on undirected edges, the chosen-edge graph's only cycles are mutual
  2-cycles, which a min-color rule dedups.
- supervertex merging is scatter-min equivalence propagation + path halving
  inside a `lax.while_loop` (the reference's merge_labels kernels,
  label/merge_labels.cuh:47) — no host round-trips.
- the host loop only polls one boolean per Borůvka round ("any cross edge
  left?"), ≤ log2(V) polls total. Round 1 did per-round host union-find
  over the chosen edges (VERDICT #5) — unusable at the 10M-edge BASELINE
  graph; this version touches the host once per round.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from raft_tpu.core.sparse_types import CSRMatrix

_I32_MAX = np.iinfo(np.int32).max

# Edge-compaction schedule bounds (see mst()): each _compact CALL lands
# the rounds on one new _boruvka_round shape, so at most
# _COMPACT_STEPS + 1 distinct sizes (all of the form ceil-halvings of
# this input's nnz, floored at _COMPACT_MIN) are ever compiled.
_COMPACT_STEPS = 3
_COMPACT_MIN = 4096


@dataclasses.dataclass
class GraphCOO:
    """ref: mst_solver.cuh:19 `Graph_COO` {src, dst, weights, n_edges}."""
    src: jnp.ndarray
    dst: jnp.ndarray
    weights: jnp.ndarray
    n_edges: int


@functools.partial(jax.jit, static_argnames=("n",))
def _boruvka_round(colors, src, dst, weights, n: int):
    """One Borůvka round, entirely on device.

    Returns (new_colors, edge_ids [n], include [n], any_cross) where
    ``edge_ids[c]`` is color c's chosen cross edge (junk when not
    ``include[c]``) and ``include`` marks edges to add to the forest
    (mutual 2-cycles deduped to the smaller color's pick).
    """
    cu = colors[src]
    cv = colors[dst]
    cross = cu != cv
    big = jnp.asarray(jnp.inf, weights.dtype)
    cid = jnp.arange(n, dtype=jnp.int32)

    # --- cheapest strict-total-order edge per color --------------------
    w = jnp.where(cross, weights, big)
    seg_w = jax.ops.segment_min(w, cu, num_segments=n)
    has_edge = seg_w < big

    a_key = jnp.minimum(src, dst)          # canonical undirected key, hi
    b_key = jnp.maximum(src, dst)          # canonical undirected key, lo
    sel = cross & (w == seg_w[cu])
    a_m = jnp.where(sel, a_key, _I32_MAX)
    seg_a = jax.ops.segment_min(a_m, cu, num_segments=n)
    sel &= a_m == seg_a[cu]
    b_m = jnp.where(sel, b_key, _I32_MAX)
    seg_b = jax.ops.segment_min(b_m, cu, num_segments=n)
    sel &= b_m == seg_b[cu]
    e_ids = jnp.arange(src.shape[0], dtype=jnp.int32)
    e_m = jnp.where(sel, e_ids, _I32_MAX)
    seg_e = jax.ops.segment_min(e_m, cu, num_segments=n)

    safe_e = jnp.where(has_edge, seg_e, 0)
    other = jnp.where(has_edge, cv[safe_e], cid)       # partner color
    my_a = jnp.where(has_edge, seg_a, -1)
    my_b = jnp.where(has_edge, seg_b, -1)

    # --- mutual 2-cycle dedup (same undirected edge picked both ways) --
    mutual = (has_edge & has_edge[other]
              & (my_a[other] == my_a) & (my_b[other] == my_b))
    include = has_edge & (~mutual | (cid < other))

    # --- merge supervertices: scatter-min + path halving to fixpoint ---
    lo = jnp.minimum(cid, other)
    upd = jnp.where(has_edge, lo, _I32_MAX)
    safe_other = jnp.where(has_edge, other, 0)
    r0 = jnp.arange(n, dtype=jnp.int32)
    r0 = r0.at[cid].min(upd)
    r0 = r0.at[safe_other].min(upd)
    r0 = jnp.minimum(r0, r0[r0])

    def cond(state):
        i, r, changed = state
        # diameter-safe cap (see sparse/csr.py weak_cc): chosen-edge
        # chains with adversarial color ids propagate one hop per round
        return changed & (i < jnp.int32(n + 2))

    def body(state):
        i, r, _ = state
        ra = r[cid]
        rb = r[safe_other]
        lo2 = jnp.minimum(ra, rb)
        upd2 = jnp.where(has_edge, lo2, _I32_MAX)
        nr = r.at[cid].min(upd2)
        nr = nr.at[safe_other].min(upd2)
        nr = jnp.minimum(nr, nr[nr])
        return i + 1, nr, jnp.any(nr != r)

    _, r, _ = lax.while_loop(cond, body, (jnp.int32(0), r0, jnp.bool_(True)))
    new_colors = r[colors]
    # surviving cross-edge count under the NEW coloring: the driver's
    # compaction schedule (and its termination poll) read this scalar
    n_cross = jnp.sum(new_colors[src] != new_colors[dst])
    return new_colors, seg_e, include, n_cross


@jax.jit
def _accumulate(edge_mask, eids, seg_e, include):
    # seg_e indexes the CURRENT (possibly compacted) edge arrays; eids
    # maps back to original edge ids, where the output mask lives
    safe = jnp.where(include, seg_e, 0)
    return edge_mask.at[eids[safe]].max(include)


@functools.partial(jax.jit, static_argnames=("out_size",))
def _compact(colors, src, dst, weights, eids, out_size: int):
    """Keep only cross edges (intra-component edges never matter again —
    the standard Borůvka filter, here at a STATIC out size chosen by the
    driver's bounded schedule so the jit cache stays bounded). Pad slots
    become infinite-weight self-loops (never cross, never minimal)."""
    cross = colors[src] != colors[dst]
    idx = jnp.nonzero(cross, size=out_size, fill_value=0)[0]
    valid = jnp.arange(out_size) < jnp.sum(cross)
    s2 = jnp.where(valid, src[idx], 0)
    d2 = jnp.where(valid, dst[idx], 0)
    w2 = jnp.where(valid, weights[idx],
                   jnp.asarray(jnp.inf, weights.dtype))
    e2 = jnp.where(valid, eids[idx], 0)
    return s2, d2, w2, e2


def mst(res, csr: CSRMatrix, color: Optional[np.ndarray] = None,
        symmetrize_output: bool = True) -> GraphCOO:
    """MST/MSF of an undirected graph in CSR form
    (ref: sparse/solver/mst.cuh `mst`; the input is expected symmetric, as
    in the reference's tests).

    Returns the forest as GraphCOO; `color` (if given, len V) is updated
    in place with final supervertex labels."""
    n = csr.n_rows
    src = jnp.asarray(csr.row_ids(), dtype=jnp.int32)
    dst = jnp.asarray(csr.indices, dtype=jnp.int32)
    weights = jnp.asarray(csr.data)
    # bucketing pad entries would be phantom zero-weight edges (last row →
    # vertex 0) and zero-weight MINIMA — rewrite them as infinite-weight
    # SELF-loops (src==dst is never a cross edge, so they can't bridge
    # genuinely disconnected components either)
    logical = csr.logical_nnz()
    if logical != csr.nnz:
        valid = jnp.arange(weights.shape[0]) < logical
        weights = jnp.where(valid, weights,
                            jnp.asarray(np.inf, weights.dtype))
        dst = jnp.where(valid, dst, src)

    colors = jnp.arange(n, dtype=jnp.int32) if color is None \
        else jnp.asarray(np.asarray(color, dtype=np.int32))

    edge_mask = jnp.zeros((src.shape[0],), jnp.bool_)
    max_iters = max(1, int(np.ceil(np.log2(max(n, 2)))) + 1)

    # Edge filtering (the standard Borůvka compaction, shaped for jit):
    # intra-component edges can never be chosen again, so once the
    # surviving cross count fits half the current buffer the arrays
    # shrink to the next power-of-two size. At most _COMPACT_STEPS
    # halvings (each size is one extra _boruvka_round compile); typical
    # graphs drop most edges in the first rounds, so later rounds scan a
    # fraction of E instead of all of it every time.
    eids = jnp.arange(src.shape[0], dtype=jnp.int32)
    src0, dst0, weights0 = src, dst, weights   # originals: output ids
    steps_left = _COMPACT_STEPS
    for _ in range(max_iters):
        colors, seg_e, include, n_cross = _boruvka_round(
            colors, src, dst, weights, n)
        count = int(n_cross)             # the round's single host poll
        edge_mask = _accumulate(edge_mask, eids, seg_e, include)
        if count == 0:
            break
        cur = int(src.shape[0])
        if steps_left > 0 and cur > _COMPACT_MIN and count <= cur // 2:
            # one _compact call = one new round shape = one budget step,
            # however many halvings the target size jumps
            new_size = max(_COMPACT_MIN, cur // 2)
            while new_size // 2 >= max(count, 1) \
                    and new_size // 2 >= _COMPACT_MIN:
                new_size //= 2
            steps_left -= 1
            src, dst, weights, eids = _compact(
                colors, src, dst, weights, eids, new_size)

    if color is not None:
        color[:] = np.asarray(colors)

    ids = np.nonzero(np.asarray(edge_mask))[0]
    s = np.asarray(src0)[ids]          # edge_mask lives in ORIGINAL ids
    d = np.asarray(dst0)[ids]
    w = np.asarray(weights0)[ids]
    if symmetrize_output:
        s, d, w = (np.concatenate([s, d]), np.concatenate([d, s]),
                   np.concatenate([w, w]))
    return GraphCOO(jnp.asarray(s), jnp.asarray(d), jnp.asarray(w),
                    int(s.shape[0]))
