"""Sparse solvers: thick-restart Lanczos eigsh and Borůvka MST
(ref: raft/sparse/solver/{lanczos,mst}.cuh).
"""

from .lanczos import (LanczosConfig, eigsh,  # noqa: F401
                      eigsh_mnmg, lanczos_compute_eigenpairs)
from .mst import GraphCOO, mst  # noqa: F401
