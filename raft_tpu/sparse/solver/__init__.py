"""Sparse solvers: thick-restart Lanczos eigsh and Borůvka MST
(ref: raft/sparse/solver/{lanczos,mst}.cuh).
"""

from .lanczos import LanczosConfig, eigsh, lanczos_compute_eigenpairs  # noqa: F401
from .mst import GraphCOO, mst  # noqa: F401
