"""Pallas Borůvka E-stage — the slot-grid rebuild of the reference's MST
kernels (ref: sparse/solver/detail/mst_kernels.cuh `kernel_min_edge_per_
vertex` / `min_edge_per_supervertex`, detail/mst_solver_inl.cuh:127-131).

The round-4 Borůvka round ran a 4-pass lexicographic scatter-min cascade
over all E edges through XLA (24.55 s at 1M/19M on chip, VERDICT r4 #5) —
scatter serializes on TPU. This module replaces the E-sized work with the
slot-grid machinery from sparse/grid_spmv.py, exploiting that the edge
stream's segmentation BY SOURCE VERTEX is static (CSR row order) even
though the per-round coloring is not:

* per-VERTEX cheapest cross edge: a segmented LEXICOGRAPHIC min-scan over
  the packed (tile, sub-row, lane) slot grid — the segsum kernel's scan
  structure with a (weight, rank, edge-id) KVP combine instead of adds.
  ``rank`` is a host-precomputed strict total order on UNDIRECTED edges
  (sorted canonical (min(u,v), max(u,v)) pairs), the role of the
  reference's weight-alteration trick (mst_solver_inl.cuh:235): both
  directions of an undirected edge carry the same rank, so mutual picks
  are detected by rank equality.
* the per-round cross mask needs colors[src] and colors[dst] per slot:
  colors[dst] rides the same replicated-shard dynamic gather as SpMV's
  x-gather (kernel 1); colors[src] is gathered from the tile's OWN
  8-window color slab (the packer guarantees every row in a tile lies
  within 8 row-windows of the base) via the flat one-gather relocation
  trick the emission step already uses.
* per-window accumulation mirrors SpMV kernel 3 with a lexicographic
  min-combine over the (weight, rank, edge-id) plane triples.

The per-COLOR reduction over the V per-vertex winners, the mutual-pair
dedup, and the gather-only pointer-doubling merge live in mst.py — they
are V-sized, 19× smaller than the E-stage at the BASELINE R-MAT graph.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.sparse import grid_spmv
from raft_tpu.sparse.grid_spmv import (LANES, SPAN_WINDOWS, SUBROWS,
                                       TILE_SLOTS, _F_CONT, _F_CROSS,
                                       _F_REAL, _tree_gather, _shift_lanes,
                                       _shift_subs)
from raft_tpu.util.pallas_utils import pallas_call

from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_I32_MAX = np.iinfo(np.int32).max


class MSTGridPlan:
    """Prepared per-graph state for the Pallas Borůvka E-stage (built once
    per sparsity pattern; every round reuses it — the same once-per-
    pattern lifetime as the SpMV plan)."""

    def __init__(self, *, plan, rank_grid, eid_grid, srow_grid,
                 src, dst, weights, n: int, n_edges: int):
        self.plan = plan                 # GridSpMV pytree (pattern layout)
        self.rank_grid = rank_grid       # (ntile, 8, 128) i32, IMAX pad
        self.eid_grid = eid_grid         # (ntile, 8, 128) i32, IMAX pad
        self.srow_grid = srow_grid       # (ntile, 8, 128) i32 row - base*128
        self.src = src                   # (E,) i32 original edge arrays
        self.dst = dst
        self.weights = weights
        self.n = n
        self.n_edges = n_edges


def _mst_flatten(p: MSTGridPlan):
    leaves = (p.plan, p.rank_grid, p.eid_grid, p.srow_grid,
              p.src, p.dst, p.weights)
    return leaves, (p.n, p.n_edges)


def _mst_unflatten(aux, leaves):
    p = MSTGridPlan.__new__(MSTGridPlan)
    (p.plan, p.rank_grid, p.eid_grid, p.srow_grid,
     p.src, p.dst, p.weights) = leaves
    p.n, p.n_edges = aux
    return p


jax.tree_util.register_pytree_node(MSTGridPlan, _mst_flatten,
                                   _mst_unflatten)


def prepare_mst(csr) -> MSTGridPlan:
    """Build the E-stage plan from a (symmetric) CSR graph."""
    collect: dict = {}
    plan = grid_spmv.prepare(csr, _collect=collect)
    rows, cols, data = collect["edges"]   # prepare already expanded them
    n = csr.n_rows
    a = np.minimum(rows, cols).astype(np.int64)
    b = np.maximum(rows, cols).astype(np.int64)
    # strict total order on undirected edges: index in the sorted order
    # of canonical pairs; both directions share one rank
    _, rank_of = np.unique(a * np.int64(max(csr.n_cols, 1)) + b,
                           return_inverse=True)
    rank_of = rank_of.astype(np.int32)
    eidg = collect["eid"]
    real = eidg >= 0
    safe = np.where(real, eidg, 0)
    rank_grid = np.where(real, rank_of[safe], _I32_MAX).astype(np.int32)
    eid_grid = np.where(real, eidg, _I32_MAX).astype(np.int32)
    return MSTGridPlan(
        plan=plan,
        rank_grid=jnp.asarray(rank_grid),
        eid_grid=jnp.asarray(eid_grid),
        srow_grid=jnp.asarray(collect["srow_local"]),
        src=jnp.asarray(rows.astype(np.int32)),
        dst=jnp.asarray(cols.astype(np.int32)),
        weights=jnp.asarray(data.astype(np.float32)),
        n=n, n_edges=len(rows))


# ---------------------------------------------------------------------------
# kernels
# ---------------------------------------------------------------------------


def _comb(aw, ar, ae, bw, br, be):
    """Lexicographic (weight, rank, eid) min — the strict-total-order KVP
    combine. Commutative, associative, idempotent (safe in scans)."""
    lt = (bw < aw) | ((bw == aw) & ((br < ar) | ((br == ar) & (be < ae))))
    return (jnp.where(lt, bw, aw), jnp.where(lt, br, ar),
            jnp.where(lt, be, ae))


def _idw():
    return jnp.asarray(jnp.inf, jnp.float32)


def _idi():
    return jnp.asarray(_I32_MAX, jnp.int32)


def _mst_scan_kernel(tb_ref, cdst_ref, w_ref, rank_ref, eid_ref, f_ref,
                     e_ref, sl_ref, *win_and_out_refs):
    """Per-tile segmented lexicographic min over edge runs.

    Inputs after the scalar-prefetch tile-base ref: the dst-color tile
    (from the replicated-shard gather), the static weight/rank/eid/flags/
    emit/src-row-offset grids, then the tile's 8 color-window rows.
    Outputs: the per-(row, tile) winner triple relocated to its
    (window, row%128) slot — identity (inf / int32 max) elsewhere."""
    win_refs = win_and_out_refs[:SPAN_WINDOWS]
    (ow_ref, or_ref, oe_ref,
     sw8_ref, sw_ref, sr_ref, se_ref) = win_and_out_refs[SPAN_WINDOWS:]
    del tb_ref

    f = f_ref[0]
    real = (f & _F_REAL) != 0
    cont = (f & _F_CONT) != 0
    crossm = (f & _F_CROSS) != 0

    # colors[src]: in-tile gather from this tile's own 8-window color
    # slab; the 1024-position space exceeds Mosaic's lane-local gather,
    # so it rides the row-broadcast select tree (slot p -> window p>>7,
    # lane p&127, matching the axis-0 stack of the window rows). All
    # tree sources round-trip through VMEM scratch: sublane-slicing a
    # live computed vector is an "Invalid vector register cast" in
    # Mosaic (round-5 AOT bisect; same fix as grid_spmv._segsum_body)
    sw8_ref[:] = jnp.concatenate([r[0] for r in win_refs], axis=0)
    csrc = _tree_gather(sw8_ref[:], sl_ref[0], SUBROWS)

    is_cross = real & (csrc != cdst_ref[0])
    wv = jnp.where(is_cross, w_ref[0], _idw())
    rv = jnp.where(is_cross, rank_ref[0], _idi())
    ev = jnp.where(is_cross, eid_ref[0], _idi())

    # segmented inclusive min-scan along lanes (runs are row pieces) —
    # the segsum kernel's scan with the KVP combine; identity fills
    cw, cr, ce, fl = wv, rv, ev, cont
    for d in (1, 2, 4, 8, 16, 32, 64):
        sw = jnp.where(fl, _shift_lanes(cw, d), _idw())
        sr = jnp.where(fl, _shift_lanes(cr, d), _idi())
        se = jnp.where(fl, _shift_lanes(ce, d), _idi())
        cw, cr, ce = _comb(cw, cr, ce, sw, sr, se)
        fl = fl & _shift_lanes(fl, d)

    # cross-sub-row carry: chained pieces fold the predecessors' tails
    tw, tr, te = cw[:, 127:128], cr[:, 127:128], ce[:, 127:128]
    crossf = crossm[:, 0:1]
    fs = crossf
    for d in (1, 2, 4):
        sw = jnp.where(fs, _shift_subs(tw, d), _idw())
        sr = jnp.where(fs, _shift_subs(tr, d), _idi())
        se = jnp.where(fs, _shift_subs(te, d), _idi())
        tw, tr, te = _comb(tw, tr, te, sw, sr, se)
        fs = fs & _shift_subs(fs, d)
    carw = jnp.where(crossf, _shift_subs(tw, 1), _idw())
    carr = jnp.where(crossf, _shift_subs(tr, 1), _idi())
    care = jnp.where(crossf, _shift_subs(te, 1), _idi())
    cw, cr, ce = _comb(cw, cr, ce,
                       jnp.where(crossm, carw, _idw()),
                       jnp.where(crossm, carr, _idi()),
                       jnp.where(crossm, care, _idi()))

    # emission: relocate each row's winner to its (window, row%128) slot
    # via the same in-tile select tree (Mosaic-legal lane gathers only)
    e = e_ref[0]                                          # (8, 128)
    idx = jnp.maximum(e, 0)
    keep = e >= 0
    sw_ref[:] = cw
    sr_ref[:] = cr
    se_ref[:] = ce
    gw = _tree_gather(sw_ref[:], idx, SUBROWS)
    gr = _tree_gather(sr_ref[:], idx, SUBROWS)
    ge = _tree_gather(se_ref[:], idx, SUBROWS)
    ow_ref[0] = jnp.where(keep, gw, _idw())
    or_ref[0] = jnp.where(keep, gr, _idi())
    oe_ref[0] = jnp.where(keep, ge, _idi())


def _mst_reduce_kernel(perm_ref, base_ref, cw_ref, cr_ref, ce_ref,
                       *o_refs):
    """Window-plane accumulation (SpMV kernel 3) with the KVP min-combine:
    o_refs are SPAN_WINDOWS triples (w, rank, eid) of (1, 1, 128) blocks
    at window base+d."""
    del perm_ref
    t = pl.program_id(0)
    prev = base_ref[jnp.maximum(t - 1, 0)]
    first = (t == 0) | (base_ref[t] != prev)
    cw = cw_ref[0]
    cr = cr_ref[0]
    ce = ce_ref[0]

    @pl.when(first)
    def _init():
        for d in range(SPAN_WINDOWS):
            o_refs[3 * d][0] = cw[d:d + 1]
            o_refs[3 * d + 1][0] = cr[d:d + 1]
            o_refs[3 * d + 2][0] = ce[d:d + 1]

    @pl.when(jnp.logical_not(first))
    def _acc():
        for d in range(SPAN_WINDOWS):
            aw = o_refs[3 * d][0]
            ar = o_refs[3 * d + 1][0]
            ae = o_refs[3 * d + 2][0]
            nw, nr, ne = _comb(aw, ar, ae, cw[d:d + 1], cr[d:d + 1],
                               ce[d:d + 1])
            o_refs[3 * d][0] = nw
            o_refs[3 * d + 1][0] = nr
            o_refs[3 * d + 2][0] = ne


@jax.jit
def per_vertex_min_edge(mp: MSTGridPlan, colors):
    """Per-vertex cheapest CROSS edge under ``colors`` as lexicographic
    (weight, rank, eid) triples: (minw [n], minrank [n], mineid [n]),
    identity (inf / int32 max) where a vertex has no cross edge."""
    plan = mp.plan
    n = mp.n
    ntile = plan.data_grid.shape[0]
    nwp = plan.visited.shape[1]
    colors = colors.astype(jnp.int32)

    # ---- kernel A: colors[dst] via the shard-blocked tree gather (the
    # same Mosaic-legal kernel as SpMV's kernel 1; dtype-agnostic)
    gsub = grid_spmv.GROUP_TILES * SUBROWS
    c_sh = grid_spmv._shard_rows(plan, colors)
    ngroup, grid1 = grid_spmv._gather_grid_spec(plan)
    cdst = pallas_call(
        grid_spmv._tree_gather_kernel,
        grid_spec=grid1,
        out_shape=jax.ShapeDtypeStruct((ngroup, gsub, LANES), jnp.int32),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary",)),
    )(plan.group_shard, c_sh,
      plan.cols_grid.reshape(ngroup, gsub, LANES))
    cdst_tiles = cdst.reshape(ntile, SUBROWS, LANES)

    # ---- kernel B: segmented lexicographic min-scan + emission
    cwin = jnp.zeros(nwp * LANES, jnp.int32).at[:n].set(colors)
    cwin = cwin.reshape(nwp, 1, LANES)   # (1, 1, 128) window blocks
    tile_specs = [
        pl.BlockSpec((1, SUBROWS, LANES), lambda t, tb: (t, 0, 0),
                     memory_space=pltpu.VMEM)
        for _ in range(7)
    ]
    win_specs = [
        pl.BlockSpec((1, 1, LANES),
                     (lambda t, tb, _d=d: (
                         jnp.minimum(tb[t] + _d, nwp - 1), 0, 0)),
                     memory_space=pltpu.VMEM)
        for d in range(SPAN_WINDOWS)
    ]
    grid2 = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(ntile,),
        in_specs=tile_specs + win_specs,
        out_specs=[
            pl.BlockSpec((1, SUBROWS, LANES), lambda t, tb: (t, 0, 0),
                         memory_space=pltpu.VMEM)
            for _ in range(3)
        ],
        # scratch rides the grid spec (pallas rejects the kwarg with
        # grid_spec): layout round-trips for the select-tree sources
        scratch_shapes=[pltpu.VMEM((SUBROWS, LANES), jnp.int32),
                        pltpu.VMEM((SUBROWS, LANES), jnp.float32),
                        pltpu.VMEM((SUBROWS, LANES), jnp.int32),
                        pltpu.VMEM((SUBROWS, LANES), jnp.int32)],
    )
    cw, cr, ce = pallas_call(
        _mst_scan_kernel, grid_spec=grid2,
        out_shape=[
            jax.ShapeDtypeStruct((ntile, SUBROWS, LANES), jnp.float32),
            jax.ShapeDtypeStruct((ntile, SUBROWS, LANES), jnp.int32),
            jax.ShapeDtypeStruct((ntile, SUBROWS, LANES), jnp.int32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary",)),
    )(plan.tile_base, cdst_tiles, plan.data_grid, mp.rank_grid,
      mp.eid_grid, plan.flags_grid, plan.emit_grid, mp.srow_grid,
      *([cwin] * SPAN_WINDOWS))

    # ---- kernel C: per-window-plane KVP accumulation over tiles
    out_specs = []
    out_shape = []
    for d in range(SPAN_WINDOWS):
        for dt in (jnp.float32, jnp.int32, jnp.int32):
            out_specs.append(pl.BlockSpec(
                (1, 1, LANES),
                (lambda t, pm, bs, _d=d: (bs[t] + _d, 0, 0)),
                memory_space=pltpu.VMEM))
            out_shape.append(jax.ShapeDtypeStruct((nwp, 1, LANES), dt))
    grid3 = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(ntile,),
        in_specs=[
            pl.BlockSpec((1, SUBROWS, LANES),
                         lambda t, pm, bs: (pm[t], 0, 0),
                         memory_space=pltpu.VMEM)
            for _ in range(3)
        ],
        out_specs=out_specs,
    )
    planes = pallas_call(
        _mst_reduce_kernel, grid_spec=grid3,
        out_shape=out_shape,
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary",)),
    )(plan.perm_sorted, plan.base_sorted, cw, cr, ce)

    # ---- combine the 8 window plane triples (visited-masked) ----------
    mw = jnp.full((nwp, LANES), jnp.inf, jnp.float32)
    mr = jnp.full((nwp, LANES), _I32_MAX, jnp.int32)
    me = jnp.full((nwp, LANES), _I32_MAX, jnp.int32)
    for d in range(SPAN_WINDOWS):
        vis = jnp.asarray(plan.visited[d])[:, None]
        pw = jnp.where(vis, planes[3 * d][:, 0, :], jnp.inf)
        pr = jnp.where(vis, planes[3 * d + 1][:, 0, :], _I32_MAX)
        pe = jnp.where(vis, planes[3 * d + 2][:, 0, :], _I32_MAX)
        mw, mr, me = _comb(mw, mr, me, pw, pr, pe)
    return (mw.reshape(-1)[:n], mr.reshape(-1)[:n], me.reshape(-1)[:n])
