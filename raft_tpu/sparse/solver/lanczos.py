"""Thick-restart Lanczos eigensolver (ref: raft/sparse/solver/lanczos.cuh:34
`lanczos_compute_eigenpairs`, lanczos_types.hpp:20-50 config,
detail/lanczos.cuh:402 `lanczos_smallest`).

Structure mirrors the reference: a host-driven restart loop (the data-
dependent `while (res > tol && iter < maxIter)` at detail/lanczos.cuh:537)
around jitted device work.  The per-iteration hot kernel is SpMV
(cusparseSpMV at detail/lanczos.cuh:603-623 → gather+segment_sum here) plus
Gram-Schmidt dots/axpys (cublas calls :321+ → one [ncv,n]·[n] matvec on the
MXU).  The small ncv×ncv Ritz problem (`lanczos_solve_ritz`
detail/lanczos.cuh:129 via syevd) is solved on host in float64 — TPU f64 is
emulated and ncv is tiny, exactly the "f64-on-host Ritz" plan from
SURVEY.md §7.  After a thick restart the projected matrix is an arrowhead
(diagonal Ritz block bordered by residual couplings), so we keep the full
ncv×ncv projected matrix T explicitly instead of (alpha, beta) vectors.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from raft_tpu.core import logger
from raft_tpu.core.sparse_types import COOMatrix, CSRMatrix
from raft_tpu.sparse import convert
from raft_tpu.sparse.linalg import _segment_spmv as _spmv_kernel
from raft_tpu.util.precision import with_matmul_precision


@dataclasses.dataclass
class LanczosConfig:
    """ref: lanczos_types.hpp:20-50 `lanczos_solver_config`."""
    n_components: int
    max_iterations: int = 1000
    ncv: int = 0          # 0 → min(n, max(2*k + 1, 20))
    tolerance: float = 1e-7
    which: str = "SA"     # LA | LM | SA | SM
    seed: int = 42


@jax.jit
def _orthogonalize(v, basis):
    """Full Gram-Schmidt against the rows of `basis` — one [m,n]·[n] matvec
    plus one [n,m]·[m] matvec, both MXU-shaped (the reference's per-vector
    cublas dot/axpy loop, detail/lanczos.cuh:321+, fused)."""
    coeffs = basis @ v
    return v - basis.T @ coeffs, coeffs


@functools.partial(jax.jit,
                   static_argnames=("j_start", "ncv", "n", "use_ell",
                                    "use_rank1"))
def _extend_device(m1, m2, m3, basis, v, key,
                   j_start: int, ncv: int, n: int, use_ell: bool = False,
                   rank1=None, use_rank1: bool = False):
    """Grow Krylov basis rows [j_start, ncv) entirely on device
    (ref: lanczos_aux detail/lanczos.cuh:248-340 — but where the reference
    host-drives each step through cusparse/cublas calls, the whole batch of
    steps is ONE device program here; round 1 synced the host ~3× per step,
    VERDICT #6, which at a ~70 ms tunnel RTT dominated the solve).

    Returns (basis, alphas [ncv], betas [ncv], breakdown [ncv] bool, v_next):
    ``alphas[j]``/``betas[j]`` are the tridiagonal entries produced by step
    j; ``breakdown[j]`` flags a residual norm below √eps relative to the
    operator scale (a running max of ‖A·v‖) — the step then restarts from
    a fresh random direction, as the reference does.

    The matrix arrives as (row_ids, cols, data) CSR-expanded triples, or —
    when ``use_ell`` — as (ell_cols, ell_data, dummy): the ELL slab SpMV
    (dense gather + row reduce, no scatter) is the TPU-preferred path that
    `maybe_ell` auto-selects in `_eigsh_csr` (VERDICT #9).

    ``rank1`` = (u, w_vec, alpha) applies the operator A + alpha·u·w_vecᵀ
    without materializing it — the modularity matrix B = A - d·dᵀ/2m
    (spectral lineage) is exactly this form."""
    dtype = basis.dtype

    def do_spmv(v):
        out = (jnp.sum(m2 * v[m1], axis=1) if use_ell
               else _spmv_kernel(m1, m2, m3, v, n))
        if use_rank1:
            u, wv, alpha = rank1
            out = out + alpha * u * jnp.dot(wv, v)
        return out

    def step(j, carry):
        basis, v, alphas, betas, brk, key, scale = carry
        basis = basis.at[j].set(v)
        w = do_spmv(v)
        # operator-scale estimate: running max of ‖A·v‖ over unit v —
        # scale-invariant (a 1e-4-norm Laplacian must behave exactly like
        # its unit-norm scaling; a constant floor would not)
        scale = jnp.maximum(scale, jnp.linalg.norm(w))
        w, c1 = _orthogonalize(w, basis)
        w, c2 = _orthogonalize(w, basis)     # second pass for f32
        alpha = c1[j] + c2[j]
        b = jnp.linalg.norm(w)
        key, sub = jax.random.split(key)
        # RELATIVE breakdown test: when b ≲ √eps·scale the residual is
        # orthogonalization noise — normalizing it would amplify the
        # non-orthogonal component by 1/b and corrupt the basis (observed:
        # highly symmetric graphs exhaust their ~10 distinct eigenvalues
        # well before ncv, betas decay to 1e-5 ≫ the old 1e-10 absolute
        # threshold, and Ritz values exploded to ±435 on a matrix with
        # ‖A‖ ≤ 2). A tiny TRUE coupling at this scale means the subspace
        # is numerically invariant; continuing from a fresh random
        # direction is the correct thick-restart behavior either way.
        tol_b = (jnp.sqrt(jnp.finfo(dtype).eps)
                 * jnp.maximum(scale, jnp.finfo(dtype).tiny * 1e4))
        bad = b < tol_b

        def breakdown(_):
            w2 = jax.random.normal(sub, (n,), dtype)
            w2, _ = _orthogonalize(w2, basis)
            w2, _ = _orthogonalize(w2, basis)
            return w2, jnp.linalg.norm(w2)

        w, b_div = lax.cond(bad, breakdown, lambda _: (w, b), None)
        alphas = alphas.at[j].set(alpha)
        betas = betas.at[j].set(b)           # pre-recovery coupling
        brk = brk.at[j].set(bad)
        v = w / b_div
        return basis, v, alphas, betas, brk, key, scale

    init = (basis, v, jnp.zeros((ncv,), dtype), jnp.zeros((ncv,), dtype),
            jnp.zeros((ncv,), jnp.bool_), key, jnp.zeros((), dtype))
    basis, v, alphas, betas, brk, _, _ = lax.fori_loop(
        j_start, ncv, step, init)
    return basis, jnp.stack([alphas, betas]), brk, v


@with_matmul_precision
def lanczos_compute_eigenpairs(res, a, config: LanczosConfig,
                               v0: Optional[jnp.ndarray] = None,
                               rank1=None) -> Tuple[jnp.ndarray,
                                                    jnp.ndarray]:
    """Compute k eigenpairs of symmetric sparse A
    (ref: sparse/solver/lanczos.cuh:34-86, CSR/COO overloads).

    ``rank1`` = (u, w, alpha): solve for A + alpha·u·wᵀ instead, applied
    matrix-free inside the device loop (the modularity matrix's form).

    Returns (eigenvalues [k], eigenvectors [n, k]) sorted per `which`."""
    if isinstance(a, COOMatrix):
        from raft_tpu.sparse import op as sparse_op
        a = convert.sorted_coo_to_csr(sparse_op.coo_sort(a))
    return _eigsh_csr(a, config, v0, rank1=rank1)


@with_matmul_precision
def eigsh(a, k: int = 6, which: str = "SA", v0=None, ncv: int = 0,
          maxiter: int = 1000, tol: float = 1e-7, seed: int = 42,
          res=None) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """scipy-compatible front-end (ref: pylibraft sparse/linalg/lanczos.pyx:85
    `eigsh`)."""
    cfg = LanczosConfig(n_components=k, max_iterations=maxiter, ncv=ncv,
                        tolerance=tol, which=which.upper(), seed=seed)
    return lanczos_compute_eigenpairs(res, a, cfg, v0)


def _eigsh_csr(csr: CSRMatrix, cfg: LanczosConfig, v0,
               rank1=None) -> Tuple:
    n = csr.n_rows
    k = cfg.n_components
    if k <= 0 or k >= n:
        raise ValueError(f"need 0 < n_components < n, got {k} vs {n}")
    if cfg.max_iterations < 1:
        raise ValueError(
            f"max_iterations must be >= 1, got {cfg.max_iterations}")
    ncv = cfg.ncv if cfg.ncv else min(n, max(2 * k + 1, 20))
    ncv = min(max(ncv, k + 2), n)
    which = cfg.which
    if which not in ("LA", "LM", "SA", "SM"):
        raise ValueError(f"which must be LA|LM|SA|SM, got {which}")

    dtype = jnp.float32
    r1 = None if rank1 is None else tuple(
        jnp.asarray(x, dtype) for x in rank1[:2]) + (
        jnp.asarray(rank1[2], dtype),)
    from raft_tpu.sparse.ell import maybe_ell

    ell = maybe_ell(csr)
    if ell is not None:       # regular sparsity → scatter-free slab SpMV
        mat_args = (ell.cols, ell.data.astype(dtype),
                    jnp.zeros((), dtype))
        use_ell = True
    else:
        mat_args = (csr.row_ids(), csr.indices, csr.data.astype(dtype))
        use_ell = False

    if v0 is None:
        rng = np.random.default_rng(cfg.seed)
        v = jnp.asarray(rng.standard_normal(n), dtype=dtype)
    else:
        v = jnp.asarray(v0, dtype=dtype)
    v = v / jnp.linalg.norm(v)

    basis = jnp.zeros((ncv, n), dtype=dtype)
    t = np.zeros((ncv, ncv), dtype=np.float64)   # projected matrix

    def extend(j_start: int, basis, t, v, it: int):
        """Device-batched Lanczos steps for rows [j_start, ncv); one small
        device→host fetch fills the tridiagonal entries of t."""
        key = jax.random.key(cfg.seed + 7919 * (it + 1) + j_start)
        basis, ab, brk, v = _extend_device(
            *mat_args, basis, v, key, j_start, ncv, n, use_ell,
            rank1=r1, use_rank1=r1 is not None)
        ab_h = np.asarray(ab, dtype=np.float64)   # the fetch: [2, ncv]
        brk_h = np.asarray(brk)
        for j in range(j_start, ncv):
            t[j, j] = ab_h[0, j]
            if j + 1 < ncv:
                t[j, j + 1] = t[j + 1, j] = ab_h[1, j]
        # exact invariant subspace at the last step → no outside coupling
        beta_last = 0.0 if brk_h[ncv - 1] else float(ab_h[1, ncv - 1])
        return basis, t, beta_last, v

    basis, t, beta_last, v = extend(0, basis, t, v, it=-1)

    for it in range(cfg.max_iterations):
        evals, evecs = np.linalg.eigh(t)
        # Ritz selection per `which` (ref: lanczos_solve_ritz
        # detail/lanczos.cuh:182-223 — SM/LM sort Ritz values by magnitude
        # inside the Krylov space; no spectral shift is used).
        if which == "LM":
            order = np.argsort(-np.abs(evals))
        elif which == "SM":
            order = np.argsort(np.abs(evals))
        elif which == "LA":
            order = np.argsort(-evals)
        else:
            order = np.argsort(evals)
        keep = order[:k]
        ritz_vals = evals[keep]
        s = evecs[:, keep]                      # [ncv, k]
        residuals = np.abs(beta_last * s[-1, :])
        converged = float(residuals.max()) < cfg.tolerance
        if converged or it == cfg.max_iterations - 1:
            if not converged:
                # Reference parity: lanczos_smallest exits its
                # `while (res > tol && iter < maxIter)` loop and returns the
                # best available pairs without throwing
                # (detail/lanczos.cuh:537); we surface it via the logger.
                logger.warn(
                    "lanczos: max_iterations=%d reached with residual %.3e "
                    "> tol %.3e; returning unconverged eigenpairs",
                    cfg.max_iterations, float(residuals.max()),
                    cfg.tolerance)
            ritz_vecs = basis.T @ jnp.asarray(s, dtype=dtype)
            # normalize (f32 drift) and sort ascending like scipy eigsh
            ritz_vecs = ritz_vecs / jnp.linalg.norm(ritz_vecs, axis=0)
            asc = np.argsort(ritz_vals)
            return (jnp.asarray(ritz_vals[asc], dtype=dtype),
                    ritz_vecs[:, asc])

        # -- thick restart (ref: detail/lanczos.cuh:537-700) --------------
        ritz_vecs = basis.T @ jnp.asarray(s, dtype=dtype)   # [n, k]
        q, r = jnp.linalg.qr(ritz_vecs)
        signs = jnp.sign(jnp.diagonal(r))
        signs = jnp.where(signs == 0, 1.0, signs)
        q = q * signs[None, :]                  # keep original directions
        basis = jnp.zeros_like(basis).at[:k].set(q.T)
        t = np.zeros_like(t)
        t[np.arange(k), np.arange(k)] = ritz_vals
        border = beta_last * s[-1, :]           # couplings to residual row
        t[:k, k] = border
        t[k, :k] = border
        # Extend from row k: the device loop's first step IS the Lanczos
        # step on the residual direction (writes basis row k, t[k, k],
        # t[k, k+1]); the arrowhead border above stays host-side.
        basis, t, beta_last, v = extend(k, basis, t, v, it=it)

    raise AssertionError("unreachable: loop returns at max_iterations")
