"""Thick-restart Lanczos eigensolver (ref: raft/sparse/solver/lanczos.cuh:34
`lanczos_compute_eigenpairs`, lanczos_types.hpp:20-50 config,
detail/lanczos.cuh:402 `lanczos_smallest`).

Structure mirrors the reference: a host-driven restart loop (the data-
dependent `while (res > tol && iter < maxIter)` at detail/lanczos.cuh:537)
around jitted device work.  The per-iteration hot kernel is SpMV
(cusparseSpMV at detail/lanczos.cuh:603-623 → gather+segment_sum here) plus
Gram-Schmidt dots/axpys (cublas calls :321+ → one [ncv,n]·[n] matvec on the
MXU).  The small ncv×ncv Ritz problem (`lanczos_solve_ritz`
detail/lanczos.cuh:129 via syevd) is solved on host in float64 — TPU f64 is
emulated and ncv is tiny, exactly the "f64-on-host Ritz" plan from
SURVEY.md §7.  After a thick restart the projected matrix is an arrowhead
(diagonal Ritz block bordered by residual couplings), so we keep the full
ncv×ncv projected matrix T explicitly instead of (alpha, beta) vectors.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from raft_tpu.core import logger, trace
from raft_tpu import obs
from raft_tpu.core.guards import (ConvergenceError, ConvergenceReport,
                                  IllConditionedError, resolve_guard_mode)
from raft_tpu.core.sparse_types import COOMatrix, CSRMatrix
from raft_tpu.sparse import convert
from raft_tpu.sparse.linalg import _segment_spmv as _spmv_kernel
from raft_tpu.util.precision import with_matmul_precision


@dataclasses.dataclass
class LanczosConfig:
    """ref: lanczos_types.hpp:20-50 `lanczos_solver_config`.

    ``strict`` upgrades the exhausted-budget warn-and-return to a typed
    :class:`~raft_tpu.core.guards.ConvergenceError` carrying the full
    :class:`~raft_tpu.core.guards.ConvergenceReport`."""
    n_components: int
    max_iterations: int = 1000
    ncv: int = 0          # 0 → min(n, max(2*k + 1, 20))
    tolerance: float = 1e-7
    which: str = "SA"     # LA | LM | SA | SM
    seed: int = 42
    strict: bool = False


@jax.jit
def _orthogonalize(v, basis):
    """Full Gram-Schmidt against the rows of `basis` — one [m,n]·[n] matvec
    plus one [n,m]·[m] matvec, both MXU-shaped (the reference's per-vector
    cublas dot/axpy loop, detail/lanczos.cuh:321+, fused)."""
    coeffs = basis @ v
    return v - basis.T @ coeffs, coeffs


@functools.partial(jax.jit,
                   static_argnames=("j_start", "ncv", "n", "use_ell",
                                    "use_grid", "use_dense", "use_rank1"))
def _extend_device(m1, m2, m3, basis, v, key,
                   j_start: int, ncv: int, n: int, use_ell: bool = False,
                   rank1=None, use_rank1: bool = False,
                   use_grid: bool = False, use_dense: bool = False):
    """Grow Krylov basis rows [j_start, ncv) entirely on device
    (ref: lanczos_aux detail/lanczos.cuh:248-340 — but where the reference
    host-drives each step through cusparse/cublas calls, the whole batch of
    steps is ONE device program here; round 1 synced the host ~3× per step,
    VERDICT #6, which at a ~70 ms tunnel RTT dominated the solve).

    Returns (basis, alphas [ncv], betas [ncv], breakdown [ncv] bool, v_next):
    ``alphas[j]``/``betas[j]`` are the tridiagonal entries produced by step
    j; ``breakdown[j]`` flags a residual norm below √eps relative to the
    operator scale (a running max of ‖A·v‖) — the step then restarts from
    a fresh random direction, as the reference does.

    The matrix arrives as (row_ids, cols, data) CSR-expanded triples, or —
    when ``use_ell`` — as (ell_cols, ell_data, dummy): the ELL slab SpMV
    (dense gather + row reduce, no scatter) is the TPU-preferred path that
    `maybe_ell` auto-selects in `_eigsh_csr` (VERDICT #9).

    ``rank1`` = (u, w_vec, alpha) applies the operator A + alpha·u·w_vecᵀ
    without materializing it — the modularity matrix B = A - d·dᵀ/2m
    (spectral lineage) is exactly this form."""
    dtype = basis.dtype

    def do_spmv(v):
        if use_dense:  # dense operator (eig_sel subset path): MXU matvec
            out = m1 @ v
        elif use_grid:  # slot-grid Pallas plan (grid_spmv.py); m1 = plan
            from raft_tpu.sparse.grid_spmv import spmv as grid_apply

            out = grid_apply(m1, v)
        elif use_ell:
            out = jnp.sum(m2 * v[m1], axis=1)
        else:
            out = _spmv_kernel(m1, m2, m3, v, n)
        if use_rank1:
            u, wv, alpha = rank1
            out = out + alpha * u * jnp.dot(wv, v)
        return out

    def step(j, carry):
        basis, v, alphas, betas, brk, key, scale = carry
        basis = basis.at[j].set(v)
        w = do_spmv(v)
        # operator-scale estimate: running max of ‖A·v‖ over unit v —
        # scale-invariant (a 1e-4-norm Laplacian must behave exactly like
        # its unit-norm scaling; a constant floor would not)
        scale = jnp.maximum(scale, jnp.linalg.norm(w))
        w, c1 = _orthogonalize(w, basis)
        w, c2 = _orthogonalize(w, basis)     # second pass for f32
        alpha = c1[j] + c2[j]
        b = jnp.linalg.norm(w)
        key, sub = jax.random.split(key)
        # RELATIVE breakdown test: when b ≲ √eps·scale the residual is
        # orthogonalization noise — normalizing it would amplify the
        # non-orthogonal component by 1/b and corrupt the basis (observed:
        # highly symmetric graphs exhaust their ~10 distinct eigenvalues
        # well before ncv, betas decay to 1e-5 ≫ the old 1e-10 absolute
        # threshold, and Ritz values exploded to ±435 on a matrix with
        # ‖A‖ ≤ 2). A tiny TRUE coupling at this scale means the subspace
        # is numerically invariant; continuing from a fresh random
        # direction is the correct thick-restart behavior either way.
        tol_b = (jnp.sqrt(jnp.finfo(dtype).eps)
                 * jnp.maximum(scale, jnp.finfo(dtype).tiny * 1e4))
        bad = b < tol_b

        def breakdown(_):
            w2 = jax.random.normal(sub, (n,), dtype)
            w2, _ = _orthogonalize(w2, basis)
            w2, _ = _orthogonalize(w2, basis)
            return w2, jnp.linalg.norm(w2)

        w, b_div = lax.cond(bad, breakdown, lambda _: (w, b), None)
        alphas = alphas.at[j].set(alpha)
        betas = betas.at[j].set(b)           # pre-recovery coupling
        brk = brk.at[j].set(bad)
        v = w / b_div
        return basis, v, alphas, betas, brk, key, scale

    init = (basis, v, jnp.zeros((ncv,), dtype), jnp.zeros((ncv,), dtype),
            jnp.zeros((ncv,), jnp.bool_), key, jnp.zeros((), dtype))
    basis, v, alphas, betas, brk, _, _ = lax.fori_loop(
        j_start, ncv, step, init)
    return basis, jnp.stack([alphas, betas]), brk, v


# ---------------------------------------------------------------------------
# compiled restart chunks (runtime/compiled_driver): sync_every > 1 runs
# a chunk of thick restarts as ONE device program with a donated carry —
# the Ritz solve, convergence test, QR and re-extension all in-graph
# ---------------------------------------------------------------------------


def _ritz_order_device(evals, which: str):
    """In-graph twin of :func:`_np_ritz_order` (``which`` is static)."""
    if which == "LM":
        return jnp.argsort(-jnp.abs(evals))
    if which == "SM":
        return jnp.argsort(jnp.abs(evals))
    if which == "LA":
        return jnp.argsort(-evals)
    return jnp.argsort(evals)


def _fill_t_extension(t, ab, k: int, ncv: int):
    """Write the extension's tridiagonal entries (rows [k, ncv), zeroed
    by the restart) into the projected matrix — the in-graph twin of the
    host ``extend()``'s fill loop over ``ab_h``."""
    alphas = ab[0].astype(t.dtype)
    betas = ab[1].astype(t.dtype)
    idx = jnp.arange(ncv)
    t = t.at[idx, idx].add(jnp.where(idx >= k, alphas, 0.0))
    off = jnp.where((idx >= k) & (idx < ncv - 1), betas, 0.0)[:-1]
    t = t.at[idx[:-1], idx[:-1] + 1].add(off)
    t = t.at[idx[:-1] + 1, idx[:-1]].add(off)
    return t


def _restart_step_device(mat_args, r1, carry, *, k: int, ncv: int,
                         n: int, which: str, tol: float,
                         max_iterations: int, seed: int, use_ell: bool,
                         use_grid: bool, use_dense: bool,
                         use_rank1: bool):
    """One host-loop iteration of :func:`_restart_loop`, entirely
    in-graph: Ritz solve of the carried projected matrix, the residual
    convergence test, and — unless converged or out of budget — the
    thick restart (QR with the host path's positive-diagonal sign
    convention) plus the next basis extension. ``carry.it`` counts
    consumed outer iterations, so at exit ``carry.it == n_iter``."""
    basis, t, v, beta_last, it, brk_count = carry
    evals, evecs = jnp.linalg.eigh(t)
    keep = _ritz_order_device(evals, which)[:k]
    ritz_vals = evals[keep]
    s = evecs[:, keep]
    residuals = jnp.abs(beta_last * s[-1, :])
    conv = jnp.max(residuals) < tol
    # the host loop never restarts on its LAST iteration — it finalizes
    # from the top-of-iteration state; mirror that so the carry handed
    # back for the host finalize is the same state
    done = conv | (it >= max_iterations - 1)

    def restart(args):
        basis, t, v, beta_last, brk_count = args
        ritz_vecs = basis.T @ s.astype(basis.dtype)
        q, r = jnp.linalg.qr(ritz_vecs)
        signs = jnp.sign(jnp.diagonal(r))
        signs = jnp.where(signs == 0, 1.0, signs)
        q = q * signs[None, :]                  # keep original directions
        basis = jnp.zeros_like(basis).at[:k].set(q.T)
        border = beta_last * s[-1, :]
        # soft locking, as in the host loop
        border = jnp.where(jnp.abs(border) < tol, 0.0, border)
        t = jnp.zeros_like(t)
        t = t.at[jnp.arange(k), jnp.arange(k)].set(
            ritz_vals.astype(t.dtype))
        t = t.at[:k, k].set(border.astype(t.dtype))
        t = t.at[k, :k].set(border.astype(t.dtype))
        key = jax.random.key(seed + 7919 * (it + 1) + k)
        basis, ab, brk, v = _extend_device(
            *mat_args, basis, v, key, k, ncv, n, use_ell, rank1=r1,
            use_rank1=use_rank1, use_grid=use_grid, use_dense=use_dense)
        t = _fill_t_extension(t, ab, k, ncv)
        beta_last = jnp.where(brk[ncv - 1], 0.0,
                              ab[1, ncv - 1]).astype(beta_last.dtype)
        brk_count = brk_count + jnp.sum(brk[k:]).astype(brk_count.dtype)
        return basis, t, v, beta_last, brk_count

    basis, t, v, beta_last, brk_count = lax.cond(
        done, lambda a: a, restart, (basis, t, v, beta_last, brk_count))
    return (basis, t, v, beta_last, it + 1, brk_count), done


@functools.partial(
    jax.jit,
    static_argnames=("k", "ncv", "n", "which", "tol", "max_iterations",
                     "seed", "use_ell", "use_grid", "use_dense",
                     "use_rank1"),
    donate_argnums=(4,))
def _eigsh_chunk(m1, m2, m3, r1, carry, steps, *, k: int, ncv: int,
                 n: int, which: str, tol: float, max_iterations: int,
                 seed: int, use_ell: bool, use_grid: bool,
                 use_dense: bool, use_rank1: bool):
    """Up to ``steps`` thick restarts as one device program (donated
    carry) — the compiled twin of the single-device restart loop."""
    from raft_tpu.runtime.compiled_driver import chunk_while

    def step(carry):
        return _restart_step_device(
            (m1, m2, m3), r1, carry, k=k, ncv=ncv, n=n, which=which,
            tol=tol, max_iterations=max_iterations, seed=seed,
            use_ell=use_ell, use_grid=use_grid, use_dense=use_dense,
            use_rank1=use_rank1)

    return chunk_while(step, carry, steps)


def _lanczos_sentinel(carry, steps_done: int):
    """Guard-mode boundary check for the compiled restart chunks: the
    carried residual coupling must stay finite — a NaN here means the
    basis degenerated, surfaced as the typed error at the chunk boundary
    instead of NaN Ritz pairs at the end."""
    from raft_tpu.core.guards import NonFiniteError

    beta = float(np.asarray(carry[3]))
    if not np.isfinite(beta):
        raise NonFiniteError(
            f"lanczos: non-finite residual coupling {beta!r} at compiled "
            f"chunk boundary (restart {steps_done})",
            op="sparse.solver.lanczos")


@with_matmul_precision
def lanczos_compute_eigenpairs(res, a, config: LanczosConfig,
                               v0: Optional[jnp.ndarray] = None,
                               rank1=None,
                               return_report: bool = False,
                               sync_every: Optional[int] = None
                               ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Compute k eigenpairs of symmetric sparse A
    (ref: sparse/solver/lanczos.cuh:34-86, CSR/COO overloads).

    ``rank1`` = (u, w, alpha): solve for A + alpha·u·wᵀ instead, applied
    matrix-free inside the device loop (the modularity matrix's form).

    ``sync_every``: with n > 1, chunks of n thick restarts run as ONE
    jitted program with a donated carry — Ritz solve, convergence test,
    QR and re-extension in-graph, host touched once per chunk (see
    :mod:`raft_tpu.runtime.compiled_driver`). ``sync_every=1`` is the
    host-driven restart loop, bit-for-bit; ``None`` asks the cost
    model (1 on CPU, 8–16 on an accelerator).

    Returns (eigenvalues [k], eigenvectors [n, k]) sorted per `which`;
    with ``return_report=True`` a third element, the
    :class:`~raft_tpu.core.guards.ConvergenceReport` (converged, n_iter,
    max Ritz residual, β≈0 breakdown-restart count)."""
    if isinstance(a, COOMatrix):
        from raft_tpu.sparse import op as sparse_op
        a = convert.sorted_coo_to_csr(sparse_op.coo_sort(a))
    # dense symmetric operators ride the same restart loop (eig_sel path)
    with obs.span("sparse.solver.eigsh", n=int(a.shape[0]),
                  k=int(config.n_components)):
        w, v, report = _eigsh_csr(a, config, v0, rank1=rank1,
                                  sync_every=sync_every)
    if return_report:
        return w, v, report
    return w, v


@with_matmul_precision
def eigsh(a, k: int = 6, which: str = "SA", v0=None, ncv: int = 0,
          maxiter: int = 1000, tol: float = 1e-7, seed: int = 42,
          res=None, strict: bool = False, return_report: bool = False,
          sync_every: Optional[int] = None
          ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """scipy-compatible front-end (ref: pylibraft sparse/linalg/lanczos.pyx:85
    `eigsh`).

    ``strict=True`` raises
    :class:`~raft_tpu.core.guards.ConvergenceError` when the restart
    budget is exhausted (instead of the warn-and-return reference
    parity); ``return_report=True`` appends the
    :class:`~raft_tpu.core.guards.ConvergenceReport` to the result."""
    from raft_tpu.util.input_validation import expect_finite

    if isinstance(a, (CSRMatrix, COOMatrix)):
        expect_finite(a.data, name="eigsh: A.data")
    else:
        from raft_tpu.util.input_validation import expect_square

        arr = jnp.asarray(a)
        expect_square(arr, name="eigsh: A")
        expect_finite(arr, name="eigsh: A")
    if v0 is not None:
        expect_finite(jnp.asarray(v0), name="eigsh: v0")
    cfg = LanczosConfig(n_components=k, max_iterations=maxiter, ncv=ncv,
                        tolerance=tol, which=which.upper(), seed=seed,
                        strict=strict)
    return lanczos_compute_eigenpairs(res, a, cfg, v0,
                                      return_report=return_report,
                                      sync_every=sync_every)


def _eigsh_csr(csr, cfg: LanczosConfig, v0,
               rank1=None, sync_every: Optional[int] = None) -> Tuple:
    """Thick-restart driver. ``csr`` may also be a DENSE symmetric array:
    the same restart loop then runs on an MXU matvec — the eig_sel subset
    path (ref: syevdx), which needs k extremal pairs of a dense matrix
    without materializing the full spectrum."""
    dense = not isinstance(csr, CSRMatrix)
    n = csr.shape[0]
    k = cfg.n_components
    if k <= 0 or k >= n:
        raise ValueError(f"need 0 < n_components < n, got {k} vs {n}")
    if cfg.max_iterations < 1:
        raise ValueError(
            f"max_iterations must be >= 1, got {cfg.max_iterations}")
    ncv = cfg.ncv if cfg.ncv else min(n, max(2 * k + 1, 20))
    ncv = min(max(ncv, k + 2), n)
    which = cfg.which
    if which not in ("LA", "LM", "SA", "SM"):
        raise ValueError(f"which must be LA|LM|SA|SM, got {which}")

    dtype = jnp.float32
    r1 = None if rank1 is None else tuple(
        jnp.asarray(x, dtype) for x in rank1[:2]) + (
        jnp.asarray(rank1[2], dtype),)
    from raft_tpu.sparse.ell import maybe_ell
    from raft_tpu.sparse.linalg import spmv_method

    use_ell = use_grid = use_dense = False
    method = None if dense else spmv_method(csr)
    if dense:
        mat_args = (jnp.asarray(csr, dtype), jnp.zeros((), dtype),
                    jnp.zeros((), dtype))
        use_dense = True
    elif method == "grid":
        # slot-grid Pallas plan via the shared per-matrix cache: the auto
        # decision in spmv_method has already built AND pad-ratio-gated
        # the plan (ADVICE r4 — a scattered pattern whose slot grid blows
        # past 8x nnz never reaches here on auto), so this reuses it; a
        # forced RAFT_TPU_SPMV=grid builds through the same cache (the
        # cusparseSpMV_preprocess amortization of detail/lanczos.cuh:603)
        from raft_tpu.sparse.linalg import _cached_plan

        mat_args = (_cached_plan(csr), jnp.zeros((), dtype),
                    jnp.zeros((), dtype))
        use_grid = True
    else:
        if method == "ell":   # forced: honor unconditionally (linalg.spmv
            from raft_tpu.sparse.ell import from_csr    # parity)

            ell = from_csr(csr)
        else:
            ell = maybe_ell(csr) if method == "auto" else None
        if ell is not None:   # regular sparsity → scatter-free slab SpMV
            mat_args = (ell.cols, ell.data.astype(dtype),
                        jnp.zeros((), dtype))
            use_ell = True
        else:
            mat_args = (csr.row_ids(), csr.indices,
                        csr.data.astype(dtype))

    if v0 is None:
        rng = np.random.default_rng(cfg.seed)
        v = jnp.asarray(rng.standard_normal(n), dtype=dtype)
    else:
        v = jnp.asarray(v0, dtype=dtype)
        if resolve_guard_mode() != "off":
            nv = float(jnp.linalg.norm(v))
            if not nv > 0 or not np.isfinite(nv):
                raise IllConditionedError(
                    f"eigsh: starting vector v0 has norm {nv!r} — cannot "
                    "normalize a zero/non-finite direction",
                    op="sparse.solver.eigsh")
    v = v / jnp.linalg.norm(v)   # guarded: v0 validated above; random
    #                              v0 has unit-scale norm by construction

    basis = jnp.zeros((ncv, n), dtype=dtype)
    t = np.zeros((ncv, ncv), dtype=np.float64)   # projected matrix
    stats = {"breakdowns": 0}

    def extend(j_start: int, basis, t, v, it: int):
        """Device-batched Lanczos steps for rows [j_start, ncv); one small
        device→host fetch fills the tridiagonal entries of t."""
        key = jax.random.key(cfg.seed + 7919 * (it + 1) + j_start)
        basis, ab, brk, v = _extend_device(
            *mat_args, basis, v, key, j_start, ncv, n, use_ell,
            rank1=r1, use_rank1=r1 is not None, use_grid=use_grid,
            use_dense=use_dense)
        ab_h = np.asarray(ab, dtype=np.float64)   # the fetch: [2, ncv]
        brk_h = np.asarray(brk)
        # classify β≈0 restarts: recovered-from breakdowns, not failures —
        # counted into the ConvergenceReport and traced (ISSUE 3)
        n_brk = int(brk_h[j_start:].sum())
        if n_brk:
            stats["breakdowns"] += n_brk
            trace.record_event("lanczos.breakdown", iteration=it,
                               count=n_brk)
        for j in range(j_start, ncv):
            t[j, j] = ab_h[0, j]
            if j + 1 < ncv:
                t[j, j + 1] = t[j + 1, j] = ab_h[1, j]
        # exact invariant subspace at the last step → no outside coupling
        beta_last = 0.0 if brk_h[ncv - 1] else float(ab_h[1, ncv - 1])
        return basis, t, beta_last, v

    from raft_tpu.runtime import compiled_driver

    sync = compiled_driver.resolve_sync_every(sync_every)
    if sync > 1:
        from raft_tpu.runtime import limits

        acc = compiled_driver.host_float_dtype()
        # initial basis growth stays host-driven (fills t rows [0, ncv));
        # the compiled chunks take over at the first restart
        basis, t, beta_last, v = extend(0, basis, t, v, it=-1)
        carry = (basis, jnp.asarray(t, acc), v,
                 jnp.asarray(beta_last, acc),
                 jnp.asarray(0, jnp.int32), jnp.asarray(0, jnp.int32))
        chunk_call = functools.partial(
            _eigsh_chunk, *mat_args, r1, k=k, ncv=ncv, n=n, which=which,
            tol=float(cfg.tolerance), max_iterations=cfg.max_iterations,
            seed=cfg.seed, use_ell=use_ell, use_grid=use_grid,
            use_dense=use_dense, use_rank1=r1 is not None)
        nnz = n * n if dense else int(csr.data.shape[0])
        dims = dict(n=n, ncv=ncv, nnz=max(nnz, 1), k=k)
        est = limits.estimate_seconds("sparse.lanczos_restart", **dims)
        sf, sb = limits.estimate_flops_bytes("sparse.lanczos_restart",
                                             **dims)
        carry, _, _ = compiled_driver.run_chunked(
            chunk_call, carry, max_steps=cfg.max_iterations,
            sync_every=sync, op="sparse.solver.lanczos",
            est_step_seconds=est, step_flops=sf, step_bytes=sb,
            sentinel=_lanczos_sentinel)
        basis = carry[0]
        t_h = np.asarray(carry[1], np.float64)
        beta_last = float(np.asarray(carry[3]))
        n_iter = int(np.asarray(carry[4]))
        n_brk = int(np.asarray(carry[5]))
        if n_brk:
            stats["breakdowns"] += n_brk
            trace.record_event("lanczos.breakdown", iteration=n_iter,
                               count=n_brk)
        return _finalize_ritz(basis, t_h, beta_last, n_iter, cfg, k,
                              which, dtype, stats=stats)

    return _restart_loop(extend, basis, t, v, cfg, k, ncv, which, dtype,
                         stats=stats)


def _np_ritz_order(evals, which: str):
    """Ritz selection order per ``which`` (ref: lanczos_solve_ritz
    detail/lanczos.cuh:182-223 — SM/LM sort by magnitude inside the
    Krylov space; no spectral shift), shared by the host restart loop
    and the compiled chunk's finalize."""
    if which == "LM":
        return np.argsort(-np.abs(evals))
    if which == "SM":
        return np.argsort(np.abs(evals))
    if which == "LA":
        return np.argsort(-evals)
    return np.argsort(evals)


def _finalize_ritz(basis, t, beta_last, n_iter, cfg, k, which, dtype,
                   stats=None):
    """Host float64 Ritz epilogue shared by the host-driven restart loop
    and the compiled-chunk drivers: solve the projected problem, test
    convergence, back-transform the kept pairs, and build the
    :class:`~raft_tpu.core.guards.ConvergenceReport` (warn or raise per
    ``cfg.strict`` on an exhausted budget)."""
    evals, evecs = np.linalg.eigh(t)
    keep = _np_ritz_order(evals, which)[:k]
    ritz_vals = evals[keep]
    s = evecs[:, keep]                          # [ncv, k]
    residuals = np.abs(beta_last * s[-1, :])
    converged = float(residuals.max()) < cfg.tolerance
    report = ConvergenceReport(
        converged=converged, n_iter=n_iter,
        residual=float(residuals.max()), tol=float(cfg.tolerance),
        breakdowns=0 if stats is None
        else int(stats.get("breakdowns", 0)))
    obs.record_convergence("sparse.solver.lanczos", report)
    if not converged:
        if getattr(cfg, "strict", False):
            raise ConvergenceError(
                f"lanczos: max_iterations={cfg.max_iterations} "
                f"reached with residual {report.residual:.3e} > "
                f"tol {cfg.tolerance:.3e} (strict=True)",
                report=report, op="sparse.solver.lanczos")
        # Reference parity: lanczos_smallest exits its
        # `while (res > tol && iter < maxIter)` loop and returns the
        # best available pairs without throwing
        # (detail/lanczos.cuh:537); we surface it via the logger.
        logger.warn(
            "lanczos: max_iterations=%d reached with residual %.3e "
            "> tol %.3e; returning unconverged eigenpairs",
            cfg.max_iterations, float(residuals.max()),
            cfg.tolerance)
    ritz_vecs = basis.T @ jnp.asarray(s, dtype=dtype)
    # normalize (f32 drift) and sort ascending like scipy eigsh;
    # Ritz columns come from an orthonormal-by-construction basis
    # and soft locking keeps directions nonzero
    ritz_vecs = ritz_vecs / jnp.linalg.norm(ritz_vecs, axis=0)  # guarded: orthonormal basis
    asc = np.argsort(ritz_vals)
    return (jnp.asarray(ritz_vals[asc], dtype=dtype),
            ritz_vecs[:, asc], report)


def _restart_loop(extend, basis, t, v, cfg, k, ncv, which, dtype,
                  on_iteration=None, resume=None, stats=None):
    """Host-driven thick-restart outer loop (ref: detail/lanczos.cuh:537
    `while (res > tol && iter < maxIter)`), shared by the single-device and
    MNMG drivers: `basis` may be a mesh-sharded global array — the Ritz
    back-transform (basis.T @ s), QR and row assignments are plain XLA ops
    that GSPMD partitions along the existing sharding.

    Elastic hooks (ISSUE 2): ``on_iteration(it, basis, t, beta_last, v)``
    fires at the top of each outer iteration — the state at that point
    fully determines the rest of the run (the extension keys derive from
    (seed, it, j_start), not from an ambient RNG), which is what makes
    checkpoints taken there resume bit-for-bit.  ``resume=(it0,
    beta_last)`` skips the initial extension and re-enters the loop at
    ``it0`` with the caller-provided ``basis``/``t``/``v``.
    """
    from raft_tpu.runtime import limits

    if resume is None:
        basis, t, beta_last, v = extend(0, basis, t, v, it=-1)
        it0 = 0
    else:
        it0, beta_last = resume

    for it in range(it0, cfg.max_iterations):
        if on_iteration is not None:
            on_iteration(it, basis, t, beta_last, v)
        # deadline poll AFTER the elastic hook: an expiring deadline
        # leaves the just-saved checkpoint behind, so the caller can
        # resume_from it with a fresh budget (ISSUE 5 rides the ISSUE 2
        # checkpoint-first ordering)
        limits.check_deadline("sparse.solver.lanczos")
        evals, evecs = np.linalg.eigh(t)
        keep = _np_ritz_order(evals, which)[:k]
        ritz_vals = evals[keep]
        s = evecs[:, keep]                      # [ncv, k]
        residuals = np.abs(beta_last * s[-1, :])
        converged = float(residuals.max()) < cfg.tolerance
        if converged or it == cfg.max_iterations - 1:
            return _finalize_ritz(basis, t, beta_last, it + 1, cfg, k,
                                  which, dtype, stats=stats)

        # -- thick restart (ref: detail/lanczos.cuh:537-700) --------------
        ritz_vecs = basis.T @ jnp.asarray(s, dtype=dtype)   # [n, k]
        q, r = jnp.linalg.qr(ritz_vecs)
        signs = jnp.sign(jnp.diagonal(r))
        signs = jnp.where(signs == 0, 1.0, signs)
        q = q * signs[None, :]                  # keep original directions
        basis = jnp.zeros_like(basis).at[:k].set(q.T)
        t = np.zeros_like(t)
        t[np.arange(k), np.arange(k)] = ritz_vals
        border = beta_last * s[-1, :]           # couplings to residual row
        # Soft locking (Stathopoulos): a pair whose residual is already
        # below tol is an (numerically) exact invariant direction — zero
        # its coupling so later restarts stop perturbing it, and the
        # Krylov continuation explores only the orthogonal complement.
        # This is what lets DEGENERATE eigenvalues resolve to their full
        # multiplicity: once one copy is locked, the deflated operator's
        # extremal value is the next copy, which plain Lanczos then finds
        # as a separate Ritz pair (ADVICE r4 / VERDICT r4 #8).
        border = np.where(np.abs(border) < cfg.tolerance, 0.0, border)
        t[:k, k] = border
        t[k, :k] = border
        # Extend from row k: the device loop's first step IS the Lanczos
        # step on the residual direction (writes basis row k, t[k, k],
        # t[k, k+1]); the arrowhead border above stays host-side.
        basis, t, beta_last, v = extend(k, basis, t, v, it=it)

    raise AssertionError("unreachable: loop returns at max_iterations")


# ---------------------------------------------------------------------------
# MNMG: row-partitioned Lanczos over a device mesh (VERDICT r3 #9)
# ---------------------------------------------------------------------------

def _extend_mnmg_body(rows_l, cols_g, data_l, basis_l, v_l, key,
                      j_start: int, ncv: int, n_local: int, n_true: int,
                      axis: str, use_ell: bool = False):
    """Per-shard Lanczos extension under shard_map: each device owns a row
    band of A (local row ids, GLOBAL col ids, nnz padded per band with
    rows_l == -1) and the matching slice of every basis vector. The SpMV
    all-gathers v (the row-partitioned MNMG convention,
    ref docs/source/using_raft_comms.rst:1-40 — replicate the vector,
    partition the operator); every dot/norm is a lax.psum over the axis.

    ``use_ell``: the band arrives as row-slab arrays (cols/data
    (n_local, w), rows_l = per-row lane counts) — the scatter-free
    gather+reduce formulation maybe_ell prefers on one device, applied
    per band."""
    dtype = basis_l.dtype

    def psum(x):
        return lax.psum(x, axis)

    def do_spmv(v_l):
        v_full = lax.all_gather(v_l, axis, tiled=True)
        if use_ell:
            # rows_l: (n_local,) valid-lane counts; pad lanes masked on
            # the product (they gather v[0]; 0 * inf = nan otherwise)
            lane_ok = (jnp.arange(cols_g.shape[1], dtype=jnp.int32)[None]
                       < rows_l[:, None])
            prod = jnp.where(lane_ok, data_l * v_full[cols_g], 0.0)
            return jnp.sum(prod, axis=1)
        prod = data_l * v_full[cols_g]
        # band pads carry rows_l == -1: mask the PRODUCT (pad slots gather
        # v[0]; 0 * inf would poison row 0 of the band otherwise)
        prod = jnp.where(rows_l >= 0, prod, 0.0)
        return jax.ops.segment_sum(prod, jnp.maximum(rows_l, 0),
                                   num_segments=n_local)

    def orthogonalize(w_l, basis_l):
        coeffs = psum(basis_l @ w_l)
        return w_l - basis_l.T @ coeffs, coeffs

    def gnorm(w_l):
        return jnp.sqrt(psum(jnp.sum(w_l * w_l)))   # guarded: sum of squares

    def step(j, carry):
        basis_l, v_l, alphas, betas, brk, key, scale = carry
        basis_l = basis_l.at[j].set(v_l)
        w = do_spmv(v_l)
        scale = jnp.maximum(scale, gnorm(w))
        w, c1 = orthogonalize(w, basis_l)
        w, c2 = orthogonalize(w, basis_l)
        alpha = c1[j] + c2[j]
        b = gnorm(w)
        key, sub = jax.random.split(key)
        tol_b = (jnp.sqrt(jnp.finfo(dtype).eps)
                 * jnp.maximum(scale, jnp.finfo(dtype).tiny * 1e4))
        bad = b < tol_b

        def breakdown(_):
            shard_key = jax.random.fold_in(sub, lax.axis_index(axis))
            w2 = jax.random.normal(shard_key, (n_local,), dtype)
            # zero the PADDING rows (global row >= n_true): the padded
            # operator is diag(A, 0) and a restart direction with mass
            # there would converge onto the spurious zero eigenvalue
            grow = (lax.axis_index(axis) * n_local
                    + jnp.arange(n_local, dtype=jnp.int32))
            w2 = jnp.where(grow < n_true, w2, 0.0)
            w2, _ = orthogonalize(w2, basis_l)
            w2, _ = orthogonalize(w2, basis_l)
            return w2, gnorm(w2)

        w, b_div = lax.cond(bad, breakdown, lambda _: (w, b), None)
        alphas = alphas.at[j].set(alpha)
        betas = betas.at[j].set(b)
        brk = brk.at[j].set(bad)
        v_l = w / b_div
        return basis_l, v_l, alphas, betas, brk, key, scale

    # alphas/betas/brk/scale are psum products — replicated (invariant
    # over the mesh axis), so the carry stays consistent without pcasts
    # and the P() out_specs hold
    init = (basis_l, v_l, jnp.zeros((ncv,), dtype),
            jnp.zeros((ncv,), dtype), jnp.zeros((ncv,), jnp.bool_),
            key, jnp.zeros((), dtype))
    basis_l, v_l, alphas, betas, brk, _, _ = lax.fori_loop(
        j_start, ncv, step, init)
    return basis_l, jnp.stack([alphas, betas]), brk, v_l


def _cholqr2(a_l, axis: str):
    """Distributed thin QR of a row-sharded [n_local, k] block via
    CholeskyQR2 (two rounds — enough for f32 at the k ≪ n shapes here):
    Gram ``psum`` → Cholesky → triangular solve, twice. The implicit R
    (a product of Cholesky factors) has a positive diagonal, which is
    exactly the convention the host restart path enforces by sign-fixing
    Householder QR — so the compiled MNMG restart reproduces the same Q
    without a collectives-hostile Householder factorization."""
    from jax.scipy.linalg import solve_triangular

    def one_round(q_l):
        g = lax.psum(q_l.T @ q_l, axis)
        ell = jnp.linalg.cholesky(g)
        return solve_triangular(ell, q_l.T, lower=True).T

    return one_round(one_round(a_l))


def _mnmg_restart_step(rows_l, cols_g, data_l, carry, *, k: int,
                       ncv: int, n_local: int, n_true: int, axis: str,
                       use_ell: bool, which: str, tol: float,
                       max_iterations: int, seed: int):
    """One outer restart of the MNMG loop inside a ``shard_map`` body —
    the sharded twin of :func:`_restart_step_device`: the projected
    solve and convergence test run replicated (the carry's ``t`` and
    ``beta_last`` are psum products), the Ritz back-transform and QR
    stay row-sharded (:func:`_cholqr2`), and the re-extension is the
    same :func:`_extend_mnmg_body` the host loop shard_maps."""
    basis_l, t, v_l, beta_last, it, brk_count = carry
    evals, evecs = jnp.linalg.eigh(t)
    keep = _ritz_order_device(evals, which)[:k]
    ritz_vals = evals[keep]
    s = evecs[:, keep]
    residuals = jnp.abs(beta_last * s[-1, :])
    conv = jnp.max(residuals) < tol
    done = conv | (it >= max_iterations - 1)

    def restart(args):
        basis_l, t, v_l, beta_last, brk_count = args
        ritz_l = basis_l.T @ s.astype(basis_l.dtype)    # [n_local, k]
        q_l = _cholqr2(ritz_l, axis)
        basis_l = jnp.zeros_like(basis_l).at[:k].set(q_l.T)
        border = beta_last * s[-1, :]
        border = jnp.where(jnp.abs(border) < tol, 0.0, border)
        t = jnp.zeros_like(t)
        t = t.at[jnp.arange(k), jnp.arange(k)].set(
            ritz_vals.astype(t.dtype))
        t = t.at[:k, k].set(border.astype(t.dtype))
        t = t.at[k, :k].set(border.astype(t.dtype))
        key = jax.random.key(seed + 7919 * (it + 1) + k)
        basis_l, ab, brk, v_l = _extend_mnmg_body(
            rows_l, cols_g, data_l, basis_l, v_l, key, j_start=k,
            ncv=ncv, n_local=n_local, n_true=n_true, axis=axis,
            use_ell=use_ell)
        t = _fill_t_extension(t, ab, k, ncv)
        beta_last = jnp.where(brk[ncv - 1], 0.0,
                              ab[1, ncv - 1]).astype(beta_last.dtype)
        brk_count = brk_count + jnp.sum(brk[k:]).astype(brk_count.dtype)
        return basis_l, t, v_l, beta_last, brk_count

    basis_l, t, v_l, beta_last, brk_count = lax.cond(
        done, lambda a: a, restart,
        (basis_l, t, v_l, beta_last, brk_count))
    return (basis_l, t, v_l, beta_last, it + 1, brk_count), done


def eigsh_mnmg(a, k: int = 6, mesh=None, axis: str = "data",
               which: str = "SA", v0=None, ncv: int = 0,
               maxiter: int = 1000, tol: float = 1e-7,
               seed: int = 42, comms=None,
               checkpoint_every: Optional[int] = None,
               checkpoint_dir: Optional[str] = None,
               checkpoint_keep: int = 2,
               resume_from: Optional[str] = None,
               strict: bool = False,
               return_report: bool = False,
               sync_every: Optional[int] = None
               ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Multi-device eigsh: A row-partitioned over ``mesh[axis]``, the
    Lanczos extension shard_mapped (SpMV = local band product over an
    all-gathered v; dots/norms psum'd), the restart loop's dense algebra
    GSPMD-partitioned along the basis sharding.

    Composes BASELINE config 4 with config 5's mesh: the same row-band
    convention as the MNMG k-means/kNN paths
    (ref: docs/source/using_raft_comms.rst:1-40).

    Elastic execution (ISSUE 2): ``checkpoint_every=n`` saves restart
    state (unpadded basis, projected matrix t, residual vector,
    beta_last, iteration) every n-th outer restart; with a ``comms``
    clique attached, each restart health-checks the peers, and on a
    failure the survivors agree → shrink → the row bands are REBUILT
    for the smaller device count (n_local = ceil(n / n_dev) changes) →
    the last checkpoint resumes the restart loop on fewer ranks.
    ``resume_from`` accepts a checkpoint file or directory."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from raft_tpu.comms.errors import CommsAbortedError, PeerFailedError
    from raft_tpu.core import checkpoint as core_ckpt

    if mesh is None:
        raise ValueError("eigsh_mnmg requires a jax.sharding.Mesh")
    csr = a
    if isinstance(csr, COOMatrix):
        from raft_tpu.sparse import op as sparse_op
        csr = convert.sorted_coo_to_csr(sparse_op.coo_sort(csr))
    n = csr.n_rows
    cfg = LanczosConfig(n_components=k, max_iterations=maxiter, ncv=ncv,
                        tolerance=tol, which=which.upper(), seed=seed,
                        strict=strict)
    if k <= 0 or k >= n:
        raise ValueError(f"need 0 < k < n, got {k} vs {n}")
    if cfg.max_iterations < 1:
        raise ValueError(
            f"max_iterations must be >= 1, got {cfg.max_iterations}")
    ncv = cfg.ncv if cfg.ncv else min(n, max(2 * k + 1, 20))
    ncv = min(max(ncv, k + 2), n)
    which = cfg.which
    if which not in ("LA", "LM", "SA", "SM"):
        raise ValueError(f"which must be LA|LM|SA|SM, got {which}")
    dtype = jnp.float32

    from raft_tpu.util.math import cdiv

    manager = None
    if checkpoint_every is not None:
        if checkpoint_dir is None:
            raise ValueError("checkpoint_every requires checkpoint_dir")
        manager = core_ckpt.CheckpointManager(
            checkpoint_dir, prefix="eigsh", keep=checkpoint_keep)

    rows_h, cols_h, data_h = csr.host_edges()
    data_h = data_h.astype(np.float32)

    from raft_tpu.runtime import compiled_driver

    sync = compiled_driver.resolve_sync_every(sync_every)

    def build_extend(cur_mesh):
        """Everything that depends on the device count, bundled so a
        post-shrink survivor mesh can rebuild it: row bands with equal
        local size + equal padded nnz, the jitted shard_map extension,
        and `place` to (re-)pad host state onto the mesh. `n_local =
        ceil(n / n_dev)` changes when the mesh shrinks, so the band
        layout and padding are NOT reusable across meshes — but the
        unpadded state (basis[:, :n], v[:n]) is, because padding rows
        of the operator are zero and every code path (initial v, spmv,
        breakdown restarts) keeps the padded slots exactly 0."""
        n_dev = cur_mesh.shape[axis]
        n_local = cdiv(n, n_dev)
        n_pad = n_local * n_dev
        band = rows_h // n_local

        shard = NamedSharding(cur_mesh, P(axis))
        # Per-band ELL slab when the padding trade is favorable (the same
        # <= 4x stored/actual gate as maybe_ell): gather + dense row
        # reduce, no scatter — otherwise the segment-sum band formulation.
        from raft_tpu.sparse.ell import MAX_AUTO_PADDING

        row_len_h = np.zeros(n_pad, np.int64)
        np.add.at(row_len_h, rows_h, 1)
        width = int(row_len_h.max()) if len(rows_h) else 0
        width = max(8 * -(-max(width, 1) // 8), 8)
        use_ell = (len(rows_h) > 0
                   and n_pad * width <= MAX_AUTO_PADDING * len(rows_h))
        if use_ell:
            cols_e = np.zeros((n_pad, width), np.int32)
            data_e = np.zeros((n_pad, width), np.float32)
            lanes = (np.arange(len(rows_h))
                     - np.concatenate([[0], np.cumsum(row_len_h)[:-1]]
                                      )[rows_h])
            cols_e[rows_h, lanes] = cols_h
            data_e[rows_h, lanes] = data_h
            rows_g = jax.device_put(
                jnp.asarray(row_len_h.astype(np.int32)), shard)
            cols_g = jax.device_put(jnp.asarray(cols_e), shard)
            data_g = jax.device_put(jnp.asarray(data_e), shard)
        else:
            counts = np.bincount(band, minlength=n_dev)
            nnz_max = max(int(counts.max()), 1)
            rows_b = np.full((n_dev, nnz_max), -1, np.int32)
            cols_b = np.zeros((n_dev, nnz_max), np.int32)
            data_b = np.zeros((n_dev, nnz_max), np.float32)
            for d in range(n_dev):
                m = band == d
                c = int(counts[d])
                rows_b[d, :c] = rows_h[m] - d * n_local
                cols_b[d, :c] = cols_h[m]
                data_b[d, :c] = data_h[m]
            rows_g = jax.device_put(rows_b.reshape(-1), shard)
            cols_g = jax.device_put(cols_b.reshape(-1), shard)
            data_g = jax.device_put(data_b.reshape(-1), shard)

        def make_extend(j_start):
            body = functools.partial(_extend_mnmg_body, j_start=j_start,
                                     ncv=ncv, n_local=n_local, n_true=n,
                                     axis=axis, use_ell=use_ell)
            return jax.jit(jax.shard_map(
                body, mesh=cur_mesh,
                in_specs=(P(axis), P(axis), P(axis), P(None, axis),
                          P(axis), P()),
                out_specs=(P(None, axis), P(), P(), P(axis))))

        extend_cache = {}

        def extend(j_start, basis, t, v, it):
            key = jax.random.key(cfg.seed + 7919 * (it + 1) + j_start)
            if j_start not in extend_cache:
                extend_cache[j_start] = make_extend(j_start)
            basis, ab, brk, v = extend_cache[j_start](
                rows_g, cols_g, data_g, basis, v, key)
            ab_h = np.asarray(ab, dtype=np.float64)
            brk_h = np.asarray(brk)
            n_brk = int(brk_h[j_start:].sum())
            if n_brk:
                stats["breakdowns"] += n_brk
                trace.record_event("lanczos.breakdown", iteration=it,
                                   count=n_brk)
            for j in range(j_start, ncv):
                t[j, j] = ab_h[0, j]
                if j + 1 < ncv:
                    t[j, j + 1] = t[j + 1, j] = ab_h[1, j]
            beta_last = 0.0 if brk_h[ncv - 1] else float(ab_h[1, ncv - 1])
            return basis, t, beta_last, v

        def place(basis_h, v_h):
            b = np.zeros((ncv, n_pad), np.float32)
            b[:, :n] = basis_h
            vp = np.zeros(n_pad, np.float32)
            vp[:n] = v_h
            return (jax.device_put(jnp.asarray(b),
                                   NamedSharding(cur_mesh, P(None, axis))),
                    jax.device_put(jnp.asarray(vp), shard))

        run_chunk = None
        if sync > 1:
            from raft_tpu.runtime.compiled_driver import chunk_while

            restart_body = functools.partial(
                _mnmg_restart_step, k=k, ncv=ncv, n_local=n_local,
                n_true=n, axis=axis, use_ell=use_ell, which=which,
                tol=float(cfg.tolerance),
                max_iterations=cfg.max_iterations, seed=cfg.seed)

            def chunk_body(rows_l, cols_l, data_l, carry, steps):
                def one(car):
                    return restart_body(rows_l, cols_l, data_l, car)

                return chunk_while(one, carry, steps)

            # carry = (basis_l, t, v_l, beta_last, it, brk_count): t and
            # the scalars are psum products — replicated, P() holds
            carry_specs = (P(None, axis), P(), P(axis), P(), P(), P())
            chunk = jax.jit(jax.shard_map(
                chunk_body, mesh=cur_mesh,
                in_specs=(P(axis), P(axis), P(axis), carry_specs, P()),
                out_specs=(carry_specs, P(), P())),
                donate_argnums=(3,))

            def run_chunk(carry, steps):
                return chunk(rows_g, cols_g, data_g, carry, steps)

        return extend, place, run_chunk

    t = np.zeros((ncv, ncv), dtype=np.float64)
    stats = {"breakdowns": 0}
    resume = None
    if resume_from is not None:
        entries = _load_eigsh_checkpoint(resume_from)
        basis_h = np.asarray(entries["basis"], np.float32)
        v_h = np.asarray(entries["v"], np.float32)
        t = np.asarray(entries["t"], np.float64).copy()
        resume = (int(entries["it"]), float(entries["beta_last"]))
    else:
        rng = np.random.default_rng(cfg.seed)
        v_h = (np.asarray(v0, np.float32) if v0 is not None
               else rng.standard_normal(n).astype(np.float32))
        v_h = v_h / np.linalg.norm(v_h)
        basis_h = np.zeros((ncv, n), np.float32)

    extend, place, run_chunk = build_extend(mesh)
    basis, v = place(basis_h, v_h)
    ckpt_stride = (max(1, int(checkpoint_every))
                   if checkpoint_every is not None else None)

    if sync > 1:
        from raft_tpu.runtime import limits

        acc = compiled_driver.host_float_dtype()
        if resume is None:
            # initial basis growth stays host-driven; chunks take over
            # at the first restart
            basis, t, beta_last, v = extend(0, basis, t, v, it=-1)
            it0 = 0
        else:
            it0, beta_last = resume
        carry = (basis, jnp.asarray(t, acc), v,
                 jnp.asarray(beta_last, acc),
                 jnp.asarray(it0, jnp.int32), jnp.asarray(0, jnp.int32))
        n_iter = it0
        last_saved = [it0 if resume_from is not None else -1]
        dims = dict(n=n, ncv=ncv, nnz=max(len(rows_h), 1), k=k)
        est = limits.estimate_seconds("sparse.lanczos_restart", **dims)
        sf, sb = limits.estimate_flops_bytes("sparse.lanczos_restart",
                                             **dims)

        def boundary(cr, steps_done, done_flag):
            # checkpoint FIRST, then health-probe — the on_iteration
            # ordering of the host loop, at chunk granularity; the saved
            # entries use the same format, so resume_from round-trips
            # between the host-driven and compiled paths
            if manager is not None and (
                    (last_saved[0] < 0 and steps_done == 0)
                    or steps_done - max(last_saved[0], 0) >= ckpt_stride):
                manager.save(steps_done, {
                    "basis": np.asarray(cr[0])[:, :n],
                    "t": np.asarray(cr[1], np.float64),
                    "v": np.asarray(cr[2])[:n],
                    "beta_last": float(np.asarray(cr[3])),
                    "it": int(steps_done),
                })
                last_saved[0] = steps_done
            if comms is not None:
                comms.ensure_healthy()

        while True:
            try:
                carry, n_iter, _ = compiled_driver.run_chunked(
                    run_chunk, carry, max_steps=cfg.max_iterations,
                    sync_every=sync, op="sparse.solver.lanczos",
                    steps_done=n_iter, est_step_seconds=est,
                    step_flops=sf, step_bytes=sb,
                    boundary=boundary, sentinel=_lanczos_sentinel)
                break
            except (PeerFailedError, CommsAbortedError) as err:
                if comms is None or manager is None:
                    raise
                latest = manager.restore_latest()
                if latest is None:
                    raise
                step, entries = latest
                survivors = comms.agree_on_survivors()
                comms = comms.shrink(survivors)
                mesh = comms.mesh
                logger.warn(
                    "eigsh_mnmg: peer failure (%s); resuming restart "
                    "%d on %d survivors", err, step, len(survivors))
                trace.record_event("eigsh.elastic_resume", step=step,
                                   survivors=len(survivors))
                extend, place, run_chunk = build_extend(mesh)
                basis, v = place(
                    np.asarray(entries["basis"], np.float32),
                    np.asarray(entries["v"], np.float32))
                n_iter = int(entries["it"])
                last_saved[0] = n_iter
                carry = (basis,
                         jnp.asarray(np.asarray(entries["t"],
                                                np.float64), acc),
                         v, jnp.asarray(float(entries["beta_last"]), acc),
                         jnp.asarray(n_iter, jnp.int32),
                         jnp.asarray(0, jnp.int32))
        basis = carry[0]
        t_h = np.asarray(carry[1], np.float64)
        beta_last = float(np.asarray(carry[3]))
        n_brk = int(np.asarray(carry[5]))
        if n_brk:
            stats["breakdowns"] += n_brk
            trace.record_event("lanczos.breakdown", iteration=n_iter,
                               count=n_brk)
        w, vecs, report = _finalize_ritz(
            basis, t_h, beta_last, int(np.asarray(carry[4])), cfg, k,
            which, dtype, stats=stats)
        if return_report:
            return w, vecs[:n], report
        return w, vecs[:n]

    def on_iteration(it, basis_d, t_d, beta_last_d, v_d):
        # checkpoint FIRST, then health-probe: a failure surfaced by the
        # probe recovers from exactly this state, so the shrunken rerun
        # and a clean resume from the same file agree bit-for-bit
        if manager is not None and it % ckpt_stride == 0:
            manager.save(it, {
                "basis": np.asarray(basis_d)[:, :n],
                "t": np.asarray(t_d, np.float64),
                "v": np.asarray(v_d)[:n],
                "beta_last": float(beta_last_d),
                "it": int(it),
            })
        if comms is not None:
            comms.ensure_healthy()

    hook = (on_iteration if (manager is not None or comms is not None)
            else None)
    while True:
        try:
            w, vecs, report = _restart_loop(extend, basis, t, v, cfg, k,
                                            ncv, which, dtype,
                                            on_iteration=hook,
                                            resume=resume, stats=stats)
            break
        except (PeerFailedError, CommsAbortedError) as err:
            if comms is None or manager is None:
                raise
            latest = manager.restore_latest()
            if latest is None:
                raise
            step, entries = latest
            survivors = comms.agree_on_survivors()
            comms = comms.shrink(survivors)
            mesh = comms.mesh
            logger.warn(
                "eigsh_mnmg: peer failure (%s); resuming restart %d on "
                "%d survivors", err, step, len(survivors))
            trace.record_event("eigsh.elastic_resume", step=step,
                               survivors=len(survivors))
            extend, place, run_chunk = build_extend(mesh)
            basis, v = place(np.asarray(entries["basis"], np.float32),
                             np.asarray(entries["v"], np.float32))
            t = np.asarray(entries["t"], np.float64).copy()
            resume = (int(entries["it"]), float(entries["beta_last"]))
    if return_report:
        return w, vecs[:n], report
    return w, vecs[:n]


def _load_eigsh_checkpoint(resume_from):
    import os

    from raft_tpu.core import checkpoint as core_ckpt

    if os.path.isdir(resume_from):
        mgr = core_ckpt.CheckpointManager(resume_from, prefix="eigsh")
        latest = mgr.restore_latest()
        if latest is None:
            raise FileNotFoundError(
                f"no eigsh checkpoints under {resume_from!r}")
        return latest[1]
    return core_ckpt.restore_checkpoint(resume_from)
