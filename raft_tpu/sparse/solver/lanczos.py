"""Thick-restart Lanczos eigensolver (ref: raft/sparse/solver/lanczos.cuh:34
`lanczos_compute_eigenpairs`, lanczos_types.hpp:20-50 config,
detail/lanczos.cuh:402 `lanczos_smallest`).

Structure mirrors the reference: a host-driven restart loop (the data-
dependent `while (res > tol && iter < maxIter)` at detail/lanczos.cuh:537)
around jitted device work.  The per-iteration hot kernel is SpMV
(cusparseSpMV at detail/lanczos.cuh:603-623 → gather+segment_sum here) plus
Gram-Schmidt dots/axpys (cublas calls :321+ → one [ncv,n]·[n] matvec on the
MXU).  The small ncv×ncv Ritz problem (`lanczos_solve_ritz`
detail/lanczos.cuh:129 via syevd) is solved on host in float64 — TPU f64 is
emulated and ncv is tiny, exactly the "f64-on-host Ritz" plan from
SURVEY.md §7.  After a thick restart the projected matrix is an arrowhead
(diagonal Ritz block bordered by residual couplings), so we keep the full
ncv×ncv projected matrix T explicitly instead of (alpha, beta) vectors.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.core import logger
from raft_tpu.core.sparse_types import COOMatrix, CSRMatrix
from raft_tpu.sparse import convert
from raft_tpu.sparse.linalg import _segment_spmv as _spmv_kernel


@dataclasses.dataclass
class LanczosConfig:
    """ref: lanczos_types.hpp:20-50 `lanczos_solver_config`."""
    n_components: int
    max_iterations: int = 1000
    ncv: int = 0          # 0 → min(n, max(2*k + 1, 20))
    tolerance: float = 1e-7
    which: str = "SA"     # LA | LM | SA | SM
    seed: int = 42


@jax.jit
def _orthogonalize(v, basis):
    """Full Gram-Schmidt against the rows of `basis` — one [m,n]·[n] matvec
    plus one [n,m]·[m] matvec, both MXU-shaped (the reference's per-vector
    cublas dot/axpy loop, detail/lanczos.cuh:321+, fused)."""
    coeffs = basis @ v
    return v - basis.T @ coeffs, coeffs


def lanczos_compute_eigenpairs(res, a, config: LanczosConfig,
                               v0: Optional[jnp.ndarray] = None
                               ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Compute k eigenpairs of symmetric sparse A
    (ref: sparse/solver/lanczos.cuh:34-86, CSR/COO overloads).

    Returns (eigenvalues [k], eigenvectors [n, k]) sorted per `which`."""
    if isinstance(a, COOMatrix):
        from raft_tpu.sparse import op as sparse_op
        a = convert.sorted_coo_to_csr(sparse_op.coo_sort(a))
    return _eigsh_csr(a, config, v0)


def eigsh(a, k: int = 6, which: str = "SA", v0=None, ncv: int = 0,
          maxiter: int = 1000, tol: float = 1e-7, seed: int = 42,
          res=None) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """scipy-compatible front-end (ref: pylibraft sparse/linalg/lanczos.pyx:85
    `eigsh`)."""
    cfg = LanczosConfig(n_components=k, max_iterations=maxiter, ncv=ncv,
                        tolerance=tol, which=which.upper(), seed=seed)
    return lanczos_compute_eigenpairs(res, a, cfg, v0)


def _eigsh_csr(csr: CSRMatrix, cfg: LanczosConfig, v0) -> Tuple:
    n = csr.n_rows
    k = cfg.n_components
    if k <= 0 or k >= n:
        raise ValueError(f"need 0 < n_components < n, got {k} vs {n}")
    if cfg.max_iterations < 1:
        raise ValueError(
            f"max_iterations must be >= 1, got {cfg.max_iterations}")
    ncv = cfg.ncv if cfg.ncv else min(n, max(2 * k + 1, 20))
    ncv = min(max(ncv, k + 2), n)
    which = cfg.which
    if which not in ("LA", "LM", "SA", "SM"):
        raise ValueError(f"which must be LA|LM|SA|SM, got {which}")

    row_ids, cols = csr.row_ids(), csr.indices
    dtype = jnp.float32
    data = csr.data.astype(dtype)

    if v0 is None:
        rng = np.random.default_rng(cfg.seed)
        v = jnp.asarray(rng.standard_normal(n), dtype=dtype)
    else:
        v = jnp.asarray(v0, dtype=dtype)
    v = v / jnp.linalg.norm(v)

    basis = jnp.zeros((ncv, n), dtype=dtype)
    t = np.zeros((ncv, ncv), dtype=np.float64)   # projected matrix

    def extend(j_start: int, basis, t, v):
        """Grow the Krylov basis rows [j_start, ncv) with Lanczos steps
        (ref: lanczos_aux detail/lanczos.cuh:248-340).  Returns the final
        out-of-basis coupling beta_last and next direction v."""
        beta_last = 0.0
        for j in range(j_start, ncv):
            basis = basis.at[j].set(v)
            w = _spmv_kernel(row_ids, cols, data, v, n)
            w, c1 = _orthogonalize(w, basis)
            w, c2 = _orthogonalize(w, basis)     # second pass for f32
            t[j, j] = float(c1[j] + c2[j])
            b = float(jnp.linalg.norm(w))
            if j + 1 < ncv:
                t[j, j + 1] = t[j + 1, j] = b
            beta_last = b
            if b < 1e-10:
                rng = np.random.default_rng(cfg.seed + j + 1)
                w = jnp.asarray(rng.standard_normal(n), dtype=dtype)
                w, _ = _orthogonalize(w, basis)
                b = float(jnp.linalg.norm(w))
                if j + 1 == ncv:
                    beta_last = 0.0   # exact invariant subspace
            v = w / b
        return basis, t, beta_last, v

    basis, t, beta_last, v = extend(0, basis, t, v)

    for it in range(cfg.max_iterations):
        evals, evecs = np.linalg.eigh(t)
        # Ritz selection per `which` (ref: lanczos_solve_ritz
        # detail/lanczos.cuh:182-223 — SM/LM sort Ritz values by magnitude
        # inside the Krylov space; no spectral shift is used).
        if which == "LM":
            order = np.argsort(-np.abs(evals))
        elif which == "SM":
            order = np.argsort(np.abs(evals))
        elif which == "LA":
            order = np.argsort(-evals)
        else:
            order = np.argsort(evals)
        keep = order[:k]
        ritz_vals = evals[keep]
        s = evecs[:, keep]                      # [ncv, k]
        residuals = np.abs(beta_last * s[-1, :])
        converged = float(residuals.max()) < cfg.tolerance
        if converged or it == cfg.max_iterations - 1:
            if not converged:
                # Reference parity: lanczos_smallest exits its
                # `while (res > tol && iter < maxIter)` loop and returns the
                # best available pairs without throwing
                # (detail/lanczos.cuh:537); we surface it via the logger.
                logger.warn(
                    "lanczos: max_iterations=%d reached with residual %.3e "
                    "> tol %.3e; returning unconverged eigenpairs",
                    cfg.max_iterations, float(residuals.max()),
                    cfg.tolerance)
            ritz_vecs = basis.T @ jnp.asarray(s, dtype=dtype)
            # normalize (f32 drift) and sort ascending like scipy eigsh
            ritz_vecs = ritz_vecs / jnp.linalg.norm(ritz_vecs, axis=0)
            asc = np.argsort(ritz_vals)
            return (jnp.asarray(ritz_vals[asc], dtype=dtype),
                    ritz_vecs[:, asc])

        # -- thick restart (ref: detail/lanczos.cuh:537-700) --------------
        ritz_vecs = basis.T @ jnp.asarray(s, dtype=dtype)   # [n, k]
        q, r = jnp.linalg.qr(ritz_vecs)
        signs = jnp.sign(jnp.diagonal(r))
        signs = jnp.where(signs == 0, 1.0, signs)
        q = q * signs[None, :]                  # keep original directions
        basis = jnp.zeros_like(basis).at[:k].set(q.T).at[k].set(v)
        t = np.zeros_like(t)
        t[np.arange(k), np.arange(k)] = ritz_vals
        border = beta_last * s[-1, :]           # couplings to residual row
        t[:k, k] = border
        t[k, :k] = border
        # Lanczos step on the residual row k, then extend the rest
        w = _spmv_kernel(row_ids, cols, data, v, n)
        w, c1 = _orthogonalize(w, basis)
        w, c2 = _orthogonalize(w, basis)
        t[k, k] = float(c1[k] + c2[k])
        b = float(jnp.linalg.norm(w))
        if k + 1 < ncv:
            t[k, k + 1] = t[k + 1, k] = b
        beta_last = b
        if b < 1e-10:
            rng = np.random.default_rng(cfg.seed + 1000 + it)
            w = jnp.asarray(rng.standard_normal(n), dtype=dtype)
            w, _ = _orthogonalize(w, basis)
            b = float(jnp.linalg.norm(w))
        v = w / b
        basis, t, beta_last, v = extend(k + 1, basis, t, v)

    raise AssertionError("unreachable: loop returns at max_iterations")
