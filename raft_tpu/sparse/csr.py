"""Weakly-connected components over CSR adjacency (ref: raft/sparse/csr.hpp
`weak_cc`:123 / `weak_cc_batched`:41-87, detail/csr.cuh — the label
propagation kernels cuML's DBSCAN builds on).

TPU formulation: min-label propagation over the edge list (scatter-min
both directions) + pointer jumping, iterated to a fixpoint inside one
`lax.while_loop` — the same device-resident union-find dataflow as the
MST color merge (sparse/solver/mst.py) and merge_labels. The reference's
batching (weak_cc_batched processes row windows to bound GPU memory) is
unnecessary here — the edge list streams through fixed-shape segment ops
— but the batched spelling is kept for API parity.

Labels are 1-based (component = 1 + min vertex id in it), with
``MAX_LABEL`` marking filtered-out vertices — the reference's contract
(csr.hpp:30-40: a filter lambda excludes non-"core" points).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from raft_tpu.core.sparse_types import CSRMatrix
from raft_tpu.label.merge_labels import MAX_LABEL


@functools.partial(jax.jit, static_argnames=("n", "axis"))
def _weak_cc_device(src, dst, vmask, n: int, active=None,
                    axis: Optional[str] = None):
    """Label-propagation fixpoint. With ``axis`` (the MNMG path, under
    shard_map) each device scatter-mins its own edge band and a
    ``lax.pmin`` after every round restores the global minimum — the
    same rounds, so the fixpoint and the diameter cap are shared."""
    cid = jnp.arange(n, dtype=jnp.int32)
    if active is None:
        # filtered vertices are barriers: they take no label, pass none
        active = vmask[src] & vmask[dst]
    safe_src = jnp.where(active, src, 0)
    safe_dst = jnp.where(active, dst, 0)
    r0 = jnp.where(vmask, cid, _i32(MAX_LABEL))

    def halve(r):
        # pointer jump through vertex labels; MAX_LABEL stays put
        tgt = jnp.clip(r, 0, n - 1)
        return jnp.where(r < n, jnp.minimum(r, r[tgt]), r)

    def propagate(r):
        ls = r[safe_src]
        ld = r[safe_dst]
        lo = jnp.minimum(ls, ld)
        upd = jnp.where(active, lo, _i32(MAX_LABEL))
        r = r.at[safe_dst].min(upd)
        r = r.at[safe_src].min(upd)
        if axis is not None:
            r = lax.pmin(r, axis)
        return halve(r)

    def cond(state):
        i, r, changed = state
        # DIAMETER-SAFE cap: min-label propagation is only guaranteed one
        # hop per round (pointer jumps target the smallest-ID vertex,
        # which can be topologically useless on adversarial paths), so a
        # log-bound silently truncates long chains. The `changed` flag
        # exits in O(log) rounds on ordinary graphs; the cap only bounds
        # the pathological worst case.
        return changed & (i < jnp.int32(n + 2))

    def body(state):
        i, r, _ = state
        nr = propagate(r)
        return i + 1, nr, jnp.any(nr != r)

    _, r, _ = lax.while_loop(cond, body,
                             (jnp.int32(0), propagate(r0), jnp.bool_(True)))
    return jnp.where(r < n, r + 1, _i32(MAX_LABEL))   # 1-based


def _i32(x):
    return jnp.asarray(x, jnp.int32)


def weak_cc(res, csr: CSRMatrix,
            mask: Optional[np.ndarray] = None) -> jnp.ndarray:
    """Weakly-connected component labels (1-based; filtered vertices get
    ``MAX_LABEL``). Directed edges are treated as undirected, exactly the
    reference's "weak" semantics.

    >>> import numpy as np, scipy.sparse as sp
    >>> from raft_tpu.core.sparse_types import CSRMatrix
    >>> from raft_tpu.sparse.csr import weak_cc
    >>> a = sp.csr_matrix(np.array([[0, 1, 0], [0, 0, 0], [0, 0, 0]],
    ...                            np.float32))
    >>> np.asarray(weak_cc(None, CSRMatrix.from_scipy(a))).tolist()
    [1, 1, 3]
    """
    n = csr.n_rows
    vmask = jnp.ones((n,), jnp.bool_) if mask is None \
        else jnp.asarray(mask).astype(jnp.bool_)
    src = csr.row_ids().astype(jnp.int32)
    dst = jnp.asarray(csr.indices).astype(jnp.int32)
    # bucketing pad entries must not connect the last row to vertex 0:
    # rewrite them as self-loops, which never merge components. The mask
    # bound is the device scalar indptr[-1], so this stays jit-traceable.
    dst = jnp.where(jnp.arange(dst.shape[0]) < csr.indptr[-1], dst, src)
    return _weak_cc_device(src, dst, vmask, n)


def weak_cc_batched(res, csr: CSRMatrix, start_vertex_id: int = 0,
                    batch_size: Optional[int] = None,
                    mask: Optional[np.ndarray] = None) -> jnp.ndarray:
    """API-parity spelling of weak_cc_batched (csr.hpp:41-87). The
    reference batches row windows to bound GPU memory; the TPU edge-list
    formulation needs no batching, so all batches resolve in one device
    fixpoint. ``start_vertex_id``/``batch_size`` are accepted for call
    compatibility and ignored (they cannot change the result)."""
    del start_vertex_id, batch_size
    return weak_cc(res, csr, mask=mask)


# ---------------------------------------------------------------------------
# MNMG: edge-partitioned weak_cc over a device mesh (round 4 — the same
# row-band convention as eigsh_mnmg / kmeans_fit_mnmg; r3 VERDICT missing
# item: MNMG beyond k-means/kNN)
# ---------------------------------------------------------------------------

def weak_cc_mnmg(res, csr: CSRMatrix, mesh, axis: str = "data",
                 mask: Optional[np.ndarray] = None) -> jnp.ndarray:
    """Multi-device weak_cc: the edge list is split into equal bands over
    ``mesh[axis]`` (labels replicated — n int32 labels are small next to
    the edge list); each round runs the band-local scatter-min in
    parallel and pmins the results over the mesh.

    Same semantics as :func:`weak_cc` (1-based labels, mask barriers)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    if mesh is None:
        raise ValueError("weak_cc_mnmg requires a jax.sharding.Mesh")
    n = csr.n_rows
    n_dev = mesh.shape[axis]
    vmask = np.ones((n,), np.bool_) if mask is None \
        else np.asarray(mask).astype(np.bool_)

    src, dst, _ = csr.host_edges()
    nnz = len(src)
    active = vmask[src] & vmask[dst]

    per = -(-max(nnz, 1) // n_dev)
    pad = per * n_dev - nnz
    src_b = np.pad(src, (0, pad))
    dst_b = np.pad(dst, (0, pad))
    act_b = np.pad(active, (0, pad))          # pad edges inactive

    shard = NamedSharding(mesh, P(axis))
    body = functools.partial(_weak_cc_device, n=n, axis=axis)
    fn = jax.jit(jax.shard_map(
        lambda s_, d_, a_, v_: body(s_, d_, v_, active=a_), mesh=mesh,
        in_specs=(P(axis), P(axis), P(axis), P()),
        out_specs=P()))
    return fn(jax.device_put(jnp.asarray(src_b), shard),
              jax.device_put(jnp.asarray(dst_b), shard),
              jax.device_put(jnp.asarray(act_b), shard),
              jnp.asarray(vmask))
