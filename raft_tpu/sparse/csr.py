"""Weakly-connected components over CSR adjacency (ref: raft/sparse/csr.hpp
`weak_cc`:123 / `weak_cc_batched`:41-87, detail/csr.cuh — the label
propagation kernels cuML's DBSCAN builds on).

TPU formulation: min-label propagation over the edge list (scatter-min
both directions) + pointer jumping, iterated to a fixpoint inside one
`lax.while_loop` — the same device-resident union-find dataflow as the
MST color merge (sparse/solver/mst.py) and merge_labels. The reference's
batching (weak_cc_batched processes row windows to bound GPU memory) is
unnecessary here — the edge list streams through fixed-shape segment ops
— but the batched spelling is kept for API parity.

Labels are 1-based (component = 1 + min vertex id in it), with
``MAX_LABEL`` marking filtered-out vertices — the reference's contract
(csr.hpp:30-40: a filter lambda excludes non-"core" points).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from raft_tpu.core.sparse_types import CSRMatrix
from raft_tpu.label.merge_labels import MAX_LABEL


@functools.partial(jax.jit, static_argnames=("n",))
def _weak_cc_device(src, dst, vmask, n: int):
    cid = jnp.arange(n, dtype=jnp.int32)
    # filtered vertices are barriers: they take no label and pass none
    active = vmask[src] & vmask[dst]
    safe_src = jnp.where(active, src, 0)
    safe_dst = jnp.where(active, dst, 0)
    r0 = jnp.where(vmask, cid, _i32(MAX_LABEL))

    def halve(r):
        # pointer jump through vertex labels; MAX_LABEL stays put
        tgt = jnp.clip(r, 0, n - 1)
        return jnp.where(r < n, jnp.minimum(r, r[tgt]), r)

    def propagate(r):
        ls = r[safe_src]
        ld = r[safe_dst]
        lo = jnp.minimum(ls, ld)
        upd = jnp.where(active, lo, _i32(MAX_LABEL))
        r = r.at[safe_dst].min(upd)
        r = r.at[safe_src].min(upd)
        return halve(r)

    def cond(state):
        i, r, changed = state
        # DIAMETER-SAFE cap: min-label propagation is only guaranteed one
        # hop per round (pointer jumps target the smallest-ID vertex,
        # which can be topologically useless on adversarial paths), so a
        # log-bound silently truncates long chains. The `changed` flag
        # exits in O(log) rounds on ordinary graphs; the cap only bounds
        # the pathological worst case.
        return changed & (i < jnp.int32(n + 2))

    def body(state):
        i, r, _ = state
        nr = propagate(r)
        return i + 1, nr, jnp.any(nr != r)

    _, r, _ = lax.while_loop(cond, body,
                             (jnp.int32(0), propagate(r0), jnp.bool_(True)))
    return jnp.where(r < n, r + 1, _i32(MAX_LABEL))   # 1-based


def _i32(x):
    return jnp.asarray(x, jnp.int32)


def weak_cc(res, csr: CSRMatrix,
            mask: Optional[np.ndarray] = None) -> jnp.ndarray:
    """Weakly-connected component labels (1-based; filtered vertices get
    ``MAX_LABEL``). Directed edges are treated as undirected, exactly the
    reference's "weak" semantics.

    >>> import numpy as np, scipy.sparse as sp
    >>> from raft_tpu.core.sparse_types import CSRMatrix
    >>> from raft_tpu.sparse.csr import weak_cc
    >>> a = sp.csr_matrix(np.array([[0, 1, 0], [0, 0, 0], [0, 0, 0]],
    ...                            np.float32))
    >>> np.asarray(weak_cc(None, CSRMatrix.from_scipy(a))).tolist()
    [1, 1, 3]
    """
    n = csr.n_rows
    vmask = jnp.ones((n,), jnp.bool_) if mask is None \
        else jnp.asarray(mask).astype(jnp.bool_)
    src = csr.row_ids().astype(jnp.int32)
    dst = jnp.asarray(csr.indices).astype(jnp.int32)
    # bucketing pad entries must not connect the last row to vertex 0:
    # rewrite them as self-loops, which never merge components. The mask
    # bound is the device scalar indptr[-1], so this stays jit-traceable.
    dst = jnp.where(jnp.arange(dst.shape[0]) < csr.indptr[-1], dst, src)
    return _weak_cc_device(src, dst, vmask, n)


def weak_cc_batched(res, csr: CSRMatrix, start_vertex_id: int = 0,
                    batch_size: Optional[int] = None,
                    mask: Optional[np.ndarray] = None) -> jnp.ndarray:
    """API-parity spelling of weak_cc_batched (csr.hpp:41-87). The
    reference batches row windows to bound GPU memory; the TPU edge-list
    formulation needs no batching, so all batches resolve in one device
    fixpoint. ``start_vertex_id``/``batch_size`` are accepted for call
    compatibility and ignored (they cannot change the result)."""
    del start_vertex_id, batch_size
    return weak_cc(res, csr, mask=mask)
