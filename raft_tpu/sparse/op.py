"""Structural COO/CSR operations (ref: raft/sparse/op/{sort,filter,reduce,
row_op,slice}.cuh).

These change nnz or ordering, so they run host-side (numpy) — the same role
the reference's thrust sorts/scans play — and hand static-shape device
buffers to the jitted compute layer.
"""

from __future__ import annotations

from typing import Callable

import jax.numpy as jnp
import numpy as np

from raft_tpu.core.sparse_types import COOMatrix, CSRMatrix
from raft_tpu.sparse.convert import _host


def coo_sort(coo: COOMatrix) -> COOMatrix:
    """Sort COO entries by (row, col) (ref: sparse/op/sort.cuh `coo_sort`)."""
    rows, cols, data = _host(coo.rows), _host(coo.cols), _host(coo.data)
    order = np.lexsort((cols, rows))
    return COOMatrix(jnp.asarray(rows[order]), jnp.asarray(cols[order]),
                     jnp.asarray(data[order]), coo.shape)


def coo_remove_scalar(coo: COOMatrix, scalar) -> COOMatrix:
    """Drop entries equal to `scalar` (ref: sparse/op/filter.cuh
    `coo_remove_scalar`)."""
    rows, cols, data = _host(coo.rows), _host(coo.cols), _host(coo.data)
    keep = data != scalar
    return COOMatrix(jnp.asarray(rows[keep]), jnp.asarray(cols[keep]),
                     jnp.asarray(data[keep]), coo.shape)


def coo_remove_zeros(coo: COOMatrix) -> COOMatrix:
    """ref: sparse/op/filter.cuh `coo_remove_zeros`."""
    return coo_remove_scalar(coo, 0)


def max_duplicates(coo: COOMatrix) -> COOMatrix:
    """Merge duplicate (row, col) entries keeping the max value
    (ref: sparse/op/reduce.cuh `max_duplicates`)."""
    return reduce_duplicates(coo, np.maximum.reduceat)


def sum_duplicates(coo: COOMatrix) -> COOMatrix:
    """Merge duplicate (row, col) entries by summing (scipy-compatible
    canonicalization; the reference exposes max via op/reduce.cuh and sums
    inside convert/symmetrize kernels)."""
    return reduce_duplicates(coo, np.add.reduceat)


def reduce_duplicates(coo: COOMatrix,
                      reduceat: Callable[[np.ndarray, np.ndarray], np.ndarray]
                      ) -> COOMatrix:
    """Shared dedup: sort by (row, col), segment-reduce runs of equal keys
    (ref: sparse/op/reduce.cuh `compute_duplicates_mask` + scatter)."""
    rows, cols, data = _host(coo.rows), _host(coo.cols), _host(coo.data)
    if rows.shape[0] == 0:
        return coo
    order = np.lexsort((cols, rows))
    rows, cols, data = rows[order], cols[order], data[order]
    new_run = np.empty(rows.shape[0], dtype=bool)
    new_run[0] = True
    np.logical_or(rows[1:] != rows[:-1], cols[1:] != cols[:-1],
                  out=new_run[1:])
    starts = np.nonzero(new_run)[0]
    merged = reduceat(data, starts)
    return COOMatrix(jnp.asarray(rows[starts]), jnp.asarray(cols[starts]),
                     jnp.asarray(merged), coo.shape)


def csr_row_op(csr: CSRMatrix, fn) -> jnp.ndarray:
    """Apply `fn(row_id, values_segment)` conceptually per row; here realized
    as a vectorized map over (row_ids, data) (ref: sparse/op/row_op.cuh
    `csr_row_op` hands each row's [start, stop) to a device lambda).

    ``fn`` receives ONLY logical entries: nnz-bucketing pad slots are
    sliced off eagerly (an arbitrary user fn — counts, min-reductions,
    means — can't be pad-masked generically). Under jit tracing the slice
    is impossible; there the caller must pass an unpadded matrix
    (``csr.depad()`` before the jit boundary)."""
    import jax as _jax

    if not isinstance(csr.indptr, _jax.core.Tracer):
        csr = csr.depad()
    row_ids = csr.row_ids()
    return fn(row_ids, csr.data)


def csr_row_slice(csr: CSRMatrix, start: int, stop: int) -> CSRMatrix:
    """Extract rows [start, stop) as a new CSR matrix
    (ref: sparse/op/slice.cuh `csr_row_slice_indptr` /
    `csr_row_slice_populate`)."""
    indptr = _host(csr.indptr)
    lo, hi = int(indptr[start]), int(indptr[stop])
    new_indptr = (indptr[start:stop + 1] - lo).astype(indptr.dtype)
    return CSRMatrix(jnp.asarray(new_indptr),
                     jnp.asarray(_host(csr.indices)[lo:hi]),
                     jnp.asarray(_host(csr.data)[lo:hi]),
                     (stop - start, csr.n_cols))
