"""Slot-grid SpMV/SpMM — the TPU rebuild of the cuSPARSE SpMV kernel layer.

The reference's sparse stack bottoms out in cusparseSpMV/SpMM
(ref: sparse/detail/cusparse_wrappers.h:86-200), the hot kernel of the
Lanczos loop (ref: sparse/solver/detail/lanczos.cuh:303-319).  The round-3
hardware sweep measured the XLA gather+segment_sum formulation at 0.07
GFLOP/s on 9.4M nnz (tpu_battery_out/bench_full.jsonl) — the gather and the
scatter both serialize through XLA's generic element-at-a-time paths.

This module replaces both sides with Mosaic-expressible structure:

* **Gather** — Mosaic's vector gather (``tpu.dynamic_gather``) is
  LANE-LOCAL: the source may span at most one vreg (128 lanes) along the
  gather dimension ("Multiple source vregs along gather dimension", round-5
  hardware capture; the round-3 width-128 probe did not generalize).  So x
  is tiled into column shards held as (shard_w/128, 128) VMEM blocks,
  and kernel 1 gathers each slot tile through a ROW-BROADCAST SELECT
  TREE: for each shard row, broadcast the row across the block's
  sublanes, one legal 128-wide ``take_along_axis`` on the low 7 index
  bits, and a mask-accumulate where the high bits match the row.  Tiles
  are packed per shard in groups of GROUP_TILES so one grid step
  amortizes the tree over GROUP_TILES*1024 slots with the shard block
  resident.  shard_w is DENSITY-ADAPTIVE (_auto_shard_w, 8192..65536):
  sparse per-shard streams starve the 1024-row tile window and explode
  padding at narrow shards, while the tree's VPU cost grows with
  shard_w — the chooser targets ~50% tile fill.
* **Scatter** — there is no scatter on TPU.  Entries are packed (host-side,
  once per sparsity pattern — the analogue of cusparseSpMV_preprocess) into
  a (tile, sub-row, lane) grid in CSR row order, so each row's products are
  contiguous runs.  Kernel 2 reduces runs with an EXACT segmented scan
  (7 lane steps + a 3-step cross-sub-row carry; f32 tree sums confined to
  each row — no cross-row cancellation), then emits one partial per row per
  tile through a flat one-gather relocation to its (window, row%128) slot.
* **Accumulation** — kernel 3 walks tiles in base-window order (a host-
  sorted permutation riding scalar prefetch) and accumulates each tile's
  (8, 128) window contributions into 8 window-aligned output planes;
  revisits are consecutive by construction, which is exactly the Pallas
  output-accumulation contract.

The packing rules live in ``_native/raft_tpu_native.cpp:rt_spmv_pack`` (with
a pure-Python fallback): runs split into <=128-slot pieces, pieces cross
sub-rows only when filling to lane 127 (the carry contract), and every row
in a tile stays within 8 row-windows of the tile base (the emission range).

Numerical contract: products and sums are f32; each row's sum is a tree
reduction over its own entries only (padding slots are masked before the
multiply, so stored zeros still propagate inf/nan per IEEE while pad slots
never can).
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.util.math import cdiv, round_up_to_multiple
from raft_tpu.util.pallas_utils import pallas_call

from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

LANES = 128
SUBROWS = 8
TILE_SLOTS = LANES * SUBROWS          # 1024
SPAN_WINDOWS = 8                      # emission range: 8 x 128 rows per tile
SHARD_W_MAX = 65536                   # widest x shard the gather tree
                                      # walks (512 rows unrolled — the
                                      # VPU cost per slot scales with
                                      # shard_w/128)
SHARD_W_MIN = 8192
GROUP_TILES = 8                       # tiles per kernel-1 grid step (one
                                      # shard per group; pad granularity)

_F_CONT = 1                           # slot continues the run from lane-1
_F_REAL = 2                           # slot holds a real entry
_F_CROSS = 4                          # lane belongs to the sub-row's leading
                                      # run chained from the previous sub-row


def _pack_python(row: np.ndarray, span_windows: int
                 ) -> Tuple[np.ndarray, np.ndarray]:
    """Pure-Python mirror of rt_spmv_pack (toolchain-free fallback)."""
    slots: list = []
    bases: list = []
    base = -1
    i, nnz = 0, len(row)
    while i < nnz:
        r = int(row[i])
        j = i
        while j < nnz and row[j] == r:
            j += 1
        run = j - i
        while run > 0:
            if len(slots) % TILE_SLOTS == 0:
                base = -1
            if base < 0:
                base = r >> 7
                bases.append(base)
            if (r >> 7) - base >= span_windows:
                pad = TILE_SLOTS - len(slots) % TILE_SLOTS
                slots.extend([-1] * pad)
                continue
            lane = len(slots) % LANES
            rem = LANES - lane
            if run <= rem:
                slots.extend(range(i, i + run))
                i += run
                run = 0
            elif lane == 0:
                slots.extend(range(i, i + LANES))
                i += LANES
                run -= LANES
            else:
                slots.extend([-1] * rem)
    tail = (-len(slots)) % TILE_SLOTS
    slots.extend([-1] * tail)
    return (np.asarray(slots, np.int32),
            np.asarray(bases, np.int32))


def _pack(row: np.ndarray, span_windows: int
          ) -> Tuple[np.ndarray, np.ndarray]:
    from raft_tpu import _native

    lib = _native.get_lib()
    if lib is None:
        return _pack_python(row, span_windows)
    import ctypes

    row = np.ascontiguousarray(row, np.int32)
    nnz = len(row)
    # worst case ~2x slots (alternating pad), tiles bounded by slots/1024
    cap = int(round_up_to_multiple(max(4 * nnz, TILE_SLOTS), TILE_SLOTS))
    while True:
        slot_src = np.empty(cap, np.int32)
        tile_base = np.zeros(cap // TILE_SLOTS, np.int32)
        n = lib.rt_spmv_pack(
            row.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)), nnz,
            span_windows,
            slot_src.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)), cap,
            tile_base.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            cap // TILE_SLOTS)
        if n >= 0:
            return slot_src[:n], tile_base[:n // TILE_SLOTS]
        cap *= 2


class GridSpMV:
    """Prepared SpMV plan for one sparsity pattern (role of the cuSPARSE
    preprocessed SpMV descriptor).  Build once per matrix with
    :func:`prepare`; apply with :func:`spmv` / :func:`spmm`.

    Registered as a pytree so it can close over or flow through jit; all
    metadata except the device arrays is static.
    """

    def __init__(self, *, cols_grid, data_grid, flags_grid, emit_grid,
                 group_shard, tile_base, perm_sorted, base_sorted,
                 visited, shape, nnz, n_shards, shard_w, pad_ratio):
        self.cols_grid = cols_grid        # (ntile, 8, 128) i32 shard-local
        self.data_grid = data_grid        # (ntile, 8, 128) f32
        self.flags_grid = flags_grid      # (ntile, 8, 128) i32
        self.emit_grid = emit_grid        # (ntile, 8, 128) i32, -1 = none
        self.group_shard = group_shard    # (ntile//GROUP_TILES,) i32
        self.tile_base = tile_base        # (ntile,) i32 (build order)
        self.perm_sorted = perm_sorted    # (ntile,) i32: tiles by base
        self.base_sorted = base_sorted    # (ntile,) i32
        self.visited = visited            # (8, NWP) bool (host constant)
        # flatten aux cached once: _grid_flatten runs on EVERY dispatch
        # when the plan is a jit argument (the supported pattern — see
        # the HTTP-413 note in benches), and tobytes() would otherwise
        # copy+hash ~n_rows/16 bytes per call
        self._vis_aux = (visited.tobytes(), visited.shape)
        self.shape = shape
        self.nnz = nnz                    # logical nnz packed
        self.n_shards = n_shards
        self.shard_w = shard_w            # columns per x shard (static)
        self.pad_ratio = pad_ratio        # slots / nnz (build diagnostic)

    @property
    def n_rows(self):
        return self.shape[0]

    @property
    def n_cols(self):
        return self.shape[1]

    def matvec(self, x):
        return spmv(self, x)


def _grid_flatten(g: GridSpMV):
    leaves = (g.cols_grid, g.data_grid, g.flags_grid, g.emit_grid,
              g.group_shard, g.tile_base, g.perm_sorted, g.base_sorted)
    aux = (g._vis_aux, g.shape, g.nnz,
           g.n_shards, g.shard_w, g.pad_ratio)
    return leaves, aux


def _grid_unflatten(aux, leaves):
    vis_aux, shape, nnz, n_shards, shard_w, pad_ratio = aux
    g = GridSpMV.__new__(GridSpMV)
    (g.cols_grid, g.data_grid, g.flags_grid, g.emit_grid,
     g.group_shard, g.tile_base, g.perm_sorted, g.base_sorted) = leaves
    g.visited = np.frombuffer(vis_aux[0], np.bool_).reshape(vis_aux[1])
    g._vis_aux = vis_aux
    g.shape, g.nnz, g.n_shards, g.shard_w, g.pad_ratio = (
        shape, nnz, n_shards, shard_w, pad_ratio)
    return g


jax.tree_util.register_pytree_node(GridSpMV, _grid_flatten, _grid_unflatten)


def _auto_shard_w(n_rows: int, n_cols: int, nnz: int,
                  span_windows: int = SPAN_WINDOWS) -> int:
    """Density-adaptive shard width. A tile spans <= SPAN_WINDOWS*128
    rows, so the slots available to fill it are the nnz falling in a
    (1024-row x shard_w-col) rectangle ~= nnz * (1024/n_rows) *
    (shard_w/n_cols); below ~50% fill the packer must cut tiles early
    and padding explodes (measured round 5: uniform 10 nnz/row at 1M^2
    packs at pad 14.2x with shard_w=8192 but ~1.6x at 65536 — the
    row-window constraint binds, not the stream). The tree gather's VPU
    cost scales the OTHER way (shard_w/128 rows walked per block), so
    pick the narrowest shard whose estimated fill reaches 50%."""
    if nnz <= 0:
        return SHARD_W_MIN
    # fill >= 50%: nnz * (span_windows*LANES rows)/n_rows * (w/n_cols)
    # >= TILE_SLOTS/2  =>  w >= n_rows*n_cols*TILE_SLOTS /
    # (2*nnz*span_windows*LANES)
    span_rows = max(1, span_windows * LANES)
    need = max(1, (n_rows * max(n_cols, 1) * TILE_SLOTS)
               // (2 * nnz * span_rows))
    w = SHARD_W_MIN
    while w < SHARD_W_MAX and w < need:
        w *= 2
    return w


def prepare(csr, span_windows: int = SPAN_WINDOWS,
            shard_w: int = None, _collect: dict = None) -> GridSpMV:
    """Build the slot-grid plan from a CSRMatrix (host-side, once per
    pattern — the cusparseSpMV_preprocess analogue).

    ``_collect`` (internal, used by sparse/solver/mst_grid.py): a dict
    that receives host-side per-slot metadata the SpMV apply does not
    need — ``eid`` (ntile, 8, 128) original-edge index per real slot
    (-1 on pads), ``srow_local`` (ntile, 8, 128) row offset from the
    tile's base window (0 on pads, < 1024 on real slots by the packer's
    span contract), and ``edges`` = the (rows, cols, data) host triple
    (so the caller need not re-expand the CSR)."""
    rows, cols, data = csr.host_edges()
    data = data.astype(np.float32)
    nnz_log = len(rows)
    n_rows, n_cols = csr.shape

    # shrink the shard to the matrix so small patterns don't pad up to
    # the full shard width; a kernel-1 group is GROUP_TILES tiles drawing
    # from ONE shard, so per-shard streams pad to group granularity
    if shard_w is None:
        shard_w = _auto_shard_w(n_rows, n_cols, nnz_log, span_windows)
    shard_w = min(shard_w, round_up_to_multiple(max(n_cols, 1), 128))
    n_shards = max(1, cdiv(n_cols, shard_w))
    group_slots = GROUP_TILES * TILE_SLOTS

    all_src_col: list = []        # per-slot column (shard-local), 0 pad
    all_src_data: list = []
    all_src_row: list = []        # per-slot row, -1 pad
    all_src_eid: list = []        # per-slot original edge id, -1 pad
    all_bases: list = []
    group_shard: list = []

    # ONE stable bucket sort replaces the per-shard full-nnz masks
    # (O(n_shards * nnz) — the prepare() hotspot at 10M+ nnz): stable
    # argsort by shard id preserves ascending original order within
    # each shard, which is exactly what the boolean mask produced.
    shard_id = cols // shard_w
    order = np.argsort(shard_id, kind="stable")
    bounds = np.searchsorted(shard_id[order], np.arange(n_shards + 1))
    for s in range(n_shards):
        sl = order[bounds[s]:bounds[s + 1]]
        if len(sl) == 0:
            continue
        srow, scol, sdat = rows[sl], cols[sl] - s * shard_w, data[sl]
        slot_src, bases = _pack(srow, span_windows)
        # pad the shard's slot stream to a kernel-1 group multiple; pad
        # tiles carry base 0 and no real slots
        n = len(slot_src)
        npad = round_up_to_multiple(n, group_slots)
        slot_src = np.pad(slot_src, (0, npad - n), constant_values=-1)
        bases = np.pad(bases, (0, npad // TILE_SLOTS - len(bases)))
        real = slot_src >= 0
        idx = np.where(real, slot_src, 0)
        all_src_col.append(np.where(real, scol[idx], 0).astype(np.int32))
        all_src_data.append(
            np.where(real, sdat[idx], 0).astype(np.float32))
        all_src_row.append(np.where(real, srow[idx], -1).astype(np.int32))
        if _collect is not None:
            orig = sl.astype(np.int32)     # ascending original edge ids
            all_src_eid.append(np.where(real, orig[idx], -1
                                        ).astype(np.int32))
        all_bases.append(bases)
        group_shard.extend([s] * (npad // group_slots))

    if not all_src_col:   # empty matrix: a single all-pad group
        all_src_col = [np.zeros(group_slots, np.int32)]
        all_src_data = [np.zeros(group_slots, np.float32)]
        all_src_row = [np.full(group_slots, -1, np.int32)]
        all_src_eid = [np.full(group_slots, -1, np.int32)]
        all_bases = [np.zeros(group_slots // TILE_SLOTS, np.int32)]
        group_shard = [0]

    scol = np.concatenate(all_src_col)
    sdat = np.concatenate(all_src_data)
    srow = np.concatenate(all_src_row)
    tile_base = np.concatenate(all_bases)
    n_slots = len(scol)
    n_tiles = n_slots // TILE_SLOTS

    # --- flags (vectorized over the whole grid) ---
    rg = srow.reshape(n_tiles, SUBROWS, LANES)
    real = rg >= 0
    cont = np.zeros_like(real)
    cont[:, :, 1:] = real[:, :, 1:] & (rg[:, :, 1:] == rg[:, :, :-1])
    chain = np.zeros((n_tiles, SUBROWS), np.bool_)   # sub-row continues prev
    chain[:, 1:] = (real[:, 1:, 0] & real[:, :-1, 127]
                    & (rg[:, 1:, 0] == rg[:, :-1, 127]))
    # leading-run mask: lanes up to the first run break of the sub-row
    brk = ~cont & (np.arange(LANES) > 0)             # run break at lane l
    lead = np.cumsum(brk, axis=2) == 0               # lane 0 always leads
    cross = lead & chain[:, :, None]
    flags = (cont * _F_CONT + real * _F_REAL + cross * _F_CROSS
             ).astype(np.int32)

    # --- emissions: one per (row, tile) at the end of its last piece ---
    is_end = real.copy()
    is_end[:, :, :-1] &= (rg[:, :, :-1] != rg[:, :, 1:])
    # lane 127 is an end unless the run chains into the next sub-row
    is_end[:, :-1, 127] &= ~chain[:, 1:]
    t_i, s_i, l_i = np.nonzero(is_end)
    q = rg[t_i, s_i, l_i] - tile_base[t_i] * LANES
    if q.size and (q.min() < 0 or q.max() >= TILE_SLOTS):
        raise AssertionError("packer emitted a row outside its tile span")
    emit = np.full((n_tiles, TILE_SLOTS), -1, np.int32)
    emit[t_i, q] = (s_i * LANES + l_i).astype(np.int32)
    emit = emit.reshape(n_tiles, SUBROWS, LANES)

    if _collect is not None:
        eid_flat = np.concatenate(all_src_eid) if all_src_eid else \
            np.full(n_slots, -1, np.int32)
        real_flat = srow >= 0
        srow_local = np.where(
            real_flat,
            srow - np.repeat(tile_base, TILE_SLOTS) * LANES, 0)
        _collect["eid"] = eid_flat.reshape(n_tiles, SUBROWS, LANES)
        _collect["srow_local"] = srow_local.astype(np.int32).reshape(
            n_tiles, SUBROWS, LANES)
        _collect["edges"] = (rows, cols, data)

    # --- tile ordering + visited masks for the window planes ---
    perm = np.argsort(tile_base, kind="stable").astype(np.int32)
    base_sorted = tile_base[perm]
    nwp = cdiv(max(n_rows, 1), LANES) + SPAN_WINDOWS
    visited = np.zeros((SPAN_WINDOWS, nwp), np.bool_)
    for d in range(SPAN_WINDOWS):
        visited[d, np.minimum(tile_base + d, nwp - 1)] = True

    return GridSpMV(
        cols_grid=jnp.asarray(
            scol.reshape(n_tiles, SUBROWS, LANES)),
        data_grid=jnp.asarray(sdat.reshape(n_tiles, SUBROWS, LANES)),
        flags_grid=jnp.asarray(flags),
        emit_grid=jnp.asarray(emit),
        group_shard=jnp.asarray(np.asarray(group_shard, np.int32)),
        tile_base=jnp.asarray(tile_base),
        perm_sorted=jnp.asarray(perm),
        base_sorted=jnp.asarray(base_sorted),
        visited=visited,
        shape=(n_rows, n_cols), nnz=nnz_log, n_shards=n_shards,
        shard_w=shard_w,
        pad_ratio=float(n_slots) / max(nnz_log, 1))


# ---------------------------------------------------------------------------
# kernels
# ---------------------------------------------------------------------------

def _lane_gather(src, idx):
    """Same-shape gather along lanes (take_along_axis axis=1) spelled as
    the exact lax.gather form Mosaic lowers to tpu.dynamic_gather —
    jnp.take_along_axis canonicalizes indices to int64 under x64, which
    Mosaic rejects; idx stays int32 here.  LANE-LOCAL ONLY: legal when
    the source's lane dimension is <= 128 (one vreg along the gather
    dim); wider sources must go through :func:`_tree_gather`."""
    dnums = jax.lax.GatherDimensionNumbers(
        offset_dims=(), collapsed_slice_dims=(1,), start_index_map=(1,),
        operand_batching_dims=(0,), start_indices_batching_dims=(0,))
    return jax.lax.gather(
        src, idx[..., None], dnums, (1, 1),
        mode=jax.lax.GatherScatterMode.PROMISE_IN_BOUNDS)


def _tree_gather(src_rows, idx, out_sublanes: int):
    """out[s, l] = src_rows[idx[s, l] >> 7, idx[s, l] & 127] via the
    row-broadcast select tree — the Mosaic-legal wide-range gather.

    src_rows: (S, 128); idx: (out_sublanes, 128) i32 in [0, S*128).
    Each step is one legal lane-local gather plus a mask-accumulate, so
    VPU cost is ~5 vector ops per source row per block of sublanes."""
    n_rows = src_rows.shape[0]
    hi = jax.lax.shift_right_logical(idx, jnp.int32(7))
    lo = jax.lax.bitwise_and(idx, jnp.int32(127))
    acc = jnp.zeros((out_sublanes, LANES), src_rows.dtype)
    zero = jnp.zeros((), src_rows.dtype)
    for r in range(n_rows):
        row = jax.lax.broadcast_in_dim(
            src_rows[r:r + 1, :], (out_sublanes, LANES), (0, 1))
        g = _lane_gather(row, lo)
        acc = acc + jnp.where(hi == r, g, zero)
    return acc


def _tree_gather_kernel(shard_ref, x_ref, i_ref, o_ref):
    """Kernel 1: gather a GROUP_TILES-tile block of slots from the
    group's x shard.  x_ref (1, S, 128): the shard, un-replicated;
    i_ref/o_ref (1, GROUP_TILES*SUBROWS, 128)."""
    del shard_ref
    o_ref[0] = _tree_gather(x_ref[0], i_ref[0], i_ref.shape[1])


def _f0():
    """A strongly-typed f32 zero: weak python floats lower as f64 casts
    inside Mosaic kernels under jax_enable_x64."""
    return jnp.float32(0.0)


def _roll32(x, d, axis):
    """tpu.rotate via pltpu.roll — 32-bit only on current Mosaic, so
    bools round-trip through i32; the shift amount is pinned i32 (a bare
    python int becomes an i64 rotate operand under jax_enable_x64)."""
    d = jnp.int32(d)
    if x.dtype == jnp.bool_:
        return pltpu.roll(x.astype(jnp.int32), d, axis) != 0
    return pltpu.roll(x, d, axis)


def _shift_lanes(x, d):
    """Shift right along lanes by d, zero/False fill.

    Spelled as rotate+mask: the concat-of-slices spelling needs an
    unaligned-lane relayout Mosaic cannot do ("Invalid vector register
    cast" — round-5 deviceless-AOT bisect, the reason no segsum kernel
    ever compiled on hardware before this round)."""
    rolled = _roll32(x, d, x.ndim - 1)
    lane = jax.lax.broadcasted_iota(jnp.int32, x.shape, x.ndim - 1)
    if x.dtype == jnp.bool_:
        return rolled & (lane >= d)
    return jnp.where(lane < d, jnp.zeros((), x.dtype), rolled)


def _shift_subs(x, d):
    """Shift down along sub-rows by d, zero/False fill (rotate+mask; see
    :func:`_shift_lanes`)."""
    rolled = _roll32(x, d, x.ndim - 2)
    sub = jax.lax.broadcasted_iota(jnp.int32, x.shape, x.ndim - 2)
    if x.dtype == jnp.bool_:
        return rolled & (sub >= d)
    return jnp.where(sub < d, jnp.zeros((), x.dtype), rolled)


def _segsum_body(g, dat, f, e, s_ref):
    """Exact segmented-scan tile reduction + flat emission relocation —
    the shared body of the SpMV scan kernel and its k-batched SpMM twin.
    ``s_ref``: an (8, 128) f32 VMEM scratch; the scan result is round-
    tripped through it so the emission tree's sublane slices see a
    canonical vreg layout (slicing the scan's live value directly is an
    "Invalid vector register cast" in Mosaic — round-5 AOT bisect).
    Returns the tile's (8, 128) per-(window, row%128) contribution."""
    real = (f & _F_REAL) != 0
    cont = (f & _F_CONT) != 0
    crossm = (f & _F_CROSS) != 0

    p = jnp.where(real, g * dat, _f0())

    # segmented inclusive scan along lanes: runs are row pieces
    c, fl = p, cont
    for d in (1, 2, 4, 8, 16, 32, 64):
        c = c + jnp.where(fl, _shift_lanes(c, d), _f0())
        fl = fl & _shift_lanes(fl, d)

    # cross-sub-row carry: a piece chained from the previous sub-row adds
    # the chain sum of the predecessors' tails (each tail is its sub-row's
    # final segment value — exactly the chained piece's partial)
    tails = c[:, 127:128]
    crossf = crossm[:, 0:1]
    ts, fs = tails, crossf
    for d in (1, 2, 4):
        ts = ts + jnp.where(fs, _shift_subs(ts, d), _f0())
        fs = fs & _shift_subs(fs, d)
    car = jnp.where(crossf, _shift_subs(ts, 1), _f0())
    c = c + jnp.where(crossm, car, _f0())

    # emission: relocate each row's final partial to its (window, row%128)
    # slot. The emission position space is the whole 1024-slot tile, so a
    # flat lane gather is Mosaic-illegal (source > 1 vreg along the
    # gather dim); the in-tile relocation rides the same row-broadcast
    # select tree as kernel 1 (8 sublane rows -> 8 legal lane gathers)
    s_ref[:] = c
    contrib = _tree_gather(s_ref[:], jnp.maximum(e, 0), SUBROWS)
    return jnp.where(e >= 0, contrib, _f0())


def _segsum_kernel(g_ref, d_ref, f_ref, e_ref, o_ref, s_ref):
    o_ref[0] = _segsum_body(g_ref[0], d_ref[0], f_ref[0], e_ref[0],
                            s_ref)


def _reduce_kernel(perm_ref, base_ref, c_ref, *o_refs):
    del perm_ref
    t = pl.program_id(0)
    prev = base_ref[jnp.maximum(t - 1, 0)]
    first = (t == 0) | (base_ref[t] != prev)
    contrib = c_ref[0]

    @pl.when(first)
    def _init():
        for d in range(SPAN_WINDOWS):
            o_refs[d][0] = contrib[d:d + 1]

    @pl.when(jnp.logical_not(first))
    def _acc():
        for d in range(SPAN_WINDOWS):
            o_refs[d][0] += contrib[d:d + 1]


def _shard_rows(fmt: GridSpMV, v):
    """Pad a length-n_cols vector to the shard grid: (n_shards, S, 128)."""
    total = fmt.n_shards * fmt.shard_w
    vpad = jnp.zeros(total, v.dtype).at[:fmt.n_cols].set(v)
    return vpad.reshape(fmt.n_shards, fmt.shard_w // LANES, LANES)


def _gather_grid_spec(fmt: GridSpMV):
    """Kernel-1 grid spec: one step per GROUP_TILES-tile group, the
    group's shard block chosen by scalar prefetch."""
    s_rows = fmt.shard_w // LANES
    gsub = GROUP_TILES * SUBROWS
    ngroup = fmt.data_grid.shape[0] // GROUP_TILES
    return ngroup, pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(ngroup,),
        in_specs=[
            pl.BlockSpec((1, s_rows, LANES), lambda g, sh: (sh[g], 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, gsub, LANES), lambda g, sh: (g, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, gsub, LANES), lambda g, sh: (g, 0, 0),
                               memory_space=pltpu.VMEM),
    )


@jax.jit
def _spmv_impl(fmt: GridSpMV, x):
    n_rows, n_cols = fmt.shape
    ntile = fmt.data_grid.shape[0]
    nwp = fmt.visited.shape[1]
    gsub = GROUP_TILES * SUBROWS

    x_sh = _shard_rows(fmt, x.astype(jnp.float32))
    ngroup, grid1 = _gather_grid_spec(fmt)
    gathered = pallas_call(
        _tree_gather_kernel, grid_spec=grid1,
        out_shape=jax.ShapeDtypeStruct((ngroup, gsub, LANES),
                                       jnp.float32),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary",)),
    )(fmt.group_shard, x_sh, fmt.cols_grid.reshape(ngroup, gsub, LANES))

    prod_tiles = gathered.reshape(ntile, SUBROWS, LANES)

    contrib = pallas_call(
        _segsum_kernel,
        grid=(ntile,),
        in_specs=[
            pl.BlockSpec((1, SUBROWS, LANES), lambda t: (t, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, SUBROWS, LANES), lambda t: (t, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, SUBROWS, LANES), lambda t: (t, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, SUBROWS, LANES), lambda t: (t, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, SUBROWS, LANES), lambda t: (t, 0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((ntile, SUBROWS, LANES),
                                       jnp.float32),
        scratch_shapes=[pltpu.VMEM((SUBROWS, LANES), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary",)),
    )(prod_tiles, fmt.data_grid, fmt.flags_grid, fmt.emit_grid)

    grid3 = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(ntile,),
        in_specs=[pl.BlockSpec((1, SUBROWS, LANES),
                               lambda t, pm, bs: (pm[t], 0, 0),
                               memory_space=pltpu.VMEM)],
        out_specs=[
            pl.BlockSpec((1, 1, LANES),
                         (lambda t, pm, bs, _d=d: (bs[t] + _d, 0, 0)),
                         memory_space=pltpu.VMEM)
            for d in range(SPAN_WINDOWS)
        ],
    )
    planes = pallas_call(
        _reduce_kernel, grid_spec=grid3,
        out_shape=[jax.ShapeDtypeStruct((nwp, 1, LANES), jnp.float32)
                   for _ in range(SPAN_WINDOWS)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary",)),
    )(fmt.perm_sorted, fmt.base_sorted, contrib)

    y = jnp.zeros((nwp, LANES), jnp.float32)
    for d in range(SPAN_WINDOWS):
        y = y + jnp.where(jnp.asarray(fmt.visited[d])[:, None],
                          planes[d][:, 0, :], 0.0)
    return y.reshape(-1)[:n_rows]


def spmv(fmt: GridSpMV, x) -> jnp.ndarray:
    """y = A @ x on the prepared plan (f32)."""
    x = jnp.asarray(x)
    if x.shape != (fmt.n_cols,):
        raise ValueError(f"x must be ({fmt.n_cols},), got {x.shape}")
    return _spmv_impl(fmt, x)


# ---------------------------------------------------------------------------
# k-batched SpMM (VERDICT r4 #4): one fused pass per KT-column group —
# the pattern metadata (cols/flags/emit grids) is read ONCE per group
# instead of once per column, and the three kernel launches amortize
# over KT columns. Ref: cusparseSpMM (sparse/linalg/spmm.hpp:42).
# ---------------------------------------------------------------------------

KT = 8              # columns per fused pass (sublane-aligned)


def _gather_kt_kernel(shard_ref, bt_ref, i_ref, o_ref):
    """Gather one B-column of the KT group for one tile group. The grid
    is (ngroup, KT) with the slot-index block a function of the group
    only, so Pallas keeps it resident across the KT steps — the indices
    are fetched from HBM once per pattern position and reused for every
    column ('gather once per pattern position, broadcast across a k-tile
    of B lanes') while the per-step VMEM footprint stays at the SpMV
    path's (one group plane, not KT of them)."""
    del shard_ref
    o_ref[0, 0] = _tree_gather(bt_ref[0, 0], i_ref[0], i_ref.shape[1])


def _segsum_kt_kernel(g_ref, d_ref, f_ref, e_ref, o_ref, s_ref):
    # grid (ntile, KT): the flags/emit/data blocks depend on the tile
    # index only, so Pallas keeps them resident across the KT steps
    o_ref[0, 0] = _segsum_body(g_ref[0, 0, 0], d_ref[0], f_ref[0],
                               e_ref[0], s_ref)


def _reduce_kt_kernel(perm_ref, base_ref, c_ref, *o_refs):
    del perm_ref
    t = pl.program_id(0)
    prev = base_ref[jnp.maximum(t - 1, 0)]
    first = (t == 0) | (base_ref[t] != prev)
    contrib = c_ref[0]                      # (KT, SUBROWS, LANES)

    @pl.when(first)
    def _init():
        for d in range(SPAN_WINDOWS):
            o_refs[d][0] = contrib[:, d, :]

    @pl.when(jnp.logical_not(first))
    def _acc():
        for d in range(SPAN_WINDOWS):
            o_refs[d][0] += contrib[:, d, :]


@jax.jit
def _spmm_kt_impl(fmt: GridSpMV, bt):
    """One fused KT-column pass. ``bt`` is (KT, n_shards, S, 128) f32
    (transposed, shard-gridded columns of B)."""
    n_rows, _ = fmt.shape
    s_rows = fmt.shard_w // LANES
    ntile = fmt.data_grid.shape[0]
    nwp = fmt.visited.shape[1]
    gsub = GROUP_TILES * SUBROWS
    ngroup = ntile // GROUP_TILES

    grid1 = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(ngroup, KT),
        in_specs=[
            pl.BlockSpec((1, 1, s_rows, LANES),
                         lambda g, q, sh: (q, sh[g], 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, gsub, LANES), lambda g, q, sh: (g, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, 1, gsub, LANES),
                               lambda g, q, sh: (g, q, 0, 0),
                               memory_space=pltpu.VMEM),
    )
    gathered = pallas_call(
        _gather_kt_kernel, grid_spec=grid1,
        out_shape=jax.ShapeDtypeStruct((ngroup, KT, gsub, LANES),
                                       jnp.float32),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary", "arbitrary")),
    )(fmt.group_shard, bt,
      fmt.cols_grid.reshape(ngroup, gsub, LANES))

    # free 5-D view: the (q, stream) group layout re-read per tile —
    # tile t lives at group t // GROUP_TILES, local slab t % GROUP_TILES
    # (the slot stream is group-consecutive, so no transpose is
    # materialized)
    g5 = gathered.reshape(ngroup, KT, GROUP_TILES, SUBROWS, LANES)

    contrib = pallas_call(
        _segsum_kt_kernel,
        grid=(ntile, KT),
        in_specs=[
            # lax.div/rem with explicit i32 constants: python `//` would
            # run jnp type promotion on the traced index, which recurses
            # in jax.export lowering under x64 (same class as the
            # radix-select fori-index workaround)
            pl.BlockSpec((1, 1, 1, SUBROWS, LANES),
                         lambda t, q: (
                             jax.lax.div(t, jnp.int32(GROUP_TILES)), q,
                             jax.lax.rem(t, jnp.int32(GROUP_TILES)), 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, SUBROWS, LANES), lambda t, q: (t, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, SUBROWS, LANES), lambda t, q: (t, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, SUBROWS, LANES), lambda t, q: (t, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, 1, SUBROWS, LANES),
                               lambda t, q: (t, q, 0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((ntile, KT, SUBROWS, LANES),
                                       jnp.float32),
        scratch_shapes=[pltpu.VMEM((SUBROWS, LANES), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary", "arbitrary")),
    )(g5, fmt.data_grid, fmt.flags_grid, fmt.emit_grid)

    grid3 = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(ntile,),
        in_specs=[pl.BlockSpec((1, KT, SUBROWS, LANES),
                               lambda t, pm, bs: (pm[t], 0, 0, 0),
                               memory_space=pltpu.VMEM)],
        out_specs=[
            pl.BlockSpec((1, KT, LANES),
                         (lambda t, pm, bs, _d=d: (bs[t] + _d, 0, 0)),
                         memory_space=pltpu.VMEM)
            for d in range(SPAN_WINDOWS)
        ],
    )
    planes = pallas_call(
        _reduce_kt_kernel, grid_spec=grid3,
        out_shape=[jax.ShapeDtypeStruct((nwp, KT, LANES), jnp.float32)
                   for _ in range(SPAN_WINDOWS)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary",)),
    )(fmt.perm_sorted, fmt.base_sorted, contrib)

    y = jnp.zeros((nwp, KT, LANES), jnp.float32)
    for d in range(SPAN_WINDOWS):
        y = y + jnp.where(jnp.asarray(fmt.visited[d])[:, None, None],
                          planes[d], 0.0)
    # (window, q, lane) -> (row, q)
    return jnp.transpose(y, (0, 2, 1)).reshape(-1, KT)[:n_rows]


def spmm(fmt: GridSpMV, b) -> jnp.ndarray:
    """C = A @ B for dense B (n_cols, k).

    k >= 2 runs the k-batched fused pass per KT-column group (metadata
    read once per group, slot indices gathered once per pattern position
    and reused across the group — VERDICT r4 #4); k == 1 falls through
    to the SpMV kernels."""
    b = jnp.asarray(b)
    if b.ndim != 2 or b.shape[0] != fmt.n_cols:
        raise ValueError(f"b must be ({fmt.n_cols}, k), got {b.shape}")
    k = b.shape[1]
    if k < 2:
        cols = jax.lax.map(lambda col: _spmv_impl(fmt, col), b.T)
        return cols.T
    shard_w = fmt.shard_w
    n_shards = fmt.n_shards
    kg = cdiv(k, KT)
    bp = jnp.zeros((n_shards * shard_w, kg * KT), jnp.float32)
    bp = bp.at[:fmt.n_cols, :k].set(b.astype(jnp.float32))
    bt_groups = bp.T.reshape(kg, KT, n_shards, shard_w // LANES, LANES)
    # static unroll over the (small) group count: kg is ceil(k / 8) and
    # the per-group executable is reused across the unrolled calls
    outs = [_spmm_kt_impl(fmt, bt_groups[g]) for g in range(kg)]
    return jnp.concatenate(outs, axis=1)[:, :k]
