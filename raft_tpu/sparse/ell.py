"""ELL (padded-row) sparse format and kernels — the TPU-preferred layout
for SpMV/SpMM on moderately regular sparsity.

Rationale (SURVEY.md §7 "hard parts"): TPU has no gather/scatter atomics,
and a ``segment_sum`` over the nnz axis serializes through a scatter-add.
Packing each row's nonzeros into a fixed-width [n_rows, width] slab turns
SpMV into a *dense* gather + row reduction — fixed shapes, VPU-vectorized,
no scatter at all — at the cost of padding (stored zeros). The classic
GPU ELL trade-off applies: it wins when max_row_nnz is within a small
factor of mean_row_nnz; `from_csr` reports the padding ratio so callers
(or the auto dispatch in sparse.linalg.spmv) can decide.

The reference keeps CSR/COO only and leans on cuSPARSE's internal formats;
this module is the equivalent of that hidden format choice made explicit.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.core.sparse_types import CSRMatrix
from raft_tpu.util.math import round_up_to_multiple


@dataclasses.dataclass(frozen=True)
class ELLMatrix:
    """Row-padded sparse matrix: cols/data are [n_rows, width]; padding
    lanes have col == 0 and data == 0. Kernels mask on ``row_len`` (lanes
    beyond a row's nnz) rather than trusting the zero data: a padded lane
    gathers x[0], and 0 * inf = nan would otherwise leak into the row sum
    while a stored-zero entry must still propagate inf/nan per IEEE."""

    cols: jnp.ndarray     # int32 [n_rows, width]
    data: jnp.ndarray     # [n_rows, width]
    shape: Tuple[int, int]
    nnz: int
    row_len: jnp.ndarray = None  # int32 [n_rows] — valid lanes per row

    @property
    def n_rows(self) -> int:
        return self.shape[0]

    @property
    def n_cols(self) -> int:
        return self.shape[1]

    @property
    def width(self) -> int:
        return self.cols.shape[1]

    @property
    def padding_ratio(self) -> float:
        """stored / actual nonzeros (1.0 = no waste)."""
        total = self.n_rows * self.width
        return total / max(self.nnz, 1)


def from_csr(csr: CSRMatrix, lane_multiple: int = 8) -> ELLMatrix:
    """Pack CSR into ELL; width = max row nnz rounded up to a lane multiple
    (8 sublanes keeps the slab layout friendly)."""
    indptr = np.asarray(csr.indptr)
    row_len = np.diff(indptr)
    width = int(row_len.max()) if row_len.size else 0
    width = max(round_up_to_multiple(max(width, 1), lane_multiple),
                lane_multiple)
    n_rows = csr.n_rows
    nnz = int(indptr[-1])

    cols_h = np.zeros((n_rows, width), np.int32)
    data_h = np.zeros((n_rows, width), np.asarray(csr.data).dtype)
    src_cols = np.asarray(csr.indices)[:nnz]   # logical slice: bucketing
    src_data = np.asarray(csr.data)[:nnz]      # pads aren't row members
    rows = np.repeat(np.arange(n_rows), row_len)
    lanes = np.arange(nnz) - np.repeat(indptr[:-1], row_len)
    cols_h[rows, lanes] = src_cols
    data_h[rows, lanes] = src_data
    return ELLMatrix(jnp.asarray(cols_h), jnp.asarray(data_h),
                     csr.shape, nnz,
                     row_len=jnp.asarray(row_len.astype(np.int32)))


def _lane_mask(data, row_len):
    if row_len is None:           # legacy slab with no lane bookkeeping
        return None
    return jnp.arange(data.shape[1], dtype=jnp.int32)[None, :] \
        < row_len[:, None]


@jax.jit
def _ell_spmv(cols, data, x, mask):
    # dense gather [n_rows, width] then a fixed-shape row reduction —
    # no segment ids, no scatter; padded lanes masked (0 * inf = nan)
    prod = data * x[cols]
    if mask is not None:
        prod = jnp.where(mask, prod, 0)
    return jnp.sum(prod, axis=1)


def spmv(ell: ELLMatrix, x) -> jnp.ndarray:
    """y = A·x on the ELL slab."""
    return _ell_spmv(ell.cols, ell.data, jnp.asarray(x),
                     _lane_mask(ell.data, ell.row_len))


@jax.jit
def _ell_spmm(cols, data, b, mask):
    # [n_rows, width, k] gather; contraction over width. Padded lanes are
    # masked on the GATHERED operand (so 0-data × b[0]=inf can't make nan)
    bg = b[cols, :]
    if mask is not None:
        bg = jnp.where(mask[:, :, None], bg, 0)
    return jnp.einsum("rw,rwk->rk", data, bg)


def spmm(ell: ELLMatrix, b) -> jnp.ndarray:
    """C = A·B for dense B [n_cols, k]."""
    return _ell_spmm(ell.cols, ell.data, jnp.asarray(b),
                     _lane_mask(ell.data, ell.row_len))


# Auto-dispatch threshold: beyond this stored/actual ratio the padding
# costs more bandwidth than the segment-sum path's scatter.
MAX_AUTO_PADDING = 4.0


def maybe_ell(csr: CSRMatrix):
    """ELL view of ``csr`` when the padding trade-off is favorable, else
    None."""
    indptr = np.asarray(csr.indptr)
    row_len = np.diff(indptr)
    if row_len.size == 0:
        return None
    # judge on the unrounded width (max vs mean row nnz); the lane
    # rounding in from_csr is a constant additive cost, not a skew signal
    stored = csr.n_rows * max(int(row_len.max()), 1)
    nnz = max(int(indptr[-1]), 1)
    if stored / nnz > MAX_AUTO_PADDING:
        return None
    return from_csr(csr)
