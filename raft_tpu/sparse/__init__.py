"""Sparse primitives (ref: cpp/include/raft/sparse/ — formats, conversions,
linalg, ops, matrix helpers, solvers).

TPU design notes
----------------
Sparse irregularity is handled the XLA way, not the CUDA way:

* compute kernels (``spmv``/``spmm``/``sddmm``/``masked_matmul``) are
  formulated as gathers + ``segment_sum`` over a static-``nnz`` buffer, so a
  single trace serves every matrix with the same nnz/shape — no atomics, no
  dynamic shapes inside jit;
* structure-producing ops (sort, dedup, conversions, filtering) run on host
  (numpy) exactly where the reference runs thrust/cub on a stream, because
  their output nnz is data-dependent and would break jit shapes.
"""

from raft_tpu.core.sparse_types import COOMatrix, CSRMatrix  # noqa: F401
from raft_tpu.sparse.ell import ELLMatrix  # noqa: F401

from . import convert, ell, grid_spmv, linalg, matrix, op  # noqa: F401
from raft_tpu.sparse.grid_spmv import GridSpMV  # noqa: F401
from . import solver  # noqa: F401
from raft_tpu.sparse.csr import (weak_cc, weak_cc_batched,  # noqa: F401
                                 weak_cc_mnmg)
