"""Sparse linear algebra (ref: raft/sparse/linalg/{spmm,sddmm,masked_matmul,
add,degree,laplacian,norm,symmetrize,transpose}.*).

TPU formulation: every kernel is a gather + ``segment_sum`` over the nnz
axis — static shapes, no atomics, fully fusable by XLA.  The cuSPARSE
handle-and-buffer dance (detail/cusparse_wrappers.h) disappears: a jitted
function *is* the preprocessed plan, cached by (shape, nnz, dtype).
"""

from __future__ import annotations

import contextlib
import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.core.bitset import Bitmap, Bitset
from raft_tpu.core.sparse_types import COOMatrix, CSRMatrix
from raft_tpu.sparse import convert, op


def _zero_pad_entries(vals, pattern: CSRMatrix):
    """Re-establish the bucketing invariant (pad entries carry data == 0)
    for ops that compute fresh per-nnz values over a padded pattern —
    a pattern pad slot (row n-1, col 0) would otherwise receive a real
    dot product that downstream linear ops would sum in. The mask is the
    DEVICE scalar indptr[-1] (the logical nnz), so this traces under jit;
    for unpadded matrices it is a no-op elementwise select."""
    return jnp.where(jnp.arange(vals.shape[0]) < pattern.indptr[-1],
                     vals, 0)


@functools.partial(jax.jit, static_argnames=("n_rows",))
def _segment_spmv(row_ids, cols, data, x, n_rows: int, limit=None):
    prod = data * x[cols]
    if limit is not None:
        # bucketing pad slots gather x[0]; data there is 0, but 0 * inf
        # (or 0 * nan) is nan — mask the PRODUCT, not just the data
        prod = jnp.where(jnp.arange(prod.shape[0]) < limit, prod, 0)
    return jax.ops.segment_sum(prod, row_ids, num_segments=n_rows,
                               indices_are_sorted=True)


# auto-dispatch threshold for the slot-grid plan: below this the per-call
# plan build (host packing) costs more than the gather it saves
_GRID_MIN_NNZ = 1 << 18

# pad-ratio acceptance bound for the auto grid upgrade (ADVICE r4):
# packing pads a full 1024-slot tile whenever consecutive rows in a
# shard's stream are >8 row-windows apart, so scattered patterns on tall
# matrices can expand slots by orders of magnitude — ballooning device
# memory and running slower than the segment path. The auto path builds
# the plan once and accepts it only under this expansion.
_GRID_MAX_PAD_RATIO = 8.0


def spmv_method(a=None, x=None) -> str:
    """Resolve the SpMV formulation. ``RAFT_TPU_SPMV`` ∈ {auto, grid, ell,
    segment} forces a path; ``auto`` picks the slot-grid Pallas plan
    (grid_spmv.py) for large-nnz matrices on the compiled backend —
    subject to the plan's measured ``pad_ratio`` (≤ 8×) — and the
    ell/segment pair elsewhere. Returns the forced name, or "grid"/"auto"
    for the auto decision.

    ``x`` (optional): the dense operand. The auto upgrade requires f32 on
    BOTH sides — under ``jax_enable_x64`` a f64 operand promotes the
    segment result to f64, and the grid plan (f32 compute) must not flip
    the output dtype based on nnz crossing the threshold."""
    from raft_tpu.core import env

    m = env.read("RAFT_TPU_SPMV")
    if m != "auto" or a is None:
        return m
    from raft_tpu.util.pallas_utils import use_interpret

    if isinstance(a.indptr, jax.core.Tracer) or isinstance(
            a.data, jax.core.Tracer):
        return "auto"   # plans are host-built; never auto-build under jit
    if jnp.dtype(a.data.dtype) != jnp.dtype(jnp.float32):
        return "auto"   # the grid plan computes in f32; keep f64 exact
    if x is not None and jnp.dtype(jnp.asarray(x).dtype) != jnp.dtype(
            jnp.float32):
        return "auto"   # keep x64 promotion semantics on the segment path
    cached = getattr(a, "_spmv_auto_method", None)
    if cached is not None:
        return cached   # one device fetch per MATRIX, not per call
    nnz = int(np.asarray(a.indptr)[-1])
    method = "auto"
    if nnz >= _GRID_MIN_NNZ and not use_interpret():
        plan = _cached_plan(a)
        if plan.pad_ratio <= _GRID_MAX_PAD_RATIO:
            method = "grid"     # plan stays memoized for the apply
        else:
            # reject: free the oversized grid arrays (frozen containers
            # that forbid attribute writes simply skip the memo)
            with contextlib.suppress(AttributeError):
                del a._grid_plan
    with contextlib.suppress(AttributeError):
        a._spmv_auto_method = method
    return method


def spmv(a, x, guard_mode=None) -> jnp.ndarray:
    """y = A·x for sparse A (ref: sparse/linalg/spmv — cusparseSpMV wrapper
    in detail/cusparse_wrappers.h).

    Accepts a prepared GridSpMV plan (the Pallas slot-grid kernels — see
    raft_tpu.sparse.grid_spmv; build with ``grid_spmv.prepare``), a
    CSRMatrix (gather + segment_sum; auto-upgraded to a fresh grid plan
    for large nnz on the compiled backend — prefer preparing once for
    repeated products), or an ELLMatrix (dense row-slab reduction).

    ``guard_mode`` overrides the numeric guard (core/guards.py): under
    ``check``/``recover`` a fused finite sentinel rides the product and
    a non-finite result with finite operands raises
    :class:`~raft_tpu.core.guards.NonFiniteError` (``recover`` retries
    one matmul tier up first). ``off`` (default) adds nothing.

    Admission (ISSUE 5): with a ``runtime.limits`` work budget active
    and a matrix exposing its nnz/shape (CSR, ELL), a product whose
    resident footprint (values + indices + vectors) would overrun the
    budget raises :class:`~raft_tpu.runtime.limits.RejectedError` with
    the estimate — sparse operands admit no bit-equal tiling here. With
    no budget active this path is untouched."""
    from raft_tpu.runtime import limits

    budget = limits.active_budget()
    if budget is not None:
        data = getattr(a, "data", None)
        n_rows = getattr(a, "n_rows", None)
        if data is not None and n_rows is not None:
            xv = jnp.asarray(x)
            est = limits.estimate_bytes(
                "sparse.spmv", n_rows=int(n_rows),
                n_cols=int(xv.shape[0]), nnz=int(jnp.asarray(data).size),
                itemsize=xv.dtype.itemsize)
            if not limits.admit("sparse.spmv", est, budget=budget):
                limits.reject("sparse.spmv", est, budget=budget)

    def compute():
        from raft_tpu.sparse.ell import ELLMatrix, spmv as ell_spmv
        from raft_tpu.sparse.grid_spmv import GridSpMV
        from raft_tpu.sparse.grid_spmv import spmv as grid_apply

        if isinstance(a, GridSpMV):
            return grid_apply(a, x)
        if isinstance(a, ELLMatrix):
            return ell_spmv(a, x)
        method = spmv_method(a, x)
        if method == "grid":
            return grid_apply(_cached_plan(a), x)
        if method == "ell":
            from raft_tpu.sparse.ell import from_csr

            return ell_spmv(from_csr(a), x)
        return _segment_spmv(a.row_ids(), a.indices, a.data, x, a.n_rows,
                             limit=a.indptr[-1])

    out = compute()
    from raft_tpu.core.guards import guard_output, resolve_guard_mode

    if resolve_guard_mode(guard_mode) == "off":
        return out
    from raft_tpu.util.numerics import matmul_escalation

    vals = getattr(a, "data", None)
    inputs = (x,) if vals is None else (vals, x)
    return guard_output("sparse.linalg.spmv", out, inputs=inputs,
                        recover=matmul_escalation(compute,
                                                  op="sparse.linalg.spmv"),
                        mode=guard_mode)


def _cached_plan(a):
    """The matrix's GridSpMV plan, built once and memoized on the object
    (an eager caller's matvec loop must not re-run the host pack per
    call — the plan is the cusparse preprocessed-descriptor analogue
    and has the same once-per-pattern lifetime)."""
    plan = getattr(a, "_grid_plan", None)
    if plan is None:
        from raft_tpu.sparse.grid_spmv import prepare

        plan = prepare(a)
        with contextlib.suppress(AttributeError):
            a._grid_plan = plan    # frozen containers skip the memo
    return plan


@functools.partial(jax.jit, static_argnames=("n_rows",))
def _segment_spmm(row_ids, cols, data, b, n_rows: int, limit=None):
    prods = data[:, None] * b[cols, :]
    if limit is not None:
        prods = jnp.where((jnp.arange(prods.shape[0]) < limit)[:, None],
                          prods, 0)
    return jax.ops.segment_sum(prods, row_ids, num_segments=n_rows,
                               indices_are_sorted=True)


def spmm(a, b, alpha=1.0, beta=0.0, c=None) -> jnp.ndarray:
    """C = alpha·A·B + beta·C for sparse A [m,n], dense B [n,k]
    (ref: sparse/linalg/spmm.hpp:42). Accepts a GridSpMV plan, CSRMatrix
    or ELLMatrix."""
    from raft_tpu.sparse.ell import ELLMatrix, spmm as ell_spmm
    from raft_tpu.sparse.grid_spmv import GridSpMV
    from raft_tpu.sparse.grid_spmv import spmm as grid_spmm

    if isinstance(a, GridSpMV):
        out = grid_spmm(a, jnp.asarray(b))
    elif isinstance(a, ELLMatrix):
        out = ell_spmm(a, jnp.asarray(b))
    else:
        method = spmv_method(a, b)   # same dispatch vocabulary as spmv
        if method == "grid":         # same plan cache as spmv
            out = grid_spmm(_cached_plan(a), jnp.asarray(b))
        elif method == "ell":        # forced RAFT_TPU_SPMV=ell: honor it
            from raft_tpu.sparse.ell import from_csr

            out = ell_spmm(from_csr(a), jnp.asarray(b))
        else:
            out = _segment_spmm(a.row_ids(), a.indices, a.data,
                                jnp.asarray(b), a.n_rows,
                                limit=a.indptr[-1])
    out = alpha * out
    if c is not None and beta != 0.0:
        out = out + beta * jnp.asarray(c)
    return out


@jax.jit
def _pattern_dots(a, bt, row_ids, cols):
    # one fused gather-dot per nnz: sum_k A[i,k] * Bt[k,j] at (i,j) in pattern
    return jnp.einsum("nk,nk->n", a[row_ids, :], bt[:, cols].T)


def sddmm(a, b, pattern: CSRMatrix, alpha=1.0, beta=0.0) -> CSRMatrix:
    """C = alpha·(A·B ∘ spy(C)) + beta·C — sampled dense-dense matmul
    (ref: sparse/linalg/sddmm.hpp:43; A [m,k] and B [k,n] dense, C CSR).

    Only the nnz positions of `pattern` are computed: a gather of A rows and
    B columns followed by a row-wise dot — the TPU analog of cusparseSDDMM."""
    a = jnp.asarray(a)
    b = jnp.asarray(b)
    vals = _pattern_dots(a, b, pattern.row_ids(), pattern.indices)
    new = alpha * vals.astype(pattern.data.dtype)
    if beta != 0.0:
        new = new + beta * pattern.data
    new = _zero_pad_entries(new, pattern)
    return CSRMatrix(pattern.indptr, pattern.indices, new, pattern.shape)


def masked_matmul(a, b, mask, alpha=1.0, beta=0.0,
                  c: Optional[CSRMatrix] = None) -> CSRMatrix:
    """C = alpha·((A·Bᵀ) ∘ spy(mask)) + beta·C
    (ref: sparse/linalg/masked_matmul.cuh:47 bitmap overload, :92 bitset
    overload — bitset = one row's pattern repeated over all m rows).

    A is [m,k], B is [n,k] (row-major, multiplied transposed), mask is a
    Bitmap [m,n] or Bitset [n]."""
    a = jnp.asarray(a)
    b = jnp.asarray(b)
    m = a.shape[0]
    if isinstance(mask, Bitmap):
        pattern = convert.bitmap_to_csr(mask)
    elif isinstance(mask, Bitset):
        pattern = convert.bitset_to_csr(mask, m)
    else:
        pattern = mask  # already a CSR pattern
    vals = _pattern_dots(a, b.T, pattern.row_ids(), pattern.indices)
    new = alpha * vals.astype(a.dtype)
    if c is not None and beta != 0.0:
        new = new + beta * c.data
    new = _zero_pad_entries(new, pattern)
    return CSRMatrix(pattern.indptr, pattern.indices, new,
                     (m, pattern.n_cols))


def csr_add(a: CSRMatrix, b: CSRMatrix) -> CSRMatrix:
    """C = A + B with structural union (ref: sparse/linalg/add.cuh
    `csr_add_calc_inds` / `csr_add_finalize`)."""
    coo_a, coo_b = convert.csr_to_coo(a), convert.csr_to_coo(b)
    rows = jnp.concatenate([coo_a.rows, coo_b.rows])
    cols = jnp.concatenate([coo_a.cols, coo_b.cols])
    data = jnp.concatenate([coo_a.data, coo_b.data])
    merged = op.sum_duplicates(COOMatrix(rows, cols, data, a.shape))
    return convert.sorted_coo_to_csr(merged)


def coo_degree(coo: COOMatrix) -> jnp.ndarray:
    """Per-row nnz count (ref: sparse/linalg/degree.cuh `coo_degree`)."""
    return jax.ops.segment_sum(jnp.ones_like(coo.rows), coo.rows,
                               num_segments=coo.n_rows)


def rows_sum(csr: CSRMatrix) -> jnp.ndarray:
    """Per-row value sum — the degree matrix diagonal for an adjacency."""
    return jax.ops.segment_sum(csr.data, csr.row_ids(), indices_are_sorted=True,
                               num_segments=csr.n_rows)


def csr_row_normalize_l1(csr: CSRMatrix) -> CSRMatrix:
    """Scale each row to unit L1 norm (ref: sparse/linalg/norm.cuh
    `csr_row_normalize_l1`)."""
    row_ids = csr.row_ids()
    norms = jax.ops.segment_sum(jnp.abs(csr.data), row_ids, indices_are_sorted=True,
                                num_segments=csr.n_rows)
    norms = jnp.where(norms == 0, 1, norms)
    return CSRMatrix(csr.indptr, csr.indices, csr.data / norms[row_ids],
                     csr.shape)


def csr_row_normalize_max(csr: CSRMatrix) -> CSRMatrix:
    """Scale each row by its max value (ref: sparse/linalg/norm.cuh
    `csr_row_normalize_max`)."""
    row_ids = csr.row_ids()
    maxs = jax.ops.segment_max(csr.data, row_ids, num_segments=csr.n_rows,
                                indices_are_sorted=True)
    maxs = jnp.where(maxs <= 0, 1, maxs)
    return CSRMatrix(csr.indptr, csr.indices, csr.data / maxs[row_ids],
                     csr.shape)


def transpose(csr: CSRMatrix) -> CSRMatrix:
    """CSR transpose (ref: sparse/linalg/transpose.cuh — cusparseCsr2cscEx2;
    here a host re-sort of the transposed COO)."""
    coo = convert.csr_to_coo(csr)
    flipped = COOMatrix(coo.cols, coo.rows, coo.data,
                        (csr.n_cols, csr.n_rows))
    return convert.sorted_coo_to_csr(op.coo_sort(flipped))


def coo_symmetrize(coo: COOMatrix, reduceat=np.add.reduceat) -> COOMatrix:
    """Symmetrize A by merging it with Aᵀ under a reduction
    (ref: sparse/linalg/symmetrize.cuh:29 `coo_symmetrize` applies an edge
    reduction op to (v_ij, v_ji); default sum)."""
    rows = jnp.concatenate([coo.rows, coo.cols])
    cols = jnp.concatenate([coo.cols, coo.rows])
    data = jnp.concatenate([coo.data, coo.data])
    doubled = COOMatrix(rows, cols, data,
                        (max(coo.shape), max(coo.shape)))
    merged = op.reduce_duplicates(doubled, reduceat)
    return op.coo_remove_zeros(merged)


def symmetrize_knn_graph(knn_indices, knn_dists) -> COOMatrix:
    """Symmetrize a k-NN graph given [n,k] neighbor indices + distances
    (ref: sparse/linalg/symmetrize.cuh:161 `symmetrize` — union of the
    directed k-NN edges and their reverses, max-merged)."""
    idx = np.asarray(knn_indices)
    dist = np.asarray(knn_dists)
    n, k = idx.shape
    rows = np.repeat(np.arange(n, dtype=idx.dtype), k)
    coo = COOMatrix(jnp.asarray(rows), jnp.asarray(idx.ravel()),
                    jnp.asarray(dist.ravel()), (n, n))
    return coo_symmetrize(coo, np.maximum.reduceat)


def laplacian(csr: CSRMatrix) -> CSRMatrix:
    """Graph Laplacian L = D − A of a CSR adjacency matrix
    (ref: sparse/linalg/laplacian.cuh `compute_graph_laplacian`,
    detail/laplacian.cuh:40 — self-loops are ignored and each row gains a
    diagonal degree entry)."""
    if csr.n_rows != csr.n_cols:
        raise ValueError("Laplacian requires a square adjacency matrix")
    coo = convert.csr_to_coo(csr).to_host()
    off_diag = coo.rows != coo.cols
    rows = coo.rows[off_diag]
    cols = coo.cols[off_diag]
    vals = coo.data[off_diag]
    deg = np.zeros(csr.n_rows, dtype=vals.dtype)
    np.add.at(deg, rows, vals)
    n = csr.n_rows
    all_rows = np.concatenate([rows, np.arange(n, dtype=rows.dtype)])
    all_cols = np.concatenate([cols, np.arange(n, dtype=cols.dtype)])
    all_vals = np.concatenate([-vals, deg])
    merged = COOMatrix(jnp.asarray(all_rows), jnp.asarray(all_cols),
                       jnp.asarray(all_vals), (n, n))
    return convert.sorted_coo_to_csr(op.coo_sort(merged))


def laplacian_normalized(csr: CSRMatrix) -> CSRMatrix:
    """Symmetric-normalized Laplacian D^{-1/2}·L·D^{-1/2}
    (ref: sparse/linalg/laplacian.cuh `laplacian_normalized`; zero degrees
    are treated as one, detail/laplacian.cuh `zero_to_one_functor`)."""
    lap = laplacian(csr)
    deg = np.zeros(csr.n_rows, dtype=np.asarray(lap.data).dtype)
    coo = convert.csr_to_coo(csr).to_host()
    off_diag = coo.rows != coo.cols
    np.add.at(deg, coo.rows[off_diag], coo.data[off_diag])
    deg = np.where(deg == 0, 1, deg)
    inv_sqrt = jnp.asarray(1.0 / np.sqrt(deg))
    row_ids = lap.row_ids()
    vals = lap.data * inv_sqrt[row_ids] * inv_sqrt[lap.indices]
    return CSRMatrix(lap.indptr, lap.indices, vals, lap.shape)


def csr_row_norm(csr: CSRMatrix, norm_type: str = "l2") -> jnp.ndarray:
    """Per-row norms of a CSR matrix (ref: sparse/linalg/norm.cuh
    rowNormCsr — l1/l2/linf over each row's stored values).

    >>> import numpy as np, scipy.sparse as sp
    >>> from raft_tpu.core.sparse_types import CSRMatrix
    >>> from raft_tpu.sparse.linalg import csr_row_norm
    >>> a = sp.csr_matrix(np.array([[3., 4.], [0., 2.]]))
    >>> np.asarray(csr_row_norm(CSRMatrix.from_scipy(a))).tolist()
    [5.0, 2.0]
    """
    rows = csr.row_ids()
    if norm_type == "l1":
        return jax.ops.segment_sum(jnp.abs(csr.data), rows,
                                   num_segments=csr.n_rows,
                                   indices_are_sorted=True)
    if norm_type == "l2":
        return jnp.sqrt(jax.ops.segment_sum(csr.data * csr.data, rows,
                                            num_segments=csr.n_rows,
                                            indices_are_sorted=True))
    if norm_type == "linf":
        # clamp: empty rows see segment_max's -inf identity; |x| ≥ 0 makes
        # the clamp a no-op for any non-empty row
        return jnp.maximum(
            jax.ops.segment_max(jnp.abs(csr.data), rows,
                                num_segments=csr.n_rows,
                                indices_are_sorted=True), 0.0)
    raise ValueError(f"norm_type must be l1|l2|linf, got {norm_type}")


# Reference-spelling aliases (sparse/linalg/{degree,symmetrize}.cuh).
degree = coo_degree
symmetrize = coo_symmetrize
