"""Format conversions (ref: raft/sparse/convert/{coo,csr,dense}.cuh,
detail/adj_to_csr.cuh, detail/bitmap_to_csr.cuh, detail/bitset_to_csr.cuh).

Output nnz is data-dependent for most conversions, so these run host-side
(the reference likewise drives them from host code with device scans); the
results are returned as device arrays ready for the jitted compute ops.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
import numpy as np

from raft_tpu.core.bitset import Bitmap, Bitset
from raft_tpu.core.sparse_types import COOMatrix, CSRMatrix


def _host(x) -> np.ndarray:
    return np.asarray(x)


def _counts_to_indptr(rows: np.ndarray, n_rows: int,
                      dtype=np.int32) -> np.ndarray:
    """Row-occurrence counts → CSR indptr (shared by every *_to_csr)."""
    counts = np.bincount(rows, minlength=n_rows)
    indptr = np.zeros(n_rows + 1, dtype=dtype)
    np.cumsum(counts, out=indptr[1:])
    return indptr


def sorted_coo_to_csr(coo: COOMatrix) -> CSRMatrix:
    """Row-sorted COO → CSR (ref: sparse/convert/csr.cuh `sorted_coo_to_csr`).

    The rows array must already be sorted (use op.coo_sort first)."""
    rows = _host(coo.rows)
    indptr = _counts_to_indptr(rows, coo.n_rows, dtype=rows.dtype)
    return CSRMatrix(jnp.asarray(indptr), jnp.asarray(coo.cols),
                     jnp.asarray(coo.data), coo.shape)


def csr_to_coo(csr: CSRMatrix) -> COOMatrix:
    """CSR → COO by expanding indptr into per-nnz row ids
    (ref: sparse/convert/coo.cuh `csr_to_coo`)."""
    indptr = _host(csr.indptr)
    rows = np.repeat(np.arange(csr.n_rows, dtype=_host(csr.indices).dtype),
                     np.diff(indptr))
    # logical nnz: drops bucketing pad entries, so every conversion-based
    # consumer (transpose, laplacian, csr_add, ...) sees the true structure
    n = int(indptr[-1])
    return COOMatrix(jnp.asarray(rows), jnp.asarray(csr.indices[:n]),
                     jnp.asarray(csr.data[:n]), csr.shape)


def dense_to_csr(dense, tol: float = 0.0) -> CSRMatrix:
    """Dense → CSR keeping entries with |x| > tol
    (reverse of csr_to_dense; used by tests and masked_matmul setup)."""
    d = _host(dense)
    mask = np.abs(d) > tol
    rows, cols = np.nonzero(mask)
    indptr = _counts_to_indptr(rows, d.shape[0])
    return CSRMatrix(jnp.asarray(indptr), jnp.asarray(cols.astype(np.int32)),
                     jnp.asarray(d[rows, cols]), d.shape)


def csr_to_dense(csr: CSRMatrix) -> jnp.ndarray:
    """CSR → dense (ref: sparse/convert/dense.cuh `csr_to_dense`).

    jit-compatible: scatter-add into a zero matrix with static shapes."""
    row_ids = csr.row_ids()
    out = jnp.zeros(csr.shape, dtype=csr.data.dtype)
    return out.at[row_ids, csr.indices].add(csr.data)


def adj_to_csr(adj, row_ind: Optional[np.ndarray] = None) -> CSRMatrix:
    """Boolean adjacency matrix → CSR with unit values
    (ref: sparse/convert/csr.cuh `adj_to_csr`, detail/adj_to_csr.cuh)."""
    a = _host(adj).astype(bool)
    rows, cols = np.nonzero(a)
    indptr = _counts_to_indptr(rows, a.shape[0])
    data = np.ones(rows.shape[0], dtype=np.float32)
    return CSRMatrix(jnp.asarray(indptr), jnp.asarray(cols.astype(np.int32)),
                     jnp.asarray(data), a.shape)


def bitmap_to_csr(bitmap: Bitmap) -> CSRMatrix:
    """Bitmap mask (n_rows × n_cols bits) → CSR structure with unit values
    (ref: sparse/convert/csr.cuh `bitmap_to_csr`, detail/bitmap_to_csr.cuh)."""
    return adj_to_csr(bitmap.to_bool_matrix())


def bitset_to_csr(bitset: Bitset, n_rows: int) -> CSRMatrix:
    """Single-row bitset repeated over n_rows → CSR
    (ref: sparse/convert/csr.cuh `bitset_to_csr`, detail/bitset_to_csr.cuh:
    every row of the output has the same sparsity pattern)."""
    bools = _host(bitset.to_bools())
    cols = np.nonzero(bools)[0].astype(np.int32)
    nnz_row = cols.shape[0]
    indptr = (np.arange(n_rows + 1, dtype=np.int32) * nnz_row).astype(np.int32)
    cols_all = np.tile(cols, n_rows)
    data = np.ones(cols_all.shape[0], dtype=np.float32)
    return CSRMatrix(jnp.asarray(indptr), jnp.asarray(cols_all),
                     jnp.asarray(data), (n_rows, bitset.size))


# Reference-spelling alias (sparse/convert/csr.cuh: the sorted-COO→CSR
# path is the conversion the reference exposes as coo_to_csr).
coo_to_csr = sorted_coo_to_csr
