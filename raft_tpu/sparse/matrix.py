"""Sparse matrix helpers: CSR select_k, diagonal ops, TF-IDF / BM25 encoders
(ref: raft/sparse/matrix/{select_k,diagonal,preprocessing}.cuh).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.core.sparse_types import COOMatrix, CSRMatrix
from raft_tpu.matrix import select_k as dense_select_k
from raft_tpu.matrix.select_k import SelectAlgo
from raft_tpu.sparse import convert


def select_k(res, csr: CSRMatrix, k: int, select_min: bool = True,
             in_idx=None, algo=SelectAlgo.AUTO
             ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Per-row top-k over a CSR matrix with logical shape [batch, len]
    (ref: sparse/matrix/select_k.cuh:64).

    Returns (values [batch,k], indices [batch,k]); rows with fewer than k
    entries are padded with the dummy bound value and index -1.  TPU
    formulation: scatter the ragged rows into a padded [batch, max_row_len]
    tile (static shape), then run the dense select_k path — the irregular
    part is a single scatter, the selection rides the tuned dense kernel.
    Dense-band cells (max_row_len inside radix_select.preferred's band)
    therefore ride the digit-histogram radix kernel under the default
    AUTO dispatch; ``algo`` passes an explicit SelectAlgo through to the
    dense tournament, and the selection is bit-identical to dense
    select_k over the same materialized rows (the pad sentinel sorts
    strictly last and can only surface on under-filled rows, where both
    paths emit it)."""
    indptr = np.asarray(csr.indptr)
    row_len = np.diff(indptr)
    max_len = max(int(row_len.max()) if row_len.size else 0, k)
    n_rows = csr.n_rows

    dtype = np.dtype(csr.data.dtype)
    pad_val = np.inf if select_min else -np.inf
    if not np.issubdtype(dtype, np.floating):
        info = np.iinfo(dtype)
        pad_val = info.max if select_min else info.min

    # position of each nnz inside its row; bucketing pad entries (beyond
    # indptr[-1]) are pushed out of bounds so the scatter drops them —
    # otherwise their zero values would land in the last row's padding
    # slots and win the selection over real negative entries
    row_ids = csr.row_ids()
    offsets = jnp.arange(csr.nnz) - jnp.asarray(indptr[:-1])[row_ids]
    offsets = jnp.where(jnp.arange(csr.nnz) < int(indptr[-1]),
                        offsets, max_len)
    padded_val = jnp.full((n_rows, max_len), pad_val, dtype=csr.data.dtype)
    padded_val = padded_val.at[row_ids, offsets].set(csr.data,
                                                     mode="drop")
    col_src = jnp.asarray(in_idx)[csr.indices] if in_idx is not None \
        else csr.indices
    padded_idx = jnp.full((n_rows, max_len), -1, dtype=csr.indices.dtype)
    padded_idx = padded_idx.at[row_ids, offsets].set(col_src, mode="drop")

    vals, pos = dense_select_k(res, padded_val, k, select_min=select_min,
                               algo=algo)
    idx = jnp.take_along_axis(padded_idx, pos, axis=1)
    # positions selected from padding keep index -1
    valid = pos < jnp.asarray(row_len)[:, None]
    idx = jnp.where(valid, idx, -1)
    return vals, idx


def diagonal(mat) -> jnp.ndarray:
    """Extract the diagonal of a CSR/COO matrix as a dense vector
    (ref: sparse/matrix/diagonal.cuh:21,92)."""
    if isinstance(mat, CSRMatrix):
        coo = convert.csr_to_coo(mat)
    else:
        coo = mat
    on_diag = coo.rows == coo.cols
    n = min(coo.shape)
    contrib = jnp.where(on_diag, coo.data, 0)
    return jax.ops.segment_sum(contrib, jnp.minimum(coo.rows, n - 1),
                               num_segments=n)


def set_diagonal(csr: CSRMatrix, scalar) -> CSRMatrix:
    """Set existing diagonal entries to a scalar value
    (ref: sparse/matrix/diagonal.cuh:69 `set_diagonal`)."""
    row_ids = csr.row_ids()
    on_diag = (row_ids == csr.indices) \
        & (jnp.arange(csr.nnz) < csr.indptr[-1])   # jit-safe pad mask
    return CSRMatrix(csr.indptr, csr.indices,
                     jnp.where(on_diag, scalar, csr.data), csr.shape)


def scale_by_diagonal_symmetric(csr: CSRMatrix) -> CSRMatrix:
    """A[i,j] /= sqrt(d[i])·sqrt(d[j]) (ref: sparse/matrix/diagonal.cuh:44
    `scale_by_diagonal_symmetric`)."""
    d = diagonal(csr)
    inv = jnp.where(d != 0, 1.0 / jnp.sqrt(jnp.abs(d)), 1.0)
    row_ids = csr.row_ids()
    return CSRMatrix(csr.indptr, csr.indices,
                     csr.data * inv[row_ids] * inv[csr.indices], csr.shape)


# ---------------------------------------------------------------------------
# Text preprocessing (ref: sparse/matrix/preprocessing.cuh:28-101,
# detail/preprocessing.cuh — fit_tfidf/fit_bm25 + transform kernels)
# ---------------------------------------------------------------------------

def _fit_counts(coo: COOMatrix):
    """featIdCount[c] = nnz entries in column c (documents containing the
    feature); fullIdLen = sum of all values (total token count)
    (ref: detail/preprocessing.cuh fit_tfidf:61-89)."""
    n_cols = coo.n_cols
    feat_count = jax.ops.segment_sum(jnp.ones_like(coo.cols), coo.cols,
                                     num_segments=n_cols)
    full_len = jnp.sum(coo.data)
    return feat_count, full_len


def encode_tfidf(coo_or_csr) -> jnp.ndarray:
    """TF-IDF value per nnz entry (ref: sparse/matrix/preprocessing.cuh:28
    `encode_tfidf`; transform kernel detail/preprocessing.cuh:199-213:
    tf = log(v), idf = log(num_rows / featIdCount[col] + 1), out = tf·idf)."""
    coo = convert.csr_to_coo(coo_or_csr) \
        if isinstance(coo_or_csr, CSRMatrix) else coo_or_csr
    feat_count, _ = _fit_counts(coo)
    tf = jnp.log(coo.data.astype(jnp.float32))
    idf = jnp.log(coo.n_rows / feat_count[coo.cols].astype(jnp.float32) + 1.0)
    return tf * idf


def encode_bm25(coo_or_csr, k_param: float = 1.6,
                b_param: float = 0.75) -> jnp.ndarray:
    """Okapi BM25 value per nnz entry (ref: sparse/matrix/preprocessing.cuh
    `encode_bm25`; transform kernel detail/preprocessing.cuh:162-184:
    bm = ((k1+1)·tf) / (k1·((1−b) + b·rowLen/avgLen) + tf), out = idf·bm)."""
    coo = convert.csr_to_coo(coo_or_csr) \
        if isinstance(coo_or_csr, CSRMatrix) else coo_or_csr
    feat_count, full_len = _fit_counts(coo)
    row_len = jax.ops.segment_sum(coo.data, coo.rows,
                                  num_segments=coo.n_rows)
    avg_len = full_len.astype(jnp.float32) / coo.n_rows
    tf = jnp.log(coo.data.astype(jnp.float32))
    idf = jnp.log(coo.n_rows / feat_count[coo.cols].astype(jnp.float32) + 1.0)
    bm = ((k_param + 1.0) * tf) / (
        k_param * ((1.0 - b_param)
                   + b_param * (row_len[coo.rows].astype(jnp.float32)
                                / avg_len)) + tf)
    return idf * bm


# Reference-spelling alias (sparse/matrix/diagonal.cuh get_diagonal).
get_diagonal = diagonal
