"""Batch executor: per-bucket warmed executables, one device launch per
coalesced batch (serving tentpole, part 2).

Three service kinds wrap the library's row-independent query primitives
— brute-force kNN (:class:`KnnService`), pairwise distance
(:class:`PairwiseService`), and kmeans assignment
(:class:`KMeansPredictService`). Row independence is the whole game:
each output row depends only on its own query row plus the service's
fixed operand (database / corpus / centroids), so concatenating many
tenants' rows, padding to the shape bucket, launching once, and slicing
back per request is BIT-IDENTICAL to running each request alone (the
same invariant the PR-5 row-tiled degraded paths are CI-gated on).

Compile discipline: a serving executable is built once per
(service, bucket) through :mod:`raft_tpu.runtime.aot` —
``aot_export`` lowers the traced function to a versioned StableHLO
artifact, and the executor runs ``jax.jit(exported.call)`` so repeat
launches hit the jit cache with zero Python retracing (functions whose
lowering cannot serialize fall back to plain ``jax.jit``, same
warm-once contract). :meth:`Executor.warm` walks the bucket ladder and
invokes every executable once, so steady-state serving performs ZERO
compiles — asserted by tests via the executor's trace counter (the
Python-trace hook that ticks exactly when a jit cache misses) and
metered through ``runtime_compile_cache_total{cache="serve"}``.

QoS enforcement at dispatch (policy in ``serve/qos.py``):

- requests that expired in queue fail fast with
  ``DeadlineExceededError`` before any padding or launch;
- a batch whose footprint estimate exceeds the serving budget is SPLIT
  in half recursively (each half re-buckets to a smaller warmed
  executable — the serve-layer spelling of row tiling);
- a single request that cannot fit even alone runs EAGERLY under
  ``limits.budget_scope``, where the PR-5 instrumented entry points
  degrade to their bit-identical row-tiled paths or raise the typed
  ``RejectedError`` the caller's future surfaces.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu import obs
from raft_tpu.runtime import limits
from raft_tpu.serve.queue import (Batch, BatchPolicy, Request,
                                  RequestQueue, bucket_ladder,
                                  bucket_rows)

__all__ = [
    "Service", "KnnService", "IvfKnnService", "IvfPqKnnService",
    "IvfMnmgKnnService", "PairwiseService", "KMeansPredictService",
    "Executor", "ExecutorStats",
]


class Service:
    """One servable query op: a fixed operand (database, corpus,
    centroids) plus a pure row-independent function of the query block.

    Subclasses define ``_build()`` returning the traced function (first
    arguments = the fixed operands, last = the query block) and
    :meth:`unpack` mapping (batched output, row span) to one request's
    result."""

    name: str = "service"

    def __init__(self, fixed_args: Tuple, dim: int, dtype=jnp.float32):
        # (serve_epoch, fixed operands, per-epoch statics) swapped as
        # ONE tuple: a concurrent dispatch reads the whole snapshot in
        # a single attribute load, so it can never pair new-shape
        # operands with an executable compiled for the old shapes (the
        # streaming-compaction torn-swap hazard). The epoch is part of
        # the executor's executable-cache key.
        self._serving: Tuple = (
            0, tuple(jnp.asarray(a) for a in fixed_args), None)
        self.dim = int(dim)
        self.dtype = jnp.dtype(dtype)

    @property
    def fixed_args(self) -> Tuple:
        return self._serving[1]

    @property
    def serve_epoch(self) -> int:
        return self._serving[0]

    def serving(self) -> Tuple:
        """Atomic serving snapshot ``(epoch, fixed_args, statics)`` —
        dispatch reads this once per launch and threads the same
        snapshot through cache lookup and the call itself."""
        return self._serving

    def swap_fixed_args(self, fixed_args: Tuple, *, statics=None,
                        bump_epoch: bool = False) -> int:
        """Publish new fixed operands (single writer). Same-shape swaps
        keep the epoch — warmed executables stay valid because AOT
        bakes shapes, not values; a shape-changing swap must pass
        ``bump_epoch=True`` so stale-shape executables are never
        reused. Returns the serving epoch now in force."""
        epoch = self._serving[0] + (1 if bump_epoch else 0)
        self._serving = (
            epoch, tuple(jnp.asarray(a) for a in fixed_args), statics)
        return epoch

    # -- subclass surface ---------------------------------------------

    def _build(self) -> Callable:
        raise NotImplementedError

    def _build_for(self, serving: Tuple) -> Callable:
        """Build the traced function for one serving snapshot. The
        default ignores the snapshot (static services); epoch-swapping
        services override this to close over ``serving[2]`` so the
        compiled statics always match the snapshot's shapes."""
        return self._build()

    def unpack(self, out, start: int, rows: int):
        """Slice one request's rows back out of the batched output."""
        raise NotImplementedError

    def estimate_bytes(self, rows: int) -> int:
        """HBM footprint estimate for a ``rows``-row launch (feeds the
        batch budget check)."""
        raise NotImplementedError

    def eager(self, queries):
        """Unbatched reference path — the public API call the degraded
        (budget_scope) route takes. Must return exactly what
        :meth:`unpack` returns for those rows."""
        raise NotImplementedError

    # -- shared -------------------------------------------------------

    def example(self, rows: int) -> jnp.ndarray:
        return jnp.zeros((rows, self.dim), self.dtype)

    def validate(self, queries: np.ndarray) -> None:
        if queries.ndim != 2 or queries.shape[1] != self.dim:
            raise ValueError(
                f"{self.name}: queries must be [rows, {self.dim}], "
                f"got {queries.shape}")


class KnnService(Service):
    """Batched brute-force kNN against a fixed database
    (:func:`raft_tpu.neighbors.knn`). Per-request result:
    ``(distances [rows, k], indices [rows, k])``."""

    def __init__(self, db, k: int, metric: str = "l2"):
        db = jnp.asarray(db)
        super().__init__((db,), dim=db.shape[1], dtype=db.dtype)
        self.k = int(k)
        self.metric = metric
        self.name = f"knn_k{k}_{metric}"

    def _build(self):
        from raft_tpu.neighbors import knn

        k, metric = self.k, self.metric

        def fn(db, q):
            return knn(None, db, q, k=k, metric=metric)
        return fn

    def unpack(self, out, start, rows):
        d, i = out
        return d[start:start + rows], i[start:start + rows]

    def estimate_bytes(self, rows):
        db = self.fixed_args[0]
        return limits.estimate_bytes(
            "neighbors.brute_force_knn", n_queries=rows,
            n_db=db.shape[0], n_dims=self.dim, k=self.k,
            itemsize=self.dtype.itemsize)

    def eager(self, queries):
        from raft_tpu.neighbors import knn

        return knn(None, self.fixed_args[0], jnp.asarray(queries),
                   k=self.k, metric=self.metric)

    def epilogue(self) -> str:
        """Which selection epilogue this service's launches take —
        "fused" (k <= 256), "radix" (the digit-histogram chunked path
        above it), or "scan" — quoted straight from
        :func:`raft_tpu.neighbors.brute_force.knn_plan`, the predicate
        knn() itself routes through, so the warm-path report can never
        drift from the compiled dispatch."""
        from raft_tpu.neighbors.brute_force import knn_plan

        path, _ = knn_plan(1, int(self.fixed_args[0].shape[0]), self.k,
                           metric=self.metric)
        return path

    def selection_bytes(self, rows: int) -> int:
        """Modeled selection-stage HBM bytes for a ``rows``-row launch
        on the radix epilogue ((NPASS+2) streamed passes over the
        (rows, n_db) distance block — benches/select_model.py is the
        canonical statement of the model); 0 off the radix path."""
        if self.epilogue() != "radix":
            return 0
        from raft_tpu.matrix.radix_select import NPASS

        return (NPASS + 2) * rows * int(self.fixed_args[0].shape[0]) * 4


class IvfKnnService(Service):
    """Batched IVF-Flat kNN against a fixed index
    (:func:`raft_tpu.neighbors.ivf_flat.search`). One instance per
    (k, nprobe) — the executor's (service, bucket) executable cache then
    holds one warmed executable per (bucket, nprobe), so sweeping nprobe
    at steady state never compiles. Per-request result:
    ``(distances [rows, k], indices [rows, k])`` in original database
    row numbering. Row independence holds (each query row's coarse
    probe, gather and epilogue see only its own row), so the batched
    launch is bit-identical to per-request eager searches.

    Full scans (nprobe >= n_lists) are exact brute force by definition —
    serve those through :class:`KnnService` on the reconstructed
    database instead; this service rejects the degenerate setting."""

    def __init__(self, index, k: int, nprobe: int):
        super().__init__((index.centroids, index.packed_db,
                          index.packed_ids, index.starts, index.sizes),
                         dim=index.dim, dtype=index.packed_db.dtype)
        if not 0 < nprobe < index.n_lists:
            raise ValueError(
                f"IvfKnnService needs 0 < nprobe < n_lists "
                f"(got nprobe={nprobe}, n_lists={index.n_lists}); "
                f"nprobe >= n_lists is a full scan — use KnnService on "
                f"index.reconstruct()")
        self.index = index
        self.k = int(k)
        self.nprobe = int(nprobe)
        self.name = f"ivf_knn_k{k}_np{nprobe}_{index.metric}"

    def _build(self):
        from raft_tpu.neighbors.ivf_flat import _search_body, _use_radix

        k, nprobe = self.k, self.nprobe
        cap_max, metric = self.index.cap_max, self.index.metric
        use_radix = _use_radix(nprobe * cap_max, k, self.fixed_args[1])

        def fn(centroids, packed_db, packed_ids, starts, sizes, q):
            return _search_body(q, centroids, packed_db, packed_ids,
                                starts, sizes, k=k, nprobe=nprobe,
                                cap_max=cap_max, metric=metric,
                                use_radix=use_radix)
        return fn

    def unpack(self, out, start, rows):
        d, i = out
        return d[start:start + rows], i[start:start + rows]

    def estimate_bytes(self, rows):
        return limits.estimate_bytes(
            "neighbors.ivf_search", n_queries=rows,
            probe_rows=self.nprobe * self.index.cap_max,
            n_dims=self.dim, k=self.k, itemsize=self.dtype.itemsize,
            packed_rows=int(self.index.packed_db.shape[0]))

    def eager(self, queries):
        from raft_tpu.neighbors import ivf_flat

        return ivf_flat.search(None, self.index, jnp.asarray(queries),
                               self.k, self.nprobe)

    def epilogue(self) -> str:
        """"ivf" — quoted from :func:`knn_plan` with this service's
        (n_lists, nprobe), the same predicate the brute-force services
        quote, so the warm-path report and the compiled dispatch share
        one source of truth."""
        from raft_tpu.neighbors.brute_force import knn_plan

        path, _ = knn_plan(1, self.index.n_db, self.k,
                           metric=self.index.metric,
                           n_lists=self.index.n_lists,
                           nprobe=self.nprobe)
        return path


class IvfPqKnnService(Service):
    """Batched IVF-PQ kNN against a fixed index
    (:func:`raft_tpu.neighbors.ivf_pq.search`'s ADC path). One
    instance per (k, nprobe) — the executor's (service, bucket)
    executable cache then holds one warmed executable per
    (bucket, nprobe), so sweeping nprobe at steady state never
    compiles. Per-request result: ``(distances [rows, k], indices
    [rows, k])`` in original database row numbering; distances are
    asymmetric PQ distances (the served trade: the index in HBM is the
    compressed one). Row independence holds exactly as for
    :class:`IvfKnnService`, so the batched launch is bit-identical to
    per-request eager searches.

    The refine stage re-scores against HOST-side raw rows and is an
    offline/eager lever (:func:`raft_tpu.neighbors.ivf_pq.search` with
    ``refine > 0``) — the served hot path stays one device launch.
    Full scans (nprobe >= n_lists) are exact brute force by definition
    — serve those through :class:`KnnService` on ``index.raw()``; this
    service rejects the degenerate setting."""

    def __init__(self, index, k: int, nprobe: int):
        super().__init__((index.centroids, index.codebooks,
                          index.packed_codes, index.packed_ids,
                          index.starts, index.sizes),
                         dim=index.dim, dtype=jnp.float32)
        if not 0 < nprobe < index.n_lists:
            raise ValueError(
                f"IvfPqKnnService needs 0 < nprobe < n_lists "
                f"(got nprobe={nprobe}, n_lists={index.n_lists}); "
                f"nprobe >= n_lists is a full scan — use KnnService on "
                f"index.raw()")
        self.index = index
        self.k = int(k)
        self.nprobe = int(nprobe)
        self.name = (f"ivf_pq_knn_k{k}_np{nprobe}_m{index.m}"
                     f"_{index.metric}")

    def _build(self):
        from raft_tpu.neighbors.ivf_pq import (_search_body,
                                               _use_onehot_lut)
        from raft_tpu.neighbors.ivf_flat import _use_radix

        k, nprobe = self.k, self.nprobe
        cap_max, metric = self.index.cap_max, self.index.metric
        use_radix = _use_radix(nprobe * cap_max, k, self.fixed_args[3])
        use_onehot = _use_onehot_lut()

        def fn(centroids, codebooks, packed_codes, packed_ids, starts,
               sizes, q):
            return _search_body(q, centroids, codebooks, packed_codes,
                                packed_ids, starts, sizes, k=k,
                                nprobe=nprobe, cap_max=cap_max,
                                metric=metric, use_radix=use_radix,
                                use_onehot=use_onehot)
        return fn

    def unpack(self, out, start, rows):
        d, i = out
        return d[start:start + rows], i[start:start + rows]

    def estimate_bytes(self, rows):
        return limits.estimate_bytes(
            "neighbors.ivf_pq_search", n_queries=rows,
            nprobe=self.nprobe,
            probe_rows=self.nprobe * self.index.cap_max,
            n_dims=self.dim, k=self.k, m=self.index.m,
            n_codes=self.index.n_codes, itemsize=self.dtype.itemsize,
            packed_rows=int(self.index.packed_codes.shape[0]))

    def eager(self, queries):
        from raft_tpu.neighbors import ivf_pq

        return ivf_pq.search(None, self.index, jnp.asarray(queries),
                             self.k, self.nprobe)

    def epilogue(self) -> str:
        """"ivf_pq" — quoted from :func:`knn_plan` with this service's
        (n_lists, nprobe, pq=True), the same predicate the other kNN
        services quote, so the warm-path report and the compiled
        dispatch share one source of truth."""
        from raft_tpu.neighbors.brute_force import knn_plan

        path, _ = knn_plan(1, self.index.n_db, self.k,
                           metric=self.index.metric,
                           n_lists=self.index.n_lists,
                           nprobe=self.nprobe, pq=True)
        return path


class IvfMnmgKnnService(Service):
    """Batched sharded IVF-Flat kNN against a fixed
    :class:`~raft_tpu.neighbors.ivf_mnmg.IvfMnmgIndex`
    (:func:`raft_tpu.neighbors.ivf_mnmg.search_mnmg`'s one-program
    ``shard_map`` path as the traced body — coarse probe replicated,
    per-rank gather/score/select, in-graph candidate all-gather, global
    merge). Per-request result: ``(distances [rows, k], indices
    [rows, k])`` in global database row numbering; row independence
    holds exactly as for the single-rank service, so the batched launch
    is bit-identical to per-request searches.

    Full scans (nprobe >= n_lists) delegate to brute force by
    definition — serve those via :class:`KnnService` on
    ``index.reconstruct()``; this service rejects the degenerate
    setting just like :class:`IvfKnnService`."""

    def __init__(self, index, k: int, nprobe: int):
        super().__init__((index.flat.centroids, index.packed_db_sh,
                          index.packed_ids_sh, index.starts_sh,
                          index.sizes_sh),
                         dim=index.dim, dtype=index.packed_db_sh.dtype)
        if not 0 < nprobe < index.n_lists:
            raise ValueError(
                f"IvfMnmgKnnService needs 0 < nprobe < n_lists "
                f"(got nprobe={nprobe}, n_lists={index.n_lists}); "
                f"nprobe >= n_lists is a full scan — use KnnService on "
                f"index.reconstruct()")
        self.index = index
        self.k = int(k)
        self.nprobe = int(nprobe)
        self.name = (f"ivf_mnmg_k{k}_np{nprobe}_r{index.n_ranks}"
                     f"_{index.metric}")

    def _build(self):
        from jax.sharding import PartitionSpec as P

        from raft_tpu.neighbors.ivf_flat import _probe_topk
        from raft_tpu.neighbors.ivf_mnmg import _merge_body, _radix_flags

        idx = self.index
        k, nprobe = self.k, self.nprobe
        cap_max, metric = idx.cap_max, idx.metric
        mesh, axis, n_ranks = idx.mesh, idx.axis, idx.n_ranks
        use_radix, use_radix_merge = _radix_flags(
            idx, k, nprobe, self.fixed_args[1])

        def shard_fn(db_s, ids_s, st_s, sz_s, q, c):
            vals, ids = _probe_topk(
                q, c, db_s[0], ids_s[0], st_s[0], sz_s[0], k=k,
                nprobe=nprobe, cap_max=cap_max, metric=metric,
                use_radix=use_radix)
            return vals[None], ids[None]

        def fn(centroids, db_sh, ids_sh, starts_sh, sizes_sh, q):
            av, ai = jax.shard_map(
                shard_fn, mesh=mesh,
                in_specs=(P(axis), P(axis), P(axis), P(axis), P(), P()),
                out_specs=(P(axis), P(axis)))(
                    db_sh, ids_sh, starts_sh, sizes_sh, q, centroids)
            pool_v = jnp.moveaxis(av, 0, 1).reshape(
                q.shape[0], n_ranks * k)
            pool_i = jnp.moveaxis(ai, 0, 1).reshape(
                q.shape[0], n_ranks * k)
            return _merge_body(pool_v, pool_i, k=k, metric=metric,
                               use_radix=use_radix_merge)
        return fn

    def unpack(self, out, start, rows):
        d, i = out
        return d[start:start + rows], i[start:start + rows]

    def estimate_bytes(self, rows):
        return limits.estimate_bytes(
            "neighbors.ivf_mnmg_search", n_queries=rows,
            probe_rows=self.nprobe * self.index.cap_max,
            n_dims=self.dim, k=self.k, n_ranks=self.index.n_ranks,
            itemsize=self.dtype.itemsize,
            packed_rows=self.index.cap_rank_max)

    def eager(self, queries):
        from raft_tpu.neighbors import ivf_mnmg

        return ivf_mnmg.search_mnmg(None, self.index,
                                    jnp.asarray(queries), self.k,
                                    self.nprobe)

    def epilogue(self) -> str:
        """"ivf" — quoted from :func:`knn_plan` with this service's
        (n_lists, nprobe), same source of truth as the single-rank
        services."""
        from raft_tpu.neighbors.brute_force import knn_plan

        path, _ = knn_plan(1, self.index.n_db, self.k,
                           metric=self.index.metric,
                           n_lists=self.index.n_lists,
                           nprobe=self.nprobe)
        return path


class PairwiseService(Service):
    """Batched pairwise distance rows against a fixed corpus
    (:func:`raft_tpu.distance.pairwise_distance`). Per-request result:
    the ``[rows, n_corpus]`` distance block."""

    def __init__(self, corpus, metric=None):
        from raft_tpu.distance import DistanceType

        corpus = jnp.asarray(corpus)
        super().__init__((corpus,), dim=corpus.shape[1],
                         dtype=corpus.dtype)
        self.metric = metric or DistanceType.L2Expanded
        self.name = f"pairwise_{self.metric.value}"

    def _build(self):
        from raft_tpu.distance import pairwise_distance

        metric = self.metric

        def fn(corpus, q):
            return pairwise_distance(None, q, corpus, metric=metric)
        return fn

    def unpack(self, out, start, rows):
        return out[start:start + rows]

    def estimate_bytes(self, rows):
        corpus = self.fixed_args[0]
        return limits.estimate_bytes(
            "distance.pairwise_distance", m=rows, n=corpus.shape[0],
            k=self.dim, itemsize=self.dtype.itemsize)

    def eager(self, queries):
        from raft_tpu.distance import pairwise_distance

        return pairwise_distance(None, jnp.asarray(queries),
                                 self.fixed_args[0], metric=self.metric)


class KMeansPredictService(Service):
    """Batched nearest-centroid assignment against fixed centroids.
    Per-request result: ``(labels [rows], inertia)`` — the
    :func:`raft_tpu.cluster.kmeans.kmeans_predict` contract, with the
    inertia summed over the request's own rows only."""

    def __init__(self, centroids):
        centroids = jnp.asarray(centroids)
        super().__init__((centroids,), dim=centroids.shape[1],
                         dtype=centroids.dtype)
        self.name = f"kmeans_predict_k{centroids.shape[0]}"

    def _build(self):
        from raft_tpu.cluster.kmeans import _assign
        from raft_tpu.util import precision

        def fn(centroids, q):
            # same precision scope as the public kmeans_predict — the
            # per-row (dist, label) pairs must match it bit-for-bit
            with precision.scope():
                dist, labels = _assign(q, centroids)
            return dist, labels
        return fn

    def unpack(self, out, start, rows):
        dist, labels = out
        sl = slice(start, start + rows)
        return labels[sl], jnp.sum(dist[sl])

    def estimate_bytes(self, rows):
        c = self.fixed_args[0]
        return limits.estimate_bytes(
            "distance.pairwise_distance", m=rows, n=c.shape[0],
            k=self.dim, itemsize=self.dtype.itemsize)

    def eager(self, queries):
        from raft_tpu.cluster.kmeans import kmeans_predict

        return kmeans_predict(None, jnp.asarray(queries),
                              self.fixed_args[0])


@dataclass
class ExecutorStats:
    """Serving counters (process-local, metrics-independent — the load
    generator reads these even with ``RAFT_TPU_METRICS=off``)."""

    batches: int = 0
    requests: int = 0
    rows: int = 0                   # real rows launched
    padded_rows: int = 0            # pad overhead launched
    splits: int = 0                 # budget-driven batch splits
    degraded: int = 0               # eager budget_scope fallbacks
    deadline_failed: int = 0
    cancelled: int = 0              # hedge losers dropped at drain
    traces: int = 0                 # Python retraces (compile events)
    exec_hits: int = 0              # executable-cache hits
    exec_misses: int = 0
    per_batch_rows: List[int] = field(default_factory=list)
    # responses served per brownout level (level -> count); {0: n} or
    # empty means brownout never engaged
    brownout_levels: Dict[int, int] = field(default_factory=dict)

    def coalescing_factor(self) -> float:
        """Mean real rows per device launch — the number the bench
        reports (1.0 = no coalescing happening)."""
        return self.rows / self.batches if self.batches else 0.0


class Executor:
    """Drains a :class:`RequestQueue` and issues one device launch per
    coalesced batch, through per-bucket AOT-warmed executables."""

    def __init__(self, services: Sequence[Service],
                 queue: Optional[RequestQueue] = None, *,
                 policy: Optional[BatchPolicy] = None, qos=None,
                 use_aot: bool = True, brownout=None, faults=None):
        self.services: Dict[str, Service] = {s.name: s for s in services}
        self.qos = qos
        self.brownout = brownout
        if brownout is not None:
            if brownout.qos is None:
                brownout.qos = qos
            # every ladder level is a first-class service: registered
            # here, pre-warmed by warm() through the normal bucket
            # ladder — stepping down at steady state never compiles
            for ladder in brownout.ladders.values():
                for svc in ladder.services:
                    self.services.setdefault(svc.name, svc)
        # chaos hook (loadgen slow-replica scenario): a
        # comms.faults.FaultInjector whose armed stall is applied
        # per-launch, emulating a straggling replica
        self.faults = faults
        self.queue = queue or RequestQueue(policy, qos=qos)
        if self.queue.qos is None:
            self.queue.qos = qos
        self.use_aot = use_aot
        self.stats = ExecutorStats()
        # keyed (service name, serve epoch, bucket rows) — the epoch
        # component retires stale-shape executables across streaming
        # compaction swaps
        self._executables: Dict[Tuple[str, int, int], Callable] = {}
        self._exec_lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    # -- client surface -----------------------------------------------

    def submit(self, op: str, queries, *, tenant: str = "default",
               deadline_s: Optional[float] = None):
        """Validate against the service and enqueue; returns the
        request's :class:`~raft_tpu.serve.queue.ResultFuture`."""
        return self.submit_request(op, queries, tenant=tenant,
                                   deadline_s=deadline_s).future

    def submit_request(self, op: str, queries, *,
                       tenant: str = "default",
                       deadline_s: Optional[float] = None,
                       hedge: bool = False) -> Request:
        """:meth:`submit` returning the :class:`Request` — callers that
        need the stamped brownout level or cancellation (hedged
        dispatch) hold the request. When a brownout controller is
        attached, the requested op resolves through the tenant's
        current ladder level HERE, at admission: the level is part of
        the request's identity, not a dispatch-time surprise."""
        level = 0
        if self.brownout is not None:
            op, level = self.brownout.resolve(op, tenant)
        svc = self._service(op)
        q = np.asarray(queries, svc.dtype)
        svc.validate(q)
        return self.queue.submit_request(op, q, tenant=tenant,
                                         deadline_s=deadline_s,
                                         level=level, hedge=hedge)

    def _service(self, op: str) -> Service:
        svc = self.services.get(op)
        if svc is None:
            raise ValueError(f"unknown serve op {op!r}; registered: "
                             f"{sorted(self.services)}")
        return svc

    # -- executable cache ---------------------------------------------

    def _get_executable(self, svc: Service, rows: int,
                        serving: Optional[Tuple] = None) -> Callable:
        if serving is None:
            serving = svc.serving()
        key = (svc.name, serving[0], rows)
        exe = self._executables.get(key)
        if exe is not None:
            self.stats.exec_hits += 1
            obs.inc("runtime_compile_cache_total", 1, cache="serve",
                    outcome="hit")
            return exe
        with self._exec_lock:
            exe = self._executables.get(key)
            if exe is None:
                exe = self._build_executable(svc, rows, serving)
                self._executables[key] = exe
                # an epoch bump obsoletes every earlier epoch's
                # executables for this service (their baked shapes no
                # longer match any serving snapshot) — drop them so the
                # cache only ever tracks live shapes. Gated on the
                # PUBLISHED epoch, not this snapshot's: pre-warming a
                # pending (not yet published) epoch must never evict
                # the executables still serving traffic.
                stale = [k for k in self._executables
                         if k[0] == svc.name and k[1] < svc.serve_epoch]
                for k in stale:
                    del self._executables[k]
        return exe

    def _build_executable(self, svc: Service, rows: int,
                          serving: Tuple) -> Callable:
        self.stats.exec_misses += 1
        obs.inc("runtime_compile_cache_total", 1, cache="serve",
                outcome="miss")
        fn = svc._build_for(serving)
        stats = self.stats

        def traced(*args):
            # host side effect runs at TRACE time only: this is the
            # compile-count hook the zero-recompile assertion reads
            stats.traces += 1
            return fn(*args)

        example = (*serving[1], svc.example(rows))
        if obs.perf_enabled():
            # static-cost extraction (ISSUE 13): profile the RAW fn —
            # not `traced`, whose retrace hook must only tick for real
            # serving compiles — under the same (service, bucket) key
            # this cache uses. The extra lowering is a warm-time cost
            # paid only with RAFT_TPU_PERF=on.
            obs.profile_executable(
                svc.name, rows, fn=fn, example=example,
                model_bytes=svc.estimate_bytes(rows))
        if self.use_aot:
            from raft_tpu.runtime.aot import aot_export

            try:
                exported = aot_export(traced, *example)
                return jax.jit(exported.call)
            except Exception:
                # lowering not serializable (some interpret-mode Pallas
                # bodies): same warm-once contract via plain jit
                obs.emit_event("serve.aot_fallback", service=svc.name,
                               rows=rows)
        return jax.jit(traced)

    def warm(self, buckets: Optional[Sequence[int]] = None) -> int:
        """Build AND invoke the executable for every (service, bucket)
        so steady-state serving never compiles. Default buckets: the
        ladder up to the queue's ``max_batch``. Returns the number of
        executables warmed."""
        if buckets is None:
            buckets = bucket_ladder(self.queue.policy.max_batch)
        n = 0
        for svc in self.services.values():
            t0 = time.monotonic()
            for b in buckets:
                serving = svc.serving()
                exe = self._get_executable(svc, b, serving)
                out = exe(*serving[1], svc.example(b))
                jax.block_until_ready(out)
                n += 1
                if obs.perf_enabled():
                    # second, compile-free invocation so every warmed
                    # profile carries a measured roofline fraction (the
                    # first call's wall time is dominated by compile)
                    t1 = time.monotonic()
                    out = exe(*serving[1], svc.example(b))
                    jax.block_until_ready(out)
                    obs.record_launch(svc.name, b,
                                      time.monotonic() - t1)
            dt = time.monotonic() - t0
            obs.observe("serve_warmup_seconds", dt, service=svc.name)
            # kNN services also report which selection epilogue their
            # warmed executables compiled (the serve-path CI gate
            # asserts k > 256 services warm onto "radix")
            ep = getattr(svc, "epilogue", None)
            obs.emit_event("serve.warmed", service=svc.name,
                           buckets=list(buckets), seconds=round(dt, 4),
                           **({"epilogue": ep()} if ep else {}))
        return n

    # -- dispatch -----------------------------------------------------

    def _fail(self, req: Request, exc: BaseException) -> None:
        req.future.set_exception(exc)

    def _expire_check(self, reqs: List[Request]) -> List[Request]:
        live = []
        for r in reqs:
            if r.cancelled is not None:
                # hedge loser (or shutdown): cancel() already resolved
                # the future with the typed rejection — just drop it so
                # no launch is spent on a request nobody is waiting for
                self.stats.cancelled += 1
                obs.inc("serve_cancelled_total", 1, op=f"serve.{r.op}",
                        reason=r.cancelled)
            elif r.expired():
                self.stats.deadline_failed += 1
                obs.inc("limits_deadline_exceeded_total", 1,
                        op=f"serve.{r.op}")
                wait = time.monotonic() - r.t_enqueue
                exc = limits.DeadlineExceededError(
                    f"serve.{r.op}: deadline expired in queue "
                    f"({r.deadline.budget_s:g}s budget, waited "
                    f"{wait:.3f}s)",
                    op=f"serve.{r.op}", budget_s=r.deadline.budget_s)
                with obs.use_context(r.ctx):
                    obs.record_failure(exc, tenant=r.tenant)
                if self.qos is not None and obs.enabled():
                    self.qos.record_outcome(r.op, r.tenant, wait,
                                            failed=True)
                self._fail(r, exc)
            else:
                live.append(r)
        return live

    def dispatch(self, batch: Batch) -> None:
        """Run one coalesced batch to completion (expiry fast-fail,
        budget split/degrade, pad-to-bucket, launch, unpad).

        When metrics are on the whole batch runs under a
        ``serve.batch`` span that links the member request_ids — the
        coalescing join point of the per-request traces."""
        svc = self._service(batch.op)
        live = self._expire_check(batch.requests)
        if not live:
            return
        if obs.enabled():
            ids = [r.ctx.request_id for r in live if r.ctx is not None]
            with obs.span("serve.batch", op=batch.op,
                          requests=len(live),
                          rows=sum(r.rows for r in live),
                          request_ids=ids):
                self._dispatch_within_budget(svc, live)
        else:
            self._dispatch_within_budget(svc, live)

    def _dispatch_within_budget(self, svc: Service,
                                reqs: List[Request]) -> None:
        rows = sum(r.rows for r in reqs)
        budget = self.qos.batch_budget() if self.qos is not None \
            else limits.active_budget()
        if budget is not None and \
                svc.estimate_bytes(bucket_rows(rows)) > budget.limit_bytes:
            if len(reqs) > 1:
                # split: both halves land on smaller, already-warm
                # buckets — the serve-layer row tiling
                self.stats.splits += 1
                obs.inc("serve_batch_splits_total", 1, op=svc.name)
                mid = len(reqs) // 2
                self._dispatch_within_budget(svc, reqs[:mid])
                self._dispatch_within_budget(svc, reqs[mid:])
                return
            self._dispatch_degraded(svc, reqs[0], budget)
            return
        self._launch(svc, reqs, rows)

    def _dispatch_degraded(self, svc: Service, req: Request,
                           budget: limits.WorkBudget) -> None:
        """Single request over the batch budget: run the public API
        eagerly under ``budget_scope`` — the PR-5 row-tiled degraded
        path keeps the footprint bounded and the bits identical, or
        raises the typed rejection this future surfaces."""
        self.stats.degraded += 1
        obs.inc("serve_degraded_total", 1, op=svc.name)
        try:
            scope_s = req.deadline.remaining() if req.deadline else None
            # the degraded path runs library entry points on this
            # thread — adopting the request's context means every span,
            # limits check, and chunk boundary below carries its ids
            with obs.use_context(req.ctx), limits.budget_scope(budget):
                if scope_s is not None:
                    with limits.deadline_scope(max(scope_s, 0.0)):
                        out = svc.eager(req.queries)
                else:
                    out = svc.eager(req.queries)
            jax.block_until_ready(out)
        except (limits.RejectedError,
                limits.DeadlineExceededError) as exc:
            self._fail(req, exc)
            return
        except Exception as exc:  # noqa: BLE001 — future must resolve
            self._fail(req, exc)
            return
        self._finish(svc, [req], out, batched=False)

    def _launch(self, svc: Service, reqs: List[Request],
                rows: int) -> None:
        brows = bucket_rows(rows)
        padded = np.zeros((brows, svc.dim), svc.dtype)
        at = 0
        for r in reqs:
            padded[at:at + r.rows] = r.queries
            at += r.rows
        # one serving snapshot for the whole launch: the executable and
        # the fixed operands it was compiled for always come from the
        # SAME epoch, even if a compaction swap lands mid-dispatch
        serving = svc.serving()
        exe = self._get_executable(svc, brows, serving)
        if self.faults is not None:
            # chaos: an armed FaultInjector stall straggles this
            # replica's launches (the hedge gate's slow-replica lever)
            stall = self.faults.current_stall()
            if stall > 0:
                time.sleep(stall)
        t0 = time.monotonic()
        try:
            out = exe(*serving[1], jnp.asarray(padded))
            jax.block_until_ready(out)
        except Exception as exc:  # noqa: BLE001 — futures must resolve
            for r in reqs:
                self._fail(r, exc)
            return
        dt = time.monotonic() - t0
        self.stats.batches += 1
        self.stats.rows += rows
        self.stats.padded_rows += brows - rows
        self.stats.per_batch_rows.append(rows)
        obs.record_launch(svc.name, brows, dt)
        if obs.enabled():
            obs.observe("serve_batch_rows", rows,
                        help="real rows per coalesced device launch")
            obs.observe("serve_launch_seconds", dt, op=svc.name)
            now = time.monotonic()
            for r in reqs:
                wait = t0 - r.t_enqueue
                obs.observe("serve_queue_wait_seconds", wait,
                            help="submit-to-launch-start wait (the "
                                 "queue side of the wait/execute "
                                 "split)")
                if r.ctx is not None:
                    # per-request trace slices, manufactured from the
                    # shared launch timing: request = queue_wait +
                    # execute. Each request gets a synthetic tid so
                    # overlapping requests nest correctly in the
                    # chrome-trace rendering.
                    tid = 1_000_000 + (r.seq % 1_000_000)
                    obs.record_span(
                        "serve.request", t_start=r.t_enqueue,
                        duration=now - r.t_enqueue, parent=None,
                        thread=tid, ctx=r.ctx, op=svc.name,
                        rows=r.rows, tenant=r.tenant,
                        level=r.level, hedge=r.hedge)
                    obs.record_span(
                        "serve.queue_wait", t_start=r.t_enqueue,
                        duration=wait, parent="serve.request",
                        thread=tid, ctx=r.ctx)
                    obs.record_span(
                        "serve.execute", t_start=t0, duration=dt,
                        parent="serve.request", thread=tid, ctx=r.ctx)
            # selection-stage achieved bandwidth for services whose
            # launches ride the radix epilogue (modeled bytes from the
            # benches/select_model.py pass count over the launch time)
            sel = getattr(svc, "selection_bytes", None)
            sel_bytes = sel(brows) if sel else 0
            if sel_bytes and dt > 0:
                obs.set_gauge("select_k_bytes_per_s", sel_bytes / dt,
                              help="modeled selection bytes / launch "
                                   "seconds on the radix epilogue",
                              op=svc.name)
        self._finish(svc, reqs, out, batched=True)

    def _check_floor(self, r: Request) -> None:
        """Post-serve floor audit: a response stamped below the
        tenant's ``min_quality`` is a controller bug — flight-record
        the violation (metric + bundle), never silently ship it as
        normal quality."""
        if r.level == 0 or self.qos is None:
            return
        floor = self.qos.policy(r.tenant).min_quality
        if floor is None or r.level <= floor:
            return
        from raft_tpu.serve.brownout import BrownoutFloorError

        exc = BrownoutFloorError(
            f"serve.{r.op}: served tenant {r.tenant!r} at brownout "
            f"level {r.level}, below its min_quality floor {floor}",
            op=r.op, tenant=r.tenant, level=r.level, floor=floor)
        obs.inc("serve_brownout_floor_violations_total", 1, op=r.op,
                tenant=r.tenant)
        with obs.use_context(r.ctx):
            obs.record_failure(exc, tenant=r.tenant)

    def _finish(self, svc: Service, reqs: List[Request], out,
                batched: bool) -> None:
        at = 0
        now = time.monotonic()
        meter_slo = self.qos is not None and obs.enabled()
        for r in reqs:
            if r.expired():
                # computed but missed its SLO: the contract is the
                # deadline, not best-effort delivery
                self.stats.deadline_failed += 1
                obs.inc("limits_deadline_exceeded_total", 1,
                        op=f"serve.{r.op}")
                exc = limits.DeadlineExceededError(
                    f"serve.{r.op}: deadline expired during execution",
                    op=f"serve.{r.op}", budget_s=r.deadline.budget_s)
                with obs.use_context(r.ctx):
                    obs.record_failure(exc, tenant=r.tenant)
                if meter_slo:
                    self.qos.record_outcome(r.op, r.tenant,
                                            now - r.t_enqueue,
                                            failed=True)
                self._fail(r, exc)
            else:
                if batched:
                    r.future.set_result(svc.unpack(out, at, r.rows))
                else:
                    r.future.set_result(out)
                if meter_slo:
                    self.qos.record_outcome(r.op, r.tenant,
                                            now - r.t_enqueue)
                self._check_floor(r)
            self.stats.requests += 1
            lv = self.stats.brownout_levels
            lv[r.level] = lv.get(r.level, 0) + 1
            obs.inc("serve_requests_total", 1, op=svc.name,
                    tenant=r.tenant)
            at += r.rows

    # -- worker loop --------------------------------------------------

    def start(self) -> "Executor":
        """Spawn the drain thread (idempotent)."""
        if self._thread is not None and self._thread.is_alive():
            return self
        self._stop.clear()
        self._thread = threading.Thread(target=self._run,
                                        name="raft-tpu-serve",
                                        daemon=True)
        self._thread.start()
        obs.emit_event("serve.start", services=sorted(self.services))
        return self

    def _run(self) -> None:
        while not self._stop.is_set():
            if self.brownout is not None:
                self.brownout.maybe_tick(self)
            batch = self.queue.next_batch(timeout=0.05)
            if batch is None:
                continue
            self.dispatch(batch)
        # drain what is left so no future hangs across stop()
        while True:
            batch = self.queue.next_batch(timeout=0.0)
            if batch is None or not batch.requests:
                break
            self.dispatch(batch)

    def stop(self, *, close_queue: bool = True) -> None:
        """Stop the worker; by default also closes the queue (new
        submits fail) and drains pending requests first."""
        if close_queue:
            self.queue.close()
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=30.0)
            self._thread = None
        s = self.stats
        obs.emit_event(
            "serve.stop", batches=s.batches, requests=s.requests,
            rows=s.rows, coalescing=round(s.coalescing_factor(), 3),
            splits=s.splits, degraded=s.degraded,
            deadline_failed=s.deadline_failed)

    def __enter__(self) -> "Executor":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()
