"""Request queue with shape-bucket coalescing (serving tentpole, part 1).

The unit of admission is a :class:`Request` — one tenant's small query
block (a handful of rows) against a named service. The unit of device
work is a *coalesced batch*: every drained request's rows concatenated,
padded up to a shape bucket (:func:`bucket_rows` — power-of-two-ish row
counts so the executor's per-bucket executables stay a small, warmable
set), launched once, and sliced back per request. The queue is the
boundary between the two: callers see per-request futures and typed
errors; the executor sees batches.

Batching policy (the classic dynamic-batching pair):

``max_batch``
    coalescing cap in ROWS — a batch is dispatched as soon as the
    drained rows reach it (an oversize single request still forms its
    own batch: the cap bounds coalescing, not request size).
``max_wait_ms``
    latency bound — a non-empty queue never holds its OLDEST request
    longer than this before dispatch, however empty the batch.

Backpressure and QoS are wired into the existing ``runtime/limits``
taxonomy: a full queue raises
:class:`~raft_tpu.runtime.limits.RejectedError` with
``reason="queue_full"`` and ticks ``limits_rejected_total`` — the same
typed refusal an over-budget launch gets — and every request carries a
:class:`~raft_tpu.runtime.limits.Deadline` so expiry-in-queue fails fast
instead of wasting a launch (the executor polls it at drain).

Fairness: dequeue order across tenants is weighted fair queuing over a
per-tenant virtual time (rows served divided by tenant weight; the
lowest virtual time goes first). One tenant flooding the queue advances
only its own clock, so a light tenant's next request dequeues almost
immediately — starvation-freedom under a hog is a test, not a hope
(``tests/test_serve.py``).
"""

from __future__ import annotations

import collections
import threading
import time
from dataclasses import dataclass, field
from typing import Deque, Dict, List, Optional

import numpy as np

from raft_tpu import obs
from raft_tpu.runtime import limits

__all__ = [
    "BUCKET_FLOOR", "bucket_rows", "bucket_ladder",
    "Request", "ResultFuture", "Batch", "BatchPolicy", "RequestQueue",
]


# Smallest bucket: one sublane group. Buckets ascend power-of-two-ish
# (8, 12, 16, 24, 32, 48, 64, ...): each step is x1.5 or x1.33, so
# pad-to-bucket waste is bounded at 33% while the number of distinct
# executables per service stays logarithmic in max_batch.
BUCKET_FLOOR = 8


def bucket_rows(n: int, floor: int = BUCKET_FLOOR) -> int:
    """Round a row count up to its shape bucket.

    Buckets are ``floor * {1, 1.5, 2, 3, 4, 6, 8, ...}`` — powers of two
    and their midpoints. Deterministic, monotone, and idempotent
    (``bucket_rows(bucket_rows(n)) == bucket_rows(n)``).

    >>> [bucket_rows(n) for n in (1, 8, 9, 12, 13, 17, 25, 100)]
    [8, 8, 12, 12, 16, 24, 32, 128]
    """
    if n <= 0:
        raise ValueError(f"row count must be positive, got {n}")
    b = int(floor)
    while b < n:
        # alternate x1.5 (pow2 -> midpoint) and x4/3 (midpoint -> pow2)
        b = b * 3 // 2 if (b & (b - 1)) == 0 else b * 4 // 3
    return b


def bucket_ladder(max_rows: int, floor: int = BUCKET_FLOOR) -> List[int]:
    """Every bucket up to (and including) the one covering ``max_rows``
    — the set the executor pre-warms so steady-state serving never meets
    an unseen shape."""
    out = [int(floor)]
    while out[-1] < max_rows:
        b = out[-1]
        out.append(b * 3 // 2 if (b & (b - 1)) == 0 else b * 4 // 3)
    return out


class ResultFuture:
    """One request's completion slot: the caller blocks on
    :meth:`result`, the executor fulfills exactly once with either a
    value or a typed exception. First fulfillment wins; later ones are
    ignored (a hedged loser may be cancelled and then still complete)."""

    __slots__ = ("_event", "_value", "_exc", "_callbacks", "_lock")

    def __init__(self):
        self._event = threading.Event()
        self._value = None
        self._exc: Optional[BaseException] = None
        self._callbacks: List = []
        self._lock = threading.Lock()

    def _fulfill(self, value, exc) -> None:
        with self._lock:
            if self._event.is_set():
                return                       # first outcome wins
            self._value = value
            self._exc = exc
            self._event.set()
            callbacks, self._callbacks = self._callbacks, []
        for cb in callbacks:
            cb(self)

    def set_result(self, value) -> None:
        self._fulfill(value, None)

    def set_exception(self, exc: BaseException) -> None:
        self._fulfill(None, exc)

    def add_done_callback(self, fn) -> None:
        """Run ``fn(self)`` when the future resolves (immediately if it
        already has). Callbacks run on the fulfilling thread — keep
        them tiny and non-blocking (hedge bookkeeping, latency
        samples)."""
        with self._lock:
            if not self._event.is_set():
                self._callbacks.append(fn)
                return
        fn(self)

    def done(self) -> bool:
        return self._event.is_set()

    def result(self, timeout: Optional[float] = None):
        """Block for the outcome; raises the typed serving error
        (``DeadlineExceededError`` / ``RejectedError``) when the request
        failed, ``TimeoutError`` when nothing arrived in ``timeout``."""
        if not self._event.wait(timeout):
            raise TimeoutError("serve request still pending")
        if self._exc is not None:
            raise self._exc
        return self._value

    def exception(self, timeout: Optional[float] = None
                  ) -> Optional[BaseException]:
        """The outcome's exception (None on success) — the peek
        :meth:`result` can't offer because it re-raises."""
        if not self._event.wait(timeout):
            raise TimeoutError("serve request still pending")
        return self._exc


@dataclass
class Request:
    """One enqueued query block (internal to serve/)."""

    op: str
    queries: np.ndarray                 # [rows, dim], service dtype
    tenant: str
    seq: int                            # global arrival order
    t_enqueue: float                    # monotonic
    deadline: Optional[limits.Deadline] = None
    future: ResultFuture = field(default_factory=ResultFuture)
    # minted at submit when RAFT_TPU_TRACING=on; None otherwise — every
    # downstream propagation site keys off `ctx is None`
    ctx: Optional[obs.TraceContext] = None
    # brownout quality level this request was admitted at (0 = full
    # quality); stamped by the executor at submit, echoed on the span
    level: int = 0
    # True when this request is a hedge re-issue (Dean & Barroso) — the
    # second leg of a first-completion-wins pair
    hedge: bool = False
    # cancellation reason, or None. Set via :meth:`cancel` (hedge loser,
    # shutdown); a cancelled request is swept/skipped instead of launched
    cancelled: Optional[str] = None

    @property
    def rows(self) -> int:
        return int(self.queries.shape[0])

    def expired(self) -> bool:
        return self.deadline is not None and self.deadline.expired()

    def cancel(self, reason: str) -> None:
        """Best-effort cancellation: mark the request so the executor
        drops it at drain instead of launching it. A request already in
        flight still completes — its future's first outcome wins, so a
        cancelled-then-completed loser is simply ignored."""
        self.cancelled = reason
        self.future.set_exception(limits.RejectedError(
            f"serve.{self.op}: request cancelled ({reason})",
            op=f"serve.{self.op}", reason="cancelled"))


@dataclass
class Batch:
    """A drained, same-op set of requests the executor launches once."""

    op: str
    requests: List[Request]

    @property
    def rows(self) -> int:
        return sum(r.rows for r in self.requests)


@dataclass
class BatchPolicy:
    max_batch: int = 256                # coalescing cap, in rows
    max_wait_ms: float = 5.0            # oldest-request latency bound
    max_queue: int = 1024               # queued requests before backpressure

    def __post_init__(self):
        if self.max_batch < 1 or self.max_queue < 1:
            raise ValueError("max_batch and max_queue must be >= 1")
        if self.max_wait_ms < 0:
            raise ValueError("max_wait_ms must be >= 0")


class _OpState:
    """Per-op pending requests + the weighted-fair clock."""

    __slots__ = ("tenants", "vtime", "oldest_seq", "oldest_t", "rows")

    def __init__(self):
        self.tenants: Dict[str, Deque[Request]] = {}
        self.vtime: Dict[str, float] = {}
        self.rows = 0

    def push(self, req: Request, weight: float) -> None:
        dq = self.tenants.get(req.tenant)
        if dq is None:
            dq = self.tenants[req.tenant] = collections.deque()
        if not dq:
            # (re)activation: no banked credit — an idle tenant's clock
            # catches up to the busiest floor so it cannot burst-starve
            # others, but keeps its fair-share head start
            active = [self.vtime[t] for t, d in self.tenants.items()
                      if d and t != req.tenant]
            floor = min(active) if active else 0.0
            self.vtime[req.tenant] = max(
                self.vtime.get(req.tenant, 0.0), floor)
        dq.append(req)
        self.rows += req.rows

    def oldest(self) -> Optional[Request]:
        head = [d[0] for d in self.tenants.values() if d]
        return min(head, key=lambda r: r.seq) if head else None

    def peek_fair(self) -> Optional[Request]:
        """The head request of the lowest-virtual-time tenant (ties go
        to arrival order) — the next fair pop, without committing."""
        live = [t for t, d in self.tenants.items() if d]
        if not live:
            return None
        t = min(live, key=lambda t: (self.vtime.get(t, 0.0),
                                     self.tenants[t][0].seq))
        return self.tenants[t][0]

    def pop(self, req: Request, weight: float) -> None:
        """Commit a :meth:`peek_fair` choice: dequeue it and advance its
        tenant's virtual clock by rows/weight."""
        popped = self.tenants[req.tenant].popleft()
        assert popped is req
        self.vtime[req.tenant] = (self.vtime.get(req.tenant, 0.0)
                                  + req.rows / weight)
        self.rows -= req.rows

    def empty(self) -> bool:
        return self.rows == 0 and not any(self.tenants.values())


class RequestQueue:
    """Thread-safe multi-tenant request queue with shape-bucket
    coalescing. Producers call :meth:`submit`; the executor's worker
    thread calls :meth:`next_batch`."""

    def __init__(self, policy: Optional[BatchPolicy] = None, *,
                 qos=None):
        self.policy = policy or BatchPolicy()
        self.qos = qos
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._ops: Dict[str, _OpState] = {}
        self._seq = 0
        self._pending = 0
        self._closed = False

    # -- producer side ------------------------------------------------

    def submit(self, op: str, queries, *, tenant: str = "default",
               deadline_s: Optional[float] = None) -> ResultFuture:
        """Enqueue one query block; returns its :class:`ResultFuture`.

        Raises :class:`~raft_tpu.runtime.limits.RejectedError`
        (``reason="queue_full"``) when the queue — or the tenant's QoS
        share of it — is at capacity: backpressure is an admission
        decision, typed and metered exactly like an over-budget launch.
        """
        return self.submit_request(op, queries, tenant=tenant,
                                   deadline_s=deadline_s).future

    def submit_request(self, op: str, queries, *,
                       tenant: str = "default",
                       deadline_s: Optional[float] = None,
                       level: int = 0,
                       hedge: bool = False) -> Request:
        """:meth:`submit`, but returns the :class:`Request` itself —
        callers that need cancellation (hedged dispatch) or the stamped
        brownout ``level`` hold the request, everyone else holds just
        the future.

        Before the capacity check, dead heads (expired in queue, or
        cancelled hedge losers) are swept out: a request that can no
        longer be served must not hold a queue slot against a live
        successor during a spike."""
        queries = np.asarray(queries)
        if queries.ndim != 2 or queries.shape[0] < 1:
            raise ValueError(
                f"queries must be [rows>=1, dim], got {queries.shape}")
        if deadline_s is None and self.qos is not None:
            deadline_s = self.qos.policy(tenant).deadline_s
        dl = limits.Deadline(deadline_s) if deadline_s is not None else None
        swept: List[Request] = []
        try:
            with self._cond:
                if self._closed:
                    raise limits.RejectedError(
                        f"serve.{op}: queue is closed — the serving "
                        "runtime is shutting down", op=f"serve.{op}",
                        reason="queue_closed")
                self._sweep_dead_locked(swept)
                if self._pending >= self.policy.max_queue:
                    obs.inc("limits_rejected_total", 1,
                            reason="queue_full", op=f"serve.{op}")
                    exc = limits.RejectedError(
                        f"serve.{op}: queue full ({self._pending} "
                        f"requests >= max_queue="
                        f"{self.policy.max_queue}) — retry with "
                        "backoff or shed load", op=f"serve.{op}",
                        reason="queue_full")
                    obs.record_failure(exc, tenant=tenant)
                    raise exc
                if self.qos is not None:
                    self.qos.check_tenant_share(
                        op, tenant, self._tenant_pending(op, tenant))
                st = self._ops.get(op)
                if st is None:
                    st = self._ops[op] = _OpState()
                req = Request(op=op, queries=queries, tenant=tenant,
                              seq=self._seq, t_enqueue=time.monotonic(),
                              deadline=dl, ctx=obs.mint(tenant=tenant),
                              level=int(level), hedge=bool(hedge))
                self._seq += 1
                st.push(req, self._weight(tenant))
                self._pending += 1
                obs.set_gauge("serve_queue_depth", self._pending,
                              help="requests waiting in the serving "
                                   "queue")
                self._cond.notify_all()
        finally:
            # futures resolve OUTSIDE the queue lock: done-callbacks
            # (hedge bookkeeping) may touch other locks
            self._resolve_swept(swept)
        return req

    def _sweep_dead_locked(self, swept: List[Request]) -> None:
        """Pop expired/cancelled HEAD requests (under the lock) so they
        stop holding queue slots; the caller resolves their futures
        after releasing it. Virtual time does not advance — no rows
        were served."""
        for op in list(self._ops):
            st = self._ops[op]
            for dq in st.tenants.values():
                while dq and (dq[0].cancelled is not None
                              or dq[0].expired()):
                    r = dq.popleft()
                    st.rows -= r.rows
                    self._pending -= 1
                    swept.append(r)
            if st.empty():
                del self._ops[op]
        if swept:
            obs.set_gauge("serve_queue_depth", self._pending,
                          help="requests waiting in the serving queue")

    def _resolve_swept(self, swept: List[Request]) -> None:
        for r in swept:
            if r.cancelled is not None:
                continue                 # cancel() resolved it already
            wait = time.monotonic() - r.t_enqueue
            obs.inc("limits_deadline_exceeded_total", 1,
                    op=f"serve.{r.op}")
            exc = limits.DeadlineExceededError(
                f"serve.{r.op}: deadline expired in queue (swept at "
                f"successor enqueue; {r.deadline.budget_s:g}s budget, "
                f"waited {wait:.3f}s)",
                op=f"serve.{r.op}", budget_s=r.deadline.budget_s)
            with obs.use_context(r.ctx):
                obs.record_failure(exc, tenant=r.tenant)
            if self.qos is not None and obs.enabled():
                self.qos.record_outcome(r.op, r.tenant, wait,
                                        failed=True)
            r.future.set_exception(exc)

    def _weight(self, tenant: str) -> float:
        if self.qos is None:
            return 1.0
        return self.qos.policy(tenant).weight

    def _tenant_pending(self, op: str, tenant: str) -> int:
        st = self._ops.get(op)
        if st is None:
            return 0
        dq = st.tenants.get(tenant)
        return len(dq) if dq else 0

    # -- consumer (executor) side -------------------------------------

    def next_batch(self, timeout: Optional[float] = None
                   ) -> Optional[Batch]:
        """Block until a batch is due, then drain and return it.

        A batch becomes due when drained rows would reach ``max_batch``,
        or the oldest pending request has waited ``max_wait_ms``, or the
        queue is closing. Returns None on ``timeout`` (executor idles)
        or when closed and empty (executor exits)."""
        deadline = (time.monotonic() + timeout
                    if timeout is not None else None)
        with self._cond:
            while True:
                req = self._oldest_request()
                if req is not None:
                    st = self._ops[req.op]
                    age_ms = (time.monotonic() - req.t_enqueue) * 1e3
                    if (st.rows >= self.policy.max_batch
                            or age_ms >= self.policy.max_wait_ms
                            or self._closed):
                        return self._drain(req.op)
                    wait = (self.policy.max_wait_ms - age_ms) / 1e3
                elif self._closed:
                    return None
                else:
                    wait = None
                if deadline is not None:
                    rem = deadline - time.monotonic()
                    if rem <= 0:
                        return None
                    wait = rem if wait is None else min(wait, rem)
                self._cond.wait(wait)

    def _oldest_request(self) -> Optional[Request]:
        heads = [st.oldest() for st in self._ops.values()]
        heads = [h for h in heads if h is not None]
        return min(heads, key=lambda r: r.seq) if heads else None

    def _drain(self, op: str) -> Batch:
        """Assemble one batch for ``op`` under the lock: weighted-fair
        pops until the row cap (the first request always ships, however
        large — the cap bounds coalescing, not request size)."""
        st = self._ops[op]
        reqs: List[Request] = []
        rows = 0
        while rows < self.policy.max_batch:
            head = st.peek_fair()
            if head is None:
                break
            if reqs and rows + head.rows > self.policy.max_batch:
                break
            st.pop(head, self._weight(head.tenant))
            reqs.append(head)
            rows += head.rows
        self._pending -= len(reqs)
        if st.empty():
            del self._ops[op]
        obs.set_gauge("serve_queue_depth", self._pending,
                      help="requests waiting in the serving queue")
        return Batch(op=op, requests=reqs)

    # -- lifecycle ----------------------------------------------------

    def pending(self) -> int:
        with self._lock:
            return self._pending

    def close(self) -> None:
        """Stop accepting submissions; wake the executor so it drains
        what is left and exits."""
        with self._cond:
            self._closed = True
            self._cond.notify_all()
