"""Multi-tenant QoS: per-tenant admission, deadlines, and budget-aware
batch sizing (serving tentpole, part 3).

This module owns POLICY; enforcement lives where the information is:

- **Admission** (here + ``queue.py``): a tenant's share of the queue is
  bounded (``max_queued``); an over-share submit raises
  :class:`~raft_tpu.runtime.limits.RejectedError`
  (``reason="queue_full"``) and ticks
  ``limits_rejected_total{reason="queue_full"}`` — backpressure is the
  same typed refusal the HBM admission layer gives an over-budget
  launch, so callers need exactly one retry/shed policy.
- **Deadlines** (``queue.py`` submit + ``executor.py`` drain): each
  request is wired into a :class:`~raft_tpu.runtime.limits.Deadline`
  (tenant default or per-request override). A request that expires in
  queue fast-fails with ``DeadlineExceededError`` at drain — the launch
  it would have wasted goes to requests that can still meet their SLO —
  and the executor runs each batch under
  :func:`~raft_tpu.runtime.limits.deadline_scope` of the tightest
  surviving deadline so host-side work stays polled.
- **Memory budget** (``executor.py`` dispatch): a coalesced batch whose
  footprint estimate (``limits.estimate_bytes``) exceeds
  :meth:`QosPolicy.batch_budget` is SPLIT into smaller (still-warm)
  buckets; a single request that cannot fit even alone degrades through
  the PR-5 row-tiled path by running eagerly under
  :func:`~raft_tpu.runtime.limits.budget_scope` — bit-identical output,
  bounded footprint — and only raises ``RejectedError`` when even that
  cannot fit.
- **Fairness** (``queue.py``): tenant ``weight`` feeds the weighted-fair
  virtual clock; a heavy tenant gets proportionally more rows per unit
  time, never the whole pipe.
- **SLOs** (ISSUE 10, here + ``executor.py`` finish): a tenant may carry
  a latency objective (``slo_latency_s`` at ``slo_target``). Every
  completed/failed request records an outcome —
  ``slo_requests_total{tenant,outcome}`` with outcome ∈
  ``ok``/``violation``/``deadline`` — and a sliding-window burn-rate
  gauge ``slo_burn_rate{tenant}`` (windowed violation fraction over the
  tolerated fraction; >1 means the error budget is burning faster than
  the objective allows). Surfaced in ``loadgen.LoadReport``.
"""

from __future__ import annotations

import collections
import threading
import time
from dataclasses import dataclass
from typing import Deque, Dict, Optional, Tuple

from raft_tpu import obs
from raft_tpu.runtime import limits

__all__ = ["TenantPolicy", "QosPolicy"]


@dataclass(frozen=True)
class TenantPolicy:
    """Per-tenant serving contract.

    weight
        fair-share weight (relative rows per unit time under load).
    deadline_s
        default request deadline; None = no deadline unless the request
        carries one.
    max_queued
        per-tenant cap on queued requests (None = only the global
        ``max_queue`` bounds it).
    slo_latency_s
        latency objective: a completed request slower than this is an
        SLO *violation* (counted, not failed). None = no SLO.
    slo_target
        the objective's success fraction (e.g. 0.99 = "99% of requests
        under ``slo_latency_s``"); the burn-rate gauge is the windowed
        violation fraction divided by the tolerated ``1 - slo_target``.
    min_quality
        brownout floor (ISSUE 16): the DEEPEST degradation-ladder level
        the controller may serve this tenant at. 0 pins full quality
        (the tenant is exempt from brownout); None = the whole ladder
        is fair game. Serving below this floor is a contract violation
        the executor flight-records.
    """

    weight: float = 1.0
    deadline_s: Optional[float] = None
    max_queued: Optional[int] = None
    slo_latency_s: Optional[float] = None
    slo_target: float = 0.99
    min_quality: Optional[int] = None

    def __post_init__(self):
        if not self.weight > 0:
            raise ValueError(f"tenant weight must be > 0, "
                             f"got {self.weight}")
        if self.max_queued is not None and self.max_queued < 1:
            raise ValueError("max_queued must be >= 1 when set")
        if self.slo_latency_s is not None and not self.slo_latency_s > 0:
            raise ValueError("slo_latency_s must be > 0 when set")
        if not (0.0 < self.slo_target < 1.0):
            raise ValueError(f"slo_target must be in (0, 1), "
                             f"got {self.slo_target}")
        if self.min_quality is not None and self.min_quality < 0:
            raise ValueError(f"min_quality must be >= 0 when set, "
                             f"got {self.min_quality}")


class QosPolicy:
    """Tenant policy table + the serving-side memory budget.

    ``tenants`` maps tenant name -> :class:`TenantPolicy`; unknown
    tenants get ``default``. ``budget`` is a
    :class:`~raft_tpu.runtime.limits.WorkBudget` (or byte count) that
    bounds one coalesced launch; None defers to the ambient
    ``limits.active_budget()`` (env/scope), which may itself be None —
    unbudgeted serving, the default."""

    #: sliding window the burn-rate gauge averages over (seconds)
    SLO_WINDOW_S = 60.0

    def __init__(self, tenants: Optional[Dict[str, TenantPolicy]] = None,
                 *, default: Optional[TenantPolicy] = None, budget=None):
        self.tenants = dict(tenants or {})
        self.default = default or TenantPolicy()
        if budget is None or isinstance(budget, limits.WorkBudget):
            self._budget = budget
        else:
            self._budget = limits.WorkBudget(budget)
        # per-tenant (t_monotonic, violated) outcome window for burn rate
        self._slo_lock = threading.Lock()
        self._slo_window: Dict[str, Deque[Tuple[float, bool]]] = {}

    def policy(self, tenant: str) -> TenantPolicy:
        return self.tenants.get(tenant, self.default)

    def batch_budget(self) -> Optional[limits.WorkBudget]:
        """The budget one coalesced launch must fit: the explicit
        serving budget when set, else the ambient limits scope."""
        return self._budget if self._budget is not None \
            else limits.active_budget()

    def check_tenant_share(self, op: str, tenant: str,
                           tenant_pending: int) -> None:
        """Per-tenant queue-share admission (called by
        :meth:`~raft_tpu.serve.queue.RequestQueue.submit` under its
        lock). Raises the same typed ``queue_full`` rejection as the
        global cap, labeled with the tenant."""
        cap = self.policy(tenant).max_queued
        if cap is not None and tenant_pending >= cap:
            obs.inc("limits_rejected_total", 1, reason="queue_full",
                    op=f"serve.{op}")
            exc = limits.RejectedError(
                f"serve.{op}: tenant {tenant!r} queue share full "
                f"({tenant_pending} >= max_queued={cap})",
                op=f"serve.{op}", reason="queue_full")
            obs.record_failure(exc, tenant=tenant)
            raise exc

    # -- per-tenant SLO accounting (ISSUE 10) --------------------------

    def record_outcome(self, op: str, tenant: str, latency_s: float,
                       *, failed: bool = False) -> None:
        """Fold one finished request into the tenant's SLO accounting
        (executor ``_finish`` / deadline-fail paths call this when
        metrics are on).

        Outcome taxonomy: ``deadline`` — the request FAILED (expired);
        ``violation`` — it completed but slower than the tenant's
        ``slo_latency_s``; ``ok`` otherwise (including tenants with no
        SLO: without an objective nothing can be violated)."""
        pol = self.policy(tenant)
        if failed:
            outcome = "deadline"
        elif (pol.slo_latency_s is not None
                and latency_s > pol.slo_latency_s):
            outcome = "violation"
        else:
            outcome = "ok"
        obs.inc("slo_requests_total", 1, tenant=tenant, outcome=outcome,
                help="requests by per-tenant SLO outcome "
                     "(ok|violation|deadline)")
        if pol.slo_latency_s is None:
            return
        now = time.monotonic()
        bad = outcome != "ok"
        with self._slo_lock:
            win = self._slo_window.get(tenant)
            if win is None:
                win = self._slo_window[tenant] = collections.deque()
            win.append((now, bad))
            cutoff = now - self.SLO_WINDOW_S
            while win and win[0][0] < cutoff:
                win.popleft()
            n = len(win)
            n_bad = sum(1 for _, b in win if b)
        tolerated = 1.0 - pol.slo_target
        burn = (n_bad / n) / tolerated if n else 0.0
        obs.set_gauge("slo_burn_rate", burn, tenant=tenant,
                      help="sliding-window SLO violation fraction over "
                           "the tolerated fraction (>1 = error budget "
                           "burning too fast)")

    def slo_snapshot(self) -> Dict[str, dict]:
        """Per-tenant SLO state for report surfacing: window counts and
        the current burn rate, keyed by tenant (only tenants that have
        recorded outcomes appear)."""
        out: Dict[str, dict] = {}
        with self._slo_lock:
            items = [(t, list(w)) for t, w in self._slo_window.items()]
        for tenant, win in items:
            pol = self.policy(tenant)
            n = len(win)
            n_bad = sum(1 for _, b in win if b)
            tolerated = 1.0 - pol.slo_target
            out[tenant] = {
                "slo_latency_s": pol.slo_latency_s,
                "slo_target": pol.slo_target,
                "window_requests": n,
                "window_bad": n_bad,
                "burn_rate": (n_bad / n) / tolerated if n else 0.0,
            }
        return out
