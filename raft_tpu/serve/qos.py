"""Multi-tenant QoS: per-tenant admission, deadlines, and budget-aware
batch sizing (serving tentpole, part 3).

This module owns POLICY; enforcement lives where the information is:

- **Admission** (here + ``queue.py``): a tenant's share of the queue is
  bounded (``max_queued``); an over-share submit raises
  :class:`~raft_tpu.runtime.limits.RejectedError`
  (``reason="queue_full"``) and ticks
  ``limits_rejected_total{reason="queue_full"}`` — backpressure is the
  same typed refusal the HBM admission layer gives an over-budget
  launch, so callers need exactly one retry/shed policy.
- **Deadlines** (``queue.py`` submit + ``executor.py`` drain): each
  request is wired into a :class:`~raft_tpu.runtime.limits.Deadline`
  (tenant default or per-request override). A request that expires in
  queue fast-fails with ``DeadlineExceededError`` at drain — the launch
  it would have wasted goes to requests that can still meet their SLO —
  and the executor runs each batch under
  :func:`~raft_tpu.runtime.limits.deadline_scope` of the tightest
  surviving deadline so host-side work stays polled.
- **Memory budget** (``executor.py`` dispatch): a coalesced batch whose
  footprint estimate (``limits.estimate_bytes``) exceeds
  :meth:`QosPolicy.batch_budget` is SPLIT into smaller (still-warm)
  buckets; a single request that cannot fit even alone degrades through
  the PR-5 row-tiled path by running eagerly under
  :func:`~raft_tpu.runtime.limits.budget_scope` — bit-identical output,
  bounded footprint — and only raises ``RejectedError`` when even that
  cannot fit.
- **Fairness** (``queue.py``): tenant ``weight`` feeds the weighted-fair
  virtual clock; a heavy tenant gets proportionally more rows per unit
  time, never the whole pipe.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Optional

from raft_tpu import obs
from raft_tpu.runtime import limits

__all__ = ["TenantPolicy", "QosPolicy"]


@dataclass(frozen=True)
class TenantPolicy:
    """Per-tenant serving contract.

    weight
        fair-share weight (relative rows per unit time under load).
    deadline_s
        default request deadline; None = no deadline unless the request
        carries one.
    max_queued
        per-tenant cap on queued requests (None = only the global
        ``max_queue`` bounds it).
    """

    weight: float = 1.0
    deadline_s: Optional[float] = None
    max_queued: Optional[int] = None

    def __post_init__(self):
        if not self.weight > 0:
            raise ValueError(f"tenant weight must be > 0, "
                             f"got {self.weight}")
        if self.max_queued is not None and self.max_queued < 1:
            raise ValueError("max_queued must be >= 1 when set")


class QosPolicy:
    """Tenant policy table + the serving-side memory budget.

    ``tenants`` maps tenant name -> :class:`TenantPolicy`; unknown
    tenants get ``default``. ``budget`` is a
    :class:`~raft_tpu.runtime.limits.WorkBudget` (or byte count) that
    bounds one coalesced launch; None defers to the ambient
    ``limits.active_budget()`` (env/scope), which may itself be None —
    unbudgeted serving, the default."""

    def __init__(self, tenants: Optional[Dict[str, TenantPolicy]] = None,
                 *, default: Optional[TenantPolicy] = None, budget=None):
        self.tenants = dict(tenants or {})
        self.default = default or TenantPolicy()
        if budget is None or isinstance(budget, limits.WorkBudget):
            self._budget = budget
        else:
            self._budget = limits.WorkBudget(budget)

    def policy(self, tenant: str) -> TenantPolicy:
        return self.tenants.get(tenant, self.default)

    def batch_budget(self) -> Optional[limits.WorkBudget]:
        """The budget one coalesced launch must fit: the explicit
        serving budget when set, else the ambient limits scope."""
        return self._budget if self._budget is not None \
            else limits.active_budget()

    def check_tenant_share(self, op: str, tenant: str,
                           tenant_pending: int) -> None:
        """Per-tenant queue-share admission (called by
        :meth:`~raft_tpu.serve.queue.RequestQueue.submit` under its
        lock). Raises the same typed ``queue_full`` rejection as the
        global cap, labeled with the tenant."""
        cap = self.policy(tenant).max_queued
        if cap is not None and tenant_pending >= cap:
            obs.inc("limits_rejected_total", 1, reason="queue_full",
                    op=f"serve.{op}")
            raise limits.RejectedError(
                f"serve.{op}: tenant {tenant!r} queue share full "
                f"({tenant_pending} >= max_queued={cap})",
                op=f"serve.{op}", reason="queue_full")
