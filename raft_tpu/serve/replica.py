"""Replica groups: the sharded-ANN serving tier (ROADMAP item 1, serve
half).

A :class:`ReplicaGroup` fronts N warmed :class:`~raft_tpu.serve.Executor`
replicas — each its own queue/QoS/executable-cache stack serving the
same ops — behind one router:

- **Weighted-fair routing**: each replica carries a virtual clock that
  advances by ``rows / weight`` per routed request (the same
  virtual-time discipline the queue's per-tenant scheduler uses, lifted
  one level): under load every replica receives rows proportional to
  its weight, and an idle fleet routes to the least-loaded replica.
- **Spill**: a replica that refuses a submit with the typed
  ``RejectedError`` backpressure (queue full, breaker open) does not
  fail the request — the router spills it to the next replica in
  virtual-time order and counts the spill; only when EVERY healthy
  replica refuses does the typed rejection reach the caller.
- **Health-gated membership**: a failed replica is routed around the
  moment it is marked; with a :class:`~raft_tpu.comms.comms.MeshComms`
  attached, :meth:`ReplicaGroup.heal` rides the elastic machinery —
  ``ensure_healthy`` surfaces the typed peer failure,
  ``agree_on_survivors`` reaches consensus, ``shrink()`` carves the
  survivor clique — and the ``on_shrink`` callback repacks the sharded
  index (:func:`raft_tpu.neighbors.ivf_mnmg.shrink_mnmg`) and rebuilds
  warmed replicas for the survivor count. The whole recovery returns a
  typed :class:`RecoveryReport` carrying recovery seconds and the
  post-recovery SLO snapshot (PR-10's burn-rate gauge is the witness
  that survivors keep answering within budget).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from raft_tpu import obs
from raft_tpu.runtime import limits
from raft_tpu.serve.executor import Executor

__all__ = ["Replica", "ReplicaGroup", "ReplicaGroupStats",
           "RecoveryReport"]


@dataclass
class Replica:
    """One routed serving replica: an executor plus router state."""

    name: str
    executor: Executor
    weight: float = 1.0
    healthy: bool = True
    failed_reason: Optional[str] = None
    vtime: float = 0.0              # weighted-fair virtual clock (rows/weight)
    routed: int = 0                 # requests routed here
    spilled_from: int = 0           # rejections that spilled elsewhere


@dataclass
class ReplicaGroupStats:
    """Router counters (process-local, metrics-independent)."""

    routed: int = 0
    spills: int = 0                 # submits retried on another replica
    rejected: int = 0               # submits every replica refused
    failures: int = 0               # replicas marked failed
    recoveries: int = 0             # completed heal() shrink cycles
    last_recovery_s: float = 0.0


@dataclass(frozen=True)
class RecoveryReport:
    """One completed failure-recovery cycle, typed (the chaos gate
    asserts on these fields, not on log scraping)."""

    reason: str                     # the typed failure that triggered it
    survivors: Tuple[int, ...]      # old ranks that survived
    dead: Tuple[int, ...]           # old ranks declared dead
    recovery_s: float               # ensure_healthy -> serving again
    repacked: bool                  # on_shrink rebuilt the replicas
    slo: Dict[str, dict]            # post-recovery per-tenant SLO state


class ReplicaGroup:
    """Route requests across replica executors with weighted-fair spill
    and health-gated membership.

    ``executors``: the replica stack (each already holding the same
    service set). ``weights``: per-replica fair-share weights (default
    1.0 each). ``comms``: optional elastic clique whose rank *i* backs
    replica *i* — arms :meth:`heal`. ``on_shrink(comms, survivors)``:
    recovery callback returning the replacement executor list for the
    survivor clique (repacked + ready to warm), or None to keep the
    surviving replicas as-is.
    """

    def __init__(self, executors: Sequence[Executor], *,
                 names: Optional[Sequence[str]] = None,
                 weights: Optional[Sequence[float]] = None,
                 comms=None,
                 on_shrink: Optional[Callable] = None):
        if not executors:
            raise ValueError("need at least one replica executor")
        names = list(names) if names else [
            f"replica{i}" for i in range(len(executors))]
        weights = list(weights) if weights else [1.0] * len(executors)
        if not (len(names) == len(weights) == len(executors)):
            raise ValueError("executors/names/weights length mismatch")
        for w in weights:
            if not w > 0:
                raise ValueError(f"replica weight must be > 0, got {w}")
        self._replicas = [Replica(name=n, executor=e, weight=w)
                          for n, e, w in zip(names, executors, weights)]
        self.comms = comms
        self.on_shrink = on_shrink
        self.stats = ReplicaGroupStats()
        self._lock = threading.Lock()
        self._started = False

    # -- membership ----------------------------------------------------

    @property
    def replicas(self) -> List[Replica]:
        return list(self._replicas)

    def healthy(self) -> List[Replica]:
        return [r for r in self._replicas if r.healthy]

    def _resolve(self, which) -> Replica:
        if isinstance(which, Replica):
            return which
        if isinstance(which, int):
            return self._replicas[which]
        for r in self._replicas:
            if r.name == which:
                return r
        raise ValueError(f"unknown replica {which!r}; have "
                         f"{[r.name for r in self._replicas]}")

    def mark_failed(self, which, reason: str = "marked failed") -> None:
        """Health-gate a replica out of routing (no executor teardown —
        use :meth:`fail_replica` for the kill simulation)."""
        r = self._resolve(which)
        with self._lock:
            if not r.healthy:
                return
            r.healthy = False
            r.failed_reason = reason
            self.stats.failures += 1
        obs.inc("serve_replica_failures_total", 1, replica=r.name)
        obs.emit_event("serve.replica_failed", replica=r.name,
                       reason=reason)

    def fail_replica(self, which, reason: str = "killed") -> Replica:
        """The in-process kill: gate the replica out, tear its drain
        thread down WITHOUT the graceful drain, and fail whatever is
        still queued with the typed rejection — the observable a
        SIGKILL'd replica produces (in-flight work is lost, the router
        keeps answering on the survivors)."""
        r = self._resolve(which)
        self.mark_failed(r, reason)
        ex = r.executor
        ex.queue.close()
        ex._stop.set()
        if ex._thread is not None:
            ex._thread.join(timeout=10.0)
            ex._thread = None
        while True:
            batch = ex.queue.next_batch(timeout=0.0)
            if batch is None or not batch.requests:
                break
            for req in batch.requests:
                req.future.set_exception(limits.RejectedError(
                    f"serve.{req.op}: replica {r.name} failed "
                    f"({reason})", op=f"serve.{req.op}",
                    reason="replica_failed"))
        return r

    # -- routing -------------------------------------------------------

    def _pick_order(self) -> List[Replica]:
        """Healthy replicas in ascending virtual-time order (ties by
        position — deterministic)."""
        live = [(r.vtime, i, r)
                for i, r in enumerate(self._replicas) if r.healthy]
        live.sort(key=lambda t: (t[0], t[1]))
        return [r for _, _, r in live]

    def route(self, op: str, queries, *, tenant: str = "default",
              deadline_s: Optional[float] = None
              ) -> Tuple[Replica, "object"]:
        """Submit to the fleet; returns ``(replica, future)`` so callers
        that need per-replica attribution (the loadgen) get it. Spills
        typed rejections down the virtual-time order; re-raises the last
        rejection when every healthy replica refused."""
        rows = int(np.asarray(queries).shape[0])
        with self._lock:
            order = self._pick_order()
        if not order:
            with self._lock:
                self.stats.rejected += 1
            raise limits.RejectedError(
                f"serve.{op}: no healthy replica in the group",
                op=f"serve.{op}", reason="no_replica")
        last_exc: Optional[limits.RejectedError] = None
        for n_tried, r in enumerate(order):
            try:
                fut = r.executor.submit(op, queries, tenant=tenant,
                                        deadline_s=deadline_s)
            except limits.RejectedError as exc:
                last_exc = exc
                with self._lock:
                    r.spilled_from += 1
                    self.stats.spills += 1
                obs.inc("serve_replica_spills_total", 1, replica=r.name)
                continue
            with self._lock:
                # weighted-fair advance; a replica rejoining far behind
                # snaps to the fleet floor instead of absorbing a flood
                floor = min((o.vtime for o in order), default=0.0)
                r.vtime = max(r.vtime, floor) + rows / r.weight
                r.routed += 1
                self.stats.routed += 1
                if n_tried:
                    pass            # spill already counted above
            return r, fut
        with self._lock:
            self.stats.rejected += 1
        raise last_exc

    def submit(self, op: str, queries, *, tenant: str = "default",
               deadline_s: Optional[float] = None):
        """Fleet submit (router-attributed): the future only."""
        return self.route(op, queries, tenant=tenant,
                          deadline_s=deadline_s)[1]

    # -- recovery ------------------------------------------------------

    def heal(self, *, timeout: Optional[float] = None
             ) -> Optional[RecoveryReport]:
        """Run one health check against the attached comms clique and,
        on a typed failure, the full recovery: consensus -> shrink ->
        mark dead replicas -> ``on_shrink`` repack -> warm replacements.
        Returns None when the clique is healthy."""
        if self.comms is None:
            raise ValueError("heal() needs a comms clique attached")
        from raft_tpu.comms.errors import (CommsAbortedError,
                                           PeerFailedError)

        t0 = time.monotonic()
        try:
            self.comms.ensure_healthy()
            return None
        except (PeerFailedError, CommsAbortedError) as exc:
            reason = f"{type(exc).__name__}: {exc}"
        obs.emit_event("serve.replica_heal_begin", reason=reason)
        old_size = self.comms.get_size()
        survivors = tuple(self.comms.agree_on_survivors(timeout))
        dead = tuple(sorted(set(range(old_size)) - set(survivors)))
        new_comms = self.comms.shrink(survivors)
        with self._lock:
            self.comms = new_comms
        for r in dead:
            if r < len(self._replicas):
                self.mark_failed(r, reason)
        repacked = False
        if self.on_shrink is not None:
            new_execs = self.on_shrink(new_comms, survivors)
            if new_execs:
                replacements = [
                    Replica(name=f"replica{i}", executor=e,
                            weight=self._replicas[old].weight
                            if old < len(self._replicas) else 1.0)
                    for i, (old, e) in enumerate(
                        zip(survivors, new_execs))]
                with self._lock:
                    self._replicas = replacements
                for r in replacements:
                    r.executor.warm()
                    if self._started:
                        r.executor.start()
                repacked = True
        dt = time.monotonic() - t0
        with self._lock:
            self.stats.recoveries += 1
            self.stats.last_recovery_s = dt
        obs.observe("serve_recovery_seconds", dt,
                    help="typed-failure detection to serving-again")
        obs.emit_event("serve.replica_shrink", survivors=list(survivors),
                       dead=list(dead), recovery_s=round(dt, 4),
                       repacked=repacked)
        return RecoveryReport(reason=reason, survivors=survivors,
                              dead=dead, recovery_s=dt,
                              repacked=repacked,
                              slo=self.slo_snapshot())

    # -- fleet surface -------------------------------------------------

    def warm(self, buckets: Optional[Sequence[int]] = None) -> int:
        return sum(r.executor.warm(buckets)
                   for r in self._replicas if r.healthy)

    def slo_snapshot(self) -> Dict[str, dict]:
        """Per-tenant SLO state merged across replicas (window counts
        summed, burn rate recomputed fleet-wide)."""
        merged: Dict[str, dict] = {}
        for r in self._replicas:
            qos = getattr(r.executor, "qos", None)
            if qos is None or not hasattr(qos, "slo_snapshot"):
                continue
            for tenant, snap in qos.slo_snapshot().items():
                cur = merged.setdefault(tenant, {
                    "slo_latency_s": snap["slo_latency_s"],
                    "slo_target": snap["slo_target"],
                    "window_requests": 0, "window_bad": 0,
                    "burn_rate": 0.0})
                cur["window_requests"] += snap["window_requests"]
                cur["window_bad"] += snap["window_bad"]
        for tenant, cur in merged.items():
            n, bad = cur["window_requests"], cur["window_bad"]
            tolerated = 1.0 - cur["slo_target"]
            cur["burn_rate"] = (bad / n) / tolerated if n else 0.0
        return merged

    def start(self) -> "ReplicaGroup":
        for r in self._replicas:
            if r.healthy:
                r.executor.start()
        with self._lock:
            self._started = True
        obs.emit_event("serve.group_start",
                       replicas=[r.name for r in self._replicas])
        return self

    def stop(self) -> None:
        for r in self._replicas:
            if r.healthy:
                r.executor.stop()
        with self._lock:
            self._started = False
        s = self.stats
        obs.emit_event("serve.group_stop", routed=s.routed,
                       spills=s.spills, rejected=s.rejected,
                       failures=s.failures, recoveries=s.recoveries)

    def __enter__(self) -> "ReplicaGroup":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()
