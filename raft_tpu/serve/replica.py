"""Replica groups: the sharded-ANN serving tier (ROADMAP item 1, serve
half).

A :class:`ReplicaGroup` fronts N warmed :class:`~raft_tpu.serve.Executor`
replicas — each its own queue/QoS/executable-cache stack serving the
same ops — behind one router:

- **Weighted-fair routing**: each replica carries a virtual clock that
  advances by ``rows / weight`` per routed request (the same
  virtual-time discipline the queue's per-tenant scheduler uses, lifted
  one level): under load every replica receives rows proportional to
  its weight, and an idle fleet routes to the least-loaded replica.
- **Spill**: a replica that refuses a submit with the typed
  ``RejectedError`` backpressure (queue full, breaker open) does not
  fail the request — the router spills it to the next replica in
  virtual-time order and counts the spill; only when EVERY healthy
  replica refuses does the typed rejection reach the caller.
- **Health-gated membership**: a failed replica is routed around the
  moment it is marked; with a :class:`~raft_tpu.comms.comms.MeshComms`
  attached, :meth:`ReplicaGroup.heal` rides the elastic machinery —
  ``ensure_healthy`` surfaces the typed peer failure,
  ``agree_on_survivors`` reaches consensus, ``shrink()`` carves the
  survivor clique — and the ``on_shrink`` callback repacks the sharded
  index (:func:`raft_tpu.neighbors.ivf_mnmg.shrink_mnmg`) and rebuilds
  warmed replicas for the survivor count. The whole recovery returns a
  typed :class:`RecoveryReport` carrying recovery seconds and the
  post-recovery SLO snapshot (PR-10's burn-rate gauge is the witness
  that survivors keep answering within budget).
"""

from __future__ import annotations

import collections
import heapq
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Deque, Dict, List, Optional, Sequence, Tuple

import numpy as np

from raft_tpu import obs
from raft_tpu.core import env as _env_mod
from raft_tpu.runtime import limits
from raft_tpu.serve.executor import Executor
from raft_tpu.serve.queue import Request, ResultFuture, bucket_rows

__all__ = ["Replica", "ReplicaGroup", "ReplicaGroupStats",
           "RecoveryReport", "HedgePolicy"]


@dataclass
class Replica:
    """One routed serving replica: an executor plus router state."""

    name: str
    executor: Executor
    weight: float = 1.0
    healthy: bool = True
    failed_reason: Optional[str] = None
    vtime: float = 0.0              # weighted-fair virtual clock (rows/weight)
    routed: int = 0                 # requests routed here
    spilled_from: int = 0           # rejections that spilled elsewhere


@dataclass
class ReplicaGroupStats:
    """Router counters (process-local, metrics-independent)."""

    routed: int = 0
    spills: int = 0                 # submits retried on another replica
    rejected: int = 0               # submits every replica refused
    failures: int = 0               # replicas marked failed
    recoveries: int = 0             # completed heal() shrink cycles
    last_recovery_s: float = 0.0
    hedges_issued: int = 0          # second legs actually dispatched
    hedges_won: int = 0             # hedge leg finished first
    hedges_suppressed: int = 0      # budget / no-replica suppressions

    def hedge_rate(self) -> float:
        """Issued hedges over routed submits — the ≤5% invariant the
        slow-replica gate asserts."""
        return self.hedges_issued / self.routed if self.routed else 0.0


@dataclass(frozen=True)
class HedgePolicy:
    """Hedged-request tuning (Dean & Barroso, "The Tail at Scale").

    A hedge fires only after the request has outlived the adaptive
    per-bucket delay — the ``quantile`` (default p95) of the last
    ``window`` primary completion latencies for that row bucket — so
    ~`1 - quantile` of requests are even eligible, and the per-tenant
    budget (``budget_fraction`` of primary submits over
    ``budget_window_s``) hard-caps amplification below that. Until
    ``min_samples`` completions exist for a bucket there is no delay
    estimate and no hedging: an unwarmed fleet must not hedge blind."""

    delay_floor_s: float = 0.002    # never hedge earlier than this
    quantile: float = 0.95
    window: int = 128               # latency samples kept per bucket
    min_samples: int = 16
    budget_fraction: float = 0.05   # hedges / primaries, per tenant
    budget_window_s: float = 60.0

    def __post_init__(self):
        if not self.delay_floor_s >= 0:
            raise ValueError("delay_floor_s must be >= 0")
        if not (0.0 < self.quantile < 1.0):
            raise ValueError(
                f"quantile must be in (0, 1), got {self.quantile}")
        if self.window < 1 or self.min_samples < 1:
            raise ValueError("window and min_samples must be >= 1")
        if not (0.0 < self.budget_fraction <= 1.0):
            raise ValueError(f"budget_fraction must be in (0, 1], "
                             f"got {self.budget_fraction}")
        if not self.budget_window_s > 0:
            raise ValueError("budget_window_s must be > 0")


class _HedgeEntry:
    """One watched submit: the caller-visible outer future plus up to
    two legs (primary, hedge). First leg to SUCCEED fulfills the outer
    future and cancels the other; the outer future fails only when no
    leg can succeed anymore."""

    __slots__ = ("op", "queries", "tenant", "deadline_s", "outer",
                 "primary", "primary_replica", "hedge", "t0", "lock",
                 "decided")

    def __init__(self, op, queries, tenant, deadline_s, outer,
                 primary: Request, primary_replica: str, t0: float):
        self.op = op
        self.queries = queries
        self.tenant = tenant
        self.deadline_s = deadline_s
        self.outer = outer
        self.primary = primary
        self.primary_replica = primary_replica
        self.hedge: Optional[Request] = None
        self.t0 = t0
        self.lock = threading.Lock()
        self.decided = False            # a leg claimed the outcome


class _Hedger:
    """The group's hedge engine: one scheduler thread over a time-heap
    of watched submits, per-bucket latency windows, and per-tenant
    :class:`~raft_tpu.runtime.limits.RateBudget` caps."""

    def __init__(self, group: "ReplicaGroup", policy: HedgePolicy):
        self._group = group
        self.policy = policy
        self._cond = threading.Condition()
        self._heap: List[Tuple[float, int, _HedgeEntry]] = []
        self._seq = 0
        self._samples: Dict[int, Deque[float]] = {}
        self._samples_lock = threading.Lock()
        self._budgets: Dict[str, limits.RateBudget] = {}
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    # -- lifecycle -----------------------------------------------------

    def start(self) -> None:
        with self._cond:
            if self._thread is not None and self._thread.is_alive():
                return
            self._stop.clear()
            self._thread = threading.Thread(target=self._run,
                                            name="raft-tpu-hedge",
                                            daemon=True)
            self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        with self._cond:
            self._cond.notify_all()
            thread, self._thread = self._thread, None
        if thread is not None:
            # join OUTSIDE the condition: the scheduler loop takes it
            thread.join(timeout=10.0)

    # -- delay estimate ------------------------------------------------

    def _record_sample(self, bucket: int, latency_s: float) -> None:
        with self._samples_lock:
            dq = self._samples.get(bucket)
            if dq is None:
                dq = self._samples[bucket] = collections.deque(
                    maxlen=self.policy.window)
            dq.append(latency_s)

    def hedge_delay(self, bucket: int) -> Optional[float]:
        """The adaptive delay for a row bucket: the policy quantile of
        recent primary completions, floored at ``delay_floor_s``; None
        until ``min_samples`` completions exist (no blind hedging)."""
        with self._samples_lock:
            dq = self._samples.get(bucket)
            if dq is None or len(dq) < self.policy.min_samples:
                return None
            samples = sorted(dq)
        idx = min(int(len(samples) * self.policy.quantile),
                  len(samples) - 1)
        return max(samples[idx], self.policy.delay_floor_s)

    def _budget(self, tenant: str) -> limits.RateBudget:
        b = self._budgets.get(tenant)
        if b is None:
            with self._samples_lock:
                b = self._budgets.setdefault(
                    tenant, limits.RateBudget(
                        max_fraction=self.policy.budget_fraction,
                        window_s=self.policy.budget_window_s))
        return b

    # -- the watched-submit surface -------------------------------------

    def watch(self, replica: Replica, req: Request) -> ResultFuture:
        """Wrap one routed primary request: returns the outer future,
        schedules the hedge timer when a delay estimate exists, and
        wires the first-success-wins state machine."""
        outer = ResultFuture()
        t0 = time.monotonic()
        entry = _HedgeEntry(req.op, req.queries, req.tenant,
                            req.deadline.budget_s if req.deadline
                            else None, outer, req, replica.name, t0)
        self._budget(req.tenant).note()
        req.future.add_done_callback(
            lambda fut: self._on_leg_done(entry, req, fut,
                                          is_hedge=False))
        delay = self.hedge_delay(bucket_rows(req.rows))
        if delay is not None:
            with self._cond:
                self._seq += 1
                heapq.heappush(self._heap, (t0 + delay, self._seq,
                                            entry))
                self._cond.notify_all()
        return outer

    def _on_leg_done(self, entry: _HedgeEntry, req: Request, fut,
                     is_hedge: bool) -> None:
        # Runs on the fulfilling (executor drain) thread. Decisions are
        # made under entry.lock; SIDE EFFECTS run after releasing it —
        # cancel() fulfills the loser's future, which fires THIS
        # callback again synchronously on the same thread, so doing it
        # under the (non-reentrant) lock would deadlock the drain loop.
        exc = fut.exception(timeout=0)
        if not is_hedge and exc is None:
            self._record_sample(bucket_rows(req.rows),
                                time.monotonic() - entry.t0)
        win = fail = raced = False
        to_cancel: Optional[Request] = None
        with entry.lock:
            other = entry.primary if is_hedge else entry.hedge
            if exc is None:
                if not entry.decided:
                    entry.decided = True
                    win = True
                    raced = entry.hedge is not None
                    if other is not None and not other.future.done():
                        to_cancel = other
            elif not entry.decided and (other is None
                                        or other.future.done()):
                # no leg can succeed anymore: surface this failure
                entry.decided = True
                fail = True
        if win:
            entry.outer.set_result(fut.result(timeout=0))
            if raced:
                obs.inc("serve_hedges_total", 1,
                        outcome="won" if is_hedge else "lost")
                if is_hedge:
                    with self._group._lock:
                        self._group.stats.hedges_won += 1
            if to_cancel is not None:
                to_cancel.cancel("hedge_lost")
        elif fail:
            entry.outer.set_exception(exc)

    # -- scheduler thread ----------------------------------------------

    def _run(self) -> None:
        while not self._stop.is_set():
            with self._cond:
                if not self._heap:
                    self._cond.wait(0.1)
                    continue
                fire_at, _, entry = self._heap[0]
                now = time.monotonic()
                if fire_at > now:
                    self._cond.wait(min(fire_at - now, 0.1))
                    continue
                heapq.heappop(self._heap)
            self._fire(entry)

    def _fire(self, entry: _HedgeEntry) -> None:
        with entry.lock:
            if entry.decided or entry.primary.future.done():
                return                      # primary made it in time
        if entry.primary.cancelled is not None:
            return
        if not self._budget(entry.tenant).try_spend():
            with self._group._lock:
                self._group.stats.hedges_suppressed += 1
            obs.inc("serve_hedges_total", 1, outcome="suppressed")
            return
        try:
            _, hedge_req = self._group._route_request(
                entry.op, entry.queries, tenant=entry.tenant,
                deadline_s=entry.deadline_s, hedge=True,
                exclude=entry.primary_replica)
        except limits.RejectedError:
            with self._group._lock:
                self._group.stats.hedges_suppressed += 1
            obs.inc("serve_hedges_total", 1, outcome="suppressed")
            return
        issue = False
        with entry.lock:
            if not entry.decided:
                entry.hedge = hedge_req
                issue = True
        if not issue:
            # primary finished while we were routing: the hedge is a
            # dead leg — cancel it before it burns a launch
            hedge_req.cancel("hedge_unneeded")
        if issue:
            with self._group._lock:
                self._group.stats.hedges_issued += 1
            obs.inc("serve_hedges_total", 1, outcome="issued",
                    help="hedged second legs by outcome "
                         "(issued|won|lost|suppressed)")
            hedge_req.future.add_done_callback(
                lambda fut: self._on_leg_done(entry, hedge_req, fut,
                                              is_hedge=True))


@dataclass(frozen=True)
class RecoveryReport:
    """One completed failure-recovery cycle, typed (the chaos gate
    asserts on these fields, not on log scraping)."""

    reason: str                     # the typed failure that triggered it
    survivors: Tuple[int, ...]      # old ranks that survived
    dead: Tuple[int, ...]           # old ranks declared dead
    recovery_s: float               # ensure_healthy -> serving again
    repacked: bool                  # on_shrink rebuilt the replicas
    slo: Dict[str, dict]            # post-recovery per-tenant SLO state


class ReplicaGroup:
    """Route requests across replica executors with weighted-fair spill
    and health-gated membership.

    ``executors``: the replica stack (each already holding the same
    service set). ``weights``: per-replica fair-share weights (default
    1.0 each). ``comms``: optional elastic clique whose rank *i* backs
    replica *i* — arms :meth:`heal`. ``on_shrink(comms, survivors)``:
    recovery callback returning the replacement executor list for the
    survivor clique (repacked + ready to warm), or None to keep the
    surviving replicas as-is.
    """

    def __init__(self, executors: Sequence[Executor], *,
                 names: Optional[Sequence[str]] = None,
                 weights: Optional[Sequence[float]] = None,
                 comms=None,
                 on_shrink: Optional[Callable] = None,
                 hedge: Optional[HedgePolicy] = None):
        if not executors:
            raise ValueError("need at least one replica executor")
        names = list(names) if names else [
            f"replica{i}" for i in range(len(executors))]
        weights = list(weights) if weights else [1.0] * len(executors)
        if not (len(names) == len(weights) == len(executors)):
            raise ValueError("executors/names/weights length mismatch")
        for w in weights:
            if not w > 0:
                raise ValueError(f"replica weight must be > 0, got {w}")
        self._replicas = [Replica(name=n, executor=e, weight=w)
                          for n, e, w in zip(names, executors, weights)]
        self.comms = comms
        self.on_shrink = on_shrink
        self._leader: Optional[str] = None   # write-leader marker
        self.stats = ReplicaGroupStats()
        self._lock = threading.Lock()
        self._started = False
        # hedged dispatch (ISSUE 16): armed by passing a HedgePolicy,
        # kill-switched fleet-wide by RAFT_TPU_HEDGE=off
        if hedge is not None and not bool(_env_mod.read("RAFT_TPU_HEDGE")):
            hedge = None
        self.hedge = hedge
        self._hedger = _Hedger(self, hedge) if hedge is not None else None

    # -- membership ----------------------------------------------------

    @property
    def replicas(self) -> List[Replica]:
        return list(self._replicas)

    def healthy(self) -> List[Replica]:
        return [r for r in self._replicas if r.healthy]

    def _resolve(self, which) -> Replica:
        if isinstance(which, Replica):
            return which
        if isinstance(which, int):
            return self._replicas[which]
        for r in self._replicas:
            if r.name == which:
                return r
        raise ValueError(f"unknown replica {which!r}; have "
                         f"{[r.name for r in self._replicas]}")

    def mark_failed(self, which, reason: str = "marked failed") -> None:
        """Health-gate a replica out of routing (no executor teardown —
        use :meth:`fail_replica` for the kill simulation)."""
        r = self._resolve(which)
        with self._lock:
            if not r.healthy:
                return
            r.healthy = False
            r.failed_reason = reason
            if self._leader == r.name:
                self._leader = None     # until the election promotes
            self.stats.failures += 1
        obs.inc("serve_replica_failures_total", 1, replica=r.name)
        obs.emit_event("serve.replica_failed", replica=r.name,
                       reason=reason)

    def rejoin(self, which) -> Replica:
        """Bring a marked-failed replica back into routing (the
        operator "it's healthy again" signal). Its stale virtual clock
        snaps to the fleet floor at the next route — the rejoiner gets
        its fair share immediately, not a catch-up flood."""
        r = self._resolve(which)
        with self._lock:
            r.healthy = True
            r.failed_reason = None
        if self._started:
            r.executor.start()
        obs.emit_event("serve.replica_rejoin", replica=r.name)
        return r

    def spawn(self, name: str, executor: Executor, *,
              weight: float = 1.0, warm: bool = True) -> Replica:
        """Grow the fleet by one replica — the inverse of :meth:`heal`
        (ISSUE 18 / ROADMAP item 6): a NEW executor (typically serving
        a WAL-caught-up or freshly restored index) joins routing.

        The joiner's virtual clock starts at 0 and snaps to the fleet
        floor on its first route — exactly the :meth:`rejoin`
        discipline, so a spawn gets its fair share immediately, never a
        catch-up flood. ``warm=True`` pre-warms the executor's serving
        buckets BEFORE the replica becomes routable, so the first
        production query hits a compiled executable (the zero-
        post-warm-recompile acceptance)."""
        if not weight > 0:
            raise ValueError(f"replica weight must be > 0, got {weight}")
        for r in self._replicas:
            if r.name == name:
                raise ValueError(f"replica name {name!r} already in "
                                 "the group (rejoin it instead)")
        if warm:
            executor.warm()
        rep = Replica(name=name, executor=executor, weight=float(weight))
        with self._lock:
            self._replicas.append(rep)
            started = self._started
        if started:
            executor.start()
        obs.emit_event("serve.replica_spawn", replica=name,
                       weight=float(weight), warmed=bool(warm))
        return rep

    def promote(self, which) -> Replica:
        """Re-point write routing at a new leader replica (ISSUE 20:
        the serving-tier half of a fleet election, called from the
        election node's ``on_promote`` hook or by the orchestrator).

        Deliberately does NOT touch any executor: the promoted
        replica's index was already the most-caught-up mirror, its
        serving snapshot is already published, and the role change
        moves no rows — so the warmed executables survive verbatim and
        the query path sees ZERO post-promotion recompiles (the chaos
        witness asserts this via ``ExecutorStats.traces``). Queries
        keep routing across every healthy replica; only the leader
        marker — where :class:`~raft_tpu.serve.ingest.IngestController`
        mutations must land — moves."""
        r = self._resolve(which)
        if not r.healthy:
            raise ValueError(
                f"cannot promote failed replica {r.name!r} "
                f"({r.failed_reason}); rejoin it first")
        with self._lock:
            prev, self._leader = self._leader, r.name
        obs.emit_event("serve.replica_promoted", replica=r.name,
                       previous=prev)
        obs.inc("serve_replica_promotions_total", 1, replica=r.name)
        return r

    @property
    def leader(self) -> Optional[Replica]:
        """The current write-leader replica (None until promoted)."""
        name = self._leader
        return None if name is None else self._resolve(name)

    def fail_replica(self, which, reason: str = "killed") -> Replica:
        """The in-process kill: gate the replica out, tear its drain
        thread down WITHOUT the graceful drain, and fail whatever is
        still queued with the typed rejection — the observable a
        SIGKILL'd replica produces (in-flight work is lost, the router
        keeps answering on the survivors)."""
        r = self._resolve(which)
        self.mark_failed(r, reason)
        ex = r.executor
        ex.queue.close()
        ex._stop.set()
        if ex._thread is not None:
            ex._thread.join(timeout=10.0)
            ex._thread = None
        while True:
            batch = ex.queue.next_batch(timeout=0.0)
            if batch is None or not batch.requests:
                break
            for req in batch.requests:
                req.future.set_exception(limits.RejectedError(
                    f"serve.{req.op}: replica {r.name} failed "
                    f"({reason})", op=f"serve.{req.op}",
                    reason="replica_failed"))
        return r

    # -- routing -------------------------------------------------------

    def _pick_order(self) -> List[Replica]:
        """Healthy replicas in ascending virtual-time order (ties by
        position — deterministic)."""
        live = [(r.vtime, i, r)
                for i, r in enumerate(self._replicas) if r.healthy]
        live.sort(key=lambda t: (t[0], t[1]))
        return [r for _, _, r in live]

    def route(self, op: str, queries, *, tenant: str = "default",
              deadline_s: Optional[float] = None
              ) -> Tuple[Replica, "object"]:
        """Submit to the fleet; returns ``(replica, future)`` so callers
        that need per-replica attribution (the loadgen) get it. Spills
        typed rejections down the virtual-time order; re-raises the last
        rejection when every healthy replica refused."""
        rep, req = self._route_request(op, queries, tenant=tenant,
                                       deadline_s=deadline_s)
        return rep, req.future

    def _route_request(self, op: str, queries, *,
                       tenant: str = "default",
                       deadline_s: Optional[float] = None,
                       hedge: bool = False,
                       exclude: Optional[str] = None
                       ) -> Tuple[Replica, Request]:
        """The routing core: ``(replica, Request)``. ``exclude`` skips
        one replica by name — a hedge's second leg must land somewhere
        other than the straggler it is hedging against."""
        rows = int(np.asarray(queries).shape[0])
        with self._lock:
            order = [r for r in self._pick_order()
                     if r.name != exclude]
        if not order:
            with self._lock:
                self.stats.rejected += 1
            raise limits.RejectedError(
                f"serve.{op}: no healthy replica in the group",
                op=f"serve.{op}", reason="no_replica")
        last_exc: Optional[limits.RejectedError] = None
        for r in order:
            try:
                req = r.executor.submit_request(
                    op, queries, tenant=tenant, deadline_s=deadline_s,
                    hedge=hedge)
            except limits.RejectedError as exc:
                last_exc = exc
                with self._lock:
                    r.spilled_from += 1
                    self.stats.spills += 1
                obs.inc("serve_replica_spills_total", 1, replica=r.name)
                continue
            with self._lock:
                # weighted-fair advance; a replica rejoining far behind
                # snaps to the fleet floor instead of absorbing a flood
                # (floor = the OTHERS' minimum — including r itself
                # would make the laggard its own floor and never snap)
                floor = min((o.vtime for o in order if o is not r),
                            default=r.vtime)
                r.vtime = max(r.vtime, floor) + rows / r.weight
                r.routed += 1
                self.stats.routed += 1
            return r, req
        with self._lock:
            self.stats.rejected += 1
        raise last_exc

    def submit(self, op: str, queries, *, tenant: str = "default",
               deadline_s: Optional[float] = None):
        """Fleet submit (router-attributed): the future only. With a
        :class:`HedgePolicy` attached this is the hedged entry point:
        the returned future is fulfilled by whichever leg succeeds
        first (the loser is cancelled, typed), and the per-tenant hedge
        budget bounds second legs at ``budget_fraction`` of submits."""
        rep, req = self._route_request(op, queries, tenant=tenant,
                                       deadline_s=deadline_s)
        if self._hedger is None:
            return req.future
        return self._hedger.watch(rep, req)

    # -- recovery ------------------------------------------------------

    def heal(self, *, timeout: Optional[float] = None
             ) -> Optional[RecoveryReport]:
        """Run one health check against the attached comms clique and,
        on a typed failure, the full recovery: consensus -> shrink ->
        mark dead replicas -> ``on_shrink`` repack -> warm replacements.
        Returns None when the clique is healthy."""
        if self.comms is None:
            raise ValueError("heal() needs a comms clique attached")
        from raft_tpu.comms.errors import (CommsAbortedError,
                                           PeerFailedError)

        t0 = time.monotonic()
        try:
            self.comms.ensure_healthy()
            return None
        except (PeerFailedError, CommsAbortedError) as exc:
            reason = f"{type(exc).__name__}: {exc}"
        obs.emit_event("serve.replica_heal_begin", reason=reason)
        old_size = self.comms.get_size()
        survivors = tuple(self.comms.agree_on_survivors(timeout))
        dead = tuple(sorted(set(range(old_size)) - set(survivors)))
        new_comms = self.comms.shrink(survivors)
        with self._lock:
            self.comms = new_comms
        for r in dead:
            if r < len(self._replicas):
                self.mark_failed(r, reason)
        repacked = False
        if self.on_shrink is not None:
            new_execs = self.on_shrink(new_comms, survivors)
            if new_execs:
                replacements = [
                    Replica(name=f"replica{i}", executor=e,
                            weight=self._replicas[old].weight
                            if old < len(self._replicas) else 1.0)
                    for i, (old, e) in enumerate(
                        zip(survivors, new_execs))]
                with self._lock:
                    self._replicas = replacements
                for r in replacements:
                    r.executor.warm()
                    if self._started:
                        r.executor.start()
                repacked = True
        dt = time.monotonic() - t0
        with self._lock:
            self.stats.recoveries += 1
            self.stats.last_recovery_s = dt
        obs.observe("serve_recovery_seconds", dt,
                    help="typed-failure detection to serving-again")
        obs.emit_event("serve.replica_shrink", survivors=list(survivors),
                       dead=list(dead), recovery_s=round(dt, 4),
                       repacked=repacked)
        return RecoveryReport(reason=reason, survivors=survivors,
                              dead=dead, recovery_s=dt,
                              repacked=repacked,
                              slo=self.slo_snapshot())

    # -- fleet surface -------------------------------------------------

    def warm(self, buckets: Optional[Sequence[int]] = None) -> int:
        return sum(r.executor.warm(buckets)
                   for r in self._replicas if r.healthy)

    def slo_snapshot(self) -> Dict[str, dict]:
        """Per-tenant SLO state merged across replicas (window counts
        summed, burn rate recomputed fleet-wide)."""
        merged: Dict[str, dict] = {}
        for r in self._replicas:
            qos = getattr(r.executor, "qos", None)
            if qos is None or not hasattr(qos, "slo_snapshot"):
                continue
            for tenant, snap in qos.slo_snapshot().items():
                cur = merged.setdefault(tenant, {
                    "slo_latency_s": snap["slo_latency_s"],
                    "slo_target": snap["slo_target"],
                    "window_requests": 0, "window_bad": 0,
                    "burn_rate": 0.0})
                cur["window_requests"] += snap["window_requests"]
                cur["window_bad"] += snap["window_bad"]
        for tenant, cur in merged.items():
            n, bad = cur["window_requests"], cur["window_bad"]
            tolerated = 1.0 - cur["slo_target"]
            cur["burn_rate"] = (bad / n) / tolerated if n else 0.0
        return merged

    def start(self) -> "ReplicaGroup":
        for r in self._replicas:
            if r.healthy:
                r.executor.start()
        if self._hedger is not None:
            self._hedger.start()
        with self._lock:
            self._started = True
        obs.emit_event("serve.group_start",
                       replicas=[r.name for r in self._replicas])
        return self

    def stop(self) -> None:
        if self._hedger is not None:
            self._hedger.stop()
        for r in self._replicas:
            if r.healthy:
                r.executor.stop()
        with self._lock:
            self._started = False
        s = self.stats
        obs.emit_event("serve.group_stop", routed=s.routed,
                       spills=s.spills, rejected=s.rejected,
                       failures=s.failures, recoveries=s.recoveries,
                       hedges_issued=s.hedges_issued,
                       hedges_won=s.hedges_won,
                       hedge_rate=round(s.hedge_rate(), 4))

    def __enter__(self) -> "ReplicaGroup":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()
