"""Serving-side streaming ingest (ISSUE 17, serving half): a live
IVF-Flat serve op whose fixed operands track a
:class:`~raft_tpu.neighbors.streaming.StreamingIndex` across online
mutation, background compaction and drift refits — without ever
pausing the query path on a compile.

The moving part the static serve stack never had to handle: a
compaction or refit swaps the index's packed arrays, and the swap can
CHANGE THEIR SHAPES (lists repack to new caps). The executor's warmed
executables bake shapes at AOT export, so a naive in-place swap of
``fixed_args`` would either crash the next launch (shape mismatch) or
force an inline compile (a pause — exactly what zero-pause compaction
promises away). Two mechanisms close the gap:

- **Epoch-consistent launches** (``serve/executor.py``): every service
  holds its serving state as ONE atomically-swapped tuple
  ``(epoch, fixed_args, statics)``; dispatch reads the snapshot once
  and threads it through executable lookup (cache key includes the
  epoch) and the call itself, so a swap landing mid-dispatch can never
  pair new-shape operands with an old-shape executable. Queries racing
  a swap serve the OLD snapshot — immutable arrays, still-correct
  results, exactly the "atomic swap between serve batches" contract.

- **Pre-warm, then publish** (:class:`IngestController`): when a swap
  changes shapes, the controller builds AND invokes the new epoch's
  executables for the whole bucket ladder while queries continue
  against the old epoch, and only then publishes the new serving
  tuple. Same-shape swaps (deletes, fitting inserts) publish
  immediately — the warmed executables stay valid because AOT bakes
  shapes, not values.

:class:`StreamingKnnService` is the service: same traced body as
``IvfKnnService`` plus the tombstone mask operand, rebuilt per epoch
from the streaming snapshot. :class:`IngestController` owns the trio
(stream, executor, compactor) and keeps them consistent — foreground
``insert``/``delete`` re-snapshot inline; background compaction swaps
arrive through the compactor's ``on_change`` hook on the worker
thread.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu import obs
from raft_tpu.neighbors.streaming import Compactor, StreamingIndex
from raft_tpu.runtime import limits
from raft_tpu.serve.executor import Executor, Service
from raft_tpu.serve.queue import bucket_ladder

__all__ = ["StreamingKnnService", "IngestController", "NotLeaderError"]


class NotLeaderError(RuntimeError):
    """A mutation reached a controller whose replica is not the fleet
    leader. Carries the redirect: ``leader`` is the rank clients should
    re-send the write to (with the SAME ``write_id`` — the seq-dedup
    map makes the replay idempotent even when the original leader
    applied it before dying)."""

    def __init__(self, *, leader: int, rank: Optional[int] = None):
        where = f"replica rank {rank}" if rank is not None else \
            "a follower replica"
        super().__init__(
            f"not the leader: {where} cannot accept writes; redirect "
            f"to leader rank {leader} (replay with the same write_id — "
            f"the seq-dedup map makes the retry idempotent)")
        self.leader = int(leader)
        self.rank = rank


class StreamingKnnService(Service):
    """Batched IVF-Flat kNN against a LIVE streaming index. The traced
    body is :func:`ivf_flat._search_body` with the epoch's tombstone
    words as a sixth fixed operand — deleted rows are masked out
    in-score, bit-identical to a rebuild without them for the
    candidates scanned (the PR-9 masked-validity path).

    Unlike the static services, the fixed operands are a *snapshot*
    that :meth:`prepare`/:meth:`publish` roll forward as the index
    mutates. ``prepare()`` computes the serving tuple for the stream's
    current snapshot (bumping the serve epoch iff any operand shape
    changed); ``publish()`` installs it atomically. The controller
    interposes a pre-warm between the two for shape-changing swaps;
    :meth:`refresh` is the immediate prepare+publish for callers that
    accept an inline compile.

    Caller contract mirrors :class:`IvfKnnService`: one instance per
    (k, nprobe), ``0 < nprobe < n_lists`` (full scans are brute force
    over the live rows — serve those through the stream's exact path),
    and k at most the live-row count."""

    def __init__(self, stream: StreamingIndex, k: int, nprobe: int):
        flat = stream.flat
        if not 0 < nprobe < flat.n_lists:
            raise ValueError(
                f"StreamingKnnService needs 0 < nprobe < n_lists "
                f"(got nprobe={nprobe}, n_lists={flat.n_lists}); "
                f"nprobe >= n_lists is a full scan — serve it through "
                f"StreamingIndex.search's exact path")
        self.stream = stream
        self.k = int(k)
        self.nprobe = int(nprobe)
        self.name = f"stream_knn_k{k}_np{nprobe}_{flat.metric}"
        super().__init__((), dim=flat.dim, dtype=flat.packed_db.dtype)
        self._version = -1
        pending, version = self.prepare()
        self.publish(pending, version)

    # -- snapshot roll-forward ----------------------------------------

    def prepare(self) -> Optional[Tuple[Tuple, int]]:
        """Compute ``(pending_serving, stream_version)`` for the
        stream's current snapshot, or None when already serving it.
        The pending tuple's epoch is bumped iff any fixed operand's
        shape (or compiled static) differs from what is being served —
        same-shape swaps reuse the warmed executables."""
        snap = self.stream.snapshot
        if snap.version == self._version:
            return None
        from raft_tpu.neighbors.ivf_flat import _use_radix

        flat = snap.flat
        probe_rows = self.nprobe * flat.cap_max
        if probe_rows < self.k:
            raise ValueError(
                f"{self.name}: nprobe={self.nprobe} reaches at most "
                f"{probe_rows} candidates < k={self.k} after the "
                f"latest repack; raise nprobe")
        fixed = tuple(jnp.asarray(a) for a in (
            flat.centroids, flat.packed_db, flat.packed_ids,
            flat.starts, flat.sizes, snap.tomb_words))
        statics: Dict[str, object] = {
            "cap_max": int(flat.cap_max),
            "metric": flat.metric,
            "use_radix": bool(_use_radix(probe_rows, self.k,
                                         flat.packed_db)),
        }
        epoch, cur_fixed, cur_statics = self.serving()
        same = (cur_statics == statics
                and len(cur_fixed) == len(fixed)
                and all(a.shape == b.shape and a.dtype == b.dtype
                        for a, b in zip(cur_fixed, fixed)))
        return (epoch + (0 if same else 1), fixed, statics), snap.version

    def publish(self, pending: Tuple, version: int) -> bool:
        """Install a prepared serving tuple (single writer — the
        controller's serve lock). One attribute store: concurrent
        dispatches see either the old snapshot or the new one, never a
        torn pair. Returns True when the epoch advanced (shapes
        changed)."""
        changed = pending[0] != self.serve_epoch
        self._serving = pending
        self._version = int(version)
        return changed

    def refresh(self) -> bool:
        """Immediate prepare+publish (no pre-warm): the next launch at
        a bumped epoch compiles inline. Returns True when the epoch
        advanced."""
        p = self.prepare()
        if p is None:
            return False
        return self.publish(*p)

    # -- Service surface ----------------------------------------------

    def _build_for(self, serving: Tuple):
        from raft_tpu.neighbors.ivf_flat import _search_body

        k, nprobe = self.k, self.nprobe
        st = serving[2]
        cap_max, metric = st["cap_max"], st["metric"]
        use_radix = st["use_radix"]

        def fn(centroids, packed_db, packed_ids, starts, sizes,
               tomb_words, q):
            return _search_body(q, centroids, packed_db, packed_ids,
                                starts, sizes, tomb_words, k=k,
                                nprobe=nprobe, cap_max=cap_max,
                                metric=metric, use_radix=use_radix)
        return fn

    def unpack(self, out, start, rows):
        d, i = out
        return d[start:start + rows], i[start:start + rows]

    def estimate_bytes(self, rows):
        _, fixed, st = self.serving()
        return limits.estimate_bytes(
            "neighbors.ivf_search", n_queries=rows,
            probe_rows=self.nprobe * st["cap_max"],
            n_dims=self.dim, k=self.k, itemsize=self.dtype.itemsize,
            packed_rows=int(fixed[1].shape[0]))

    def eager(self, queries):
        return self.stream.search(jnp.asarray(queries), self.k,
                                  self.nprobe)

    def epilogue(self) -> str:
        """"ivf" — quoted from :func:`knn_plan` like the static kNN
        services, so the warm report shares their source of truth."""
        from raft_tpu.neighbors.brute_force import knn_plan

        flat = self.stream.flat
        path, _ = knn_plan(1, flat.n_db, self.k, metric=flat.metric,
                           n_lists=flat.n_lists, nprobe=self.nprobe)
        return path


class IngestController:
    """The serving trio — :class:`StreamingIndex`, :class:`Executor`,
    :class:`Compactor` — wired so every index mutation lands on the
    serve path as an atomic, pre-warmed snapshot swap.

    Foreground :meth:`insert`/:meth:`delete` mutate the stream then
    re-snapshot the streaming services inline (a shape-changing
    overflow repack pays its re-warm on the INGEST call, never on a
    query). Background compaction and refit arrive through the
    compactor's ``on_change`` hook on the worker thread. Both routes
    serialize on one serve lock, and shape-changing swaps warm the new
    epoch's executables across the whole bucket ladder before
    publishing — the zero-pause half of the ISSUE-17 contract, gated
    by loadgen's recall floor across swaps."""

    def __init__(self, stream: StreamingIndex,
                 services: Sequence[StreamingKnnService], *,
                 queue=None, policy=None, qos=None, use_aot: bool = True,
                 brownout=None, faults=None,
                 compact_interval: Optional[float] = None,
                 tombstone_frac: Optional[float] = None,
                 refit: bool = True,
                 warm_buckets: Optional[Sequence[int]] = None,
                 extra_services: Sequence[Service] = (),
                 shipper=None, election=None):
        self.stream = stream
        self.streaming_services: List[StreamingKnnService] = \
            list(services)
        for svc in self.streaming_services:
            if svc.stream is not stream:
                raise ValueError(
                    f"service {svc.name} wraps a different "
                    f"StreamingIndex than this controller's")
        self.executor = Executor(
            [*self.streaming_services, *extra_services], queue=queue,
            policy=policy, qos=qos, use_aot=use_aot, brownout=brownout,
            faults=faults)
        self.compactor = Compactor(
            stream, interval=compact_interval,
            tombstone_frac=tombstone_frac, refit=refit,
            on_change=self._on_index_change)
        # WAL shipping (ISSUE 18): a wal_ship.WalShipper replicating
        # this stream's journal to follower replicas — attached/started
        # with the controller so records ship for exactly the window
        # mutations can arrive through this surface
        self.shipper = shipper
        if shipper is not None and shipper.index is not stream:
            raise ValueError(
                "shipper replicates a different StreamingIndex than "
                "this controller's")
        # Leader failover (ISSUE 20): an election.ElectionNode makes
        # the controller leader-aware — mutations on a follower raise
        # the typed NotLeaderError redirect, and role changes roll the
        # serving snapshot forward on the node's worker thread. The
        # election node owns the shipper while leading, so the two
        # wirings are mutually exclusive.
        self.election = election
        if election is not None:
            if shipper is not None:
                raise ValueError(
                    "pass shipper= OR election= — the election node "
                    "owns the WAL shipper across role changes")
            if election.index is not stream:
                raise ValueError(
                    "election node coordinates a different "
                    "StreamingIndex than this controller's")
            self._wire_election(election)
        self._serve_lock = threading.Lock()
        self._warm_buckets = (list(warm_buckets)
                              if warm_buckets is not None else None)
        self.refreshes = 0   # snapshot publishes (any swap)
        self.swaps = 0       # epoch-bumped publishes (shape changed)

    def _buckets(self) -> Sequence[int]:
        if self._warm_buckets is not None:
            return self._warm_buckets
        return bucket_ladder(self.executor.queue.policy.max_batch)

    # -- lifecycle ----------------------------------------------------

    def start(self, *, warm: bool = True) -> "IngestController":
        if warm:
            self.executor.warm(self._buckets())
        if self.shipper is not None:
            self.shipper.attach()
            self.shipper.start()
        if self.election is not None:
            self.election.start()
        self.executor.start()
        self.compactor.start()
        return self

    def stop(self) -> None:
        """Compactor first (no swap may land while the executor
        drains), then the executor, then the shipper (every record the
        compactor/executor window produced is already shipped — the
        hook fires synchronously on append); worker failures re-raise
        here, after the drain."""
        try:
            self.compactor.stop()
        finally:
            try:
                self.executor.stop()
            finally:
                try:
                    if self.shipper is not None:
                        try:
                            self.shipper.stop()
                        finally:
                            self.shipper.detach()
                finally:
                    if self.election is not None:
                        self.election.stop()

    def __enter__(self) -> "IngestController":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- leader awareness (ISSUE 20) ----------------------------------

    def is_leader(self) -> bool:
        """True when this controller accepts writes (no election wired
        = the single-node regime, always the leader)."""
        return self.election is None or self.election.is_leader()

    @property
    def leader(self) -> Optional[int]:
        """The fleet's current leader rank (None without an election)."""
        return None if self.election is None else self.election.leader

    def _require_leader(self) -> None:
        el = self.election
        if el is not None and not el.is_leader():
            if obs.enabled():
                obs.inc("serve_not_leader_rejects_total")
            raise NotLeaderError(leader=el.leader, rank=el.rank)

    def _wire_election(self, election) -> None:
        """Chain the controller into the node's role-change hooks: a
        role switch rolls the serving snapshot forward on the worker
        thread. Promotion is content-neutral (the KIND_TERM record
        moves no rows), so the publish never changes operand shapes —
        the warmed executables survive and the query path sees ZERO
        recompiles; a demotion's snapshot resync MAY change shapes and
        pays its rewarm here, off the query path, like any ingest."""
        def chain(prev):
            def hook(node):
                self._on_index_change()
                obs.emit_event("serve.ingest_role_change",
                               role=node.role, term=node.index.term,
                               leader=node.leader)
                if prev is not None:
                    prev(node)
            return hook
        election.on_promote = chain(election.on_promote)
        election.on_repoint = chain(election.on_repoint)
        election.on_demote = chain(election.on_demote)

    # -- ingest surface -----------------------------------------------

    def insert(self, rows, labels: Optional[np.ndarray] = None, *,
               write_id: Optional[int] = None) -> np.ndarray:
        """Journal + apply an insert, then roll the serving snapshot
        forward. Returns the assigned external ids. On a follower
        replica raises the typed :class:`NotLeaderError` redirect;
        pass ``write_id`` so an in-flight batch replayed at the new
        leader after failover lands exactly once (seq-dedup)."""
        self._require_leader()
        ids = self.stream.insert(rows, labels, write_id=write_id)
        self._on_index_change()
        return ids

    def delete(self, ids) -> int:
        """Tombstone ids, then roll the serving snapshot forward —
        always same-shape (the per-epoch fixed bitset), so the publish
        is immediate and the warmed executables survive. Deletes are
        naturally idempotent, so the failover replay needs no
        write_id. Raises :class:`NotLeaderError` on a follower."""
        self._require_leader()
        n = self.stream.delete(ids)
        self._on_index_change()
        return n

    def compact(self, *, reason: str = "manual") -> None:
        """Foreground compaction cycle (the background worker's
        :meth:`Compactor.run_once` does the same off-thread)."""
        self.stream.compact(reason=reason)
        self._on_index_change()

    def submit(self, op: str, queries, **kw):
        return self.executor.submit(op, queries, **kw)

    # -- snapshot roll-forward ----------------------------------------

    def _on_index_change(self) -> None:
        """Re-snapshot every streaming service; pre-warm before
        publishing when shapes changed. Runs on whichever thread
        mutated the index (ingest caller or compactor worker) — the
        serve lock serializes the two, and queries never block on it
        (dispatch only reads the published tuple)."""
        with self._serve_lock:
            for svc in self.streaming_services:
                p = svc.prepare()
                if p is None:
                    continue
                pending, version = p
                bumped = pending[0] != svc.serve_epoch
                if bumped:
                    t0 = time.monotonic()
                    buckets = list(self._buckets())
                    for b in buckets:
                        exe = self.executor._get_executable(
                            svc, b, pending)
                        out = exe(*pending[1], svc.example(b))
                        jax.block_until_ready(out)
                    obs.emit_event(
                        "serve.ingest_rewarm", service=svc.name,
                        epoch=pending[0], buckets=buckets,
                        seconds=round(time.monotonic() - t0, 4))
                svc.publish(pending, version)
                self.refreshes += 1
                if bumped:
                    self.swaps += 1
                    obs.inc("serve_streaming_swaps_total", 1,
                            service=svc.name)
