"""raft_tpu.serve — shape-bucketed dynamic batching with multi-tenant
QoS on top of the PR 3–5 robustness stack.

The serving runtime coalesces many small per-user query blocks into the
large padded batches the accelerator is fast at, while keeping the
per-request contract: bit-identical results, typed errors
(``RejectedError`` backpressure, ``DeadlineExceededError`` expiry),
and weighted-fair scheduling across tenants.

Quickstart::

    from raft_tpu import serve

    ex = serve.Executor(
        [serve.KnnService(db, k=10)],
        policy=serve.BatchPolicy(max_batch=256, max_wait_ms=5.0),
        qos=serve.QosPolicy({"gold": serve.TenantPolicy(
            weight=4.0, slo_latency_s=0.05)}),   # 99% under 50 ms
    )
    ex.warm()                       # zero compiles after this
    with ex:                        # start/stop the drain thread
        fut = ex.submit("knn_k10_l2", queries, tenant="gold",
                        deadline_s=0.1)
        dist, idx = fut.result(timeout=1.0)
"""

from raft_tpu.serve.brownout import (BrownoutController,
                                     BrownoutFloorError,
                                     DegradationLadder, ivf_ladder,
                                     knn_ladder)
from raft_tpu.serve.executor import (Executor, ExecutorStats,
                                     IvfKnnService, IvfMnmgKnnService,
                                     IvfPqKnnService, KnnService,
                                     KMeansPredictService,
                                     PairwiseService, Service)
from raft_tpu.serve.ingest import IngestController, StreamingKnnService
from raft_tpu.serve.loadgen import (CatchupLoadReport, ChaosReport,
                                    FleetReport, LoadReport,
                                    StreamingReport,
                                    catchup_under_load, closed_loop,
                                    fleet_closed_loop, open_loop,
                                    run_chaos, streaming_loop)
from raft_tpu.serve.qos import QosPolicy, TenantPolicy
from raft_tpu.serve.replica import (HedgePolicy, RecoveryReport,
                                    Replica, ReplicaGroup,
                                    ReplicaGroupStats)
from raft_tpu.serve.queue import (BUCKET_FLOOR, Batch, BatchPolicy,
                                  Request, RequestQueue, ResultFuture,
                                  bucket_ladder, bucket_rows)

__all__ = [
    "BUCKET_FLOOR", "bucket_rows", "bucket_ladder",
    "Request", "ResultFuture", "Batch", "BatchPolicy", "RequestQueue",
    "TenantPolicy", "QosPolicy",
    "Service", "KnnService", "IvfKnnService", "IvfPqKnnService",
    "IvfMnmgKnnService", "PairwiseService", "KMeansPredictService",
    "Executor", "ExecutorStats",
    "Replica", "ReplicaGroup", "ReplicaGroupStats", "RecoveryReport",
    "HedgePolicy",
    "BrownoutController", "BrownoutFloorError", "DegradationLadder",
    "ivf_ladder", "knn_ladder",
    "StreamingKnnService", "IngestController",
    "LoadReport", "FleetReport", "ChaosReport", "StreamingReport",
    "CatchupLoadReport",
    "closed_loop", "open_loop", "fleet_closed_loop", "streaming_loop",
    "catchup_under_load", "run_chaos",
]
