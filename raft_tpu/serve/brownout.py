"""Adaptive quality brownout (ISSUE 16 tentpole, control half).

Under overload the serve tier used to be binary: full quality or a
typed refusal. This module adds the middle ground production ANN
systems actually live in — *degrade quality before availability*:

- a :class:`DegradationLadder` per logical op: ordered quality levels
  (level 0 = full quality), each a distinct
  :class:`~raft_tpu.serve.executor.Service` instance (IVF nprobe
  32→16→8→4, brute-force k-cap, …). Every level registers with the
  executor and pre-warms through the normal bucket ladder, so STEPPING
  DOWN NEVER COMPILES — the zero-recompile contract the serve tier is
  CI-gated on extends to brownout transitions (asserted via the
  executor's retrace counter in ci/smoke.sh).
- a :class:`BrownoutController` running classic hysteresis over the
  PR-10 signals: engage (step down one level) when a tenant's SLO
  burn rate exceeds ``engage_burn`` (>1 = error budget burning faster
  than the objective tolerates) OR the queue is past ``queue_high`` of
  capacity; recover (step back up) only after ``clean_windows``
  consecutive clean windows of ``window_s`` — asymmetry is the point:
  react in one tick, relax slowly enough not to oscillate.
- a per-tenant contract floor: ``qos.TenantPolicy.min_quality`` caps
  how deep the controller may degrade that tenant (0 pins full
  quality). The executor re-checks the floor at finish; a served
  response below it is a :class:`BrownoutFloorError` flight-recorder
  bundle, not a silent quality leak.

Every resolved level is observable: gauge
``serve_brownout_level{service,tenant}``, a ``serve.brownout_step``
event per transition, the ``level`` stamped on each request's span,
and a per-level histogram in :class:`ExecutorStats`/loadgen reports.

Kill switch: ``RAFT_TPU_BROWNOUT=off`` pins every resolve to level 0
(the controller still ticks its signals, so flipping it back on
engages immediately).
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

from raft_tpu import obs
from raft_tpu.core import env as _env_mod

__all__ = [
    "BrownoutFloorError", "DegradationLadder", "BrownoutController",
    "ivf_ladder", "knn_ladder",
]


class BrownoutFloorError(RuntimeError):
    """A response was served BELOW the tenant's ``min_quality`` floor —
    a contract violation (controller bug), flight-recorded, never
    expected in a healthy tree."""

    def __init__(self, msg: str, *, op: str, tenant: str, level: int,
                 floor: int):
        super().__init__(msg)
        self.op = op
        self.tenant = tenant
        self.level = level
        self.floor = floor


class DegradationLadder:
    """Ordered quality levels for one logical serve op.

    ``services[0]`` is full quality and its name is the op clients
    submit; deeper indices are progressively cheaper. Cheapness is
    validated, not assumed: each level's ``estimate_bytes`` at a
    reference bucket must be <= its predecessor's — a ladder that gets
    more expensive as it "degrades" is a configuration bug caught at
    construction."""

    def __init__(self, services: Sequence, *, check_rows: int = 64):
        services = list(services)
        if not services:
            raise ValueError("a ladder needs at least one level")
        dims = {s.dim for s in services}
        if len(dims) != 1:
            raise ValueError(
                f"ladder levels disagree on query dim: {sorted(dims)}")
        for lo, hi in zip(services[1:], services[:-1]):
            if lo.estimate_bytes(check_rows) > hi.estimate_bytes(
                    check_rows):
                raise ValueError(
                    f"ladder not monotone: level {lo.name!r} costs more "
                    f"than its predecessor {hi.name!r} "
                    f"({lo.estimate_bytes(check_rows)} > "
                    f"{hi.estimate_bytes(check_rows)} bytes at "
                    f"{check_rows} rows)")
        self.services = services
        self.op = services[0].name

    @property
    def depth(self) -> int:
        """Number of levels (max level index is ``depth - 1``)."""
        return len(self.services)

    def service(self, level: int):
        return self.services[min(max(int(level), 0),
                                 len(self.services) - 1)]

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return (f"DegradationLadder({self.op!r}, "
                f"levels={[s.name for s in self.services]})")


def ivf_ladder(index, k: int,
               nprobes: Sequence[int] = (32, 16, 8, 4)
               ) -> DegradationLadder:
    """The canonical IVF brownout ladder: one
    :class:`~raft_tpu.serve.executor.IvfKnnService` per nprobe,
    descending — fewer probed lists, cheaper search, lower recall.
    nprobes above ``n_lists - 1`` are clamped out (a full scan is not a
    quality LEVEL)."""
    from raft_tpu.serve.executor import IvfKnnService

    nps = [int(np_) for np_ in nprobes if 0 < int(np_) < index.n_lists]
    if sorted(set(nps), reverse=True) != nps:
        raise ValueError(
            f"nprobes must be strictly descending, got {list(nprobes)}")
    if not nps:
        raise ValueError(
            f"no valid nprobe below n_lists={index.n_lists} in "
            f"{list(nprobes)}")
    return DegradationLadder(
        [IvfKnnService(index, k=k, nprobe=np_) for np_ in nps])


def knn_ladder(db, ks: Sequence[int],
               metric: str = "l2") -> DegradationLadder:
    """Brute-force k-cap ladder: same database, descending k — a
    degraded response returns FEWER neighbors (``[rows, k_level]``),
    which callers observe via the stamped level."""
    from raft_tpu.serve.executor import KnnService

    ks = [int(k) for k in ks]
    if sorted(set(ks), reverse=True) != ks:
        raise ValueError(f"ks must be strictly descending, got {ks}")
    return DegradationLadder(
        [KnnService(db, k=k, metric=metric) for k in ks])


class _TenantState:
    """Hysteresis state for one (op, tenant) key (controller-internal,
    mutated only under the controller lock)."""

    __slots__ = ("level", "last_step", "clean_since")

    def __init__(self):
        self.level = 0
        self.last_step = 0.0                 # monotonic of last change
        self.clean_since: Optional[float] = None


class BrownoutController:
    """Hysteresis over (SLO burn rate, queue depth) driving per-
    (op, tenant) ladder levels.

    engage_burn
        step DOWN when a tenant's windowed burn rate exceeds this
        (1.0 = the PR-10 "error budget burning too fast" threshold).
    queue_high
        ... or when queue depth exceeds this fraction of ``max_queue``
        (queue pressure leads the burn signal — it spikes before
        latencies have even been recorded).
    step_interval_s
        at most one step down per key per this interval: the control
        loop must outrun the spike, not chase its own latency.
    window_s / clean_windows
        step UP one level only after ``clean_windows`` consecutive
        windows of ``window_s`` with both signals clean — and the clean
        count restarts after each up-step, so recovery walks the ladder
        gently instead of snapping to full quality and re-browning.
    """

    def __init__(self, ladders: Sequence[DegradationLadder], *,
                 qos=None, engage_burn: float = 1.0,
                 queue_high: float = 0.8, step_interval_s: float = 0.25,
                 window_s: float = 1.0, clean_windows: int = 3,
                 enabled: Optional[bool] = None):
        ladders = list(ladders)
        self.ladders: Dict[str, DegradationLadder] = {
            lad.op: lad for lad in ladders}
        if len(self.ladders) != len(ladders):
            raise ValueError("duplicate ladder op")
        self.qos = qos
        self.engage_burn = float(engage_burn)
        self.queue_high = float(queue_high)
        self.step_interval_s = float(step_interval_s)
        self.window_s = float(window_s)
        self.clean_windows = int(clean_windows)
        if enabled is None:
            enabled = bool(_env_mod.read("RAFT_TPU_BROWNOUT"))
        self.enabled = enabled
        self._lock = threading.Lock()
        self._state: Dict[Tuple[str, str], _TenantState] = {}
        self._last_tick = 0.0

    # -- resolution (executor submit path) ----------------------------

    def max_level(self, op: str, tenant: str) -> int:
        """Deepest level this tenant may be served at for ``op``: the
        ladder depth capped by the tenant's ``min_quality`` floor."""
        ladder = self.ladders[op]
        cap = ladder.depth - 1
        if self.qos is not None:
            floor = self.qos.policy(tenant).min_quality
            if floor is not None:
                cap = min(cap, int(floor))
        return cap

    def resolve(self, op: str, tenant: str) -> Tuple[str, int]:
        """Map a client-requested op to (service op to run, level) for
        this tenant, under the current controller state. Unknown ops
        (no ladder) pass through at level 0."""
        ladder = self.ladders.get(op)
        if ladder is None or not self.enabled:
            return op, 0
        with self._lock:
            st = self._state.get((op, tenant))
            level = st.level if st is not None else 0
        level = min(level, self.max_level(op, tenant))
        return ladder.service(level).name, level

    def level(self, op: str, tenant: str) -> int:
        with self._lock:
            st = self._state.get((op, tenant))
            return st.level if st is not None else 0

    # -- control loop --------------------------------------------------

    def maybe_tick(self, executor) -> None:
        """Rate-limited tick driven from the executor drain loop: reads
        queue fraction and the per-tenant burn rates, then runs the
        hysteresis step. Cheap enough to call per batch."""
        now = time.monotonic()
        with self._lock:
            if now - self._last_tick < self.step_interval_s / 2:
                return
            self._last_tick = now
        qfrac = (executor.queue.pending()
                 / executor.queue.policy.max_queue)
        burn = {}
        if self.qos is not None:
            for tenant, row in self.qos.slo_snapshot().items():
                burn[tenant] = row["burn_rate"]
        self.tick(queue_frac=qfrac, burn_by_tenant=burn, now=now)

    def tick(self, *, queue_frac: float,
             burn_by_tenant: Dict[str, float],
             now: Optional[float] = None) -> None:
        """One hysteresis step over every (op, tenant) key. Exposed
        with an injectable clock so tests drive it deterministically."""
        if now is None:
            now = time.monotonic()
        queue_hot = queue_frac > self.queue_high
        with self._lock:
            # keys to evaluate: every tenant with a burn signal, plus
            # every key already degraded (it must keep being evaluated
            # even after its tenant goes quiet, or it never recovers)
            keys = {(op, t) for op in self.ladders
                    for t in burn_by_tenant}
            keys.update(k for k, st in self._state.items()
                        if st.level > 0)
            for key in keys:
                op, tenant = key
                hot = queue_hot or (burn_by_tenant.get(tenant, 0.0)
                                    > self.engage_burn)
                st = self._state.get(key)
                if st is None:
                    if not hot:
                        continue
                    st = self._state[key] = _TenantState()
                if hot:
                    st.clean_since = None
                    cap = self.max_level(op, tenant)
                    if (st.level < cap
                            and now - st.last_step
                            >= self.step_interval_s):
                        self._step(st, key, st.level + 1, now,
                                   reason="hot")
                else:
                    if st.clean_since is None:
                        st.clean_since = now
                    elif (st.level > 0
                          and now - st.clean_since
                          >= self.clean_windows * self.window_s):
                        # one step up per clean streak; restart the
                        # streak so the next up-step earns itself too
                        self._step(st, key, st.level - 1, now,
                                   reason="clean")
                        st.clean_since = now

    def _step(self, st: _TenantState, key: Tuple[str, str],
              level: int, now: float, *, reason: str) -> None:
        # under self._lock; obs is itself thread-safe
        prev, st.level, st.last_step = st.level, level, now
        op, tenant = key
        obs.set_gauge("serve_brownout_level", level, service=op,
                      tenant=tenant,
                      help="current degradation-ladder level served "
                           "(0 = full quality)")
        obs.emit_event("serve.brownout_step", service=op, tenant=tenant,
                       level=level, prev=prev, reason=reason)

    # -- reporting -----------------------------------------------------

    def snapshot(self) -> Dict[str, Dict[str, int]]:
        """Current levels, ``{op: {tenant: level}}`` — only non-zero
        entries (loadgen report surfacing)."""
        out: Dict[str, Dict[str, int]] = {}
        with self._lock:
            for (op, tenant), st in self._state.items():
                if st.level > 0:
                    out.setdefault(op, {})[tenant] = st.level
        return out
