"""Load generator for the serving runtime (serving tentpole, part 4).

Two standard modes, both against a live :class:`~raft_tpu.serve.Executor`:

closed loop
    N client threads, each submit → wait → submit. Offered load tracks
    service rate automatically, so the measured queries/sec IS the
    saturation throughput for that concurrency; latency is the classic
    closed-loop response time.
open loop
    requests arrive on a fixed schedule (Poisson or uniform) regardless
    of completions — the arrival process real traffic has. Latency
    percentiles under open loop expose queueing delay that closed loop
    hides (coordinated omission).

Both report p50/p99 latency, achieved queries/sec and rows/sec, the
executor's coalescing factor (real rows per device launch), and the
typed-error counts (rejections, deadline expiries) — the numbers the
acceptance bench (``bench.py --serve``) emits to ``BENCH_r06.json``.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

import numpy as np

from raft_tpu.runtime import limits

__all__ = ["LoadReport", "FleetReport", "closed_loop", "open_loop",
           "fleet_closed_loop"]


@dataclass
class LoadReport:
    """One load-generation run, summarized."""

    mode: str
    duration_s: float
    completed: int = 0
    rejected: int = 0                   # typed RejectedError
    deadline_failed: int = 0            # typed DeadlineExceededError
    rows: int = 0
    latencies_ms: List[float] = field(default_factory=list)
    coalescing_factor: float = 0.0
    batches: int = 0
    pad_overhead: float = 0.0           # padded rows / real rows
    select_k_bytes_per_s: float = 0.0   # radix-epilogue selection bandwidth
    slo: Dict[str, dict] = field(default_factory=dict)  # tenant -> SLO state
    obs_snapshot: Optional[Dict[str, object]] = None    # when metrics on

    @property
    def qps(self) -> float:
        return self.completed / self.duration_s if self.duration_s else 0.0

    @property
    def rows_per_s(self) -> float:
        return self.rows / self.duration_s if self.duration_s else 0.0

    def percentile_ms(self, q: float) -> float:
        if not self.latencies_ms:
            return float("nan")
        return float(np.percentile(np.asarray(self.latencies_ms), q))

    @property
    def p50_ms(self) -> float:
        return self.percentile_ms(50.0)

    @property
    def p99_ms(self) -> float:
        return self.percentile_ms(99.0)

    def as_dict(self) -> Dict[str, object]:
        out = {
            "mode": self.mode,
            "duration_s": round(self.duration_s, 3),
            "completed": self.completed,
            "rejected": self.rejected,
            "deadline_failed": self.deadline_failed,
            "rows": self.rows,
            "qps": round(self.qps, 2),
            "rows_per_s": round(self.rows_per_s, 1),
            "p50_ms": round(self.p50_ms, 3),
            "p99_ms": round(self.p99_ms, 3),
            "coalescing_factor": round(self.coalescing_factor, 3),
            "batches": self.batches,
            "pad_overhead": round(self.pad_overhead, 4),
            "select_k_bytes_per_s": round(self.select_k_bytes_per_s, 1),
        }
        if self.slo:
            out["slo"] = self.slo
        if self.obs_snapshot is not None:
            # parity with the bench.py north-star line: serving
            # artifacts carry the counter families that explain their
            # latency numbers
            out["obs"] = self.obs_snapshot
        return out


def _snapshot(executor) -> tuple:
    s = executor.stats
    return (s.batches, s.rows, s.padded_rows)


def _finalize(report: LoadReport, executor, before: tuple,
              t0: float) -> LoadReport:
    report.duration_s = time.monotonic() - t0
    b0, r0, p0 = before
    s = executor.stats
    db, dr, dp = s.batches - b0, s.rows - r0, s.padded_rows - p0
    report.batches = db
    report.coalescing_factor = dr / db if db else 0.0
    report.pad_overhead = dp / dr if dr else 0.0
    # selection-stage bandwidth: the Executor._launch gauge for kNN
    # services on the radix epilogue (last-observed value per service;
    # report the peak across services — stays 0.0 with metrics off)
    from raft_tpu import obs

    fam = obs.snapshot()["metrics"].get("select_k_bytes_per_s")
    if fam and fam.get("series"):
        report.select_k_bytes_per_s = max(
            float(s["value"]) for s in fam["series"])
    # per-tenant SLO state (ISSUE 10): burn rate + window counts from
    # the executor's QosPolicy, when one is wired and metering
    qos = getattr(executor, "qos", None)
    if qos is not None and hasattr(qos, "slo_snapshot"):
        report.slo = qos.slo_snapshot()
    if obs.enabled():
        report.obs_snapshot = obs.snapshot()
    return report


def _record(report: LoadReport, lock: threading.Lock, rows: int,
            t_submit: float, future, wait_s: float) -> None:
    """Wait one future out and fold the outcome into the report."""
    try:
        future.result(timeout=wait_s)
        ok, kind = True, None
    except limits.RejectedError:
        ok, kind = False, "rejected"
    except limits.DeadlineExceededError:
        ok, kind = False, "deadline"
    except TimeoutError:
        ok, kind = False, None
    lat_ms = (time.monotonic() - t_submit) * 1e3
    with lock:
        if ok:
            report.completed += 1
            report.rows += rows
            report.latencies_ms.append(lat_ms)
        elif kind == "rejected":
            report.rejected += 1
        elif kind == "deadline":
            report.deadline_failed += 1


def closed_loop(executor, op: str, *, clients: int = 8,
                rows: int = 4, duration_s: float = 2.0,
                tenants: Optional[Sequence[str]] = None,
                deadline_s: Optional[float] = None,
                seed: int = 0, wait_s: float = 30.0) -> LoadReport:
    """``clients`` threads in a submit→wait loop for ``duration_s``.
    Tenant ``i`` is ``tenants[i % len(tenants)]`` (default: one shared
    tenant), so a skewed tenant list doubles as a fairness workload."""
    svc = executor._service(op)
    tenants = list(tenants) if tenants else ["default"]
    report = LoadReport(mode="closed", duration_s=0.0)
    lock = threading.Lock()
    stop = threading.Event()
    before = _snapshot(executor)
    t0 = time.monotonic()

    def client(i: int) -> None:
        rng = np.random.default_rng(seed + i)
        tenant = tenants[i % len(tenants)]
        while not stop.is_set():
            q = rng.standard_normal((rows, svc.dim)).astype(svc.dtype)
            t_submit = time.monotonic()
            try:
                fut = executor.submit(op, q, tenant=tenant,
                                      deadline_s=deadline_s)
            except limits.RejectedError:
                with lock:
                    report.rejected += 1
                time.sleep(0.001)       # brief backoff, stay closed-loop
                continue
            _record(report, lock, rows, t_submit, fut, wait_s)

    threads = [threading.Thread(target=client, args=(i,), daemon=True)
               for i in range(clients)]
    for t in threads:
        t.start()
    time.sleep(duration_s)
    stop.set()
    for t in threads:
        t.join(timeout=wait_s)
    return _finalize(report, executor, before, t0)


@dataclass
class FleetReport:
    """One replica-fleet load run: per-replica rows plus the merged
    fleet row (ISSUE 11 loadgen satellite)."""

    per_replica: Dict[str, LoadReport] = field(default_factory=dict)
    fleet: Optional[LoadReport] = None
    routed: int = 0                     # router counters for the run
    spills: int = 0
    router_rejected: int = 0
    killed: Optional[str] = None        # replica killed mid-run, if any
    kill_at_s: Optional[float] = None   # offset from run start
    # seconds from the kill to the first subsequent completion meeting
    # the tenant's SLO latency (any completion when no SLO is set);
    # None when nothing was killed, +inf when nothing recovered
    recovery_time_to_slo_s: Optional[float] = None

    def as_dict(self) -> Dict[str, object]:
        out: Dict[str, object] = {
            "mode": "fleet_closed",
            "replicas": {name: r.as_dict()
                         for name, r in self.per_replica.items()},
            "fleet": self.fleet.as_dict() if self.fleet else None,
            "routed": self.routed,
            "spills": self.spills,
            "router_rejected": self.router_rejected,
        }
        if self.killed is not None:
            out["killed"] = self.killed
            out["kill_at_s"] = round(self.kill_at_s, 3)
            out["recovery_time_to_slo_s"] = (
                round(self.recovery_time_to_slo_s, 4)
                if self.recovery_time_to_slo_s is not None else None)
        return out


def _slo_latency_s(group, tenant: str) -> Optional[float]:
    """The tenant's SLO latency from the first replica carrying a QoS
    policy (replicas share one policy table by construction)."""
    for r in group.replicas:
        qos = getattr(r.executor, "qos", None)
        if qos is not None:
            # a policy table without SLO fields means "no SLO configured"
            try:
                return qos.policy(tenant).slo_latency_s
            except (AttributeError, KeyError):
                return None
    return None


def fleet_closed_loop(group, op: str, *, clients: int = 8,
                      rows: int = 4, duration_s: float = 2.0,
                      tenants: Optional[Sequence[str]] = None,
                      deadline_s: Optional[float] = None,
                      seed: int = 0, wait_s: float = 30.0,
                      kill_after_s: Optional[float] = None,
                      kill=None) -> FleetReport:
    """Closed-loop load against a :class:`~raft_tpu.serve.ReplicaGroup`.

    Routes every submit through the group's weighted-fair router and
    attributes each completion to the replica that served it, so the
    report carries one p50/p99/qps row per replica plus the merged
    fleet row. With ``kill_after_s`` set, a killer thread fires ``kill``
    (default: :meth:`ReplicaGroup.fail_replica` on the last healthy
    replica) mid-run and the report's ``recovery_time_to_slo_s`` is the
    time from the kill to the first subsequent completion meeting the
    tenant's SLO latency — the serving-side recovery witness the chaos
    gate asserts on."""
    tenants = list(tenants) if tenants else ["default"]
    svc = None
    for r in group.healthy():
        try:
            svc = r.executor._service(op)
            break
        except KeyError:
            continue
    if svc is None:
        raise KeyError(f"no healthy replica serves op {op!r}")
    slo_s = _slo_latency_s(group, tenants[0])

    fleet = FleetReport()
    per_rep: Dict[str, LoadReport] = {
        r.name: LoadReport(mode="fleet_closed", duration_s=0.0)
        for r in group.replicas}
    merged = LoadReport(mode="fleet_closed", duration_s=0.0)
    lock = threading.Lock()
    stop = threading.Event()
    # (t_kill, recovery) shared with the record path
    kill_state: Dict[str, Optional[float]] = {"t_kill": None,
                                              "recovery": None}
    snaps = {r.name: (r, _snapshot(r.executor)) for r in group.replicas}
    routed0, spills0, rej0 = (group.stats.routed, group.stats.spills,
                              group.stats.rejected)
    t0 = time.monotonic()

    def record(rep_name: str, t_submit: float, fut) -> None:
        try:
            fut.result(timeout=wait_s)
            ok, kind = True, None
        except limits.RejectedError:
            ok, kind = False, "rejected"
        except limits.DeadlineExceededError:
            ok, kind = False, "deadline"
        except TimeoutError:
            ok, kind = False, None
        t_done = time.monotonic()
        lat_ms = (t_done - t_submit) * 1e3
        with lock:
            reports = [merged]
            if rep_name in per_rep:
                reports.append(per_rep[rep_name])
            for rep in reports:
                if ok:
                    rep.completed += 1
                    rep.rows += rows
                    rep.latencies_ms.append(lat_ms)
                elif kind == "rejected":
                    rep.rejected += 1
                elif kind == "deadline":
                    rep.deadline_failed += 1
            t_kill = kill_state["t_kill"]
            if (ok and t_kill is not None
                    and kill_state["recovery"] is None
                    and t_submit >= t_kill
                    and (slo_s is None or lat_ms * 1e-3 <= slo_s)):
                kill_state["recovery"] = t_done - t_kill

    def client(i: int) -> None:
        rng = np.random.default_rng(seed + i)
        tenant = tenants[i % len(tenants)]
        while not stop.is_set():
            q = rng.standard_normal((rows, svc.dim)).astype(svc.dtype)
            t_submit = time.monotonic()
            try:
                replica, fut = group.route(op, q, tenant=tenant,
                                           deadline_s=deadline_s)
            except limits.RejectedError:
                with lock:
                    merged.rejected += 1
                time.sleep(0.001)
                continue
            record(replica.name, t_submit, fut)

    def killer() -> None:
        if stop.wait(kill_after_s):
            return                      # run ended before the kill
        live = group.healthy()
        if not live:
            return
        target = live[-1]
        with lock:
            fleet.killed = target.name
            kill_state["t_kill"] = time.monotonic()
            fleet.kill_at_s = kill_state["t_kill"] - t0
        if kill is not None:
            kill(target)
        else:
            group.fail_replica(target, "loadgen kill")

    threads = [threading.Thread(target=client, args=(i,), daemon=True)
               for i in range(clients)]
    if kill_after_s is not None:
        threads.append(threading.Thread(target=killer, daemon=True))
    for t in threads:
        t.start()
    time.sleep(duration_s)
    stop.set()
    for t in threads:
        t.join(timeout=wait_s)

    for name, (replica, before) in snaps.items():
        _finalize(per_rep[name], replica.executor, before, t0)
    merged.duration_s = time.monotonic() - t0
    merged.batches = sum(r.batches for r in per_rep.values())
    tot_rows = sum(r.coalescing_factor * r.batches
                   for r in per_rep.values())
    merged.coalescing_factor = (tot_rows / merged.batches
                                if merged.batches else 0.0)
    merged.slo = (group.slo_snapshot()
                  if hasattr(group, "slo_snapshot") else {})
    fleet.per_replica = per_rep
    fleet.fleet = merged
    fleet.routed = group.stats.routed - routed0
    fleet.spills = group.stats.spills - spills0
    fleet.router_rejected = group.stats.rejected - rej0
    if fleet.killed is not None:
        fleet.recovery_time_to_slo_s = (
            kill_state["recovery"] if kill_state["recovery"] is not None
            else float("inf"))
    return fleet


def open_loop(executor, op: str, *, rate_qps: float = 200.0,
              rows: int = 4, duration_s: float = 2.0,
              tenants: Optional[Sequence[str]] = None,
              deadline_s: Optional[float] = None,
              poisson: bool = True, seed: int = 0,
              wait_s: float = 30.0) -> LoadReport:
    """Submit on a fixed arrival schedule (Poisson by default) without
    waiting for completions — each in-flight request is awaited by a
    collector thread, so measured latency includes queueing delay
    (no coordinated omission)."""
    svc = executor._service(op)
    tenants = list(tenants) if tenants else ["default"]
    rng = np.random.default_rng(seed)
    report = LoadReport(mode="open", duration_s=0.0)
    lock = threading.Lock()
    collectors: List[threading.Thread] = []
    before = _snapshot(executor)
    t0 = time.monotonic()
    end = t0 + duration_s
    next_at = t0
    i = 0
    while True:
        now = time.monotonic()
        if now >= end:
            break
        if now < next_at:
            time.sleep(min(next_at - now, 0.005))
            continue
        gap = (rng.exponential(1.0 / rate_qps) if poisson
               else 1.0 / rate_qps)
        next_at += gap
        q = rng.standard_normal((rows, svc.dim)).astype(svc.dtype)
        tenant = tenants[i % len(tenants)]
        i += 1
        t_submit = time.monotonic()
        try:
            fut = executor.submit(op, q, tenant=tenant,
                                  deadline_s=deadline_s)
        except limits.RejectedError:
            with lock:
                report.rejected += 1
            continue
        c = threading.Thread(
            target=_record,
            args=(report, lock, rows, t_submit, fut, wait_s),
            daemon=True)
        c.start()
        collectors.append(c)
    for c in collectors:
        c.join(timeout=wait_s)
    return _finalize(report, executor, before, t0)


def main(argv: Optional[Sequence[str]] = None) -> int:
    """``python -m raft_tpu.serve.loadgen`` — run the generator against
    a synthetic kNN fleet and print the report as JSON.

    ``--replicas N`` spins up N warmed replicas behind a
    :class:`~raft_tpu.serve.ReplicaGroup` and runs the fleet closed
    loop (per-replica rows + merged row); ``--kill-after S`` kills one
    replica mid-run and reports ``recovery_time_to_slo_s``."""
    import argparse
    import json

    p = argparse.ArgumentParser(prog="raft_tpu.serve.loadgen")
    p.add_argument("--replicas", type=int, default=1)
    p.add_argument("--clients", type=int, default=8)
    p.add_argument("--rows", type=int, default=4)
    p.add_argument("--duration", type=float, default=2.0)
    p.add_argument("--mode", choices=("closed", "open"),
                   default="closed")
    p.add_argument("--rate-qps", type=float, default=200.0)
    p.add_argument("--n-db", type=int, default=4096)
    p.add_argument("--dim", type=int, default=64)
    p.add_argument("--k", type=int, default=10)
    p.add_argument("--metric", default="l2")
    p.add_argument("--deadline", type=float, default=None)
    p.add_argument("--slo-ms", type=float, default=None,
                   help="default-tenant SLO latency (arms burn-rate "
                        "metering and the recovery-to-SLO clock)")
    p.add_argument("--kill-after", type=float, default=None,
                   help="kill one replica this many seconds into the "
                        "run (needs --replicas >= 2)")
    p.add_argument("--seed", type=int, default=0)
    args = p.parse_args(argv)
    if args.kill_after is not None and args.replicas < 2:
        p.error("--kill-after needs --replicas >= 2")

    from raft_tpu.serve import (BatchPolicy, Executor, KnnService,
                                QosPolicy, ReplicaGroup, TenantPolicy)
    from raft_tpu.serve.queue import bucket_ladder

    rng = np.random.default_rng(args.seed)
    db = rng.standard_normal((args.n_db, args.dim)).astype(np.float32)
    op = f"knn_k{args.k}_{args.metric}"

    def make_executor():
        qos = None
        if args.slo_ms is not None:
            qos = QosPolicy({"default": TenantPolicy(
                slo_latency_s=args.slo_ms * 1e-3)})
        ex = Executor([KnnService(db, k=args.k, metric=args.metric)],
                      policy=BatchPolicy(max_batch=256, max_wait_ms=2.0),
                      qos=qos)
        ex.warm(bucket_ladder(256))
        return ex

    common = dict(clients=args.clients, rows=args.rows,
                  duration_s=args.duration, deadline_s=args.deadline,
                  seed=args.seed)
    if args.replicas > 1:
        group = ReplicaGroup([make_executor()
                              for _ in range(args.replicas)])
        with group:
            report = fleet_closed_loop(group, op,
                                       kill_after_s=args.kill_after,
                                       **common)
    else:
        ex = make_executor()
        with ex:
            if args.mode == "open":
                common.pop("clients")
                report = open_loop(ex, op, rate_qps=args.rate_qps,
                                   **common)
            else:
                report = closed_loop(ex, op, **common)
    print(json.dumps(report.as_dict(), indent=2, sort_keys=True))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
