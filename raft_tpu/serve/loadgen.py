"""Load generator for the serving runtime (serving tentpole, part 4).

Two standard modes, both against a live :class:`~raft_tpu.serve.Executor`:

closed loop
    N client threads, each submit → wait → submit. Offered load tracks
    service rate automatically, so the measured queries/sec IS the
    saturation throughput for that concurrency; latency is the classic
    closed-loop response time.
open loop
    requests arrive on a fixed schedule (Poisson or uniform) regardless
    of completions — the arrival process real traffic has. Latency
    percentiles under open loop expose queueing delay that closed loop
    hides (coordinated omission).

Both report p50/p99 latency, achieved queries/sec and rows/sec, the
executor's coalescing factor (real rows per device launch), and the
typed-error counts (rejections, deadline expiries) — the numbers the
acceptance bench (``bench.py --serve``) emits to ``BENCH_r06.json``.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

import numpy as np

from raft_tpu.runtime import limits

__all__ = ["LoadReport", "closed_loop", "open_loop"]


@dataclass
class LoadReport:
    """One load-generation run, summarized."""

    mode: str
    duration_s: float
    completed: int = 0
    rejected: int = 0                   # typed RejectedError
    deadline_failed: int = 0            # typed DeadlineExceededError
    rows: int = 0
    latencies_ms: List[float] = field(default_factory=list)
    coalescing_factor: float = 0.0
    batches: int = 0
    pad_overhead: float = 0.0           # padded rows / real rows
    select_k_bytes_per_s: float = 0.0   # radix-epilogue selection bandwidth
    slo: Dict[str, dict] = field(default_factory=dict)  # tenant -> SLO state
    obs_snapshot: Optional[Dict[str, object]] = None    # when metrics on

    @property
    def qps(self) -> float:
        return self.completed / self.duration_s if self.duration_s else 0.0

    @property
    def rows_per_s(self) -> float:
        return self.rows / self.duration_s if self.duration_s else 0.0

    def percentile_ms(self, q: float) -> float:
        if not self.latencies_ms:
            return float("nan")
        return float(np.percentile(np.asarray(self.latencies_ms), q))

    @property
    def p50_ms(self) -> float:
        return self.percentile_ms(50.0)

    @property
    def p99_ms(self) -> float:
        return self.percentile_ms(99.0)

    def as_dict(self) -> Dict[str, object]:
        out = {
            "mode": self.mode,
            "duration_s": round(self.duration_s, 3),
            "completed": self.completed,
            "rejected": self.rejected,
            "deadline_failed": self.deadline_failed,
            "rows": self.rows,
            "qps": round(self.qps, 2),
            "rows_per_s": round(self.rows_per_s, 1),
            "p50_ms": round(self.p50_ms, 3),
            "p99_ms": round(self.p99_ms, 3),
            "coalescing_factor": round(self.coalescing_factor, 3),
            "batches": self.batches,
            "pad_overhead": round(self.pad_overhead, 4),
            "select_k_bytes_per_s": round(self.select_k_bytes_per_s, 1),
        }
        if self.slo:
            out["slo"] = self.slo
        if self.obs_snapshot is not None:
            # parity with the bench.py north-star line: serving
            # artifacts carry the counter families that explain their
            # latency numbers
            out["obs"] = self.obs_snapshot
        return out


def _snapshot(executor) -> tuple:
    s = executor.stats
    return (s.batches, s.rows, s.padded_rows)


def _finalize(report: LoadReport, executor, before: tuple,
              t0: float) -> LoadReport:
    report.duration_s = time.monotonic() - t0
    b0, r0, p0 = before
    s = executor.stats
    db, dr, dp = s.batches - b0, s.rows - r0, s.padded_rows - p0
    report.batches = db
    report.coalescing_factor = dr / db if db else 0.0
    report.pad_overhead = dp / dr if dr else 0.0
    # selection-stage bandwidth: the Executor._launch gauge for kNN
    # services on the radix epilogue (last-observed value per service;
    # report the peak across services — stays 0.0 with metrics off)
    from raft_tpu import obs

    fam = obs.snapshot()["metrics"].get("select_k_bytes_per_s")
    if fam and fam.get("series"):
        report.select_k_bytes_per_s = max(
            float(s["value"]) for s in fam["series"])
    # per-tenant SLO state (ISSUE 10): burn rate + window counts from
    # the executor's QosPolicy, when one is wired and metering
    qos = getattr(executor, "qos", None)
    if qos is not None and hasattr(qos, "slo_snapshot"):
        report.slo = qos.slo_snapshot()
    if obs.enabled():
        report.obs_snapshot = obs.snapshot()
    return report


def _record(report: LoadReport, lock: threading.Lock, rows: int,
            t_submit: float, future, wait_s: float) -> None:
    """Wait one future out and fold the outcome into the report."""
    try:
        future.result(timeout=wait_s)
        ok, kind = True, None
    except limits.RejectedError:
        ok, kind = False, "rejected"
    except limits.DeadlineExceededError:
        ok, kind = False, "deadline"
    except TimeoutError:
        ok, kind = False, None
    lat_ms = (time.monotonic() - t_submit) * 1e3
    with lock:
        if ok:
            report.completed += 1
            report.rows += rows
            report.latencies_ms.append(lat_ms)
        elif kind == "rejected":
            report.rejected += 1
        elif kind == "deadline":
            report.deadline_failed += 1


def closed_loop(executor, op: str, *, clients: int = 8,
                rows: int = 4, duration_s: float = 2.0,
                tenants: Optional[Sequence[str]] = None,
                deadline_s: Optional[float] = None,
                seed: int = 0, wait_s: float = 30.0) -> LoadReport:
    """``clients`` threads in a submit→wait loop for ``duration_s``.
    Tenant ``i`` is ``tenants[i % len(tenants)]`` (default: one shared
    tenant), so a skewed tenant list doubles as a fairness workload."""
    svc = executor._service(op)
    tenants = list(tenants) if tenants else ["default"]
    report = LoadReport(mode="closed", duration_s=0.0)
    lock = threading.Lock()
    stop = threading.Event()
    before = _snapshot(executor)
    t0 = time.monotonic()

    def client(i: int) -> None:
        rng = np.random.default_rng(seed + i)
        tenant = tenants[i % len(tenants)]
        while not stop.is_set():
            q = rng.standard_normal((rows, svc.dim)).astype(svc.dtype)
            t_submit = time.monotonic()
            try:
                fut = executor.submit(op, q, tenant=tenant,
                                      deadline_s=deadline_s)
            except limits.RejectedError:
                with lock:
                    report.rejected += 1
                time.sleep(0.001)       # brief backoff, stay closed-loop
                continue
            _record(report, lock, rows, t_submit, fut, wait_s)

    threads = [threading.Thread(target=client, args=(i,), daemon=True)
               for i in range(clients)]
    for t in threads:
        t.start()
    time.sleep(duration_s)
    stop.set()
    for t in threads:
        t.join(timeout=wait_s)
    return _finalize(report, executor, before, t0)


def open_loop(executor, op: str, *, rate_qps: float = 200.0,
              rows: int = 4, duration_s: float = 2.0,
              tenants: Optional[Sequence[str]] = None,
              deadline_s: Optional[float] = None,
              poisson: bool = True, seed: int = 0,
              wait_s: float = 30.0) -> LoadReport:
    """Submit on a fixed arrival schedule (Poisson by default) without
    waiting for completions — each in-flight request is awaited by a
    collector thread, so measured latency includes queueing delay
    (no coordinated omission)."""
    svc = executor._service(op)
    tenants = list(tenants) if tenants else ["default"]
    rng = np.random.default_rng(seed)
    report = LoadReport(mode="open", duration_s=0.0)
    lock = threading.Lock()
    collectors: List[threading.Thread] = []
    before = _snapshot(executor)
    t0 = time.monotonic()
    end = t0 + duration_s
    next_at = t0
    i = 0
    while True:
        now = time.monotonic()
        if now >= end:
            break
        if now < next_at:
            time.sleep(min(next_at - now, 0.005))
            continue
        gap = (rng.exponential(1.0 / rate_qps) if poisson
               else 1.0 / rate_qps)
        next_at += gap
        q = rng.standard_normal((rows, svc.dim)).astype(svc.dtype)
        tenant = tenants[i % len(tenants)]
        i += 1
        t_submit = time.monotonic()
        try:
            fut = executor.submit(op, q, tenant=tenant,
                                  deadline_s=deadline_s)
        except limits.RejectedError:
            with lock:
                report.rejected += 1
            continue
        c = threading.Thread(
            target=_record,
            args=(report, lock, rows, t_submit, fut, wait_s),
            daemon=True)
        c.start()
        collectors.append(c)
    for c in collectors:
        c.join(timeout=wait_s)
    return _finalize(report, executor, before, t0)
