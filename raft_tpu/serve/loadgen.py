"""Load generator for the serving runtime (serving tentpole, part 4).

Two standard modes, both against a live :class:`~raft_tpu.serve.Executor`:

closed loop
    N client threads, each submit → wait → submit. Offered load tracks
    service rate automatically, so the measured queries/sec IS the
    saturation throughput for that concurrency; latency is the classic
    closed-loop response time.
open loop
    requests arrive on a fixed schedule (Poisson or uniform) regardless
    of completions — the arrival process real traffic has. Latency
    percentiles under open loop expose queueing delay that closed loop
    hides (coordinated omission).

Both report p50/p99 latency, achieved queries/sec and rows/sec, the
executor's coalescing factor (real rows per device launch), and the
typed-error counts (rejections, deadline expiries) — the numbers the
acceptance bench (``bench.py --serve``) emits to ``BENCH_r06.json``.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

import numpy as np

from raft_tpu.runtime import limits

__all__ = ["LoadReport", "FleetReport", "ChaosReport",
           "StreamingReport", "CatchupLoadReport", "closed_loop",
           "open_loop", "fleet_closed_loop", "streaming_loop",
           "catchup_under_load", "run_chaos", "CHAOS_SCENARIOS"]


@dataclass
class LoadReport:
    """One load-generation run, summarized."""

    mode: str
    duration_s: float
    completed: int = 0
    rejected: int = 0                   # typed RejectedError
    deadline_failed: int = 0            # typed DeadlineExceededError
    rows: int = 0
    latencies_ms: List[float] = field(default_factory=list)
    coalescing_factor: float = 0.0
    batches: int = 0
    pad_overhead: float = 0.0           # padded rows / real rows
    select_k_bytes_per_s: float = 0.0   # radix-epilogue selection bandwidth
    slo: Dict[str, dict] = field(default_factory=dict)  # tenant -> SLO state
    obs_snapshot: Optional[Dict[str, object]] = None    # when metrics on
    # responses served per brownout level during the run ({} or {0: n}
    # means brownout never engaged) — the ISSUE-16 report column
    brownout_levels: Dict[int, int] = field(default_factory=dict)

    @property
    def qps(self) -> float:
        return self.completed / self.duration_s if self.duration_s else 0.0

    @property
    def rows_per_s(self) -> float:
        return self.rows / self.duration_s if self.duration_s else 0.0

    def percentile_ms(self, q: float) -> float:
        if not self.latencies_ms:
            return float("nan")
        return float(np.percentile(np.asarray(self.latencies_ms), q))

    @property
    def p50_ms(self) -> float:
        return self.percentile_ms(50.0)

    @property
    def p99_ms(self) -> float:
        return self.percentile_ms(99.0)

    def as_dict(self) -> Dict[str, object]:
        out = {
            "mode": self.mode,
            "duration_s": round(self.duration_s, 3),
            "completed": self.completed,
            "rejected": self.rejected,
            "deadline_failed": self.deadline_failed,
            "rows": self.rows,
            "qps": round(self.qps, 2),
            "rows_per_s": round(self.rows_per_s, 1),
            "p50_ms": round(self.p50_ms, 3),
            "p99_ms": round(self.p99_ms, 3),
            "coalescing_factor": round(self.coalescing_factor, 3),
            "batches": self.batches,
            "pad_overhead": round(self.pad_overhead, 4),
            "select_k_bytes_per_s": round(self.select_k_bytes_per_s, 1),
        }
        if self.brownout_levels:
            out["brownout_levels"] = {
                str(k): v for k, v in sorted(
                    self.brownout_levels.items())}
            out["brownout_max_level"] = max(self.brownout_levels)
        if self.slo:
            out["slo"] = self.slo
        if self.obs_snapshot is not None:
            # parity with the bench.py north-star line: serving
            # artifacts carry the counter families that explain their
            # latency numbers
            out["obs"] = self.obs_snapshot
        return out


def _snapshot(executor) -> tuple:
    s = executor.stats
    return (s.batches, s.rows, s.padded_rows,
            dict(s.brownout_levels))


def _finalize(report: LoadReport, executor, before: tuple,
              t0: float) -> LoadReport:
    report.duration_s = time.monotonic() - t0
    b0, r0, p0, *rest = before         # 3-tuple accepted (pre-ISSUE 16)
    lv0 = rest[0] if rest else {}
    s = executor.stats
    db, dr, dp = s.batches - b0, s.rows - r0, s.padded_rows - p0
    report.batches = db
    report.coalescing_factor = dr / db if db else 0.0
    report.pad_overhead = dp / dr if dr else 0.0
    report.brownout_levels = {
        lvl: n - lv0.get(lvl, 0)
        for lvl, n in s.brownout_levels.items()
        if n - lv0.get(lvl, 0) > 0}
    # selection-stage bandwidth: the Executor._launch gauge for kNN
    # services on the radix epilogue (last-observed value per service;
    # report the peak across services — stays 0.0 with metrics off)
    from raft_tpu import obs

    fam = obs.snapshot()["metrics"].get("select_k_bytes_per_s")
    if fam and fam.get("series"):
        report.select_k_bytes_per_s = max(
            float(s["value"]) for s in fam["series"])
    # per-tenant SLO state (ISSUE 10): burn rate + window counts from
    # the executor's QosPolicy, when one is wired and metering
    qos = getattr(executor, "qos", None)
    if qos is not None and hasattr(qos, "slo_snapshot"):
        report.slo = qos.slo_snapshot()
    if obs.enabled():
        report.obs_snapshot = obs.snapshot()
    return report


def _record(report: LoadReport, lock: threading.Lock, rows: int,
            t_submit: float, future, wait_s: float) -> None:
    """Wait one future out and fold the outcome into the report."""
    try:
        future.result(timeout=wait_s)
        ok, kind = True, None
    except limits.RejectedError:
        ok, kind = False, "rejected"
    except limits.DeadlineExceededError:
        ok, kind = False, "deadline"
    except TimeoutError:
        ok, kind = False, None
    lat_ms = (time.monotonic() - t_submit) * 1e3
    with lock:
        if ok:
            report.completed += 1
            report.rows += rows
            report.latencies_ms.append(lat_ms)
        elif kind == "rejected":
            report.rejected += 1
        elif kind == "deadline":
            report.deadline_failed += 1


def closed_loop(executor, op: str, *, clients: int = 8,
                rows: int = 4, duration_s: float = 2.0,
                tenants: Optional[Sequence[str]] = None,
                deadline_s: Optional[float] = None,
                seed: int = 0, wait_s: float = 30.0) -> LoadReport:
    """``clients`` threads in a submit→wait loop for ``duration_s``.
    Tenant ``i`` is ``tenants[i % len(tenants)]`` (default: one shared
    tenant), so a skewed tenant list doubles as a fairness workload."""
    svc = executor._service(op)
    tenants = list(tenants) if tenants else ["default"]
    report = LoadReport(mode="closed", duration_s=0.0)
    lock = threading.Lock()
    stop = threading.Event()
    before = _snapshot(executor)
    t0 = time.monotonic()

    def client(i: int) -> None:
        rng = np.random.default_rng(seed + i)
        tenant = tenants[i % len(tenants)]
        while not stop.is_set():
            q = rng.standard_normal((rows, svc.dim)).astype(svc.dtype)
            t_submit = time.monotonic()
            try:
                fut = executor.submit(op, q, tenant=tenant,
                                      deadline_s=deadline_s)
            except limits.RejectedError:
                with lock:
                    report.rejected += 1
                time.sleep(0.001)       # brief backoff, stay closed-loop
                continue
            _record(report, lock, rows, t_submit, fut, wait_s)

    threads = [threading.Thread(target=client, args=(i,), daemon=True)
               for i in range(clients)]
    for t in threads:
        t.start()
    time.sleep(duration_s)
    stop.set()
    for t in threads:
        t.join(timeout=wait_s)
    return _finalize(report, executor, before, t0)


@dataclass
class FleetReport:
    """One replica-fleet load run: per-replica rows plus the merged
    fleet row (ISSUE 11 loadgen satellite)."""

    per_replica: Dict[str, LoadReport] = field(default_factory=dict)
    fleet: Optional[LoadReport] = None
    routed: int = 0                     # router counters for the run
    spills: int = 0
    router_rejected: int = 0
    hedges_issued: int = 0              # hedged second legs dispatched
    hedges_won: int = 0                 # hedge finished before primary
    hedge_rate: float = 0.0             # issued / routed (the ≤5% cap)
    killed: Optional[str] = None        # replica killed mid-run, if any
    kill_at_s: Optional[float] = None   # offset from run start
    # seconds from the kill to the first subsequent completion meeting
    # the tenant's SLO latency (any completion when no SLO is set);
    # None when nothing was killed, +inf when nothing recovered
    recovery_time_to_slo_s: Optional[float] = None

    def as_dict(self) -> Dict[str, object]:
        out: Dict[str, object] = {
            "mode": "fleet_closed",
            "replicas": {name: r.as_dict()
                         for name, r in self.per_replica.items()},
            "fleet": self.fleet.as_dict() if self.fleet else None,
            "routed": self.routed,
            "spills": self.spills,
            "router_rejected": self.router_rejected,
            "hedges_issued": self.hedges_issued,
            "hedges_won": self.hedges_won,
            "hedge_rate": round(self.hedge_rate, 4),
        }
        if self.killed is not None:
            out["killed"] = self.killed
            out["kill_at_s"] = round(self.kill_at_s, 3)
            out["recovery_time_to_slo_s"] = (
                round(self.recovery_time_to_slo_s, 4)
                if self.recovery_time_to_slo_s is not None else None)
        return out


def _slo_latency_s(group, tenant: str) -> Optional[float]:
    """The tenant's SLO latency from the first replica carrying a QoS
    policy (replicas share one policy table by construction)."""
    for r in group.replicas:
        qos = getattr(r.executor, "qos", None)
        if qos is not None:
            # a policy table without SLO fields means "no SLO configured"
            try:
                return qos.policy(tenant).slo_latency_s
            except (AttributeError, KeyError):
                return None
    return None


def fleet_closed_loop(group, op: str, *, clients: int = 8,
                      rows: int = 4, duration_s: float = 2.0,
                      tenants: Optional[Sequence[str]] = None,
                      deadline_s: Optional[float] = None,
                      seed: int = 0, wait_s: float = 30.0,
                      kill_after_s: Optional[float] = None,
                      kill=None) -> FleetReport:
    """Closed-loop load against a :class:`~raft_tpu.serve.ReplicaGroup`.

    Routes every submit through the group's weighted-fair router and
    attributes each completion to the replica that served it, so the
    report carries one p50/p99/qps row per replica plus the merged
    fleet row. With ``kill_after_s`` set, a killer thread fires ``kill``
    (default: :meth:`ReplicaGroup.fail_replica` on the last healthy
    replica) mid-run and the report's ``recovery_time_to_slo_s`` is the
    time from the kill to the first subsequent completion meeting the
    tenant's SLO latency — the serving-side recovery witness the chaos
    gate asserts on."""
    tenants = list(tenants) if tenants else ["default"]
    svc = None
    for r in group.healthy():
        try:
            svc = r.executor._service(op)
            break
        except KeyError:
            continue
    if svc is None:
        raise KeyError(f"no healthy replica serves op {op!r}")
    slo_s = _slo_latency_s(group, tenants[0])

    fleet = FleetReport()
    per_rep: Dict[str, LoadReport] = {
        r.name: LoadReport(mode="fleet_closed", duration_s=0.0)
        for r in group.replicas}
    merged = LoadReport(mode="fleet_closed", duration_s=0.0)
    lock = threading.Lock()
    stop = threading.Event()
    # (t_kill, recovery) shared with the record path
    kill_state: Dict[str, Optional[float]] = {"t_kill": None,
                                              "recovery": None}
    snaps = {r.name: (r, _snapshot(r.executor)) for r in group.replicas}
    routed0, spills0, rej0 = (group.stats.routed, group.stats.spills,
                              group.stats.rejected)
    hedged0, hwon0 = (group.stats.hedges_issued, group.stats.hedges_won)
    t0 = time.monotonic()

    def record(rep_name: str, t_submit: float, fut) -> None:
        try:
            fut.result(timeout=wait_s)
            ok, kind = True, None
        except limits.RejectedError:
            ok, kind = False, "rejected"
        except limits.DeadlineExceededError:
            ok, kind = False, "deadline"
        except TimeoutError:
            ok, kind = False, None
        t_done = time.monotonic()
        lat_ms = (t_done - t_submit) * 1e3
        with lock:
            reports = [merged]
            if rep_name in per_rep:
                reports.append(per_rep[rep_name])
            for rep in reports:
                if ok:
                    rep.completed += 1
                    rep.rows += rows
                    rep.latencies_ms.append(lat_ms)
                elif kind == "rejected":
                    rep.rejected += 1
                elif kind == "deadline":
                    rep.deadline_failed += 1
            t_kill = kill_state["t_kill"]
            if (ok and t_kill is not None
                    and kill_state["recovery"] is None
                    and t_submit >= t_kill
                    and (slo_s is None or lat_ms * 1e-3 <= slo_s)):
                kill_state["recovery"] = t_done - t_kill

    def client(i: int) -> None:
        rng = np.random.default_rng(seed + i)
        tenant = tenants[i % len(tenants)]
        while not stop.is_set():
            q = rng.standard_normal((rows, svc.dim)).astype(svc.dtype)
            t_submit = time.monotonic()
            try:
                replica, fut = group.route(op, q, tenant=tenant,
                                           deadline_s=deadline_s)
            except limits.RejectedError:
                with lock:
                    merged.rejected += 1
                time.sleep(0.001)
                continue
            record(replica.name, t_submit, fut)

    def killer() -> None:
        if stop.wait(kill_after_s):
            return                      # run ended before the kill
        live = group.healthy()
        if not live:
            return
        target = live[-1]
        with lock:
            fleet.killed = target.name
            kill_state["t_kill"] = time.monotonic()
            fleet.kill_at_s = kill_state["t_kill"] - t0
        if kill is not None:
            kill(target)
        else:
            group.fail_replica(target, "loadgen kill")

    threads = [threading.Thread(target=client, args=(i,), daemon=True)
               for i in range(clients)]
    if kill_after_s is not None:
        threads.append(threading.Thread(target=killer, daemon=True))
    for t in threads:
        t.start()
    time.sleep(duration_s)
    stop.set()
    for t in threads:
        t.join(timeout=wait_s)

    for name, (replica, before) in snaps.items():
        _finalize(per_rep[name], replica.executor, before, t0)
    merged.duration_s = time.monotonic() - t0
    merged.batches = sum(r.batches for r in per_rep.values())
    tot_rows = sum(r.coalescing_factor * r.batches
                   for r in per_rep.values())
    merged.coalescing_factor = (tot_rows / merged.batches
                                if merged.batches else 0.0)
    merged.slo = (group.slo_snapshot()
                  if hasattr(group, "slo_snapshot") else {})
    fleet.per_replica = per_rep
    fleet.fleet = merged
    fleet.routed = group.stats.routed - routed0
    fleet.spills = group.stats.spills - spills0
    fleet.router_rejected = group.stats.rejected - rej0
    fleet.hedges_issued = group.stats.hedges_issued - hedged0
    fleet.hedges_won = group.stats.hedges_won - hwon0
    fleet.hedge_rate = (fleet.hedges_issued / fleet.routed
                        if fleet.routed else 0.0)
    if fleet.killed is not None:
        fleet.recovery_time_to_slo_s = (
            kill_state["recovery"] if kill_state["recovery"] is not None
            else float("inf"))
    return fleet


def open_loop(executor, op: str, *, rate_qps: float = 200.0,
              rows: int = 4, duration_s: float = 2.0,
              tenants: Optional[Sequence[str]] = None,
              deadline_s: Optional[float] = None,
              poisson: bool = True, seed: int = 0,
              wait_s: float = 30.0) -> LoadReport:
    """Submit on a fixed arrival schedule (Poisson by default) without
    waiting for completions — each in-flight request is awaited by a
    collector thread, so measured latency includes queueing delay
    (no coordinated omission)."""
    svc = executor._service(op)
    tenants = list(tenants) if tenants else ["default"]
    rng = np.random.default_rng(seed)
    report = LoadReport(mode="open", duration_s=0.0)
    lock = threading.Lock()
    collectors: List[threading.Thread] = []
    before = _snapshot(executor)
    t0 = time.monotonic()
    end = t0 + duration_s
    next_at = t0
    i = 0
    while True:
        now = time.monotonic()
        if now >= end:
            break
        if now < next_at:
            time.sleep(min(next_at - now, 0.005))
            continue
        gap = (rng.exponential(1.0 / rate_qps) if poisson
               else 1.0 / rate_qps)
        next_at += gap
        q = rng.standard_normal((rows, svc.dim)).astype(svc.dtype)
        tenant = tenants[i % len(tenants)]
        i += 1
        t_submit = time.monotonic()
        try:
            fut = executor.submit(op, q, tenant=tenant,
                                  deadline_s=deadline_s)
        except limits.RejectedError:
            with lock:
                report.rejected += 1
            continue
        c = threading.Thread(
            target=_record,
            args=(report, lock, rows, t_submit, fut, wait_s),
            daemon=True)
        c.start()
        collectors.append(c)
    for c in collectors:
        c.join(timeout=wait_s)
    return _finalize(report, executor, before, t0)


# ---------------------------------------------------------------------------
# traffic-chaos scenario pack (ISSUE 16)
# ---------------------------------------------------------------------------

@dataclass
class ChaosReport:
    """One chaos scenario run: named phases (each a LoadReport/
    FleetReport dict) plus the resilience witnesses the CI gates
    assert on — typed fields, not log scraping."""

    scenario: str
    phases: Dict[str, Dict[str, object]] = field(default_factory=dict)
    brownout_max_level: int = 0         # deepest level actually served
    brownout_recovered: bool = True     # level back to 0 at scenario end
    retraces_during: int = 0            # compiles after the warm phase
    rejected_total: int = 0             # typed rejections, all phases
    hedges_issued: int = 0
    hedges_won: int = 0
    hedge_rate: float = 0.0
    notes: Dict[str, object] = field(default_factory=dict)

    def as_dict(self) -> Dict[str, object]:
        out: Dict[str, object] = {
            "scenario": self.scenario,
            "phases": self.phases,
            "brownout_max_level": self.brownout_max_level,
            "brownout_recovered": self.brownout_recovered,
            "retraces_during": self.retraces_during,
            "rejected_total": self.rejected_total,
            "hedges_issued": self.hedges_issued,
            "hedges_won": self.hedges_won,
            "hedge_rate": round(self.hedge_rate, 4),
        }
        if self.notes:
            out["notes"] = self.notes
        return out


def _group_closed_loop(group, op: str, *, clients: int = 8,
                       rows: int = 4, duration_s: float = 2.0,
                       tenants: Optional[Sequence[str]] = None,
                       deadline_s: Optional[float] = None,
                       seed: int = 0, wait_s: float = 30.0
                       ) -> LoadReport:
    """Closed loop through :meth:`ReplicaGroup.submit` — the HEDGED
    fleet entry point (``fleet_closed_loop`` deliberately routes
    unhedged for per-replica attribution; this helper measures what a
    hedging client experiences)."""
    tenants = list(tenants) if tenants else ["default"]
    report = LoadReport(mode="group_closed", duration_s=0.0)
    lock = threading.Lock()
    stop = threading.Event()
    dim = None
    dtype = None
    for r in group.healthy():
        try:
            svc = r.executor._service(op)
            dim, dtype = svc.dim, svc.dtype
            break
        except (KeyError, ValueError):
            continue
    if dim is None:
        raise KeyError(f"no healthy replica serves op {op!r}")
    t0 = time.monotonic()

    def client(i: int) -> None:
        rng = np.random.default_rng(seed + i)
        tenant = tenants[i % len(tenants)]
        while not stop.is_set():
            q = rng.standard_normal((rows, dim)).astype(dtype)
            t_submit = time.monotonic()
            try:
                fut = group.submit(op, q, tenant=tenant,
                                   deadline_s=deadline_s)
            except limits.RejectedError:
                with lock:
                    report.rejected += 1
                time.sleep(0.001)
                continue
            _record(report, lock, rows, t_submit, fut, wait_s)

    threads = [threading.Thread(target=client, args=(i,), daemon=True)
               for i in range(clients)]
    for t in threads:
        t.start()
    time.sleep(duration_s)
    stop.set()
    for t in threads:
        t.join(timeout=wait_s)
    report.duration_s = time.monotonic() - t0
    return report


def chaos_traffic_step(executor, op: str, *, base_qps: float = 50.0,
                       step_factor: float = 4.0, rows: int = 4,
                       phase_s: float = 2.0,
                       recovery_s: Optional[float] = None,
                       tenants: Optional[Sequence[str]] = None,
                       deadline_s: Optional[float] = None,
                       seed: int = 0) -> ChaosReport:
    """Open-loop traffic step: ``base_qps`` → ``step_factor`` × → back.

    The witnesses: the brownout controller engages during the step
    (level > 0 responses served), every transition rides pre-warmed
    executables (``retraces_during`` stays 0), and the level returns
    to 0 in the recovery phase."""
    rep = ChaosReport(scenario="traffic_step")
    traces0 = executor.stats.traces
    common = dict(rows=rows, tenants=tenants, deadline_s=deadline_s,
                  seed=seed)
    phases = (("base", base_qps, phase_s),
              ("step", base_qps * step_factor, phase_s),
              ("recovery", base_qps,
               phase_s if recovery_s is None else recovery_s))
    for name, qps, dur in phases:
        r = open_loop(executor, op, rate_qps=qps, duration_s=dur,
                      **common)
        rep.phases[name] = r.as_dict()
        rep.rejected_total += r.rejected
        if r.brownout_levels:
            rep.brownout_max_level = max(rep.brownout_max_level,
                                         max(r.brownout_levels))
    rep.retraces_during = executor.stats.traces - traces0
    ctl = getattr(executor, "brownout", None)
    rep.brownout_recovered = ctl is None or not ctl.snapshot()
    if ctl is not None:
        rep.notes["controller"] = ctl.snapshot()
    return rep


def chaos_slow_replica(group, op: str, *, stall_s: float = 0.05,
                       victim: int = 0, clients: int = 8,
                       rows: int = 4, phase_s: float = 2.0,
                       stall_duty: float = 1.0,
                       stall_period_s: float = 0.5,
                       tenants: Optional[Sequence[str]] = None,
                       deadline_s: Optional[float] = None,
                       seed: int = 0) -> ChaosReport:
    """One replica straggles (``FaultInjector.stall`` on its executor);
    hedged dispatch must hold fleet p99 near the healthy baseline while
    spending at most the hedge budget. Phases: ``healthy`` (baseline),
    ``stalled`` (victim straggling), ``healed`` (stall disarmed).

    ``stall_duty`` < 1 makes the straggler INTERMITTENT: the stall is
    armed for ``stall_duty x stall_period_s`` of every period and
    disarmed for the rest — the GC-pause/compaction profile hedging is
    built for. A constant straggler (duty 1.0, the default) slows half
    a 2-replica fleet's traffic, more demand than a <= 5% hedge budget
    can cover by design; the duty-cycled profile keeps the latency
    spikes in the tail where the budget reaches them."""
    if not 0.0 < stall_duty <= 1.0:
        raise ValueError(f"stall_duty must be in (0, 1], "
                         f"got {stall_duty}")
    if not stall_period_s > 0.0:
        raise ValueError(f"stall_period_s must be > 0, "
                         f"got {stall_period_s}")
    inj = group.replicas[victim].executor.faults
    if inj is None:
        raise ValueError(
            f"replica {victim} has no FaultInjector attached — build "
            f"its Executor with faults=FaultInjector(...) to run the "
            f"slow-replica scenario")
    rep = ChaosReport(scenario="slow_replica")
    issued0, won0 = (group.stats.hedges_issued, group.stats.hedges_won)
    routed0 = group.stats.routed
    common = dict(clients=clients, rows=rows, duration_s=phase_s,
                  tenants=tenants, deadline_s=deadline_s, seed=seed)
    r = _group_closed_loop(group, op, **common)
    rep.phases["healthy"] = r.as_dict()
    rep.rejected_total += r.rejected
    toggler: Optional[threading.Thread] = None
    stop_toggle = threading.Event()
    if stall_duty < 1.0:
        def _toggle() -> None:
            while True:
                inj.stall(stall_s)
                if stop_toggle.wait(stall_period_s * stall_duty):
                    return
                inj.stall(0.0)
                if stop_toggle.wait(stall_period_s
                                    * (1.0 - stall_duty)):
                    return

        toggler = threading.Thread(target=_toggle, daemon=True,
                                   name="raft-tpu-stall-toggle")
        toggler.start()
    else:
        inj.stall(stall_s)
    try:
        r = _group_closed_loop(group, op, **common)
        rep.phases["stalled"] = r.as_dict()
        rep.rejected_total += r.rejected
    finally:
        stop_toggle.set()
        if toggler is not None:
            toggler.join(timeout=10.0)
        inj.stall(0.0)
    r = _group_closed_loop(group, op, **common)
    rep.phases["healed"] = r.as_dict()
    rep.rejected_total += r.rejected
    rep.hedges_issued = group.stats.hedges_issued - issued0
    rep.hedges_won = group.stats.hedges_won - won0
    routed = group.stats.routed - routed0
    rep.hedge_rate = rep.hedges_issued / routed if routed else 0.0
    rep.notes["victim"] = group.replicas[victim].name
    rep.notes["stall_s"] = stall_s
    if stall_duty < 1.0:
        rep.notes["stall_duty"] = stall_duty
        rep.notes["stall_period_s"] = stall_period_s
    return rep


def chaos_hog_tenant(executor, op: str, *, hog_clients: int = 6,
                     light_clients: int = 2, rows: int = 4,
                     phase_s: float = 2.0,
                     deadline_s: Optional[float] = None,
                     seed: int = 0) -> ChaosReport:
    """One tenant floods the queue while a light tenant keeps its small
    trickle: weighted-fair scheduling plus per-tenant brownout should
    degrade the HOG (its burn rate spikes first) while the light
    tenant — typically pinned by ``min_quality`` — keeps full
    quality."""
    rep = ChaosReport(scenario="hog_tenant")
    tenants = ["hog"] * hog_clients + ["light"] * light_clients
    r = closed_loop(executor, op, clients=hog_clients + light_clients,
                    rows=rows, duration_s=phase_s, tenants=tenants,
                    deadline_s=deadline_s, seed=seed)
    rep.phases["flood"] = r.as_dict()
    rep.rejected_total = r.rejected
    if r.brownout_levels:
        rep.brownout_max_level = max(r.brownout_levels)
    ctl = getattr(executor, "brownout", None)
    if ctl is not None:
        snap = ctl.snapshot()
        rep.notes["controller"] = snap
        rep.notes["light_level"] = max(
            (lv.get("light", 0) for lv in snap.values()), default=0)
        rep.brownout_recovered = not snap
    return rep


def chaos_kill_mid_spike(group, op: str, *, clients: int = 8,
                         rows: int = 4, phase_s: float = 2.0,
                         kill_after_s: Optional[float] = None,
                         tenants: Optional[Sequence[str]] = None,
                         deadline_s: Optional[float] = None,
                         seed: int = 0) -> ChaosReport:
    """A replica dies at the peak of a closed-loop spike: heal-path
    routing, brownout and hedging all under one roof. Wraps
    :func:`fleet_closed_loop`'s kill machinery and surfaces its
    recovery-to-SLO clock."""
    rep = ChaosReport(scenario="kill_mid_spike")
    fr = fleet_closed_loop(
        group, op, clients=clients, rows=rows, duration_s=phase_s,
        tenants=tenants, deadline_s=deadline_s, seed=seed,
        kill_after_s=kill_after_s
        if kill_after_s is not None else phase_s / 3)
    rep.phases["spike"] = fr.as_dict()
    rep.rejected_total = (fr.fleet.rejected if fr.fleet else 0) \
        + fr.router_rejected
    rep.hedges_issued = fr.hedges_issued
    rep.hedges_won = fr.hedges_won
    rep.hedge_rate = fr.hedge_rate
    levels = (fr.fleet.brownout_levels if fr.fleet else {}) or {}
    for rrep in fr.per_replica.values():
        for lvl, n in rrep.brownout_levels.items():
            levels[lvl] = levels.get(lvl, 0) + n
    if levels:
        rep.brownout_max_level = max(levels)
    rep.notes["killed"] = fr.killed
    rep.notes["recovery_time_to_slo_s"] = fr.recovery_time_to_slo_s
    return rep


def chaos_kill_leader(group, op: str, *, clients: int = 8,
                      rows: int = 4, phase_s: float = 2.0,
                      kill_after_s: Optional[float] = None,
                      promote=None,
                      tenants: Optional[Sequence[str]] = None,
                      deadline_s: Optional[float] = None,
                      seed: int = 0) -> ChaosReport:
    """The fleet's WRITE leader dies at the peak of a closed-loop
    spike (ISSUE 20, the serve half of a leader election): queries
    keep routing across the survivors throughout, and a survivor is
    promoted via :meth:`ReplicaGroup.promote` — the leader MARKER
    moves, no data does, so the promotion itself is recompile-free.

    ``promote`` picks the successor from the group (default: the
    first healthy survivor — a real fleet passes the election
    winner's replica here). Stamps both failover clocks the CI gate
    reads: ``time_to_new_leader_s`` (kill to promote-returned) and
    ``recovery_time_to_slo_s`` (kill to the first subsequent
    completion meeting the tenant SLO)."""
    rep = ChaosReport(scenario="kill_leader")
    # make the write leader the replica the kill machinery targets
    leader = group.promote(group.healthy()[-1].name)
    state: Dict[str, float] = {}

    def kill_leader(target) -> None:
        t_kill = time.monotonic()
        group.fail_replica(target, "leader killed")
        pick = promote(group) if promote is not None \
            else group.healthy()[0]
        group.promote(getattr(pick, "name", pick))
        state["time_to_new_leader_s"] = time.monotonic() - t_kill

    fr = fleet_closed_loop(
        group, op, clients=clients, rows=rows, duration_s=phase_s,
        tenants=tenants, deadline_s=deadline_s, seed=seed,
        kill_after_s=kill_after_s
        if kill_after_s is not None else phase_s / 3,
        kill=kill_leader)
    rep.phases["spike"] = fr.as_dict()
    rep.rejected_total = (fr.fleet.rejected if fr.fleet else 0) \
        + fr.router_rejected
    rep.hedges_issued = fr.hedges_issued
    rep.hedges_won = fr.hedges_won
    rep.hedge_rate = fr.hedge_rate
    rep.notes["killed_leader"] = fr.killed
    rep.notes["old_leader"] = leader.name
    new = group.leader
    rep.notes["new_leader"] = None if new is None else new.name
    rep.notes["time_to_new_leader_s"] = state.get(
        "time_to_new_leader_s")
    rep.notes["recovery_time_to_slo_s"] = fr.recovery_time_to_slo_s
    return rep


@dataclass
class StreamingReport:
    """One streaming-ingest load run (ISSUE 17): sustained inserts +
    deletes racing concurrent queries, with per-query recall measured
    against an exact reference over the snapshot the query targeted.
    ``min_recall`` across the run is the swap-safety witness the CI
    gate asserts a floor on — it covers every query served while a
    compaction swap was in flight."""

    duration_s: float
    queries: int = 0
    failed: int = 0
    ingest_rows: int = 0
    deleted_rows: int = 0
    ingest_batches: int = 0
    latencies_ms: List[float] = field(default_factory=list)
    recalls: List[float] = field(default_factory=list)
    swaps: int = 0                      # epoch-bumped (shape) swaps
    refreshes: int = 0                  # all serving-snapshot publishes
    compactions: int = 0                # background compaction cycles
    n_live_final: int = 0

    @property
    def qps(self) -> float:
        return self.queries / self.duration_s if self.duration_s else 0.0

    @property
    def ingest_rate(self) -> float:
        """Inserted rows per second, sustained across the run."""
        return (self.ingest_rows / self.duration_s
                if self.duration_s else 0.0)

    def percentile_ms(self, q: float) -> float:
        if not self.latencies_ms:
            return float("nan")
        return float(np.percentile(np.asarray(self.latencies_ms), q))

    @property
    def p50_ms(self) -> float:
        return self.percentile_ms(50.0)

    @property
    def p99_ms(self) -> float:
        return self.percentile_ms(99.0)

    @property
    def min_recall(self) -> float:
        return min(self.recalls) if self.recalls else float("nan")

    @property
    def mean_recall(self) -> float:
        return (float(np.mean(self.recalls)) if self.recalls
                else float("nan"))

    def as_dict(self) -> Dict[str, object]:
        return {
            "mode": "streaming",
            "duration_s": round(self.duration_s, 3),
            "queries": self.queries,
            "failed": self.failed,
            "qps": round(self.qps, 2),
            "ingest_rows": self.ingest_rows,
            "deleted_rows": self.deleted_rows,
            "ingest_batches": self.ingest_batches,
            "ingest_rate": round(self.ingest_rate, 1),
            "p50_ms": round(self.p50_ms, 3),
            "p99_ms": round(self.p99_ms, 3),
            "min_recall": round(self.min_recall, 4),
            "mean_recall": round(self.mean_recall, 4),
            "swaps": self.swaps,
            "refreshes": self.refreshes,
            "compactions": self.compactions,
            "n_live_final": self.n_live_final,
        }


def _snapshot_exact_ids(snap, q: np.ndarray, k: int) -> np.ndarray:
    """Exact top-k external ids over one streaming snapshot's live
    rows — the numpy reference the per-query recall is scored against
    (test/bench scale only: materializes the full distance matrix)."""
    flat = snap.flat
    ids = np.asarray(flat.packed_ids)
    rows = np.asarray(flat.packed_db)
    live = ids >= 0
    words = np.asarray(snap.tomb_words)
    if words.size:
        safe = np.clip(ids, 0, None)
        live &= ((words[safe // 32] >> (safe % 32)) & 1) == 0
    rows, ids = rows[live], ids[live]
    q = np.asarray(q, np.float32)
    rows = np.asarray(rows, np.float32)
    if flat.metric == "ip":
        d = -(q @ rows.T)
    else:
        d = ((q * q).sum(1)[:, None] - 2.0 * (q @ rows.T)
             + (rows * rows).sum(1)[None, :])
    top = np.argsort(d, axis=1, kind="stable")[:, :k]
    return ids[top]


def streaming_loop(controller, op: str, *, clients: int = 4,
                   rows: int = 4, duration_s: float = 2.0,
                   ingest_rows: int = 32,
                   ingest_interval_s: float = 0.05,
                   delete_frac: float = 0.3, seed: int = 0,
                   wait_s: float = 30.0) -> StreamingReport:
    """Sustained ingest racing concurrent queries against one
    :class:`~raft_tpu.serve.ingest.IngestController`.

    One ingester thread inserts ``ingest_rows`` rows every
    ``ingest_interval_s`` and tombstones ``delete_frac`` of each batch
    (feeding the background compactor); ``clients`` query threads run
    the closed loop against ``op``, each scoring its response against
    an exact reference computed over the snapshot it targeted — so a
    torn or stale swap shows up as a recall dip, not a silent wrong
    answer. Recall is relative to exact search over the live rows, so
    the floor a gate asserts must budget for the op's nprobe (use
    ``nprobe = n_lists - 1`` for a near-exact probe that still rides
    the masked partial path)."""
    svc = controller.executor._service(op)
    k = svc.k
    report = StreamingReport(duration_s=0.0)
    lock = threading.Lock()
    stop = threading.Event()
    swaps0 = controller.swaps
    refreshes0 = controller.refreshes
    compactions0 = controller.compactor.compactions

    def ingester() -> None:
        rng = np.random.default_rng(seed + 10_000)
        while not stop.is_set():
            batch = rng.standard_normal(
                (ingest_rows, svc.dim)).astype(svc.dtype)
            ids = controller.insert(batch)
            n_del = int(round(len(ids) * delete_frac))
            if n_del:
                controller.delete(ids[:n_del])
            with lock:
                report.ingest_rows += len(ids)
                report.ingest_batches += 1
                report.deleted_rows += n_del
            if stop.wait(ingest_interval_s):
                return

    def _recall(got: np.ndarray, ref: np.ndarray) -> float:
        return float(np.mean(
            [len(set(got[j].tolist()) & set(ref[j].tolist())) / k
             for j in range(got.shape[0])]))

    def client(i: int) -> None:
        rng = np.random.default_rng(seed + i)
        while not stop.is_set():
            q = rng.standard_normal((rows, svc.dim)).astype(svc.dtype)
            before = svc.stream.snapshot
            t_submit = time.monotonic()
            try:
                fut = controller.submit(op, q)
                d, got = fut.result(timeout=wait_s)
            except Exception:  # noqa: BLE001 — tallied, loop continues
                with lock:
                    report.failed += 1
                continue
            lat_ms = (time.monotonic() - t_submit) * 1e3
            got = np.asarray(got)
            # a query in flight across swaps legitimately serves ANY
            # consistent version from its submit→complete window —
            # score against each and keep the best. A torn swap
            # matches NO version and still craters the recall.
            rec = _recall(got, _snapshot_exact_ids(before, q, k))
            if rec < 1.0:
                for snap in svc.stream.recent_snapshots():
                    if snap.version <= before.version or rec >= 1.0:
                        continue
                    rec = max(rec, _recall(
                        got, _snapshot_exact_ids(snap, q, k)))
            with lock:
                report.queries += 1
                report.latencies_ms.append(lat_ms)
                report.recalls.append(rec)

    threads = [threading.Thread(target=ingester, daemon=True)]
    threads += [threading.Thread(target=client, args=(i,), daemon=True)
                for i in range(clients)]
    t0 = time.monotonic()
    for t in threads:
        t.start()
    time.sleep(duration_s)
    stop.set()
    for t in threads:
        t.join(timeout=wait_s)
    report.duration_s = time.monotonic() - t0
    report.swaps = controller.swaps - swaps0
    report.refreshes = controller.refreshes - refreshes0
    report.compactions = controller.compactor.compactions - compactions0
    report.n_live_final = controller.stream.n_live
    return report


@dataclass
class CatchupLoadReport:
    """One WAL catch-up run under query load (ISSUE 18): a follower
    replays the leader's shipped records while queries race the
    mirror-applies, each scored against an exact reference over the
    snapshot it targeted. ``min_recall`` is the recall-floor-during-
    catch-up witness the acceptance criteria gate on; ``skipped``
    counts queries deferred while the follower held fewer than ``k``
    live rows (a snapshot-bootstrapped follower starts empty)."""

    duration_s: float
    queries: int = 0
    skipped: int = 0
    latencies_ms: List[float] = field(default_factory=list)
    recalls: List[float] = field(default_factory=list)
    applied_seq: int = -1
    target_seq: int = -1
    resyncs: int = 0
    catchup_seconds: float = float("nan")

    @property
    def min_recall(self) -> float:
        return min(self.recalls) if self.recalls else float("nan")

    @property
    def mean_recall(self) -> float:
        return (float(np.mean(self.recalls)) if self.recalls
                else float("nan"))

    def percentile_ms(self, q: float) -> float:
        if not self.latencies_ms:
            return float("nan")
        return float(np.percentile(np.asarray(self.latencies_ms), q))

    def as_dict(self) -> Dict[str, object]:
        return {
            "mode": "catchup",
            "duration_s": round(self.duration_s, 3),
            "queries": self.queries,
            "skipped": self.skipped,
            "p50_ms": round(self.percentile_ms(50.0), 3),
            "p99_ms": round(self.percentile_ms(99.0), 3),
            "min_recall": round(self.min_recall, 4),
            "mean_recall": round(self.mean_recall, 4),
            "applied_seq": self.applied_seq,
            "target_seq": self.target_seq,
            "resyncs": self.resyncs,
            "catchup_seconds": round(self.catchup_seconds, 3),
        }


def catchup_under_load(follower, *, k: int, nprobe: int,
                       target_seq: int, rows: int = 4, seed: int = 0,
                       wait_s: float = 30.0) -> CatchupLoadReport:
    """Drive one :class:`~raft_tpu.neighbors.wal_ship.WalFollower`
    through a full catch-up (snapshot resync if gapped, then record
    drain to ``target_seq``) while querying it the whole time.

    A worker thread runs ``follower.catch_up()`` then drains shipped
    records until ``follower.applied_seq >= target_seq``; the
    foreground loop searches the follower's index directly, scoring
    per-query recall against the exact reference over the snapshot the
    query targeted (best-of over ``recent_snapshots()`` when a
    mirror-apply published mid-flight — the :func:`streaming_loop`
    discipline). Queries are counted as ``skipped`` while the follower
    holds fewer than ``k`` live rows. The returned report's
    ``min_recall`` covers every query answered during catch-up."""
    index = follower.index
    report = CatchupLoadReport(duration_s=0.0, target_seq=target_seq)
    done = threading.Event()
    errors: List[BaseException] = []
    t0 = time.monotonic()

    def worker() -> None:
        try:
            cr = follower.catch_up(timeout=wait_s)
            report.catchup_seconds = cr.seconds
            while follower.applied_seq < target_seq:
                if follower.drain() == 0:
                    if time.monotonic() - t0 > wait_s:
                        raise TimeoutError(
                            f"follower stalled at seq "
                            f"{follower.applied_seq} < {target_seq}")
                    time.sleep(0.001)
        except BaseException as exc:  # noqa: BLE001 — re-raised below
            errors.append(exc)
        finally:
            done.set()

    def _recall(got: np.ndarray, ref: np.ndarray) -> float:
        return float(np.mean(
            [len(set(got[j].tolist()) & set(ref[j].tolist())) / k
             for j in range(got.shape[0])]))

    rng = np.random.default_rng(seed)
    # warm the search BEFORE racing it against the apply stream: the
    # first call's compile can outlast the snapshot ring (applies keep
    # publishing), which would make its result unscorable
    warm_snap = index.snapshot
    if warm_snap.n_live >= k:
        index.search(rng.standard_normal(
            (rows, warm_snap.flat.dim)).astype(np.float32), k, nprobe)
    t = threading.Thread(target=worker, daemon=True)
    t.start()
    while True:
        if errors:
            break
        snap = index.snapshot
        if snap.n_live < k:
            if done.is_set():
                # catch-up finished — re-read once (the snapshot
                # install may have landed after the first read) and
                # give up only if the follower truly never grew to k
                snap = index.snapshot
                if snap.n_live < k:
                    break
            else:
                report.skipped += 1
                time.sleep(0.001)
                continue
        q = rng.standard_normal(
            (rows, snap.flat.dim)).astype(np.float32)
        t_q = time.monotonic()
        _, got = index.search(q, k, nprobe)
        lat_ms = (time.monotonic() - t_q) * 1e3
        # grab the candidate versions NOW, before the (slow) exact
        # scoring — applies keep publishing and would walk the bounded
        # ring past the version the search actually served
        cands = [snap] + [s for s in index.recent_snapshots()
                          if s.version > snap.version]
        got = np.asarray(got)
        rec = 0.0
        # a mirror-apply published mid-flight: any consistent version
        # from the query window is legitimate
        for s in cands:
            rec = max(rec, _recall(got, _snapshot_exact_ids(s, q, k)))
            if rec >= 1.0:
                break
        report.queries += 1
        report.latencies_ms.append(lat_ms)
        report.recalls.append(rec)
        if done.is_set():
            break  # at least one query answered post-catch-up
    t.join(timeout=wait_s)
    if errors:
        raise errors[0]
    report.duration_s = time.monotonic() - t0
    report.applied_seq = follower.applied_seq
    report.resyncs = follower.resyncs
    return report


#: scenario name -> callable(target, op, **kwargs). ``traffic_step``
#: and ``hog_tenant`` take an Executor; the fleet scenarios take a
#: ReplicaGroup.
CHAOS_SCENARIOS = {
    "traffic_step": chaos_traffic_step,
    "slow_replica": chaos_slow_replica,
    "hog_tenant": chaos_hog_tenant,
    "kill_mid_spike": chaos_kill_mid_spike,
    "kill_leader": chaos_kill_leader,
}


def run_chaos(scenario: str, target, op: str, **kwargs) -> ChaosReport:
    """Dispatch one named chaos scenario against an Executor or
    ReplicaGroup (see :data:`CHAOS_SCENARIOS`)."""
    fn = CHAOS_SCENARIOS.get(scenario)
    if fn is None:
        raise ValueError(f"unknown chaos scenario {scenario!r}; have "
                         f"{sorted(CHAOS_SCENARIOS)}")
    return fn(target, op, **kwargs)


def main(argv: Optional[Sequence[str]] = None) -> int:
    """``python -m raft_tpu.serve.loadgen`` — run the generator against
    a synthetic kNN fleet and print the report as JSON.

    ``--replicas N`` spins up N warmed replicas behind a
    :class:`~raft_tpu.serve.ReplicaGroup` and runs the fleet closed
    loop (per-replica rows + merged row); ``--kill-after S`` kills one
    replica mid-run and reports ``recovery_time_to_slo_s``."""
    import argparse
    import json

    p = argparse.ArgumentParser(prog="raft_tpu.serve.loadgen")
    p.add_argument("--replicas", type=int, default=1)
    p.add_argument("--clients", type=int, default=8)
    p.add_argument("--rows", type=int, default=4)
    p.add_argument("--duration", type=float, default=2.0)
    p.add_argument("--mode", choices=("closed", "open"),
                   default="closed")
    p.add_argument("--rate-qps", type=float, default=200.0)
    p.add_argument("--n-db", type=int, default=4096)
    p.add_argument("--dim", type=int, default=64)
    p.add_argument("--k", type=int, default=10)
    p.add_argument("--metric", default="l2")
    p.add_argument("--deadline", type=float, default=None)
    p.add_argument("--slo-ms", type=float, default=None,
                   help="default-tenant SLO latency (arms burn-rate "
                        "metering and the recovery-to-SLO clock)")
    p.add_argument("--kill-after", type=float, default=None,
                   help="kill one replica this many seconds into the "
                        "run (needs --replicas >= 2)")
    p.add_argument("--chaos", choices=sorted(CHAOS_SCENARIOS),
                   default=None,
                   help="run one chaos scenario instead of a plain "
                        "load run (arms brownout + hedging)")
    p.add_argument("--stall", type=float, default=0.05,
                   help="slow-replica scenario stall seconds")
    p.add_argument("--seed", type=int, default=0)
    args = p.parse_args(argv)
    if args.kill_after is not None and args.replicas < 2:
        p.error("--kill-after needs --replicas >= 2")

    from raft_tpu.serve import (BatchPolicy, BrownoutController,
                                Executor, HedgePolicy, KnnService,
                                QosPolicy, ReplicaGroup, TenantPolicy,
                                knn_ladder)
    from raft_tpu.serve.queue import bucket_ladder

    rng = np.random.default_rng(args.seed)
    db = rng.standard_normal((args.n_db, args.dim)).astype(np.float32)
    op = f"knn_k{args.k}_{args.metric}"

    def make_executor(*, with_brownout: bool = False, faults=None):
        qos = None
        if args.slo_ms is not None:
            qos = QosPolicy({"default": TenantPolicy(
                slo_latency_s=args.slo_ms * 1e-3)})
        brown = None
        if with_brownout:
            ks = sorted({args.k, max(args.k // 2, 1),
                         max(args.k // 4, 1)}, reverse=True)
            brown = BrownoutController(
                [knn_ladder(db, ks, metric=args.metric)], qos=qos)
        ex = Executor([KnnService(db, k=args.k, metric=args.metric)],
                      policy=BatchPolicy(max_batch=256, max_wait_ms=2.0),
                      qos=qos, brownout=brown, faults=faults)
        ex.warm(bucket_ladder(256))
        return ex

    common = dict(clients=args.clients, rows=args.rows,
                  duration_s=args.duration, deadline_s=args.deadline,
                  seed=args.seed)
    if args.chaos is not None:
        import json as _json

        from raft_tpu.comms.faults import FaultInjector

        kw = dict(rows=args.rows, phase_s=args.duration,
                  deadline_s=args.deadline, seed=args.seed)
        if args.chaos in ("traffic_step", "hog_tenant"):
            if args.chaos == "traffic_step":
                kw["base_qps"] = args.rate_qps
            ex = make_executor(with_brownout=True)
            with ex:
                report = run_chaos(args.chaos, ex, op, **kw)
        else:
            n = max(args.replicas, 2)
            group = ReplicaGroup(
                [make_executor(faults=FaultInjector(seed=args.seed))
                 for _ in range(n)],
                hedge=HedgePolicy())
            kw["clients"] = args.clients
            if args.chaos == "slow_replica":
                kw["stall_s"] = args.stall
            with group:
                report = run_chaos(args.chaos, group, op, **kw)
        print(_json.dumps(report.as_dict(), indent=2, sort_keys=True))
        return 0
    if args.replicas > 1:
        group = ReplicaGroup([make_executor()
                              for _ in range(args.replicas)])
        with group:
            report = fleet_closed_loop(group, op,
                                       kill_after_s=args.kill_after,
                                       **common)
    else:
        ex = make_executor()
        with ex:
            if args.mode == "open":
                common.pop("clients")
                report = open_loop(ex, op, rate_qps=args.rate_qps,
                                   **common)
            else:
                report = closed_loop(ex, op, **common)
    print(json.dumps(report.as_dict(), indent=2, sort_keys=True))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
