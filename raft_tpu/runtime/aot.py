"""AOT export/import of compiled computations (ref: the explicit template
instantiation machinery — util/raft_explicit.hpp, cpp/src/*.cu TUs,
developer_guide.md:301-323 — whose purpose is "pay compilation once,
ship a callable artifact").

`jax.export` serializes a jitted function as versioned StableHLO with
embedded calling conventions; `deserialize(...).call` runs it with no
Python retracing. Artifacts are portable across processes and across
compatible jax versions, and may target multiple platforms at once
(`platforms=("tpu", "cpu")`).
"""

from __future__ import annotations

import os
from typing import Callable, Optional, Sequence

import jax
from jax import export as _jexport


def aot_export(fn: Callable, *example_args,
               platforms: Optional[Sequence[str]] = None,
               **jit_kwargs):
    """Trace + lower ``fn`` at the example arguments' shapes/dtypes.

    Returns a `jax.export.Exported`; use :func:`serialize_computation` /
    :func:`save_computation` to persist it. ``platforms`` defaults to the
    current backend (pass ``("tpu", "cpu")`` for a dual-target artifact).
    """
    jfn = fn if isinstance(fn, jax.stages.Wrapped) \
        else jax.jit(fn, **jit_kwargs)
    if platforms is not None:
        return _jexport.export(jfn, platforms=tuple(platforms))(
            *example_args)
    return _jexport.export(jfn)(*example_args)


def serialize_computation(exported) -> bytes:
    """Exported → portable bytes (versioned StableHLO artifact)."""
    return bytes(exported.serialize())


def deserialize_computation(blob: bytes) -> Callable:
    """Bytes → callable running the compiled computation (no retracing).

    The callable validates shapes/dtypes against the export-time
    signature, exactly as the reference's instantiated symbols fix their
    template parameters.
    """
    exp = _jexport.deserialize(bytearray(blob))
    return exp.call


def save_computation(exported, path: str) -> None:
    tmp = f"{path}.tmp{os.getpid()}"
    with open(tmp, "wb") as f:
        f.write(serialize_computation(exported))
    os.replace(tmp, path)


def load_computation(path: str) -> Callable:
    with open(path, "rb") as f:
        return deserialize_computation(f.read())
