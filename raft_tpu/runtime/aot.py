"""AOT export/import of compiled computations (ref: the explicit template
instantiation machinery — util/raft_explicit.hpp, cpp/src/*.cu TUs,
developer_guide.md:301-323 — whose purpose is "pay compilation once,
ship a callable artifact").

`jax.export` serializes a jitted function as versioned StableHLO with
embedded calling conventions; `deserialize(...).call` runs it with no
Python retracing. Artifacts are portable across processes and across
compatible jax versions, and may target multiple platforms at once
(`platforms=("tpu", "cpu")`).

Integrity (ISSUE 3): :func:`save_computation` writes a ``<path>.sha256``
sidecar next to the artifact; :func:`load_computation` verifies it when
present and wraps truncation/bit-rot/deserialize failures in
:class:`~raft_tpu.core.guards.ArtifactCorruptError` naming the path —
a corrupt compiled program must never be half-loaded into the runtime.
"""

from __future__ import annotations

import hashlib
import os
import time
from typing import Callable, Optional, Sequence

import jax
from jax import export as _jexport

from raft_tpu import obs
from raft_tpu.core.guards import ArtifactCorruptError


def aot_export(fn: Callable, *example_args,
               platforms: Optional[Sequence[str]] = None,
               **jit_kwargs):
    """Trace + lower ``fn`` at the example arguments' shapes/dtypes.

    Returns a `jax.export.Exported`; use :func:`serialize_computation` /
    :func:`save_computation` to persist it. ``platforms`` defaults to the
    current backend (pass ``("tpu", "cpu")`` for a dual-target artifact).
    """
    jfn = fn if isinstance(fn, jax.stages.Wrapped) \
        else jax.jit(fn, **jit_kwargs)
    t0 = time.monotonic()
    if platforms is not None:
        exported = _jexport.export(jfn, platforms=tuple(platforms))(
            *example_args)
    else:
        exported = _jexport.export(jfn)(*example_args)
    obs.observe("runtime_compile_seconds", time.monotonic() - t0,
                what="aot_export")
    return exported


def serialize_computation(exported) -> bytes:
    """Exported → portable bytes (versioned StableHLO artifact)."""
    return bytes(exported.serialize())


def deserialize_computation(blob: bytes) -> Callable:
    """Bytes → callable running the compiled computation (no retracing).

    The callable validates shapes/dtypes against the export-time
    signature, exactly as the reference's instantiated symbols fix their
    template parameters.
    """
    exp = _jexport.deserialize(bytearray(blob))
    return exp.call


def _sidecar(path: str) -> str:
    return f"{path}.sha256"


def save_computation(exported, path: str) -> None:
    """Persist an Exported atomically (tmp + rename) with a sha256
    sidecar for load-time integrity verification."""
    blob = serialize_computation(exported)
    obs.inc("runtime_artifact_bytes_written_total", len(blob))
    digest = hashlib.sha256(blob).hexdigest()
    tmp = f"{path}.tmp{os.getpid()}"
    with open(tmp, "wb") as f:
        f.write(blob)
    os.replace(tmp, path)
    tmp = f"{_sidecar(path)}.tmp{os.getpid()}"
    with open(tmp, "w") as f:
        f.write(f"{digest}\n")
    os.replace(tmp, _sidecar(path))


def load_computation(path: str) -> Callable:
    """Load + verify a persisted computation.

    Raises :class:`~raft_tpu.core.guards.ArtifactCorruptError` when the
    sha256 sidecar (if present) does not match the artifact bytes, or
    when deserialization rejects them (truncation, bit flips). Artifacts
    saved without a sidecar (pre-guardrails) still load; the deserialize
    failure wrapping applies either way."""
    with open(path, "rb") as f:
        blob = f.read()
    sidecar = _sidecar(path)
    if os.path.exists(sidecar):
        with open(sidecar) as f:
            want = f.read().strip()
        got = hashlib.sha256(blob).hexdigest()
        if got != want:
            obs.inc("runtime_artifact_corrupt_total", 1, check="sha256")
            raise ArtifactCorruptError(
                f"compiled artifact {path!r} failed its sha256 integrity "
                f"check (sidecar {sidecar!r}: expected {want}, got {got}) "
                "— the file was truncated or corrupted on disk; re-export "
                "the computation", path=path)
    try:
        return deserialize_computation(blob)
    except ArtifactCorruptError:
        raise
    except Exception as e:
        obs.inc("runtime_artifact_corrupt_total", 1, check="deserialize")
        raise ArtifactCorruptError(
            f"compiled artifact {path!r} failed to deserialize "
            f"({type(e).__name__}: {e}); the file is corrupt or was "
            "produced by an incompatible serialization version — "
            "re-export the computation", path=path) from e
