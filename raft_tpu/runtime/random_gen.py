"""raft::runtime::random parity (ref:
raft_runtime/random/rmat_rectangular_generator.hpp:22
`rmat_rectangular_gen`, instantiated for {int, int64_t} × {float, double}
theta by cpp/CMakeLists.txt:277-280).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax.numpy as jnp
import numpy as np

from raft_tpu.random.rmat import rmat_rectangular_gen as _rmat
from raft_tpu.random.rng_state import RngState

_INDEX_TYPES = (np.int32, np.int64)


def rmat_rectangular_gen(handle, state: RngState, theta, r_scale: int,
                         c_scale: int, n_edges: int,
                         out_dtype=np.int32
                         ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Host-callable R-MAT edge generator over a per-level theta table
    (ref call shape: rmat_rectangular_gen(handle, rng, theta, out,
    r_scale, c_scale) — the out buffer becomes a returned (src, dst))."""
    if np.dtype(out_dtype).type not in _INDEX_TYPES:
        raise TypeError(
            f"index dtype must be one of {_INDEX_TYPES}, got {out_dtype} "
            f"(the reference instantiates exactly these)")
    theta = None if theta is None else np.asarray(theta, np.float32)
    return _rmat(handle, state, r_scale=r_scale, c_scale=c_scale,
                 n_edges=n_edges, theta=theta, dtype=jnp.dtype(out_dtype))
