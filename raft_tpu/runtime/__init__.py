"""Runtime instantiation layer (ref: cpp/include/raft_runtime/ + cpp/src/
— the pre-instantiated, host-callable API surface compiled into
`libraft.so`, usable without a CUDA compiler; SURVEY.md §2.11).

The TPU translation has two halves:

- **AOT export** (:mod:`raft_tpu.runtime.aot`): where the reference
  pre-instantiates templates into `.cu` TUs (explicit-instantiation
  discipline, util/raft_explicit.hpp), the XLA equivalent is
  ahead-of-time serialization: `jax.export` lowers a jitted function to
  versioned StableHLO that loads and runs WITHOUT retracing Python — the
  artifact a deployment ships instead of source + trace time.
- **Instantiated entry points** (:mod:`solver`, :mod:`random_gen`): the
  concrete functions the reference exposes from libraft.so —
  `raft::runtime::solver::lanczos_solver` (raft_runtime/solver/lanczos.hpp:23)
  and `raft::runtime::random::rmat_rectangular_gen`
  (raft_runtime/random/rmat_rectangular_generator.hpp:22) — with the same
  {float}×{index-type} instantiation matrix made explicit.
"""

from raft_tpu.runtime.aot import (aot_export, deserialize_computation,
                                  load_computation, save_computation,
                                  serialize_computation)
from raft_tpu.runtime import limits, random_gen, solver
from raft_tpu.runtime import compiled_driver

__all__ = [
    "aot_export", "serialize_computation", "deserialize_computation",
    "save_computation", "load_computation", "solver", "random_gen",
    "limits", "compiled_driver",
]
