"""raft::runtime::solver parity (ref: raft_runtime/solver/lanczos.hpp:23
`lanczos_solver`, instantiated for {int, int64_t} × {float, double} by
cpp/src/raft_runtime/solver/lanczos_solver.cuh:10-24 macro FUNC_DEF into
four .cu TUs, cpp/CMakeLists.txt:281-284).

The instantiation matrix is explicit here too: index dtype ∈ {int32,
int64}, value dtype ∈ {float32, float64} — anything else is rejected at
the boundary, mirroring the reference's fixed symbol set rather than
silently tracing a new variant.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax.numpy as jnp
import numpy as np

from raft_tpu.core.sparse_types import CSRMatrix
from raft_tpu.sparse.solver.lanczos import (LanczosConfig,
                                            lanczos_compute_eigenpairs)

_INDEX_TYPES = (np.int32, np.int64)
_VALUE_TYPES = (np.float32, np.float64)


def lanczos_solver(handle, config: LanczosConfig, rows, cols, vals,
                   v0: Optional[np.ndarray] = None,
                   n: Optional[int] = None
                   ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Host-callable thick-restart Lanczos over raw CSR buffers
    (ref signature: lanczos_solver(res, config, rows, cols, vals, v0,
    eigenvalues, eigenvectors) — outputs returned rather than written).

    ``rows`` is the CSR indptr (len n+1), ``cols``/``vals`` the column
    indices and values.
    """
    rows = np.asarray(rows)
    cols = np.asarray(cols)
    vals = np.asarray(vals)
    if rows.dtype.type not in _INDEX_TYPES or \
            cols.dtype.type not in _INDEX_TYPES:
        raise TypeError(
            f"index dtype must be one of {_INDEX_TYPES}, got "
            f"{rows.dtype}/{cols.dtype} (the reference instantiates "
            f"exactly these, lanczos_solver.cuh:10-24)")
    if vals.dtype.type not in _VALUE_TYPES:
        raise TypeError(
            f"value dtype must be one of {_VALUE_TYPES}, got {vals.dtype}")
    nn = int(n if n is not None else rows.shape[0] - 1)
    csr = CSRMatrix(jnp.asarray(rows), jnp.asarray(cols),
                    jnp.asarray(vals), (nn, nn))
    return lanczos_compute_eigenpairs(handle, csr, config, v0)
