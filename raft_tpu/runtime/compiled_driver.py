"""Compiled solver inner loops with donated carries (ROADMAP item 4).

Every iterative solver in this repo was host-driven: one jitted step per
iteration, paying a dispatch + device→host readback + deadline-poll
round-trip each time (~70 ms tunnel RTT on the remote-dispatch TPU setup
vs ~12 ms of device work at the BASELINE kmeans shape). The exemplar
repos are all pjit-shaped — compile the whole sharded computation once,
donate the carry, let XLA schedule the ICI collectives.

This module is the shared chunk-runner both solver families wire into:

- :func:`chunk_while` — the in-graph half: up to ``steps`` iterations of
  a ``step_fn(carry) -> (carry, done)`` body inside ONE
  ``lax.while_loop`` with an early-exit flag, embeddable inside ``jit``
  or ``shard_map`` bodies (the caller owns compilation and donation, so
  the MNMG paths can fuse their per-iteration ``lax.psum`` epilogues
  into the same program).
- :func:`run_chunked` — the host half: drives a compiled chunk program
  until convergence or a step budget, touching the host ONCE per chunk.
  Every host-side robustness hook moves to the chunk boundary: the
  deadline poll, the checkpoint/health ``boundary`` callback (fired
  BEFORE the poll, so an expiring budget always leaves a resumable
  checkpoint behind), and the guard-mode ``sentinel``. Each boundary
  records a span, bumps ``solver_host_syncs_total`` and emits a
  ``compiled_driver.chunk`` trace event — the always-on signal CI uses
  to catch a reintroduced per-iteration ``block_until_ready``.
- :func:`default_sync_every` / :func:`resolve_sync_every` — the cost
  model for the chunk length: 1 on CPU (host dispatch is cheap there,
  and 1 routes callers through their unchanged host-driven path
  bit-for-bit), 8–16 on an accelerator sized so the per-chunk
  dispatch+readback overhead stays under ~5% of device work.
"""

from __future__ import annotations

import math
import time
import warnings
from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from raft_tpu import obs
from raft_tpu.core import trace
from raft_tpu.core.guards import resolve_guard_mode
from raft_tpu.runtime import limits

# Donation is a no-op on backends without buffer aliasing (CPU); the
# resulting "Some donated buffers were not usable" UserWarning is noise
# for the virtual-device test meshes, not a correctness signal.
warnings.filterwarnings(
    "ignore", message="Some donated buffers were not usable")

# Nominal per-launch host overhead (dispatch + small-scalar readback) by
# backend, seconds. The tpu figure is the measured tunnel RTT the bench
# harness documents (benches/harness.py::_sync); it only seeds the cost
# model — run_chunked refines with measured per-chunk wall time.
DISPATCH_OVERHEAD_S = {"tpu": 0.070, "gpu": 5e-4, "cpu": 5e-5}

# Accelerator chunk-length clamp: below 8 the per-chunk overhead still
# shows at the north-star shape; above 16 a converged fit wastes up to a
# chunk of dead iterations and deadline granularity degrades.
SYNC_EVERY_LO = 8
SYNC_EVERY_HI = 16

# Fraction of a chunk's device time the per-chunk host overhead is
# allowed to cost before the cost model grows the chunk.
_OVERHEAD_FRAC = 0.05


def host_float_dtype():
    """Accumulator dtype for in-graph convergence scalars: float64 when
    x64 is enabled (matches the host loops' Python-float math bit-for-
    bit in the test meshes), float32 otherwise (TPU f64 is emulated)."""
    return jnp.float64 if jax.config.jax_enable_x64 else jnp.float32


def default_sync_every(*, step_seconds: Optional[float] = None,
                       backend: Optional[str] = None) -> int:
    """Pick a chunk length. CPU → 1 (callers route through their
    host-driven path unchanged). Accelerators → the smallest chunk that
    keeps the per-launch overhead under ~5% of device work, clamped to
    [8, 16]; with no step estimate, the top of the clamp."""
    backend = backend or jax.default_backend()
    if backend == "cpu":
        return 1
    overhead = DISPATCH_OVERHEAD_S.get(backend, 1e-3)
    if step_seconds is None or step_seconds <= 0.0:
        return SYNC_EVERY_HI
    n = math.ceil(overhead / (_OVERHEAD_FRAC * step_seconds))
    return max(SYNC_EVERY_LO, min(SYNC_EVERY_HI, n))


def resolve_sync_every(sync_every: Optional[int], *,
                       step_seconds: Optional[float] = None,
                       backend: Optional[str] = None) -> int:
    """Validate an explicit ``sync_every`` or fall back to the cost
    model. Every chunked entry point funnels through here so the
    default policy has one spelling."""
    if sync_every is None:
        return default_sync_every(step_seconds=step_seconds,
                                  backend=backend)
    n = int(sync_every)
    if n < 1:
        raise ValueError(f"sync_every must be >= 1, got {sync_every}")
    return n


def chunk_while(step_fn: Callable[[Any], Tuple[Any, Any]], carry,
                steps) -> Tuple[Any, Any, Any]:
    """Run up to ``steps`` iterations of ``step_fn`` in-graph.

    ``step_fn(carry) -> (carry, done)``; the loop exits early once
    ``done`` goes true, so a converged chunk stops doing work instead of
    burning its remaining iterations. ``steps`` is a TRACED int32 — one
    executable serves full chunks and the tail chunk alike. Returns
    ``(carry, ran, done)`` with ``ran`` the number of body executions.

    This is the in-graph half only: callers wrap it in ``jax.jit``
    (donating the carry) or embed it inside a ``shard_map`` body so the
    per-iteration collectives fuse into the same program.
    """
    def cond(state):
        i, _, done = state
        return jnp.logical_and(i < steps, jnp.logical_not(done))

    def body(state):
        i, carry, _ = state
        carry, done = step_fn(carry)
        return i + 1, carry, done

    init = (jnp.zeros((), jnp.int32), carry, jnp.zeros((), jnp.bool_))
    ran, carry, done = lax.while_loop(cond, body, init)
    return carry, ran, done


def run_chunked(chunk_call: Callable, carry, *, max_steps: int,
                sync_every: int, op: str, steps_done: int = 0,
                est_step_seconds: Optional[float] = None,
                step_flops: Optional[float] = None,
                step_bytes: Optional[float] = None,
                boundary: Optional[Callable] = None,
                sentinel: Optional[Callable] = None):
    """Drive a compiled chunk program to convergence or ``max_steps``.

    ``chunk_call(carry, steps) -> (carry, ran, done)`` is the caller's
    jitted chunk (typically :func:`chunk_while` under ``jit`` or
    ``shard_map``); ``ran``/``done`` are device scalars and fetching
    them is THE host sync of the chunk. Per boundary, in order:

    1. ``boundary(carry, steps_done, done)`` — checkpoint then health
       probe, exactly the host-loop ordering: the checkpoint lands
       before anything below can raise, so deadline expiry and peer
       failure both leave a resumable file.
    2. ``limits.check_deadline(op)`` — the deadline poll.
    3. ``limits.check_chunk_budget`` — fast-fail BEFORE launching a
       chunk whose estimated cost exceeds the remaining slack
       (``est_step_seconds`` seeds the estimate; measured per-chunk
       wall time refines it), so ``sync_every > 1`` cannot blow a
       deadline by a whole chunk.
    4. launch, under an obs span; then ``solver_host_syncs_total``,
       the ``compiled_driver.chunk`` trace event, and the
       ``deadline_slack_seconds`` histogram.
    5. ``sentinel(carry, steps_done)`` — guard-mode numeric check,
       invoked only when guards are armed (the off mode costs nothing).

    With ``RAFT_TPU_PERF=on`` and per-step model costs (``step_flops``
    / ``step_bytes`` — the (flops, bytes) pair behind the same
    ``limits.estimate_seconds`` call that seeded ``est_step_seconds``),
    every chunk's measured wall time additionally feeds the roofline
    attribution under the ``(op, "chunk")`` profile key, and the live
    HBM watermark is polled at each boundary. Off (the default) both
    are single-bool no-ops.

    Returns ``(carry, steps_done, done)``. ``steps_done`` starts at the
    caller's offset so a resumed fit keeps global iteration counts.
    """
    if step_flops or step_bytes:
        obs.profile_executable(op, "chunk",
                               model_flops=step_flops or 0.0,
                               model_bytes=step_bytes or 0.0)
    done = False
    per_step = est_step_seconds
    while True:
        if boundary is not None:
            boundary(carry, steps_done, done)
        limits.check_deadline(op)
        if done or steps_done >= max_steps:
            return carry, steps_done, done
        n = min(int(sync_every), max_steps - steps_done)
        if per_step is not None and per_step > 0.0:
            limits.check_chunk_budget(op, per_step * n)
        t0 = time.monotonic()
        with obs.span(op + ".chunk", steps=n) as sp:
            carry, ran_d, done_d = chunk_call(
                carry, jnp.asarray(n, jnp.int32))
            ran = int(ran_d)          # the chunk's single host sync
            done = bool(done_d)
            # device-wall attrs for the chrome-trace async lane (the
            # span's own duration is host wall across the sync)
            sp.set_attr(ran=ran,
                        wall_s=round(time.monotonic() - t0, 6))
        wall = time.monotonic() - t0
        steps_done += ran
        if ran > 0:
            per_step = wall / ran     # measured refinement of the model
            obs.record_launch(op, "chunk", wall, steps=ran)
        obs.record_hbm_watermark()
        obs.inc("solver_host_syncs_total", 1, op=op)
        trace.record_event("compiled_driver.chunk", op=op, steps=ran,
                           done=bool(done))
        rem = limits.remaining()
        if rem is not None and obs.enabled():
            obs.observe("deadline_slack_seconds", max(rem, 0.0),
                        help="time left on the binding deadline at a "
                             "compiled-chunk boundary (seconds)")
        if sentinel is not None and resolve_guard_mode() != "off":
            sentinel(carry, steps_done)
