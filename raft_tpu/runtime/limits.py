"""Workload limits: deadlines, budget-aware admission, and OOM-safe
degraded execution (the request-level contract layered over the
elastic/guarded core; ref: core/interruptible.hpp and the mr/ resource
layer — ``interruptible::synchronize`` bounds *time*, the limiting
resource adaptors bound *memory*; this module grows both into a serving
contract: every call finishes, fails typed before its deadline, or is
refused up front).

Three cooperating pieces:

``Deadline`` / :func:`deadline_scope`
    An absolute-time budget carried in a thread-local scope (the same
    scope idiom as ``core/guards.py``). Host-driver loops poll
    :func:`check_deadline` at their existing cancellation/checkpoint
    boundaries; the comms layer caps blocking-recv timeouts and retry
    backoff with :func:`remaining` so a deadline on rank 0 bounds the
    whole collective instead of racing a fixed ``default_recv_timeout``.

``WorkBudget`` / :func:`budget_scope`
    An HBM-bytes admission limit, seeded from an explicit byte count,
    ``device_memory_stats()``, or the ``RAFT_TPU_HBM_BUDGET`` env var
    (malformed values raise at import — fail loud, never a silent
    fallback). Instrumented entry points (pairwise_distance, brute-force
    kNN, gemm, spmv) consult :func:`estimate_bytes` *before* launching:
    over-budget monolithic launches are never attempted — they degrade
    to a bit-equal row-tiled/streamed path or raise
    :class:`RejectedError` with the estimate attached.

``CircuitBreaker``
    N consecutive typed failures per op key → fast-fail with cooldown,
    protecting callers from retry storms against an op that keeps
    missing its deadline or budget.

Taxonomy (both ``RuntimeError`` subclasses, consistent with
``core/guards.py`` and ``comms/errors.py`` so pre-taxonomy ``except
RuntimeError`` callers keep working):

==========================  =============================================
type                        meaning
==========================  =============================================
``DeadlineExceededError``   the active :class:`Deadline` expired before
                            the op finished (typed, never a hang)
``RejectedError``           the op was refused up front — over budget
                            even tiled (``reason='over_budget'``) or the
                            circuit breaker is open
                            (``reason='breaker_open'``); carries the
                            byte ``estimate`` when known
==========================  =============================================

With **no limits scope active** (no deadline, no budget — the default),
every instrumented op takes its exact pre-limits code path: the fast
path pays one thread-local read and nothing else, and outputs are
bit-identical to the un-instrumented library.

Observability (through the ``obs`` facade only):
``limits_deadline_exceeded_total{op}``, ``limits_rejected_total{reason,
op}``, ``limits_degraded_total{op}``, ``limits_breaker_state{op}``
(0 closed / 1 open), and a ``deadline_slack_seconds`` histogram
(time left when a deadline scope exits cleanly — the headroom a
latency SLO actually has).
"""

from __future__ import annotations

import collections as _collections
import contextlib
import threading
import time
from typing import Dict, Optional

from raft_tpu import obs
from raft_tpu.core import env as _env_mod
from raft_tpu.core import hw as _hw

__all__ = [
    "DeadlineExceededError", "RejectedError",
    "Deadline", "deadline_scope", "current_deadline", "remaining",
    "check_deadline", "sleep_within_deadline",
    "WorkBudget", "budget_scope", "active_budget", "set_default_budget",
    "parse_bytes", "estimate_bytes", "admit", "reject", "record_degraded",
    "estimate_seconds", "estimate_flops_bytes", "check_chunk_budget",
    "CircuitBreaker", "get_breaker", "reset_breakers",
    "RateBudget",
]

# breaker policy: consecutive typed failures before opening, and how
# long an open breaker fast-fails before allowing a half-open probe
BREAKER_THRESHOLD = 5
BREAKER_COOLDOWN_S = 30.0


# ---------------------------------------------------------------------------
# taxonomy
# ---------------------------------------------------------------------------

class DeadlineExceededError(RuntimeError):
    """The active :class:`Deadline` expired before the operation
    finished.

    Parameters
    ----------
    message : human-readable description (always names the operation).
    op : dotted name of the operation that observed the expiry.
    budget_s : the scope's original time budget in seconds.
    """

    def __init__(self, message: str, *, op: Optional[str] = None,
                 budget_s: Optional[float] = None):
        super().__init__(message)
        self.op = op
        self.budget_s = budget_s


class RejectedError(RuntimeError):
    """The operation was refused up front — admission control, not a
    mid-flight failure.

    ``reason`` is ``'over_budget'`` (the footprint estimate exceeds the
    active :class:`WorkBudget` even for the tiled path) or
    ``'breaker_open'`` (the op's circuit breaker is fast-failing).
    ``estimate`` / ``budget`` carry the byte counts when known, so the
    caller can shrink the request instead of blind-retrying."""

    def __init__(self, message: str, *, op: Optional[str] = None,
                 estimate: Optional[int] = None,
                 budget: Optional[int] = None,
                 reason: str = "over_budget"):
        super().__init__(message)
        self.op = op
        self.estimate = estimate
        self.budget = budget
        self.reason = reason


# ---------------------------------------------------------------------------
# deadlines
# ---------------------------------------------------------------------------

class Deadline:
    """An absolute-time budget on the monotonic clock.

    Created with a relative budget in seconds; queried as
    :meth:`remaining`. Instances are immutable facts about wall time —
    scoping and nesting live in :func:`deadline_scope`."""

    __slots__ = ("budget_s", "expires_at", "_ops")

    def __init__(self, seconds: float):
        seconds = float(seconds)
        if not seconds >= 0.0:
            raise ValueError(
                f"deadline budget must be >= 0 seconds, got {seconds!r}")
        self.budget_s = seconds
        self.expires_at = time.monotonic() + seconds
        # op keys that polled this deadline — a clean scope exit counts
        # as a breaker success for each of them
        self._ops: set = set()

    def remaining(self) -> float:
        """Seconds left (negative once expired)."""
        return self.expires_at - time.monotonic()

    def expired(self) -> bool:
        return self.remaining() <= 0.0


_tls = threading.local()


def _deadline_stack():
    if not hasattr(_tls, "deadlines"):
        _tls.deadlines = []
    return _tls.deadlines


def _budget_stack():
    if not hasattr(_tls, "budgets"):
        _tls.budgets = []
    return _tls.budgets


def current_deadline() -> Optional[Deadline]:
    """The binding deadline: of every scope on this thread's stack, the
    one that expires first (a nested scope can tighten the budget but
    never extend past an enclosing one). None when no scope is active —
    the caller's fast path."""
    st = _deadline_stack()
    if not st:
        return None
    return min(st, key=lambda d: d.expires_at)


def remaining(default: Optional[float] = None) -> Optional[float]:
    """Seconds left on the binding deadline, or ``default`` when no
    deadline scope is active. The comms layer uses this to cap recv
    timeouts and retry backoff."""
    d = current_deadline()
    return default if d is None else d.remaining()


@contextlib.contextmanager
def deadline_scope(seconds: float):
    """Thread-local deadline for a region.

    Everything under the scope — solver host loops, blocking recvs,
    retry backoff — observes the budget through
    :func:`check_deadline` / :func:`remaining`. On a clean exit the
    remaining slack is recorded in the ``deadline_slack_seconds``
    histogram and the breakers of every op polled under the scope see a
    success."""
    d = Deadline(seconds)
    _deadline_stack().append(d)
    try:
        yield d
    except BaseException:
        _deadline_stack().pop()
        raise
    else:
        _deadline_stack().pop()
        if obs.enabled():
            obs.observe("deadline_slack_seconds", max(d.remaining(), 0.0),
                        help="time left when a deadline scope exits "
                             "cleanly (seconds)")
        for op in d._ops:
            get_breaker(op).record_success()


def check_deadline(op: str) -> None:
    """The deadline poll: no-op (one thread-local read) when no scope is
    active; raises :class:`DeadlineExceededError` once the binding
    deadline expires, and :class:`RejectedError` (``breaker_open``) when
    ``op``'s breaker is fast-failing.

    Rides the same host-sync boundaries as ``CancelToken.check()`` —
    solvers call it where they already poll for cancellation,
    checkpoints, or peer health."""
    d = current_deadline()
    if d is None:
        return
    br = get_breaker(op)
    if not br.allow():
        obs.inc("limits_rejected_total", 1, reason="breaker_open", op=op)
        exc = RejectedError(
            f"{op}: circuit breaker open after "
            f"{br.threshold} consecutive typed failures "
            f"(cooldown {br.cooldown_s:g}s) — fast-failing instead of "
            "burning the deadline", op=op, reason="breaker_open")
        obs.record_failure(exc)
        raise exc
    d._ops.add(op)
    rem = d.remaining()
    if rem <= 0.0:
        br.record_failure()
        obs.inc("limits_deadline_exceeded_total", 1, op=op)
        exc = DeadlineExceededError(
            f"{op}: deadline exceeded ({d.budget_s:g}s budget, "
            f"{-rem:.3f}s over)", op=op, budget_s=d.budget_s)
        obs.record_failure(exc)
        raise exc


def sleep_within_deadline(seconds: float, *, op: str = "sleep") -> None:
    """``time.sleep`` that honors the active deadline scope.

    With no scope active it is exactly ``time.sleep(seconds)``. Under a
    scope it sleeps in short slices and raises
    :class:`DeadlineExceededError` the moment the deadline expires —
    so a fault-injected stall (or any long backoff) cannot hold a
    sender past its budget."""
    if current_deadline() is None:
        time.sleep(seconds)
        return
    end = time.monotonic() + float(seconds)
    while True:
        check_deadline(op)
        rem = end - time.monotonic()
        if rem <= 0.0:
            return
        time.sleep(min(rem, 0.05))


# ---------------------------------------------------------------------------
# work budgets (HBM admission)
# ---------------------------------------------------------------------------

# parse_bytes moved to core/env.py (the knob-registry home of every
# RAFT_TPU_* parser); re-exported here because it has been limits' public
# API since PR 5.
parse_bytes = _env_mod.parse_bytes


class WorkBudget:
    """An HBM-bytes admission limit.

    Holds a single number — the largest transient working set an
    instrumented op may plan for. Seed it explicitly, from the env
    (``RAFT_TPU_HBM_BUDGET``), or from live device telemetry via
    :meth:`from_device`."""

    __slots__ = ("limit_bytes",)

    def __init__(self, limit_bytes: int):
        limit_bytes = int(limit_bytes)
        if limit_bytes <= 0:
            raise ValueError(
                f"budget must be a positive byte count, got {limit_bytes}")
        self.limit_bytes = limit_bytes

    @classmethod
    def from_device(cls, device=None, *,
                    fraction: float = 0.9) -> "WorkBudget":
        """Seed from ``device_memory_stats()``: ``fraction`` of the
        bytes not currently in use. Raises ``RuntimeError`` when the
        backend reports no memory limit (host CPU test backends) —
        pass an explicit byte count there instead."""
        from raft_tpu.core.memory import device_memory_stats

        stats = device_memory_stats(device)
        limit = int(stats.get("bytes_limit", 0) or 0)
        if limit <= 0:
            raise RuntimeError(
                "device reports no memory limit; seed WorkBudget with an "
                "explicit byte count or RAFT_TPU_HBM_BUDGET")
        free = limit - int(stats.get("bytes_in_use", 0) or 0)
        return cls(max(int(free * float(fraction)), 1))

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"WorkBudget(limit_bytes={self.limit_bytes})"


# process-global default budget, seeded from the env at import. A
# malformed value raises HERE (import time) — loud, immediate, and
# impossible to mistake for "unlimited".
_env_budget = _env_mod.read("RAFT_TPU_HBM_BUDGET")
_default_budget: Optional[WorkBudget] = (
    WorkBudget(parse_bytes(_env_budget, name="RAFT_TPU_HBM_BUDGET"))
    if _env_budget is not None and _env_budget.strip() != "" else None)


def set_default_budget(budget) -> Optional[WorkBudget]:
    """Set (or clear, with None) the process-wide admission budget —
    the programmatic twin of ``RAFT_TPU_HBM_BUDGET``. Accepts a
    :class:`WorkBudget` or a byte count. Returns the previous value."""
    global _default_budget
    prev = _default_budget
    if budget is None:
        _default_budget = None
    elif isinstance(budget, WorkBudget):
        _default_budget = budget
    else:
        _default_budget = WorkBudget(budget)
    return prev


def active_budget() -> Optional[WorkBudget]:
    """The binding budget: of every scope on this thread's stack the
    smallest limit, else the process-wide default (env-seeded), else
    None — in which case admission is disabled and instrumented ops run
    their exact pre-limits path."""
    st = _budget_stack()
    if st:
        return min(st, key=lambda b: b.limit_bytes)
    return _default_budget


@contextlib.contextmanager
def budget_scope(budget):
    """Thread-local admission budget for a region. Accepts a
    :class:`WorkBudget`, a byte count, or None to seed from the current
    device's live memory telemetry (:meth:`WorkBudget.from_device`)."""
    if budget is None:
        b = WorkBudget.from_device()
    elif isinstance(budget, WorkBudget):
        b = budget
    else:
        b = WorkBudget(budget)
    _budget_stack().append(b)
    try:
        yield b
    finally:
        _budget_stack().pop()


# ---------------------------------------------------------------------------
# footprint estimation + admission
# ---------------------------------------------------------------------------

def _est_pairwise(*, m, n, k, itemsize):
    # both operands resident + the full m×n output block
    return (m * k + n * k + m * n) * itemsize


def _est_knn(*, n_queries, n_db, n_dims, k, itemsize,
             dist_itemsize=4):
    # operands + the monolithic q×n f32 distance block the fused/chunked
    # paths would otherwise materialize per launch, + top-k outputs
    return ((n_queries * n_dims + n_db * n_dims) * itemsize
            + n_queries * n_db * dist_itemsize
            + n_queries * k * (dist_itemsize + 4))


def _est_ivf_search(*, n_queries, probe_rows, n_dims, k, itemsize,
                    packed_rows=0, dist_itemsize=4):
    # resident packed lists + queries + the gathered q×probe_rows
    # candidate tile (rows, fine-distance block, ids, valid mask) the
    # probe scan materializes per launch, + top-k outputs
    return ((packed_rows * n_dims + n_queries * n_dims) * itemsize
            + n_queries * probe_rows
            * (n_dims * itemsize + dist_itemsize + 4 + 1)
            + n_queries * k * (dist_itemsize + 4))


def _est_ivf_mnmg_search(*, n_queries, probe_rows, n_dims, k, n_ranks,
                         itemsize, packed_rows=0, dist_itemsize=4):
    # the sharded search is SPMD: each device holds its own packed shard
    # (packed_rows = per-rank rows) and runs the same static-shape probe
    # scan as the single-rank path, plus the replicated all-gathered
    # [q, n_ranks*k] merge pool and the final top-k outputs — the
    # estimate bounds ONE device's footprint, which is what admission
    # protects
    return ((packed_rows * n_dims + n_queries * n_dims) * itemsize
            + n_queries * probe_rows
            * (n_dims * itemsize + dist_itemsize + 4 + 1)
            + n_queries * n_ranks * k * (dist_itemsize + 4)
            + n_queries * k * (dist_itemsize + 4))


def _est_ivf_pq_search(*, n_queries, nprobe, probe_rows, n_dims, k, m,
                       n_codes, itemsize=4, refine=0, packed_rows=0,
                       dist_itemsize=4):
    # resident packed codes (m bytes/row) + ids + queries, the
    # per-(query, probed-list) LUT block the ADC stage materializes,
    # the gathered code tile (codes, ADC score block, ids, valid mask),
    # the refine stage's raw-row candidate tile when armed, and the
    # top-k outputs
    rr = max(k, refine)
    est = (packed_rows * (m + 4) + n_queries * n_dims * itemsize
           + n_queries * nprobe * m * n_codes * dist_itemsize
           + n_queries * probe_rows * (m + dist_itemsize + 4 + 1)
           + n_queries * rr * (dist_itemsize + 4))
    if refine:
        est += n_queries * rr * (n_dims * itemsize + dist_itemsize)
    return est


def _est_streaming_compact(*, packed_rows, n_dims, itemsize,
                           id_itemsize=4):
    # the double-buffered repack: old packed matrix + ids resident
    # while the new buffer fills (bounded by the same capacity), plus
    # the coarse relabel pass's row reads — 2× packed arrays is the
    # honest peak the swap window holds
    return (2 * packed_rows * (n_dims * itemsize + id_itemsize)
            + packed_rows * n_dims * itemsize)


def _est_gemm(*, m, n, k, itemsize, out_itemsize=None):
    out_itemsize = itemsize if out_itemsize is None else out_itemsize
    return (m * k + k * n) * itemsize + m * n * out_itemsize


def _est_spmv(*, n_rows, n_cols, nnz, itemsize, index_itemsize=4):
    return (nnz * (itemsize + index_itemsize)
            + (n_cols + n_rows) * itemsize)


_ESTIMATORS = {
    "distance.pairwise_distance": _est_pairwise,
    "neighbors.brute_force_knn": _est_knn,
    "neighbors.ivf_search": _est_ivf_search,
    "neighbors.ivf_mnmg_search": _est_ivf_mnmg_search,
    "neighbors.ivf_pq_search": _est_ivf_pq_search,
    "neighbors.streaming_compact": _est_streaming_compact,
    "linalg.gemm": _est_gemm,
    "sparse.spmv": _est_spmv,
}


def estimate_bytes(op: str, **dims) -> int:
    """Per-op HBM footprint estimate for the *monolithic* launch, from
    static shapes only (never touches the device). Known ops:
    ``distance.pairwise_distance(m, n, k, itemsize)``,
    ``neighbors.brute_force_knn(n_queries, n_db, n_dims, k, itemsize)``,
    ``neighbors.ivf_search(n_queries, probe_rows, n_dims, k, itemsize[,
    packed_rows])``,
    ``neighbors.ivf_mnmg_search(n_queries, probe_rows, n_dims, k,
    n_ranks, itemsize[, packed_rows])``,
    ``neighbors.ivf_pq_search(n_queries, nprobe, probe_rows, n_dims,
    k, m, n_codes[, itemsize, refine, packed_rows])``,
    ``linalg.gemm(m, n, k, itemsize[, out_itemsize])``,
    ``sparse.spmv(n_rows, n_cols, nnz, itemsize[, index_itemsize])``."""
    try:
        fn = _ESTIMATORS[op]
    except KeyError:
        raise ValueError(
            f"no footprint estimator for op {op!r}; known: "
            f"{sorted(_ESTIMATORS)}") from None
    return int(fn(**dims))


# ---------------------------------------------------------------------------
# chunk-seconds estimation (the time twin of estimate_bytes, for the
# compiled-inner-loop driver's pre-launch deadline admission)
# ---------------------------------------------------------------------------

# Order-of-magnitude sustained throughput by backend: FLOP/s and HBM
# bytes/s. Intentionally coarse — these seed a FAST-FAIL decision (can
# this chunk possibly fit the remaining deadline slack?), never a
# measurement; run_chunked replaces the estimate with measured per-chunk
# wall time after the first launch. The tables live in core/hw.py
# (ISSUE 13) next to the theoretical-peak roofline table so the
# admission model and the roofline denominator can't drift apart
# silently; re-bound here because they have been limits' spelling since
# PR 5.
_PEAK_FLOP_S = _hw.SUSTAINED_FLOP_S
_PEAK_BYTES_S = _hw.SUSTAINED_BYTES_S


def _sec_lloyd_step(*, m, k, n_clusters, itemsize=4):
    # fused assignment+update: one [m,k]·[k,K] distance contraction plus
    # the one-hot [K,m]·[m,k] update, both MXU passes over X
    flops = 4.0 * m * k * n_clusters
    bytes_ = (m * k + 2.0 * n_clusters * k) * itemsize
    return flops, bytes_


def _sec_lanczos_restart(*, n, ncv, nnz, k=0, itemsize=4):
    # one thick restart: up to ncv extension steps of SpMV (2·nnz) plus
    # two Gram-Schmidt passes (4 matvecs against the [ncv, n] basis),
    # the Ritz back-transform/QR, and the ncv³ projected eigenproblem
    flops = ncv * (2.0 * nnz + 8.0 * n * ncv) + 4.0 * n * ncv * max(k, 1) \
        + 30.0 * ncv ** 3
    bytes_ = ncv * (nnz * (itemsize + 4) + n * ncv * itemsize)
    return flops, bytes_


# The bytes-priced (admission/warm) ops carry flops/bytes twins so the
# roofline attribution layer can cost every op the executor warms with
# the same dim vocabulary estimate_bytes already uses — raftlint R13
# fails the build if the two tables or their signatures drift.

def _sec_pairwise(*, m, n, k, itemsize):
    # one m×n×k MXU contraction plus the O(m·n) metric epilogue
    flops = 2.0 * m * n * k + 3.0 * m * n
    return flops, _est_pairwise(m=m, n=n, k=k, itemsize=itemsize)


def _sec_knn(*, n_queries, n_db, n_dims, k, itemsize,
             dist_itemsize=4):
    # the full q×db distance block plus the tiled insert/drain top-k
    flops = 2.0 * n_queries * n_db * n_dims \
        + 4.0 * n_queries * n_db
    return flops, _est_knn(n_queries=n_queries, n_db=n_db,
                           n_dims=n_dims, k=k, itemsize=itemsize,
                           dist_itemsize=dist_itemsize)


def _sec_ivf_search(*, n_queries, probe_rows, n_dims, k, itemsize,
                    packed_rows=0, dist_itemsize=4):
    # fine distances over the gathered probe tile plus its top-k drain
    flops = 2.0 * n_queries * probe_rows * n_dims \
        + 4.0 * n_queries * probe_rows
    return flops, _est_ivf_search(
        n_queries=n_queries, probe_rows=probe_rows, n_dims=n_dims,
        k=k, itemsize=itemsize, packed_rows=packed_rows,
        dist_itemsize=dist_itemsize)


def _sec_ivf_mnmg_search(*, n_queries, probe_rows, n_dims, k, n_ranks,
                         itemsize, packed_rows=0, dist_itemsize=4):
    # per-device SPMD cost: the local probe scan plus the replicated
    # [q, n_ranks*k] merge-pool top-k (same ONE-device scope as the
    # footprint estimate)
    flops = 2.0 * n_queries * probe_rows * n_dims \
        + 4.0 * n_queries * (probe_rows + n_ranks * k)
    return flops, _est_ivf_mnmg_search(
        n_queries=n_queries, probe_rows=probe_rows, n_dims=n_dims,
        k=k, n_ranks=n_ranks, itemsize=itemsize,
        packed_rows=packed_rows, dist_itemsize=dist_itemsize)


def _sec_ivf_pq_search(*, n_queries, nprobe, probe_rows, n_dims, k, m,
                       n_codes, itemsize=4, refine=0, packed_rows=0,
                       dist_itemsize=4):
    # the LUT build is ONE batched residual×codebook contraction
    # (2·q·nprobe·n_codes·d — every probed list's m subspace LUTs in a
    # single einsum), the LUT-sum touches one code + one LUT entry per
    # (candidate, subspace), the top-k drain mirrors the flat scan, and
    # an armed refine adds one exact pass over the rr raw-row tile
    rr = max(k, refine)
    flops = (2.0 * n_queries * nprobe * n_codes * n_dims
             + 2.0 * n_queries * probe_rows * m
             + 4.0 * n_queries * probe_rows)
    if refine:
        flops += 2.0 * n_queries * rr * n_dims
    return flops, _est_ivf_pq_search(
        n_queries=n_queries, nprobe=nprobe, probe_rows=probe_rows,
        n_dims=n_dims, k=k, m=m, n_codes=n_codes, itemsize=itemsize,
        refine=refine, packed_rows=packed_rows,
        dist_itemsize=dist_itemsize)


def _sec_streaming_compact(*, packed_rows, n_dims, itemsize,
                           id_itemsize=4):
    # bandwidth-bound: the repack streams every packed byte through
    # once out and once in (the coarse relabel contraction is the only
    # FLOP term — one row×centroid pass, centroids ≪ rows)
    flops = 2.0 * packed_rows * n_dims
    return flops, _est_streaming_compact(
        packed_rows=packed_rows, n_dims=n_dims, itemsize=itemsize,
        id_itemsize=id_itemsize)


def _sec_gemm(*, m, n, k, itemsize, out_itemsize=None):
    return 2.0 * m * n * k, _est_gemm(m=m, n=n, k=k,
                                      itemsize=itemsize,
                                      out_itemsize=out_itemsize)


def _sec_spmv(*, n_rows, n_cols, nnz, itemsize, index_itemsize=4):
    return 2.0 * nnz, _est_spmv(n_rows=n_rows, n_cols=n_cols,
                                nnz=nnz, itemsize=itemsize,
                                index_itemsize=index_itemsize)


_SECONDS_ESTIMATORS = {
    "cluster.lloyd_step": _sec_lloyd_step,
    "sparse.lanczos_restart": _sec_lanczos_restart,
    "distance.pairwise_distance": _sec_pairwise,
    "neighbors.brute_force_knn": _sec_knn,
    "neighbors.ivf_search": _sec_ivf_search,
    "neighbors.ivf_mnmg_search": _sec_ivf_mnmg_search,
    "neighbors.ivf_pq_search": _sec_ivf_pq_search,
    "neighbors.streaming_compact": _sec_streaming_compact,
    "linalg.gemm": _sec_gemm,
    "sparse.spmv": _sec_spmv,
}


def estimate_flops_bytes(op: str, **dims) -> tuple:
    """The per-step ``(flops, bytes)`` pair behind
    :func:`estimate_seconds` — exposed so the compiled-driver call
    sites can hand the same model costs to the perf-attribution layer
    (``obs.profile_executable`` / ``record_launch``) that already seed
    their chunk admission. Same op vocabulary as
    :func:`estimate_seconds`."""
    try:
        fn = _SECONDS_ESTIMATORS[op]
    except KeyError:
        raise ValueError(
            f"no seconds estimator for op {op!r}; known: "
            f"{sorted(_SECONDS_ESTIMATORS)}") from None
    flops, bytes_ = fn(**dims)
    return float(flops), float(bytes_)


def estimate_seconds(op: str, *, backend: Optional[str] = None,
                     **dims) -> float:
    """Per-step wall-clock estimate for a compiled chunk's admission
    check — the seconds twin of :func:`estimate_bytes`: the op's inner
    step is costed as ``max(flops/peak_flops, bytes/peak_bandwidth)``
    on ``backend`` (default: the active JAX backend) from static shapes
    only. Known ops: ``cluster.lloyd_step(m, k, n_clusters[,
    itemsize])``, ``sparse.lanczos_restart(n, ncv, nnz[, k,
    itemsize])``."""
    try:
        fn = _SECONDS_ESTIMATORS[op]
    except KeyError:
        raise ValueError(
            f"no seconds estimator for op {op!r}; known: "
            f"{sorted(_SECONDS_ESTIMATORS)}") from None
    if backend is None:
        import jax

        backend = jax.default_backend()
    flops, bytes_ = fn(**dims)
    return max(flops / _PEAK_FLOP_S.get(backend, 5e10),
               bytes_ / _PEAK_BYTES_S.get(backend, 2e10))


def check_chunk_budget(op: str, est_seconds: float) -> None:
    """Pre-launch admission for a compiled chunk: raise
    :class:`DeadlineExceededError` when the chunk's cost estimate
    exceeds the binding deadline's remaining slack — failing BEFORE the
    launch instead of discovering the expiry a whole chunk later. No-op
    without an active deadline scope. Counts into the same breaker and
    ``limits_deadline_exceeded_total`` series as an observed expiry."""
    d = current_deadline()
    if d is None:
        return
    d._ops.add(op)
    rem = d.remaining()
    if est_seconds > rem:
        get_breaker(op).record_failure()
        obs.inc("limits_deadline_exceeded_total", 1, op=op)
        exc = DeadlineExceededError(
            f"{op}: compiled chunk estimated at {est_seconds:.3f}s "
            f"exceeds the {max(rem, 0.0):.3f}s left on the "
            f"{d.budget_s:g}s deadline — failing before launch",
            op=op, budget_s=d.budget_s)
        obs.record_failure(exc)
        raise exc


def admit(op: str, estimate: int, *,
          budget: Optional[WorkBudget] = None) -> bool:
    """Admission check at an instrumented entry point.

    True → the monolithic launch fits (counts a breaker success).
    False → over budget; the caller degrades to its tiled path or calls
    :func:`reject`. Raises :class:`RejectedError` (``breaker_open``)
    immediately when the op's breaker is fast-failing. With no budget
    active, always True (and touches no breaker — the no-scope fast
    path stays bit-identical)."""
    b = budget if budget is not None else active_budget()
    if b is None:
        return True
    br = get_breaker(op)
    if not br.allow():
        obs.inc("limits_rejected_total", 1, reason="breaker_open", op=op)
        exc = RejectedError(
            f"{op}: circuit breaker open after {br.threshold} "
            f"consecutive typed failures (cooldown {br.cooldown_s:g}s)",
            op=op, estimate=int(estimate), reason="breaker_open")
        obs.record_failure(exc)
        raise exc
    if int(estimate) <= b.limit_bytes:
        br.record_success()
        return True
    return False


def reject(op: str, estimate: int, *,
           budget: Optional[WorkBudget] = None,
           detail: str = "") -> None:
    """Refuse the request: even the tiled path cannot fit. Records a
    breaker failure, counts ``limits_rejected_total{reason=
    'over_budget'}``, and raises :class:`RejectedError` carrying the
    byte estimate."""
    b = budget if budget is not None else active_budget()
    limit = b.limit_bytes if b is not None else None
    get_breaker(op).record_failure()
    obs.inc("limits_rejected_total", 1, reason="over_budget", op=op)
    exc = RejectedError(
        f"{op}: estimated footprint {int(estimate)} bytes exceeds the "
        f"admission budget ({limit} bytes) even for the tiled path"
        + (f"; {detail}" if detail else ""),
        op=op, estimate=int(estimate), budget=limit)
    obs.record_failure(exc)
    raise exc


def record_degraded(op: str) -> None:
    """Count a degraded (tiled/streamed) execution the admission layer
    chose instead of the monolithic launch."""
    obs.inc("limits_degraded_total", 1, op=op)


# ---------------------------------------------------------------------------
# circuit breaker
# ---------------------------------------------------------------------------

class CircuitBreaker:
    """Consecutive-typed-failure breaker for one op key.

    Closed (normal) until ``threshold`` consecutive failures, then open:
    :meth:`allow` returns False (callers fast-fail with
    ``RejectedError(reason='breaker_open')``) until ``cooldown_s`` has
    elapsed, after which one half-open probe is allowed — a success
    closes the breaker, a failure re-opens it immediately."""

    def __init__(self, op: str, *, threshold: int = BREAKER_THRESHOLD,
                 cooldown_s: float = BREAKER_COOLDOWN_S):
        self.op = op
        self.threshold = int(threshold)
        self.cooldown_s = float(cooldown_s)
        self._failures = 0
        self._opened_at: Optional[float] = None
        self._lock = threading.Lock()

    def allow(self) -> bool:
        with self._lock:
            if self._opened_at is None:
                return True
            if time.monotonic() - self._opened_at < self.cooldown_s:
                return False
            # half-open: let one probe through; a failure re-opens
            self._opened_at = None
            self._failures = self.threshold - 1
            self._set_gauge(0)
            return True

    def record_failure(self) -> None:
        with self._lock:
            self._failures += 1
            if self._failures >= self.threshold and self._opened_at is None:
                self._opened_at = time.monotonic()
                self._set_gauge(1)

    def record_success(self) -> None:
        with self._lock:
            self._failures = 0
            if self._opened_at is not None:
                self._opened_at = None
                self._set_gauge(0)

    @property
    def open(self) -> bool:
        with self._lock:
            return self._opened_at is not None

    def _set_gauge(self, state: int) -> None:
        # called under self._lock; obs is itself thread-safe
        obs.set_gauge("limits_breaker_state", state, op=self.op,
                      help="circuit breaker state (0 closed, 1 open)")


_breakers: Dict[str, CircuitBreaker] = {}
_breakers_lock = threading.Lock()


def get_breaker(op: str) -> CircuitBreaker:
    """The process-global breaker for an op key (created on first use)."""
    br = _breakers.get(op)
    if br is None:
        with _breakers_lock:
            br = _breakers.setdefault(op, CircuitBreaker(op))
    return br


def reset_breakers() -> None:
    """Drop all breaker state (tests and REPL hygiene)."""
    with _breakers_lock:
        _breakers.clear()


# ---------------------------------------------------------------------------
# rate budgets (ISSUE 16: retry budgets, hedge budgets)
# ---------------------------------------------------------------------------

class RateBudget:
    """A sliding-window spend budget for *secondary* work — retries,
    hedges — that must never amplify an overload.

    Two modes, one mechanism:

    - **absolute** (``max_events``): at most N spends per ``window_s``.
      The retry-budget shape: a recovering peer sees a bounded retry
      rate no matter how many callers are failing.
    - **fractional** (``max_fraction`` of :meth:`note`-recorded base
      events): spends are capped at a fraction of primary traffic in
      the window. The hedge-budget shape (Dean & Barroso's ≤5%): with
      no primaries there is nothing to hedge against, so the budget is
      empty, and a traffic spike raises the allowance proportionally
      instead of letting hedges pile on a fixed cap.

    Both can be set; the tighter one wins. :meth:`try_spend` is a
    check-and-commit — a True return has already consumed the slot, so
    concurrent spenders can't overshoot."""

    __slots__ = ("max_events", "max_fraction", "window_s",
                 "_base", "_spent", "_lock")

    def __init__(self, *, max_events: Optional[int] = None,
                 max_fraction: Optional[float] = None,
                 window_s: float = 60.0):
        if max_events is None and max_fraction is None:
            raise ValueError(
                "RateBudget needs max_events and/or max_fraction")
        if max_events is not None and max_events < 0:
            raise ValueError(f"max_events must be >= 0, got {max_events}")
        if max_fraction is not None and not (0.0 <= max_fraction <= 1.0):
            raise ValueError(
                f"max_fraction must be in [0, 1], got {max_fraction}")
        if not window_s > 0:
            raise ValueError(f"window_s must be > 0, got {window_s}")
        self.max_events = max_events
        self.max_fraction = max_fraction
        self.window_s = float(window_s)
        self._base: "collections.deque" = _collections.deque()
        self._spent: "collections.deque" = _collections.deque()
        self._lock = threading.Lock()

    def _trim(self, now: float) -> None:
        # under self._lock
        cutoff = now - self.window_s
        for dq in (self._base, self._spent):
            while dq and dq[0] < cutoff:
                dq.popleft()

    def note(self, n: int = 1) -> None:
        """Record ``n`` base (primary) events — the denominator for
        ``max_fraction`` mode. No-op cost in absolute mode is fine;
        callers need not branch."""
        if self.max_fraction is None:
            return
        now = time.monotonic()
        with self._lock:
            self._trim(now)
            self._base.extend([now] * int(n))

    def try_spend(self, n: int = 1) -> bool:
        """Atomically consume ``n`` budget slots if the window allows
        it. False means the caller must skip the retry/hedge (and
        should meter the suppression)."""
        n = int(n)
        now = time.monotonic()
        with self._lock:
            self._trim(now)
            spent = len(self._spent)
            if self.max_events is not None \
                    and spent + n > self.max_events:
                return False
            if self.max_fraction is not None:
                allowed = int(len(self._base) * self.max_fraction)
                if spent + n > allowed:
                    return False
            self._spent.extend([now] * n)
            return True

    def spent(self) -> int:
        """Spends currently inside the window (observability/tests)."""
        now = time.monotonic()
        with self._lock:
            self._trim(now)
            return len(self._spent)

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return (f"RateBudget(max_events={self.max_events}, "
                f"max_fraction={self.max_fraction}, "
                f"window_s={self.window_s})")
