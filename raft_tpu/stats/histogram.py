"""Per-column histogram (ref: raft/stats/histogram.cuh, detail/histogram.cuh).

The reference ships nine CUDA strategies (smem bit-packed atomics, gmem
atomics, match_any, smem hash — stats/stats_types.hpp:22-52) chosen by
``HistType``. On TPU there are no atomics to tune: a histogram is a
scatter-add (XLA lowers jnp.add.at-style segment sums efficiently), a
one-hot matmul that rides the MXU (small bin counts), or a FACTORED
hi/lo one-hot contraction (mid/large bin counts — bin = 128*hi + lo,
batched MXU matmul per column; the on-chip sweep measured the scatter
~35x slower there). We keep the ``HistType`` vocabulary for API parity;
every member maps onto these three TPU formulations with
``HistTypeAuto`` picking by n_bins.
"""

from __future__ import annotations

import enum

import jax.numpy as jnp


class HistType(enum.Enum):
    """API-parity enum (ref: stats_types.hpp:22-52). On TPU all smem/gmem
    atomic strategies collapse to scatter-add; small-bin cases use the
    one-hot matmul path."""

    SmemBits1 = 1
    SmemBits2 = 2
    SmemBits4 = 4
    SmemBits8 = 8
    SmemBits16 = 16
    Gmem = "gmem"
    Smem = "smem"
    SmemMatchAny = "smem_match_any"
    SmemHash = "smem_hash"
    Auto = "auto"


# Below this many bins a one-hot (n, bins) matmul against ones is cheaper
# than scatter: it is a single MXU-friendly contraction with no serialization.
_ONEHOT_BIN_LIMIT = 512

# Between the one-hot limit and this, the factored path applies (below);
# beyond it, scatter-add (a 2^14-bin one-hot pair still fits comfortably,
# and real bin counts beyond that are rare).
_FACTORED_BIN_LIMIT = 1 << 14


def _histogram_factored(bins, valid, n_bins: int):
    """Mid/large-bin histogram as a FACTORED one-hot contraction: write
    bin = 128*hi + lo, then H[c, hi, lo] = sum_r OHhi[r,c,hi]*OHlo[r,c,lo]
    — a batched (n_hi, chunk) @ (chunk, 128) MXU matmul per column
    instead of a scatter-add (the on-chip sweep measured the scatter at
    1.4e8 items/s vs 5e9+ for contraction-shaped stats — TPU has no
    atomics, so scatter serializes; the MXU does not). Exact: one-hot
    products are 0/1, per-chunk partial counts are integers < 2^24 in
    f32, accumulated into int32 across row chunks."""
    import jax

    n_rows, n_cols = bins.shape
    n_hi = (n_bins + 127) // 128
    if n_rows == 0:
        return jnp.zeros((n_bins, n_cols), jnp.int32)
    # out-of-range rows get hi = n_hi (matches no one-hot column)
    hi = jnp.where(valid, bins >> 7, n_hi)
    lo = bins & 127
    # chunk rows so the transient bf16 one-hots stay ~<=64 MB
    chunk = max(8, (32 << 20) // max(n_cols * (128 + n_hi), 1))
    chunk = min(chunk, n_rows)
    n_chunks = -(-n_rows // chunk)
    pad = n_chunks * chunk - n_rows
    if pad:
        hi = jnp.pad(hi, ((0, pad), (0, 0)), constant_values=n_hi)
        lo = jnp.pad(lo, ((0, pad), (0, 0)))
    hi = hi.reshape(n_chunks, chunk, n_cols)
    lo = lo.reshape(n_chunks, chunk, n_cols)
    iota_hi = jnp.arange(n_hi, dtype=jnp.int32)
    iota_lo = jnp.arange(128, dtype=jnp.int32)

    def body(acc, sl):
        h, l = sl
        ohhi = (h[..., None] == iota_hi).astype(jnp.bfloat16)
        ohlo = (l[..., None] == iota_lo).astype(jnp.bfloat16)
        part = jax.lax.dot_general(
            ohhi, ohlo, (((0,), (0,)), ((1,), (1,))),
            preferred_element_type=jnp.float32)      # (n_cols, n_hi, 128)
        return acc + part.astype(jnp.int32), None

    acc0 = jnp.zeros((n_cols, n_hi, 128), jnp.int32)
    h, _ = jax.lax.scan(body, acc0, (hi, lo))
    return h.reshape(n_cols, n_hi * 128)[:, :n_bins].T


def histogram(data, n_bins: int, binner=None,
              hist_type: HistType = HistType.Auto):
    """Per-column histogram of ``data`` (n_rows, n_cols) -> (n_bins, n_cols).

    ``binner(value, row, col)`` maps a value to its bin (default: identity
    cast to int, the reference's default IdentityBinner). Out-of-range bins
    are dropped, matching the reference's bounds check.
    """
    if data.ndim == 1:
        data = data[:, None]
    n_rows, n_cols = data.shape

    if binner is None:
        bins = data.astype(jnp.int32)
    else:
        rows = jnp.arange(n_rows)[:, None]
        cols = jnp.arange(n_cols)[None, :]
        bins = binner(data, rows, cols).astype(jnp.int32)

    valid = (bins >= 0) & (bins < n_bins)

    if hist_type is not HistType.Gmem and n_bins <= _ONEHOT_BIN_LIMIT:
        # (n_bins, n_rows) x (n_rows, n_cols) contraction per column via
        # broadcasting: one_hot is (n_rows, n_cols, n_bins).
        onehot = (bins[..., None] == jnp.arange(n_bins)[None, None, :])
        onehot = jnp.where(valid[..., None], onehot, False)
        return jnp.sum(onehot, axis=0, dtype=jnp.int32).T

    if hist_type is not HistType.Gmem and n_bins <= _FACTORED_BIN_LIMIT:
        return _histogram_factored(bins, valid, n_bins)

    # Scatter-add path: flatten (bin, col) into a single segment id.
    clipped = jnp.clip(bins, 0, n_bins - 1)
    flat_ids = clipped * n_cols + jnp.arange(n_cols)[None, :]
    weights = valid.astype(jnp.int32)
    out = jnp.zeros((n_bins * n_cols,), jnp.int32)
    out = out.at[flat_ids.reshape(-1)].add(weights.reshape(-1))
    return out.reshape(n_bins, n_cols)
