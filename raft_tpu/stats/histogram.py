"""Per-column histogram (ref: raft/stats/histogram.cuh, detail/histogram.cuh).

The reference ships nine CUDA strategies (smem bit-packed atomics, gmem
atomics, match_any, smem hash — stats/stats_types.hpp:22-52) chosen by
``HistType``. On TPU there are no atomics to tune: a histogram is a
scatter-add (XLA lowers jnp.add.at-style segment sums efficiently) or, for
small bin counts, a one-hot matmul that rides the MXU. We keep the
``HistType`` vocabulary for API parity; every member maps onto the same two
TPU formulations with ``HistTypeAuto`` picking by n_bins.
"""

from __future__ import annotations

import enum

import jax.numpy as jnp


class HistType(enum.Enum):
    """API-parity enum (ref: stats_types.hpp:22-52). On TPU all smem/gmem
    atomic strategies collapse to scatter-add; small-bin cases use the
    one-hot matmul path."""

    SmemBits1 = 1
    SmemBits2 = 2
    SmemBits4 = 4
    SmemBits8 = 8
    SmemBits16 = 16
    Gmem = "gmem"
    Smem = "smem"
    SmemMatchAny = "smem_match_any"
    SmemHash = "smem_hash"
    Auto = "auto"


# Below this many bins a one-hot (n, bins) matmul against ones is cheaper
# than scatter: it is a single MXU-friendly contraction with no serialization.
_ONEHOT_BIN_LIMIT = 512


def histogram(data, n_bins: int, binner=None,
              hist_type: HistType = HistType.Auto):
    """Per-column histogram of ``data`` (n_rows, n_cols) -> (n_bins, n_cols).

    ``binner(value, row, col)`` maps a value to its bin (default: identity
    cast to int, the reference's default IdentityBinner). Out-of-range bins
    are dropped, matching the reference's bounds check.
    """
    if data.ndim == 1:
        data = data[:, None]
    n_rows, n_cols = data.shape

    if binner is None:
        bins = data.astype(jnp.int32)
    else:
        rows = jnp.arange(n_rows)[:, None]
        cols = jnp.arange(n_cols)[None, :]
        bins = binner(data, rows, cols).astype(jnp.int32)

    valid = (bins >= 0) & (bins < n_bins)

    if hist_type is not HistType.Gmem and n_bins <= _ONEHOT_BIN_LIMIT:
        # (n_bins, n_rows) x (n_rows, n_cols) contraction per column via
        # broadcasting: one_hot is (n_rows, n_cols, n_bins).
        onehot = (bins[..., None] == jnp.arange(n_bins)[None, None, :])
        onehot = jnp.where(valid[..., None], onehot, False)
        return jnp.sum(onehot, axis=0, dtype=jnp.int32).T

    # Scatter-add path: flatten (bin, col) into a single segment id.
    clipped = jnp.clip(bins, 0, n_bins - 1)
    flat_ids = clipped * n_cols + jnp.arange(n_cols)[None, :]
    weights = valid.astype(jnp.int32)
    out = jnp.zeros((n_bins * n_cols,), jnp.int32)
    out = out.at[flat_ids.reshape(-1)].add(weights.reshape(-1))
    return out.reshape(n_bins, n_cols)
