"""First/second-moment statistics (ref: raft/stats/{mean,stddev,sum,meanvar,
mean_center,minmax,cov,weighted_mean}.cuh).

The reference reduces along rows or columns with bespoke coalesced/strided
kernels; here every reduction is a jnp reduction XLA maps onto the VPU/MXU.
All functions take ``axis`` (0 = per-column stats over rows, the reference's
default layout) and are jit-safe.
"""

from __future__ import annotations

import jax.numpy as jnp
from raft_tpu.util.precision import with_matmul_precision


def mean(x, axis: int = 0):
    """Per-column (axis=0) or per-row (axis=1) mean. Ref: stats/mean.cuh."""
    return jnp.mean(x, axis=axis)


def sum_(x, axis: int = 0):
    """Column/row sums. Ref: stats/sum.cuh."""
    return jnp.sum(x, axis=axis)


def vars_(x, mu=None, axis: int = 0, sample: bool = True):
    """Variance about ``mu`` (computed if None). ``sample`` divides by n-1.
    Ref: stats/stddev.cuh (vars overloads)."""
    n = x.shape[axis]
    if mu is None:
        mu = jnp.mean(x, axis=axis)
    centered = x - jnp.expand_dims(mu, axis)
    denom = (n - 1) if sample else n
    return jnp.sum(centered * centered, axis=axis) / denom


def stddev(x, mu=None, axis: int = 0, sample: bool = True):
    """Standard deviation. Ref: stats/stddev.cuh."""
    return jnp.sqrt(vars_(x, mu=mu, axis=axis, sample=sample))


def meanvar(x, axis: int = 0, sample: bool = True):
    """Single-pass mean+variance pair. Ref: stats/meanvar.cuh."""
    mu = jnp.mean(x, axis=axis)
    return mu, vars_(x, mu=mu, axis=axis, sample=sample)


def mean_center(x, mu=None, axis: int = 0):
    """Subtract the mean along ``axis``. Ref: stats/mean_center.cuh."""
    if mu is None:
        mu = jnp.mean(x, axis=axis)
    return x - jnp.expand_dims(mu, axis)


def mean_add(x, mu, axis: int = 0):
    """Add a mean vector back (inverse of mean_center). Ref: mean_center.cuh."""
    return x + jnp.expand_dims(mu, axis)


def minmax(x, axis: int = 0, rows=None, row_ids=None):
    """Per-column (min, max). Optional ``row_ids`` restricts to a sampled row
    subset, mirroring the reference's sampledRows path. Ref: stats/minmax.cuh."""
    if row_ids is not None:
        x = jnp.take(x, row_ids, axis=0)
    elif rows is not None:
        x = x[:rows]
    return jnp.min(x, axis=axis), jnp.max(x, axis=axis)


@with_matmul_precision
def cov(x, mu=None, sample: bool = True, center: bool = True):
    """Covariance matrix of row-sample data ``x`` (n, d) -> (d, d).

    One dot_general on the MXU instead of the reference's gemm-over-centered
    buffer (stats/cov.cuh; it optionally destroys the input by centering
    in place — we stay functional).
    """
    n = x.shape[0]
    if center:
        if mu is None:
            mu = jnp.mean(x, axis=0)
        x = x - mu[None, :]
    denom = (n - 1) if sample else n
    return (x.T @ x) / denom


def weighted_mean(x, weights, axis: int = 0):
    """Weighted mean along ``axis``; ``weights`` has length x.shape[axis].
    Ref: stats/weighted_mean.cuh (weighted_mean)."""
    w = jnp.asarray(weights)
    wsum = jnp.sum(w)
    return jnp.tensordot(w, x, axes=([0], [axis])) / wsum


def row_weighted_mean(x, weights):
    """Per-row weighted mean over columns (weights: ncols).
    Ref: stats/weighted_mean.cuh (row_weighted_mean)."""
    return weighted_mean(x, weights, axis=1)


def col_weighted_mean(x, weights):
    """Per-column weighted mean over rows (weights: nrows).
    Ref: stats/weighted_mean.cuh (col_weighted_mean)."""
    return weighted_mean(x, weights, axis=0)
