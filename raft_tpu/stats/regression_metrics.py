"""Classification/regression scoring (ref: raft/stats/{accuracy,r2_score,
regression_metrics}.cuh)."""

from __future__ import annotations

import jax.numpy as jnp


def accuracy(predictions, ref_predictions):
    """Fraction of exact matches. Ref: stats/accuracy.cuh."""
    p = jnp.asarray(predictions)
    r = jnp.asarray(ref_predictions)
    return jnp.mean((p == r).astype(jnp.result_type(float)))


def r2_score(y, y_hat):
    """Coefficient of determination 1 - SS_res/SS_tot.
    Ref: stats/r2_score.cuh."""
    y = jnp.asarray(y)
    y_hat = jnp.asarray(y_hat)
    mu = jnp.mean(y)
    ss_tot = jnp.sum((y - mu) ** 2)
    ss_res = jnp.sum((y - y_hat) ** 2)
    return 1.0 - ss_res / ss_tot


def regression_metrics(predictions, ref_predictions):
    """(mean_abs_error, mean_squared_error, median_abs_error).

    Median via sort (TPU-friendly; the reference uses a cub device sort +
    midpoint pick, stats/detail/scores.cuh). Ref: stats/regression_metrics.cuh.
    """
    p = jnp.asarray(predictions, dtype=jnp.result_type(float))
    r = jnp.asarray(ref_predictions, dtype=jnp.result_type(float))
    err = p - r
    abs_err = jnp.abs(err)
    mae = jnp.mean(abs_err)
    mse = jnp.mean(err * err)
    s = jnp.sort(abs_err)
    n = s.shape[0]
    medae = jnp.where(n % 2 == 1, s[n // 2],
                      0.5 * (s[n // 2 - 1] + s[n // 2]))
    return mae, mse, medae
