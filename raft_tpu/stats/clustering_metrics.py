"""External clustering metrics (ref: raft/stats/{contingency_matrix,
rand_index,adjusted_rand_index,mutual_info_score,homogeneity_score,
completeness_score,v_measure,silhouette_score}.cuh).

TPU-first design note: the reference's ``rand_index`` launches an
O(n^2/2) pair-counting kernel (stats/detail/rand_index.cuh) and the
entropy-family metrics each walk a contingency matrix with bespoke kernels.
Here *one* scatter-add contingency matrix feeds every metric in closed form
— the pair counts a/b/c/d are algebraic functions of the contingency table,
so no quadratic work is needed.
"""

from __future__ import annotations

import jax.numpy as jnp
from raft_tpu.util.precision import with_matmul_precision


def _num_classes(arr, n=None):
    if n is not None:
        return int(n)
    return int(jnp.max(arr)) + 1


def contingency_matrix(y_true, y_pred, n_classes_true: int = None,
                       n_classes_pred: int = None):
    """(n_true, n_pred) label co-occurrence counts via one scatter-add.
    Labels are assumed monotonic from 0 (use raft_tpu.label.make_monotonic
    first, exactly like the reference's workflow).
    Ref: stats/contingency_matrix.cuh."""
    y_true = jnp.asarray(y_true)
    y_pred = jnp.asarray(y_pred)
    nt = _num_classes(y_true, n_classes_true)
    np_ = _num_classes(y_pred, n_classes_pred)
    # With an explicit (too-small) class count, out-of-range labels would be
    # silently DROPPED by the scatter-add under jit; validate eagerly when
    # the labels are concrete so the error is loud where it can be.
    import jax as _jax
    if not isinstance(y_true, _jax.core.Tracer):
        mt, mp = int(jnp.max(y_true)), int(jnp.max(y_pred))
        if mt >= nt or mp >= np_:
            raise ValueError(
                f"labels exceed the class count: max labels ({mt}, {mp}) "
                f"vs n_classes ({nt}, {np_})")
    flat = y_true.astype(jnp.int32) * np_ + y_pred.astype(jnp.int32)
    # One-hot bincount sums on the VPU instead of serializing through
    # TPU's scatter-add — but its (n_samples, table) intermediate can
    # materialize under eager execution, so the dispatch is bounded on
    # BOTH the table size and the intermediate's element count (~128 MB
    # bool cap); beyond that the scatter path's O(n) memory wins
    # (round-2 advisor finding: 1M samples × 4096 table ≈ 4 GB eager).
    if nt * np_ <= 4096 and flat.shape[0] * (nt * np_) <= (1 << 27):
        onehot = flat[:, None] == jnp.arange(nt * np_, dtype=jnp.int32)
        out = jnp.sum(onehot, axis=0, dtype=jnp.result_type(int))
    else:
        out = jnp.zeros((nt * np_,), jnp.result_type(int))
        out = out.at[flat].add(1)
    return out.reshape(nt, np_)


def _comb2(x):
    x = x.astype(jnp.result_type(float))
    return x * (x - 1.0) / 2.0


def rand_index(y_a, y_b, n_classes: int = None):
    """Rand index. Closed form over the contingency table (equivalent to the
    reference's O(n^2) pair kernel, stats/detail/rand_index.cuh which the
    header itself flags for this optimisation).

    Pass ``n_classes`` to make the function jit-traceable (class counts
    are shape-determining)."""
    c = contingency_matrix(y_a, y_b, n_classes, n_classes)
    n = jnp.asarray(y_a).shape[0]
    sum_ij = jnp.sum(_comb2(c))
    sum_a = jnp.sum(_comb2(jnp.sum(c, axis=1)))
    sum_b = jnp.sum(_comb2(jnp.sum(c, axis=0)))
    total = _comb2(jnp.asarray(n))
    agreements = total + 2.0 * sum_ij - sum_a - sum_b
    return agreements / total


def adjusted_rand_index(y_a, y_b, n_classes: int = None):
    """Corrected-for-chance Rand index. Ref: stats/adjusted_rand_index.cuh.

    Pass ``n_classes`` to make the function jit-traceable."""
    c = contingency_matrix(y_a, y_b, n_classes, n_classes)
    n = jnp.asarray(y_a).shape[0]
    sum_ij = jnp.sum(_comb2(c))
    sum_a = jnp.sum(_comb2(jnp.sum(c, axis=1)))
    sum_b = jnp.sum(_comb2(jnp.sum(c, axis=0)))
    total = _comb2(jnp.asarray(n))
    expected = sum_a * sum_b / total
    max_index = 0.5 * (sum_a + sum_b)
    denom = max_index - expected
    # All-singleton / single-cluster degenerate cases: perfect agreement.
    return jnp.where(denom == 0, 1.0, (sum_ij - expected) / denom)


def mutual_info_score(y_a, y_b, n_classes: int = None):
    """Mutual information (natural log) between two labelings.
    Ref: stats/mutual_info_score.cuh."""
    c = contingency_matrix(y_a, y_b, n_classes, n_classes).astype(jnp.result_type(float))
    n = jnp.sum(c)
    pij = c / n
    pi = jnp.sum(pij, axis=1, keepdims=True)
    pj = jnp.sum(pij, axis=0, keepdims=True)
    logterm = jnp.where(pij > 0, jnp.log(pij / (pi * pj)), 0.0)
    return jnp.sum(pij * logterm)


def _conditional_entropy(c):
    """H(rows | cols) from a contingency matrix."""
    n = jnp.sum(c)
    pj = jnp.sum(c, axis=0)  # marginal of the conditioning labels
    pij = c / n
    ratio = jnp.where(c > 0, c / pj[None, :], 1.0)
    return -jnp.sum(jnp.where(c > 0, pij * jnp.log(ratio), 0.0))


def _label_entropy(counts, n):
    p = counts / n
    return -jnp.sum(jnp.where(p > 0, p * jnp.log(p), 0.0))


def homogeneity_score(y_true, y_pred, n_classes: int = None):
    """1 - H(C|K)/H(C): each predicted cluster contains members of a single
    class. Ref: stats/homogeneity_score.cuh."""
    c = contingency_matrix(y_true, y_pred, n_classes, n_classes).astype(
        jnp.result_type(float))
    n = jnp.sum(c)
    h_c = _label_entropy(jnp.sum(c, axis=1), n)
    h_ck = _conditional_entropy(c)
    return jnp.where(h_c == 0, 1.0, 1.0 - h_ck / h_c)


def completeness_score(y_true, y_pred, n_classes: int = None):
    """Homogeneity with roles swapped. Ref: stats/completeness_score.cuh."""
    return homogeneity_score(y_pred, y_true, n_classes)


def v_measure(y_true, y_pred, n_classes: int = None, beta: float = 1.0):
    """Weighted harmonic mean of homogeneity and completeness.
    Ref: stats/v_measure.cuh (beta default 1.0)."""
    h = homogeneity_score(y_true, y_pred, n_classes)
    c = completeness_score(y_true, y_pred, n_classes)
    denom = beta * h + c
    return jnp.where(denom == 0, 0.0, (1.0 + beta) * h * c / denom)


@with_matmul_precision
def silhouette_score(res, x, labels, n_clusters: int, metric=None,
                     chunk: int = 4096):
    """Mean silhouette coefficient s(i) = (b-a)/max(a,b).

    Rebuilt from the distance layer (the reference's silhouette_score.cuh is
    vestigial after the cuVS migration — SURVEY.md §2.8). Per-point mean
    distance to every cluster comes from one (chunked) pairwise-distance
    matrix times a cluster one-hot — a single MXU contraction per chunk —
    rather than a per-pair atomic kernel.
    """
    from raft_tpu.distance.pairwise import pairwise_distance, DistanceType

    if metric is None:
        metric = DistanceType.L2Unexpanded
    x = jnp.asarray(x)
    labels = jnp.asarray(labels).astype(jnp.int32)
    n = x.shape[0]
    onehot = (labels[:, None] == jnp.arange(n_clusters)[None, :]).astype(
        x.dtype)                                   # (n, k)
    counts = jnp.sum(onehot, axis=0)               # (k,)

    sil_sum = jnp.zeros((), x.dtype)
    for start in range(0, n, chunk):
        xb = x[start:start + chunk]
        lb = labels[start:start + chunk]
        d = pairwise_distance(res, xb, x, metric=metric)   # (b, n)
        cluster_sums = d @ onehot                          # (b, k)
        own = counts[lb]
        # a: mean distance to own cluster, excluding self (distance 0).
        a = jnp.where(own > 1,
                      cluster_sums[jnp.arange(xb.shape[0]), lb]
                      / jnp.maximum(own - 1, 1),
                      0.0)
        mean_other = cluster_sums / jnp.maximum(counts[None, :], 1)
        mean_other = jnp.where(
            (jnp.arange(n_clusters)[None, :] == lb[:, None])
            | (counts[None, :] == 0),
            jnp.inf, mean_other)
        b = jnp.min(mean_other, axis=1)
        s = jnp.where(own > 1, (b - a) / jnp.maximum(jnp.maximum(a, b), 1e-30),
                      0.0)
        sil_sum = sil_sum + jnp.sum(s)
    return sil_sum / n
