"""Information-theoretic stats and model-selection criteria
(ref: raft/stats/{entropy,kl_divergence,information_criterion,dispersion}.cuh).
"""

from __future__ import annotations

import enum

import jax.numpy as jnp

from raft_tpu.stats.histogram import histogram


def entropy(labels, lower: int = None, upper: int = None):
    """Shannon entropy (natural log) of an integer label array whose values
    lie in [lower, upper). Ref: stats/entropy.cuh (detail builds a histogram
    then reduces -p log p)."""
    labels = jnp.asarray(labels)
    if lower is None:
        lower = 0
    n_classes = int(upper - lower) if upper is not None else int(
        jnp.max(labels)) + 1 - lower
    counts = histogram(labels - lower, n_classes)[:, 0]
    n = labels.shape[0]
    p = counts.astype(jnp.result_type(float)) / n
    return -jnp.sum(jnp.where(p > 0, p * jnp.log(p), 0.0))


def kl_divergence(p, q):
    """KL(P || Q) = sum p log(p/q), skipping p==0 terms (ref:
    stats/kl_divergence.cuh detail op)."""
    p = jnp.asarray(p)
    q = jnp.asarray(q)
    return jnp.sum(jnp.where(p > 0, p * jnp.log(p / q), 0.0))


class IC_Type(enum.Enum):
    """Ref: stats_types.hpp IC_Type {AIC, AICc, BIC}."""

    AIC = "aic"
    AICc = "aicc"
    BIC = "bic"


def information_criterion_batched(loglikelihood, ic_type: IC_Type,
                                  n_params: int, n_samples: int):
    """Penalised log-likelihood per batch member (ref:
    stats/information_criterion.cuh, detail/batched/information_criterion.cuh:
    IC = -2 ll + penalty; AICc adds the small-sample correction)."""
    ll = jnp.asarray(loglikelihood)
    k = n_params
    n = n_samples
    if ic_type is IC_Type.AIC:
        penalty = 2.0 * k
    elif ic_type is IC_Type.AICc:
        penalty = 2.0 * k + (2.0 * k * (k + 1)) / (n - k - 1)
    elif ic_type is IC_Type.BIC:
        penalty = jnp.log(jnp.asarray(float(n))) * k
    else:  # pragma: no cover - exhaustive enum
        raise ValueError(f"unknown IC type {ic_type}")
    return -2.0 * ll + penalty


def cluster_dispersion(centroids, cluster_sizes, n_points: int = None):
    """Weighted RMS spread of cluster centroids about the size-weighted
    global centroid: sqrt(sum_i n_i ||c_i - mu||^2), mu = sum_i n_i c_i / N.
    Useful for choosing k. Ref: stats/dispersion.cuh,
    detail/dispersion.cuh:47-131 (weightedMeanKernel + dispersionKernel,
    final sqrt on host)."""
    centroids = jnp.asarray(centroids)
    sizes = jnp.asarray(cluster_sizes)
    if n_points is None:
        n_points = jnp.sum(sizes)
    mu = jnp.sum(centroids * sizes[:, None].astype(centroids.dtype),
                 axis=0) / n_points
    d2 = jnp.sum((centroids - mu[None, :]) ** 2, axis=1)
    return jnp.sqrt(jnp.sum(d2 * sizes.astype(centroids.dtype)))


def information_criterion(loglikelihood, ic_type: IC_Type, n_params: int,
                          n_samples: int):
    """Scalar spelling (ref: stats/information_criterion.cuh — the
    non-batched overload; identical math on a scalar log-likelihood)."""
    return information_criterion_batched(loglikelihood, ic_type, n_params,
                                         n_samples)
