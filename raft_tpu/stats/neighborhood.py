"""Neighborhood-preservation metrics (ref: raft/stats/neighborhood_recall.cuh
and the vestigial stats/trustworthiness_score.cuh, rebuilt from this repo's
distance + select_k layers per SURVEY.md §2.8)."""

from __future__ import annotations

import jax
import jax.numpy as jnp


def neighborhood_recall(indices, ref_indices, distances=None,
                        ref_distances=None, eps: float = 1e-4):
    """Fraction of k-NN indices matching a reference k-NN result.

    When distances are supplied, an index mismatch still counts if the
    distances coincide within ``eps`` (tie handling, mirroring
    stats/neighborhood_recall.cuh:77-162's distance-equality fallback).
    """
    idx = jnp.asarray(indices)
    ref = jnp.asarray(ref_indices)
    n, k = idx.shape
    # (n, k, k) membership test: is idx[i, j] anywhere in ref[i, :]?
    match = idx[:, :, None] == ref[:, None, :]
    if distances is not None and ref_distances is not None:
        d = jnp.asarray(distances)
        rd = jnp.asarray(ref_distances)
        tie = jnp.abs(d[:, :, None] - rd[:, None, :]) <= eps
        match = match | tie
    hits = jnp.sum(jnp.any(match, axis=2).astype(jnp.result_type(float)))
    return hits / (n * k)


def trustworthiness_score(res, x, x_embedded, n_neighbors: int,
                          metric=None, batch_size: int = 512):
    """Trustworthiness of a low-dimensional embedding:

        T = 1 - 2/(n k (2n - 3k - 1)) * sum_i sum_{j in kNN_emb(i)}
                max(0, rank_orig(i, j) - k)

    where rank_orig is 1-based among non-self points. Ranks come from
    comparison counting (#points strictly closer) on chunked
    pairwise-distance rows — no (n, n) argsort materialised, one broadcast
    reduction per chunk. Ref: stats/trustworthiness_score.cuh (vestigial
    upstream; formula per its cuML lineage).
    """
    from raft_tpu.distance.pairwise import pairwise_distance, DistanceType

    if metric is None:
        metric = DistanceType.L2SqrtUnexpanded
    x = jnp.asarray(x)
    emb = jnp.asarray(x_embedded)
    n = x.shape[0]
    k = n_neighbors

    penalty = jnp.zeros((), jnp.result_type(float))
    for start in range(0, n, batch_size):
        xb = x[start:start + batch_size]
        eb = emb[start:start + batch_size]
        b = xb.shape[0]
        rows = jnp.arange(b)

        d_emb = pairwise_distance(res, eb, emb, metric=metric)   # (b, n)
        d_emb = d_emb.at[rows, start + rows].set(jnp.inf)        # drop self
        _, nn_emb = jax.lax.top_k(-d_emb, k)                     # (b, k)

        d_orig = pairwise_distance(res, xb, x, metric=metric)    # (b, n)
        self_d = d_orig[rows, start + rows]
        d_nn = jnp.take_along_axis(d_orig, nn_emb, axis=1)       # (b, k)
        closer = d_orig[:, None, :] < d_nn[:, :, None]           # (b, k, n)
        rank0 = jnp.sum(closer, axis=2)                          # 0-based,
        rank0 = rank0 - (self_d[:, None] < d_nn)                 # self out
        rank1 = rank0.astype(jnp.result_type(float)) + 1.0                  # 1-based
        penalty = penalty + jnp.sum(jnp.maximum(rank1 - k, 0.0))
    return 1.0 - penalty * (2.0 / (n * k * (2.0 * n - 3.0 * k - 1.0)))
