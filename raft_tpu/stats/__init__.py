"""Statistics and metrics layer (ref: cpp/include/raft/stats/ — SURVEY.md §2.8).

Every primitive is a pure jnp function, jit-composable and shardable. Where
the reference uses bespoke CUDA kernels (histogram smem strategies, O(n^2)
rand-index pair counting), the TPU design reformulates the computation as
matmul / segment-sum / sort primitives that XLA tiles onto the MXU:

- histogram          -> one-hot matmul (small bins) / factored hi-lo contraction (mid) / scatter-add (huge)
- contingency matrix -> 2-D scatter-add; rand/ARI/MI/V-measure derive from it
  in closed form instead of pair-counting kernels
- silhouette/trustworthiness -> tiled pairwise-distance reductions on the
  fused contraction kernel layer (rebuilt here since the reference moved its
  copies to cuVS; stats/silhouette_score.cuh, stats/trustworthiness_score.cuh
  are vestigial upstream)
"""

from raft_tpu.stats.moments import (  # noqa: F401
    mean,
    stddev,
    vars_,
    sum_,
    meanvar,
    mean_center,
    mean_add,
    minmax,
    cov,
    weighted_mean,
    row_weighted_mean,
    col_weighted_mean,
)
from raft_tpu.stats.histogram import HistType, histogram  # noqa: F401
from raft_tpu.stats.information import (  # noqa: F401
    entropy,
    kl_divergence,
    IC_Type,
    information_criterion,
    information_criterion_batched,
    cluster_dispersion,
)
from raft_tpu.stats.clustering_metrics import (  # noqa: F401
    contingency_matrix,
    rand_index,
    adjusted_rand_index,
    mutual_info_score,
    homogeneity_score,
    completeness_score,
    v_measure,
    silhouette_score,
)
from raft_tpu.stats.regression_metrics import (  # noqa: F401
    accuracy,
    r2_score,
    regression_metrics,
)
from raft_tpu.stats.neighborhood import (  # noqa: F401
    neighborhood_recall,
    trustworthiness_score,
)
