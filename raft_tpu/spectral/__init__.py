"""Spectral graph analysis (ref: cpp/include/raft/spectral/ — SURVEY.md §2.7).

The reference retains the partition/modularity *analyzers* (the spectral
clustering driver moved to cuVS); both are provided here, plus the matrix
wrappers' semantics (Laplacian / modularity operators) expressed as pure
functions over the sparse layer.
"""

from raft_tpu.spectral.analyzers import (  # noqa: F401
    analyze_partition,
    analyze_modularity,
)
from raft_tpu.spectral.partition import (  # noqa: F401
    modularity_maximization,
    partition,
)
