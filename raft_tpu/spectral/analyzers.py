"""Partition quality analyzers (ref: raft/spectral/partition.cuh:38
`analyzePartition`, modularity_maximization.cuh:31 `analyzeModularity`,
detail/partition.hpp:47-93, detail/modularity_maximization.hpp:42-84,
detail/spectral_util.cuh `construct_indicator`).

The reference loops over clusters, building a dense indicator vector per
cluster and evaluating one SpMV + dot per cluster. Here all indicators are
evaluated at once: the quadratic forms x_i^T L x_i for every cluster i are
the diagonal of H^T L H with H the one-hot [n, k] membership matrix — one
SpMM + one elementwise reduction on the MXU instead of k SpMV round trips.
"""

from __future__ import annotations

import jax.numpy as jnp

from raft_tpu.core.sparse_types import COOMatrix, CSRMatrix
from raft_tpu.sparse import convert
from raft_tpu.util.precision import with_matmul_precision


def _csr(a):
    if isinstance(a, COOMatrix):
        from raft_tpu.sparse import op as sparse_op
        return convert.sorted_coo_to_csr(sparse_op.coo_sort(a))
    return a


def _membership(clusters, n_clusters, dtype):
    clusters = jnp.asarray(clusters).astype(jnp.int32)
    return (clusters[:, None] == jnp.arange(n_clusters)[None, :]).astype(
        dtype)  # [n, k]


def _spmm(csr: CSRMatrix, h):
    """A @ H via gather + segment-sum over nnz (same kernel family as the
    sparse layer's spmv)."""
    row_ids = csr.row_ids()
    gathered = csr.data[:, None] * h[csr.indices]          # [nnz, k]
    out = jnp.zeros((csr.n_rows, h.shape[1]), h.dtype)
    return out.at[row_ids].add(gathered)


@with_matmul_precision
def analyze_partition(res, csr, n_clusters: int, clusters):
    """Returns (edge_cut, cost) for a clustering of a weighted undirected
    graph (ref: partition.cuh:38; cost is the ratio-cut sum of
    x_i^T L x_i / |cluster_i|, edge_cut = sum x_i^T L x_i / 2).
    """
    csr = _csr(csr)
    h = _membership(clusters, n_clusters, csr.data.dtype)   # [n, k]
    # L x = D x - A x ; degrees = row sums of A
    ah = _spmm(csr, h)                                      # [n, k]
    deg = _spmm(csr, jnp.ones((csr.n_rows, 1), csr.data.dtype))[:, 0]
    lh = deg[:, None] * h - ah
    quad = jnp.sum(h * lh, axis=0)                          # x_i^T L x_i, [k]
    sizes = jnp.sum(h, axis=0)
    nonempty = sizes > 0
    edge_cut = jnp.sum(quad) / 2.0
    cost = jnp.sum(jnp.where(nonempty, quad / jnp.maximum(sizes, 1), 0.0))
    return edge_cut, cost


@with_matmul_precision
def analyze_modularity(res, csr, n_clusters: int, clusters):
    """Returns the modularity of a clustering (ref:
    modularity_maximization.cuh:31; detail computes
    sum_i x_i^T B x_i / ||d||_1 with B x = A x - (d^T x / ||d||_1) d).
    """
    csr = _csr(csr)
    h = _membership(clusters, n_clusters, csr.data.dtype)   # [n, k]
    ah = _spmm(csr, h)                                      # [n, k]
    deg = _spmm(csr, jnp.ones((csr.n_rows, 1), csr.data.dtype))[:, 0]
    two_m = jnp.sum(deg)                                    # ||d||_1
    dtx = deg @ h                                           # [k]
    bh = ah - (dtx[None, :] / two_m) * deg[:, None]
    quad = jnp.sum(h * bh, axis=0)
    return jnp.sum(quad) / two_m
