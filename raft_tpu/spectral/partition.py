"""Spectral partitioning / modularity clustering drivers.

Lineage: the spectral *clustering* drivers moved from the reference to
cuVS (`cuvs::cluster::spectral`; the reference keeps the analyzers +
matrix wrappers, spectral/partition.cuh:38). Rebuilt here from this
repo's primitives, exactly as SURVEY.md §7's charter prescribes:

    laplacian (sparse.linalg) → smallest/largest eigenpairs via
    thick-restart Lanczos (sparse.solver) → k-means on the embedding
    (cluster.kmeans) → quality analyzers (spectral.analyzers).

The classic pipeline of von Luxburg's tutorial, with every stage the
TPU-native implementation (ELL-auto SpMV inside Lanczos, fused Lloyd
kernel inside k-means).
"""

from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp

from raft_tpu.cluster.kmeans import KMeansParams, kmeans_fit
from raft_tpu.core.sparse_types import COOMatrix, CSRMatrix
from raft_tpu.sparse import convert
from raft_tpu.sparse.linalg import laplacian, laplacian_normalized
from raft_tpu.sparse.solver.lanczos import LanczosConfig, \
    lanczos_compute_eigenpairs


def _as_csr(a) -> CSRMatrix:
    if isinstance(a, COOMatrix):
        from raft_tpu.sparse import op as sparse_op
        return convert.sorted_coo_to_csr(sparse_op.coo_sort(a))
    return a


def _embed(res, csr: CSRMatrix, n_components: int, which: str,
           normalized: bool, ncv: int, max_iterations: int,
           tolerance: float, seed: int):
    lap = laplacian_normalized(csr) if normalized else laplacian(csr)
    cfg = LanczosConfig(n_components=n_components,
                        max_iterations=max_iterations,
                        ncv=ncv, tolerance=tolerance, which=which,
                        seed=seed)
    vals, vecs = lanczos_compute_eigenpairs(res, lap, cfg)
    return vals, vecs


def partition(res, graph, n_clusters: int, n_eig_vects: int = 0,
              normalized: bool = True, ncv: int = 0,
              max_iterations: int = 200, tolerance: float = 1e-4,
              seed: int = 0, mesh=None, data_axis: str = "data"
              ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Spectral partition of an undirected graph (CSR/COO adjacency).

    Returns (clusters [n], eigenvalues [k], eigenvectors [n, k]).
    Embedding = the ``n_eig_vects`` (default: n_clusters) smallest
    eigenvectors of the (normalized) Laplacian; rows are L2-normalized
    before k-means (the Ng–Jordan–Weiss step), matching the reference
    lineage's transform_eigen_matrix (detail/spectral_util.cuh:33).

    With ``mesh``, the whole pipeline is multi-device on the row-band
    convention: the Laplacian eigensolve runs `eigsh_mnmg` (operator
    row-partitioned over ``mesh[data_axis]``) and the embedding k-means
    runs `kmeans_fit_mnmg` over the same axis — BASELINE config 4
    composed with config 5's mesh.
    """
    csr = _as_csr(graph)
    k = n_eig_vects or n_clusters
    if mesh is not None:
        from raft_tpu.cluster.kmeans import kmeans_fit_mnmg
        from raft_tpu.sparse.solver.lanczos import eigsh_mnmg

        lap = laplacian_normalized(csr) if normalized else laplacian(csr)
        vals, vecs = eigsh_mnmg(lap, k=k, mesh=mesh, axis=data_axis,
                                which="SA", ncv=ncv,
                                maxiter=max_iterations,
                                tol=tolerance, seed=seed)

        def fit(params, emb):
            return kmeans_fit_mnmg(res, params, emb, mesh=mesh,
                                   data_axis=data_axis)
    else:
        vals, vecs = _embed(res, csr, k, "SA", normalized, ncv,
                            max_iterations, tolerance, seed)

        def fit(params, emb):
            return kmeans_fit(res, params, emb)

    # Ng–Jordan–Weiss row normalization + embedding k-means: ONE tail
    # for both pipelines (only the eigensolve and the k-means driver
    # differ between single-device and mesh)
    norms = jnp.linalg.norm(vecs, axis=1, keepdims=True)
    emb = (vecs / jnp.maximum(norms, 1e-12)).astype(jnp.float32)
    c, inertia, labels, _ = fit(
        KMeansParams(n_clusters=n_clusters, seed=seed), emb)
    return labels, vals, vecs


def modularity_maximization(res, graph, n_clusters: int,
                            n_eig_vects: int = 0, ncv: int = 0,
                            max_iterations: int = 200,
                            tolerance: float = 1e-4, seed: int = 0
                            ) -> Tuple[jnp.ndarray, jnp.ndarray,
                                       jnp.ndarray]:
    """Modularity-maximizing clustering: k-means on the LARGEST
    eigenvectors of the modularity matrix B = A - d·dᵀ/2m (lineage:
    modularity_maximization.cuh — the driver moved to cuVS).

    B is dense but never materialized: B·v = A·v - d (dᵀv)/2m is a rank-1
    correction folded into the Lanczos device loop's SpMV (the ``rank1``
    operator of lanczos_compute_eigenpairs); rows of the embedding are
    L2-normalized before k-means.
    """
    import numpy as np

    from raft_tpu.sparse.linalg import csr_row_norm

    csr = _as_csr(graph)
    k = n_eig_vects or n_clusters
    cfg = LanczosConfig(n_components=k, max_iterations=max_iterations,
                        ncv=ncv, tolerance=tolerance, which="LA",
                        seed=seed)
    # degree vector + total edge weight for the rank-1 term
    deg = jnp.asarray(csr_row_norm(csr, "l1"))
    two_m = jnp.maximum(jnp.sum(deg), 1e-12)
    vals, vecs = lanczos_compute_eigenpairs(
        res, csr, cfg, rank1=(deg, deg, -1.0 / float(np.asarray(two_m))))
    norms = jnp.linalg.norm(vecs, axis=1, keepdims=True)
    emb = (vecs / jnp.maximum(norms, 1e-12)).astype(jnp.float32)
    _, _, labels, _ = kmeans_fit(
        res, KMeansParams(n_clusters=n_clusters, seed=seed), emb)
    return labels, vals, vecs
