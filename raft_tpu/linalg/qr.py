"""QR factorization (ref: linalg/qr.cuh — cuSOLVER geqrf/orgqr)."""

from __future__ import annotations

import jax.numpy as jnp


def qr_get_q(res, matrix):
    """Q factor only (ref: qr.cuh qrGetQ)."""
    q, _ = jnp.linalg.qr(jnp.asarray(matrix), mode="reduced")
    return q


def qr_get_qr(res, matrix):
    """(Q, R) (ref: qr.cuh qrGetQR)."""
    return jnp.linalg.qr(jnp.asarray(matrix), mode="reduced")
