"""Rank-1 Cholesky update (ref: linalg/cholesky_r1_update.cuh).

The reference grows an L factor of A by one row/column incrementally:
given L of A[:n-1,:n-1] and the new column A[:,n-1], compute the new row
of L.  Same math here; the triangular solve is `solve_triangular`.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax.scipy.linalg import solve_triangular


def cholesky_r1_update(res, L, A_new_col, n: int, lower: bool = True,
                       eps: float = 0.0):
    """Extend Cholesky factor by one rank.

    Args:
      L: [n, n] buffer whose leading (n-1)×(n-1) block is the factor of the
         previous matrix (lower) — only that block is read.
      A_new_col: the new column A[:n, n-1] (length n).
      n: new size.
    Returns the updated [n, n] factor (lower/upper per ``lower``).
    """
    L = jnp.asarray(L)
    a = jnp.asarray(A_new_col).ravel()
    if not lower:
        L = L.T
    if n == 1:
        val = jnp.sqrt(jnp.maximum(a[0], eps if eps > 0 else a[0]))
        out = L.at[0, 0].set(val)
        return out if lower else out.T
    Lsub = L[: n - 1, : n - 1]
    # Solve L[:n-1,:n-1] · x = a[:n-1]
    x = solve_triangular(Lsub, a[: n - 1], lower=True)
    d_sq = a[n - 1] - jnp.dot(x, x)
    if eps > 0:
        d_sq = jnp.maximum(d_sq, eps)
    d = jnp.sqrt(d_sq)
    out = L.at[n - 1, : n - 1].set(x)
    out = out.at[n - 1, n - 1].set(d)
    out = out.at[: n - 1, n - 1].set(jnp.zeros((n - 1,), dtype=L.dtype))
    return out if lower else out.T
