"""Rank-1 Cholesky update (ref: linalg/cholesky_r1_update.cuh).

The reference grows an L factor of A by one row/column incrementally:
given L of A[:n-1,:n-1] and the new column A[:,n-1], compute the new row
of L.  Same math here; the triangular solve is `solve_triangular`.

Numerical guardrails: the new diagonal pivot ``d² = a[n-1] - xᵀx`` goes
negative exactly when the update is not positive definite at working
precision — the reference's ``potrf info > 0`` condition, which this
routine used to bury in a silent ``sqrt(negative) = NaN`` whenever
``eps=0``.  Under guard mode ``check`` the negative pivot raises
:class:`~raft_tpu.core.guards.IllConditionedError`; under ``recover``
the solve + inner product re-run one ladder tier up (float64 on host —
the pivot loss is cancellation in f32, not matmul-tier noise) and only
an f64-confirmed negative pivot raises.  Mode ``off`` keeps today's NaN.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.scipy.linalg import solve_triangular

from raft_tpu.core import trace
from raft_tpu.core.guards import IllConditionedError, resolve_guard_mode


def _f64_pivot(Lsub, a, n: int):
    """Escalated pivot recomputation: the triangular solve and inner
    product at the f64 host rung (see util/numerics.py LADDER)."""
    from raft_tpu.util.numerics import f64_host

    L64, a64 = f64_host(Lsub, a)
    x64 = np.linalg.solve(np.tril(L64), a64[: n - 1])
    return x64, float(a64[n - 1] - x64 @ x64)


def cholesky_r1_update(res, L, A_new_col, n: int, lower: bool = True,
                       eps: float = 0.0,
                       guard_mode: Optional[str] = None):
    """Extend Cholesky factor by one rank.

    Args:
      L: [n, n] buffer whose leading (n-1)×(n-1) block is the factor of the
         previous matrix (lower) — only that block is read.
      A_new_col: the new column A[:n, n-1] (length n).
      n: new size.
      guard_mode: per-call override of the numerical guard mode
        ('off' | 'check' | 'recover'); None defers to the global knob.
    Returns the updated [n, n] factor (lower/upper per ``lower``).

    Raises :class:`~raft_tpu.core.guards.IllConditionedError` when the
    update pivot is negative with ``eps<=0`` under guard mode
    'check'/'recover' (after f64 confirmation in 'recover').
    """
    mode = resolve_guard_mode(guard_mode)
    L = jnp.asarray(L)
    a = jnp.asarray(A_new_col).ravel()
    # guards need host values; inside a jit trace the taxonomy cannot
    # raise data-dependently — the unguarded math traces as before
    traced = isinstance(L, jax.core.Tracer) or isinstance(a, jax.core.Tracer)
    if not lower:
        L = L.T
    if n == 1:
        if mode != "off" and eps <= 0 and not traced \
                and not float(a[0]) > 0:
            raise IllConditionedError(
                f"cholesky_r1_update: first pivot A[0,0] = {float(a[0])!r}"
                " is not positive — the matrix is not positive definite",
                op="linalg.cholesky_r1_update")
        val = jnp.sqrt(jnp.maximum(a[0], eps if eps > 0 else a[0]))
        out = L.at[0, 0].set(val)
        return out if lower else out.T
    Lsub = L[: n - 1, : n - 1]
    # Solve L[:n-1,:n-1] · x = a[:n-1]
    x = solve_triangular(Lsub, a[: n - 1], lower=True)
    d_sq = a[n - 1] - jnp.dot(x, x)
    if mode != "off" and eps <= 0 and not traced:
        d_sq_h = float(d_sq)
        if not d_sq_h > 0:      # catches negative, zero, and NaN pivots
            if mode == "recover":
                trace.record_event("guards.escalate",
                                   op="linalg.cholesky_r1_update",
                                   tier="f64", pivot=d_sq_h)
                x64, d_sq64 = _f64_pivot(Lsub, a, n)
                if d_sq64 > 0:
                    x = jnp.asarray(x64, L.dtype)
                    d_sq = jnp.asarray(d_sq64, L.dtype)
                else:
                    raise IllConditionedError(
                        "cholesky_r1_update: pivot remains non-positive "
                        f"({d_sq64!r}) at the f64 ladder rung — the "
                        "updated matrix is genuinely not positive "
                        "definite (non-PSD rank-1 update)",
                        op="linalg.cholesky_r1_update")
            else:
                raise IllConditionedError(
                    f"cholesky_r1_update: negative pivot d² = {d_sq_h!r} "
                    f"at step n={n} with eps=0 — non-PSD update at "
                    "working precision (guard_mode='recover' retries at "
                    "f64; guard_mode='off' restores silent NaN)",
                    op="linalg.cholesky_r1_update")
    if eps > 0:
        d_sq = jnp.maximum(d_sq, eps)
    d = jnp.sqrt(d_sq)   # guarded: pivot checked above / eps floor
    out = L.at[n - 1, : n - 1].set(x)
    out = out.at[n - 1, n - 1].set(d)
    out = out.at[: n - 1, n - 1].set(jnp.zeros((n - 1,), dtype=L.dtype))
    return out if lower else out.T
