"""Row/column reductions (ref: linalg/reduce.cuh, coalesced_reduction-inl.cuh,
strided_reduction.cuh, reduce_rows_by_key.cuh, reduce_cols_by_key.cuh).

The reference dispatches coalesced vs strided kernel families by layout
(reduce.cuh:63,148) and picks thin/medium/thick block policies by shape.  On
TPU a reduction is a single XLA `reduce` the compiler tiles onto the VPU; the
layout dispatch collapses to an ``axis`` argument.  ``apply`` selects whether
the reduction runs along rows or columns, matching the reference's
``Apply::ALONG_ROWS/ALONG_COLUMNS`` vocabulary (linalg_types.hpp).
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp

from raft_tpu.core import operators as ops

ALONG_ROWS = "along_rows"        # reduce each row → one value per row
ALONG_COLUMNS = "along_columns"  # reduce each column → one value per column


def _axis(apply: str) -> int:
    if apply == ALONG_ROWS:
        return 1
    if apply == ALONG_COLUMNS:
        return 0
    raise ValueError(f"apply must be ALONG_ROWS or ALONG_COLUMNS, got {apply}")


def reduce(res, data, apply: str = ALONG_ROWS,
           init: Optional[float] = None,
           main_op: Callable = ops.identity_op,
           reduce_op: Callable = ops.add_op,
           final_op: Callable = ops.identity_op,
           inplace: bool = False, out=None):
    """Generalized reduction: final_op(reduce(main_op(x), init))
    (ref: reduce.cuh raft::linalg::reduce).

    ``init`` defaults to the reduction's identity (the reference makes the
    caller supply it; a defaulted 0 must not clamp min/max results).
    """
    data = jnp.asarray(data)
    axis = _axis(apply)
    mapped = main_op(data)
    if reduce_op is ops.add_op:
        red = jnp.sum(mapped, axis=axis)
        if init is not None:
            red = red + jnp.asarray(init, dtype=mapped.dtype)
    elif reduce_op is ops.min_op:
        red = jnp.min(mapped, axis=axis)
        if init is not None:
            red = jnp.minimum(red, jnp.asarray(init, dtype=mapped.dtype))
    elif reduce_op is ops.max_op:
        red = jnp.max(mapped, axis=axis)
        if init is not None:
            red = jnp.maximum(red, jnp.asarray(init, dtype=mapped.dtype))
    elif reduce_op is ops.mul_op:
        red = jnp.prod(mapped, axis=axis)
        if init is not None:
            red = red * jnp.asarray(init, dtype=mapped.dtype)
    else:
        if init is None:
            raise ValueError(
                "a custom reduce_op needs an explicit init (its identity); "
                "there is no way to infer it")
        init_val = jnp.asarray(init, dtype=mapped.dtype)
        red = jax.lax.reduce(mapped, init_val,
                             lambda a, b: reduce_op(a, b), (axis,))
    out_val = final_op(red)
    if inplace and out is not None:
        return reduce_op(out, out_val)
    return out_val


def coalesced_reduction(res, data, init: Optional[float] = None, **kw):
    """Reduce along the contiguous (last) dimension
    (ref: coalesced_reduction.cuh)."""
    return reduce(res, data, apply=ALONG_ROWS, init=init, **kw)


def strided_reduction(res, data, init: Optional[float] = None, **kw):
    """Reduce along the strided (first) dimension
    (ref: strided_reduction.cuh)."""
    return reduce(res, data, apply=ALONG_COLUMNS, init=init, **kw)


# Up to this many keys the one-hot contraction beats segment-sum (the
# r2 sweep measured the segment path at ~100 GB/s vs ~750 for
# contraction-shaped reductions; scatter serializes on TPU).
_MATMUL_KEY_LIMIT = 1024


def _keyed_rowsum_matmul(data, keys, n_keys: int):
    """out[k, :] = sum_{i: keys[i]==k} data[i, :] as a one-hot MXU
    contraction, row-chunked so the transient bf16 one-hot stays small.

    Precision floor: this op replaces an EXACT segment sum, so it never
    follows the tier below 'high' — the one-hot side is exactly bf16
    (one pass economy) and the data side always gets its bf16 hi/lo
    split (2 MXU passes, ~2^-17), even when the session opted into the
    single-pass 'default' tier (which would round data to ~8 mantissa
    bits — a silent downgrade of a formerly exact op). 'highest' is
    honored. Same chunked one-hot shape as the Lloyd interpreter
    fallback (contractions._lloyd_jnp_chunked lineage) — kept separate
    because that site also carries counts and runs inside the
    kernel-reference path."""
    from raft_tpu.linalg.contractions import (_kernel_dot_exact_lhs,
                                              _round_to_bf16_f32)
    from raft_tpu.util.precision import current_mode

    n_rows = data.shape[0]
    # int32 key domain: narrow key dtypes (uint8 etc.) would overflow on
    # the iota and on the out-of-range pad sentinel
    keys = keys.astype(jnp.int32)
    chunk = max(8, (32 << 20) // max(2 * n_keys, 1))
    chunk = min(chunk, n_rows)
    n_chunks = -(-n_rows // chunk)
    pad = n_chunks * chunk - n_rows
    if pad:
        data = jnp.pad(data, ((0, pad), (0, 0)))
        keys = jnp.pad(keys, (0, pad), constant_values=n_keys)
    dc = data.reshape(n_chunks, chunk, data.shape[1])
    kc = keys.reshape(n_chunks, chunk)
    iota = jnp.arange(n_keys, dtype=jnp.int32)

    exact_tier = current_mode() == "highest"

    def body(acc, sl):
        d, k = sl
        oh = (iota[:, None] == k[None, :]).astype(jnp.bfloat16)
        d = d.astype(jnp.float32)
        if exact_tier:
            return acc + _kernel_dot_exact_lhs(oh, d), None
        # tier-independent 'high' floor: bf16 hi/lo split of the data
        # side, one-hot side exact (see docstring)
        d_hi_f = _round_to_bf16_f32(d)
        part = jnp.dot(oh, d_hi_f.astype(jnp.bfloat16),
                       preferred_element_type=jnp.float32)
        part = part + jnp.dot(oh, (d - d_hi_f).astype(jnp.bfloat16),
                              preferred_element_type=jnp.float32)
        return acc + part, None

    acc0 = jnp.zeros((n_keys, data.shape[1]), jnp.float32)
    out, _ = jax.lax.scan(body, acc0, (dc, kc))
    return out


def reduce_rows_by_key(res, data, keys, n_unique_keys: int, weights=None):
    """Sum rows that share a key: out[k, :] = Σ_{i: keys[i]==k} w[i]·data[i, :]
    (ref: reduce_rows_by_key.cuh).

    TPU formulation: small key counts ride a one-hot MXU contraction at
    the library precision tier (exact one-hot side; the r2 sweep put the
    segment path ~7x below the bandwidth roofline); large key counts and
    integer data keep the segment-sum (sorted-segment scatter, exact in
    the input dtype).
    """
    data = jnp.asarray(data)
    keys = jnp.asarray(keys)
    if weights is not None:
        data = data * jnp.asarray(weights)[:, None].astype(data.dtype)
    # fast path only for dtypes the f32 contraction can represent —
    # f64 (x64 mode) keeps the exact segment accumulation
    if (n_unique_keys <= _MATMUL_KEY_LIMIT and data.shape[0] > 0
            and data.dtype in (jnp.float32, jnp.bfloat16, jnp.float16)):
        return _keyed_rowsum_matmul(data, keys, n_unique_keys
                                    ).astype(data.dtype)
    return jax.ops.segment_sum(data, keys, num_segments=n_unique_keys)


def reduce_cols_by_key(res, data, keys, n_unique_keys: int):
    """Sum columns that share a key: out[:, k] = Σ_{j: keys[j]==k} data[:, j]
    (ref: reduce_cols_by_key.cuh)."""
    data = jnp.asarray(data)
    keys = jnp.asarray(keys)
    return jax.ops.segment_sum(data.T, keys, num_segments=n_unique_keys).T
