"""Row/column reductions (ref: linalg/reduce.cuh, coalesced_reduction-inl.cuh,
strided_reduction.cuh, reduce_rows_by_key.cuh, reduce_cols_by_key.cuh).

The reference dispatches coalesced vs strided kernel families by layout
(reduce.cuh:63,148) and picks thin/medium/thick block policies by shape.  On
TPU a reduction is a single XLA `reduce` the compiler tiles onto the VPU; the
layout dispatch collapses to an ``axis`` argument.  ``apply`` selects whether
the reduction runs along rows or columns, matching the reference's
``Apply::ALONG_ROWS/ALONG_COLUMNS`` vocabulary (linalg_types.hpp).
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp

from raft_tpu.core import operators as ops

ALONG_ROWS = "along_rows"        # reduce each row → one value per row
ALONG_COLUMNS = "along_columns"  # reduce each column → one value per column


def _axis(apply: str) -> int:
    if apply == ALONG_ROWS:
        return 1
    if apply == ALONG_COLUMNS:
        return 0
    raise ValueError(f"apply must be ALONG_ROWS or ALONG_COLUMNS, got {apply}")


def reduce(res, data, apply: str = ALONG_ROWS,
           init: Optional[float] = None,
           main_op: Callable = ops.identity_op,
           reduce_op: Callable = ops.add_op,
           final_op: Callable = ops.identity_op,
           inplace: bool = False, out=None):
    """Generalized reduction: final_op(reduce(main_op(x), init))
    (ref: reduce.cuh raft::linalg::reduce).

    ``init`` defaults to the reduction's identity (the reference makes the
    caller supply it; a defaulted 0 must not clamp min/max results).
    """
    data = jnp.asarray(data)
    axis = _axis(apply)
    mapped = main_op(data)
    if reduce_op is ops.add_op:
        red = jnp.sum(mapped, axis=axis)
        if init is not None:
            red = red + jnp.asarray(init, dtype=mapped.dtype)
    elif reduce_op is ops.min_op:
        red = jnp.min(mapped, axis=axis)
        if init is not None:
            red = jnp.minimum(red, jnp.asarray(init, dtype=mapped.dtype))
    elif reduce_op is ops.max_op:
        red = jnp.max(mapped, axis=axis)
        if init is not None:
            red = jnp.maximum(red, jnp.asarray(init, dtype=mapped.dtype))
    elif reduce_op is ops.mul_op:
        red = jnp.prod(mapped, axis=axis)
        if init is not None:
            red = red * jnp.asarray(init, dtype=mapped.dtype)
    else:
        if init is None:
            raise ValueError(
                "a custom reduce_op needs an explicit init (its identity); "
                "there is no way to infer it")
        init_val = jnp.asarray(init, dtype=mapped.dtype)
        red = jax.lax.reduce(mapped, init_val,
                             lambda a, b: reduce_op(a, b), (axis,))
    out_val = final_op(red)
    if inplace and out is not None:
        return reduce_op(out, out_val)
    return out_val


def coalesced_reduction(res, data, init: Optional[float] = None, **kw):
    """Reduce along the contiguous (last) dimension
    (ref: coalesced_reduction.cuh)."""
    return reduce(res, data, apply=ALONG_ROWS, init=init, **kw)


def strided_reduction(res, data, init: Optional[float] = None, **kw):
    """Reduce along the strided (first) dimension
    (ref: strided_reduction.cuh)."""
    return reduce(res, data, apply=ALONG_COLUMNS, init=init, **kw)


def reduce_rows_by_key(res, data, keys, n_unique_keys: int, weights=None):
    """Sum rows that share a key: out[k, :] = Σ_{i: keys[i]==k} w[i]·data[i, :]
    (ref: reduce_rows_by_key.cuh).

    TPU formulation: segment-sum — a scatter-add XLA lowers to an efficient
    sorted-segment reduction; no atomics needed.
    """
    data = jnp.asarray(data)
    keys = jnp.asarray(keys)
    if weights is not None:
        data = data * jnp.asarray(weights)[:, None].astype(data.dtype)
    return jax.ops.segment_sum(data, keys, num_segments=n_unique_keys)


def reduce_cols_by_key(res, data, keys, n_unique_keys: int):
    """Sum columns that share a key: out[:, k] = Σ_{j: keys[j]==k} data[:, j]
    (ref: reduce_cols_by_key.cuh)."""
    data = jnp.asarray(data)
    keys = jnp.asarray(keys)
    return jax.ops.segment_sum(data.T, keys, num_segments=n_unique_keys).T
