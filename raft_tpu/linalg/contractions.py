"""Tiled GEMM-like contraction kernels — the raft_tpu analogue of the
reference's contractions engine (linalg/contractions.cuh:52-80,
linalg/detail/contractions.cuh:16-309 `Contractions_NT`).

The reference exposes a register/smem tiling policy (Kblk/Mblk/Nblk/veclen)
that the (now-cuVS) pairwise-distance and fused-L2-argmin kernels were built
on.  The TPU equivalent is a Pallas block template: a (TM, TN) output tile
per grid step, X/Y tiles staged in VMEM, the inner product on the MXU via
``jnp.dot``, and the epilogue (norm add, min/argmin) fused on the VPU.  The
grid's second axis is the reduction axis over Y tiles, so the running
min/argmin accumulates in the resident output block — the same dataflow the
CUDA kernel achieves with registers, expressed as a revisited block.

Two entry kernels:

- :func:`pairwise_l2_pallas` — full m×n squared-L2 distance matrix
  (the primitive under raft_tpu.distance.pairwise_distance).
- :func:`fused_l2_argmin_pallas` — fused distance + argmin, never
  materializing the m×n matrix (the k-means hot kernel; the reference's
  fusedL2NN built from this same contraction layer).
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from raft_tpu.util.math import cdiv, round_up_to_multiple
from raft_tpu.util.pallas_utils import use_interpret


def _pad2(x: jnp.ndarray, rows: int, cols: int) -> jnp.ndarray:
    pr, pc = rows - x.shape[0], cols - x.shape[1]
    if pr or pc:
        return jnp.pad(x, ((0, pr), (0, pc)))
    return x


# ---------------------------------------------------------------------------
# pairwise L2: D[i, j] = ||x_i||² - 2·x_i·y_j + ||y_j||²
# ---------------------------------------------------------------------------


def _l2_tile_kernel(x_ref, y_ref, out_ref):
    x = x_ref[:]
    y = y_ref[:]
    xn = jnp.sum(x * x, axis=1, keepdims=True)
    yn = jnp.sum(y * y, axis=1, keepdims=True)
    cross = jnp.dot(x, y.T, preferred_element_type=jnp.float32)
    out_ref[:] = xn - 2.0 * cross + yn.T


def _inside_shard_map(*arrays) -> bool:
    """True when tracing inside shard_map (operands carry varying mesh
    axes). The Pallas kernels fall back to the jnp formulation there: the
    per-shard problem is tile-sized already and pallas_call's vma plumbing
    under the interpreter rejects replicated×varying mixes; XLA fuses the
    jnp path onto the MXU just as well at shard granularity."""
    return any(bool(getattr(jax.typeof(a), "vma", None)) for a in arrays)


@functools.partial(jax.jit, static_argnames=("tm", "tn"))
def _pairwise_l2_padded(x, y, tm: int, tn: int):
    m, k = x.shape
    n = y.shape[0]
    grid = (m // tm, n // tn)
    return pl.pallas_call(
        _l2_tile_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((tm, k), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((tn, k), lambda i, j: (j, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((tm, tn), lambda i, j: (i, j),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((m, n), jnp.float32),
        interpret=use_interpret(),
    )(x, y)


def pairwise_l2_pallas(x, y, sqrt: bool = False,
                       tm: int = 256, tn: int = 256) -> jnp.ndarray:
    """Squared (or rooted) L2 distance matrix between rows of x and y.

    x: [m, k] f32/bf16, y: [n, k].  Inputs are zero-padded to tile multiples
    (zero feature padding does not change distances).
    """
    x = jnp.asarray(x)
    y = jnp.asarray(y)
    m, k = x.shape
    n = y.shape[0]
    if _inside_shard_map(x, y):
        out = (jnp.sum(x * x, 1, keepdims=True)
               - 2.0 * jnp.dot(x, y.T, preferred_element_type=jnp.float32)
               + jnp.sum(y * y, 1)[None, :])
    else:
        tm = min(tm, round_up_to_multiple(m, 8))
        tn = min(tn, round_up_to_multiple(n, 128))
        mp = round_up_to_multiple(m, tm)
        np_ = round_up_to_multiple(n, tn)
        kp = round_up_to_multiple(k, 128)
        out = _pairwise_l2_padded(_pad2(x, mp, kp), _pad2(y, np_, kp),
                                  tm, tn)
        out = out[:m, :n]
    out = jnp.maximum(out, 0.0)
    return jnp.sqrt(out) if sqrt else out


# ---------------------------------------------------------------------------
# fused L2 + argmin (the k-means assignment kernel; ref: cuVS fusedL2NN
# built on this contraction layer)
# ---------------------------------------------------------------------------


def _fused_l2_argmin_kernel(x_ref, y_ref, val_ref, idx_ref, *,
                            tn: int, n_valid: int):
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        val_ref[:] = jnp.full_like(val_ref, jnp.inf)
        idx_ref[:] = jnp.zeros_like(idx_ref)

    x = x_ref[:]
    y = y_ref[:]
    xn = jnp.sum(x * x, axis=1, keepdims=True)
    yn = jnp.sum(y * y, axis=1, keepdims=True)
    d = xn - 2.0 * jnp.dot(x, y.T, preferred_element_type=jnp.float32) + yn.T

    tm = d.shape[0]
    col = jax.lax.broadcasted_iota(jnp.int32, (tm, tn), 1)
    gcol = col + j * tn
    # Mask padded centroid rows so they never win the argmin.
    d = jnp.where(gcol < n_valid, d, jnp.inf)

    tile_min = jnp.min(d, axis=1, keepdims=True)
    # Smallest index among ties — the reference's KVP argmin tie rule.
    tile_arg = jnp.min(jnp.where(d == tile_min, gcol, jnp.iinfo(jnp.int32).max),
                       axis=1, keepdims=True)

    prev_val = val_ref[:]
    prev_idx = idx_ref[:]
    better = tile_min[:, 0] < prev_val
    val_ref[:] = jnp.where(better, tile_min[:, 0], prev_val)
    idx_ref[:] = jnp.where(better, tile_arg[:, 0], prev_idx)


@functools.partial(jax.jit, static_argnames=("tm", "tn", "n_valid"))
def _fused_l2_argmin_padded(x, y, tm: int, tn: int, n_valid: int):
    m, k = x.shape
    n = y.shape[0]
    grid = (m // tm, n // tn)
    kernel = functools.partial(_fused_l2_argmin_kernel, tn=tn,
                               n_valid=n_valid)
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((tm, k), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((tn, k), lambda i, j: (j, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((tm,), lambda i, j: (i,),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((tm,), lambda i, j: (i,),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((m,), jnp.float32),
            jax.ShapeDtypeStruct((m,), jnp.int32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=use_interpret(),
    )(x, y)


def fused_l2_argmin_pallas(x, y, tm: int = 1024, tn: int = 256
                           ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(min_dist², argmin) of each row of x against rows of y, fused.

    Never materializes the m×n distance matrix: HBM traffic is O(mk + nk + m)
    instead of O(mn) — the property that makes Lloyd iterations bandwidth-
    friendly at k=4096.

    ``tm`` is a hint: honored in interpreter mode, but rounded up to a
    1024-multiple on hardware (XLA's 1-D layout constraint — see inline
    comment). Workloads whose forced tiles exceed the VMEM budget fall
    back to the jnp formulation, as do shard_map-traced calls.
    """
    x = jnp.asarray(x)
    y = jnp.asarray(y)
    m, k = x.shape
    n = y.shape[0]
    tn = min(tn, round_up_to_multiple(n, 128))
    kp = round_up_to_multiple(k, 128)
    if use_interpret():
        tm = min(tm, round_up_to_multiple(m, 8))   # honor the caller's tile
    else:
        # Compiled path: the 1-D val/idx outputs are blocked (tm,) and XLA
        # lays large 1-D f32/i32 arrays out with tile T(1024), so tm must
        # be a 1024-multiple (verified on v5e: T(512) block fails Mosaic
        # layout checks). Callers tune VMEM via tn/k, not tm.
        tm = max(1024, round_up_to_multiple(tm, 1024))
    # Fall back to the jnp formulation when inside shard_map (see
    # _inside_shard_map) or when the forced row tile would blow VMEM
    # (x tile + y tile at ~16 MB/core budget; large-k workloads).
    vmem_bytes = (tm * kp + tn * kp) * 4
    if _inside_shard_map(x, y) or vmem_bytes > 12 * 1024 * 1024:
        d = (jnp.sum(x * x, 1, keepdims=True)
             - 2.0 * jnp.dot(x, y.T, preferred_element_type=jnp.float32)
             + jnp.sum(y * y, 1)[None, :])
        return (jnp.maximum(jnp.min(d, axis=1), 0.0),
                jnp.argmin(d, axis=1).astype(jnp.int32))
    mp = round_up_to_multiple(m, tm)
    np_ = round_up_to_multiple(n, tn)
    val, idx = _fused_l2_argmin_padded(_pad2(x, mp, kp), _pad2(y, np_, kp),
                                       tm, tn, n)
    return jnp.maximum(val[:m], 0.0), idx[:m]
