"""Tiled GEMM-like contraction kernels — the raft_tpu analogue of the
reference's contractions engine (linalg/contractions.cuh:52-80,
linalg/detail/contractions.cuh:16-309 `Contractions_NT`).

The reference exposes a register/smem tiling policy (Kblk/Mblk/Nblk/veclen)
that the (now-cuVS) pairwise-distance and fused-L2-argmin kernels were built
on.  The TPU equivalent is a Pallas block template: X row-tiles streamed
through VMEM, Y (the centroid/query side) resident in VMEM, the inner
product on the MXU via ``jnp.dot``, and the epilogue (norm add, min/argmin,
one-hot accumulation) fused on the VPU/MXU.  TPU grids are sequential per
core, so accumulator blocks (centroid sums/counts) live in revisited output
blocks — the dataflow the CUDA kernel achieves with registers and atomics,
expressed as resident VMEM state.

Three entry kernels:

- :func:`pairwise_l2_pallas` — full m×n squared-L2 distance matrix
  (the primitive under raft_tpu.distance.pairwise_distance).
- :func:`fused_l2_argmin_pallas` — fused distance + argmin, never
  materializing the m×n matrix (the reference's fusedL2NN lineage).
- :func:`fused_lloyd_pallas` — a FULL Lloyd iteration in one kernel:
  distance + argmin + one-hot centroid sum/count accumulation on the MXU.
  Reads X exactly once per iteration; the centroid update costs a second
  matmul instead of a scatter (TPU has no fast scatter; the one-hot matmul
  runs at MXU rate — measured 9.6 ms vs segment_sum's 22.4 ms at 1M×128,
  k=1024 on v5e).

All kernels run inside shard_map with check_vma=True (per-shard MNMG path):
operands are pcast to the joint varying-axes set and out_shapes carry vma
(see raft_tpu.util.pallas_utils).
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from raft_tpu.matrix.epilogue import (argmin_ref, assign_onehot,
                                      iota_argmin, label_onehot,
                                      masked_fold)
from raft_tpu.util.math import round_up_to_multiple
from raft_tpu.util.pallas_utils import (interpret_needs_ref, join_vma,
                                        out_struct, pallas_call)
from raft_tpu.util.precision import current_mode, with_matmul_precision

# Per-kernel VMEM working-set budget (v5e has ~16 MB/core; leave headroom
# for Mosaic's own buffers and double-buffered pipelining).
_VMEM_BUDGET = 10 * 1024 * 1024


def _kernel_dot(a, b, exact_lhs: bool = False):
    """``a @ b`` with f32 accumulation at the policy's accuracy tier,
    spelled so it lowers under Mosaic (which rejects Precision.HIGH):

    - 'default': one bf16 MXU pass (~8 mantissa bits) — the fast path.
    - 'high': manual bf16 hi/lo split — a = hi + lo with both halves bf16,
      a·b ≈ hi·hi + hi·lo + lo·hi (3 MXU passes, ~2^-17 relative; the
      dropped lo·lo term is below that). This is the same bf16x3
      decomposition XLA uses for Precision.HIGH outside kernels.
    - 'highest': full f32 (Mosaic lowers HIGHEST natively) — the
      accuracy contract of the reference's CUBLAS_COMPUTE_32F / f32-FMA
      kernels (ref: linalg/detail/cublaslt_wrappers.hpp:28-62).

    ``exact_lhs=True`` declares that ``a``'s values are exactly
    bf16-representable (a one-hot 0/1 matrix): its lo half is identically
    zero, so the 'high' tier drops that pass (2 passes instead of 3).
    Non-f32 inputs (bf16) take a single exact-multiply pass regardless.
    """
    mode = current_mode()
    f32 = jnp.float32
    bf16 = jnp.bfloat16
    if a.dtype == bf16 and b.dtype == bf16:
        # both already bf16: one MXU pass multiplies them exactly
        # (bf16×bf16 with f32 accumulation loses nothing)
        return jnp.dot(a, b, preferred_element_type=f32,
                       precision=_ONE_PASS)
    # ONLY bf16 is exactly representable in a split's hi half (its lo is
    # identically zero, so that pass can be skipped — the same economy
    # exact_lhs declares for one-hot matrices). f16/f64 are NOT: f16
    # carries 10 mantissa bits vs bf16's 7; f64 carries 52.
    a_exact = exact_lhs or a.dtype == bf16
    b_exact = b.dtype == bf16
    if a.dtype != f32 or b.dtype != f32:
        # mixed or non-f32 dtypes: promote to a common f32 pair so every
        # operand still gets the tier's mantissa handling — the old
        # early-return silently truncated non-f32 cases to one bf16 pass
        # even at tier 'highest' (round-2 advisor finding)
        a, b = a.astype(f32), b.astype(f32)
    if mode == "default":
        return jnp.dot(a, b, preferred_element_type=f32,
                       precision=_ONE_PASS)
    if mode == "high":
        a_hi_f = _round_to_bf16_f32(a)
        b_hi_f = _round_to_bf16_f32(b)
        a_hi = a_hi_f.astype(jnp.bfloat16)
        b_hi = b_hi_f.astype(jnp.bfloat16)
        out = jnp.dot(a_hi, b_hi, preferred_element_type=f32,
                      precision=_ONE_PASS)
        if not b_exact:
            b_lo = (b - b_hi_f).astype(jnp.bfloat16)
            out = out + jnp.dot(a_hi, b_lo, preferred_element_type=f32,
                                precision=_ONE_PASS)
        if not a_exact:
            a_lo = (a - a_hi_f).astype(jnp.bfloat16)
            out = out + jnp.dot(a_lo, b_hi, preferred_element_type=f32,
                                precision=_ONE_PASS)
        return out
    return jnp.dot(a, b, preferred_element_type=f32,
                   precision=jax.lax.Precision.HIGHEST)


def _kernel_dot_exact_lhs(a, b):
    return _kernel_dot(a, b, exact_lhs=True)


def _pad2(x: jnp.ndarray, rows: int, cols: int) -> jnp.ndarray:
    pr, pc = rows - x.shape[0], cols - x.shape[1]
    if pr or pc:
        return jnp.pad(x, ((0, pr), (0, pc)))
    return x


def _round_to_bf16_f32(a):
    """Round f32 to its nearest-bf16 value (round-half-to-even), KEPT in
    f32 — via integer bit arithmetic.

    The natural spelling ``a_hi.astype(f32)`` (a bf16→f32 convert right
    after an f32→bf16 one) is a convert PAIR that XLA's algebraic
    simplifier deletes under ``--xla_allow_excess_precision`` (on by
    default on TPU): the residual ``a - a_hi`` then folds to ZERO and the
    bf16x3 'high' tier silently becomes ONE bf16 pass. That is invisible
    on CPU (every CPU path is f32-exact) and was caught only by the
    on-chip smoke tier (pairwise rel-err ~1.5e-3 ≈ single-pass, knn
    agreement 0.95). ``lax.reduce_precision`` is the canonical guard but
    has no Mosaic lowering, so kernels and HBM pre-split share this
    bitcast spelling instead — it rounds identically to ``astype``
    (pinned by tests/test_precision.py) and is opaque to the simplifier.

    NaN inputs produce a GARBAGE hi half (the +0x7FFF carry can walk the
    payload through the exponent into the sign bit: quiet-NaN 0x7FC00000
    → inf, full-payload 0x7FFFFFFF → -0.0) — but the lo half
    ``a - hi`` is NaN for every NaN input, so NaN still propagates
    through any split dot that includes the lo pass. Callers that skip
    the lo pass (``exact_lhs``/bf16-exact operands) never see NaN there:
    bf16 inputs take the single-pass branch before any split.
    """
    u = jax.lax.bitcast_convert_type(a, jnp.uint32)
    u = u + jnp.uint32(0x7FFF) + ((u >> 16) & jnp.uint32(1))
    return jax.lax.bitcast_convert_type(u & jnp.uint32(0xFFFF0000),
                                        jnp.float32)


def _split_hi_lo(a):
    """f32 → (hi, lo) bf16 halves with a ≈ hi + lo (~2^-17 residual).

    Done ONCE in HBM before the kernel launch: the hi/lo pair is the
    tier-'high' operand format, so kernels never re-split per grid step
    (the resident-Y kernels used to pay the split np_×kp cast every one
    of their m/tm steps), and the pair costs exactly the same bytes as
    the f32 original (2+2 vs 4). The hi rounding goes through
    :func:`_round_to_bf16_f32` so the residual survives XLA's
    excess-precision convert-pair elision."""
    hi_f = _round_to_bf16_f32(a)
    hi = hi_f.astype(jnp.bfloat16)
    lo = (a - hi_f).astype(jnp.bfloat16)
    return hi, lo


def _use_split(*arrays) -> bool:
    """Tier-'high' f32 operands take the pre-split bf16-pair kernels."""
    return current_mode() == "high" and all(
        a.dtype == jnp.float32 for a in arrays)


_ONE_PASS = jax.lax.Precision.DEFAULT            # bf16 multiply is exact


def _packed_split_default() -> bool:
    """Opt-in default for the depth-packed bf16x3 spelling
    (``RAFT_TPU_SPLIT_PACKED=1``), threaded into the kernels as a STATIC
    jit argument. CAVEAT: the env is read when the kernel entries
    (fused_lloyd_pallas / fused_argmin_pallas) run — if a caller wraps
    them in its own jax.jit (lloyd_step does), the read
    happens at that trace and is NOT in the outer cache key, so flipping
    the env mid-process reuses the stale executable. Callers that need
    to vary the spelling at runtime must pass ``packed=`` explicitly
    (what benches/tune_northstar.py does); the env var is a process-level
    default, set before first use."""
    from raft_tpu.core import env

    return env.read("RAFT_TPU_SPLIT_PACKED")


def _cross_split(xh, xl, yh_t, yl_t, packed: bool = False):
    """x·yᵀ from pre-split bf16 halves: hi·hi + hi·lo + lo·hi (the bf16x3
    decomposition; the dropped lo·lo term is ~2^-34 relative).

    ``packed``: concatenate the three dots along the CONTRACTION dim into
    one 3k-deep dot — the same three product sets and FLOPs, but one dot
    dispatch instead of three plus two (tm × np_) f32 VPU adds, which may
    pipeline better at small k. The f32 accumulation ORDER differs (one
    running sum across 3k vs per-dot totals then adds), so results agree
    to ~1 ulp, not bitwise. Benched by benches/tune_northstar.py; becomes
    the default only if hardware data says so."""
    f32 = jnp.float32
    if packed:
        xcat = jnp.concatenate([xh, xh, xl], axis=1)        # (tm, 3k)
        ycat = jnp.concatenate([yh_t, yl_t, yh_t], axis=0)  # (3k, np_)
        return jnp.dot(xcat, ycat, preferred_element_type=f32,
                       precision=_ONE_PASS)
    return (jnp.dot(xh, yh_t, preferred_element_type=f32,
                    precision=_ONE_PASS)
            + jnp.dot(xh, yl_t, preferred_element_type=f32,
                      precision=_ONE_PASS)
            + jnp.dot(xl, yh_t, preferred_element_type=f32,
                      precision=_ONE_PASS))


def _metric_tile_split(xh, xl, xn, yh, yl, yn, metric: str,
                       packed: bool = False):
    """Split-operand twin of :func:`_metric_tile`. ``xn`` (tm, 1) and
    ``yn`` (1, np_) are squared norms precomputed OUTSIDE in full f32 —
    more accurate than the in-kernel recompute they replace."""
    cross = _cross_split(xh, xl, yh.T, yl.T, packed=packed)
    if metric == "l2":
        return xn - 2.0 * cross + yn
    if metric == "cosine":
        eps = jnp.asarray(1e-30, jnp.float32)
        return 1.0 - cross / (jnp.sqrt(xn + eps) * jnp.sqrt(yn + eps))
    if metric == "inner":
        return -cross
    raise ValueError(f"unknown metric {metric!r}")


# Shared masking + fused argmin over a distance tile (see
# :func:`_distance_tile` for the tie rule and index-dtype rationale).
# The implementation — including the Mosaic-legality rationale it
# carries — moved into the unified epilogue layer (ISSUE 14); this
# alias keeps the kernels' historical spelling.
_mask_argmin = iota_argmin


def _distance_tile_split(xh, xl, xn, yh, yl, yn, n_valid: int,
                         metric: str = "l2", packed: bool = False,
                         finite: bool = False):
    return _mask_argmin(
        _metric_tile_split(xh, xl, xn, yh, yl, yn, metric, packed=packed),
        n_valid, finite=finite)


def _sq_norms(a):
    """Row squared norms in full f32 (elementwise — no MXU tier concerns)."""
    a = a.astype(jnp.float32)
    return jnp.sum(a * a, axis=1)


def _argmin_jnp(x, y, metric: str = "l2"):
    # Plain-jnp reference for the interpreter-under-shard_map path
    # (pallas_utils.interpret_needs_ref). Same epilogue (argmin tie rule)
    # as the kernels; numerics match the 'default'/'highest' kernels
    # exactly and the 'high' split kernels to ~2^-17 (the split
    # decomposition and precomputed norms round differently at the last
    # bit — ties between float-identical distances can differ there).
    d = _metric_tile(x, y, metric)
    minval, arg = argmin_ref(d)
    if metric == "l2":
        minval = jnp.maximum(minval, 0.0)
    return minval, arg


def _lloyd_jnp(x, y):
    # Shared-iota spelling (epilogue lever, VERDICT task 6) on the jnp
    # reference path too: iota_argmin's column iota feeds the one-hot,
    # so the reference prices the same epilogue shape as the kernels.
    # Bit-identical to the previous lax.argmin + fresh-iota spelling:
    # iota_argmin keeps the first-minimum tie rule and the static
    # aligned n_valid skips the masking pass (same d).
    d = _metric_tile(x, y, "l2")
    col, minval, arg = iota_argmin(d, y.shape[0])
    val = jnp.maximum(minval[:, 0], 0.0)
    oh = assign_onehot(col, arg).astype(jnp.float32)
    sums = _kernel_dot_exact_lhs(oh.T, x.astype(jnp.float32))
    counts = jnp.sum(oh, axis=0)
    return sums, counts, val, arg[:, 0]


def _tm_fits(tm: int, kp: int, np_: int, mn_bufs: int, const_bytes: int,
             itemsize: int = 4) -> bool:
    """Whether an EXPLICIT row-tile request fits the VMEM budget (the
    companion to _pick_tm for caller-supplied tm: clamping a request with
    min() against _pick_tm's PREFERENCE would silently cap every request
    at 256 and mislabel tuning-sweep rows)."""
    need = const_bytes + 2 * tm * kp * itemsize + mn_bufs * tm * np_ * 4
    return need <= _VMEM_BUDGET


_LLOYD_TM_ORDER = (1024, 512, 256, 128, 64, 32, 16, 8)


def _pick_tm(kp: int, np_: int, mn_bufs: int, const_bytes: int,
             itemsize: int = 4,
             order: tuple = (512, 256, 1024, 128, 64, 32, 16, 8)
             ) -> Optional[int]:
    """Largest row-tile that keeps the kernel working set under budget.

    Working set ≈ const (resident Y/accumulators) + double-buffered X tile
    + ``mn_bufs`` (tm × np_) f32 intermediates (distance tile, one-hot).

    512 leads the default preference order: measured fastest on v5e at
    the BASELINE shape at the FIXED bf16x3 kernel (r3 tune artifact
    `tpu_battery_out/northstar_tune.jsonl` tm_sweep @ tier 'high':
    12.29 ms at tm=512 vs 13.84 at 256, 13.9 at 1024, 15.5 at 128 for
    1M×128 k=1024). The r2 sweep that put 256 first (10.7 ms) was
    measured while XLA's excess-precision pass had folded the split to a
    single bf16 pass — a different (lighter) kernel; at the real 5-pass
    working set the larger tile amortizes Y-resident reloads better.
    The LLOYD plan overrides with _LLOYD_TM_ORDER (1024 first): the r5
    tune at the leaner epilogue flipped the ranking (12.06 ms at 1024 vs
    13.38 at 512 — the epilogue no longer dominates the bigger tile's
    intermediate traffic)."""
    for tm in order:
        need = const_bytes + 2 * tm * kp * itemsize + mn_bufs * tm * np_ * 4
        if need <= _VMEM_BUDGET:
            return tm
    return None


# ---------------------------------------------------------------------------
# pairwise L2: D[i, j] = ||x_i||² - 2·x_i·y_j + ||y_j||²
# ---------------------------------------------------------------------------


def _metric_tile(x, y, metric: str):
    """Distance tile for one (x-tile, y-tile) pair — the fused epilogue
    menu of the contraction engine (ref lineage: the pairwise-distance
    kernels cuVS builds on Contractions_NT; L2 = fusedL2NN, cosine =
    fusedCosineNN). ``metric``: 'l2' (squared), 'cosine' (1 - cos), or
    'inner' (negative inner product — a similarity turned distance so the
    same argmin machinery applies)."""
    cross = _kernel_dot(x, y.T)
    if metric == "l2":
        xn = jnp.sum(x * x, axis=1, keepdims=True)
        yn = jnp.sum(y * y, axis=1, keepdims=True)
        return xn - 2.0 * cross + yn.T
    if metric == "cosine":
        eps = jnp.asarray(1e-30, jnp.float32)
        xn = jnp.sqrt(jnp.sum(x * x, axis=1, keepdims=True) + eps)
        yn = jnp.sqrt(jnp.sum(y * y, axis=1, keepdims=True) + eps)
        return 1.0 - cross / (xn * yn.T)
    if metric == "inner":
        return -cross
    raise ValueError(f"unknown metric {metric!r}")


def _pairwise_tile_kernel(x_ref, y_ref, out_ref, *, metric: str):
    out_ref[:] = _metric_tile(x_ref[:], y_ref[:], metric)


def _pairwise_tile_kernel_split(xh_ref, xl_ref, xn_ref, yh_ref, yl_ref,
                                yn_ref, out_ref, *, metric: str):
    out_ref[:] = _metric_tile_split(xh_ref[:], xl_ref[:], xn_ref[:].T,
                                    yh_ref[:], yl_ref[:], yn_ref[:],
                                    metric)


@functools.partial(jax.jit, static_argnames=("tm", "tn", "metric"))
def _pairwise_padded_split(xh, xl, xn, yh, yl, yn, tm: int, tn: int,
                           metric: str):
    m, k = xh.shape
    n = yh.shape[0]
    vma, (xh, xl, xn, yh, yl, yn) = join_vma(xh, xl, xn, yh, yl, yn)
    return pallas_call(
        functools.partial(_pairwise_tile_kernel_split, metric=metric),
        grid=(m // tm, n // tn),
        in_specs=[
            pl.BlockSpec((tm, k), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((tm, k), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, tm), lambda i, j: (0, i),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((tn, k), lambda i, j: (j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((tn, k), lambda i, j: (j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, tn), lambda i, j: (0, j),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((tm, tn), lambda i, j: (i, j),
                               memory_space=pltpu.VMEM),
        out_shape=out_struct((m, n), jnp.float32, vma),
    )(xh, xl, xn, yh, yl, yn)


@functools.partial(jax.jit, static_argnames=("rows", "kp"))
def _split_side(a, rows: int, kp: int):
    """Pad one operand to its tile multiple, split to the bf16 pair,
    precompute f32 squared norms laid out as a (1, rows) block. Jitted so
    the pad/cast/subtract/norm steps fuse into one dispatch instead of
    eager HBM round-trips (callers already inside jit inline it free).
    Shared by :func:`_split_operands` (both sides, per call) and
    :func:`lloyd_prepare` (X side, hoisted out of the Lloyd loop) — ONE
    production path so the prepared-loop bit-identical contract can't
    drift."""
    ap = _pad2(a, rows, kp)
    h, lo = _split_hi_lo(ap)
    return h, lo, _sq_norms(ap)[None, :]


def _split_operands(x, y, mp: int, np_: int, kp: int):
    xh, xl, xn = _split_side(x, mp, kp)
    yh, yl, yn = _split_side(y, np_, kp)
    return xh, xl, xn, yh, yl, yn


@functools.partial(jax.jit, static_argnames=("tm", "tn", "metric"))
def _pairwise_padded(x, y, tm: int, tn: int, metric: str = "l2"):
    m, k = x.shape
    n = y.shape[0]
    grid = (m // tm, n // tn)
    vma, (x, y) = join_vma(x, y)
    return pallas_call(
        functools.partial(_pairwise_tile_kernel, metric=metric),
        grid=grid,
        in_specs=[
            pl.BlockSpec((tm, k), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((tn, k), lambda i, j: (j, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((tm, tn), lambda i, j: (i, j),
                               memory_space=pltpu.VMEM),
        out_shape=out_struct((m, n), jnp.float32, vma),
    )(x, y)


@with_matmul_precision
def pairwise_pallas(x, y, metric: str = "l2",
                    tm: int = 256, tn: int = 256) -> jnp.ndarray:
    """Distance matrix between rows of x and y under a fused epilogue
    metric ('l2' squared, 'cosine', 'inner' = negative inner product).

    x: [m, k] f32/bf16, y: [n, k].  Inputs are zero-padded to tile
    multiples (zero rows/features are exact no-ops for every epilogue:
    they contribute nothing to cross terms or norms).
    """
    x = jnp.asarray(x)
    y = jnp.asarray(y)
    m, k = x.shape
    n = y.shape[0]
    if interpret_needs_ref(x, y):
        return _metric_tile(x, y, metric)
    tm = min(tm, round_up_to_multiple(m, 8))
    tn = min(tn, round_up_to_multiple(n, 128))
    mp = round_up_to_multiple(m, tm)
    np_ = round_up_to_multiple(n, tn)
    kp = round_up_to_multiple(k, 128)
    if _use_split(x, y):
        out = _pairwise_padded_split(
            *_split_operands(x, y, mp, np_, kp), tm, tn, metric)
    else:
        out = _pairwise_padded(_pad2(x, mp, kp), _pad2(y, np_, kp), tm, tn,
                               metric)
    return out[:m, :n]


def pairwise_l2_pallas(x, y, sqrt: bool = False,
                       tm: int = 256, tn: int = 256) -> jnp.ndarray:
    """Squared (or rooted) L2 distance matrix between rows of x and y.

    x: [m, k] f32/bf16, y: [n, k].  Inputs are zero-padded to tile multiples
    (zero feature padding does not change distances).
    """
    out = jnp.maximum(pairwise_pallas(x, y, "l2", tm, tn), 0.0)
    return jnp.sqrt(out) if sqrt else out   # guarded: clamped >= 0 above


# ---------------------------------------------------------------------------
# unexpanded metrics: VPU reduction tiles (no GEMM form)
# ---------------------------------------------------------------------------
# The reference builds EVERY metric on the tiled Contractions_NT engine
# (linalg/detail/contractions.cuh:16) — the expanded ones ride its GEMM
# core, the unexpanded ones its same tiling with a per-element op. This is
# the TPU shape of that second family: the k axis rides the GRID (a
# (tm, kc) x-block against a (kc, tn) yᵀ-block per step, output tile
# accumulated across k steps), so the (tm, kc, tn) broadcast lives only in
# VMEM — never the [m, n, k] HBM intermediate of the jnp broadcast
# formulation the round-3 verdict flagged (weak: _blocked_rowwise).

UNEXPANDED_METRICS = ("l1", "linf", "canberra", "lp", "hamming", "l2un")


def unexpanded_ref(x, y, metric: str, p: float = 2.0):
    """jnp reference formulation (one x-row-block) — the interpreter/vma
    fallback and the test oracle. Accumulation-order-compatible with the
    kernel up to f32 rounding; f64 inputs stay f64 here (only the Pallas
    path is f32-typed)."""
    dt = jnp.promote_types(x.dtype, jnp.float32)
    a = x.astype(dt)[:, None, :]
    b = y.astype(dt)[None, :, :]
    if metric == "l1":
        return jnp.sum(jnp.abs(a - b), axis=-1)
    if metric == "l2un":
        d = a - b
        return jnp.sum(d * d, axis=-1)
    if metric == "linf":
        return jnp.max(jnp.abs(a - b), axis=-1)
    if metric == "canberra":
        num = jnp.abs(a - b)
        den = jnp.abs(a) + jnp.abs(b)
        return jnp.sum(jnp.where(den > 0, num / jnp.where(den > 0, den, 1.0),
                                 0.0), axis=-1)
    if metric == "lp":
        return jnp.sum(jnp.abs(a - b) ** p, axis=-1)
    if metric == "hamming":
        return jnp.sum((a != b).astype(jnp.float32), axis=-1)
    raise ValueError(f"unknown unexpanded metric {metric!r}")


def _unexpanded_tile_kernel(xt_ref, yt_ref, o_ref, *, metric: str, p: float):
    # both operands arrive k-major — the k-chunk rides the SUBLANE dim so
    # every block keeps a 128-aligned lane dim (Mosaic tiling rule), and
    # the (kc, tm, tn) broadcast reduces over axis 0 with no transposes
    kk = pl.program_id(2)
    xc = xt_ref[:].astype(jnp.float32)         # (kc, tm)
    yc = yt_ref[:].astype(jnp.float32)         # (kc, tn)
    a = xc[:, :, None]
    b = yc[:, None, :]
    if metric == "l1":
        val = jnp.sum(jnp.abs(a - b), axis=0)
    elif metric == "l2un":
        d = a - b
        val = jnp.sum(d * d, axis=0)
    elif metric == "linf":
        val = jnp.max(jnp.abs(a - b), axis=0)
    elif metric == "canberra":
        num = jnp.abs(a - b)
        den = jnp.abs(a) + jnp.abs(b)
        val = jnp.sum(jnp.where(den > 0,
                                num / jnp.where(den > 0, den, _f32(1.0)),
                                _f32(0.0)), axis=0)
    elif metric == "lp":
        val = jnp.sum(jnp.abs(a - b) ** _f32(p), axis=0)
    elif metric == "hamming":
        val = jnp.sum((a != b).astype(jnp.float32), axis=0)
    else:
        raise ValueError(metric)

    if metric == "linf":
        @pl.when(kk == 0)
        def _init():
            o_ref[:] = val

        @pl.when(kk != 0)
        def _acc():
            o_ref[:] = jnp.maximum(o_ref[:], val)
    else:
        @pl.when(kk == 0)
        def _init():
            o_ref[:] = val

        @pl.when(kk != 0)
        def _acc():
            o_ref[:] += val


def _f32(v):
    return jnp.float32(v)


@functools.partial(jax.jit,
                   static_argnames=("tm", "tn", "kc", "metric", "p"))
def _unexpanded_padded(xt, yt, tm: int, tn: int, kc: int, metric: str,
                       p: float):
    k, m = xt.shape
    n = yt.shape[1]
    vma, (xt, yt) = join_vma(xt, yt)
    return pallas_call(
        functools.partial(_unexpanded_tile_kernel, metric=metric, p=p),
        grid=(m // tm, n // tn, k // kc),
        in_specs=[
            pl.BlockSpec((kc, tm), lambda i, j, kk: (kk, i),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((kc, tn), lambda i, j, kk: (kk, j),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((tm, tn), lambda i, j, kk: (i, j),
                               memory_space=pltpu.VMEM),
        out_shape=out_struct((m, n), jnp.float32, vma),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary", "arbitrary", "arbitrary")),
    )(xt, yt)


def pairwise_unexpanded_pallas(x, y, metric: str, p: float = 2.0,
                               tm: int = 128, tn: int = 256,
                               kc: int = 32) -> jnp.ndarray:
    """Unexpanded pairwise metric matrix on the VPU reduction tile.

    metric ∈ UNEXPANDED_METRICS; raw reductions only — callers apply the
    metric's scalar epilogue (lp's ^(1/p), hamming's /k, l2un's sqrt)
    outside, where XLA fuses it over the (m, n) result. Zero padding is
    exact for every metric here (pad features contribute f(0,0) = 0 to a
    sum and 0 to a max)."""
    x = jnp.asarray(x)
    y = jnp.asarray(y)
    if metric not in UNEXPANDED_METRICS:
        raise ValueError(f"metric must be one of {UNEXPANDED_METRICS}")
    if interpret_needs_ref(x, y):
        return unexpanded_ref(x, y, metric, p)
    m, k = x.shape
    n = y.shape[0]
    tm = min(tm, round_up_to_multiple(m, 8))
    tn = min(tn, round_up_to_multiple(n, 128))
    kc = min(kc, round_up_to_multiple(k, 8))
    tm = max(tm, 128)                # lane dim of the xᵀ block
    mp = round_up_to_multiple(m, tm)
    np_ = round_up_to_multiple(n, tn)
    kp = round_up_to_multiple(k, kc)
    xtp = _pad2(x, mp, kp).T
    ytp = _pad2(y, np_, kp).T
    return _unexpanded_padded(xtp, ytp, tm, tn, kc, metric,
                              float(p))[:m, :n]


# ---------------------------------------------------------------------------
# fused L2 + argmin (the k-means assignment kernel; ref: cuVS fusedL2NN
# built on this contraction layer)
# ---------------------------------------------------------------------------


def _distance_tile(x, y, n_valid: int, metric: str = "l2",
                   finite: bool = False):
    """Masked metric tile + its per-row (min, argmin). Shapes:
    x (tm, kp), y (np_, kp) → col (tm, np_) column iota,
    minval (tm, 1), arg (tm, 1).

    The argmin is spelled manually in :func:`_mask_argmin` (reduce-min +
    masked column iota) because lax.argmin's variadic-reduce lowering
    fails Mosaic legalization at narrow tiles. The index dtype is pinned
    to int32 via the iota/sentinel dtype (jnp.argmin would bind int64
    under jax_enable_x64, which Mosaic rejects). The first-minimum tie
    rule — smallest column index among equal minima, enforced by the
    reduce-min over masked indices — matches the fused-NN KVP min-reduce
    (the value-then-key reduce op of the cuVS fused-distance lineage;
    note kvp.hpp's operator< itself orders key-then-value — it is the
    reduce op, not operator<, that defines the tie rule)."""
    return _mask_argmin(_metric_tile(x, y, metric), n_valid,
                        finite=finite)


# Tiled-kernel epilogue shared by the split and non-split variants:
# initialize the revisited (val, idx) block on the first y-tile, then
# fold this tile's (min, argmin) in (ties keep the earlier tile — the
# global first-minimum rule). Implementation: epilogue.masked_fold
# (ISSUE 14); the alias keeps the kernels' historical spelling.
_fold_running_min = masked_fold


def _argmin_resident_kernel(x_ref, y_ref, val_ref, idx_ref, *,
                            n_valid: int, metric: str):
    _, minval, arg = _distance_tile(x_ref[:], y_ref[:], n_valid, metric)
    val_ref[:] = minval.T                            # (1, tm)
    idx_ref[:] = arg.T


def _argmin_resident_kernel_split(xh_ref, xl_ref, xn_ref, yh_ref, yl_ref,
                                  yn_ref, val_ref, idx_ref, *,
                                  n_valid: int, metric: str,
                                  packed: bool = False):
    _, minval, arg = _distance_tile_split(
        xh_ref[:], xl_ref[:], xn_ref[:].T, yh_ref[:], yl_ref[:],
        yn_ref[:], n_valid, metric, packed=packed)
    val_ref[:] = minval.T
    idx_ref[:] = arg.T


def _argmin_tiled_kernel(x_ref, y_ref, val_ref, idx_ref, *,
                         tn: int, n_valid: int, metric: str):
    j = pl.program_id(1)
    _, minval, arg = _distance_tile(x_ref[:], y_ref[:],
                                    n_valid - j * tn, metric)
    _fold_running_min(val_ref, idx_ref, minval, arg, j * tn)


def _argmin_tiled_kernel_split(xh_ref, xl_ref, xn_ref, yh_ref, yl_ref,
                               yn_ref, val_ref, idx_ref, *,
                               tn: int, n_valid: int, metric: str,
                               packed: bool = False):
    j = pl.program_id(1)
    _, minval, arg = _distance_tile_split(
        xh_ref[:], xl_ref[:], xn_ref[:].T, yh_ref[:], yl_ref[:],
        yn_ref[:], n_valid - j * tn, metric, packed=packed)
    _fold_running_min(val_ref, idx_ref, minval, arg, j * tn)


@functools.partial(jax.jit, static_argnames=("tm", "n_valid", "metric"))
def _fused_argmin_resident(x, y, tm: int, n_valid: int, metric: str):
    m, kp = x.shape
    np_ = y.shape[0]
    vma, (x, y) = join_vma(x, y)
    kernel = functools.partial(_argmin_resident_kernel, n_valid=n_valid,
                               metric=metric)
    return pallas_call(
        kernel,
        grid=(m // tm,),
        in_specs=[
            pl.BlockSpec((tm, kp), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((np_, kp), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, tm), lambda i: (0, i),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, tm), lambda i: (0, i),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            out_struct((1, m), jnp.float32, vma),
            out_struct((1, m), jnp.int32, vma),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel",)),
    )(x, y)


@functools.partial(jax.jit,
                   static_argnames=("tm", "n_valid", "metric", "packed"))
def _fused_argmin_resident_split(xh, xl, xn, yh, yl, yn, tm: int,
                                 n_valid: int, metric: str,
                                 packed: bool = False):
    m, kp = xh.shape
    np_ = yh.shape[0]
    vma, (xh, xl, xn, yh, yl, yn) = join_vma(xh, xl, xn, yh, yl, yn)
    kernel = functools.partial(_argmin_resident_kernel_split,
                               n_valid=n_valid, metric=metric,
                               packed=packed)
    return pallas_call(
        kernel,
        grid=(m // tm,),
        in_specs=[
            pl.BlockSpec((tm, kp), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((tm, kp), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, tm), lambda i: (0, i),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((np_, kp), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((np_, kp), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, np_), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, tm), lambda i: (0, i),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, tm), lambda i: (0, i),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            out_struct((1, m), jnp.float32, vma),
            out_struct((1, m), jnp.int32, vma),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel",)),
    )(xh, xl, xn, yh, yl, yn)


@functools.partial(jax.jit,
                   static_argnames=("tm", "tn", "n_valid", "metric"))
def _fused_argmin_tiled(x, y, tm: int, tn: int, n_valid: int, metric: str):
    m, kp = x.shape
    n = y.shape[0]
    vma, (x, y) = join_vma(x, y)
    kernel = functools.partial(_argmin_tiled_kernel, tn=tn, n_valid=n_valid,
                               metric=metric)
    return pallas_call(
        kernel,
        grid=(m // tm, n // tn),
        in_specs=[
            pl.BlockSpec((tm, kp), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((tn, kp), lambda i, j: (j, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, tm), lambda i, j: (0, i),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, tm), lambda i, j: (0, i),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            out_struct((1, m), jnp.float32, vma),
            out_struct((1, m), jnp.int32, vma),
        ],
        compiler_params=pltpu.CompilerParams(
            # axis 0 (rows) is parallel; axis 1 revisits the val/idx block
            dimension_semantics=("parallel", "arbitrary")),
    )(x, y)


@functools.partial(jax.jit,
                   static_argnames=("tm", "tn", "n_valid", "metric",
                                    "packed"))
def _fused_argmin_tiled_split(xh, xl, xn, yh, yl, yn, tm: int, tn: int,
                              n_valid: int, metric: str,
                              packed: bool = False):
    m, kp = xh.shape
    n = yh.shape[0]
    vma, (xh, xl, xn, yh, yl, yn) = join_vma(xh, xl, xn, yh, yl, yn)
    kernel = functools.partial(_argmin_tiled_kernel_split, tn=tn,
                               n_valid=n_valid, metric=metric,
                               packed=packed)
    return pallas_call(
        kernel,
        grid=(m // tm, n // tn),
        in_specs=[
            pl.BlockSpec((tm, kp), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((tm, kp), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, tm), lambda i, j: (0, i),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((tn, kp), lambda i, j: (j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((tn, kp), lambda i, j: (j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, tn), lambda i, j: (0, j),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, tm), lambda i, j: (0, i),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, tm), lambda i, j: (0, i),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            out_struct((1, m), jnp.float32, vma),
            out_struct((1, m), jnp.int32, vma),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
    )(xh, xl, xn, yh, yl, yn)


@with_matmul_precision
def fused_argmin_pallas(x, y, metric: str = "l2",
                        tm: Optional[int] = None, tn: int = 512,
                        packed: Optional[bool] = None
                        ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(min_dist, argmin) of each row of x against rows of y under a fused
    metric epilogue ('l2' squared, 'cosine', 'inner'), never materializing
    the m×n distance matrix: HBM traffic is O(mk + nk + m) instead of
    O(mn) — the property that makes Lloyd iterations bandwidth-friendly at
    k=4096 (ref lineage: fusedL2NN / fusedCosineNN on Contractions_NT).

    Y stays resident in VMEM when it fits (one X pass, no revisits); larger
    Y falls back to a 2-axis grid with a running (min, argmin) in the
    revisited per-row output block.
    """
    x = jnp.asarray(x)
    y = jnp.asarray(y)
    m, k = x.shape
    n = y.shape[0]
    packed = _packed_split_default() if packed is None else bool(packed)
    if interpret_needs_ref(x, y):
        val, idx = _argmin_jnp(x, y, metric)
        return val, idx.astype(jnp.int32)
    kp = round_up_to_multiple(k, 128)
    np_ = round_up_to_multiple(n, 128)
    isz = jnp.dtype(x.dtype).itemsize
    auto_tm = _pick_tm(kp, np_, mn_bufs=2, const_bytes=np_ * kp * isz,
                       itemsize=isz)
    split = _use_split(x, y)
    if auto_tm is not None:
        # same explicit-tm contract as fused_lloyd_pallas: honor a
        # request that fits VMEM, fall back to auto otherwise
        if tm is not None and _tm_fits(tm, kp, np_, 2, np_ * kp * isz,
                                       isz):
            tm_ = tm
        else:
            tm_ = auto_tm
        tm_ = max(8, round_up_to_multiple(min(tm_, m), 8))
        mp = round_up_to_multiple(m, tm_)
        if split:
            val, idx = _fused_argmin_resident_split(
                *_split_operands(x, y, mp, np_, kp), tm_, n, metric,
                packed=packed)
        else:
            val, idx = _fused_argmin_resident(
                _pad2(x, mp, kp), _pad2(y, np_, kp), tm_, n, metric)
    else:
        tn_ = min(tn, np_)
        tm_ = _pick_tm(kp, tn_, mn_bufs=2, const_bytes=tn_ * kp * isz,
                       itemsize=isz) or 8
        if tm is not None:
            tm_ = min(tm, tm_)
        tm_ = max(8, round_up_to_multiple(min(tm_, m), 8))
        mp = round_up_to_multiple(m, tm_)
        npp = round_up_to_multiple(n, tn_)
        if split:
            val, idx = _fused_argmin_tiled_split(
                *_split_operands(x, y, mp, npp, kp), tm_, tn_, n, metric,
                packed=packed)
        else:
            val, idx = _fused_argmin_tiled(
                _pad2(x, mp, kp), _pad2(y, npp, kp), tm_, tn_, n, metric)
    return val[0, :m], idx[0, :m]


def fused_l2_argmin_pallas(x, y, tm: Optional[int] = None,
                           tn: int = 512, packed: Optional[bool] = None
                           ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(min_dist², argmin) under squared L2 — see :func:`fused_argmin_pallas`."""
    val, idx = fused_argmin_pallas(x, y, "l2", tm, tn, packed=packed)
    return jnp.maximum(val, 0.0), idx


# ---------------------------------------------------------------------------
# fused Lloyd iteration: distance + argmin + one-hot sums/counts, one pass
# ---------------------------------------------------------------------------


def _lloyd_kernel(x_ref, y_ref, sums_ref, counts_ref, val_ref, idx_ref, *,
                  tm: int, n_valid: int, m_valid: int):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        sums_ref[:] = jnp.zeros_like(sums_ref)
        counts_ref[:] = jnp.zeros_like(counts_ref)

    x = x_ref[:]
    # finite=True: k-means on non-finite data is undefined — the NaN
    # argmin clause is dead weight on the epilogue-bound kernel
    col, minval, arg = _distance_tile(x, y_ref[:], n_valid, finite=True)
    val_ref[:] = jnp.maximum(minval, 0.0).T
    idx_ref[:] = arg.T

    # One-hot accumulation on the MXU: padded X rows are zero (no effect
    # on sums) but must not inflate counts — mask them out. The mask is
    # static per shape: aligned m (the north-star 1M at tm=512) skips it.
    # assign_onehot REUSES the argmin's column iota (the shared-iota
    # lever, VERDICT task 6).
    row_mask = None
    if m_valid < pl.num_programs(0) * tm:
        row = jax.lax.broadcasted_iota(jnp.int32, (tm, 1), 0) + i * tm
        row_mask = row < m_valid
    oh = assign_onehot(col, arg, row_mask).astype(jnp.float32)
    sums_ref[:] += _kernel_dot_exact_lhs(oh.T, x.astype(jnp.float32))
    counts_ref[:] += jnp.sum(oh, axis=0, keepdims=True)
    # (counts ride the already-f32 one-hot here; the split kernel fuses
    # its bf16→f32 convert into the reduce — see _lloyd_kernel_split)


def _lloyd_kernel_split(xh_ref, xl_ref, xn_ref, yh_ref, yl_ref, yn_ref,
                        sums_ref, counts_ref, val_ref, idx_ref, *,
                        tm: int, n_valid: int, m_valid: int,
                        packed: bool = False, counts_mxu: bool = False):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        sums_ref[:] = jnp.zeros_like(sums_ref)
        counts_ref[:] = jnp.zeros_like(counts_ref)

    col, minval, arg = _distance_tile_split(
        xh_ref[:], xl_ref[:], xn_ref[:].T, yh_ref[:], yl_ref[:],
        yn_ref[:], n_valid, packed=packed, finite=True)
    val_ref[:] = jnp.maximum(minval, 0.0).T
    idx_ref[:] = arg.T

    # one-hot is exact in bf16; X arrives pre-split, so the 'high'-tier
    # update is two one-pass MXU dots against the hi/lo halves — or one
    # depth-packed 2tm-deep dot when ``packed`` (see _cross_split).
    # Row-validity mask statically skipped at aligned m (see _lloyd_kernel).
    row_mask = None
    if m_valid < pl.num_programs(0) * tm:
        row = jax.lax.broadcasted_iota(jnp.int32, (tm, 1), 0) + i * tm
        row_mask = row < m_valid
    ohb = assign_onehot(col, arg, row_mask).astype(jnp.bfloat16)
    f32 = jnp.float32
    if packed:
        ohcat = jnp.concatenate([ohb.T, ohb.T], axis=1)     # (np_, 2tm)
        xcat = jnp.concatenate([xh_ref[:], xl_ref[:]], axis=0)
        sums_ref[:] += jnp.dot(ohcat, xcat, preferred_element_type=f32,
                               precision=_ONE_PASS)
    else:
        sums_ref[:] += (jnp.dot(ohb.T, xh_ref[:],
                                preferred_element_type=f32,
                                precision=_ONE_PASS)
                        + jnp.dot(ohb.T, xl_ref[:],
                                  preferred_element_type=f32,
                                  precision=_ONE_PASS))
    if counts_mxu:
        # counts as ONE MXU row-vector dot (1s @ one-hot) instead of a
        # (tm, np_) VPU reduce — the epilogue is VPU-bound (BASELINE
        # roofline), so trading the reduce onto the matrix unit is a
        # candidate lever; tune case 'counts_mxu' prices it (r5)
        ones = jnp.ones((1, tm), jnp.bfloat16)
        counts_ref[:] += jnp.dot(ones, ohb, preferred_element_type=f32,
                                 precision=_ONE_PASS)
    else:
        # convert-on-reduce: one fused pass (accumulate bf16 inputs into
        # an f32 sum) instead of a full (tm, np_) astype pass + a reduce
        # — counts <= tm are exact in f32
        counts_ref[:] += jnp.sum(ohb, axis=0, keepdims=True, dtype=f32)


@functools.partial(jax.jit,
                   static_argnames=("tm", "n_valid", "m_valid", "packed",
                                    "counts_mxu"))
def _fused_lloyd_padded_split(xh, xl, xn, yh, yl, yn, tm: int,
                              n_valid: int, m_valid: int,
                              packed: bool = False,
                              counts_mxu: bool = False):
    m, kp = xh.shape
    np_ = yh.shape[0]
    vma, (xh, xl, xn, yh, yl, yn) = join_vma(xh, xl, xn, yh, yl, yn)
    kernel = functools.partial(_lloyd_kernel_split, tm=tm, n_valid=n_valid,
                               m_valid=m_valid, packed=packed,
                               counts_mxu=counts_mxu)
    return pallas_call(
        kernel,
        grid=(m // tm,),
        in_specs=[
            pl.BlockSpec((tm, kp), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((tm, kp), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, tm), lambda i: (0, i),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((np_, kp), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((np_, kp), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, np_), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((np_, kp), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, np_), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, tm), lambda i: (0, i),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, tm), lambda i: (0, i),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            out_struct((np_, kp), jnp.float32, vma),
            out_struct((1, np_), jnp.float32, vma),
            out_struct((1, m), jnp.float32, vma),
            out_struct((1, m), jnp.int32, vma),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary",)),
    )(xh, xl, xn, yh, yl, yn)


@functools.partial(jax.jit,
                   static_argnames=("tm", "n_valid", "m_valid"))
def _fused_lloyd_padded(x, y, tm: int, n_valid: int, m_valid: int):
    m, kp = x.shape
    np_ = y.shape[0]
    vma, (x, y) = join_vma(x, y)
    kernel = functools.partial(_lloyd_kernel, tm=tm, n_valid=n_valid,
                               m_valid=m_valid)
    return pallas_call(
        kernel,
        grid=(m // tm,),
        in_specs=[
            pl.BlockSpec((tm, kp), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((np_, kp), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((np_, kp), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, np_), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, tm), lambda i: (0, i),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, tm), lambda i: (0, i),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            out_struct((np_, kp), jnp.float32, vma),
            out_struct((1, np_), jnp.float32, vma),
            out_struct((1, m), jnp.float32, vma),
            out_struct((1, m), jnp.int32, vma),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary",)),
    )(x, y)


def _lloyd_tile_plan(m: int, k: int, n: int, itemsize: int,
                     tm: Optional[int]):
    """The fused-Lloyd tile selection — ONE copy shared by
    :func:`fused_lloyd_pallas` and :func:`lloyd_prepare`, because the
    prepared path's bit-identical contract requires both to pick the
    same tiles. Returns ``(tm, mp, kp, np_)``; ``tm is None`` means the
    Y+sums working set exceeds VMEM residency (callers take the chunked
    fallback)."""
    kp = round_up_to_multiple(k, 128)
    np_ = round_up_to_multiple(n, 128)
    const = np_ * kp * (itemsize + 4) + 4 * np_   # y + sums + counts
    auto_tm = _pick_tm(kp, np_, mn_bufs=2, const_bytes=const,
                       itemsize=itemsize, order=_LLOYD_TM_ORDER)
    # explicit tm (the tuning sweep's knob) is honored whenever it fits
    # VMEM — NOT min()'d against the preference order, which would cap
    # every request at the preferred 256; unsafe requests fall back to
    # auto
    if tm is None:
        tm = auto_tm
    elif auto_tm is None or not _tm_fits(tm, kp, np_, 2, const, itemsize):
        tm = auto_tm
    if tm is None:
        return None, None, kp, np_
    tm = max(8, round_up_to_multiple(min(tm, m), 8))
    return tm, round_up_to_multiple(m, tm), kp, np_


@with_matmul_precision
def lloyd_prepare(x, n_clusters: int, tm: Optional[int] = None):
    """Hoist the LOOP-INVARIANT operand work of the tier-'high' fused
    Lloyd kernel out of the iteration loop.

    At tier 'high' every :func:`fused_lloyd_pallas` call re-derives X's
    bf16 hi/lo halves and squared norms — ~1.3 GB of HBM traffic per
    iteration at the north-star shape (1M×128: read 512 MB f32, write
    2×256 MB bf16 + 4 MB norms) that is identical across Lloyd
    iterations because X never changes. The reference hoists the same
    way: cuVS k-means precomputes row norms once per fit, outside the
    minimum-distance loop. Returns ``(ops, meta)``:

    - ``ops``: tuple of device arrays (xh, xl, xn) padded to the chosen
      tile grid — pass to :func:`fused_lloyd_prepared` every iteration.
    - ``meta``: dict of STATIC kwargs for :func:`fused_lloyd_prepared`
      (tile size, true row count).

    Returns ``(None, None)`` when the prepared path does not apply —
    any of: tier ≠ 'high', non-f32 dtype, interpreter mode, or Y+sums
    exceeding VMEM residency (the chunked fallback path) — callers then
    use :func:`fused_lloyd_pallas` unchanged. Outputs of the prepared
    step are BIT-IDENTICAL to the unprepared call: same kernel, same
    operand bytes, only their production is hoisted.
    """
    x = jnp.asarray(x)
    m, k = x.shape
    if (current_mode() != "high" or x.dtype != jnp.float32
            or interpret_needs_ref(x)):
        return None, None
    tm, mp, kp, np_ = _lloyd_tile_plan(m, k, n_clusters, 4, tm)
    if tm is None:                            # VMEM-fallback path
        return None, None
    return _split_side(x, mp, kp), {"tm": tm, "m": m}


@with_matmul_precision
def fused_lloyd_prepared(ops, y, *, tm: int, m: int,
                         counts_mxu: bool = False,
                         packed: Optional[bool] = None
                         ) -> Tuple[jnp.ndarray, jnp.ndarray,
                                    jnp.ndarray, jnp.ndarray]:
    """Per-iteration half of the prepared Lloyd pass: split/norm Y (the
    centroids — tiny, they change every iteration) and run the resident
    split kernel against the hoisted X operands from
    :func:`lloyd_prepare`. Same return contract as
    :func:`fused_lloyd_pallas`, bit-identical results."""
    xh, xl, xn = ops
    y = jnp.asarray(y)
    n, k = y.shape
    kp = xh.shape[1]
    np_ = round_up_to_multiple(n, 128)
    packed = _packed_split_default() if packed is None else bool(packed)
    yp = _pad2(y.astype(jnp.float32), np_, kp)
    yh, yl = _split_hi_lo(yp)
    yn = _sq_norms(yp)[None, :]
    sums, counts, val, idx = _fused_lloyd_padded_split(
        xh, xl, xn, yh, yl, yn, tm, n, m, packed=packed,
        counts_mxu=counts_mxu)
    return (sums[:n, :k], counts[0, :n],
            jnp.maximum(val[0, :m], 0.0), idx[0, :m])


@with_matmul_precision
def fused_lloyd_pallas(x, y, tm: Optional[int] = None,
                       packed: Optional[bool] = None
                       ) -> Tuple[jnp.ndarray, jnp.ndarray,
                                  jnp.ndarray, jnp.ndarray]:
    """One full Lloyd iteration's data pass, fused into a single kernel.

    Returns ``(sums [n, k] f32, counts [n] f32, min_dist² [m] f32,
    labels [m] i32)`` — the caller divides sums by counts (and psums them
    first on the MNMG path). X is read exactly once; both the distance and
    the one-hot update contraction run on the MXU while the X tile is
    resident.

    Requires Y (+ the [n, k] sums accumulator) to fit in VMEM; larger
    problems fall back to :func:`fused_l2_argmin_pallas` + an XLA one-hot
    matmul (still scatter-free).

    ``packed`` selects the depth-packed bf16x3 spelling wherever split
    dots exist: the tier-'high' resident path AND (via the argmin kernel)
    the VMEM fallback. It is (deliberately, without warning) a no-op at
    other tiers and for bf16 inputs, which have no split dots to pack.
    """
    x = jnp.asarray(x)
    y = jnp.asarray(y)
    m, k = x.shape
    n = y.shape[0]
    packed = _packed_split_default() if packed is None else bool(packed)
    if interpret_needs_ref(x, y):
        sums, counts, val, idx = _lloyd_jnp(x, y)
        return sums, counts, val, idx.astype(jnp.int32)
    isz = jnp.dtype(x.dtype).itemsize
    tm, mp, kp, np_ = _lloyd_tile_plan(m, k, n, isz, tm)
    if tm is None:
        # Y (+ sums) exceed VMEM: fused argmin kernel, then a CHUNKED
        # one-hot update so the m×n one-hot never materializes in HBM.
        val, idx = fused_l2_argmin_pallas(x, y, packed=packed)
        chunk = max(1, min(m, (1 << 25) // max(n, 1)))   # ≈128 MB of one-hot
        mp = round_up_to_multiple(m, chunk)
        xp = _pad2(x, mp, k).reshape(mp // chunk, chunk, k)
        # padded rows get label n → an all-zero one_hot row (no effect)
        idxp = jnp.pad(idx, (0, mp - m), constant_values=n) \
            .reshape(mp // chunk, chunk)

        def body(carry, inp):
            sums, counts = carry
            xc, ic = inp
            oh = label_onehot(ic, n)
            sums = sums + _kernel_dot_exact_lhs(oh.T, xc.astype(jnp.float32))
            return (sums, counts + jnp.sum(oh, axis=0)), None

        (sums, counts), _ = jax.lax.scan(
            body, (jnp.zeros((n, k), jnp.float32),
                   jnp.zeros((n,), jnp.float32)), (xp, idxp))
        return sums, counts, val, idx
    if _use_split(x, y):
        sums, counts, val, idx = _fused_lloyd_padded_split(
            *_split_operands(x, y, mp, np_, kp), tm, n, m, packed=packed)
    else:
        sums, counts, val, idx = _fused_lloyd_padded(
            _pad2(x, mp, kp), _pad2(y, np_, kp), tm, n, m)
    return (sums[:n, :k], counts[0, :n],
            jnp.maximum(val[0, :m], 0.0), idx[0, :m])
