"""BLAS-level ops: gemm/gemv/axpy/dot/transpose
(ref: linalg/gemm.cuh, gemv.cuh, axpy.cuh, dot.cuh, transpose.cuh and the
cuBLAS(Lt) wrapper layer linalg/detail/cublas_wrappers.hpp,
cublaslt_wrappers.hpp:28-62).

The reference routes gemm through cublasLt with a compute-type table
(fp32/fp16/int8).  On TPU the MXU is driven through `lax.dot_general` with
``preferred_element_type`` as the compute-type knob; bf16 inputs with f32
accumulation is the fast path.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
from jax import lax

from raft_tpu.util.precision import resolve, with_matmul_precision


@with_matmul_precision
def gemm(res, A, B, alpha: float = 1.0, beta: float = 0.0, C=None,
         trans_a: bool = False, trans_b: bool = False,
         compute_type=None, precision=None, guard_mode=None):
    """C = alpha·op(A)·op(B) + beta·C (ref: linalg/gemm.cuh).

    ``compute_type`` maps the reference's cublasLt compute-type selection
    (detail/cublaslt_wrappers.hpp get_matmul_type): None → accumulate in
    f32 (or f64 for f64 inputs); pass jnp.float32 explicitly to force MXU
    bf16×bf16→f32 style accumulation for low-precision inputs.
    ``precision`` ('default' | 'high' | 'highest' | lax.Precision) is the
    MXU pass-count knob — the other half of the compute-type table; None
    defers to the framework policy (util.precision, default 'high' =
    bf16x3, measured ~1e-6 rel-err; 'highest' for strict f32 parity).

    ``guard_mode`` ('off' | 'check' | 'recover') overrides the numeric
    guard (core/guards.py): 'check' fetches a fused finite sentinel with
    the result; 'recover' re-runs one matmul tier up on a non-finite
    output with finite inputs.

    Admission (ISSUE 5): with a ``runtime.limits`` work budget active, a
    gemm whose operands + accumulator would overrun it raises
    :class:`~raft_tpu.runtime.limits.RejectedError` carrying the byte
    estimate — a dense matmul has no bit-equal tiled fallback here, so
    over-budget requests are refused rather than attempted. With no
    budget active this path is untouched.
    """
    A = jnp.asarray(A)
    B = jnp.asarray(B)
    if trans_a:
        A = A.T
    if trans_b:
        B = B.T
    if compute_type is None:
        compute_type = jnp.float64 if A.dtype == jnp.float64 else jnp.float32

    from raft_tpu.runtime import limits

    budget = limits.active_budget()
    if budget is not None:
        est = limits.estimate_bytes(
            "linalg.gemm", m=A.shape[0], n=B.shape[1], k=A.shape[1],
            itemsize=A.dtype.itemsize,
            out_itemsize=jnp.dtype(compute_type).itemsize)
        if not limits.admit("linalg.gemm", est, budget=budget):
            limits.reject("linalg.gemm", est, budget=budget)

    def compute():
        out = lax.dot_general(A, B, (((1,), (0,)), ((), ())),
                              preferred_element_type=compute_type,
                              precision=resolve(precision))
        out = (alpha * out).astype(A.dtype) if alpha != 1.0 \
            else out.astype(A.dtype)
        if C is not None and beta != 0.0:
            out = out + beta * jnp.asarray(C)
        return out

    out = compute()
    from raft_tpu.core.guards import guard_output, resolve_guard_mode

    if resolve_guard_mode(guard_mode) == "off":
        return out
    from raft_tpu.util.numerics import matmul_escalation

    inputs = (A, B) if C is None else (A, B, C)
    return guard_output("linalg.gemm", out, inputs=inputs,
                        recover=matmul_escalation(compute, op="linalg.gemm"),
                        mode=guard_mode)


@with_matmul_precision
def gemv(res, A, x, alpha: float = 1.0, beta: float = 0.0, y=None,
         trans: bool = False):
    """y = alpha·op(A)·x + beta·y (ref: linalg/gemv.cuh)."""
    A = jnp.asarray(A)
    x = jnp.asarray(x)
    if trans:
        A = A.T
    out = alpha * (A @ x)
    if y is not None and beta != 0.0:
        out = out + beta * jnp.asarray(y)
    return out.astype(A.dtype)


def axpy(res, alpha: float, x, y):
    """y = alpha·x + y (ref: linalg/axpy.cuh)."""
    return alpha * jnp.asarray(x) + jnp.asarray(y)


@with_matmul_precision
def dot(res, x, y):
    """Inner product (ref: linalg/dot.cuh)."""
    x = jnp.asarray(x)
    return jnp.dot(x.ravel(), jnp.asarray(y).ravel(),
                   preferred_element_type=jnp.float32 if
                   x.dtype != jnp.float64 else jnp.float64).astype(x.dtype)


def transpose(res, A):
    """Out-of-place transpose (ref: linalg/transpose.cuh — cublas geam)."""
    return jnp.asarray(A).T


def scal(res, alpha: float, x):
    return alpha * jnp.asarray(x)


def mean_squared_error(res, a, b, weight: float = 1.0):
    """weight · mean((a-b)^2) (ref: linalg/mean_squared_error.cuh)."""
    a = jnp.asarray(a)
    b = jnp.asarray(b)
    d = a - b
    return weight * jnp.mean(d * d)
