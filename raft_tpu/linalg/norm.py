"""Row/column norms and normalization (ref: linalg/norm.cuh,
normalize.cuh, norm_types.hpp)."""

from __future__ import annotations

import jax.numpy as jnp

from raft_tpu.linalg.reduce import ALONG_COLUMNS, ALONG_ROWS, _axis

L1Norm = "l1"
L2Norm = "l2"
LinfNorm = "linf"


def norm(res, data, norm_type: str = L2Norm, apply: str = ALONG_ROWS,
         sqrt: bool = False):
    """Per-row/column norm (ref: norm.cuh rowNorm/colNorm).

    Matches the reference's convention: L2 returns the *squared* norm unless
    ``sqrt=True`` (rowNorm's fin_op).
    """
    data = jnp.asarray(data)
    axis = _axis(apply)
    if norm_type == L1Norm:
        out = jnp.sum(jnp.abs(data), axis=axis)
    elif norm_type == L2Norm:
        out = jnp.sum(data * data, axis=axis)
        if sqrt:
            out = jnp.sqrt(out)     # guarded: sum of squares is >= 0
    elif norm_type == LinfNorm:
        out = jnp.max(jnp.abs(data), axis=axis)
    else:
        raise ValueError(f"unknown norm {norm_type}")
    return out


def row_norm(res, data, norm_type: str = L2Norm, sqrt: bool = False):
    return norm(res, data, norm_type, ALONG_ROWS, sqrt)


def col_norm(res, data, norm_type: str = L2Norm, sqrt: bool = False):
    return norm(res, data, norm_type, ALONG_COLUMNS, sqrt)


def normalize(res, data, norm_type: str = L2Norm, eps: float = 1e-8):
    """Row-normalize (ref: normalize.cuh row_normalize)."""
    data = jnp.asarray(data)
    if norm_type == L2Norm:
        # eps floors the divide below
        n = jnp.sqrt(                           # guarded: sum of squares
            jnp.sum(data * data, axis=1, keepdims=True))
    elif norm_type == L1Norm:
        n = jnp.sum(jnp.abs(data), axis=1, keepdims=True)
    elif norm_type == LinfNorm:
        n = jnp.max(jnp.abs(data), axis=1, keepdims=True)
    else:
        raise ValueError(f"unknown norm {norm_type}")
    return jnp.where(n > eps, data / jnp.maximum(n, eps),
                     jnp.zeros_like(data))
