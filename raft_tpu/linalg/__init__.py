"""Dense linear algebra (ref: cpp/include/raft/linalg/)."""

from raft_tpu.linalg.blas import (  # noqa: F401
    gemm,
    gemv,
    axpy,
    dot,
    transpose,
    scal,
    mean_squared_error,
)
from raft_tpu.linalg.eltwise import (  # noqa: F401
    add,
    add_scalar,
    subtract,
    subtract_scalar,
    multiply,
    multiply_scalar,
    divide,
    divide_scalar,
    power,
    power_scalar,
    sqrt,
    unary_op,
    write_only_unary_op,
    binary_op,
    ternary_op,
)
from raft_tpu.linalg.map import (  # noqa: F401
    map,
    map_offset,
    map_reduce,
    map_then_reduce,
)
from raft_tpu.linalg.reduce import (  # noqa: F401
    ALONG_ROWS,
    ALONG_COLUMNS,
    reduce,
    coalesced_reduction,
    strided_reduction,
    reduce_rows_by_key,
    reduce_cols_by_key,
)
from raft_tpu.linalg.matrix_vector_op import matrix_vector_op  # noqa: F401
from raft_tpu.linalg.norm import (  # noqa: F401
    L1Norm,
    L2Norm,
    LinfNorm,
    norm,
    row_norm,
    col_norm,
    normalize,
)
from raft_tpu.linalg.eig import eig_dc, eig_jacobi, eig_sel  # noqa: F401
from raft_tpu.linalg.qr import qr_get_q, qr_get_qr  # noqa: F401
from raft_tpu.linalg.svd import (  # noqa: F401
    svd_qr,
    svd_eig,
    svd_jacobi,
    svd_reconstruction,
    evaluate_svd_by_reconstruction,
    rsvd_fixed_rank,
    rsvd_perc,
    randomized_svd,
)
from raft_tpu.linalg.lstsq import (  # noqa: F401
    lstsq_svd_qr,
    lstsq_svd_jacobi,
    lstsq_eig,
    lstsq_qr,
)
from raft_tpu.linalg.cholesky import cholesky_r1_update  # noqa: F401
from raft_tpu.linalg.pca import (  # noqa: F401
    Solver,
    PCAResult,
    TSVDResult,
    IncrementalPCAState,
    pca_fit,
    pca_transform,
    pca_inverse_transform,
    pca_fit_transform,
    pca_partial_fit,
    pca_finalize,
    tsvd_fit,
    tsvd_transform,
    tsvd_inverse_transform,
    tsvd_fit_transform,
    cal_eig,
    sign_flip_components,
)
from raft_tpu.linalg.contractions import (  # noqa: F401
    pairwise_l2_pallas,
    fused_l2_argmin_pallas,
)
