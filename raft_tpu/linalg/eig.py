"""Symmetric eigensolvers (ref: linalg/eig.cuh — cuSOLVER syevd/syevj/syevdx).

XLA's `eigh` (QDWH-eig on TPU) replaces cuSOLVER's divide-&-conquer path
(`eig_dc`). `eig_jacobi` is a REAL one-sided-free cyclic Jacobi solver —
the syevj analogue — honoring the reference's tol/sweeps semantics
(cusolverDnsyevj's residual tolerance and max_sweeps knobs): rotation sets
use the round-robin parallel ordering, so each set is n/2 disjoint
rotations applied as ONE dense orthogonal factor on the MXU (two matmuls),
the TPU-idiomatic form of the reference's batched element rotations.
``eig_sel`` (syevdx subset selection) slices the full decomposition at
small n, and above ``_EIG_SEL_ITERATIVE_MIN_N`` dispatches to a dense-
operator thick-restart Lanczos (sparse/solver/lanczos.py) that computes
ONLY the requested extremal pairs on MXU matvecs — the TPU analogue of
syevdx's bisection + inverse-iteration window.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from raft_tpu.core import logger
from raft_tpu import obs
from raft_tpu.util.precision import with_matmul_precision

EigVecUsage = ("OVERWRITE_INPUT", "COPY_INPUT")


def eig_dc(res, matrix):
    """Divide-and-conquer eigendecomposition of a symmetric matrix.

    Returns (eigenvalues ascending, eigenvectors as columns)
    (ref: eig.cuh eig_dc → cusolverDnsyevd).
    """
    m = jnp.asarray(matrix)
    w, v = jnp.linalg.eigh(m)
    return w, v


@functools.lru_cache(maxsize=64)
def _round_robin_pairs(n: int) -> np.ndarray:
    """Circle-method tournament schedule: n-1 rounds of n/2 disjoint
    pairs covering every (p, q) once. n must be even. [n-1, n/2, 2]."""
    assert n % 2 == 0
    players = list(range(n))
    rounds = []
    for _ in range(n - 1):
        pairs = [(players[i], players[n - 1 - i]) for i in range(n // 2)]
        rounds.append([(min(p, q), max(p, q)) for p, q in pairs])
        players = [players[0]] + [players[-1]] + players[1:-1]
    return np.asarray(rounds, dtype=np.int32)


@functools.partial(jax.jit, static_argnames=("max_sweeps",))
def _jacobi_sweeps(a, pairs, tol, max_sweeps: int):
    """Cyclic Jacobi with parallel orderings until off(A) ≤ tol·||A||_F
    or ``max_sweeps`` sweeps (ref: syevj semantics)."""
    n = a.shape[0]
    eye = jnp.eye(n, dtype=a.dtype)
    norm = jnp.linalg.norm(a)

    def rotation_set(carry, pq):
        a, v = carry
        p, q = pq[:, 0], pq[:, 1]
        app = a[p, p]
        aqq = a[q, q]
        apq = a[p, q]
        # rotation angle per pair (Golub & Van Loan 8.4): skip tiny apq
        safe = jnp.abs(apq) > jnp.finfo(a.dtype).tiny * 16
        tau = (aqq - app) / jnp.where(safe, 2.0 * apq, 1.0)
        # Golub & Van Loan convention sign(0) = +1: equal diagonal entries
        # (tau == 0) still need a 45° rotation — jnp.sign(0) = 0 would make
        # the rotation the identity and never annihilate apq.
        sgn = jnp.where(tau >= 0, 1.0, -1.0).astype(a.dtype)
        t = sgn / (jnp.abs(tau) + jnp.sqrt(1.0 + tau * tau))
        c = 1.0 / jnp.sqrt(1.0 + t * t)
        s = t * c
        c = jnp.where(safe, c, 1.0)
        s = jnp.where(safe, s, 0.0)
        # one dense orthogonal factor applying all n/2 disjoint rotations
        g = eye.at[p, p].set(c).at[q, q].set(c) \
               .at[p, q].set(s).at[q, p].set(-s)
        a = g.T @ a @ g
        v = v @ g
        return (a, v), None

    def sweep_body(state):
        i, a, v, _ = state
        (a, v), _ = lax.scan(rotation_set, (a, v), pairs)
        off = jnp.sqrt(jnp.maximum(
            jnp.sum(a * a) - jnp.sum(jnp.diagonal(a) ** 2), 0.0))
        return i + 1, a, v, off

    def sweep_cond(state):
        i, _, _, off = state
        return (off > tol * norm) & (i < max_sweeps)

    i, a, v, off = lax.while_loop(
        sweep_cond, sweep_body,
        (jnp.int32(0), a, eye, jnp.asarray(jnp.inf, a.dtype)))
    return jnp.diagonal(a), v, i, off, norm


@with_matmul_precision
def eig_jacobi(res, matrix, tol: float = 1e-7, sweeps: int = 15,
               strict: bool = False, return_report: bool = False,
               guard_mode=None):
    """Jacobi eigensolver (ref: eig.cuh eig_jacobi → cusolverDnsyevj).

    Returns (eigenvalues ascending, eigenvectors as columns). ``tol`` is
    the off-diagonal Frobenius residual relative to ||A||_F; ``sweeps``
    caps the cyclic sweeps — both the reference's syevj knobs, actually
    honored (round 1 aliased this to eig_dc).

    Numerical guardrails (ISSUE 3): hitting the sweep limit is the
    cuSOLVER ``syevj info = n+1`` breakdown. ``strict=True`` raises
    :class:`~raft_tpu.core.guards.ConvergenceError`; under guard mode
    ``'recover'`` the decomposition re-runs at the f64 host rung of the
    escalation ladder (exact LAPACK ``eigh``) and the report is marked
    ``escalated``. ``return_report=True`` appends the
    :class:`~raft_tpu.core.guards.ConvergenceReport`.
    """
    from raft_tpu.core import trace
    from raft_tpu.core.guards import (ConvergenceError, ConvergenceReport,
                                      resolve_guard_mode)
    from raft_tpu.runtime import limits

    def finish(w, v, report):
        if return_report:
            return w, v, report
        return w, v

    a = jnp.asarray(matrix)
    # eig_jacobi runs its sweeps as ONE device launch — the deadline
    # polls bracket it (entry + the post-launch host fetch below)
    limits.check_deadline("linalg.eig_jacobi")
    if jnp.issubdtype(a.dtype, jnp.complexfloating):
        # the real-rotation sweeps below would silently drop the imaginary
        # part; Hermitian input goes to the QDWH path (syevj handles
        # complex in the reference too, just by a different rotation form)
        w, v = eig_dc(res, a)
        return finish(w, v, ConvergenceReport(
            converged=True, n_iter=0, residual=0.0, tol=float(tol),
            detail="complex input: exact eig_dc path"))
    n = a.shape[0]
    if n <= 1:
        return finish(jnp.diagonal(a), jnp.eye(n, dtype=a.dtype),
                      ConvergenceReport(converged=True, n_iter=0,
                                        residual=0.0, tol=float(tol)))
    dtype = a.dtype if a.dtype in (jnp.float32, jnp.float64) \
        else jnp.float32
    a = a.astype(dtype)
    np_ = n + (n % 2)
    ap = a
    if np_ != n:                       # pad with a decoupled diagonal slot
        ap = jnp.pad(a, ((0, 1), (0, 1)))
    pairs = jnp.asarray(_round_robin_pairs(np_))
    w, v, n_sweeps, off, norm = _jacobi_sweeps(
        ap, pairs, jnp.asarray(tol, dtype), sweeps)
    # the padded slot stays exactly decoupled (every rotation touching it
    # sees a zero off-diagonal → identity), so dropping row/col n is exact
    w, v = w[:n], v[:n, :n]
    limits.check_deadline("linalg.eig_jacobi")
    mode = resolve_guard_mode(guard_mode)
    traced = isinstance(w, jax.core.Tracer)
    if (mode != "off" or strict or return_report) and not traced:
        # one tiny fetch (3 scalars) only when someone is listening
        off_h, norm_h = float(off), float(norm)
        report = ConvergenceReport(
            converged=off_h <= tol * norm_h, n_iter=int(n_sweeps),
            residual=off_h / norm_h if norm_h > 0 else 0.0,
            tol=float(tol))
        if report.converged:
            obs.record_convergence("linalg.eig_jacobi", report)
        else:
            if mode == "recover":
                # sweep-limit breakdown → escalate to the f64 host rung
                # (exact LAPACK eigh — "matches the f64 reference")
                from raft_tpu.util.numerics import f64_host

                trace.record_event("guards.escalate", op="linalg.eig_jacobi",
                                   tier="f64", residual=report.residual)
                obs.inc("guards_escalations_total", 1,
                        op="linalg.eig_jacobi")
                w64, v64 = np.linalg.eigh(f64_host(a))
                report.escalated = True
                report.converged = True
                report.detail = "escalated to f64 host eigh"
                obs.record_convergence("linalg.eig_jacobi", report)
                return finish(jnp.asarray(w64, dtype),
                              jnp.asarray(v64, dtype), report)
            obs.record_convergence("linalg.eig_jacobi", report)
            if strict:
                raise ConvergenceError(
                    f"eig_jacobi: sweep limit {sweeps} reached with "
                    f"off-diagonal residual {report.residual:.3e} > tol "
                    f"{tol:.3e} (syevj info=n+1 class; strict=True)",
                    report=report, op="linalg.eig_jacobi")
            logger.warn(
                "eig_jacobi: sweep limit %d hit (residual %.3e > tol "
                "%.3e); returning unconverged decomposition", sweeps,
                report.residual, tol)
    else:
        report = None
    order = jnp.argsort(w)
    return finish(w[order], v[:, order],
                  report if report is not None else ConvergenceReport(
                      converged=True, n_iter=-1, residual=float("nan"),
                      tol=float(tol), detail="not polled (guard off)"))


# Above this size (and for small-enough subsets) eig_sel switches from
# slice-of-full-eigh to the dense-operator thick-restart Lanczos: the
# subset solver's cost is ~restarts * ncv MXU matvecs (O(n^2 * ncv)) vs
# the full decomposition's O(n^3) — the same trade syevdx makes with
# bisection + inverse iteration on the tridiagonalization.
_EIG_SEL_ITERATIVE_MIN_N = 512


def _eig_dc_slice(res, m, n_eig_vals: int, largest: bool):
    w, v = eig_dc(res, m)
    if largest:
        return w[-n_eig_vals:], v[:, -n_eig_vals:]
    return w[:n_eig_vals], v[:, :n_eig_vals]


def eig_sel(res, matrix, n_eig_vals: int, largest: bool = True,
            tol: float = 1e-6, exact=None):
    """Subset eigendecomposition (ref: eig.cuh eig_sel → syevdx).

    Returns the ``n_eig_vals`` largest (or smallest) eigenpairs, eigenvalues
    ascending within the selection, vectors as columns.

    For f32 matrices with n >= 512 and k <= n/3 (k <= n/2 when
    ``exact=False`` forces it) the full spectrum is never materialized: a
    dense-operator thick-restart Lanczos with soft locking
    (sparse/solver/lanczos.py) runs the extremal subspace to ``tol`` on MXU
    matvecs — the TPU shape of the reference's windowed syevdx
    (detail/cusolver_wrappers.hpp syevdx family). Past k ~ n/3 the restart
    matvec volume crosses the full QDWH-eig's cost, so the auto dispatch
    slices the full decomposition instead.

    Accuracy contract: the reference's syevdx is an EXACT subset solver,
    while Lanczos resolves one Krylov direction per distinct eigenvalue —
    locking deflates converged pairs so degenerate copies emerge as
    separate Ritz pairs (the solve carries a small overshoot buffer so
    boundary clusters have room to surface), and every iterative result
    is VERIFIED before return: per-pair residuals ``|A v - w v|`` and the
    pairwise orthogonality of the returned vectors are checked on host —
    duplicate eigenvalues with orthogonal vectors are a correctly
    resolved multiplicity, while near-parallel vectors or residuals above
    ~10*tol*|A| (e.g. an unconverged pair) fall back to the exact eig_dc
    slice. ``exact``:

    * ``None`` (default) — auto: iterative inside the envelope above,
      exact slice elsewhere; iterative results always verified.
    * ``True`` — always the exact eig_dc slice (the strict syevdx
      contract). f64 input on the TPU backend additionally routes the
      decomposition to host LAPACK (``np.linalg.eigh``) — TPU f64 is
      emulated, and parity-critical f64 callers want the exact result.
    * ``False`` — force the iterative path whenever it applies
      (f32, k <= n/2); still verified with fallback.
    """
    m = jnp.asarray(matrix)
    n = m.shape[0]
    k = n_eig_vals
    if not 0 < k <= n:
        raise ValueError(f"need 0 < n_eig_vals <= n, got {k} vs {n}")
    if isinstance(m, jax.core.Tracer):
        # under jit only the pure-XLA slice traces (the iterative driver
        # and the f64-host fallback are host-driven — same guard as
        # sparse.linalg.spmv_method's "never auto-build under jit")
        return _eig_dc_slice(res, m, k, largest)
    is_f32 = jnp.dtype(m.dtype) == jnp.dtype(jnp.float32)
    want_iter = k < n and is_f32 and (
        (exact is False and k <= n // 2)
        or (exact is None and n >= _EIG_SEL_ITERATIVE_MIN_N
            and k <= n // 3))
    if want_iter:
        # f32 only: the Lanczos driver computes in f32, and an f64 input
        # (x64 mode) must keep the full-precision exact slice
        from raft_tpu.sparse.solver.lanczos import (LanczosConfig,
                                                    lanczos_compute_eigenpairs)

        # overshoot buffer: a few extra pairs give a boundary cluster
        # room to surface all its copies before the selection cuts
        k_solve = min(k + 4, n - 1)
        cfg = LanczosConfig(n_components=k_solve, max_iterations=200,
                            tolerance=tol,
                            which="LA" if largest else "SA")
        w, v = lanczos_compute_eigenpairs(res, m, cfg)
        order = jnp.argsort(w)          # ascending; slice the k requested
        sel = order[-k:] if largest else order[:k]
        w, v = w[sel], v[:, sel]
        # --- verification (ADVICE r4 medium) -----------------------------
        # residuals: one n×k MXU matmul, fetched with the values; the
        # k×k Gram matrix checks the returned vectors are genuinely
        # distinct directions (duplicate VALUES with orthogonal vectors
        # are a correctly resolved multiplicity — not a failure).
        # full-f32 precision pinned: at JAX DEFAULT a TPU matmul runs one
        # bf16 pass, whose ~1e-3 noise would fail these checks spuriously
        # and demote every call to the exact slice
        with jax.default_matmul_precision("float32"):
            resid = jnp.linalg.norm(m @ v - v * w[None, :], axis=0)
            gram = v.T @ v
        w_h = np.asarray(w, np.float64)
        resid_h = np.asarray(resid, np.float64)
        gram_h = np.asarray(gram, np.float64)
        # operator-scale estimate: max |selected w| alone collapses for
        # smallest-pair queries on matrices whose small eigenvalues sit
        # near zero (the bound would demand absolute accuracy the f32
        # matvec cannot deliver); ||A||_F / sqrt(n) <= ||A||_2 restores a
        # spectrum-wide floor while staying a LOWER bound (conservative)
        scale = max(float(np.abs(w_h).max(initial=0.0)),
                    float(jnp.linalg.norm(m)) / float(np.sqrt(n)),
                    float(np.finfo(np.float32).tiny))
        sqrt_eps = float(np.sqrt(np.finfo(np.float32).eps))
        resid_ok = resid_h.max(initial=0.0) <= max(10.0 * tol,
                                                   sqrt_eps) * scale
        offdiag = float(np.abs(gram_h - np.eye(k)).max()) if k > 1 else 0.0
        ortho_ok = offdiag < 1e-3
        if resid_ok and ortho_ok:
            return w, v
        logger.warn(
            "eig_sel: iterative subset failed verification (max residual "
            "%.3e, max Gram offdiag %.3e, scale %.3e) — falling back to "
            "the exact eig_dc slice", float(resid_h.max(initial=0.0)),
            offdiag, scale)
    if (jnp.dtype(m.dtype) == jnp.dtype(jnp.float64)
            and jax.default_backend() == "tpu"):
        # f64-on-host parity fallback: TPU f64 is emulated; callers that
        # pass f64 want the reference's exact contract (VERDICT r4 #8)
        w_h, v_h = np.linalg.eigh(np.asarray(m))
        if largest:
            w_h, v_h = w_h[-k:], v_h[:, -k:]
        else:
            w_h, v_h = w_h[:k], v_h[:, :k]
        return jnp.asarray(w_h), jnp.asarray(v_h)
    return _eig_dc_slice(res, m, k, largest)
