"""Symmetric eigensolvers (ref: linalg/eig.cuh — cuSOLVER syevd/syevj/syevdx).

XLA's `eigh` (QDWH-eig on TPU) replaces cuSOLVER's divide-&-conquer and
Jacobi paths; both reference spellings are kept and dispatch to the same
compiled routine.  ``eig_sel`` (syevdx subset selection) computes the full
decomposition and slices — on TPU the full eigh is MXU-bound and subset
tricks don't pay until n is very large, where Lanczos
(raft_tpu.sparse.solver) is the right tool anyway.
"""

from __future__ import annotations

import jax.numpy as jnp

EigVecUsage = ("OVERWRITE_INPUT", "COPY_INPUT")


def eig_dc(res, matrix):
    """Divide-and-conquer eigendecomposition of a symmetric matrix.

    Returns (eigenvalues ascending, eigenvectors as columns)
    (ref: eig.cuh eig_dc → cusolverDnsyevd).
    """
    m = jnp.asarray(matrix)
    w, v = jnp.linalg.eigh(m)
    return w, v


def eig_jacobi(res, matrix, tol: float = 1e-7, sweeps: int = 15):
    """Jacobi eigensolver spelling (ref: eig.cuh eig_jacobi → syevj).

    tol/sweeps are accepted for parity; XLA's eigh is already
    iteration-free from the caller's perspective.
    """
    return eig_dc(res, matrix)


def eig_sel(res, matrix, n_eig_vals: int, largest: bool = True):
    """Subset eigendecomposition (ref: eig.cuh eig_sel → syevdx).

    Returns the ``n_eig_vals`` largest (or smallest) eigenpairs, eigenvalues
    ascending within the selection, vectors as columns.
    """
    w, v = eig_dc(res, matrix)
    if largest:
        return w[-n_eig_vals:], v[:, -n_eig_vals:]
    return w[:n_eig_vals], v[:, :n_eig_vals]
