"""SVD and randomized SVD (ref: linalg/svd.cuh, rsvd.cuh).

Full SVD maps to XLA's `jnp.linalg.svd`; the reference's QR- and
Jacobi-flavoured spellings dispatch to the same routine.  Randomized SVD
keeps the reference's structure (row/column-sampled range finder + small
exact SVD) built from MXU matmuls and QR — the algorithm of Halko et al.
that rsvd.cuh implements.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from raft_tpu.random.rng_state import RngState
from raft_tpu.util.precision import with_matmul_precision


@with_matmul_precision
def svd_qr(res, matrix, full_matrices: bool = False):
    """SVD returning (U, S, V) with V as columns of right singular vectors
    (ref: svd.cuh svdQR).  Note: returns V, not V^T."""
    u, s, vt = jnp.linalg.svd(jnp.asarray(matrix),
                              full_matrices=full_matrices)
    return u, s, vt.T


@with_matmul_precision
def svd_eig(res, matrix):
    """SVD via eigendecomposition of the Gram matrix
    (ref: svd.cuh svdEig — the path used when n_rows >> n_cols)."""
    a = jnp.asarray(matrix)
    w, v = jnp.linalg.eigh(a.T @ a)          # ascending
    w = w[::-1]
    v = v[:, ::-1]
    s = jnp.sqrt(jnp.maximum(w, 0.0))
    u = (a @ v) / jnp.maximum(s[None, :], jnp.finfo(a.dtype).tiny)
    return u, s, v


@with_matmul_precision
def svd_jacobi(res, matrix, tol: float = 1e-7, sweeps: int = 15):
    """Jacobi SVD spelling (ref: svd.cuh svdJacobi → gesvdj)."""
    return svd_qr(res, matrix)


@with_matmul_precision
def svd_reconstruction(res, u, s, v):
    """A ≈ U·diag(S)·V^T (ref: svd.cuh svdReconstruction)."""
    return (jnp.asarray(u) * jnp.asarray(s)[None, :]) @ jnp.asarray(v).T


@with_matmul_precision
def evaluate_svd_by_reconstruction(res, matrix, u, s, v,
                                   tol: float = 1e-3) -> bool:
    """ref: svd.cuh evaluateSVDByL2Norm."""
    a = jnp.asarray(matrix)
    recon = svd_reconstruction(res, u, s, v)
    err = jnp.linalg.norm(a - recon) / jnp.maximum(jnp.linalg.norm(a), 1e-30)
    return bool(err < tol)


@with_matmul_precision
def rsvd_fixed_rank(res, matrix, k: int, p: int = 10, n_iter: int = 2,
                    state: Optional[RngState] = None,
                    use_bbt: Optional[bool] = None):
    """Randomized SVD, fixed rank k with oversampling p
    (ref: rsvd.cuh rsvd_fixed_rank / randomized_svd).

    Structure follows the reference's range-finder: Gaussian sketch →
    power iterations with QR re-orthonormalization → small SVD in the
    subspace.  All heavy ops are MXU matmuls.
    """
    a = jnp.asarray(matrix)
    m, n = a.shape
    state = state or RngState(seed=0)
    ell = min(k + p, min(m, n))
    omega = jax.random.normal(state.next_key(), (n, ell), dtype=a.dtype)
    y = a @ omega
    q, _ = jnp.linalg.qr(y)
    for _ in range(n_iter):
        z, _ = jnp.linalg.qr(a.T @ q)
        q, _ = jnp.linalg.qr(a @ z)
    b = q.T @ a                                   # ell × n
    ub, s, vt = jnp.linalg.svd(b, full_matrices=False)
    u = q @ ub
    return u[:, :k], s[:k], vt[:k].T


@with_matmul_precision
def rsvd_perc(res, matrix, perc: float, p: int = 10, n_iter: int = 2,
              state: Optional[RngState] = None):
    """Rank chosen as a fraction of min(m,n) (ref: rsvd.cuh rsvdPerc)."""
    m, n = jnp.asarray(matrix).shape
    k = max(1, int(perc * min(m, n)))
    return rsvd_fixed_rank(res, matrix, k, p=p, n_iter=n_iter, state=state)


randomized_svd = rsvd_fixed_rank
