"""Least squares solvers (ref: linalg/lstsq.cuh — SVD/eig/QR variants)."""

from __future__ import annotations

import jax.numpy as jnp
from raft_tpu.util.input_validation import expect_2d, expect_finite
from raft_tpu.util.precision import with_matmul_precision


def _scale_rows(x, s):
    """Row-scale that is correct for both 1-D (single-RHS) and 2-D b."""
    return x * s if x.ndim == 1 else x * s[:, None]


def _validate(op: str, A, b):
    """RAFT_EXPECTS-style entry checks shared by the lstsq variants:
    shapes always, values only when the guard mode says so."""
    expect_2d(A, name=f"{op}: A")
    if b.shape[0] != A.shape[0]:
        raise ValueError(f"{op}: b rows {b.shape[0]} != A rows "
                         f"{A.shape[0]}")
    expect_finite(A, name=f"{op}: A")
    expect_finite(b, name=f"{op}: b")


@with_matmul_precision
def lstsq_svd_qr(res, A, b):
    """Minimum-norm solution via SVD (ref: lstsq.cuh lstsqSvdQR)."""
    A = jnp.asarray(A)
    b = jnp.asarray(b)
    _validate("linalg.lstsq_svd_qr", A, b)
    u, s, vt = jnp.linalg.svd(A, full_matrices=False)
    cutoff = jnp.finfo(A.dtype).eps * max(A.shape) * s[0]
    s_inv = jnp.where(s > cutoff, 1.0 / s, 0.0)
    return vt.T @ _scale_rows(u.T @ b, s_inv)


@with_matmul_precision
def lstsq_svd_jacobi(res, A, b):
    """ref: lstsq.cuh lstsqSvdJacobi (gesvdj path)."""
    return lstsq_svd_qr(res, A, b)


@with_matmul_precision
def lstsq_eig(res, A, b):
    """Normal-equations path via eigendecomposition of AᵀA
    (ref: lstsq.cuh lstsqEig)."""
    A = jnp.asarray(A)
    b = jnp.asarray(b)
    _validate("linalg.lstsq_eig", A, b)
    g = A.T @ A
    w, v = jnp.linalg.eigh(g)
    cutoff = jnp.finfo(A.dtype).eps * max(A.shape) * jnp.max(jnp.abs(w))
    w_inv = jnp.where(jnp.abs(w) > cutoff, 1.0 / w, 0.0)
    return v @ _scale_rows(v.T @ (A.T @ b), w_inv)


@with_matmul_precision
def lstsq_qr(res, A, b):
    """QR path (ref: lstsq.cuh lstsqQR — geqrf/ormqr + triangular solve)."""
    A = jnp.asarray(A)
    b = jnp.asarray(b)
    _validate("linalg.lstsq_qr", A, b)
    q, r = jnp.linalg.qr(A, mode="reduced")
    from jax.scipy.linalg import solve_triangular

    return solve_triangular(r, q.T @ b, lower=False)
