"""Elementwise operations (ref: linalg/add.cuh, subtract.cuh, divide.cuh,
multiply.cuh, power.cuh, sqrt.cuh, unary_op.cuh, binary_op.cuh,
ternary_op.cuh, eltwise.cuh).

XLA fuses these into surrounding computations; the wrappers exist for API
parity and for the scalar variants' broadcasting rules.
"""

from __future__ import annotations

import jax.numpy as jnp


def add(res, a, b):
    return jnp.asarray(a) + jnp.asarray(b)


def add_scalar(res, a, scalar):
    return jnp.asarray(a) + scalar


def subtract(res, a, b):
    return jnp.asarray(a) - jnp.asarray(b)


def subtract_scalar(res, a, scalar):
    return jnp.asarray(a) - scalar


def multiply(res, a, b):
    return jnp.asarray(a) * jnp.asarray(b)


def multiply_scalar(res, a, scalar):
    return jnp.asarray(a) * scalar


def divide(res, a, b):
    return jnp.asarray(a) / jnp.asarray(b)


def divide_scalar(res, a, scalar):
    return jnp.asarray(a) / scalar


def power(res, a, b):
    return jnp.power(jnp.asarray(a), jnp.asarray(b))


def power_scalar(res, a, scalar):
    return jnp.power(jnp.asarray(a), scalar)


def sqrt(res, a):
    # NaN on negative input is the public elementwise op's contract
    # (ref: sqrt.cuh) — this is the primitive, not a breakdown site
    return jnp.sqrt(jnp.asarray(a))     # guarded: caller's contract


def unary_op(res, a, op):
    """out[i] = op(a[i]) (ref: unary_op.cuh)."""
    return op(jnp.asarray(a))


def write_only_unary_op(res, shape, op, dtype=jnp.float32):
    """out[i] = op(i) over a fresh array (ref: write_only_unary_op)."""
    n = 1
    for s in shape:
        n *= s
    return op(jnp.arange(n).reshape(shape)).astype(dtype)


def binary_op(res, a, b, op):
    return op(jnp.asarray(a), jnp.asarray(b))


def ternary_op(res, a, b, c, op):
    return op(jnp.asarray(a), jnp.asarray(b), jnp.asarray(c))
