"""Broadcast a vector (or two) along rows/columns of a matrix
(ref: linalg/matrix_vector_op.cuh, detail/matrix_vector_op.cuh:23-82 —
delegates to matrix::linewise_op in the reference).

``apply`` names the broadcast direction with RAFT's vocabulary:
ALONG_ROWS broadcasts a length-n_cols vector across every row;
ALONG_COLUMNS broadcasts a length-n_rows vector down every column.
"""

from __future__ import annotations

from typing import Callable

import jax.numpy as jnp

from raft_tpu.linalg.reduce import ALONG_COLUMNS, ALONG_ROWS


def matrix_vector_op(res, matrix, vec, op: Callable,
                     apply: str = ALONG_ROWS, vec2=None):
    """out[i,j] = op(m[i,j], v[j] (, v2[j])) for ALONG_ROWS
    (ref: matrix_vector_op.cuh)."""
    m = jnp.asarray(matrix)
    v = jnp.asarray(vec)
    if apply == ALONG_ROWS:
        bv = v[None, :]
        bv2 = None if vec2 is None else jnp.asarray(vec2)[None, :]
    elif apply == ALONG_COLUMNS:
        bv = v[:, None]
        bv2 = None if vec2 is None else jnp.asarray(vec2)[:, None]
    else:
        raise ValueError(f"bad apply {apply}")
    if vec2 is None:
        return op(m, bv)
    return op(m, bv, bv2)
