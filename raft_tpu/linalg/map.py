"""n-ary elementwise map primitives (ref: linalg/map.cuh:95-241,
linalg/map_reduce.cuh).

Under XLA, a map is just a traced elementwise expression — the fusion the
reference implements with vectorized-IO kernels falls out of the compiler.
These wrappers keep RAFT's calling shapes (op first-class, n-ary inputs,
offset variants) so algorithm code reads the same.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax


def map(res, op, *ins):
    """out[i] = op(ins0[i], ins1[i], ...) (ref: map.cuh map)."""
    return op(*[jnp.asarray(x) for x in ins])


def map_offset(res, op, shape_or_ref, *ins):
    """out[i] = op(i, ins0[i], ...) (ref: map.cuh map_offset).

    ``shape_or_ref`` gives the output shape (an int, tuple, or array whose
    shape is used); the flat offset is passed as the first op argument.
    """
    if isinstance(shape_or_ref, int):
        shape = (shape_or_ref,)
    elif isinstance(shape_or_ref, tuple):
        shape = shape_or_ref
    else:
        shape = tuple(shape_or_ref.shape)
    n = 1
    for s in shape:
        n *= s
    idx = jnp.arange(n).reshape(shape)
    return op(idx, *[jnp.asarray(x) for x in ins])


def map_reduce(res, op, reduce_op, init, *ins):
    """reduce(op(ins...)) to scalar (ref: map_reduce.cuh map_reduce)."""
    mapped = op(*[jnp.asarray(x) for x in ins])
    flat = mapped.ravel()
    out = init
    # Use lax.reduce for general monoids; jnp covers the common ones fast.
    if reduce_op in (jnp.add, None):
        return jnp.sum(flat) + init
    return lax.reduce(flat, jnp.asarray(init, dtype=flat.dtype),
                      lambda a, b: reduce_op(a, b), (0,))


def map_then_reduce(res, op, *ins):
    """Sum-reduction of a mapped expression
    (ref: map_then_reduce / map_then_sum_reduce).

    Staged reduction (minor axis first, then the rest): the r2 sweep
    measured the single `jnp.sum(x)` all-axes spelling at 127 GB/s on
    v5e while the row-reduce spelling ran at 753 — XLA's direct
    to-scalar reduce emitter does not tile the minor dim as well as the
    staged pair, which fuses into the same one pass over the data."""
    mapped = op(*[jnp.asarray(x) for x in ins])
    if mapped.ndim <= 1:
        return jnp.sum(mapped)
    return jnp.sum(jnp.sum(mapped, axis=-1))
