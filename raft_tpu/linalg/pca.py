"""PCA and truncated SVD (ref: linalg/pca.cuh:41-178, linalg/tsvd.cuh:34-160,
detail/tsvd.cuh; moved into RAFT from cuML — CHANGELOG.md:21).

Solvers mirror the reference's ``enum class solver`` (pca_types.hpp:21):
COV_EIG_DQ (covariance + divide-&-conquer eig), COV_EIG_JACOBI, and the
randomized path.  All heavy steps are MXU matmuls + XLA eigh/svd.
"""

from __future__ import annotations

import enum
from typing import NamedTuple, Optional

import jax.numpy as jnp

from raft_tpu.random.rng_state import RngState
from raft_tpu.util.precision import with_matmul_precision


class Solver(enum.Enum):
    COV_EIG_DQ = "cov_eig_dq"
    COV_EIG_JACOBI = "cov_eig_jacobi"
    RANDOMIZED = "randomized"


class PCAResult(NamedTuple):
    components: jnp.ndarray          # [n_components, n_cols]
    explained_variance: jnp.ndarray  # [n_components]
    explained_variance_ratio: jnp.ndarray
    singular_values: jnp.ndarray
    mean: jnp.ndarray                # [n_cols]
    noise_variance: jnp.ndarray      # scalar


def sign_flip_components(components, U=None):
    """Deterministic sign convention: the max-|value| entry of each
    component is made positive (ref: tsvd.cuh sign_flip / signFlip)."""
    comps = jnp.asarray(components)
    idx = jnp.argmax(jnp.abs(comps), axis=1)
    signs = jnp.sign(comps[jnp.arange(comps.shape[0]), idx])
    signs = jnp.where(signs == 0, 1.0, signs)
    comps = comps * signs[:, None]
    if U is not None:
        return comps, jnp.asarray(U) * signs[None, :]
    return comps


def cal_eig(res, cov, n_components: int, solver: Solver = Solver.COV_EIG_DQ):
    """Top-k eigenpairs of a covariance matrix, descending
    (ref: pca.cuh calEig)."""
    w, v = jnp.linalg.eigh(jnp.asarray(cov))
    w = w[::-1]
    v = v[:, ::-1]
    return w[:n_components], v[:, :n_components]


@with_matmul_precision
def pca_fit(res, X, n_components: int,
            solver: Solver = Solver.COV_EIG_DQ,
            state: Optional[RngState] = None) -> PCAResult:
    """Fit PCA (ref: pca.cuh pca_fit).

    Returns components as rows, explained variance (unbiased, n-1 divisor),
    singular values and the column mean — matching the reference's outputs.
    """
    from raft_tpu.util.input_validation import (expect_2d, expect_finite,
                                                expect_positive)

    X = jnp.asarray(X)
    expect_2d(X, name="pca_fit: X")
    expect_positive(n_components, name="pca_fit: n_components")
    expect_finite(X, name="pca_fit: X")
    n_rows, n_cols = X.shape
    mu = jnp.mean(X, axis=0)
    Xc = X - mu[None, :]

    if solver == Solver.RANDOMIZED:
        from raft_tpu.linalg.svd import rsvd_fixed_rank

        u, s, v = rsvd_fixed_rank(res, Xc, n_components, state=state)
        explained = (s * s) / (n_rows - 1)
        comps = v.T
    else:
        cov = (Xc.T @ Xc) / (n_rows - 1)
        w, v = cal_eig(res, cov, n_components, solver)
        explained = w
        s = jnp.sqrt(jnp.maximum(w * (n_rows - 1), 0.0))
        comps = v.T

    comps = sign_flip_components(comps)
    total_var = jnp.sum(jnp.var(X, axis=0, ddof=1))
    ratio = explained / total_var
    if n_components < min(n_rows, n_cols):
        noise = (total_var - jnp.sum(explained)) / (
            min(n_rows, n_cols) - n_components)
    else:
        noise = jnp.asarray(0.0, dtype=X.dtype)
    return PCAResult(comps.astype(X.dtype), explained.astype(X.dtype),
                     ratio.astype(X.dtype), s.astype(X.dtype), mu,
                     noise.astype(X.dtype))


@with_matmul_precision
def pca_transform(res, X, result: PCAResult, whiten: bool = False):
    """Project into component space (ref: pca.cuh pca_transform)."""
    X = jnp.asarray(X)
    t = (X - result.mean[None, :]) @ result.components.T
    if whiten:
        t = t / jnp.sqrt(jnp.maximum(result.explained_variance,
                                     1e-30))[None, :]
    return t


@with_matmul_precision
def pca_inverse_transform(res, T, result: PCAResult, whiten: bool = False):
    """ref: pca.cuh pca_inverse_transform."""
    T = jnp.asarray(T)
    if whiten:
        T = T * jnp.sqrt(jnp.maximum(result.explained_variance,
                                     1e-30))[None, :]
    return T @ result.components + result.mean[None, :]


@with_matmul_precision
def pca_fit_transform(res, X, n_components: int, **kw):
    result = pca_fit(res, X, n_components, **kw)
    return pca_transform(res, X, result), result


# -- truncated SVD (no centering) -------------------------------------------


class TSVDResult(NamedTuple):
    components: jnp.ndarray
    singular_values: jnp.ndarray
    explained_variance: jnp.ndarray
    explained_variance_ratio: jnp.ndarray


@with_matmul_precision
def tsvd_fit(res, X, n_components: int,
             solver: Solver = Solver.COV_EIG_DQ,
             state: Optional[RngState] = None) -> TSVDResult:
    """Truncated SVD on the *uncentered* data (ref: tsvd.cuh tsvd_fit —
    eig of XᵀX)."""
    X = jnp.asarray(X)
    n_rows = X.shape[0]
    if solver == Solver.RANDOMIZED:
        from raft_tpu.linalg.svd import rsvd_fixed_rank

        u, s, v = rsvd_fixed_rank(res, X, n_components, state=state)
        comps = v.T
    else:
        g = X.T @ X
        w, v = cal_eig(res, g, n_components, solver)
        s = jnp.sqrt(jnp.maximum(w, 0.0))
        comps = v.T
    comps = sign_flip_components(comps)
    T = X @ comps.T
    explained = jnp.var(T, axis=0, ddof=1)
    total_var = jnp.sum(jnp.var(X, axis=0, ddof=1))
    return TSVDResult(comps.astype(X.dtype), s.astype(X.dtype),
                      explained.astype(X.dtype),
                      (explained / total_var).astype(X.dtype))


@with_matmul_precision
def tsvd_transform(res, X, result: TSVDResult):
    return jnp.asarray(X) @ result.components.T


@with_matmul_precision
def tsvd_inverse_transform(res, T, result: TSVDResult):
    return jnp.asarray(T) @ result.components


@with_matmul_precision
def tsvd_fit_transform(res, X, n_components: int, **kw):
    result = tsvd_fit(res, X, n_components, **kw)
    return tsvd_transform(res, X, result), result
